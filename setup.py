"""Build script (reference: the CMake superbuild collapses to a pure-python
wheel + optional C extensions; see CMakeLists.txt:48-264 option matrix).

Native components (the tpu_dataio shared-memory ring, built via cc) are
compiled on demand at import time with a graceful pure-python fallback, so
the wheel itself stays universal. ``pip install -e .`` works offline.
"""
from setuptools import setup

setup()
