"""DataParallel wrapper (reference: fluid/dygraph/parallel.py:419)."""
from __future__ import annotations

import contextlib

from ..nn.layer.layers import Layer

__all__ = ["DataParallel"]


class DataParallel(Layer):
    """Wraps a Layer for data-parallel training.

    In the reference this builds a C++ Reducer that buckets grads (default
    25MB comm buffers) and allreduces during backward.  Here gradient sync
    is implicit: the ParallelEngine shards the batch over the mesh "data"
    axis and XLA emits the grad psum.  The wrapper preserves the eager
    API: ``model = paddle.DataParallel(model)`` then train as usual (via
    ``Model.fit``, ``fleet`` or ``ParallelEngine``).
    """

    def __init__(self, layers, strategy=None, comm_buffer_size=25,
                 last_comm_buffer_size=1, find_unused_parameters=False,
                 group=None):
        super().__init__()
        self._layers = layers
        # reducer tuning knobs are meaningless under SPMD; accepted for
        # API parity, recorded for introspection
        self.comm_buffer_size = comm_buffer_size
        self.find_unused_parameters = find_unused_parameters
        from ..distributed import fleet as _fleet
        _fleet._fleet_state["model"] = layers

    def forward(self, *inputs, **kwargs):
        return self._layers(*inputs, **kwargs)

    @contextlib.contextmanager
    def no_sync(self):
        """Reference: grad-accumulation context that suppresses the
        reducer's allreduce.  SPMD grad sync happens inside the compiled
        step (not per-backward), so this is a no-op context."""
        yield

    def scale_loss(self, loss):
        """Reference scales loss by 1/nranks before backward when the
        reducer averages by sum; XLA's psum-mean path needs no rescale."""
        return loss

    # state passthrough: checkpoints must not gain a wrapper prefix
    def state_dict(self, *args, **kwargs):
        return self._layers.state_dict(*args, **kwargs)

    def set_state_dict(self, state_dict, *args, **kwargs):
        return self._layers.set_state_dict(state_dict, *args, **kwargs)

    def parameters(self, include_sublayers=True):
        return self._layers.parameters(include_sublayers)

    def named_parameters(self, prefix="", include_sublayers=True):
        return self._layers.named_parameters(prefix, include_sublayers)
