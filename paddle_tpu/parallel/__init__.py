"""``paddle.parallel`` — eager data-parallel facade.

Reference: python/paddle/fluid/dygraph/parallel.py:419 (``DataParallel``
wrapping a Layer; C++ ``Reducer`` buckets gradients and overlaps the
allreduce with backward, fluid/imperative/reducer.cc).

TPU-native: under single-controller SPMD there is no per-process gradient
reducer to build — gradient synchronisation is the ``psum`` XLA inserts
when the batch axis of the jitted train step is sharded over the "data"
mesh axis (distributed/spmd.py).  ``DataParallel`` is therefore a thin
wrapper that (a) delegates to the inner layer, (b) registers the model
with fleet so ``distributed_optimizer``/``ParallelEngine`` pick it up, and
(c) keeps the reference's API shape (``scale_loss``, ``no_sync``,
``state_dict`` passthrough) so training scripts port unmodified.
"""
from .api import DataParallel  # noqa: F401

__all__ = ["DataParallel"]
