"""``paddle.quantization`` — QAT (fake-quant) + post-training quantization.

Reference: python/paddle/fluid/contrib/slim/quantization/ —
``ImperativeQuantAware`` (imperative/qat.py: swaps Linear/Conv2D for
quantized variants with fake-quant on weights + moving-average abs-max
activation observers), ``PostTrainingQuantization``
(post_training_quantization.py: calibration-driven scale search).

TPU-native: fake quantization is a straight-through-estimator expression
(x + stop_gradient(quant(x) - x)) that XLA fuses into the surrounding
matmul; observers are plain running stats. int8 *execution* maps to
bf16/int8 MXU paths at inference export time — the artifact carries the
scales (this mirrors the reference, whose QAT graphs also run float with
fake-quant ops until a deployment pass strips them).
"""
from __future__ import annotations

from typing import Dict, Optional

import numpy as np

from ..framework.tensor import Tensor
from ..nn.layer.layers import Layer

__all__ = ["fake_quant", "FakeQuantAbsMax", "MovingAverageAbsMaxScale",
           "QuantizedLinear", "QuantizedConv2D", "ImperativeQuantAware",
           "PostTrainingQuantization", "QuantizationTransformPass",
           "PostTrainingQuantizationProgram", "calibrate_program"]

from .passes import (PostTrainingQuantizationProgram,  # noqa: E402
                     QuantizationTransformPass, calibrate_program)


def fake_quant(x, scale, bits: int = 8):
    """Symmetric per-tensor fake quantization with an STE gradient.

    q = round(clip(x, ±scale) / scale * qmax) * scale / qmax, gradient
    passes straight through (reference fake_quantize_abs_max op).
    """
    import jax
    import jax.numpy as jnp

    qmax = float(2 ** (bits - 1) - 1)

    def fn(arr, s):
        s = jnp.maximum(s.astype(arr.dtype), 1e-8)
        q = jnp.clip(arr, -s, s) / s * qmax
        q = jnp.round(q) * s / qmax
        return arr + jax.lax.stop_gradient(q - arr)   # STE

    if isinstance(x, Tensor):
        from .. import autograd
        s_t = scale if isinstance(scale, Tensor) else \
            Tensor(jnp.asarray(scale, jnp.float32))
        return autograd.differentiable_apply(fn, x, s_t)
    return fn(x, jnp.asarray(
        scale._data if isinstance(scale, Tensor) else scale, jnp.float32))


class FakeQuantAbsMax(Layer):
    """Weight quantizer: scale = abs-max of the current tensor."""

    def __init__(self, bits: int = 8):
        super().__init__()
        self.bits = bits

    def forward(self, x):
        from ..framework.dispatch import call_op
        scale = call_op("max", call_op("abs", x))
        return fake_quant(x, scale, self.bits)


class MovingAverageAbsMaxScale(Layer):
    """Activation observer: EMA of abs-max (reference
    moving_average_abs_max op). In training mode it updates its state and
    fake-quants; in eval it applies the frozen scale."""

    def __init__(self, bits: int = 8, momentum: float = 0.9):
        super().__init__()
        import jax.numpy as jnp
        self.bits = bits
        self.momentum = momentum
        self.register_buffer("scale_state",
                             Tensor(jnp.zeros((1,), jnp.float32)))

    def forward(self, x):
        import jax
        import jax.numpy as jnp
        arr = x._data if isinstance(x, Tensor) else x
        cur = jnp.max(jnp.abs(arr)).astype(jnp.float32)
        state = self.scale_state._data.reshape(())
        if self.training:
            new = jnp.where(state == 0, cur,
                            self.momentum * state
                            + (1 - self.momentum) * cur)
            # observer state is a buffer: functional_state captures it
            # under jit; eagerly we just overwrite
            self.scale_state._data = new.reshape(1)
            scale = new
        else:
            scale = jnp.where(state == 0, cur, state)
        return fake_quant(x, scale, self.bits)


class QuantizedLinear(Layer):
    """Linear with fake-quantized weights + activations (reference
    imperative/quant_layers QuantizedLinear)."""

    def __init__(self, inner, weight_bits=8, activation_bits=8):
        super().__init__()
        self.inner = inner
        self.weight_quant = FakeQuantAbsMax(weight_bits)
        self.act_quant = MovingAverageAbsMaxScale(activation_bits)

    def forward(self, x):
        from ..nn import functional as F
        x = self.act_quant(x)
        w = self.weight_quant(self.inner.weight)
        return F.linear(x, w, self.inner.bias)


class QuantizedConv2D(Layer):
    def __init__(self, inner, weight_bits=8, activation_bits=8):
        super().__init__()
        self.inner = inner
        self.weight_quant = FakeQuantAbsMax(weight_bits)
        self.act_quant = MovingAverageAbsMaxScale(activation_bits)

    def forward(self, x):
        from ..framework.dispatch import call_op
        x = self.act_quant(x)
        w = self.weight_quant(self.inner.weight)
        return call_op("conv2d", x, w, self.inner.bias,
                       stride=self.inner._stride,
                       padding=self.inner._padding,
                       dilation=self.inner._dilation,
                       groups=self.inner._groups)


class ImperativeQuantAware:
    """QAT driver (reference imperative/qat.py:ImperativeQuantAware):
    ``quantize(model)`` swaps quantizable sublayers in place; train as
    usual; ``save_quantized_model`` exports via paddle.jit.save."""

    def __init__(self, weight_bits=8, activation_bits=8,
                 quantizable_layer_type=("Linear", "Conv2D")):
        self.weight_bits = weight_bits
        self.activation_bits = activation_bits
        self.types = set(quantizable_layer_type)

    def quantize(self, model: Layer) -> Layer:
        from ..nn import Conv2D, Linear

        def recurse(layer):
            for name, sub in list(layer._sub_layers.items()):
                if isinstance(sub, Linear) and "Linear" in self.types:
                    layer._sub_layers[name] = QuantizedLinear(
                        sub, self.weight_bits, self.activation_bits)
                elif isinstance(sub, Conv2D) and "Conv2D" in self.types:
                    layer._sub_layers[name] = QuantizedConv2D(
                        sub, self.weight_bits, self.activation_bits)
                else:
                    recurse(sub)
        recurse(model)
        return model

    def save_quantized_model(self, model, path, input_spec=None):
        """Freeze QAT fake-quant into true int8 weights, then export the
        StableHLO artifact (reference: save_quantized_model runs the
        quantized-inference pass before save_inference_model)."""
        from .. import jit
        model = convert_to_int8(model)
        model.eval()
        jit.save(model, path, input_spec=input_spec)
        return model


class PostTrainingQuantization:
    """PTQ (reference post_training_quantization.py): run calibration
    batches through the model recording per-layer activation abs-max,
    then freeze the scales into quantized layers."""

    def __init__(self, model: Layer, weight_bits=8, activation_bits=8):
        self.model = model
        self.weight_bits = weight_bits
        self.activation_bits = activation_bits
        self._scales: Dict[str, float] = {}

    def collect(self, batches) -> Dict[str, float]:
        """Feed calibration batches; returns {layer_name: act_scale}."""
        import jax.numpy as jnp
        from ..nn import Conv2D, Linear

        records: Dict[str, float] = {}
        hooks = []
        for name, sub in self.model.named_sublayers():
            if isinstance(sub, (Linear, Conv2D)):
                def mk(nm):
                    def hook(layer, inputs):
                        x = inputs[0]
                        arr = x._data if isinstance(x, Tensor) else x
                        cur = float(jnp.max(jnp.abs(arr)))
                        records[nm] = max(records.get(nm, 0.0), cur)
                        return None
                    return hook
                hooks.append(sub.register_forward_pre_hook(mk(name)))
        self.model.eval()
        try:
            for batch in batches:
                self.model(batch if isinstance(batch, Tensor)
                           else Tensor(np.asarray(batch)))
        finally:
            for h in hooks:
                h.remove()
        self._scales = records
        return dict(records)

    def quantize(self) -> Layer:
        """Swap quantizable layers, freezing collected activation scales
        (observers start from the calibrated value, eval-mode apply)."""
        import jax.numpy as jnp
        qat = ImperativeQuantAware(self.weight_bits, self.activation_bits)
        name_map = dict(self._scales)
        # remember original names before swapping
        originals = {id(sub): nm for nm, sub in
                     self.model.named_sublayers()}
        qat.quantize(self.model)
        for _, sub in self.model.named_sublayers():
            if isinstance(sub, (QuantizedLinear, QuantizedConv2D)):
                nm = originals.get(id(sub.inner))
                if nm in name_map and name_map[nm] > 0:
                    sub.act_quant.scale_state._data = jnp.asarray(
                        [name_map[nm]], jnp.float32)
        self.model.eval()
        return self.model


# ---------------------------------------------------------------------------
# quantized-inference conversion (r3 verdict partial #56: the reference's
# quantized-inference pass, slim/quantization_pass.py + imperative/qat.py
# _convert). TPU stance: WEIGHT-ONLY int8 — weights are stored int8 with
# per-output-channel fp scales (4x HBM cut, the usual TPU serving win) and
# dequantize into the matmul dtype at compute time, which XLA fuses into
# the convolution/matmul read. Activation tensors stay bf16/fp32: TPU has
# no int8 MXU path to feed, so fake-quantizing activations at inference
# would cost accuracy for zero speed.
# ---------------------------------------------------------------------------

def quantize_weight(w, bits=8, channel_axis=-1):
    """array -> (int8 values, fp32 per-channel scales)."""
    import jax.numpy as jnp
    qmax = float(2 ** (bits - 1) - 1)
    axes = tuple(i for i in range(w.ndim) if i != channel_axis % w.ndim)
    scale = jnp.max(jnp.abs(w), axis=axes, keepdims=True) / qmax
    scale = jnp.where(scale == 0, 1.0, scale)
    q = jnp.clip(jnp.round(w / scale), -qmax - 1, qmax).astype(jnp.int8)
    return q, scale.astype(jnp.float32)


class QuantizedInferenceLinear(Layer):
    """Frozen int8-weight linear (reference: the quantized op the pass
    writes into the inference program)."""

    def __init__(self, float_linear, weight_bits=8):
        import jax.numpy as jnp
        super().__init__()
        w = float_linear.weight._data  # [in, out]
        q, scale = quantize_weight(jnp.asarray(w, jnp.float32),
                                   weight_bits, channel_axis=-1)
        self.weight_int8 = self.create_parameter(
            list(q.shape), dtype="int8", is_bias=False)
        self.weight_int8._data = q
        self.weight_int8.stop_gradient = True
        self.weight_scale = self.create_parameter(
            list(scale.shape), is_bias=False)
        self.weight_scale._data = scale
        self.weight_scale.stop_gradient = True
        self.bias = float_linear.bias
        self._compute_dtype = w.dtype

    def forward(self, x):
        from ..nn import functional as F
        w = (self.weight_int8.astype("float32")
             * self.weight_scale).astype(str(self._compute_dtype))
        return F.linear(x, w, self.bias)


class QuantizedInferenceConv2D(Layer):
    def __init__(self, float_conv, weight_bits=8):
        import jax.numpy as jnp
        super().__init__()
        w = float_conv.weight._data  # [out, in/groups, kh, kw]
        q, scale = quantize_weight(jnp.asarray(w, jnp.float32),
                                   weight_bits, channel_axis=0)
        self.weight_int8 = self.create_parameter(
            list(q.shape), dtype="int8", is_bias=False)
        self.weight_int8._data = q
        self.weight_int8.stop_gradient = True
        self.weight_scale = self.create_parameter(
            list(scale.shape), is_bias=False)
        self.weight_scale._data = scale
        self.weight_scale.stop_gradient = True
        self.bias = float_conv.bias
        self._inner = float_conv
        self._compute_dtype = w.dtype

    def forward(self, x):
        from ..framework.dispatch import call_op
        w = (self.weight_int8.astype("float32")
             * self.weight_scale).astype(str(self._compute_dtype))
        c = self._inner
        return call_op("conv2d", x, w, self.bias, stride=c._stride,
                       padding=c._padding, dilation=c._dilation,
                       groups=c._groups, data_format=c._data_format)


def convert_to_int8(model: Layer, weight_bits=8) -> Layer:
    """Replace QAT-wrapped (or plain) Linear/Conv2D sublayers with frozen
    int8-weight inference layers, in place. The QAT observers' job is
    done — fake-quant trained the weights onto the int8 grid; this bakes
    that grid in."""
    from ..nn import Conv2D, Linear

    def frozen(sub):
        if isinstance(sub, QuantizedLinear):
            return QuantizedInferenceLinear(sub.inner, weight_bits)
        if isinstance(sub, QuantizedConv2D):
            return QuantizedInferenceConv2D(sub.inner, weight_bits)
        if isinstance(sub, Linear):
            return QuantizedInferenceLinear(sub, weight_bits)
        if isinstance(sub, Conv2D) and not sub._transpose:
            return QuantizedInferenceConv2D(sub, weight_bits)
        return None

    def recurse(layer):
        for name, sub in list(layer._sub_layers.items()):
            new = frozen(sub)
            if new is not None:
                layer._sub_layers[name] = new
            else:
                recurse(sub)

    recurse(model)
    return model
