"""Program-level quantization passes over the captured static graph.

Reference:
python/paddle/fluid/contrib/slim/quantization/quantization_pass.py:1
(QuantizationTransformPass rewrites the IR graph, inserting
quant/dequant ops around quantizable operators) and
post_training_quantization.py:1 (PostTrainingQuantization drives
calibration over sample data, then applies the pass with frozen scales).

TPU-native design: the static Program (static/__init__.py) replays a
recorded op-node list as a pure jitted function, so "inserting an op" is
wrapping a node's callable — the quant/dequant simulation expressed in
jnp fuses into the surrounding matmul/conv when XLA compiles the replay.
Calibration rides the replay's observer hook eagerly (no jit, host-side
abs-max/percentile accumulation), exactly one pass per batch like the
reference's sampling executor runs. Weights quantize per OUTPUT CHANNEL
(conv OIHW axis 0, matmul/linear last axis) — the reference's
channel_wise_abs_max; activations per tensor.
"""
from __future__ import annotations

from typing import Dict, Iterable, Optional, Sequence

import numpy as np

__all__ = ["QuantizationTransformPass", "PostTrainingQuantizationProgram",
           "calibrate_program"]

# ops whose (activation, weight) inputs take quant/dequant simulation;
# axis is the weight's output-channel axis for per-channel scales
_QUANTIZABLE = {"conv2d": 0, "linear": -1, "matmul": -1}


def _weight_and_act_indices(node):
    """Locate the weight (a Parameter input with rank >= 2) and the
    activation (first non-parameter input) in a recorded node."""
    widx = aidx = None
    for j, (tid, const, pname) in enumerate(node.inputs):
        if pname is not None and widx is None and \
                getattr(const, "ndim", 0) >= 2:
            widx = j
        elif pname is None and aidx is None:
            aidx = j
    return widx, aidx


def _fake_quant_sim(x, scale, bits):
    """Symmetric quant→dequant in jnp (STE gradient): the int8 grid
    simulation XLA fuses into the consuming op."""
    import jax
    import jax.numpy as jnp
    qmax = float(2 ** (bits - 1) - 1)
    s = jnp.maximum(jnp.asarray(scale, x.dtype), 1e-8)
    q = jnp.round(jnp.clip(x, -s, s) / s * qmax) * s / qmax
    return x + jax.lax.stop_gradient(q - x)


def _quant_weight_sim(w, axis, bits):
    """Per-output-channel symmetric quant→dequant of a weight array."""
    import jax.numpy as jnp
    qmax = float(2 ** (bits - 1) - 1)
    axes = tuple(i for i in range(w.ndim) if i != axis % w.ndim)
    scale = jnp.max(jnp.abs(w), axis=axes, keepdims=True)
    scale = jnp.where(scale == 0, 1.0, scale)
    q = jnp.round(jnp.clip(w, -scale, scale) / scale * qmax)
    return (q * scale / qmax).astype(w.dtype)


class QuantizationTransformPass:
    """Rewrite a Program so every quantizable node runs int8 simulation.

    With ``act_scales`` (node-index → float, from calibration) the
    activation quant uses frozen scales — the PTQ emission. Without, the
    activation scale is computed from the live tensor (dynamic abs-max),
    which is the QAT-on-static form: train the rewritten program and the
    STE gradient pulls weights onto the int8 grid.
    """

    def __init__(self, weight_bits: int = 8, activation_bits: int = 8,
                 quantizable_op_type: Sequence[str] = tuple(_QUANTIZABLE)):
        unknown = set(quantizable_op_type) - set(_QUANTIZABLE)
        if unknown:
            raise ValueError(f"cannot quantize op types {sorted(unknown)}; "
                             f"supported: {sorted(_QUANTIZABLE)}")
        self.weight_bits = weight_bits
        self.activation_bits = activation_bits
        self.op_types = set(quantizable_op_type)

    def _wrap(self, node, act_scale: Optional[float]):
        import jax.numpy as jnp
        from ..framework import static_capture as _capture

        widx, aidx = _weight_and_act_indices(node)
        axis = _QUANTIZABLE[node.op]
        inner, wbits, abits = node.fn, self.weight_bits, self.activation_bits

        def quantized_fn(*args):
            args = list(args)
            if widx is not None:
                args[widx] = _quant_weight_sim(args[widx], axis, wbits)
            if aidx is not None:
                x = args[aidx]
                s = jnp.max(jnp.abs(x)) if act_scale is None else act_scale
                args[aidx] = _fake_quant_sim(x, s, abits)
            return inner(*args)

        attrs = dict(node.attrs)
        attrs["quantized"] = {"weight_bits": wbits, "act_bits": abits,
                              "act_scale": act_scale, "channel_axis": axis}
        return _capture.OpNode(node.op, quantized_fn, node.inputs,
                               node.out_ids, attrs)

    def apply(self, program, act_scales: Optional[Dict[int, float]] = None):
        """Return a for-test clone of ``program`` with quantizable nodes
        rewritten; the original is untouched (reference pass semantics:
        a new IrGraph)."""
        act_scales = act_scales or {}
        out = program.clone(for_test=True)
        out._nodes = [
            self._wrap(n, act_scales.get(i)) if n.op in self.op_types
            else n
            for i, n in enumerate(program._nodes)]
        out._replay_cache.clear()
        quantized = [i for i, n in enumerate(program._nodes)
                     if n.op in self.op_types]
        out._quant_info = {"nodes": quantized,
                           "weight_bits": self.weight_bits,
                           "act_bits": self.activation_bits,
                           "act_scales": dict(act_scales)}
        return out


def calibrate_program(program, feed_list: Iterable[Dict[str, np.ndarray]],
                      quantizable_op_type: Sequence[str] =
                      tuple(_QUANTIZABLE),
                      algo: str = "abs_max",
                      percentile: float = 99.99) -> Dict[int, float]:
    """Replay ``program`` over calibration feeds, recording an activation
    scale per quantizable node (reference PostTrainingQuantization's
    sampling phase). ``algo``: ``abs_max`` (max over all batches) or
    ``percentile`` (given percentile of |x| per batch, max over batches —
    robust to activation outliers, reference's hist/percentile family).
    """
    import jax.numpy as jnp

    if algo not in ("abs_max", "percentile"):
        raise ValueError(f"unknown calibration algo {algo!r}")
    op_types = set(quantizable_op_type)
    params = {n: p._data for n, p in program._params.items()}
    scales: Dict[int, float] = {}

    def observer(i, node, ins):
        if node.op not in op_types:
            return
        _, aidx = _weight_and_act_indices(node)
        if aidx is None:
            return
        x = jnp.abs(jnp.asarray(ins[aidx]))
        cur = float(jnp.percentile(x, percentile)) \
            if algo == "percentile" else float(jnp.max(x))
        scales[i] = max(scales.get(i, 0.0), cur)

    for feed in feed_list:
        feeds = {k: jnp.asarray(v) for k, v in feed.items()}
        program._forward_env(feeds, params, _observer=observer)
    return scales


class PostTrainingQuantizationProgram:
    """End-to-end program PTQ driver (reference
    post_training_quantization.py:PostTrainingQuantization): calibrate →
    transform → return the quantized inference program.

    ``feed_list`` is an iterable of Executor-style feed dicts covering the
    program's declared ``static.data`` inputs.
    """

    def __init__(self, program, feed_list,
                 weight_bits: int = 8, activation_bits: int = 8,
                 quantizable_op_type: Sequence[str] = tuple(_QUANTIZABLE),
                 algo: str = "abs_max", percentile: float = 99.99):
        self.program = program
        self.feed_list = list(feed_list)
        if not self.feed_list:
            raise ValueError("PTQ needs at least one calibration feed")
        self.pass_ = QuantizationTransformPass(
            weight_bits, activation_bits, quantizable_op_type)
        self.quantizable_op_type = quantizable_op_type
        self.algo = algo
        self.percentile = percentile
        self.scales: Dict[int, float] = {}

    def quantize(self):
        self.scales = calibrate_program(
            self.program, self.feed_list, self.quantizable_op_type,
            self.algo, self.percentile)
        return self.pass_.apply(self.program, self.scales)
