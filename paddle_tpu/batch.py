"""``paddle.batch`` (reference: python/paddle/batch.py) — wrap a sample
reader into a batched reader."""

__all__ = ["batch"]


def batch(reader, batch_size, drop_last=False):
    if batch_size <= 0:
        raise ValueError("batch_size should be a positive integer")

    def batch_reader():
        buf = []
        for sample in reader():
            buf.append(sample)
            if len(buf) == batch_size:
                yield buf
                buf = []
        if buf and not drop_last:
            yield buf

    return batch_reader
