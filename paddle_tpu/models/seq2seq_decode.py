"""Compiled decode loops for TransformerModel (models/seq2seq.py).

Same one-XLA-program structure as models/generation.py (encoder prefill +
lax.while_loop decode over fixed-shape caches; greedy or flattened-beam),
specialised to the encoder-decoder wiring: cross-attention K/V computed
once, source-pad mask applied every step, decode starts at BOS.
"""
from __future__ import annotations

import numpy as np

from ..framework.tensor import Tensor, no_grad_guard

__all__ = ["run_generate"]


def _build_seq2seq_fn(model, batch, src_len, static_key):
    import jax
    import jax.numpy as jnp
    from jax import lax

    from ..nn.layer.layers import functional_state

    (max_len, num_beams, lp_alpha) = static_key
    K = num_beams
    bos, eos, pad = model.bos_id, model.eos_id, model.pad_id
    if max_len < 2:
        raise ValueError(f"max_length must be >= 2, got {max_len}")
    if max_len > model.max_length:
        raise ValueError(
            f"max_length={max_len} exceeds the model's positional table "
            f"({model.max_length})")

    def lp(length):
        if lp_alpha == 0.0:
            return jnp.ones_like(length, jnp.float32)
        return ((5.0 + length.astype(jnp.float32)) / 6.0) ** lp_alpha

    def _encode(src):
        smask = model._src_key_mask(Tensor(src), pad)
        mem = model.transformer.encoder(
            model._embed(model.src_embed, Tensor(src)), src_mask=smask)
        return mem, smask._data

    def _logits(hidden):
        return model.out_proj(hidden)._data[:, 0].astype(jnp.float32)

    def greedy_fn(params, buffers, src):
        with functional_state(model, params, buffers):
            with no_grad_guard():
                z = jnp.int32(0)
                mem, smask = _encode(src)
                dtype = mem._data.dtype
                caches, mem_kv = model._decoder_prefill(
                    mem, batch, max_len, dtype)
                tokens = jnp.full((batch, max_len), pad, jnp.int32)
                tokens = tokens.at[:, 0].set(bos)
                finished = jnp.zeros((batch,), bool)

                def cond(state):
                    tokens, caches, pos, finished = state
                    return (pos < max_len - 1) & ~jnp.all(finished)

                def body(state):
                    tokens, caches, pos, finished = state
                    tok = lax.dynamic_slice(tokens, (z, pos), (batch, 1))
                    x = model._embed(model.tgt_embed, Tensor(tok),
                                     pos_offset=pos)
                    hidden, caches = model._decoder_step(
                        x, caches, mem_kv, pos, smask)
                    nxt = jnp.argmax(_logits(hidden),
                                     axis=-1).astype(jnp.int32)
                    nxt = jnp.where(finished, pad, nxt)
                    finished = finished | (nxt == eos)
                    tokens = lax.dynamic_update_slice(
                        tokens, nxt[:, None], (z, pos + 1))
                    return tokens, caches, pos + 1, finished

                state = (tokens, caches, z, finished)
                tokens = lax.while_loop(cond, body, state)[0]
        return tokens

    def beam_fn(params, buffers, src):
        with functional_state(model, params, buffers):
            with no_grad_guard():
                z = jnp.int32(0)
                mem, smask = _encode(src)
                dtype = mem._data.dtype
                caches, mem_kv = model._decoder_prefill(
                    mem, batch, max_len, dtype)
                # flatten beams into the batch like generation.py: tile
                # to B*K. mem_kv/smask are tiled ONCE and never reordered
                # (identical across an example's beams); only the growing
                # self-attn caches are gathered by beam parent per step
                caches = tuple(
                    (jnp.repeat(ck, K, axis=0), jnp.repeat(cv, K, axis=0))
                    for ck, cv in caches)
                mem_kv = tuple(
                    (jnp.repeat(mk, K, axis=0), jnp.repeat(mv, K, axis=0))
                    for mk, mv in mem_kv)
                smask_k = jnp.repeat(smask, K, axis=0)
                vocab = model.out_proj.weight.shape[-1]
                tokens = jnp.full((batch, K, max_len), pad, jnp.int32)
                tokens = tokens.at[:, :, 0].set(bos)
                # beam 0 active, the rest start at -inf so the first
                # expansion draws K DISTINCT tokens from beam 0
                scores = jnp.tile(
                    jnp.where(jnp.arange(K) == 0, 0.0, -jnp.inf)[None, :],
                    (batch, 1))
                finished = jnp.zeros((batch, K), bool)
                gen_len = jnp.zeros((batch, K), jnp.int32)
                pad_row = jnp.where(jnp.arange(vocab) == pad, 0.0,
                                    -jnp.inf)[None, None, :]
                barange = jnp.arange(batch, dtype=jnp.int32)[:, None] * K

                def cond(state):
                    tokens, caches, scores, finished, gen_len, pos = state
                    return (pos < max_len - 1) & ~jnp.all(finished)

                def body(state):
                    tokens, caches, scores, finished, gen_len, pos = state
                    tok = lax.dynamic_slice(
                        tokens, (z, z, pos), (batch, K, 1)).reshape(
                            batch * K, 1)
                    x = model._embed(model.tgt_embed, Tensor(tok),
                                     pos_offset=pos)
                    hidden, caches = model._decoder_step(
                        x, caches, mem_kv, pos, smask_k)
                    logp = jax.nn.log_softmax(_logits(hidden)).reshape(
                        batch, K, vocab)
                    allowed = jnp.where(finished[:, :, None], pad_row,
                                        logp)
                    cand = (scores[:, :, None] + allowed).reshape(
                        batch, K * vocab)
                    scores, idx = lax.top_k(cand, K)
                    parent = (idx // vocab).astype(jnp.int32)
                    nxt = (idx % vocab).astype(jnp.int32)
                    tokens = jnp.take_along_axis(
                        tokens, parent[:, :, None], axis=1)
                    finished = jnp.take_along_axis(finished, parent,
                                                   axis=1)
                    gen_len = jnp.take_along_axis(gen_len, parent, axis=1)
                    fp = (barange + parent).reshape(-1)
                    caches = tuple((ck[fp], cv[fp]) for ck, cv in caches)
                    tokens = lax.dynamic_update_slice(
                        tokens, nxt[:, :, None], (z, z, pos + 1))
                    gen_len = gen_len + (~finished).astype(jnp.int32)
                    finished = finished | (nxt == eos)
                    return (tokens, caches, scores, finished, gen_len,
                            pos + 1)

                state = (tokens, caches, scores, finished, gen_len, z)
                tokens, _, scores, _, gen_len, _ = lax.while_loop(
                    cond, body, state)
                best = jnp.argmax(scores / lp(gen_len), axis=1)
                tokens = jnp.take_along_axis(
                    tokens, best[:, None, None], axis=1)[:, 0]
        return tokens

    return jax.jit(greedy_fn if K == 1 else beam_fn)


def run_generate(model, src, max_length=None, num_beams=1,
                 length_penalty=0.0):
    import jax.numpy as jnp

    from ..nn.layer.layers import get_buffers_tree

    if num_beams < 1:
        raise ValueError(f"num_beams must be >= 1, got {num_beams}")
    ids = src._data if isinstance(src, Tensor) else \
        jnp.asarray(np.asarray(src))
    if ids.ndim == 1:
        ids = ids[None, :]
    batch, src_len = ids.shape
    max_len = int(max_length if max_length is not None else
                  model.max_length)
    if num_beams == 1 and length_penalty != 0.0:
        raise ValueError("length_penalty requires num_beams > 1")
    static_key = (max_len, int(num_beams), float(length_penalty))
    cache = getattr(model, "_generate_fns", None)
    if cache is None:
        cache = model._generate_fns = {}
    fn_key = (batch, src_len) + static_key
    if fn_key not in cache:
        cache[fn_key] = _build_seq2seq_fn(model, batch, src_len,
                                          static_key)
    was_training = model.training
    model.eval()
    try:
        params = {k: p._data for k, p in model.named_parameters()}
        buffers = get_buffers_tree(model)
        out = cache[fn_key](params, buffers, ids)
    finally:
        if was_training:
            model.train()
    return Tensor(out, stop_gradient=True)
