"""BERT encoder model (north-star config 3: BERT-base SQuAD fine-tune).

Built on nn.TransformerEncoder; attention flows through the shared
``scaled_dot_product_attention`` op so the Pallas kernel accelerates it too.
Reference parity: the reference's ERNIE/BERT stacks built on
nn/layer/transformer.py.
"""
from __future__ import annotations

from dataclasses import dataclass

from .. import nn
from ..framework.dispatch import call_op
from ..framework.tensor import Tensor
from ..nn import functional as F

__all__ = ["BertConfig", "BertModel", "BertForQuestionAnswering",
           "BertForSequenceClassification", "BertForMaskedLM"]


@dataclass
class BertConfig:
    vocab_size: int = 30522
    hidden_size: int = 768
    num_hidden_layers: int = 12
    num_attention_heads: int = 12
    intermediate_size: int = 3072
    max_position_embeddings: int = 512
    type_vocab_size: int = 2
    hidden_dropout_prob: float = 0.1
    attention_dropout_prob: float = 0.1
    initializer_range: float = 0.02

    @classmethod
    def bert_base(cls):
        return cls()

    @classmethod
    def tiny(cls):
        return cls(vocab_size=128, hidden_size=32, num_hidden_layers=2,
                   num_attention_heads=2, intermediate_size=64,
                   max_position_embeddings=32, hidden_dropout_prob=0.0,
                   attention_dropout_prob=0.0)


class BertEmbeddings(nn.Layer):
    def __init__(self, cfg: BertConfig):
        super().__init__()
        init = nn.initializer.Normal(0.0, cfg.initializer_range)
        attr = nn.ParamAttr(initializer=init)
        self.word_embeddings = nn.Embedding(cfg.vocab_size, cfg.hidden_size,
                                            weight_attr=attr)
        self.position_embeddings = nn.Embedding(
            cfg.max_position_embeddings, cfg.hidden_size, weight_attr=attr)
        self.token_type_embeddings = nn.Embedding(
            cfg.type_vocab_size, cfg.hidden_size, weight_attr=attr)
        self.layer_norm = nn.LayerNorm(cfg.hidden_size)
        self.dropout = nn.Dropout(cfg.hidden_dropout_prob)

    def forward(self, input_ids, token_type_ids=None, position_ids=None):
        import jax.numpy as jnp
        seq = input_ids.shape[1]
        if position_ids is None:
            position_ids = Tensor(jnp.arange(seq, dtype=jnp.int64)[None, :])
        if token_type_ids is None:
            token_type_ids = Tensor(
                jnp.zeros((input_ids.shape[0], seq), jnp.int64))
        x = self.word_embeddings(input_ids) + \
            self.position_embeddings(position_ids) + \
            self.token_type_embeddings(token_type_ids)
        return self.dropout(self.layer_norm(x))


class BertModel(nn.Layer):
    def __init__(self, cfg: BertConfig):
        super().__init__()
        self.cfg = cfg
        self.embeddings = BertEmbeddings(cfg)
        layer = nn.TransformerEncoderLayer(
            cfg.hidden_size, cfg.num_attention_heads, cfg.intermediate_size,
            dropout=cfg.hidden_dropout_prob, activation="gelu",
            attn_dropout=cfg.attention_dropout_prob)
        self.encoder = nn.TransformerEncoder(layer, cfg.num_hidden_layers)
        self.pooler = nn.Linear(cfg.hidden_size, cfg.hidden_size)

    def forward(self, input_ids, token_type_ids=None, position_ids=None,
                attention_mask=None, with_pool=True):
        import jax.numpy as jnp
        if attention_mask is not None and attention_mask.ndim == 2:
            # [B, L] 1/0 padding mask -> additive [B, 1, 1, L]
            data = attention_mask._data if isinstance(
                attention_mask, Tensor) else jnp.asarray(attention_mask)
            attention_mask = Tensor(
                ((1.0 - data.astype(jnp.float32)) *
                 -1e9)[:, None, None, :])
        x = self.embeddings(input_ids, token_type_ids, position_ids)
        x = self.encoder(x, attention_mask)
        if not with_pool:  # MLM pretraining never reads the pooler
            return x, None
        pooled = call_op("tanh", self.pooler(x[:, 0]))
        return x, pooled


class BertForQuestionAnswering(nn.Layer):
    """Span-prediction head (SQuAD): start/end logits."""

    def __init__(self, cfg: BertConfig):
        super().__init__()
        self.bert = BertModel(cfg)
        self.classifier = nn.Linear(cfg.hidden_size, 2)

    def forward(self, input_ids, token_type_ids=None, position_ids=None,
                attention_mask=None, start_positions=None,
                end_positions=None):
        seq, _ = self.bert(input_ids, token_type_ids, position_ids,
                           attention_mask, with_pool=False)
        logits = self.classifier(seq)  # [B, L, 2]
        start_logits = logits[:, :, 0]
        end_logits = logits[:, :, 1]
        if start_positions is None:
            return start_logits, end_logits
        loss = (F.cross_entropy(start_logits, start_positions) +
                F.cross_entropy(end_logits, end_positions)) / 2.0
        return loss, start_logits, end_logits


class BertForMaskedLM(nn.Layer):
    """Masked-LM pretraining head: transform (dense + gelu + LN) then a
    decoder TIED to the word-embedding table, with its own output bias —
    the standard BERT pretraining objective. Positions labeled
    ``ignore_index`` (-100, the masking convention) contribute no loss."""

    def __init__(self, cfg: BertConfig):
        super().__init__()
        self.bert = BertModel(cfg)
        init = nn.ParamAttr(initializer=nn.initializer.Normal(
            0.0, cfg.initializer_range))
        self.transform = nn.Linear(cfg.hidden_size, cfg.hidden_size,
                                   weight_attr=init)
        self.layer_norm = nn.LayerNorm(cfg.hidden_size)
        self.decoder_bias = self.create_parameter(
            [cfg.vocab_size], is_bias=True)

    def forward(self, input_ids, token_type_ids=None, position_ids=None,
                attention_mask=None, labels=None):
        seq, _ = self.bert(input_ids, token_type_ids, position_ids,
                           attention_mask, with_pool=False)
        h = self.layer_norm(F.gelu(self.transform(seq)))
        logits = call_op(
            "matmul", h, self.bert.embeddings.word_embeddings.weight,
            transpose_y=True) + self.decoder_bias
        if labels is None:
            return logits
        loss = F.cross_entropy(
            call_op("reshape", logits, shape=(-1, logits.shape[-1])),
            call_op("reshape", labels, shape=(-1,)),
            ignore_index=-100, reduction="mean")
        return loss, logits


class BertForSequenceClassification(nn.Layer):
    def __init__(self, cfg: BertConfig, num_classes=2):
        super().__init__()
        self.bert = BertModel(cfg)
        self.dropout = nn.Dropout(cfg.hidden_dropout_prob)
        self.classifier = nn.Linear(cfg.hidden_size, num_classes)

    def forward(self, input_ids, token_type_ids=None, labels=None):
        _, pooled = self.bert(input_ids, token_type_ids)
        logits = self.classifier(self.dropout(pooled))
        if labels is None:
            return logits
        return F.cross_entropy(logits, labels), logits
