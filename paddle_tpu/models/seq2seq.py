"""Encoder-decoder machine-translation model with compiled decoding.

The reference's flagship seq2seq workload is the WMT Transformer built
on nn.Transformer (python/paddle/nn/layer/transformer.py) with a python
beam-search loop. Here the whole inference pass — encoder once, then a
``lax.while_loop`` over single-token decoder steps against preallocated
self-attention K/V caches — is ONE jitted XLA program, greedy or beam
(same recurrences as models/generation.py).

TPU-first notes:
- decoder self-attn caches are fixed [B, max_len, H, D] buffers written
  with dynamic_update_slice (no growing concat -> no recompiles);
- cross-attention K/V of the (fixed) encoder memory are computed ONCE
  per layer at prefill and reused every step;
- padded source positions are masked via a [B, S] source mask, padded
  TARGET history via the step's valid-slot mask.
"""
from __future__ import annotations

import numpy as np

from .. import nn
from ..framework.dispatch import call_op
from ..framework.tensor import Tensor, no_grad_guard

__all__ = ["TransformerModel"]


def _sinusoid_table(max_len, d_model):
    pos = np.arange(max_len)[:, None].astype("float32")
    dim = np.arange(0, d_model, 2).astype("float32")
    angle = pos / np.power(10000.0, dim / d_model)
    table = np.zeros((max_len, d_model), "float32")
    table[:, 0::2] = np.sin(angle)
    table[:, 1::2] = np.cos(angle)
    return table


class TransformerModel(nn.Layer):
    """Transformer MT model: token embeddings (scaled by sqrt(d_model)),
    sinusoidal positions, nn.Transformer core, tied-or-free output head.
    Reference analog: the WMT transformer example over nn.Transformer."""

    def __init__(self, src_vocab_size, tgt_vocab_size, d_model=512,
                 nhead=8, num_encoder_layers=6, num_decoder_layers=6,
                 dim_feedforward=2048, dropout=0.1, max_length=256,
                 bos_id=0, eos_id=1, pad_id=0):
        super().__init__()
        self.d_model = d_model
        self.max_length = max_length
        self.bos_id, self.eos_id, self.pad_id = bos_id, eos_id, pad_id
        self.src_embed = nn.Embedding(src_vocab_size, d_model)
        self.tgt_embed = nn.Embedding(tgt_vocab_size, d_model)
        self.register_buffer(
            "pos_table", Tensor(_sinusoid_table(max_length, d_model)))
        self.transformer = nn.Transformer(
            d_model=d_model, nhead=nhead,
            num_encoder_layers=num_encoder_layers,
            num_decoder_layers=num_decoder_layers,
            dim_feedforward=dim_feedforward, dropout=dropout)
        self.out_proj = nn.Linear(d_model, tgt_vocab_size)

    # -- embedding helpers --------------------------------------------------
    def _embed(self, table, ids, pos_offset=0):
        import jax.numpy as jnp
        x = table(ids) * (self.d_model ** 0.5)
        seq = ids.shape[1]
        if isinstance(pos_offset, int) and pos_offset + seq > \
                self.max_length:
            raise ValueError(
                f"sequence length {pos_offset + seq} exceeds the model's "
                f"positional table (max_length={self.max_length})")
        if isinstance(pos_offset, int) and pos_offset == 0:
            pe = self.pos_table._data[:seq]
        else:
            idx = pos_offset + jnp.arange(seq)
            pe = jnp.take(self.pos_table._data, idx, axis=0)
        return Tensor(x._data + pe[None, :, :].astype(x._data.dtype))

    @staticmethod
    def _src_key_mask(src, pad_id):
        """[B, 1, 1, S] bool: True = attend (non-pad source token)."""
        import jax.numpy as jnp
        ids = src._data if isinstance(src, Tensor) else jnp.asarray(src)
        return Tensor((ids != pad_id)[:, None, None, :])

    def forward(self, src, tgt):
        """Teacher-forcing logits [B, T, V]; source pads masked, target
        causal."""
        import jax.numpy as jnp
        src = src if isinstance(src, Tensor) else Tensor(jnp.asarray(src))
        tgt = tgt if isinstance(tgt, Tensor) else Tensor(jnp.asarray(tgt))
        smask = self._src_key_mask(src, self.pad_id)
        T = tgt.shape[1]
        causal = Tensor(
            (jnp.arange(T)[:, None] >= jnp.arange(T)[None, :])
            [None, None, :, :])
        mem = self.transformer.encoder(
            self._embed(self.src_embed, src), src_mask=smask)
        out = self.transformer.decoder(
            self._embed(self.tgt_embed, tgt), mem, tgt_mask=causal,
            memory_mask=smask)
        return self.out_proj(out)

    # -- compiled decode ----------------------------------------------------
    def _decoder_prefill(self, mem, batch, max_len, dtype):
        """Returns (self-attn caches, memory K/V): per decoder layer,
        preallocated self-attn K/V buffers, and the cross-attention K/V
        of the fixed memory computed ONCE. They are separate structures
        because only the self-attn caches are beam-reordered per step —
        memory K/V rows are identical across an example's beams."""
        import jax.numpy as jnp
        caches, mem_kv = [], []
        for layer in self.transformer.decoder.layers:
            a = layer.self_attn
            shape = (batch, max_len, a.num_heads, a.head_dim)
            caches.append((jnp.zeros(shape, dtype),
                           jnp.zeros(shape, dtype)))
            mk = layer.cross_attn._split_heads(layer.cross_attn.k_proj(mem))
            mv = layer.cross_attn._split_heads(layer.cross_attn.v_proj(mem))
            mem_kv.append((mk._data, mv._data))
        return caches, mem_kv

    def _decoder_step(self, x, caches, mem_kv, pos, smask_data):
        """One decoder token x [B, 1, E] at slot pos; returns (hidden,
        caches). Pre-LN/post-LN follows the layer's configuration via its
        norms, mirroring TransformerDecoderLayer.forward with cache."""
        import jax.numpy as jnp
        from jax import lax
        from ..nn import functional as F
        dec = self.transformer.decoder
        z = jnp.int32(0)
        pos = jnp.asarray(pos, jnp.int32)
        new_caches = []
        out = x
        for layer, (ck, cv), (mk, mv) in zip(dec.layers, caches, mem_kv):
            residual = out
            h = layer.norm1(out) if layer.normalize_before else out
            a = layer.self_attn
            q = a._split_heads(a.q_proj(h))
            k = a._split_heads(a.k_proj(h))
            v = a._split_heads(a.v_proj(h))
            ck = lax.dynamic_update_slice(
                ck, k._data.astype(ck.dtype), (z, pos, z, z))
            cv = lax.dynamic_update_slice(
                cv, v._data.astype(cv.dtype), (z, pos, z, z))
            valid = (jnp.arange(ck.shape[1]) <= pos)[None, None, None, :]
            sa = F.scaled_dot_product_attention(
                q, Tensor(ck), Tensor(cv), attn_mask=Tensor(valid))
            sa = a.out_proj(a._merge_heads(sa))
            out = residual + sa
            if not layer.normalize_before:
                out = layer.norm1(out)
            residual = out
            h = layer.norm2(out) if layer.normalize_before else out
            c = layer.cross_attn
            qc = c._split_heads(c.q_proj(h))
            ca = F.scaled_dot_product_attention(
                qc, Tensor(mk), Tensor(mv),
                attn_mask=Tensor(smask_data))
            ca = c.out_proj(c._merge_heads(ca))
            out = residual + ca
            if not layer.normalize_before:
                out = layer.norm2(out)
            residual = out
            h = layer.norm3(out) if layer.normalize_before else out
            h = layer.linear2(call_op(layer.activation, layer.linear1(h)))
            out = residual + h
            if not layer.normalize_before:
                out = layer.norm3(out)
            new_caches.append((ck, cv))
        if dec.norm is not None:
            out = dec.norm(out)
        return out, new_caches

    def generate(self, src, max_length=None, num_beams=1,
                 length_penalty=0.0):
        """Compiled translation: encoder once + while_loop decode from
        bos_id until eos_id or the length budget; greedy (num_beams=1)
        or beam search. Returns [B, max_length] with pads after EOS."""
        from .seq2seq_decode import run_generate
        return run_generate(self, src, max_length, num_beams,
                            length_penalty)
