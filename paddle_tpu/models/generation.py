"""Autoregressive generation, compiled once — the TPU serving decode path.

Design (TPU-first):
- The whole generate loop (prefill + ``lax.while_loop`` over decode steps)
  is ONE jitted XLA program. The KV cache is preallocated at
  ``[B, prompt+max_new, H, D]`` per layer and written with
  ``dynamic_update_slice`` — shapes never change, so there is exactly one
  compile per (batch, prompt_len, max_new, sampling-mode) class.
  Temperature is a traced scalar: changing it never recompiles.
- Early exit: the while_loop condition stops as soon as every sequence
  has emitted EOS — unlike a fixed-length scan, short answers don't pay
  for the full budget.
- Sampling (greedy / temperature / top-k / top-p) runs on-device with
  ``jax.random.categorical``; no host round-trip per token.

Reference analog: the reference serves decoder LMs through
fused_multi_transformer's fixed-capacity CacheKV
(paddle/fluid/operators/fused/fused_multi_transformer_op.cu:1) driven by
a Python sampling loop; here the loop itself is compiled.
"""
from __future__ import annotations

from dataclasses import dataclass
from typing import Optional

import numpy as np

from ..framework.tensor import Tensor, no_grad_guard

__all__ = ["GenerationConfig", "generate"]


@dataclass
class GenerationConfig:
    max_new_tokens: int = 32
    do_sample: bool = False
    temperature: float = 1.0
    top_k: int = 0            # 0 = disabled
    top_p: float = 1.0        # 1.0 = disabled
    eos_token_id: Optional[int] = None
    pad_token_id: int = 0
    seed: Optional[int] = None


def _pick_token(logits, key, do_sample, top_k, top_p, temperature):
    """logits: jnp [B, V] f32 -> jnp [B] int32. top_k/top_p are static
    (part of the compile key); temperature is traced."""
    import jax
    import jax.numpy as jnp
    if not do_sample:
        return jnp.argmax(logits, axis=-1).astype(jnp.int32)
    logits = logits / jnp.maximum(temperature, 1e-6)
    if top_k and top_k > 0:
        kth = jax.lax.top_k(logits, top_k)[0][:, -1:]
        logits = jnp.where(logits < kth, -jnp.inf, logits)
    if top_p < 1.0:
        sort_idx = jnp.argsort(-logits, axis=-1)
        sorted_logits = jnp.take_along_axis(logits, sort_idx, axis=-1)
        probs = jax.nn.softmax(sorted_logits, axis=-1)
        cum_excl = jnp.cumsum(probs, axis=-1) - probs
        keep_sorted = cum_excl < top_p          # always keeps the top-1
        inv = jnp.argsort(sort_idx, axis=-1)
        keep = jnp.take_along_axis(keep_sorted, inv, axis=-1)
        logits = jnp.where(keep, logits, -jnp.inf)
    return jax.random.categorical(key, logits, axis=-1).astype(jnp.int32)


def _build_generate_fn(model, batch, prompt_len, static_key):
    import jax
    import jax.numpy as jnp
    from jax import lax

    from ..nn.layer.layers import functional_state

    (max_new, do_sample, top_k, top_p, eos, pad) = static_key
    gpt = model.gpt if hasattr(model, "gpt") else model
    if max_new < 1:
        raise ValueError(f"max_new_tokens must be >= 1, got {max_new}")
    if not 0.0 < top_p <= 1.0:
        # top_p=0 would mask EVERY logit to -inf and categorical would
        # silently emit token 0 each step
        raise ValueError(f"top_p must be in (0, 1], got {top_p}")
    if top_k < 0:
        raise ValueError(f"top_k must be >= 0, got {top_k}")
    top_k = min(top_k, gpt.cfg.vocab_size)  # lax.top_k caps at vocab
    total_len = prompt_len + max_new
    if total_len > gpt.cfg.max_position_embeddings:
        raise ValueError(
            f"prompt_len+max_new_tokens={total_len} exceeds "
            f"max_position_embeddings={gpt.cfg.max_position_embeddings}")

    def fn(params, buffers, ids, key, temperature):
        with functional_state(model, params, buffers):
            with no_grad_guard():
                dtype = params[next(iter(params))].dtype
                caches = gpt.init_cache(batch, total_len, dtype)
                hidden, caches = gpt.prefill(
                    Tensor(ids, stop_gradient=True), caches)
                logits = gpt.logits(hidden)._data[:, 0].astype(jnp.float32)
                key, sub = jax.random.split(key)
                first = _pick_token(logits, sub, do_sample, top_k, top_p,
                                    temperature)
                finished = ((first == eos) if eos is not None
                            else jnp.zeros((batch,), bool))
                tokens = jnp.concatenate(
                    [ids.astype(jnp.int32),
                     jnp.full((batch, max_new), pad, jnp.int32)], axis=1)
                tokens = lax.dynamic_update_slice(
                    tokens, first[:, None], (jnp.int32(0), jnp.int32(prompt_len)))

                def cond(state):
                    tokens, caches, pos, finished, key = state
                    return (pos < total_len - 1) & ~jnp.all(finished)

                def body(state):
                    tokens, caches, pos, finished, key = state
                    z = jnp.int32(0)
                    tok = lax.dynamic_slice(tokens, (z, pos), (batch, 1))
                    hidden, caches = gpt.decode_step(
                        Tensor(tok, stop_gradient=True), caches, pos)
                    logits = gpt.logits(hidden)._data[:, 0].astype(
                        jnp.float32)
                    key, sub = jax.random.split(key)
                    nxt = _pick_token(logits, sub, do_sample, top_k, top_p,
                                      temperature)
                    if eos is not None:
                        nxt = jnp.where(finished, pad, nxt)
                        finished = finished | (nxt == eos)
                    tokens = lax.dynamic_update_slice(
                        tokens, nxt[:, None], (z, pos + 1))
                    return tokens, caches, pos + 1, finished, key

                state = (tokens, caches, jnp.int32(prompt_len), finished,
                         key)
                tokens = lax.while_loop(cond, body, state)[0]
        return tokens

    return jax.jit(fn)


def generate(model, input_ids, max_new_tokens=32, do_sample=False,
             temperature=1.0, top_k=0, top_p=1.0, eos_token_id=None,
             pad_token_id=0, seed=None, config=None):
    """Generate ``max_new_tokens`` continuations of ``input_ids`` [B, S].

    Returns a Tensor [B, S+max_new_tokens]; positions after an
    ``eos_token_id`` are filled with ``pad_token_id``. Prompts are assumed
    uniform-length (pad + mask-free — the standard batched-serve shape
    class; ragged prompts should be bucketed by the caller, see
    io.BucketedBatchSampler). A ``GenerationConfig`` may be passed as
    ``config=`` instead of the individual kwargs.
    """
    import jax
    import jax.numpy as jnp

    from ..nn.layer.layers import get_buffers_tree

    if config is not None:
        explicit = {k: v for k, v in [
            ("max_new_tokens", max_new_tokens != 32),
            ("do_sample", do_sample is not False),
            ("temperature", temperature != 1.0),
            ("top_k", top_k != 0), ("top_p", top_p != 1.0),
            ("eos_token_id", eos_token_id is not None),
            ("pad_token_id", pad_token_id != 0),
            ("seed", seed is not None)] if v}
        if explicit:
            raise ValueError(
                f"pass either config= or individual kwargs, not both "
                f"(got config plus {sorted(explicit)})")
        max_new_tokens = config.max_new_tokens
        do_sample = config.do_sample
        temperature = config.temperature
        top_k = config.top_k
        top_p = config.top_p
        eos_token_id = config.eos_token_id
        pad_token_id = config.pad_token_id
        seed = config.seed

    ids = input_ids._data if isinstance(input_ids, Tensor) else \
        jnp.asarray(np.asarray(input_ids))
    if ids.ndim == 1:
        ids = ids[None, :]
    batch, prompt_len = ids.shape
    static_key = (int(max_new_tokens), bool(do_sample), int(top_k),
                  float(top_p),
                  None if eos_token_id is None else int(eos_token_id),
                  int(pad_token_id))
    cache = getattr(model, "_generate_fns", None)
    if cache is None:
        cache = model._generate_fns = {}
    fn_key = (batch, prompt_len) + static_key
    if fn_key not in cache:
        cache[fn_key] = _build_generate_fn(model, batch, prompt_len,
                                           static_key)
    was_training = model.training
    model.eval()
    try:
        params = {k: p._data for k, p in model.named_parameters()}
        buffers = get_buffers_tree(model)
        if not do_sample:
            # greedy never consumes the key; a fixed one avoids advancing
            # the global generator (would desync seed-pinned experiments)
            key = jax.random.PRNGKey(0)
        elif seed is None:
            # fresh draw per call, controlled by paddle.seed(): an unseeded
            # sampling loop must not return identical "samples" every call
            from ..framework import random as _random
            key = _random.next_key()
            if jnp.issubdtype(key.dtype, jax.dtypes.prng_key):
                # normalize new-style typed keys to the legacy uint32 form
                # so seeded and unseeded calls share ONE compiled program
                key = jax.random.key_data(key)
        else:
            key = jax.random.PRNGKey(int(seed))
        out = cache[fn_key](params, buffers, ids, key,
                            jnp.float32(temperature))
    finally:
        if was_training:
            model.train()
    return Tensor(out, stop_gradient=True)
