"""Autoregressive generation, compiled once — the TPU serving decode path.

Design (TPU-first):
- The whole generate loop (prefill + ``lax.while_loop`` over decode steps)
  is ONE jitted XLA program. The KV cache is preallocated at
  ``[B, prompt+max_new, H, D]`` per layer and written with
  ``dynamic_update_slice`` — shapes never change, so there is exactly one
  compile per (batch, prompt_len, max_new, sampling-mode) class.
  Temperature is a traced scalar: changing it never recompiles.
- Early exit: the while_loop condition stops as soon as every sequence
  has emitted EOS — unlike a fixed-length scan, short answers don't pay
  for the full budget.
- Sampling (greedy / temperature / top-k / top-p) runs on-device with
  ``jax.random.categorical``; no host round-trip per token.

Reference analog: the reference serves decoder LMs through
fused_multi_transformer's fixed-capacity CacheKV
(paddle/fluid/operators/fused/fused_multi_transformer_op.cu:1) driven by
a Python sampling loop; here the loop itself is compiled.
"""
from __future__ import annotations

from dataclasses import dataclass
from typing import Optional

import numpy as np

from ..framework.tensor import Tensor, no_grad_guard

__all__ = ["GenerationConfig", "generate", "save_for_serving",
           "shard_params_megatron", "megatron_param_specs",
           "build_slot_prefill_fn",
           "build_slot_decode_fn", "build_paged_prefill_fn",
           "build_paged_decode_fn", "build_fused_step_fn",
           "build_sharded_paged_prefill_fn",
           "build_sharded_paged_decode_fn",
           "build_sharded_fused_step_fn",
           "build_draft_prefill_fn", "build_draft_propose_fn",
           "build_draft_propose_scan_fn",
           "build_spec_verify_fn", "make_draft_model"]


def shard_params_megatron(model, mesh, mp_axis="mp"):
    """Place the model's parameters in the Megatron tensor-parallel
    layout over ``mesh``: attention q/k/v and MLP-in column-sharded on
    the output dim, out-proj/MLP-out row-sharded on the input dim
    (weights are [in, out]), everything else replicated. One shared
    policy for the sharded-decode tests and the multichip dryrun."""
    import jax
    from jax.sharding import NamedSharding, PartitionSpec as P

    col = NamedSharding(mesh, P(None, mp_axis))
    row = NamedSharding(mesh, P(mp_axis, None))
    rep = NamedSharding(mesh, P())
    for name, p in model.named_parameters():
        if p._data.ndim == 2 and any(k in name for k in (
                "q_proj.weight", "k_proj.weight", "v_proj.weight",
                "mlp_fc.weight")):
            sh = col
        elif p._data.ndim == 2 and any(k in name for k in (
                "out_proj.weight", "mlp_proj.weight")):
            sh = row
        else:
            sh = rep
        p._data = jax.device_put(p._data, sh)


def megatron_param_specs(model, mp_axis="mp"):
    """The flat ``{param_name: PartitionSpec}`` dict matching
    :func:`shard_params_megatron`'s placement, keyed like
    ``get_params_tree`` — the params entry of a ``shard_map``'s
    ``in_specs`` over the tensor-parallel serving steps. Column-parallel
    weights split their OUTPUT dim, row-parallel weights their INPUT dim
    (weights are [in, out]); everything else (biases, LayerNorms,
    embeddings, the tied LM head) is replicated."""
    from jax.sharding import PartitionSpec as P

    specs = {}
    for name, p in model.named_parameters():
        if p._data.ndim == 2 and any(k in name for k in (
                "q_proj.weight", "k_proj.weight", "v_proj.weight",
                "mlp_fc.weight")):
            specs[name] = P(None, mp_axis)
        elif p._data.ndim == 2 and any(k in name for k in (
                "out_proj.weight", "mlp_proj.weight")):
            specs[name] = P(mp_axis, None)
        else:
            specs[name] = P()
    return specs


def save_for_serving(model, path, batch, prompt_len, runtime_key=False,
                     **generate_kwargs):
    """Export the COMPILED generate loop as an inference artifact: one
    StableHLO program (prefill + while_loop decode + sampling, weights
    baked in) serving ``ids [batch, prompt_len] -> tokens``. Loadable by
    jit.load / inference.create_predictor — including from C via the
    PDT_* API — with no Python model code at serve time. Sampling
    strategy and budgets are FROZEN into the artifact (pass them here);
    shapes are fixed to the serving shape class, the same contract as
    the BatchingEngine's pow2 buckets. Reference analog: exporting
    fused_multi_transformer inference programs for analysis_predictor
    (paddle/fluid/inference/api/analysis_predictor.cc:1).

    Sampling: with ``runtime_key=True`` the PRNG key is a RUNTIME INPUT
    of the artifact — it serves ``(ids [batch, prompt_len] int32,
    key [2] uint32) -> tokens``, so the caller draws per request and
    two calls on the same prompt can differ (the reference's serving
    loop draws per request; this was the standing per-request-sampling
    gap). Requires ``do_sample=True`` and no ``seed`` (the seed IS the
    runtime key now).

    Without ``runtime_key`` the key is a trace CONSTANT in the
    artifact, so a sampled export returns the same tokens for a given
    prompt on every call — sampling picks a fixed draw per artifact,
    it does not re-randomize per request. That is only sane when the
    caller chose the draw, so an unseeded ``do_sample=True`` export is
    rejected (pass ``runtime_key=True`` for per-request draws)."""
    import jax.numpy as jnp

    from .. import jit
    from ..nn.layer.layers import get_buffers_tree
    from ..static import InputSpec

    if runtime_key:
        unknown = sorted(set(generate_kwargs) - set(_GEN_DEFAULTS))
        if unknown:
            raise ValueError(
                f"runtime_key export got unsupported kwargs: {unknown}")
        resolved = dict(_GEN_DEFAULTS)
        resolved.update(generate_kwargs)
        if not resolved["do_sample"]:
            raise ValueError(
                "runtime_key=True requires do_sample=True: a greedy "
                "export never consumes the key, so a key input would "
                "be dead weight in the artifact's signature")
        if resolved["seed"] is not None:
            raise ValueError(
                "runtime_key=True replaces seed=: the key arrives per "
                "call at serve time (jax.random.PRNGKey(seed) makes "
                "one)")
        if resolved["num_beams"] != 1:
            raise ValueError("runtime_key=True requires num_beams=1 "
                             "(beam search is deterministic)")
        static_key = (
            int(resolved["max_new_tokens"]), True,
            int(resolved["top_k"]), float(resolved["top_p"]),
            None if resolved["eos_token_id"] is None
            else int(resolved["eos_token_id"]),
            int(resolved["pad_token_id"]), False)
        fn = _build_generate_fn(model, int(batch), int(prompt_len),
                                static_key)
        was_training = model.training
        model.eval()
        try:
            params = {k: p._data for k, p in model.named_parameters()}
            buffers = get_buffers_tree(model)
            temp = float(resolved["temperature"])

            def _serve_keyed(ids, key):
                return fn(params, buffers, ids, key, jnp.float32(temp),
                          jnp.int32(0))

            return jit.save(
                _serve_keyed, path,
                input_spec=[InputSpec([int(batch), int(prompt_len)],
                                      "int32"),
                            InputSpec([2], "uint32")])
        finally:
            if was_training:
                model.train()

    if generate_kwargs.get("do_sample") and \
            generate_kwargs.get("seed") is None:
        raise ValueError(
            "save_for_serving(do_sample=True) requires an explicit seed "
            "(or runtime_key=True for per-request draws): the key is "
            "baked into the artifact as a constant, so the export "
            "freezes ONE draw per prompt — make that choice explicit "
            "(and avoid silently advancing the global RNG at export "
            "time)")

    def _serve(ids):
        return generate(model, ids, **generate_kwargs)

    return jit.save(_serve, path,
                    input_spec=[InputSpec([int(batch), int(prompt_len)],
                                          "int32")])


@dataclass
class GenerationConfig:
    max_new_tokens: int = 32
    do_sample: bool = False
    temperature: float = 1.0
    top_k: int = 0            # 0 = disabled
    top_p: float = 1.0        # 1.0 = disabled
    eos_token_id: Optional[int] = None
    pad_token_id: int = 0
    seed: Optional[int] = None
    num_beams: int = 1        # >1 = deterministic beam search
    length_penalty: float = 0.0   # GNMT ((5+len)/6)^alpha; 0 = off


def _filter_logits(logits, top_k, top_p, temperature):
    """The sampling truncation shared by :func:`_pick_token` and
    :func:`_sample_probs`: temperature scaling, then static top-k /
    top-p masking to ``-inf``. Works over any leading batch shape
    (``[..., V]``)."""
    import jax
    import jax.numpy as jnp
    logits = logits / jnp.maximum(temperature, 1e-6)
    if top_k and top_k > 0:
        kth = jax.lax.top_k(logits, top_k)[0][..., -1:]
        logits = jnp.where(logits < kth, -jnp.inf, logits)
    if top_p < 1.0:
        sort_idx = jnp.argsort(-logits, axis=-1)
        sorted_logits = jnp.take_along_axis(logits, sort_idx, axis=-1)
        probs = jax.nn.softmax(sorted_logits, axis=-1)
        cum_excl = jnp.cumsum(probs, axis=-1) - probs
        keep_sorted = cum_excl < top_p          # always keeps the top-1
        inv = jnp.argsort(sort_idx, axis=-1)
        keep = jnp.take_along_axis(keep_sorted, inv, axis=-1)
        logits = jnp.where(keep, logits, -jnp.inf)
    return logits


def _pick_token(logits, key, do_sample, top_k, top_p, temperature):
    """logits: jnp [B, V] f32 -> jnp [B] int32. top_k/top_p are static
    (part of the compile key); temperature is traced."""
    import jax
    import jax.numpy as jnp
    if not do_sample:
        return jnp.argmax(logits, axis=-1).astype(jnp.int32)
    return jax.random.categorical(
        key, _filter_logits(logits, top_k, top_p, temperature),
        axis=-1).astype(jnp.int32)


def _sample_probs(logits, sample_mask, top_k, top_p, temperature):
    """The per-row SAMPLING DISTRIBUTION as explicit probabilities
    ``[N, V]`` f32 — what speculative decoding's rejection sampling
    needs on both sides of the accept ratio. Sampled rows get the
    softmax of the ``_filter_logits`` truncation (the distribution
    ``categorical(filtered_logits)`` draws from — categorical is
    shift-invariant, so the two agree exactly); greedy rows get the
    DEGENERATE one-hot at the argmax, which makes greedy speculative
    acceptance collapse to token equality with exact parity.

    ``sample_mask [N]`` bool and ``temperature [N]`` are traced."""
    import jax
    import jax.numpy as jnp
    v = logits.shape[-1]
    onehot = jax.nn.one_hot(jnp.argmax(logits, axis=-1), v,
                            dtype=jnp.float32)
    soft = jax.nn.softmax(
        _filter_logits(logits, top_k, top_p, temperature[..., None]),
        axis=-1)
    return jnp.where(sample_mask[..., None], soft, onehot)


def _categorical_probs(key, probs):
    """Draw per-row tokens from explicit probabilities ``[..., V]``
    (zero-probability entries are exactly ``-inf`` in log space, so a
    one-hot distribution picks its token DETERMINISTICALLY — the greedy
    degenerate case of the speculative sampler)."""
    import jax
    import jax.numpy as jnp
    logp = jnp.where(probs > 0, jnp.log(jnp.maximum(probs, 1e-38)),
                     -jnp.inf)
    return jax.random.categorical(key, logp, axis=-1).astype(jnp.int32)


def _spec_accept(p_probs, q_probs, drafts, n_spec, base_probs, key):
    """Device-side speculative rejection sampling (one decode cycle).

    Per slot ``s``, the draft proposed ``drafts[s, :n_spec[s]]`` and
    the verify launch produced the target's sampling distribution
    ``p_probs[s, j]`` at each candidate row ``j`` (the row that FED
    candidate ``j``'s predecessor); ``q_probs[s, j]`` is the draft's
    proposal distribution for that candidate. Standard rejection
    sampling: candidate ``d`` is accepted while ``u * q(d) < p(d)``
    (strict, with ``u ~ U[0, 1)``); the first rejected position emits a
    token from the residual ``max(p - q, 0)`` renormalized. Greedy rows
    carry one-hot distributions, collapsing all of this to exact
    argmax-equality acceptance and argmax correction — the degenerate
    case with EXACT parity to the non-speculative engine.

    ``base_probs [S, V]`` is each slot's last-row distribution, drawn
    for slots that verified nothing this launch (``n_spec == 0``: a
    prefill chunk finishing its feed emits its first token from it).

    Returns ``(accepted [S] int32, token [S] int32)`` — ``token`` is
    the corrected/residual draw when ``accepted < n_spec``, the base
    draw when ``n_spec == 0``, and unused garbage when every candidate
    was accepted (the scheduler emits the accepted drafts instead).
    """
    import jax
    import jax.numpy as jnp
    s_, k_, _v = p_probs.shape
    ku, kr, kb = jax.random.split(key, 3)
    u = jax.random.uniform(ku, (s_, k_))
    pd = jnp.take_along_axis(p_probs, drafts[..., None], axis=-1)[..., 0]
    qd = jnp.take_along_axis(q_probs, drafts[..., None], axis=-1)[..., 0]
    valid = jnp.arange(k_)[None, :] < n_spec[:, None]
    acc = valid & (u * qd < pd)
    accepted = jnp.sum(jnp.cumprod(acc.astype(jnp.int32), axis=1),
                       axis=1)                  # leading-accept count
    ridx = jnp.minimum(accepted, k_ - 1)
    pr = jnp.take_along_axis(p_probs, ridx[:, None, None], axis=1)[:, 0]
    qr = jnp.take_along_axis(q_probs, ridx[:, None, None], axis=1)[:, 0]
    res = jnp.maximum(pr - qr, 0.0)
    rsum = jnp.sum(res, axis=-1, keepdims=True)
    # a rejection implies p != q somewhere, so the residual has mass;
    # the p fallback only guards numerically-identical distributions
    res = jnp.where(rsum > 0, res / jnp.maximum(rsum, 1e-38), pr)
    rejected = accepted < n_spec
    token = jnp.where(rejected & (n_spec > 0),
                      _categorical_probs(kr, res),
                      _categorical_probs(kb, base_probs))
    return accepted, token


def _mask_preamble(attn_mask, batch, max_new):
    """(key_valid [B, total_len] bool over the prompt, real_len [B, 1])
    for a left-padded prompt mask — shared by the greedy/sampling and
    beam builders so the left-pad invariant lives in one place."""
    import jax.numpy as jnp
    key_valid = jnp.concatenate(
        [attn_mask.astype(bool), jnp.zeros((batch, max_new), bool)], axis=1)
    real_len = attn_mask.astype(jnp.int32).sum(axis=1, keepdims=True)
    return key_valid, real_len


def _step_mask(key_valid, real_len, prompt_len, total_len, pos, tile=1):
    """Per-decode-step key validity (prompt mask | generated slots up to
    pos) and per-example logical positions; tile>1 repeats rows for
    flattened beams."""
    import jax.numpy as jnp
    r = jnp.arange(total_len)
    kv = key_valid | ((r >= prompt_len) & (r <= pos))[None, :]
    positions = real_len + (pos - prompt_len)
    if tile > 1:
        kv = jnp.repeat(kv, tile, axis=0)
        positions = jnp.repeat(positions, tile, axis=0)
    return kv, positions


def _build_generate_fn(model, batch, prompt_len, static_key):
    import jax
    import jax.numpy as jnp
    from jax import lax

    from ..nn.layer.layers import functional_state

    (max_new, do_sample, top_k, top_p, eos, pad, has_mask) = static_key
    gpt = model.gpt if hasattr(model, "gpt") else model
    if max_new < 1:
        raise ValueError(f"max_new_tokens must be >= 1, got {max_new}")
    if not 0.0 < top_p <= 1.0:
        # top_p=0 would mask EVERY logit to -inf and categorical would
        # silently emit token 0 each step
        raise ValueError(f"top_p must be in (0, 1], got {top_p}")
    if top_k < 0:
        raise ValueError(f"top_k must be >= 0, got {top_k}")
    top_k = min(top_k, gpt.cfg.vocab_size)  # lax.top_k caps at vocab
    total_len = prompt_len + max_new
    if total_len > gpt.cfg.max_position_embeddings:
        raise ValueError(
            f"prompt_len+max_new_tokens={total_len} exceeds "
            f"max_position_embeddings={gpt.cfg.max_position_embeddings}")

    def fn(params, buffers, ids, key, temperature, attn_mask):
        with functional_state(model, params, buffers):
            with no_grad_guard():
                dtype = params[next(iter(params))].dtype
                z = jnp.int32(0)
                caches = gpt.init_cache(batch, total_len, dtype)
                if has_mask:
                    # ragged (left-padded) prompts: pads are masked out of
                    # attention forever; logical positions count only real
                    # tokens, so each example decodes at real_len + t
                    key_valid, real_len = _mask_preamble(
                        attn_mask, batch, max_new)
                else:
                    key_valid, real_len = None, None
                hidden, caches = gpt.prefill(
                    Tensor(ids, stop_gradient=True), caches,
                    key_valid=None if key_valid is None
                    else key_valid[:, :prompt_len])
                logits = gpt.logits(hidden)._data[:, 0].astype(jnp.float32)
                key, sub = jax.random.split(key)
                first = _pick_token(logits, sub, do_sample, top_k, top_p,
                                    temperature)
                finished = ((first == eos) if eos is not None
                            else jnp.zeros((batch,), bool))
                tokens = jnp.concatenate(
                    [ids.astype(jnp.int32),
                     jnp.full((batch, max_new), pad, jnp.int32)], axis=1)
                tokens = lax.dynamic_update_slice(
                    tokens, first[:, None], (z, jnp.int32(prompt_len)))

                def cond(state):
                    tokens, caches, pos, finished, key = state
                    return (pos < total_len - 1) & ~jnp.all(finished)

                def body(state):
                    tokens, caches, pos, finished, key = state
                    tok = lax.dynamic_slice(tokens, (z, pos), (batch, 1))
                    if has_mask:
                        kv, positions = _step_mask(
                            key_valid, real_len, prompt_len, total_len,
                            pos)
                    else:
                        kv, positions = None, None
                    hidden, caches = gpt.decode_step(
                        Tensor(tok, stop_gradient=True), caches, pos,
                        key_valid=kv, positions=positions)
                    logits = gpt.logits(hidden)._data[:, 0].astype(
                        jnp.float32)
                    key, sub = jax.random.split(key)
                    nxt = _pick_token(logits, sub, do_sample, top_k, top_p,
                                      temperature)
                    if eos is not None:
                        nxt = jnp.where(finished, pad, nxt)
                        finished = finished | (nxt == eos)
                    tokens = lax.dynamic_update_slice(
                        tokens, nxt[:, None], (z, pos + 1))
                    return tokens, caches, pos + 1, finished, key

                state = (tokens, caches, jnp.int32(prompt_len), finished,
                         key)
                tokens = lax.while_loop(cond, body, state)[0]
        return tokens

    return jax.jit(fn)


def _build_beam_fn(model, batch, prompt_len, static_key):
    """Batched beam search, compiled: beams live as a flattened [B*K]
    batch so the SAME decode_step program serves both strategies; each
    step reorders the KV cache by beam parent with one gather. Finished
    beams stay in the pool with frozen scores (only the pad continuation
    is allowed, at logprob 0). Reference analog:
    python/paddle/nn/decode.py BeamSearchDecoder semantics (tile_beam /
    gather_tree), rebuilt as one XLA program."""
    import jax
    import jax.numpy as jnp
    from jax import lax

    from ..nn.layer.layers import functional_state

    (max_new, num_beams, eos, pad, length_penalty, has_mask) = static_key
    gpt = model.gpt if hasattr(model, "gpt") else model
    K = num_beams
    vocab = gpt.cfg.vocab_size
    if max_new < 1:
        raise ValueError(f"max_new_tokens must be >= 1, got {max_new}")
    if not 2 <= K <= vocab:
        raise ValueError(f"num_beams must be in [2, vocab], got {K}")
    total_len = prompt_len + max_new
    if total_len > gpt.cfg.max_position_embeddings:
        raise ValueError(
            f"prompt_len+max_new_tokens={total_len} exceeds "
            f"max_position_embeddings={gpt.cfg.max_position_embeddings}")

    def lp(length):
        # GNMT length penalty ((5+len)/6)^alpha; alpha=0 -> pure logprob
        if length_penalty == 0.0:
            return jnp.ones_like(length, jnp.float32)
        return ((5.0 + length.astype(jnp.float32)) / 6.0) ** length_penalty

    def fn(params, buffers, ids, attn_mask):
        with functional_state(model, params, buffers):
            with no_grad_guard():
                dtype = params[next(iter(params))].dtype
                z = jnp.int32(0)
                if has_mask:
                    key_valid, real_len = _mask_preamble(
                        attn_mask, batch, max_new)
                else:
                    key_valid, real_len = None, None
                # prefill once at [B], then tile the caches to [B*K]
                caches = gpt.init_cache(batch, total_len, dtype)
                hidden, caches = gpt.prefill(
                    Tensor(ids, stop_gradient=True), caches,
                    key_valid=None if key_valid is None
                    else key_valid[:, :prompt_len])
                logp0 = jax.nn.log_softmax(
                    gpt.logits(hidden)._data[:, 0].astype(jnp.float32))
                scores, first = lax.top_k(logp0, K)        # [B, K]
                first = first.astype(jnp.int32)
                caches = tuple(
                    (jnp.repeat(ck, K, axis=0), jnp.repeat(cv, K, axis=0))
                    for ck, cv in caches)
                tokens = jnp.concatenate(
                    [ids.astype(jnp.int32),
                     jnp.full((batch, max_new), pad, jnp.int32)], axis=1)
                tokens = jnp.repeat(tokens[:, None, :], K, axis=1)
                tokens = lax.dynamic_update_slice(
                    tokens, first[:, :, None], (z, z, jnp.int32(prompt_len)))
                finished = (first == eos) if eos is not None else \
                    jnp.zeros((batch, K), bool)
                gen_len = jnp.ones((batch, K), jnp.int32)
                # one-hot pad row at -inf elsewhere: the only allowed
                # continuation of a finished beam, contributing logprob 0
                pad_row = jnp.where(jnp.arange(vocab) == pad, 0.0,
                                    -jnp.inf)[None, None, :]
                barange = jnp.arange(batch, dtype=jnp.int32)[:, None] * K

                def cond(state):
                    tokens, caches, scores, finished, gen_len, pos = state
                    return (pos < total_len - 1) & ~jnp.all(finished)

                def body(state):
                    tokens, caches, scores, finished, gen_len, pos = state
                    tok = lax.dynamic_slice(
                        tokens, (z, z, pos), (batch, K, 1)).reshape(
                            batch * K, 1)
                    if has_mask:
                        kv, positions = _step_mask(
                            key_valid, real_len, prompt_len, total_len,
                            pos, tile=K)
                    else:
                        kv, positions = None, None
                    hidden, caches = gpt.decode_step(
                        Tensor(tok, stop_gradient=True), caches, pos,
                        key_valid=kv, positions=positions)
                    logp = jax.nn.log_softmax(
                        gpt.logits(hidden)._data[:, 0].astype(jnp.float32)
                    ).reshape(batch, K, vocab)
                    allowed = jnp.where(finished[:, :, None], pad_row, logp)
                    cand = (scores[:, :, None] + allowed).reshape(
                        batch, K * vocab)
                    scores, idx = lax.top_k(cand, K)       # [B, K]
                    parent = (idx // vocab).astype(jnp.int32)
                    nxt = (idx % vocab).astype(jnp.int32)
                    # reorder beam state by parent
                    tokens = jnp.take_along_axis(
                        tokens, parent[:, :, None], axis=1)
                    finished = jnp.take_along_axis(finished, parent, axis=1)
                    gen_len = jnp.take_along_axis(gen_len, parent, axis=1)
                    fp = (barange + parent).reshape(-1)
                    caches = tuple((ck[fp], cv[fp]) for ck, cv in caches)
                    tokens = lax.dynamic_update_slice(
                        tokens, nxt[:, :, None], (z, z, pos + 1))
                    gen_len = gen_len + (~finished).astype(jnp.int32)
                    if eos is not None:
                        finished = finished | (nxt == eos)
                    return tokens, caches, scores, finished, gen_len, pos + 1

                state = (tokens, caches, scores, finished, gen_len,
                         jnp.int32(prompt_len))
                tokens, _, scores, _, gen_len, _ = lax.while_loop(
                    cond, body, state)
                best = jnp.argmax(scores / lp(gen_len), axis=1)   # [B]
                out = jnp.take_along_axis(
                    tokens, best[:, None, None], axis=1)[:, 0]
        return out

    return jax.jit(fn)


# ---------------------------------------------------------------------------
# slot-pool step functions (the continuous-batching serving decode path;
# consumed by paddle_tpu/serving/ — see serving/engine.py)
# ---------------------------------------------------------------------------

def build_slot_prefill_fn(model, bucket_len, max_len, top_k=0, top_p=1.0,
                          probe=None):
    """Build the per-bucket prefill step of the slot-based serving engine.

    Returns ``fn(params, buffers, pool, ids, key_valid, slot, sample,
    temperature, key) -> (pool, first_token, key)``:

    * ``pool`` — the shared KV pool ``[layers, 2, slots, heads, max_len,
      head_dim]`` (``serving.KVCachePool.data``); the new prompt's K/V
      are written into row ``slot`` at time indices ``[0, bucket_len)``
      with one ``dynamic_update_slice`` per layer (``slot`` is traced, so
      ONE trace serves every slot);
    * ``ids`` ``[1, bucket_len]`` int32 — the prompt LEFT-padded to the
      capacity bucket; ``key_valid`` ``[1, bucket_len]`` bool marks the
      real tokens (the exact ragged-prompt contract of ``generate``);
    * ``sample``/``temperature`` are traced scalars: greedy and sampled
      first-token picks share the single compiled program;
    * the caller jits with ``donate_argnums`` on ``pool`` so the update
      is in place.

    ``probe`` is an optional ``framework.trace_probe`` site recorded at
    trace time (the dispatch/retrace_cause idiom): one trace per
    capacity bucket is this function's whole point, and the probe makes
    a violation visible in the counters.
    """
    import jax
    import jax.numpy as jnp
    from jax import lax

    from ..framework import trace_probe as _probe
    from ..nn.layer.layers import functional_state

    gpt = model.gpt if hasattr(model, "gpt") else model
    Lb = int(bucket_len)
    if Lb < 1:
        raise ValueError(f"bucket_len must be >= 1, got {Lb}")
    if Lb > int(max_len):
        raise ValueError(f"bucket_len {Lb} exceeds pool max_len {max_len}")
    if Lb > gpt.cfg.max_position_embeddings:
        raise ValueError(
            f"bucket_len {Lb} exceeds max_position_embeddings="
            f"{gpt.cfg.max_position_embeddings}")
    top_k = min(int(top_k), gpt.cfg.vocab_size)

    def fn(params, buffers, pool, ids, key_valid, slot, sample,
           temperature, key):
        if probe is not None:  # runs at trace time only (jit caches)
            probe.record(_probe.sig_of([pool, ids, key_valid]),
                         {"bucket": Lb})
        with functional_state(model, params, buffers):
            with no_grad_guard():
                caches = gpt.init_cache(1, Lb, pool.dtype)
                hidden, caches = gpt.prefill(
                    Tensor(ids, stop_gradient=True), caches,
                    key_valid=key_valid)
                logits = gpt.logits(hidden)._data[:, 0].astype(jnp.float32)
                key, sub = jax.random.split(key)
                greedy = _pick_token(logits, sub, False, top_k, top_p, 1.0)
                sampled = _pick_token(logits, sub, True, top_k, top_p,
                                      temperature)
                first = jnp.where(sample, sampled, greedy)
                z = jnp.int32(0)
                s = jnp.asarray(slot, jnp.int32).reshape(())
                new_pool = pool
                for li, (ck, cv) in enumerate(caches):
                    # ck/cv [1, Lb, H, Dh] -> the pool's [H, Lb, Dh] rows
                    kvb = jnp.stack([jnp.swapaxes(ck[0], 0, 1),
                                     jnp.swapaxes(cv[0], 0, 1)])
                    new_pool = lax.dynamic_update_slice(
                        new_pool, kvb[None, :, None].astype(new_pool.dtype),
                        (jnp.int32(li), z, s, z, z, z))
        return new_pool, first, key

    return fn


def build_slot_decode_fn(model, num_slots, max_len, top_k=0, top_p=1.0,
                         probe=None):
    """Build THE decode step of the slot-based serving engine: one jitted
    program advancing every pool slot by one token per call.

    Returns ``fn(params, buffers, pool, tokens, pos, lo, sample_mask,
    temperature, key) -> (pool, next_tokens, key)`` over the shared KV
    pool ``[layers, 2, slots, heads, max_len, head_dim]``
    (``next_tokens`` is ``[slots + 1]``: the per-slot tokens plus the
    logits-finite sentinel of :func:`_append_nonfinite_flag`):

    * ``tokens`` ``[slots]`` int32 — each slot's last emitted token; its
      K/V are written at cache index ``pos[slot]`` with a per-slot
      scatter (slots at DIFFERENT positions decode together — the
      continuous-batching core, the Ragged-Paged-Attention shape);
    * ``lo`` ``[slots]`` int32 — first valid cache index per slot (the
      left-pad offset of its capacity bucket): attention sees exactly
      ``[lo, pos]``, and position embeddings count logical tokens
      ``pos - lo``, matching ``generate``'s ragged-prompt semantics
      token for token;
    * ``sample_mask``/``temperature`` ``[slots]`` are traced, so mixed
      greedy/sampled request batches share the ONE compiled program
      (sampling reuses :func:`_pick_token`); inactive slots compute
      garbage that the scheduler ignores and the next prefill
      overwrites.

    The caller jits with ``donate_argnums`` on ``pool``; the engine's
    ``analyze()`` must report this program donation-safe and
    host-sync-free (the PR-3 clean-bill contract).
    """
    import jax
    import jax.numpy as jnp

    from ..framework import trace_probe as _probe
    from ..nn import functional as F
    from ..nn.layer.layers import functional_state

    gpt = model.gpt if hasattr(model, "gpt") else model
    S = int(num_slots)
    L = int(max_len)
    if S < 1:
        raise ValueError(f"num_slots must be >= 1, got {S}")
    if L > gpt.cfg.max_position_embeddings:
        raise ValueError(
            f"max_len {L} exceeds max_position_embeddings="
            f"{gpt.cfg.max_position_embeddings}")
    top_k = min(int(top_k), gpt.cfg.vocab_size)

    def fn(params, buffers, pool, tokens, pos, lo, sample_mask,
           temperature, key):
        if probe is not None:  # runs at trace time only (jit caches)
            probe.record(_probe.sig_of([pool, tokens, pos, lo,
                                        temperature]), {"slots": S})
        with functional_state(model, params, buffers):
            with no_grad_guard():
                logical = (pos - lo)[:, None]
                x = gpt.wte(Tensor(tokens[:, None], stop_gradient=True)) \
                    + gpt.wpe(Tensor(logical))
                r = jnp.arange(L)
                key_valid = (r[None, :] >= lo[:, None]) \
                    & (r[None, :] <= pos[:, None])
                mask = Tensor(key_valid[:, None, None, :])
                sl = jnp.arange(S)
                new_pool = pool
                for li, block in enumerate(gpt.blocks):
                    q, k, v = block._qkv(x)
                    kh = k._data[:, 0].astype(new_pool.dtype)  # [S, H, Dh]
                    vh = v._data[:, 0].astype(new_pool.dtype)
                    # per-slot scatter: slot i's row at time index pos[i]
                    new_pool = new_pool.at[li, 0, sl, :, pos, :].set(kh)
                    new_pool = new_pool.at[li, 1, sl, :, pos, :].set(vh)
                    k_full = Tensor(jnp.swapaxes(new_pool[li, 0], 1, 2),
                                    stop_gradient=True)  # [S, L, H, Dh]
                    v_full = Tensor(jnp.swapaxes(new_pool[li, 1], 1, 2),
                                    stop_gradient=True)
                    a = F.scaled_dot_product_attention(
                        q, k_full, v_full, attn_mask=mask)
                    x = block._tail(x, a)
                x = gpt.ln_f(x)
                logits = gpt.logits(x)._data[:, 0].astype(jnp.float32)
                key, sub = jax.random.split(key)
                greedy = _pick_token(logits, sub, False, top_k, top_p, 1.0)
                sampled = _pick_token(logits, sub, True, top_k, top_p,
                                      temperature[:, None])
                nxt = jnp.where(sample_mask, sampled, greedy)
                nxt = _append_nonfinite_flag(nxt, logits)
        return new_pool, nxt, key

    return fn


def _append_nonfinite_flag(nxt, logits):
    """Append the per-cycle logits-finite sentinel to the decode step's
    token row: element ``[num_slots]`` is 1 when ANY logit this cycle is
    NaN/Inf, else 0. It rides the scheduler's existing one-per-cycle
    ``_fetch`` (the token indexing ``toks[slot]`` never reaches it), so
    the serving twin of the training numerics audit costs zero extra
    host syncs — the scheduler counts it into
    ``serving/nonfinite_cycles`` and the flight-recorder cycle record."""
    import jax.numpy as jnp
    bad = jnp.any(~jnp.isfinite(logits)).astype(jnp.int32)
    return jnp.concatenate([nxt, bad[None]])


# ---------------------------------------------------------------------------
# quantized KV blocks (PagedKVPool(dtype="int8"): per-block max-abs
# scales in a parallel [L, 2, num_blocks + 1, H] f32 array — the EQuARX
# per-chunk scheme of the PR-10 gradient wire, applied to KV storage)
# ---------------------------------------------------------------------------

def _quant_append(pool, scales, li, kv, wb, off, rows, qmax):
    """Scatter per-row K/V values into a QUANTIZED block pool.

    ``rows [N, H, Dh]`` f32 land at ``(block wb[n], offset off[n])`` of
    plane ``(li, kv)``. Per-block max-abs scales grow monotonically: a
    row whose magnitude exceeds its block's current scale bumps the
    scale (scatter-max) and the touched blocks are REQUANTIZED to the
    new scale in the same step — when the scale is unchanged the
    requantize ratio is exactly 1.0, so steady-state appends never
    erode earlier rows. Duplicate ``wb`` entries (a prefill chunk
    writing several offsets of one block, or pad rows aimed at the
    scratch block) are safe: the scatter-max makes every duplicate see
    the same old/new scales, so their requantized block bytes are
    identical, and the row offsets are distinct by construction.
    Returns ``(pool, scales)``."""
    import jax.numpy as jnp
    rows = rows.astype(jnp.float32)
    rmax = jnp.max(jnp.abs(rows), axis=-1) / qmax             # [N, H]
    old = scales[li, kv]                                      # [NB+1, H]
    new = old.at[wb].max(rmax)
    nb = jnp.maximum(new[wb], 1e-30)                          # [N, H]
    ratio = jnp.where(new[wb] > 0, old[wb] / nb, 1.0)
    blk = pool[li, kv, wb].astype(jnp.float32)                # [N,H,bs,Dh]
    requant = jnp.clip(jnp.round(blk * ratio[..., None, None]),
                       -qmax, qmax).astype(pool.dtype)
    pool = pool.at[li, kv, wb].set(requant)
    qrow = jnp.clip(jnp.round(jnp.where(new[wb][..., None] > 0,
                                        rows / nb[..., None], 0.0)),
                    -qmax, qmax).astype(pool.dtype)
    pool = pool.at[li, kv, wb, :, off, :].set(qrow)
    return pool, scales.at[li, kv].set(new)


def _quant_write_blocks(pool, scales, li, kv, table, vals, qmax):
    """Whole-block quantized write (the paged prefill path): ``vals
    [Tp, H, bs, Dh]`` f32 replace the blocks named by ``table [Tp]``,
    each with a fresh per-(block, head) max-abs scale — freshly
    allocated blocks have no prior content worth rescaling. Returns
    ``(pool, scales)``."""
    import jax.numpy as jnp
    vals = vals.astype(jnp.float32)
    sc = jnp.max(jnp.abs(vals), axis=(-2, -1)) / qmax         # [Tp, H]
    denom = jnp.maximum(sc, 1e-30)[..., None, None]
    q = jnp.clip(jnp.round(jnp.where(sc[..., None, None] > 0,
                                     vals / denom, 0.0)),
                 -qmax, qmax).astype(pool.dtype)
    pool = pool.at[li, kv, table].set(q)
    return pool, scales.at[li, kv, table].set(sc)


def _dequant_gather(pool, scales, li, kv, tables):
    """Gather-path read of a quantized pool: materialize the virtual
    cache through the page table and multiply the per-block scales
    back in AFTER the pool read ("the gather path multiplies after the
    pool read"). ``tables [S, T]`` -> f32 ``[S, T, H, bs, Dh]``."""
    import jax.numpy as jnp
    return pool[li, kv][tables].astype(jnp.float32) \
        * scales[li, kv][tables][..., None, None]


# ---------------------------------------------------------------------------
# paged step functions (block-pooled KV with page tables and prefix reuse;
# consumed by paddle_tpu/serving/paging.py — see serving/engine.py)
# ---------------------------------------------------------------------------

def build_paged_prefill_fn(model, bucket_len, block_size, top_k=0,
                           top_p=1.0, probe=None, quantized=False,
                           qmax=127.0):
    """Build the per-bucket prefill step of the PAGED serving engine.

    Returns ``fn(params, buffers, pool, ids, key_valid, table, plen,
    sample, temperature, key) -> (pool, first_token, key)`` — with
    ``quantized=True`` (``PagedKVPool(dtype="int8")``) the per-block
    scale array is threaded alongside the pool: ``fn(params, buffers,
    pool, scales, ids, ...) -> (pool, scales, first_token, key)``, the
    K/V computed in the model dtype and written through
    :func:`_quant_write_blocks`:

    * ``pool`` — the block pool ``[layers, 2, num_blocks + 1, heads,
      block_size, head_dim]`` (``serving.PagedKVPool.data``); the
      prompt's K/V are scattered block-wise through ``table``
      ``[bucket_len // block_size]`` int32 (physical block per virtual
      block; 0 = the scratch block for entries past the allocation);
    * ``ids`` ``[1, bucket_len]`` int32 — the prompt RIGHT-padded to
      the capacity bucket (paged sequences are aligned at virtual
      index 0, the property that makes blocks shareable across
      requests); ``key_valid`` ``[1, bucket_len]`` bool marks real
      tokens; ``plen`` is the TRACED real length — the first-token
      logits come from hidden position ``plen - 1``, so one trace
      serves every prompt length in the bucket;
    * ``sample``/``temperature`` are traced scalars, exactly the
      slot-prefill contract; the caller jits with ``donate_argnums``
      on ``pool``.
    """
    import jax
    import jax.numpy as jnp
    from jax import lax

    from ..framework import trace_probe as _probe
    from ..nn.layer.layers import functional_state

    gpt = model.gpt if hasattr(model, "gpt") else model
    Lb, bs = int(bucket_len), int(block_size)
    if Lb < 1:
        raise ValueError(f"bucket_len must be >= 1, got {Lb}")
    if bs < 1 or Lb % bs:
        raise ValueError(
            f"bucket_len {Lb} must be a positive multiple of "
            f"block_size {bs}")
    if Lb > gpt.cfg.max_position_embeddings:
        raise ValueError(
            f"bucket_len {Lb} exceeds max_position_embeddings="
            f"{gpt.cfg.max_position_embeddings}")
    Tp = Lb // bs
    H = gpt.cfg.num_attention_heads
    Dh = gpt.cfg.hidden_size // H
    top_k = min(int(top_k), gpt.cfg.vocab_size)

    def fn(params, buffers, pool, *rest):
        (scales, ids, key_valid, table, plen, sample, temperature,
         key) = rest if quantized else (None,) + rest
        if probe is not None:  # runs at trace time only (jit caches)
            probe.record(_probe.sig_of([pool, ids, key_valid, table]),
                         {"bucket": Lb, "table": Tp})
        with functional_state(model, params, buffers):
            with no_grad_guard():
                # right-padded: reals count 0,1,2,..., pads repeat the
                # last real position (their K/V are masked garbage that
                # lands in the scratch block or gets overwritten by the
                # decode steps that reach those virtual indices)
                pos_ids = Tensor(jnp.maximum(
                    jnp.cumsum(key_valid.astype(jnp.int32), axis=1) - 1,
                    0))
                x = gpt.wte(Tensor(ids, stop_gradient=True)) \
                    + gpt.wpe(pos_ids)
                # quantized pools keep the layer-local K/V in the model
                # dtype; quantization happens at block-write time
                cdt = x._data.dtype if quantized else pool.dtype
                new_pool, new_scales = pool, scales
                for li, block in enumerate(gpt.blocks):
                    ck = jnp.zeros((1, Lb, H, Dh), cdt)
                    cv = jnp.zeros((1, Lb, H, Dh), cdt)
                    x, ck, cv = block.prefill(x, ck, cv,
                                              key_valid=key_valid)
                    # [1, Lb, H, Dh] -> per-block [Tp, H, bs, Dh] rows
                    kb = jnp.transpose(ck[0].reshape(Tp, bs, H, Dh),
                                       (0, 2, 1, 3))
                    vb = jnp.transpose(cv[0].reshape(Tp, bs, H, Dh),
                                       (0, 2, 1, 3))
                    if quantized:
                        new_pool, new_scales = _quant_write_blocks(
                            new_pool, new_scales, li, 0, table, kb, qmax)
                        new_pool, new_scales = _quant_write_blocks(
                            new_pool, new_scales, li, 1, table, vb, qmax)
                    else:
                        new_pool = new_pool.at[li, 0, table].set(kb)
                        new_pool = new_pool.at[li, 1, table].set(vb)
                x = gpt.ln_f(x)
                z = jnp.int32(0)
                p = jnp.asarray(plen, jnp.int32).reshape(())
                last = lax.dynamic_slice(
                    x._data, (z, p - 1, z), (1, 1, x._data.shape[-1]))
                logits = gpt.logits(Tensor(last))._data[:, 0].astype(
                    jnp.float32)
                key, sub = jax.random.split(key)
                greedy = _pick_token(logits, sub, False, top_k, top_p, 1.0)
                sampled = _pick_token(logits, sub, True, top_k, top_p,
                                      temperature)
                first = jnp.where(sample, sampled, greedy)
        if quantized:
            return new_pool, new_scales, first, key
        return new_pool, first, key

    return fn


def build_paged_decode_fn(model, num_slots, table_len, block_size,
                          top_k=0, top_p=1.0, probe=None,
                          quantized=False, qmax=127.0,
                          debug_logits=False):
    """Build the per-table-bucket decode step of the PAGED serving
    engine: gather-based paged attention over the block table.

    Returns ``fn(params, buffers, pool, tokens, pos, lo, tables,
    sample_mask, temperature, key) -> (pool, next_tokens, key)`` over
    the block pool ``[layers, 2, num_blocks + 1, heads, block_size,
    head_dim]`` (``next_tokens`` ``[slots + 1]`` — the last element is
    the logits-finite sentinel, see :func:`_append_nonfinite_flag`):

    * ``tables`` ``[slots, table_len]`` int32 — each slot's page table
      padded with 0 (the scratch block) to the pow2 table bucket; the
      new token's K/V are scattered at physical block
      ``tables[s, pos[s] // block_size]``, offset ``pos[s] %
      block_size`` (the per-slot scatter of the dense step, routed
      through the page table);
    * attention runs over the GATHERED virtual cache
      ``pool[li, :, tables]`` reshaped to ``[slots, table_len *
      block_size, heads, head_dim]`` with the ``[lo, pos]`` mask and
      logical positions ``pos - lo`` unchanged from the dense step —
      scratch-block garbage is masked, never NaN;
    * ``sample_mask``/``temperature`` are traced (one program serves
      mixed greedy/sampled batches via :func:`_pick_token`); the
      caller jits with ``donate_argnums`` on ``pool``, and the
      engine's ``analyze()`` must report the program donation-safe and
      host-sync-free;
    * ``quantized=True`` (``PagedKVPool(dtype="int8")``) threads the
      per-block scale array beside the pool (``fn(params, buffers,
      pool, scales, tokens, ...) -> (pool, scales, next_tokens,
      key)``): appends go through :func:`_quant_append` and the
      gathered virtual cache is dequantized by :func:`_dequant_gather`
      — the ``[lo, pos]`` mask, sentinel and sampling are unchanged.
    """
    import jax
    import jax.numpy as jnp

    from ..framework import trace_probe as _probe
    from ..nn import functional as F
    from ..nn.layer.layers import functional_state

    gpt = model.gpt if hasattr(model, "gpt") else model
    S, T, bs = int(num_slots), int(table_len), int(block_size)
    if S < 1:
        raise ValueError(f"num_slots must be >= 1, got {S}")
    if T < 1:
        raise ValueError(f"table_len must be >= 1, got {T}")
    H = gpt.cfg.num_attention_heads
    Dh = gpt.cfg.hidden_size // H
    top_k = min(int(top_k), gpt.cfg.vocab_size)

    def fn(params, buffers, pool, *rest):
        (scales, tokens, pos, lo, tables, sample_mask, temperature,
         key) = rest if quantized else (None,) + rest
        if probe is not None:  # runs at trace time only (jit caches)
            probe.record(_probe.sig_of([pool, tokens, pos, lo, tables,
                                        temperature]),
                         {"slots": S, "table": T})
        with functional_state(model, params, buffers):
            with no_grad_guard():
                logical = (pos - lo)[:, None]
                x = gpt.wte(Tensor(tokens[:, None], stop_gradient=True)) \
                    + gpt.wpe(Tensor(logical))
                r = jnp.arange(T * bs)
                key_valid = (r[None, :] >= lo[:, None]) \
                    & (r[None, :] <= pos[:, None])
                mask = Tensor(key_valid[:, None, None, :])
                sl = jnp.arange(S)
                wb = tables[sl, pos // bs]        # write block per slot
                off = pos % bs
                new_pool, new_scales = pool, scales
                for li, block in enumerate(gpt.blocks):
                    q, k, v = block._qkv(x)
                    if quantized:
                        new_pool, new_scales = _quant_append(
                            new_pool, new_scales, li, 0, wb, off,
                            k._data[:, 0], qmax)
                        new_pool, new_scales = _quant_append(
                            new_pool, new_scales, li, 1, wb, off,
                            v._data[:, 0], qmax)
                        kg = _dequant_gather(new_pool, new_scales, li, 0,
                                             tables).astype(k._data.dtype)
                        vg = _dequant_gather(new_pool, new_scales, li, 1,
                                             tables).astype(v._data.dtype)
                    else:
                        kh = k._data[:, 0].astype(new_pool.dtype)
                        vh = v._data[:, 0].astype(new_pool.dtype)
                        new_pool = new_pool.at[
                            li, 0, wb, :, off, :].set(kh)
                        new_pool = new_pool.at[
                            li, 1, wb, :, off, :].set(vh)
                        kg = new_pool[li, 0][tables]
                        vg = new_pool[li, 1][tables]
                    # gather the virtual cache through the page table:
                    # [NB+1, H, bs, Dh][tables] -> [S, T, H, bs, Dh]
                    kf = jnp.transpose(kg, (0, 1, 3, 2, 4)).reshape(
                        S, T * bs, H, Dh)
                    vf = jnp.transpose(vg, (0, 1, 3, 2, 4)).reshape(
                        S, T * bs, H, Dh)
                    a = F.scaled_dot_product_attention(
                        q, Tensor(kf, stop_gradient=True),
                        Tensor(vf, stop_gradient=True), attn_mask=mask)
                    x = block._tail(x, a)
                x = gpt.ln_f(x)
                logits = gpt.logits(x)._data[:, 0].astype(jnp.float32)
                key, sub = jax.random.split(key)
                greedy = _pick_token(logits, sub, False, top_k, top_p, 1.0)
                sampled = _pick_token(logits, sub, True, top_k, top_p,
                                      temperature[:, None])
                nxt = jnp.where(sample_mask, sampled, greedy)
                nxt = _append_nonfinite_flag(nxt, logits)
        extra = (logits,) if debug_logits else ()
        if quantized:
            return (new_pool, new_scales, nxt) + extra + (key,)
        return (new_pool, nxt) + extra + (key,)

    return fn


def _fused_tower(gpt, x, pool, scales, write_block, write_off, blk_seq,
                 seq_qstart, seq_pos0, tables, lo, kv_len, quantized,
                 qmax):
    """The fused ragged transformer tower shared by
    :func:`build_fused_step_fn` and :func:`build_spec_verify_fn`: per
    layer, scatter every flattened row's K/V through the page table
    (quantized pools go through :func:`_quant_append`), run the fused
    ragged-paged-attention Pallas kernel over the block pool, and apply
    the block tail. Returns ``(ln_f(x), pool, scales)``."""
    import jax.numpy as jnp

    from ..ops.ragged_paged_attention import ragged_paged_attention

    for li, block in enumerate(gpt.blocks):
        q, k, v = block._qkv(x)
        # per-row scatter through the page table: row i's K/V land at
        # (write_block[i], write_off[i]) — pad rows hit the scratch
        # block nobody reads
        if quantized:
            pool, scales = _quant_append(
                pool, scales, li, 0, write_block, write_off,
                k._data[0], qmax)
            pool, scales = _quant_append(
                pool, scales, li, 1, write_block, write_off,
                v._data[0], qmax)
        else:
            pool = pool.at[li, 0, write_block, :, write_off, :].set(
                k._data[0].astype(pool.dtype))
            pool = pool.at[li, 1, write_block, :, write_off, :].set(
                v._data[0].astype(pool.dtype))
        qh = jnp.transpose(q._data, (0, 2, 1, 3))[0]
        a = ragged_paged_attention(
            qh, pool, li, blk_seq, seq_qstart, seq_pos0, tables, lo,
            kv_len, scales=scales)
        a = jnp.transpose(a[None], (0, 2, 1, 3))
        x = block._tail(x, Tensor(a, stop_gradient=True))
    return gpt.ln_f(x), pool, scales


def build_fused_step_fn(model, num_slots, q_rows, table_len, block_size,
                        top_k=0, top_p=1.0, probe=None, quantized=False,
                        qmax=127.0):
    """Build THE fused ragged serving step: one jitted program that
    advances a RAGGED batch of mixed prefill-chunk and decode rows
    through every layer with the fused paged-attention Pallas kernel
    (ops/ragged_paged_attention.py) — no gathered KV window, the kernel
    walks each sequence's page table directly in HBM. This is the
    ``GenerationEngine(attention="fused")`` decode/chunk step; the
    gather-based :func:`build_paged_decode_fn` stays as the correctness
    oracle.

    Returns ``fn(params, buffers, pool, token_ids, qpos, write_block,
    write_off, blk_seq, seq_qstart, seq_pos0, tables, lo, kv_len,
    last_row, sample_mask, temperature, key) -> (pool, next_tokens,
    key)`` over the block pool ``[layers, 2, num_blocks + 1, heads,
    block_size, head_dim]`` (``next_tokens`` ``[num_slots + 1]`` — the
    last element is the logits-finite sentinel of
    :func:`_append_nonfinite_flag`):

    * ``token_ids``/``qpos``/``write_block``/``write_off`` ``[q_rows]``
      int32 — the flattened padded ragged batch (see
      ``ops.ragged_paged_attention.ragged_layout``): each row's token,
      virtual cache position, and page-table-resolved physical write
      block/offset (pad rows write the scratch block); every row's K/V
      are scattered into the pool BEFORE the kernel runs, so a chunk
      row attends causally to its own chunk prefix;
    * ``blk_seq [q_rows / 8]``, ``seq_qstart``/``seq_pos0``/``lo``/
      ``kv_len`` ``[num_slots]``, ``tables [num_slots, table_len]`` —
      the kernel's scalar-prefetch metadata;
    * ``last_row [num_slots]`` int32 — the flattened row of each slot's
      LAST real token this launch: its hidden state produces the slot's
      next-token logits, so a slot whose final feed chunk lands this
      cycle gets its first generated token from the SAME launch that
      prefilled the tail (rows of slots mid-chunk or absent produce
      garbage the scheduler ignores);
    * ``sample_mask``/``temperature`` ``[num_slots]`` are traced (one
      program serves mixed greedy/sampled batches); the caller jits
      with ``donate_argnums`` on ``pool`` and the engine's ``analyze()``
      must report the program donation-safe and host-sync-free.

    One trace per ``(q_rows bucket, table bucket)`` — the fused twin of
    the prefill/table pow2 bucket discipline, watched by ``probe``.
    ``quantized=True`` threads the per-block scale array beside the
    pool (``fn(params, buffers, pool, scales, token_ids, ...) ->
    (pool, scales, next_tokens, key)``): rows scatter through
    :func:`_quant_append` and the kernel dequantizes in-register off
    the scale array riding its scalar-prefetch metadata.
    """
    import jax
    import jax.numpy as jnp

    from ..framework import trace_probe as _probe
    from ..nn.layer.layers import functional_state
    from ..ops.ragged_paged_attention import BLOCK_Q

    gpt = model.gpt if hasattr(model, "gpt") else model
    S, Q, T, bs = (int(num_slots), int(q_rows), int(table_len),
                   int(block_size))
    if S < 1:
        raise ValueError(f"num_slots must be >= 1, got {S}")
    if Q < BLOCK_Q or Q % BLOCK_Q:
        raise ValueError(
            f"q_rows must be a positive multiple of {BLOCK_Q}, got {Q}")
    if T < 1:
        raise ValueError(f"table_len must be >= 1, got {T}")
    top_k = min(int(top_k), gpt.cfg.vocab_size)

    def fn(params, buffers, pool, *rest):
        (scales, token_ids, qpos, write_block, write_off, blk_seq,
         seq_qstart, seq_pos0, tables, lo, kv_len, last_row,
         sample_mask, temperature, key) = \
            rest if quantized else (None,) + rest
        if probe is not None:  # runs at trace time only (jit caches)
            probe.record(_probe.sig_of([pool, token_ids, tables]),
                         {"q": Q, "table": T})
        with functional_state(model, params, buffers):
            with no_grad_guard():
                # logical positions == virtual positions (paged
                # sequences are aligned at virtual 0; lo is the mask
                # floor, not a pad offset)
                x = gpt.wte(Tensor(token_ids[None, :],
                                   stop_gradient=True)) \
                    + gpt.wpe(Tensor(qpos[None, :]))
                x, new_pool, new_scales = _fused_tower(
                    gpt, x, pool, scales, write_block, write_off,
                    blk_seq, seq_qstart, seq_pos0, tables, lo, kv_len,
                    quantized, qmax)
                last = x._data[0, last_row]             # [S, E]
                logits = gpt.logits(
                    Tensor(last[:, None, :]))._data[:, 0].astype(
                        jnp.float32)
                key, sub = jax.random.split(key)
                greedy = _pick_token(logits, sub, False, top_k, top_p, 1.0)
                sampled = _pick_token(logits, sub, True, top_k, top_p,
                                      temperature[:, None])
                nxt = jnp.where(sample_mask, sampled, greedy)
                nxt = _append_nonfinite_flag(nxt, logits)
        if quantized:
            return new_pool, new_scales, nxt, key
        return new_pool, nxt, key

    return fn


# ---------------------------------------------------------------------------
# tensor-parallel serving steps (GenerationEngine(mesh=..., mp_axis="mp")):
# the per-device Megatron twins of the paged/fused steps above, wrapped in
# shard_map over a 1-D mp mesh. The block pool is head-partitioned
# ([L, 2, NB+1, H/mp, bs, Dh] per device); page tables, free lists and the
# prefix trie stay replicated host-side, so the allocator/COW/preemption
# logic never sees the mesh. Column-parallel projections slice the
# replicated bias to their local output columns; row-parallel projections
# join their partial products with ONE psum per projection (two per layer
# plus nothing at the LM head — post-psum activations are replicated, and
# the tied embedding weight is too).
# ---------------------------------------------------------------------------


def _mp_col_linear(lin, h, mp_axis):
    """Column-parallel Linear: replicated ``h [.., in]`` in, LOCAL
    ``[.., out/mp]`` out. Inside ``shard_map`` the module's swapped-in
    weight IS the local column shard; the bias is replicated full-width
    (``shard_params_megatron`` leaves 1-D params alone), so this
    device's output columns slice it at ``axis_index * out/mp`` — the
    module call itself would add a ``[out]`` bias to a ``[.., out/mp]``
    product and fail."""
    from jax import lax
    w = lin.weight._data                        # [in, out/mp] local
    b = lin.bias._data                          # [out] replicated
    n = w.shape[1]
    i = lax.axis_index(mp_axis) * n
    return h @ w + lax.dynamic_slice(b, (i,), (n,))


def _mp_row_linear(lin, h_local, mp_axis):
    """Row-parallel Linear: LOCAL ``[.., in/mp]`` in, replicated
    ``[.., out]`` out. The local product is a PARTIAL sum over the
    input dim; one ``psum`` joins the shards and the replicated bias is
    added exactly once, post-sum."""
    from jax import lax
    return lax.psum(h_local @ lin.weight._data, mp_axis) \
        + lin.bias._data


def _mp_qkv(block, x, mp, mp_axis):
    """Per-device :meth:`GPTBlock._qkv`: ln_1 on the replicated
    activations, column-parallel q/k/v projections, heads reshaped to
    the LOCAL head count (``_split_heads`` reshapes by the global
    ``num_heads`` attribute, so the split happens manually here).
    Returns local ``q/k/v [B, L, H/mp, Dh]`` ndarrays."""
    h = block.ln_1(x)._data
    attn = block.attn
    hl = attn.num_heads // mp
    dh = attn.head_dim

    def proj(lin):
        y = _mp_col_linear(lin, h, mp_axis)
        return y.reshape(y.shape[0], y.shape[1], hl, dh)

    return proj(attn.q_proj), proj(attn.k_proj), proj(attn.v_proj)


def _mp_tail(block, x, a_local, mp_axis):
    """Per-device :meth:`GPTBlock._tail`: merge the LOCAL heads,
    row-parallel out-proj (the psum joins the head shards' attention
    outputs), residual, then the column/row-parallel MLP with its own
    psum — the Megatron two-collectives-per-layer count. ``a_local`` is
    a ``[B, L, H/mp, Dh]`` ndarray; returns the replicated Tensor."""
    from ..nn import functional as F
    a = a_local.reshape(a_local.shape[0], a_local.shape[1], -1)
    attn_out = _mp_row_linear(block.attn.out_proj, a, mp_axis)
    x = x + block.dropout(Tensor(attn_out, stop_gradient=True))
    h = block.ln_2(x)._data
    g = F.gelu(Tensor(_mp_col_linear(block.mlp_fc, h, mp_axis),
                      stop_gradient=True), approximate=True)
    m = _mp_row_linear(block.mlp_proj, g._data, mp_axis)
    return x + block.dropout(Tensor(m, stop_gradient=True))


def _mp_fused_tower(gpt, x, pool, write_block, write_off, blk_seq,
                    seq_qstart, seq_pos0, tables, lo, kv_len, mp,
                    mp_axis):
    """Per-device fused ragged tower: each device scatters its OWN
    heads' K/V into its pool shard and launches the ragged Pallas
    kernel over its local head range — the kernel's grid is already
    per-head, so the per-shard call is the UNMODIFIED kernel on an
    ``[H/mp, ...]`` slice with the replicated scalar-prefetch metadata.
    Returns ``(ln_f(x), pool)``."""
    import jax.numpy as jnp

    from ..ops.ragged_paged_attention import ragged_paged_attention

    for li, block in enumerate(gpt.blocks):
        q, k, v = _mp_qkv(block, x, mp, mp_axis)
        pool = pool.at[li, 0, write_block, :, write_off, :].set(
            k[0].astype(pool.dtype))
        pool = pool.at[li, 1, write_block, :, write_off, :].set(
            v[0].astype(pool.dtype))
        qh = jnp.transpose(q, (0, 2, 1, 3))[0]       # [H/mp, Q, Dh]
        a = ragged_paged_attention(
            qh, pool, li, blk_seq, seq_qstart, seq_pos0, tables, lo,
            kv_len)
        a = jnp.transpose(a[None], (0, 2, 1, 3))     # [1, Q, H/mp, Dh]
        x = _mp_tail(block, x, a, mp_axis)
    return gpt.ln_f(x), pool


def _mp_pool_spec(mp_axis):
    """The head-partitioned PartitionSpec of the paged block pool
    ``[L, 2, NB+1, H, bs, Dh]`` — axis 3 (heads) over ``mp_axis``."""
    from jax.sharding import PartitionSpec as P
    return P(None, None, None, mp_axis, None, None)


def _mp_mesh_check(gpt, mesh, mp_axis):
    """Validate the serving mesh and return its mp degree. The serving
    shard_maps are manual over EVERY mesh axis, so a 1-D mesh is
    required (dp replication belongs to EngineFleet, one engine per
    replica)."""
    if mp_axis not in mesh.axis_names:
        raise ValueError(
            f"mp_axis {mp_axis!r} not in mesh axes {mesh.axis_names}")
    if len(mesh.axis_names) != 1:
        raise ValueError(
            f"serving mesh must be 1-D over {mp_axis!r}, got axes "
            f"{mesh.axis_names} (replicate with EngineFleet instead)")
    mp = int(mesh.shape[mp_axis])
    H = gpt.cfg.num_attention_heads
    if H % mp:
        raise ValueError(
            f"num_attention_heads {H} not divisible by mesh "
            f"{mp_axis}={mp}")
    return mp


def build_sharded_paged_prefill_fn(model, bucket_len, block_size, mesh,
                                   mp_axis="mp", top_k=0, top_p=1.0,
                                   probe=None):
    """Tensor-parallel :func:`build_paged_prefill_fn` (non-quantized):
    the SAME ``fn(params, buffers, pool, ids, key_valid, table, plen,
    sample, temperature, key) -> (pool, first_token, key)`` signature,
    with the body wrapped in ``shard_map`` over the 1-D ``mp`` mesh.
    ``pool`` is the head-partitioned global array; each device writes
    its own heads' K/V blocks and attends over its local heads, the
    row-parallel projections psum per layer, and the first-token pick
    runs on replicated logits (identical on every device). Donation of
    the global pool flows through the shard_map boundary unchanged."""
    import jax
    import jax.numpy as jnp
    from jax import lax
    from jax.sharding import PartitionSpec as P

    from ..framework import trace_probe as _probe
    from ..nn import functional as F
    from ..nn.layer.layers import functional_state

    gpt = model.gpt if hasattr(model, "gpt") else model
    Lb, bs = int(bucket_len), int(block_size)
    if Lb < 1:
        raise ValueError(f"bucket_len must be >= 1, got {Lb}")
    if bs < 1 or Lb % bs:
        raise ValueError(
            f"bucket_len {Lb} must be a positive multiple of "
            f"block_size {bs}")
    if Lb > gpt.cfg.max_position_embeddings:
        raise ValueError(
            f"bucket_len {Lb} exceeds max_position_embeddings="
            f"{gpt.cfg.max_position_embeddings}")
    Tp = Lb // bs
    mp = _mp_mesh_check(gpt, mesh, mp_axis)
    H = gpt.cfg.num_attention_heads
    Hl = H // mp
    Dh = gpt.cfg.hidden_size // H
    top_k = min(int(top_k), gpt.cfg.vocab_size)

    def body(params, buffers, pool, ids, key_valid, table, plen, sample,
             temperature, key):
        with functional_state(model, params, buffers):
            with no_grad_guard():
                pos_ids = Tensor(jnp.maximum(
                    jnp.cumsum(key_valid.astype(jnp.int32), axis=1) - 1,
                    0))
                x = gpt.wte(Tensor(ids, stop_gradient=True)) \
                    + gpt.wpe(pos_ids)
                mask = Tensor(key_valid[:, None, None, :])
                new_pool = pool
                for li, block in enumerate(gpt.blocks):
                    q, k, v = _mp_qkv(block, x, mp, mp_axis)
                    # the single-device prefill attends over the CACHE
                    # (pool-dtype values); cast before attention so the
                    # sharded engine sees bit-identical K/V
                    kc = k.astype(new_pool.dtype)
                    vc = v.astype(new_pool.dtype)
                    kb = jnp.transpose(kc[0].reshape(Tp, bs, Hl, Dh),
                                       (0, 2, 1, 3))
                    vb = jnp.transpose(vc[0].reshape(Tp, bs, Hl, Dh),
                                       (0, 2, 1, 3))
                    new_pool = new_pool.at[li, 0, table].set(kb)
                    new_pool = new_pool.at[li, 1, table].set(vb)
                    a = F.scaled_dot_product_attention(
                        Tensor(q, stop_gradient=True),
                        Tensor(kc, stop_gradient=True),
                        Tensor(vc, stop_gradient=True),
                        attn_mask=mask, is_causal=True)
                    x = _mp_tail(block, x, a._data, mp_axis)
                x = gpt.ln_f(x)
                z = jnp.int32(0)
                p = jnp.asarray(plen, jnp.int32).reshape(())
                last = lax.dynamic_slice(
                    x._data, (z, p - 1, z), (1, 1, x._data.shape[-1]))
                logits = gpt.logits(Tensor(last))._data[:, 0].astype(
                    jnp.float32)
                key, sub = jax.random.split(key)
                greedy = _pick_token(logits, sub, False, top_k, top_p,
                                     1.0)
                sampled = _pick_token(logits, sub, True, top_k, top_p,
                                      temperature)
                first = jnp.where(sample, sampled, greedy)
        return new_pool, first, key

    rep = P()
    sm = jax.shard_map(
        body, mesh=mesh,
        in_specs=(megatron_param_specs(model, mp_axis), rep,
                  _mp_pool_spec(mp_axis)) + (rep,) * 7,
        out_specs=(_mp_pool_spec(mp_axis), rep, rep), check_vma=False)

    def fn(params, buffers, pool, ids, key_valid, table, plen, sample,
           temperature, key):
        if probe is not None:  # runs at trace time only (jit caches)
            probe.record(_probe.sig_of([pool, ids, key_valid, table]),
                         {"bucket": Lb, "table": Tp, "mp": mp})
        return sm(params, buffers, pool, ids, key_valid, table, plen,
                  sample, temperature, key)

    return fn


def build_sharded_paged_decode_fn(model, num_slots, table_len,
                                  block_size, mesh, mp_axis="mp",
                                  top_k=0, top_p=1.0, probe=None,
                                  debug_logits=False):
    """Tensor-parallel :func:`build_paged_decode_fn` (non-quantized):
    the gather-based paged-attention oracle under ``shard_map``. Each
    device scatters its heads' K/V through the replicated page table
    into its pool shard, gathers ITS OWN virtual cache window, runs
    SDPA over the local heads, and the row-parallel tail psums — the
    sampled token is computed from replicated logits, identical on
    every device. Same signature/donation contract as the single-device
    builder."""
    import jax
    import jax.numpy as jnp
    from jax.sharding import PartitionSpec as P

    from ..framework import trace_probe as _probe
    from ..nn import functional as F
    from ..nn.layer.layers import functional_state

    gpt = model.gpt if hasattr(model, "gpt") else model
    S, T, bs = int(num_slots), int(table_len), int(block_size)
    if S < 1:
        raise ValueError(f"num_slots must be >= 1, got {S}")
    if T < 1:
        raise ValueError(f"table_len must be >= 1, got {T}")
    mp = _mp_mesh_check(gpt, mesh, mp_axis)
    H = gpt.cfg.num_attention_heads
    Hl = H // mp
    Dh = gpt.cfg.hidden_size // H
    top_k = min(int(top_k), gpt.cfg.vocab_size)

    def body(params, buffers, pool, tokens, pos, lo, tables,
             sample_mask, temperature, key):
        with functional_state(model, params, buffers):
            with no_grad_guard():
                logical = (pos - lo)[:, None]
                x = gpt.wte(Tensor(tokens[:, None], stop_gradient=True)) \
                    + gpt.wpe(Tensor(logical))
                r = jnp.arange(T * bs)
                key_valid = (r[None, :] >= lo[:, None]) \
                    & (r[None, :] <= pos[:, None])
                mask = Tensor(key_valid[:, None, None, :])
                sl = jnp.arange(S)
                wb = tables[sl, pos // bs]
                off = pos % bs
                new_pool = pool
                for li, block in enumerate(gpt.blocks):
                    q, k, v = _mp_qkv(block, x, mp, mp_axis)
                    kh = k[:, 0].astype(new_pool.dtype)  # [S, H/mp, Dh]
                    vh = v[:, 0].astype(new_pool.dtype)
                    new_pool = new_pool.at[li, 0, wb, :, off, :].set(kh)
                    new_pool = new_pool.at[li, 1, wb, :, off, :].set(vh)
                    kg = new_pool[li, 0][tables]
                    vg = new_pool[li, 1][tables]
                    kf = jnp.transpose(kg, (0, 1, 3, 2, 4)).reshape(
                        S, T * bs, Hl, Dh)
                    vf = jnp.transpose(vg, (0, 1, 3, 2, 4)).reshape(
                        S, T * bs, Hl, Dh)
                    a = F.scaled_dot_product_attention(
                        Tensor(q, stop_gradient=True),
                        Tensor(kf, stop_gradient=True),
                        Tensor(vf, stop_gradient=True), attn_mask=mask)
                    x = _mp_tail(block, x, a._data, mp_axis)
                x = gpt.ln_f(x)
                logits = gpt.logits(x)._data[:, 0].astype(jnp.float32)
                key, sub = jax.random.split(key)
                greedy = _pick_token(logits, sub, False, top_k, top_p,
                                     1.0)
                sampled = _pick_token(logits, sub, True, top_k, top_p,
                                      temperature[:, None])
                nxt = jnp.where(sample_mask, sampled, greedy)
                nxt = _append_nonfinite_flag(nxt, logits)
        extra = (logits,) if debug_logits else ()
        return (new_pool, nxt) + extra + (key,)

    rep = P()
    extra_specs = (rep,) if debug_logits else ()
    sm = jax.shard_map(
        body, mesh=mesh,
        in_specs=(megatron_param_specs(model, mp_axis), rep,
                  _mp_pool_spec(mp_axis)) + (rep,) * 7,
        out_specs=(_mp_pool_spec(mp_axis), rep) + extra_specs + (rep,),
        check_vma=False)

    def fn(params, buffers, pool, tokens, pos, lo, tables, sample_mask,
           temperature, key):
        if probe is not None:  # runs at trace time only (jit caches)
            probe.record(_probe.sig_of([pool, tokens, pos, lo, tables,
                                        temperature]),
                         {"slots": S, "table": T, "mp": mp})
        return sm(params, buffers, pool, tokens, pos, lo, tables,
                  sample_mask, temperature, key)

    return fn


def build_sharded_fused_step_fn(model, num_slots, q_rows, table_len,
                                block_size, mesh, mp_axis="mp", top_k=0,
                                top_p=1.0, probe=None):
    """Tensor-parallel :func:`build_fused_step_fn` (non-quantized): THE
    fused ragged serving step under ``shard_map`` over the 1-D ``mp``
    mesh. Each device launches the ragged Pallas kernel on its own
    heads against its own pool shard (the kernel's per-head grid makes
    the per-shard call the unmodified kernel); the row-parallel
    projections contribute the only collectives — one psum per
    out-proj/MLP-out joining attention outputs before the replicated
    LM head feeds :func:`_pick_token`, so the picked token is identical
    on every device. Signature, bucket discipline and the
    ``donate_argnums`` contract on the (now head-partitioned GLOBAL)
    pool are unchanged from the single-device builder — the donated
    pool stays donated through the shard_map boundary."""
    import jax
    import jax.numpy as jnp
    from jax.sharding import PartitionSpec as P

    from ..framework import trace_probe as _probe
    from ..nn.layer.layers import functional_state
    from ..ops.ragged_paged_attention import BLOCK_Q

    gpt = model.gpt if hasattr(model, "gpt") else model
    S, Q, T, bs = (int(num_slots), int(q_rows), int(table_len),
                   int(block_size))
    if S < 1:
        raise ValueError(f"num_slots must be >= 1, got {S}")
    if Q < BLOCK_Q or Q % BLOCK_Q:
        raise ValueError(
            f"q_rows must be a positive multiple of {BLOCK_Q}, got {Q}")
    if T < 1:
        raise ValueError(f"table_len must be >= 1, got {T}")
    mp = _mp_mesh_check(gpt, mesh, mp_axis)
    top_k = min(int(top_k), gpt.cfg.vocab_size)

    def body(params, buffers, pool, token_ids, qpos, write_block,
             write_off, blk_seq, seq_qstart, seq_pos0, tables, lo,
             kv_len, last_row, sample_mask, temperature, key):
        with functional_state(model, params, buffers):
            with no_grad_guard():
                x = gpt.wte(Tensor(token_ids[None, :],
                                   stop_gradient=True)) \
                    + gpt.wpe(Tensor(qpos[None, :]))
                x, new_pool = _mp_fused_tower(
                    gpt, x, pool, write_block, write_off, blk_seq,
                    seq_qstart, seq_pos0, tables, lo, kv_len, mp,
                    mp_axis)
                last = x._data[0, last_row]             # [S, E]
                logits = gpt.logits(
                    Tensor(last[:, None, :]))._data[:, 0].astype(
                        jnp.float32)
                key, sub = jax.random.split(key)
                greedy = _pick_token(logits, sub, False, top_k, top_p,
                                     1.0)
                sampled = _pick_token(logits, sub, True, top_k, top_p,
                                      temperature[:, None])
                nxt = jnp.where(sample_mask, sampled, greedy)
                nxt = _append_nonfinite_flag(nxt, logits)
        return new_pool, nxt, key

    rep = P()
    sm = jax.shard_map(
        body, mesh=mesh,
        in_specs=(megatron_param_specs(model, mp_axis), rep,
                  _mp_pool_spec(mp_axis)) + (rep,) * 14,
        out_specs=(_mp_pool_spec(mp_axis), rep, rep), check_vma=False)

    def fn(params, buffers, pool, token_ids, qpos, write_block,
           write_off, blk_seq, seq_qstart, seq_pos0, tables, lo, kv_len,
           last_row, sample_mask, temperature, key):
        if probe is not None:  # runs at trace time only (jit caches)
            probe.record(_probe.sig_of([pool, token_ids, tables]),
                         {"q": Q, "table": T, "mp": mp})
        return sm(params, buffers, pool, token_ids, qpos, write_block,
                  write_off, blk_seq, seq_qstart, seq_pos0, tables, lo,
                  kv_len, last_row, sample_mask, temperature, key)

    return fn


# ---------------------------------------------------------------------------
# speculative decoding (the draft-propose / fused-verify pair consumed by
# GenerationEngine(spec_draft=..., spec_k=...) — see serving/engine.py)
# ---------------------------------------------------------------------------

def build_spec_verify_fn(model, num_slots, q_rows, spec_k, table_len,
                         block_size, top_k=0, top_p=1.0, probe=None,
                         quantized=False, qmax=127.0):
    """The multi-row-per-slot VERIFY variant of
    :func:`build_fused_step_fn`: one fused ragged launch where each
    speculating slot contributes its candidate rows (``[last_token,
    d_1, ..., d_{n-1}]`` — draft candidates are just extra ragged rows,
    exactly like a prefill chunk) and the per-row logits drive
    :func:`_spec_accept`'s standard rejection sampling, with exact
    greedy parity as the degenerate case. Slots mid-prefill keep
    chunking through the same launch (``n_spec == 0`` rows are plain
    feed rows whose last-row pick is the non-speculative path).

    Returns ``fn(params, buffers, pool, [scales,] token_ids, qpos,
    write_block, write_off, blk_seq, seq_qstart, seq_pos0, tables, lo,
    kv_len, last_row, n_spec, draft_toks, draft_probs, sample_mask,
    temperature, key) -> (pool, [scales,] out, key)`` where

    * ``n_spec [S]`` int32 — candidates verified per slot this launch
      (0 = plain feed/decode rows);
    * ``draft_toks [S, spec_k]`` int32 / ``draft_probs [S, spec_k, V]``
      f32 — the DEVICE-side proposals of the draft loop (the host never
      fetched them); rows ``seq_qstart + 1 + j`` of ``token_ids`` are
      overlaid with ``draft_toks[:, j]`` in-trace, because those token
      values only exist on the device;
    * ``out [2S + S*spec_k + 1]`` int32 — ``[accepted (S) | corrected
      token (S) | echoed draft tokens (S*spec_k) | logits-finite
      sentinel]``: everything the scheduler needs from its ONE fetch
      per cycle (accepted drafts are emitted host-side from the echo).

    One trace per (q bucket, table bucket), same as the fused step.
    """
    import jax
    import jax.numpy as jnp

    from ..framework import trace_probe as _probe
    from ..nn.layer.layers import functional_state
    from ..ops.ragged_paged_attention import BLOCK_Q

    gpt = model.gpt if hasattr(model, "gpt") else model
    S, Q, K, T = (int(num_slots), int(q_rows), int(spec_k),
                  int(table_len))
    if S < 1:
        raise ValueError(f"num_slots must be >= 1, got {S}")
    if K < 1:
        raise ValueError(f"spec_k must be >= 1, got {K}")
    if Q < BLOCK_Q or Q % BLOCK_Q:
        raise ValueError(
            f"q_rows must be a positive multiple of {BLOCK_Q}, got {Q}")
    if T < 1:
        raise ValueError(f"table_len must be >= 1, got {T}")
    top_k = min(int(top_k), gpt.cfg.vocab_size)

    def fn(params, buffers, pool, *rest):
        (scales, token_ids, qpos, write_block, write_off, blk_seq,
         seq_qstart, seq_pos0, tables, lo, kv_len, last_row, n_spec,
         draft_toks, draft_probs, sample_mask, temperature, key) = \
            rest if quantized else (None,) + rest
        if probe is not None:  # runs at trace time only (jit caches)
            probe.record(_probe.sig_of([pool, token_ids, tables,
                                        draft_toks]),
                         {"q": Q, "table": T, "k": K})
        with functional_state(model, params, buffers):
            with no_grad_guard():
                # overlay the device-side draft tokens into their
                # verify rows: row qstart + 1 + j carries candidate
                # d_{j+1}'s PREDECESSOR d_j... i.e. the fed token at
                # verify position j+1 is draft_toks[:, j]; invalid
                # (j >= n_spec - 1) overlays are dropped out of bounds
                rows = seq_qstart[:, None] + 1 + jnp.arange(K)[None, :]
                ok = jnp.arange(K)[None, :] < (n_spec[:, None] - 1)
                safe = jnp.where(ok, rows, Q)         # Q = out of range
                token_ids = token_ids.at[safe.reshape(-1)].set(
                    draft_toks.reshape(-1), mode="drop")
                x = gpt.wte(Tensor(token_ids[None, :],
                                   stop_gradient=True)) \
                    + gpt.wpe(Tensor(qpos[None, :]))
                x, new_pool, new_scales = _fused_tower(
                    gpt, x, pool, scales, write_block, write_off,
                    blk_seq, seq_qstart, seq_pos0, tables, lo, kv_len,
                    quantized, qmax)
                # gather the rows whose logits are actually read —
                # the S*K verify rows plus each slot's last row —
                # BEFORE the LM head: running the [vocab] matmul over
                # all Q padded ragged rows would cost Q/(S*(K+1))x
                # more for nothing (a chunk-heavy cycle reads none of
                # its chunk rows' logits)
                vrows = jnp.clip(
                    seq_qstart[:, None] + jnp.arange(K)[None, :],
                    0, Q - 1)                          # [S, K]
                sel = x._data[0][jnp.concatenate(
                    [vrows.reshape(-1), last_row])]    # [S*K+S, E]
                logits = gpt.logits(
                    Tensor(sel[:, None, :]))._data[:, 0].astype(
                        jnp.float32)                   # [S*K+S, V]
                p = _sample_probs(
                    logits[:S * K],
                    jnp.repeat(sample_mask, K),
                    top_k, top_p,
                    jnp.repeat(temperature, K)).reshape(S, K, -1)
                base = _sample_probs(logits[S * K:], sample_mask,
                                     top_k, top_p, temperature)
                key, sub = jax.random.split(key)
                accepted, token = _spec_accept(
                    p, draft_probs, draft_toks, n_spec, base, sub)
                bad = jnp.any(~jnp.isfinite(logits)).astype(jnp.int32)
                out = jnp.concatenate([
                    accepted.astype(jnp.int32), token,
                    draft_toks.astype(jnp.int32).reshape(-1),
                    bad[None]])
        if quantized:
            return new_pool, new_scales, out, key
        return new_pool, out, key

    return fn


def build_draft_prefill_fn(model, bucket_len, max_len, probe=None):
    """Context prefill into the DRAFT model's dense slot pool
    (speculative decoding): when a slot starts decoding, the draft's
    KV cache must cover the target's context ``[0, pos)`` before it
    can propose. Prompts are RIGHT-padded to the bucket (virtual index
    0 — the draft mirrors the paged pool's alignment, so ``lo == 0``
    and draft positions equal target positions token for token).

    Returns ``fn(params, buffers, pool, ids, key_valid, slot) ->
    pool`` over the draft pool ``[draft_layers, 2, slots, draft_heads,
    max_len, draft_head_dim]``; no token is sampled — proposals come
    from the :func:`build_draft_propose_fn` loop that follows. The
    caller jits with ``donate_argnums`` on ``pool``.
    """
    import jax
    import jax.numpy as jnp
    from jax import lax

    from ..framework import trace_probe as _probe
    from ..nn.layer.layers import functional_state

    gpt = model.gpt if hasattr(model, "gpt") else model
    Lb = int(bucket_len)
    if Lb < 1:
        raise ValueError(f"bucket_len must be >= 1, got {Lb}")
    if Lb > int(max_len):
        raise ValueError(f"bucket_len {Lb} exceeds pool max_len {max_len}")
    if Lb > gpt.cfg.max_position_embeddings:
        raise ValueError(
            f"bucket_len {Lb} exceeds max_position_embeddings="
            f"{gpt.cfg.max_position_embeddings}")

    def fn(params, buffers, pool, ids, key_valid, slot):
        if probe is not None:  # runs at trace time only (jit caches)
            probe.record(_probe.sig_of([pool, ids, key_valid]),
                         {"bucket": Lb})
        with functional_state(model, params, buffers):
            with no_grad_guard():
                caches = gpt.init_cache(1, Lb, pool.dtype)
                _, caches = gpt.prefill(
                    Tensor(ids, stop_gradient=True), caches,
                    key_valid=key_valid)
                z = jnp.int32(0)
                s = jnp.asarray(slot, jnp.int32).reshape(())
                new_pool = pool
                for li, (ck, cv) in enumerate(caches):
                    kvb = jnp.stack([jnp.swapaxes(ck[0], 0, 1),
                                     jnp.swapaxes(cv[0], 0, 1)])
                    new_pool = lax.dynamic_update_slice(
                        new_pool, kvb[None, :, None].astype(new_pool.dtype),
                        (jnp.int32(li), z, s, z, z, z))
        return new_pool

    return fn


def build_draft_propose_fn(model, num_slots, max_len, top_k=0, top_p=1.0,
                           probe=None):
    """One autoregressive DRAFT proposal step (speculative decoding):
    the engine runs ``spec_k`` of these back to back, feeding each
    step's proposal into the next, all device-side — the host never
    fetches a draft token (they echo back through the verify launch's
    one fetch).

    Returns ``fn(params, buffers, pool, feed_tok, pos, lo, sample_mask,
    temperature, key) -> (pool, proposal, probs, key)``:

    * ``feed_tok [S]`` int32 — the token each slot feeds this step (the
      slot's last accepted token on step 0 — a host array — or the
      previous step's device-side ``proposal``);
    * ``proposal [S]`` int32 — drawn from the draft's own sampling
      distribution (greedy slots: the argmax, deterministically);
    * ``probs [S, V]`` f32 — THE proposal distribution ``q`` (one-hot
      for greedy slots), consumed by the verify launch's rejection
      sampling;
    * the caller jits with ``donate_argnums`` on ``pool``.
    """
    import jax
    import jax.numpy as jnp

    from ..framework import trace_probe as _probe
    from ..nn import functional as F
    from ..nn.layer.layers import functional_state

    gpt = model.gpt if hasattr(model, "gpt") else model
    S = int(num_slots)
    L = int(max_len)
    if S < 1:
        raise ValueError(f"num_slots must be >= 1, got {S}")
    if L > gpt.cfg.max_position_embeddings:
        raise ValueError(
            f"max_len {L} exceeds max_position_embeddings="
            f"{gpt.cfg.max_position_embeddings}")
    top_k = min(int(top_k), gpt.cfg.vocab_size)

    def fn(params, buffers, pool, feed_tok, pos, lo, sample_mask,
           temperature, key):
        if probe is not None:  # runs at trace time only (jit caches)
            probe.record(_probe.sig_of([pool, feed_tok, pos, lo,
                                        temperature]), {"slots": S})
        with functional_state(model, params, buffers):
            with no_grad_guard():
                logical = (pos - lo)[:, None]
                x = gpt.wte(Tensor(feed_tok[:, None],
                                   stop_gradient=True)) \
                    + gpt.wpe(Tensor(logical))
                r = jnp.arange(L)
                key_valid = (r[None, :] >= lo[:, None]) \
                    & (r[None, :] <= pos[:, None])
                mask = Tensor(key_valid[:, None, None, :])
                sl = jnp.arange(S)
                new_pool = pool
                for li, block in enumerate(gpt.blocks):
                    q, k, v = block._qkv(x)
                    kh = k._data[:, 0].astype(new_pool.dtype)
                    vh = v._data[:, 0].astype(new_pool.dtype)
                    new_pool = new_pool.at[li, 0, sl, :, pos, :].set(kh)
                    new_pool = new_pool.at[li, 1, sl, :, pos, :].set(vh)
                    k_full = Tensor(jnp.swapaxes(new_pool[li, 0], 1, 2),
                                    stop_gradient=True)
                    v_full = Tensor(jnp.swapaxes(new_pool[li, 1], 1, 2),
                                    stop_gradient=True)
                    a = F.scaled_dot_product_attention(
                        q, k_full, v_full, attn_mask=mask)
                    x = block._tail(x, a)
                x = gpt.ln_f(x)
                logits = gpt.logits(x)._data[:, 0].astype(jnp.float32)
                probs = _sample_probs(logits, sample_mask, top_k, top_p,
                                      temperature)
                key, sub = jax.random.split(key)
                prop = _categorical_probs(sub, probs)
        return new_pool, prop, probs, key

    return fn


def build_draft_propose_scan_fn(model, num_slots, max_len, spec_k,
                                top_k=0, top_p=1.0, probe=None):
    """The WHOLE draft proposal loop as one compiled program:
    ``lax.scan`` over :func:`build_draft_propose_fn`'s step body —
    ``spec_k`` sequential small launches per decode cycle become ONE
    dispatch, with the step's key-split/draw order preserved exactly so
    greedy proposals (and the sampled key chain) are token-identical to
    the unrolled loop.

    Returns ``fn(params, buffers, pool, feed_tok, pos, lo, sample_mask,
    temperature, key) -> (pool, proposals [S, spec_k],
    probs [S, spec_k, V], key)``:

    * ``feed_tok [S]`` int32 — each slot's last accepted token (the
      loop's step-0 feed); later steps feed the previous step's
      device-side proposal through the scan carry;
    * step ``j`` writes at position ``min(pos + j, max_len - 1)`` — the
      same host-side clamp the unrolled loop applied, now in-trace;
    * the caller jits with ``donate_argnums`` on ``pool``.
    """
    import jax
    import jax.numpy as jnp
    from jax import lax

    from ..framework import trace_probe as _probe
    from ..nn import functional as F
    from ..nn.layer.layers import functional_state

    gpt = model.gpt if hasattr(model, "gpt") else model
    S = int(num_slots)
    L = int(max_len)
    K = int(spec_k)
    if S < 1:
        raise ValueError(f"num_slots must be >= 1, got {S}")
    if K < 1:
        raise ValueError(f"spec_k must be >= 1, got {K}")
    if L > gpt.cfg.max_position_embeddings:
        raise ValueError(
            f"max_len {L} exceeds max_position_embeddings="
            f"{gpt.cfg.max_position_embeddings}")
    top_k = min(int(top_k), gpt.cfg.vocab_size)

    def fn(params, buffers, pool, feed_tok, pos, lo, sample_mask,
           temperature, key):
        if probe is not None:  # runs at trace time only (jit caches)
            probe.record(_probe.sig_of([pool, feed_tok, pos, lo,
                                        temperature]),
                         {"slots": S, "k": K})
        with functional_state(model, params, buffers):
            with no_grad_guard():
                r = jnp.arange(L)
                sl = jnp.arange(S)

                def step(carry, j):
                    new_pool, feed, key = carry
                    pj = jnp.minimum(pos + j, L - 1)
                    logical = (pj - lo)[:, None]
                    x = gpt.wte(Tensor(feed[:, None],
                                       stop_gradient=True)) \
                        + gpt.wpe(Tensor(logical))
                    key_valid = (r[None, :] >= lo[:, None]) \
                        & (r[None, :] <= pj[:, None])
                    mask = Tensor(key_valid[:, None, None, :])
                    for li, block in enumerate(gpt.blocks):
                        q, k, v = block._qkv(x)
                        kh = k._data[:, 0].astype(new_pool.dtype)
                        vh = v._data[:, 0].astype(new_pool.dtype)
                        new_pool = new_pool.at[
                            li, 0, sl, :, pj, :].set(kh)
                        new_pool = new_pool.at[
                            li, 1, sl, :, pj, :].set(vh)
                        k_full = Tensor(
                            jnp.swapaxes(new_pool[li, 0], 1, 2),
                            stop_gradient=True)
                        v_full = Tensor(
                            jnp.swapaxes(new_pool[li, 1], 1, 2),
                            stop_gradient=True)
                        a = F.scaled_dot_product_attention(
                            q, k_full, v_full, attn_mask=mask)
                        x = block._tail(x, a)
                    x = gpt.ln_f(x)
                    logits = gpt.logits(x)._data[:, 0].astype(
                        jnp.float32)
                    probs = _sample_probs(logits, sample_mask, top_k,
                                          top_p, temperature)
                    key, sub = jax.random.split(key)
                    prop = _categorical_probs(sub, probs)
                    return (new_pool, prop, key), (prop, probs)

                (new_pool, _, key), (props, probs) = lax.scan(
                    step,
                    (pool, jnp.asarray(feed_tok, jnp.int32), key),
                    jnp.arange(K))
        return (new_pool, jnp.swapaxes(props, 0, 1),
                jnp.swapaxes(probs, 0, 1), key)

    return fn


def make_draft_model(model, num_layers=2):
    """Build the default speculative-decoding draft: a GPT with the
    target's config truncated to ``num_layers`` blocks, SHARING the
    target's token/position embeddings (the same ``Parameter`` objects
    — zero extra embedding memory, and the tied LM head stays aligned
    with the target's vocabulary) and initializing its blocks and
    final LayerNorm from the target's first ``num_layers`` blocks —
    the cheapest draft that agrees with the target more often than
    chance. Any user model exposing the same GPT surface (and vocab)
    can be passed to ``GenerationEngine(spec_draft=...)`` instead.
    """
    from dataclasses import replace

    from .gpt import GPTModel

    gpt = model.gpt if hasattr(model, "gpt") else model
    n = int(num_layers)
    if not 1 <= n <= gpt.cfg.num_hidden_layers:
        raise ValueError(
            f"num_layers must be in [1, {gpt.cfg.num_hidden_layers}], "
            f"got {num_layers}")
    draft = GPTModel(replace(gpt.cfg, num_hidden_layers=n))
    draft.wte = gpt.wte            # SHARED parameters, not copies
    draft.wpe = gpt.wpe
    for i in range(n):
        src = dict(gpt.blocks[i].named_parameters())
        for name, p in draft.blocks[i].named_parameters():
            p._data = src[name]._data
    src = dict(gpt.ln_f.named_parameters())
    for name, p in draft.ln_f.named_parameters():
        p._data = src[name]._data
    draft.eval()
    return draft


class _UnsetType:
    """Per-kwarg sentinel for generate(): distinguishes 'not passed'
    from 'explicitly passed its default', so an explicit kwarg always
    conflicts with config= (value comparison silently let config
    override e.g. an explicit temperature=1.0)."""

    def __repr__(self):
        return "<unset>"


_UNSET = _UnsetType()

# signature defaults of generate(), applied when neither the kwarg nor a
# config supplies a value
_GEN_DEFAULTS = {
    "max_new_tokens": 32, "do_sample": False, "temperature": 1.0,
    "top_k": 0, "top_p": 1.0, "eos_token_id": None, "pad_token_id": 0,
    "seed": None, "num_beams": 1, "length_penalty": 0.0,
}


def generate(model, input_ids, max_new_tokens=_UNSET, do_sample=_UNSET,
             temperature=_UNSET, top_k=_UNSET, top_p=_UNSET,
             eos_token_id=_UNSET, pad_token_id=_UNSET, seed=_UNSET,
             num_beams=_UNSET, length_penalty=_UNSET,
             attention_mask=None, config=None):
    """Generate ``max_new_tokens`` continuations of ``input_ids`` [B, S].

    Returns a Tensor [B, S+max_new_tokens]; positions after an
    ``eos_token_id`` are filled with ``pad_token_id``. Ragged prompts are
    supported via ``attention_mask`` [B, S] (1 = real token, 0 = pad):
    prompts must be LEFT-padded so the last column is each example's
    final real token; pads are invisible to attention and position
    embeddings (each example decodes at its own logical positions). A
    ``GenerationConfig`` may be passed as ``config=`` instead of the
    individual kwargs. ``num_beams > 1`` selects compiled beam search
    (deterministic; ``length_penalty`` is the GNMT alpha applied at final
    selection; ragged masks compose with beams).
    """
    import jax
    import jax.numpy as jnp

    from ..nn.layer.layers import get_buffers_tree

    passed = {
        "max_new_tokens": max_new_tokens, "do_sample": do_sample,
        "temperature": temperature, "top_k": top_k, "top_p": top_p,
        "eos_token_id": eos_token_id, "pad_token_id": pad_token_id,
        "seed": seed, "num_beams": num_beams,
        "length_penalty": length_penalty,
    }
    explicit = sorted(k for k, v in passed.items() if v is not _UNSET)
    if config is not None:
        # sentinel check, not value comparison: an explicitly passed
        # default (e.g. temperature=1.0) is a conflict too — silently
        # letting config win would override what the caller wrote
        if explicit:
            raise ValueError(
                f"pass either config= or individual kwargs, not both "
                f"(got config plus {explicit})")
        resolved = {k: getattr(config, k) for k in passed}
    else:
        resolved = {k: (_GEN_DEFAULTS[k] if v is _UNSET else v)
                    for k, v in passed.items()}
    max_new_tokens = resolved["max_new_tokens"]
    do_sample = resolved["do_sample"]
    temperature = resolved["temperature"]
    top_k = resolved["top_k"]
    top_p = resolved["top_p"]
    eos_token_id = resolved["eos_token_id"]
    pad_token_id = resolved["pad_token_id"]
    seed = resolved["seed"]
    num_beams = resolved["num_beams"]
    length_penalty = resolved["length_penalty"]

    if num_beams < 1:
        raise ValueError(f"num_beams must be >= 1, got {num_beams}")
    if num_beams > 1:
        if do_sample:
            raise ValueError("num_beams > 1 requires do_sample=False "
                             "(deterministic beam search)")
        ignored = [n for n, c in (("temperature", temperature != 1.0),
                                  ("top_k", top_k != 0),
                                  ("top_p", top_p != 1.0),
                                  ("seed", seed is not None)) if c]
        if ignored:
            raise ValueError(f"{ignored} have no effect with "
                             f"num_beams > 1 (beam search is deterministic)")
    elif length_penalty != 0.0:
        raise ValueError("length_penalty requires num_beams > 1")

    ids = input_ids._data if isinstance(input_ids, Tensor) else \
        jnp.asarray(np.asarray(input_ids))
    if ids.ndim == 1:
        ids = ids[None, :]
    batch, prompt_len = ids.shape
    mask = None
    if attention_mask is not None:
        m = attention_mask._data if isinstance(attention_mask, Tensor) \
            else np.asarray(attention_mask)
        m = np.asarray(m)
        if m.shape != (batch, prompt_len):
            raise ValueError(
                f"attention_mask shape {m.shape} != input_ids shape "
                f"{(batch, prompt_len)}")
        # decode logits come from the LAST prompt column, so real tokens
        # must be right-aligned (left padding, the batched-serve layout)
        if (np.diff(m.astype(np.int8), axis=1) < 0).any():
            raise ValueError(
                "attention_mask must be left-padded (0s then 1s per row)")
        if (m.sum(axis=1) < 1).any():
            raise ValueError("attention_mask has an all-pad row")
        if not m.all():  # an all-ones mask is just the uniform path
            mask = jnp.asarray(m.astype(np.int32))
    if num_beams > 1:
        static_key = ("beam", int(max_new_tokens), int(num_beams),
                      None if eos_token_id is None else int(eos_token_id),
                      int(pad_token_id), float(length_penalty),
                      mask is not None)
        builder = _build_beam_fn
    else:
        static_key = (int(max_new_tokens), bool(do_sample), int(top_k),
                      float(top_p),
                      None if eos_token_id is None else int(eos_token_id),
                      int(pad_token_id), mask is not None)
        builder = _build_generate_fn
    cache = getattr(model, "_generate_fns", None)
    if cache is None:
        cache = model._generate_fns = {}
    fn_key = (batch, prompt_len) + static_key
    if fn_key not in cache:
        cache[fn_key] = builder(
            model, batch, prompt_len,
            static_key[1:] if num_beams > 1 else static_key)
    was_training = model.training
    model.eval()
    try:
        params = {k: p._data for k, p in model.named_parameters()}
        buffers = get_buffers_tree(model)
        if num_beams > 1:
            out = cache[fn_key](params, buffers, ids,
                                jnp.int32(0) if mask is None else mask)
        else:
            if not do_sample:
                # greedy never consumes the key; a fixed one avoids
                # advancing the global generator (would desync seed-pinned
                # experiments)
                key = jax.random.PRNGKey(0)
            elif seed is None:
                # fresh draw per call, controlled by paddle.seed(): an
                # unseeded sampling loop must not return identical
                # "samples" every call
                from ..framework import random as _random
                key = _random.next_key()
                if jnp.issubdtype(key.dtype, jax.dtypes.prng_key):
                    # normalize new-style typed keys to the legacy uint32
                    # form so seeded and unseeded calls share ONE program
                    key = jax.random.key_data(key)
            else:
                key = jax.random.PRNGKey(int(seed))
            out = cache[fn_key](params, buffers, ids, key,
                                jnp.float32(temperature),
                                jnp.int32(0) if mask is None else mask)
    finally:
        if was_training:
            model.train()
    return Tensor(out, stop_gradient=True)
