"""GPT-2 decoder-only language model — the flagship model (north-star
config 5: GPT-2 124M hybrid-parallel).

Design notes (TPU-first):
- pre-LN blocks, causal flash-friendly attention through the single
  ``scaled_dot_product_attention`` op (is_causal=True → no mask tensor is
  ever materialised; the Pallas override exploits this).
- weights stay [in, out] for the MXU; LM head ties the embedding matrix.
- no data-dependent python control flow: one forward is one XLA program.

Reference parity target: the GPT examples built on the reference's
MultiHeadAttention/TransformerDecoder (python/paddle/nn/layer/transformer.py)
and fleet meta_parallel GPT models.
"""
from __future__ import annotations

from dataclasses import dataclass

import numpy as np

from .. import nn
from ..framework.dispatch import call_op
from ..framework.tensor import Tensor
from ..nn import functional as F

__all__ = ["GPTConfig", "GPTBlock", "GPTModel", "GPTForPretraining"]


@dataclass
class GPTConfig:
    vocab_size: int = 50304          # 50257 padded to a multiple of 128
    hidden_size: int = 768           # (MXU-friendly vocab tiling)
    num_hidden_layers: int = 12
    num_attention_heads: int = 12
    intermediate_size: int = 3072
    max_position_embeddings: int = 1024
    hidden_dropout_prob: float = 0.1
    attention_dropout_prob: float = 0.1
    initializer_range: float = 0.02

    @classmethod
    def gpt2_small(cls):  # 124M
        return cls()

    @classmethod
    def tiny(cls):  # for tests/dryrun
        return cls(vocab_size=256, hidden_size=64, num_hidden_layers=2,
                   num_attention_heads=4, intermediate_size=128,
                   max_position_embeddings=64, hidden_dropout_prob=0.0,
                   attention_dropout_prob=0.0)


class GPTBlock(nn.Layer):
    def __init__(self, cfg: GPTConfig):
        super().__init__()
        h = cfg.hidden_size
        self.ln_1 = nn.LayerNorm(h)
        self.attn = nn.MultiHeadAttention(
            h, cfg.num_attention_heads, dropout=cfg.attention_dropout_prob)
        self.ln_2 = nn.LayerNorm(h)
        self.mlp_fc = nn.Linear(h, cfg.intermediate_size)
        self.mlp_proj = nn.Linear(cfg.intermediate_size, h)
        self.dropout = nn.Dropout(cfg.hidden_dropout_prob)

    def forward(self, x, cache=None):
        # attention with implicit causal masking
        h = self.ln_1(x)
        q = self.attn._split_heads(self.attn.q_proj(h))
        if cache is not None:
            k = self.attn._split_heads(self.attn.k_proj(h))
            v = self.attn._split_heads(self.attn.v_proj(h))
            k = call_op("concat", [cache.k, k], axis=1)
            v = call_op("concat", [cache.v, v], axis=1)
            cache = nn.MultiHeadAttention.Cache(k, v)
        else:
            k = self.attn._split_heads(self.attn.k_proj(h))
            v = self.attn._split_heads(self.attn.v_proj(h))
        a = F.scaled_dot_product_attention(
            q, k, v, is_causal=True,
            dropout_p=self.attn.dropout if self.training else 0.0,
            training=self.training)
        a = self.attn.out_proj(self.attn._merge_heads(a))
        x = x + self.dropout(a)
        m = self.mlp_proj(F.gelu(self.mlp_fc(self.ln_2(x)),
                                 approximate=True))
        x = x + self.dropout(m)
        return x if cache is None else (x, cache)


class GPTModel(nn.Layer):
    def __init__(self, cfg: GPTConfig):
        super().__init__()
        self.cfg = cfg
        init = nn.initializer.Normal(0.0, cfg.initializer_range)
        self.wte = nn.Embedding(cfg.vocab_size, cfg.hidden_size,
                                weight_attr=nn.ParamAttr(initializer=init))
        self.wpe = nn.Embedding(cfg.max_position_embeddings,
                                cfg.hidden_size,
                                weight_attr=nn.ParamAttr(initializer=init))
        self.drop = nn.Dropout(cfg.hidden_dropout_prob)
        self.blocks = nn.LayerList(
            [GPTBlock(cfg) for _ in range(cfg.num_hidden_layers)])
        self.ln_f = nn.LayerNorm(cfg.hidden_size)

    def forward(self, input_ids, position_ids=None):
        if position_ids is None:
            import jax.numpy as jnp
            seq = input_ids.shape[1]
            position_ids = Tensor(jnp.arange(seq, dtype=jnp.int64)[None, :])
        x = self.wte(input_ids) + self.wpe(position_ids)
        x = self.drop(x)
        for block in self.blocks:
            x = block(x)
        return self.ln_f(x)

    def logits(self, hidden):
        """LM head tied to wte (matmul against the embedding table)."""
        return call_op("matmul", hidden, self.wte.weight, transpose_y=True)


class GPTForPretraining(nn.Layer):
    def __init__(self, cfg: GPTConfig):
        super().__init__()
        self.gpt = GPTModel(cfg)

    def forward(self, input_ids, labels=None, position_ids=None):
        hidden = self.gpt(input_ids, position_ids)
        logits = self.gpt.logits(hidden)
        if labels is None:
            return logits
        loss = F.cross_entropy(
            call_op("reshape", logits, shape=(-1, logits.shape[-1])),
            call_op("reshape", labels, shape=(-1,)),
            reduction="mean")
        return loss, logits
