"""GPT-2 decoder-only language model — the flagship model (north-star
config 5: GPT-2 124M hybrid-parallel).

Design notes (TPU-first):
- pre-LN blocks, causal flash-friendly attention through the single
  ``scaled_dot_product_attention`` op (is_causal=True → no mask tensor is
  ever materialised; the Pallas override exploits this).
- weights stay [in, out] for the MXU; LM head ties the embedding matrix.
- no data-dependent python control flow: one forward is one XLA program.

Reference parity target: the GPT examples built on the reference's
MultiHeadAttention/TransformerDecoder (python/paddle/nn/layer/transformer.py)
and fleet meta_parallel GPT models.
"""
from __future__ import annotations

from dataclasses import dataclass

import numpy as np

from .. import nn
from ..framework.dispatch import call_op
from ..framework.tensor import Tensor
from ..nn import functional as F

__all__ = ["GPTConfig", "GPTBlock", "GPTModel", "GPTForPretraining"]


@dataclass
class GPTConfig:
    vocab_size: int = 50304          # 50257 padded to a multiple of 128
    hidden_size: int = 768           # (MXU-friendly vocab tiling)
    num_hidden_layers: int = 12
    num_attention_heads: int = 12
    intermediate_size: int = 3072
    max_position_embeddings: int = 1024
    hidden_dropout_prob: float = 0.1
    attention_dropout_prob: float = 0.1
    initializer_range: float = 0.02

    @classmethod
    def gpt2_small(cls):  # 124M
        return cls()

    @classmethod
    def tiny(cls):  # for tests/dryrun
        return cls(vocab_size=256, hidden_size=64, num_hidden_layers=2,
                   num_attention_heads=4, intermediate_size=128,
                   max_position_embeddings=64, hidden_dropout_prob=0.0,
                   attention_dropout_prob=0.0)


class GPTBlock(nn.Layer):
    def __init__(self, cfg: GPTConfig):
        super().__init__()
        h = cfg.hidden_size
        self.ln_1 = nn.LayerNorm(h)
        self.attn = nn.MultiHeadAttention(
            h, cfg.num_attention_heads, dropout=cfg.attention_dropout_prob)
        self.ln_2 = nn.LayerNorm(h)
        self.mlp_fc = nn.Linear(h, cfg.intermediate_size)
        self.mlp_proj = nn.Linear(cfg.intermediate_size, h)
        self.dropout = nn.Dropout(cfg.hidden_dropout_prob)

    def _qkv(self, x):
        """ln_1 + split-head q/k/v projections (shared by train/serve)."""
        h = self.ln_1(x)
        q = self.attn._split_heads(self.attn.q_proj(h))
        k = self.attn._split_heads(self.attn.k_proj(h))
        v = self.attn._split_heads(self.attn.v_proj(h))
        return q, k, v

    def _tail(self, x, a):
        """out-proj + residual + MLP half of the block (shared)."""
        a = self.attn.out_proj(self.attn._merge_heads(a))
        x = x + self.dropout(a)
        m = self.mlp_proj(F.gelu(self.mlp_fc(self.ln_2(x)),
                                 approximate=True))
        return x + self.dropout(m)

    def forward(self, x, cache=None):
        # attention with implicit causal masking
        q, k, v = self._qkv(x)
        if cache is not None:
            k = call_op("concat", [cache.k, k], axis=1)
            v = call_op("concat", [cache.v, v], axis=1)
            cache = nn.MultiHeadAttention.Cache(k, v)
        a = F.scaled_dot_product_attention(
            q, k, v, is_causal=True,
            dropout_p=self.attn.dropout if self.training else 0.0,
            training=self.training)
        x = self._tail(x, a)
        return x if cache is None else (x, cache)

    # -- static-cache decode path (serving) ---------------------------------
    # The concat cache above grows the seq axis every step, so each decode
    # step is a NEW XLA program — fine eagerly, fatal under jit.  These two
    # methods keep the cache at a FIXED [B, max_len, H, D] shape and write
    # into it with dynamic_update_slice, so the whole generate loop compiles
    # once (reference analog: the fixed-capacity CacheKV of
    # paddle/fluid/operators/fused/fused_multi_transformer_op.cu:1).
    def prefill(self, x, cache_k, cache_v, key_valid=None):
        """Process the whole prompt; write its K/V into the cache at [0:S).

        x: [B, S, E]; cache_k/v: jnp [B, max_len, H, D] (zeros);
        key_valid: optional jnp bool [B, S] — False marks left-pad
        positions no query may attend to. Returns (hidden, cache_k,
        cache_v) with caches as raw jnp arrays.
        """
        from jax import lax
        q, k, v = self._qkv(x)
        cache_k = lax.dynamic_update_slice(
            cache_k, k._data.astype(cache_k.dtype), (0, 0, 0, 0))
        cache_v = lax.dynamic_update_slice(
            cache_v, v._data.astype(cache_v.dtype), (0, 0, 0, 0))
        mask = None if key_valid is None else \
            Tensor(key_valid[:, None, None, :])  # [B, 1(h), 1(q), S]
        a = F.scaled_dot_product_attention(q, k, v, is_causal=True,
                                           attn_mask=mask)
        return self._tail(x, a), cache_k, cache_v

    def decode_step(self, x, cache_k, cache_v, pos, key_valid=None):
        """One token: x [B, 1, E], pos scalar (traced) — attend over the
        first pos+1 cache rows (or the rows marked True in key_valid
        [B, max_len] when prompts are ragged/left-padded). Cache shapes
        never change."""
        import jax.numpy as jnp
        from jax import lax
        q, k, v = self._qkv(x)
        z = jnp.int32(0)
        pos = jnp.asarray(pos, jnp.int32)
        cache_k = lax.dynamic_update_slice(
            cache_k, k._data.astype(cache_k.dtype), (z, pos, z, z))
        cache_v = lax.dynamic_update_slice(
            cache_v, v._data.astype(cache_v.dtype), (z, pos, z, z))
        # valid-position mask, broadcast over [B, H, q=1, max_len]
        max_len = cache_k.shape[1]
        if key_valid is None:
            mask = (jnp.arange(max_len) <= pos)[None, None, None, :]
        else:
            mask = key_valid[:, None, None, :]
        a = F.scaled_dot_product_attention(
            q, Tensor(cache_k, stop_gradient=True),
            Tensor(cache_v, stop_gradient=True), attn_mask=Tensor(mask))
        return self._tail(x, a), cache_k, cache_v


class GPTModel(nn.Layer):
    def __init__(self, cfg: GPTConfig):
        super().__init__()
        self.cfg = cfg
        init = nn.initializer.Normal(0.0, cfg.initializer_range)
        self.wte = nn.Embedding(cfg.vocab_size, cfg.hidden_size,
                                weight_attr=nn.ParamAttr(initializer=init))
        self.wpe = nn.Embedding(cfg.max_position_embeddings,
                                cfg.hidden_size,
                                weight_attr=nn.ParamAttr(initializer=init))
        self.drop = nn.Dropout(cfg.hidden_dropout_prob)
        self.blocks = nn.LayerList(
            [GPTBlock(cfg) for _ in range(cfg.num_hidden_layers)])
        self.ln_f = nn.LayerNorm(cfg.hidden_size)

    def forward(self, input_ids, position_ids=None):
        if position_ids is None:
            import jax.numpy as jnp
            seq = input_ids.shape[1]
            position_ids = Tensor(jnp.arange(seq, dtype=jnp.int64)[None, :])
        x = self.wte(input_ids) + self.wpe(position_ids)
        x = self.drop(x)
        for block in self.blocks:
            x = block(x)
        return self.ln_f(x)

    def logits(self, hidden):
        """LM head tied to wte (matmul against the embedding table)."""
        return call_op("matmul", hidden, self.wte.weight, transpose_y=True)

    # -- static-cache decode path (serving) ---------------------------------
    def init_cache(self, batch, max_len, dtype):
        """Preallocate per-layer K/V buffers: tuple of (k, v) jnp arrays,
        each [B, max_len, num_heads, head_dim]."""
        import jax.numpy as jnp
        cfg = self.cfg
        hd = cfg.hidden_size // cfg.num_attention_heads
        shape = (batch, max_len, cfg.num_attention_heads, hd)
        return tuple(
            (jnp.zeros(shape, dtype), jnp.zeros(shape, dtype))
            for _ in range(cfg.num_hidden_layers))

    def prefill(self, input_ids, caches, key_valid=None):
        """Run the prompt through all blocks, filling `caches` in place
        (functionally). key_valid: optional jnp bool [B, S] marking real
        (non-left-pad) prompt positions; position embeddings then count
        only real tokens per example. Returns (last-position hidden
        [B, 1, E], caches)."""
        import jax.numpy as jnp
        seq = input_ids.shape[1]
        if key_valid is None:
            position_ids = Tensor(jnp.arange(seq, dtype=jnp.int32)[None, :])
        else:
            # left-padded: pads get position 0, reals count 0,1,2,...
            position_ids = Tensor(jnp.maximum(
                jnp.cumsum(key_valid.astype(jnp.int32), axis=1) - 1, 0))
        x = self.wte(input_ids) + self.wpe(position_ids)
        new_caches = []
        for block, (ck, cv) in zip(self.blocks, caches):
            x, ck, cv = block.prefill(x, ck, cv, key_valid=key_valid)
            new_caches.append((ck, cv))
        x = self.ln_f(x)
        last = call_op("slice", x, axes=[1], starts=[seq - 1], ends=[seq])
        return last, tuple(new_caches)

    def decode_step(self, token_ids, caches, pos, key_valid=None,
                    positions=None):
        """One decode step: token_ids [B, 1], pos scalar (may be traced).
        positions: optional per-example LOGICAL positions [B, 1] (ragged
        prompts — the cache slot `pos` is shared but position embeddings
        differ per example). Returns (hidden [B, 1, E], caches)."""
        import jax.numpy as jnp
        if positions is None:
            pos_ids = Tensor(jnp.full((1, 1), pos, dtype=jnp.int32))
        else:
            pos_ids = Tensor(positions.astype(jnp.int32))
        x = self.wte(token_ids) + self.wpe(pos_ids)
        new_caches = []
        for block, (ck, cv) in zip(self.blocks, caches):
            x, ck, cv = block.decode_step(x, ck, cv, pos,
                                          key_valid=key_valid)
            new_caches.append((ck, cv))
        return self.ln_f(x), tuple(new_caches)


def _chunked_lm_loss(hidden, labels, table, n_chunks):
    """Tied-head softmax cross-entropy WITHOUT materializing the full
    [B, S, V] logits tensor: lax.scan over sequence chunks, each chunk
    rematerialized in backward (jax.checkpoint), so peak memory is one
    [B, S/n, V] block instead of the whole thing. At GPT-2 scale
    (b8 x s1024 x v50304) the full tensor is 1.6 GB fp32 — the classic
    HBM squeeze on small-model-large-vocab training. Reference analog:
    the fused softmax-with-cross-entropy kernels
    (paddle/phi/kernels/softmax_with_cross_entropy* and
    fused c_softmax_with_cross_entropy), which exist for the same
    memory/bandwidth reason."""
    import jax
    import jax.numpy as jnp

    B, S, H = hidden.shape
    C = S // n_chunks
    hs = jnp.moveaxis(hidden.reshape(B, n_chunks, C, H), 1, 0)
    ys = jnp.moveaxis(labels.reshape(B, n_chunks, C), 1, 0)

    @jax.checkpoint
    def chunk_nll(h_c, y_c):
        logits = jnp.einsum("bch,vh->bcv", h_c, table,
                            preferred_element_type=jnp.float32)
        lse = jax.nn.logsumexp(logits, axis=-1)
        valid = y_c != -100                     # ignore_index convention
        safe = jnp.where(valid, y_c, 0).astype(jnp.int32)
        gold = jnp.take_along_axis(logits, safe[..., None],
                                   axis=-1)[..., 0]
        nll = jnp.where(valid, lse - gold, 0.0)
        return nll.sum(), valid.sum().astype(jnp.int32)

    def body(acc, xs):
        h_c, y_c = xs
        nll, n = chunk_nll(h_c, y_c)
        return (acc[0] + nll, acc[1] + n), None

    (total, count), _ = jax.lax.scan(
        body, (jnp.float32(0.0), jnp.int32(0)), (hs, ys))
    return total / jnp.maximum(count, 1).astype(jnp.float32)


class GPTForPretraining(nn.Layer):
    """GPT with the tied-embedding LM head and causal-LM loss.

    Return contract of ``forward``:

    * ``labels is None`` — the logits Tensor ``[B, S, V]``;
    * ``labels`` given, ``lm_loss_chunks == 1`` — ``(loss, logits)``;
    * ``labels`` given, ``lm_loss_chunks > 1`` — ``(loss, None)``: the
      chunked cross-entropy (``_chunked_lm_loss``) exists precisely to
      never materialize the ``[B, S, V]`` logits tensor (1.6 GB fp32 at
      GPT-2 124M scale), so there are no logits to return. Callers that
      need logits must either use ``lm_loss_chunks=1`` or call
      ``self.gpt.logits(hidden)`` themselves and pay the memory.

    ``S`` must be divisible by ``lm_loss_chunks``; a silent dense
    fallback would defeat the memory bound, so an indivisible length
    raises instead.
    """

    def __init__(self, cfg: GPTConfig, lm_loss_chunks: int = 1):
        super().__init__()
        self.gpt = GPTModel(cfg)
        if lm_loss_chunks < 1:
            raise ValueError(f"lm_loss_chunks must be >= 1, "
                             f"got {lm_loss_chunks}")
        self.lm_loss_chunks = int(lm_loss_chunks)

    def forward(self, input_ids, labels=None, position_ids=None):
        hidden = self.gpt(input_ids, position_ids)
        if labels is None:
            return self.gpt.logits(hidden)
        if self.lm_loss_chunks > 1:
            if hidden.shape[1] % self.lm_loss_chunks:
                # a silent dense fallback would re-materialize the very
                # [B, S, V] tensor this flag exists to avoid (and flip
                # the logits output between None and real) — refuse
                raise ValueError(
                    f"sequence length {hidden.shape[1]} is not divisible "
                    f"by lm_loss_chunks={self.lm_loss_chunks}")
            from ..autograd import differentiable_apply
            loss = differentiable_apply(
                lambda h, y, w: _chunked_lm_loss(h, y, w,
                                                 self.lm_loss_chunks),
                hidden, labels, self.gpt.wte.weight)
            return loss, None
        logits = self.gpt.logits(hidden)
        loss = F.cross_entropy(
            call_op("reshape", logits, shape=(-1, logits.shape[-1])),
            call_op("reshape", labels, shape=(-1,)),
            reduction="mean")
        return loss, logits

    def generate(self, input_ids, **kwargs):
        """Compiled static-cache autoregressive decode; see
        models.generation.generate."""
        from .generation import generate
        return generate(self, input_ids, **kwargs)
