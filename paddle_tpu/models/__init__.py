"""Model zoo beyond vision: transformer language models.

Analog of the reference's fleetx/examples GPT + the transformer building
blocks in python/paddle/nn/layer/transformer.py and
incubate/nn/layer/fused_transformer.py.
"""
from .gpt import GPTConfig, GPTModel, GPTForPretraining  # noqa: F401
from .bert import (BertConfig, BertModel,  # noqa: F401
                   BertForQuestionAnswering, BertForMaskedLM,
                   BertForSequenceClassification)
from .generation import (GenerationConfig, generate,  # noqa: F401
                         save_for_serving)
from .seq2seq import TransformerModel  # noqa: F401
