"""``paddle.linalg`` — linear algebra namespace.

Reference: python/paddle/linalg.py re-exporting tensor/linalg.py
(svd/qr/eig/inv/solve/... over LAPACK/cuSOLVER kernels).

TPU-native: QR/SVD/eigh/cholesky lower natively through XLA on TPU;
nonsymmetric eig runs as a host callback (XLA restriction — the
reference's eig is CPU-kernel-only too, paddle/phi/kernels/cpu/
eig_kernel.cc).
"""
from __future__ import annotations

from .framework.dispatch import call_op as _op

__all__ = ["cholesky", "det", "slogdet", "norm", "cond", "inv", "pinv",
           "svd", "qr", "lu", "eig", "eigvals", "eigh", "eigvalsh",
           "matrix_power", "matrix_rank", "solve", "triangular_solve",
           "lstsq", "multi_dot", "cholesky_solve", "corrcoef", "cov",
           "lu_unpack"]


def cholesky(x, upper=False, name=None):
    out = _op("cholesky", x)
    return _op("transpose", out, perm=list(range(out.ndim - 2))
               + [out.ndim - 1, out.ndim - 2]) if upper else out


def det(x, name=None):
    return _op("det", x)


def slogdet(x, name=None):
    return _op("slogdet", x)


def norm(x, p=None, axis=None, keepdim=False, name=None):
    if p == "fro" or (p is None and axis is None):
        return _op("frobenius_norm", x, axis=axis, keepdim=keepdim)
    if p == "nuc":
        s = _op("svd", x, full_matrices=False)[1]
        return _op("sum", s, axis=-1, keepdim=keepdim)
    return _op("p_norm", x, porder=2.0 if p is None else p, axis=axis,
               keepdim=keepdim)


def cond(x, p=None, name=None):
    return _op("cond", x, p=p)


def inv(x, name=None):
    return _op("inverse", x)


def pinv(x, rcond=1e-15, hermitian=False, name=None):
    return _op("pinv", x, rtol=rcond, hermitian=hermitian)


def svd(x, full_matrices=False, name=None):
    return _op("svd", x, full_matrices=full_matrices)


def qr(x, mode="reduced", name=None):
    return _op("qr", x, mode=mode)


def lu(x, pivot=True, get_infos=False, name=None):
    lu_mat, piv = _op("lu", x)
    if get_infos:
        # XLA's LU has no per-matrix info status; report success (0),
        # matching lapack's info==0 for the factorizations it returns
        import jax.numpy as jnp
        from .framework.tensor import Tensor
        info = Tensor(jnp.zeros(tuple(x.shape[:-2]) or (1,), jnp.int32))
        return lu_mat, piv, info
    return lu_mat, piv


def eig(x, name=None):
    return _op("eig", x)


def eigvals(x, name=None):
    return _op("eigvals", x)


def eigh(x, UPLO="L", name=None):
    return _op("eigh", x, UPLO=UPLO)


def eigvalsh(x, UPLO="L", name=None):
    return _op("eigvalsh", x, UPLO=UPLO)


def matrix_power(x, n, name=None):
    return _op("matrix_power", x, n=int(n))


def matrix_rank(x, tol=None, hermitian=False, name=None):
    return _op("matrix_rank", x, rtol=tol)


def solve(x, y, name=None):
    return _op("solve", x, y)


def triangular_solve(x, y, upper=True, transpose=False,
                     unitriangular=False, name=None):
    return _op("triangular_solve", x, y, upper=upper,
               transpose=transpose, unitriangular=unitriangular)


def lstsq(x, y, rcond=None, driver=None, name=None):
    return _op("lstsq", x, y, rcond=rcond)


def multi_dot(xs, name=None):
    return _op("multi_dot", xs)


def _a(v):
    from .framework.tensor import Tensor
    import jax.numpy as jnp
    return v._data if isinstance(v, Tensor) else jnp.asarray(v)


def _t(v):
    from .framework.tensor import Tensor
    return None if v is None else Tensor(v, stop_gradient=True)


def cholesky_solve(x, y, upper=False, name=None):
    """Solve A @ out = x given y = chol(A) (reference
    linalg.cholesky_solve over the cholesky_solve kernel)."""
    from jax.scipy.linalg import cho_solve
    return _t(cho_solve((_a(y), not upper), _a(x)))


def cov(x, rowvar=True, ddof=True, fweights=None, aweights=None,
        name=None):
    """Covariance matrix (reference linalg.cov)."""
    import jax.numpy as jnp
    fw = None if fweights is None else _a(fweights)
    aw = None if aweights is None else _a(aweights)
    return _t(jnp.cov(_a(x), rowvar=rowvar,
                      ddof=1 if ddof else 0, fweights=fw, aweights=aw))


def corrcoef(x, rowvar=True, name=None):
    import jax.numpy as jnp
    return _t(jnp.corrcoef(_a(x), rowvar=rowvar))


def lu_unpack(x, y, unpack_ludata=True, unpack_pivots=True, name=None):
    """(LU, pivots) -> (P, L, U) (reference linalg.lu_unpack). ``y`` is
    the 1-based sequential pivot vector paddle.linalg.lu returns.
    Supports arbitrary leading batch dims (host-side unpack — this is a
    checkpoint/debug utility, not a jitted hot path)."""
    import numpy as _np
    lu_mat = _np.asarray(_a(x))
    piv = _np.asarray(_a(y))
    m, n = lu_mat.shape[-2:]
    k = min(m, n)
    L = U = P = None
    if unpack_ludata:
        L = _np.tril(lu_mat[..., :, :k], -1) + _np.eye(
            m, k, dtype=lu_mat.dtype)
        U = _np.triu(lu_mat[..., :k, :])
    if unpack_pivots:
        batch = piv.shape[:-1]
        piv2 = piv.reshape(-1, piv.shape[-1])
        Ps = _np.empty(piv2.shape[:1] + (m, m), lu_mat.dtype)
        for b in range(piv2.shape[0]):
            # sequential 1-based transpositions -> permutation
            perm = _np.arange(m)
            for i in range(piv2.shape[1]):
                j = int(piv2[b, i]) - 1
                perm[i], perm[j] = perm[j], perm[i]
            Ps[b] = _np.eye(m, dtype=lu_mat.dtype)[perm].T
        P = Ps.reshape(batch + (m, m))
    return _t(P), _t(L), _t(U)
