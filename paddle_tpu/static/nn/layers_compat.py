"""Fluid-style ``paddle.static.nn`` layer builders.

Reference: python/paddle/static/nn/__init__.py re-exporting
fluid/layers/nn.py — functional builders that create parameters at the
call site and append ops to the current program. Here each builder
constructs the corresponding nn.Layer (parameters register into the
captured program automatically through dispatch) and applies it; layers
are cached per ``name=``/config so repeated executions of user build
code reuse one parameter set, mirroring fluid's unique-name behavior.

Sequence builders operate on the dense (padded, lengths) encoding
(ops/sequence_ops.py — the TPU-native LoD replacement): ``lengths`` is
an optional keyword everywhere; omitted, every row counts as full
length (an unpadded batch).
"""
from __future__ import annotations

from typing import Dict, Optional

import numpy as np

__all__ = [
    "fc_compat_registry",  # introspection/testing
    "embedding", "sparse_embedding", "conv2d", "conv3d",
    "conv2d_transpose", "conv3d_transpose", "batch_norm", "layer_norm",
    "instance_norm", "group_norm", "spectral_norm", "data_norm", "prelu",
    "bilinear_tensor_product", "deform_conv2d", "row_conv", "nce",
    "crf_decoding", "multi_box_head", "StaticRNN",
    "sequence_concat", "sequence_conv", "sequence_enumerate",
    "sequence_expand", "sequence_expand_as", "sequence_first_step",
    "sequence_last_step", "sequence_pad", "sequence_pool",
    "sequence_reshape", "sequence_reverse", "sequence_scatter",
    "sequence_slice", "sequence_softmax", "sequence_unpad",
]

# call-site layer cache (fluid's unique_name equivalent): one parameter
# set per name/config across repeated build executions
_LAYERS: Dict[tuple, object] = {}
fc_compat_registry = _LAYERS


def _cached(key, factory, name=None):
    """fluid unique_name semantics: an UNNAMED builder creates FRESH
    parameters on every call (fluid increments fc_0, fc_1, ... even in
    a Python loop over one source line); only an explicit ``name=``
    shares a parameter set across calls."""
    if name is None:
        return factory()
    layer = _LAYERS.get(key)
    if layer is None:
        layer = factory()
        _LAYERS[key] = layer
    return layer


def _pkg_nn():
    from ... import nn
    return nn


def embedding(input, size, is_sparse=False, padding_idx=None,
              param_attr=None, dtype="float32", name=None):
    nn = _pkg_nn()
    layer = _cached(("embedding", name, tuple(size), padding_idx),
                    lambda: nn.Embedding(size[0], size[1],
                                         padding_idx=padding_idx,
                                         weight_attr=param_attr),
                    name=name)
    return layer(input)


def sparse_embedding(input, size, padding_idx=None, param_attr=None,
                     is_test=False, entry=None, table_class=None,
                     dtype="float32", name=None):
    """Reference sparse_embedding feeds the PS sparse table; here the
    TPU-native mesh-sharded table (distributed/embedding.py)."""
    from ...distributed.embedding import ShardedEmbedding
    layer = _cached(("sparse_embedding", name, tuple(size), padding_idx),
                    lambda: ShardedEmbedding(size[0], size[1],
                                             padding_idx=padding_idx,
                                             track_frequency=entry
                                             is not None),
                    name=name)
    return layer(input)


def _conv(nd, transpose, input, num_filters, filter_size, stride=1,
          padding=0, dilation=1, groups=1, param_attr=None,
          bias_attr=None, data_format=None, name=None, act=None,
          output_size=None, **kwargs):
    if kwargs:
        raise TypeError(f"unsupported conv argument(s) {sorted(kwargs)}; "
                        f"silently ignoring fluid knobs would change the "
                        f"computed network")
    nn = _pkg_nn()
    df = data_format or ("NCHW" if nd == 2 else "NCDHW")
    in_c = int(input.shape[1] if df.startswith("NC")
               else input.shape[-1])
    if transpose and filter_size is None:
        if output_size is None:
            raise ValueError("conv transpose needs filter_size= or "
                             "output_size=")
        # reference semantics: derive the kernel so stride x input +
        # kernel - stride == output (padding 0)
        in_sp = (input.shape[2:2 + nd] if df.startswith("NC")
                 else input.shape[1:1 + nd])
        outs = np.atleast_1d(output_size)
        st = np.broadcast_to(np.atleast_1d(stride), (nd,))
        filter_size = tuple(int(o - (int(i) - 1) * int(s))
                            for o, i, s in zip(outs, in_sp, st))
    cls = {(2, False): nn.Conv2D, (3, False): nn.Conv3D,
           (2, True): nn.Conv2DTranspose, (3, True): nn.Conv3DTranspose}[
        (nd, transpose)]
    layer = _cached(
        ("conv", nd, transpose, name, in_c, num_filters,
         tuple(np.atleast_1d(filter_size)), tuple(np.atleast_1d(stride)),
         tuple(np.atleast_1d(padding)), tuple(np.atleast_1d(dilation)),
         groups, df),
        lambda: cls(in_c, num_filters, filter_size, stride=stride,
                    padding=padding, dilation=dilation, groups=groups,
                    weight_attr=param_attr, bias_attr=bias_attr,
                    data_format=df),
        name=name)
    out = layer(input, output_size=output_size) if transpose and \
        output_size is not None else layer(input)
    return _act(out, act)


def conv2d(input, num_filters, filter_size, **kwargs):
    return _conv(2, False, input, num_filters, filter_size, **kwargs)


def conv3d(input, num_filters, filter_size, **kwargs):
    return _conv(3, False, input, num_filters, filter_size, **kwargs)


def conv2d_transpose(input, num_filters, filter_size=None, **kwargs):
    return _conv(2, True, input, num_filters, filter_size, **kwargs)


def conv3d_transpose(input, num_filters, filter_size=None, **kwargs):
    return _conv(3, True, input, num_filters, filter_size, **kwargs)


def batch_norm(input, act=None, is_test=False, momentum=0.9,
               epsilon=1e-5, param_attr=None, bias_attr=None,
               data_layout="NCHW", name=None, **kwargs):
    nn = _pkg_nn()
    c = int(input.shape[1] if data_layout.startswith("NC")
            else input.shape[-1])
    rank = len(input.shape)
    if rank == 5:
        df5 = "NCDHW" if data_layout.startswith("NC") else "NDHWC"
        factory = lambda: nn.BatchNorm3D(c, momentum=momentum,
                                         epsilon=epsilon, data_format=df5)
    elif rank == 4:
        factory = lambda: nn.BatchNorm2D(c, momentum=momentum,
                                         epsilon=epsilon,
                                         data_format=data_layout)
    else:
        df1 = "NCL" if data_layout.startswith("NC") else "NLC"
        factory = lambda: nn.BatchNorm1D(c, momentum=momentum,
                                         epsilon=epsilon, data_format=df1)
    layer = _cached(("batch_norm", name, c, data_layout, rank), factory,
                    name=name)
    out = layer(input)
    return _act(out, act)


def layer_norm(input, scale=True, shift=True, begin_norm_axis=1,
               epsilon=1e-5, param_attr=None, bias_attr=None, act=None,
               name=None):
    nn = _pkg_nn()
    shape = tuple(int(s) for s in input.shape[begin_norm_axis:])
    layer = _cached(("layer_norm", name, shape),
                    lambda: nn.LayerNorm(list(shape), epsilon=epsilon),
                    name=name)
    return _act(layer(input), act)


def instance_norm(input, epsilon=1e-5, param_attr=None, bias_attr=None,
                  name=None):
    nn = _pkg_nn()
    c = int(input.shape[1])
    layer = _cached(("instance_norm", name, c),
                    lambda: nn.InstanceNorm2D(c, epsilon=epsilon),
                    name=name)
    return layer(input)


def group_norm(input, groups, epsilon=1e-5, param_attr=None,
               bias_attr=None, act=None, data_layout="NCHW", name=None):
    nn = _pkg_nn()
    c = int(input.shape[1])
    layer = _cached(("group_norm", name, c, groups),
                    lambda: nn.GroupNorm(groups, c, epsilon=epsilon),
                    name=name)
    return _act(layer(input), act)


def spectral_norm(weight, dim=0, power_iters=1, eps=1e-12, name=None):
    """Functional spectral normalization of a weight VAR (reference
    fluid spectral_norm op) via the registered ``spectral_norm`` op —
    so it RECORDS into captured programs. The reference persists
    weight_u/v across steps so power_iters=1 converges over training;
    this one-shot form runs >= 10 internal iterations instead."""
    import jax.numpy as jnp
    from ...framework.dispatch import call_op
    from ...framework.tensor import Tensor
    h = int(weight.shape[dim])
    rest = int(np.prod(weight.shape)) // h
    rng = np.random.RandomState(0)
    u0 = Tensor(jnp.asarray(rng.randn(h).astype(np.float32)))
    v0 = Tensor(jnp.asarray(rng.randn(rest).astype(np.float32)))
    return call_op("spectral_norm", weight, u0, v0, dim=dim,
                   power_iters=max(int(power_iters), 10), eps=eps)


def data_norm(input, epsilon=1e-5, param_attr=None, name=None, **kwargs):
    """Reference data_norm: normalize by accumulated batch statistics
    without scale/shift — the CTR stack's feature normalizer. Dense
    form: running mean/var buffers, batch stats in training."""
    nn = _pkg_nn()
    c = int(input.shape[-1])

    class _DataNorm(nn.Layer):
        def __init__(self):
            super().__init__()
            import jax.numpy as jnp
            from ...framework.tensor import Tensor
            self.register_buffer("_mean", Tensor(jnp.zeros([c])))
            self.register_buffer("_var", Tensor(jnp.ones([c])))

        def forward(self, x):
            import jax.numpy as jnp
            arr = x._data
            if self.training:
                mean = arr.mean(axis=0)
                var = arr.var(axis=0)
                # ACCUMULATE (momentum blend) — the buffers hold running
                # statistics, not the last batch; functional_state
                # threads the update through jitted steps like BN
                m = 0.9
                self._buffers["_mean"]._data = \
                    m * self._mean._data + (1 - m) * mean
                self._buffers["_var"]._data = \
                    m * self._var._data + (1 - m) * var
            else:
                mean, var = self._mean._data, self._var._data
            from ...framework.tensor import Tensor
            return Tensor((arr - mean) / jnp.sqrt(var + epsilon),
                          stop_gradient=x.stop_gradient)

    layer = _cached(("data_norm", name, c), _DataNorm,
                    name=name)
    return layer(input)


def prelu(x, mode="all", param_attr=None, data_format="NCHW", name=None):
    nn = _pkg_nn()
    if mode == "all":
        num = 1
    elif mode == "channel":
        num = int(x.shape[1] if data_format.startswith("NC")
                  else x.shape[-1])
    else:
        raise NotImplementedError(
            "prelu mode='element' (per-element alphas) is not provided; "
            "use mode='channel' or nn.PReLU directly")
    layer = _cached(("prelu", name, mode, num),
                    lambda: nn.PReLU(num_parameters=num,
                                     weight_attr=param_attr),
                    name=name)
    return layer(x)


def bilinear_tensor_product(x, y, size, act=None, param_attr=None,
                            bias_attr=None, name=None):
    nn = _pkg_nn()
    layer = _cached(("bilinear", name, int(x.shape[-1]),
                     int(y.shape[-1]), size),
                    lambda: nn.Bilinear(int(x.shape[-1]),
                                        int(y.shape[-1]), size,
                                        weight_attr=param_attr,
                                        bias_attr=bias_attr),
                    name=name)
    return _act(layer(x, y), act)


def deform_conv2d(x, offset, mask, num_filters, filter_size, stride=1,
                  padding=0, dilation=1, groups=1,
                  deformable_groups=1, im2col_step=1, param_attr=None,
                  bias_attr=None, name=None):
    from ...vision.ops import DeformConv2D
    layer = _cached(("deform_conv2d", name, int(x.shape[1]), num_filters,
                     filter_size),
                    lambda: DeformConv2D(int(x.shape[1]), num_filters,
                                         filter_size, stride=stride,
                                         padding=padding,
                                         dilation=dilation,
                                         groups=groups,
                                         deformable_groups=
                                         deformable_groups),
                    name=name)
    return layer(x, offset, mask)


def row_conv(input, future_context_size, param_attr=None, act=None,
             name=None):
    """Lookahead row convolution (reference row_conv op, DeepSpeech2)
    via the registered ``row_conv`` op (records into programs)."""
    import jax.numpy as jnp
    from ...framework.dispatch import call_op
    from ...framework.tensor import Parameter
    d = int(input.shape[-1])
    k = int(future_context_size) + 1
    w = _cached(("row_conv", name, d, k),
                lambda: Parameter(jnp.full((k, d), 1.0 / k, jnp.float32)),
                name=name)
    return _act(call_op("row_conv", input, w), act)


def nce(input, label, num_total_classes, sample_weight=None,
        param_attr=None, bias_attr=None, num_neg_samples=None, name=None,
        sampler="uniform", custom_dist=None, seed=0, is_sparse=False):
    """Noise-contrastive estimation loss (reference nce op): logistic
    loss over the true class + k uniform negative samples. Routed
    through the registered ``nce_loss`` op, so in a captured program the
    LABEL is a recorded input (feeds flow at replay); the negative
    sample ids are drawn once per call site (fixed negatives per
    program, re-drawn per step only in eager mode by calling again)."""
    import jax
    import jax.numpy as jnp
    from ...framework import random as _random
    from ...framework.dispatch import call_op
    from ...framework.tensor import Parameter, Tensor
    d = int(input.shape[-1])
    k = int(num_neg_samples or 5)
    w, b = _cached(
        ("nce", name, num_total_classes, d),
        lambda: (Parameter(jnp.asarray(
                     (np.random.RandomState(seed)
                      .randn(num_total_classes, d) / np.sqrt(d))
                     .astype(np.float32))),
                 Parameter(jnp.zeros((num_total_classes,), jnp.float32))),
        name=name)
    n_rows = int(np.asarray(label.shape)[0])
    neg = Tensor(jax.random.randint(_random.next_key(), (n_rows, k), 0,
                                    num_total_classes))
    return call_op("nce_loss", input, label, w, b, neg)


def crf_decoding(input, param_attr=None, label=None, length=None):
    """Viterbi decode over emissions with a learned transition matrix
    (reference crf_decoding op; text/ ViterbiDecoder is the engine)."""
    from ...framework.tensor import Parameter, Tensor
    import jax.numpy as jnp
    n = int(input.shape[-1])
    trans = _cached(("crf_transition", "crfw", n),
                    lambda: Parameter(jnp.zeros((n + 2, n), jnp.float32)),
                    name="crfw")
    from ...text import viterbi_decode
    lengths = length if length is not None else Tensor(
        jnp.full((input.shape[0],), input.shape[1], jnp.int64))
    # body transitions only (the reference keeps start/stop rows extra)
    body = Tensor(trans._data[2:], stop_gradient=True)
    _, path = viterbi_decode(input, body, lengths)
    return path


def multi_box_head(*args, **kwargs):
    raise NotImplementedError(
        "multi_box_head (SSD prior-box head) is not provided as a fluid "
        "builder; compose paddle.vision.ops detection primitives "
        "(yolo_box/nms/RoI ops) or a model-zoo detector instead")


def _act(out, act):
    if act is None:
        return out
    from ...nn import functional as F
    return getattr(F, act)(out)


# --------------------------------------------------------------------------
# sequence builders over the dense (padded, lengths) encoding
# --------------------------------------------------------------------------

def _full_lengths(x):
    import jax.numpy as jnp
    from ...framework.tensor import Tensor
    return Tensor(jnp.full((x.shape[0],), x.shape[1], jnp.int64))


def _seq(fname, x, lengths=None, **kwargs):
    from ...nn import functional as F
    return getattr(F, fname)(x, lengths if lengths is not None
                             else _full_lengths(x), **kwargs)


def sequence_pool(input, pool_type, lengths=None, is_test=False,
                  pad_value=0.0):
    return _seq("sequence_pool", input, lengths, pool_type=pool_type)


def sequence_softmax(input, lengths=None, use_cudnn=False, name=None):
    return _seq("sequence_softmax", input, lengths)


def sequence_reverse(x, lengths=None, name=None):
    return _seq("sequence_reverse", x, lengths)


def sequence_first_step(input, lengths=None):
    return sequence_pool(input, "first", lengths)


def sequence_last_step(input, lengths=None):
    return sequence_pool(input, "last", lengths)


def sequence_conv(input, num_filters, filter_size=3, lengths=None,
                  filter_stride=1, padding=True, padding_start=None,
                  param_attr=None, bias_attr=None, act=None, name=None):
    from ...framework.dispatch import call_op
    from ...framework.tensor import Parameter
    import jax.numpy as jnp
    if filter_stride != 1 or padding_start is not None:
        raise NotImplementedError(
            "sequence_conv supports filter_stride=1 with centered "
            "padding (the common configuration); other strides/starts "
            "would silently change the computation")
    d = int(input.shape[-1])
    w, b = _cached(
        ("sequence_conv", name, d, num_filters, filter_size,
         bias_attr is not False),
        lambda: (Parameter(jnp.asarray(
            (np.random.RandomState(0).randn(filter_size * d, num_filters)
             / np.sqrt(filter_size * d)).astype(np.float32))),
            None if bias_attr is False else Parameter(
                jnp.zeros((num_filters,), jnp.float32))),
        name=name)
    out = call_op("sequence_conv", input,
                  lengths if lengths is not None else _full_lengths(input),
                  w, bias=b, context_length=filter_size)
    return _act(out, act)


def sequence_pad(x, pad_value=0.0, maxlen=None, lengths=None, name=None):
    from ...nn import functional as F
    return F.sequence_pad(x, lengths if lengths is not None
                          else _full_lengths(x), maxlen=maxlen,
                          pad_value=pad_value)


def sequence_unpad(x, length, name=None):
    from ...nn import functional as F
    return F.sequence_unpad(x, length)


def sequence_expand(x, y, ref_level=-1, lengths=None, name=None):
    """Dense form: repeat x's rows per y's (or explicit) lengths; the
    static maxlen comes from y's time axis."""
    from ...nn import functional as F
    maxlen = int(y.shape[1]) if len(y.shape) >= 2 else 1
    return F.sequence_expand(x, lengths if lengths is not None
                             else _full_lengths(y), maxlen=maxlen)


def sequence_expand_as(x, y, name=None):
    return sequence_expand(x, y)


def sequence_concat(input, lengths_list=None, name=None):
    from ...nn import functional as F
    if lengths_list is None:
        lengths_list = [_full_lengths(x) for x in input]
    return F.sequence_concat(list(input), list(lengths_list))


def sequence_enumerate(input, win_size, lengths=None, pad_value=0,
                       name=None):
    from ...nn import functional as F
    return F.sequence_enumerate(
        input, lengths if lengths is not None else _full_lengths(input),
        win_size=win_size, pad_value=pad_value)


def sequence_slice(input, offset, length, lengths=None, name=None):
    from ...nn import functional as F
    return F.sequence_slice(
        input, lengths if lengths is not None else _full_lengths(input),
        offset, length)


def sequence_reshape(input, new_dim):
    """Reference sequence_reshape: re-chunk the feature dim (dense form:
    [B, T, D] -> [B, T*D//new_dim, new_dim])."""
    from ...framework.dispatch import call_op
    t, d = (int(s) for s in input.shape[1:])
    if (t * d) % new_dim:
        raise ValueError(f"cannot reshape T*D={t*d} into rows of "
                         f"{new_dim}")
    # batch stays symbolic (-1): static programs replay at any batch
    return call_op("reshape", input, shape=[-1, (t * d) // new_dim,
                                            new_dim])


def sequence_scatter(input, index, updates, name=None):
    """Reference sequence_scatter: add ``updates`` at per-row positions
    ``index`` (dense form over [B, T, ...]); registered op, records."""
    import jax.numpy as jnp
    from ...framework.dispatch import call_op
    from ...framework.tensor import Tensor
    idx = index if isinstance(index, Tensor) else Tensor(
        jnp.asarray(index))
    upd = updates if isinstance(updates, Tensor) else Tensor(
        jnp.asarray(updates))
    return call_op("sequence_scatter", input, idx, upd)


class StaticRNN:
    """Fluid StaticRNN builder (reference fluid/layers/control_flow.py
    StaticRNN). The dense equivalent unrolls the step function over
    axis 1 at build time — exactly what fluid's sub-block execution did
    T times, expressed jit-friendly:

        rnn = StaticRNN()
        rnn.step_input(x)                       # [B, T, D]
        rnn.memory(init=h0)
        out = rnn.unroll(lambda x_t, h: (h_new, h_new))

    The fluid ``with rnn.step():`` recording protocol needs deferred
    python tracing (a sub-block IR); it raises with this guidance —
    ``nn.RNN``/``nn.LSTM`` (lax.scan) serve the layer-level use."""

    def __init__(self, name=None):
        self._inputs = []
        self._memories = []
        self._seq_len = None

    def step(self):
        raise NotImplementedError(
            "the fluid step-recording protocol is replaced by "
            "StaticRNN.unroll(step_fn) here (or nn.RNN/nn.LSTM for "
            "layer-level recurrence over lax.scan)")

    def step_input(self, x):
        self._inputs.append(x)
        self._seq_len = int(x.shape[1])
        return x

    def memory(self, init=None, shape=None, batch_ref=None,
               init_value=0.0, init_batch_dim_idx=0, ref_batch_dim_idx=0):
        import jax.numpy as jnp
        from ...framework.tensor import Tensor
        if init is None:
            if shape is None or batch_ref is None:
                raise ValueError("memory() needs init= or "
                                 "(shape=, batch_ref=)")
            b = int(batch_ref.shape[ref_batch_dim_idx])
            init = Tensor(jnp.full((b,) + tuple(shape), init_value,
                                   jnp.float32))
        self._memories.append(init)
        return init

    def unroll(self, step_fn):
        """Run ``step_fn(*x_ts, *states) -> (out, *new_states)`` over
        axis 1 of EVERY step_input (in declaration order), eagerly
        unrolled; returns stacked outputs [B, T, ...]."""
        from ...framework.dispatch import call_op
        if not self._inputs:
            raise RuntimeError("call step_input(x) before unroll()")

        def _slice_t(x, t):
            xt = call_op("slice", x, axes=[1], starts=[t], ends=[t + 1])
            return call_op("reshape", xt,
                           shape=[-1] + list(x.shape[2:]))

        states = list(self._memories)
        outs = []
        for t in range(self._seq_len):
            xts = [_slice_t(x, t) for x in self._inputs]
            res = step_fn(*xts, *states)
            if not isinstance(res, (tuple, list)):
                res = (res,)
            out, states = res[0], list(res[1:]) or states
            outs.append(out)
        return call_op("stack", outs, axis=1)
