"""``paddle.static.nn`` — control-flow ops (cond / while_loop / case /
switch_case).

Reference: python/paddle/static/nn/__init__.py re-exporting
fluid/layers/control_flow.py (cond:68, while_loop:86), backed by
conditional_block_op.cc / while_op.cc in the C++ executor.

TPU-native: under a trace these lower to ``lax.cond`` / ``lax.while_loop``
— real XLA control flow, usable inside jitted train steps and exported
programs (r2 verdict item 9). Eagerly (concrete boolean) they just pick a
branch, exactly like the reference's dygraph mode.
"""
from __future__ import annotations

from typing import Callable, List, Sequence

from ...framework.tensor import Tensor

__all__ = ["cond", "while_loop", "case", "switch_case"]


def _is_tracer(x) -> bool:
    import jax
    return isinstance(x, jax.core.Tracer)


def _pred_value(pred):
    if isinstance(pred, Tensor):
        return pred._data
    return pred


def _to_arrays(tree):
    import jax
    return jax.tree_util.tree_map(
        lambda t: t._data if isinstance(t, Tensor) else t, tree,
        is_leaf=lambda t: isinstance(t, Tensor))


def _to_tensors(tree, template):
    """Mirror the template's Tensor/non-Tensor structure onto arrays."""
    import jax
    t_leaves, treedef = jax.tree_util.tree_flatten(
        template, is_leaf=lambda t: isinstance(t, Tensor))
    a_leaves = jax.tree_util.tree_leaves(tree)
    out = [Tensor(a) if isinstance(t, Tensor) else a
           for t, a in zip(t_leaves, a_leaves)]
    return jax.tree_util.tree_unflatten(treedef, out)


def cond(pred, true_fn: Callable, false_fn: Callable, name=None):
    """Reference: fluid/layers/control_flow.py cond — both branches must
    return structures of matching shapes/dtypes."""
    p = _pred_value(pred)
    if not _is_tracer(p):
        return true_fn() if bool(p) else false_fn()
    import jax

    template = None

    def wrap_t(fn):
        nonlocal template

        def f(_):
            nonlocal template
            out = fn()
            if template is None:
                template = out
            return _to_arrays(out)
        return f

    out = jax.lax.cond(p, wrap_t(true_fn), wrap_t(false_fn), 0)
    return _to_tensors(out, template)


def while_loop(cond_fn: Callable, body_fn: Callable,
               loop_vars: Sequence, is_test=False, name=None):
    """Reference: fluid/layers/control_flow.py while_loop. ``loop_vars``
    is a list; cond_fn(*vars) -> bool scalar, body_fn(*vars) -> new vars.
    """
    loop_vars = list(loop_vars)
    arrays = _to_arrays(loop_vars)
    traced = any(_is_tracer(a) for a in arrays) or \
        _is_tracer(_pred_value(cond_fn(*loop_vars)))
    if not traced:
        # eager: plain python loop (reference dygraph path)
        vars_ = loop_vars
        while bool(_pred_value(cond_fn(*vars_))):
            out = body_fn(*vars_)
            vars_ = list(out) if isinstance(out, (list, tuple)) else [out]
        return vars_
    import jax

    def c(arrs):
        vs = _to_tensors(arrs, loop_vars)
        return _pred_value(cond_fn(*vs))

    def b(arrs):
        vs = _to_tensors(arrs, loop_vars)
        out = body_fn(*vs)
        out = list(out) if isinstance(out, (list, tuple)) else [out]
        return _to_arrays(out)

    out = jax.lax.while_loop(c, b, arrays)
    return _to_tensors(out, loop_vars)


def case(pred_fn_pairs, default=None, name=None):
    """Reference: fluid/layers/control_flow.py case — first true pred
    wins."""
    if not pred_fn_pairs:
        raise ValueError("pred_fn_pairs must not be empty")

    def build(i):
        if i >= len(pred_fn_pairs):
            if default is None:
                # reference semantics: last fn is the fallback
                return pred_fn_pairs[-1][1]()
            return default()
        pred, fn = pred_fn_pairs[i]
        return cond(pred, fn, lambda: build(i + 1))

    return build(0)


def switch_case(branch_index, branch_fns, default=None, name=None):
    """Reference: fluid/layers/control_flow.py switch_case."""
    if isinstance(branch_fns, dict):
        pairs = sorted(branch_fns.items())
    else:
        pairs = list(enumerate(branch_fns))
    idx = _pred_value(branch_index)
    keys = [k for k, _ in pairs]
    # reference semantics: an unmatched index runs `default`, or the
    # MAX-key branch when no default is given (control_flow.py
    # switch_case) — identical for eager and traced execution
    fallback_pos = len(pairs) if default is not None else \
        keys.index(max(keys))
    if not _is_tracer(idx):
        i = int(idx)
        for k, fn in pairs:
            if k == i:
                return fn()
        return default() if default is not None else \
            pairs[fallback_pos][1]()
    import jax
    import jax.numpy as jnp

    fns = [fn for _, fn in pairs]
    if default is not None:
        fns = fns + [default]
    template = None

    def mk(fn):
        def f(_):
            nonlocal template
            out = fn()
            if template is None:
                template = out
            return _to_arrays(out)
        return f

    pos = jnp.full((), fallback_pos, jnp.int32)
    for j, k in enumerate(keys):
        pos = jnp.where(idx == k, j, pos)
    out = jax.lax.switch(pos, [mk(f) for f in fns], 0)
    return _to_tensors(out, template)


def fc(x, size, num_flatten_dims=1, weight_attr=None, bias_attr=None,
       activation=None, name=None):
    """Fully-connected layer for static graphs (reference:
    python/paddle/static/nn/common.py fc): dims ``x.shape[nfd:]`` flatten
    into the weight's input axis (weight [prod(x.shape[nfd:]), size]) and
    the output keeps the leading dims — shape ``x.shape[:nfd] + [size]``.
    Creates fresh parameters at build time — the graph is built once, so
    each call site is its own layer, matching the reference's unique
    auto-named params."""
    import numpy as _np
    from ...nn import Linear
    from ...nn import functional as F
    nfd = num_flatten_dims if num_flatten_dims >= 0 \
        else len(x.shape) + num_flatten_dims
    in_features = int(_np.prod(x.shape[nfd:]))
    if len(x.shape) != nfd + 1:
        # collapse x.shape[nfd:] into one feature axis; the batch (dim 0)
        # stays -1 so the recorded reshape replays at any batch size.
        # Linear then maps the last axis, so the output keeps the lead
        # dims: x.shape[:nfd] + [size], the reference contract.
        x = x.reshape([-1] + [int(d) for d in x.shape[1:nfd]]
                      + [in_features])
    layer = Linear(in_features, size, weight_attr=weight_attr,
                   bias_attr=bias_attr)
    out = layer(x)
    if activation is not None:
        out = getattr(F, activation)(out)
    return out


__all__.append("fc")


from .layers_compat import *  # noqa: E402,F401,F403  (fluid layer builders)
from . import layers_compat as _compat  # noqa: E402
__all__ += [n for n in _compat.__all__ if n != "fc_compat_registry"]

from ..extras import py_func  # noqa: E402,F401  (shared with static.py_func)
__all__.append("py_func")
