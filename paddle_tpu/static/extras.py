"""Legacy/compat surface of ``paddle.static`` beyond the core
Program/Executor (reference python/paddle/static/__init__.py __all__).

Grouping:
* REAL over existing machinery — program state save/load, serialization
  (over the StableHLO exporter), gradients, create_parameter/global_var,
  py_func (host callback node), accuracy/auc expressions, EMA, Print,
  CompiledProgram/ParallelExecutor facades (XLA replaced what they
  configured, so they delegate to Executor and keep the knobs as
  recorded-but-inert attrs).
* REFERENCE-MATCHING ERRORS — the IPU family raises exactly like a
  reference build without IPU support; ctr_metric_bundle raises per the
  PS/CTR scope decision (README.md).
* Device place lists (cuda/xpu/npu/mlu) return [] on this backend —
  the truthful answer to "which CUDA devices do you see".
"""
from __future__ import annotations

import os
import pickle
from typing import Dict, List, Optional, Sequence

import numpy as np

__all__ = [
    "Variable", "BuildStrategy", "ExecutionStrategy", "CompiledProgram",
    "ParallelExecutor", "Scope", "global_scope", "scope_guard",
    "create_parameter", "create_global_var", "gradients", "py_func",
    "save", "load", "save_to_file", "load_from_file",
    "load_program_state", "set_program_state", "serialize_program",
    "deserialize_program", "serialize_persistables",
    "deserialize_persistables", "normalize_program", "accuracy", "auc",
    "exponential_decay", "Print", "ExponentialMovingAverage",
    "WeightNormParamAttr", "cuda_places", "xpu_places", "npu_places",
    "mlu_places", "IpuStrategy", "IpuCompiledProgram", "ipu_shard_guard",
    "set_ipu_shard", "ctr_metric_bundle",
]


def _tensor_mod():
    from ..framework import tensor as t
    return t


# --------------------------------------------------------------------------
# aliases + strategy facades
# --------------------------------------------------------------------------

class _LazyVariableMeta(type):
    def __instancecheck__(cls, obj):
        return isinstance(obj, _tensor_mod().Tensor)


class Variable(metaclass=_LazyVariableMeta):
    """Alias for the framework Tensor (reference fluid Variable — one
    type serves both graph modes here)."""

    def __new__(cls, *a, **k):
        return _tensor_mod().Tensor(*a, **k)


class BuildStrategy:
    """Reference BuildStrategy: pass toggles for the old graph compiler.
    XLA owns fusion/memory decisions, so every knob is recorded and
    inert — kept so tuning scripts port without edits."""

    def __init__(self):
        self.__dict__["_opts"] = {}

    def __setattr__(self, k, v):
        self._opts[k] = v

    def __getattr__(self, k):
        if k.startswith("_"):
            raise AttributeError(k)
        return self.__dict__["_opts"].get(k)


class ExecutionStrategy(BuildStrategy):
    """Reference ExecutionStrategy (thread counts etc.) — inert."""


class CompiledProgram:
    """Reference CompiledProgram(program).with_data_parallel(...) —
    compilation happens per-shape inside Executor.run (XLA), so this
    wraps and forwards."""

    def __init__(self, program, build_strategy=None):
        self._program = program
        self._build_strategy = build_strategy

    def with_data_parallel(self, loss_name=None, build_strategy=None,
                           exec_strategy=None, places=None):
        self._build_strategy = build_strategy
        return self


class ParallelExecutor:
    """Legacy fluid ParallelExecutor facade -> Executor (the SPMD engine
    replaced its multi-device scheduling)."""

    def __init__(self, use_cuda=False, loss_name=None, main_program=None,
                 build_strategy=None, exec_strategy=None, scope=None,
                 share_vars_from=None):
        from . import Executor, default_main_program
        self._program = main_program or default_main_program()
        self._exe = Executor()

    def run(self, fetch_list, feed=None, return_numpy=True):
        return self._exe.run(self._program, feed=feed,
                             fetch_list=fetch_list,
                             return_numpy=return_numpy)


# --------------------------------------------------------------------------
# scope (name -> value view over the default program)
# --------------------------------------------------------------------------

class _VarView:
    def __init__(self, arr):
        self._arr = arr

    def get_tensor(self):
        return self._arr

    def __array__(self):
        return np.asarray(self._arr)


class Scope:
    """Minimal scope: resolves names against tracked program params
    plus locally set vars (reference Scope is the C++ variable table;
    XLA buffers replaced it, so this is the debugging view)."""

    def __init__(self):
        self._vars: Dict[str, np.ndarray] = {}

    def var(self, name):
        self._vars.setdefault(name, np.zeros((), np.float32))
        return _VarView(self._vars[name])

    def set(self, name, value):
        self._vars[name] = np.asarray(value)

    def find_var(self, name):
        if name in self._vars:
            return _VarView(self._vars[name])
        from . import default_main_program
        prog = default_main_program()
        if name in prog._params:
            return _VarView(np.asarray(prog._params[name]._data))
        if name in prog._var_names:
            t = prog._vars[prog._var_names[name]]
            return _VarView(np.asarray(t._data))
        return None


_global_scope = Scope()
_scope_stack: List[Scope] = []


def global_scope() -> Scope:
    return _scope_stack[-1] if _scope_stack else _global_scope


class scope_guard:
    def __init__(self, scope):
        self._scope = scope

    def __enter__(self):
        _scope_stack.append(self._scope)
        return self._scope

    def __exit__(self, *a):
        _scope_stack.pop()
        return False


# --------------------------------------------------------------------------
# var/parameter creation + autodiff + host callback
# --------------------------------------------------------------------------

def create_parameter(shape, dtype, name=None, attr=None, is_bias=False,
                     default_initializer=None):
    """Reference static.create_parameter — registered into the current
    program's parameter table so minimize()/save() see it."""
    import jax.numpy as jnp
    from ..framework import static_capture as _capture
    from ..framework.dtypes import convert_dtype
    from ..nn.initializer import Constant, XavierUniform
    t = _tensor_mod()
    init = default_initializer or (Constant(0.0) if is_bias
                                   else XavierUniform())
    data = init(tuple(int(s) for s in shape), convert_dtype(dtype))
    p = t.Parameter(jnp.asarray(data), name=name)
    from . import default_main_program
    prog = _capture.current or default_main_program()
    prog._params.setdefault(p.name, p)
    return p


def create_global_var(shape, value, dtype, persistable=False, name=None,
                      force_cpu=False):
    import jax.numpy as jnp
    from ..framework import static_capture as _capture
    from ..framework.dtypes import convert_dtype
    t = _tensor_mod()
    var = t.Tensor(jnp.full(tuple(int(s) for s in shape), value,
                            convert_dtype(dtype)), stop_gradient=True)
    if name:
        var.name = name
    from . import default_main_program
    prog = _capture.current or default_main_program()
    prog._vars[id(var)] = var
    if name:
        prog._var_names[name] = id(var)
    return var


def gradients(targets, inputs, target_gradients=None, no_grad_set=None):
    """Reference static.gradients: grads of ``targets`` w.r.t. program
    PARAMETERS among ``inputs`` (feed-var gradients would need a
    different replay closure — unsupported, loudly)."""
    from . import append_backward
    t = _tensor_mod()
    targets = targets if isinstance(targets, (list, tuple)) else [targets]
    inputs = inputs if isinstance(inputs, (list, tuple)) else [inputs]
    non_params = [x for x in inputs if not isinstance(x, t.Parameter)]
    if non_params:
        raise NotImplementedError(
            "static.gradients supports gradients w.r.t. Parameters; got "
            f"{len(non_params)} non-parameter input(s). Use "
            "append_backward/fetch of @GRAD vars for parameters, or "
            "autograd.grad in dynamic mode for arbitrary inputs")
    total = targets[0]
    for extra in targets[1:]:
        total = total + extra     # grad of sum == summed grads
    pairs = append_backward(total, parameter_list=[p.name
                                                   for p in inputs])
    by_param = {id(p): g for p, g in pairs}
    return [by_param.get(id(p)) for p in inputs]


def py_func(func, x, out, backward_func=None, skip_vars_in_backward_input=None):
    """Host-python node inside a program (reference static.nn.py_func over
    the py_func op): runs ``func`` via jax.pure_callback so the captured
    program stays jittable. ``out`` is a template Tensor carrying the
    result shape/dtype. Gradients don't flow through (as the reference
    without backward_func); backward_func is unsupported."""
    import jax
    import jax.numpy as jnp
    from ..framework.dispatch import call_op
    from ..ops.registry import get_op, register_op
    t = _tensor_mod()
    if backward_func is not None:
        raise NotImplementedError(
            "py_func backward_func is not supported; wrap the op with "
            "autograd.PyLayer in dynamic mode instead")
    xs = x if isinstance(x, (list, tuple)) else [x]
    template = out
    t_shape = tuple(template.shape)
    t_dtype = template._data.dtype

    from ..ops import registry as _registry
    # key the memo on the OUTPUT CONTRACT too: the same func with a
    # different template must register a fresh op, not reuse stale specs
    sig = "x".join(map(str, t_shape)) + str(t_dtype)
    opname = f"py_func_{id(func)}_{sig}"
    if opname not in _registry._OPS:
        def _impl(*arrays):
            # the template's LEADING dim is the batch: follow the traced
            # input's batch so the node replays under any feed size
            shape = t_shape
            if shape and arrays and getattr(arrays[0], "ndim", 0) >= 1:
                shape = (arrays[0].shape[0],) + shape[1:]
            spec = jax.ShapeDtypeStruct(shape, t_dtype)

            def host(*np_arrays):
                r = func(*[np.asarray(a) for a in np_arrays])
                return np.asarray(r, dtype=spec.dtype).reshape(spec.shape)
            return jax.pure_callback(host, spec, *arrays)
        register_op(opname, jit=False)(_impl)
    return call_op(opname, *xs)


# --------------------------------------------------------------------------
# program state persistence + serialization
# --------------------------------------------------------------------------

def load_program_state(model_path, var_list=None) -> Dict[str, np.ndarray]:
    """Reference static.load_program_state: path(.pdparams) -> dict."""
    path = model_path if model_path.endswith(".pdparams") \
        else model_path + ".pdparams"
    from ..utils.pretrained import load_pdparams
    return load_pdparams(path)


def set_program_state(program, state_dict) -> None:
    import jax.numpy as jnp
    missing = []
    for name, param in program._params.items():
        if name in state_dict:
            arr = state_dict[name]
            param._data = jnp.asarray(arr, dtype=param._data.dtype)
        else:
            missing.append(name)
    if missing:
        raise ValueError(f"state dict is missing parameters {missing[:5]}"
                         f"{'...' if len(missing) > 5 else ''}")


def save(program, model_path, protocol=4) -> None:
    """Reference static.save: program params -> .pdparams (+ .pdopt when
    an optimizer is attached)."""
    from ..framework.io import save as _fsave
    _fsave({n: p for n, p in program._params.items()},
           model_path + ".pdparams", protocol=protocol)
    if program._optimizer is not None and program._opt_state is not None:
        _fsave(program._opt_state, model_path + ".pdopt",
               protocol=protocol)


def load(program, model_path, executor=None, var_list=None) -> None:
    from ..framework.io import load as _fload
    state = _fload(model_path + ".pdparams")
    set_program_state(
        program, {k: np.asarray(v.numpy() if hasattr(v, "numpy") else v)
                  for k, v in state.items()})
    opt_path = model_path + ".pdopt"
    if program._optimizer is not None and os.path.exists(opt_path):
        program._opt_state = _fload(opt_path)


def save_to_file(path, content: bytes) -> None:
    with open(path, "wb") as f:
        f.write(content)


def load_from_file(path) -> bytes:
    with open(path, "rb") as f:
        return f.read()


def serialize_program(feed_vars, fetch_vars, program=None, **kwargs) -> bytes:
    """Reference serialize_program returns the ProgramDesc bytes; here
    the portable compiled form is the StableHLO artifact
    (save_inference_model), returned as bytes."""
    import tempfile
    from . import save_inference_model
    d = tempfile.mkdtemp()
    prefix = os.path.join(d, "prog")
    save_inference_model(prefix, feed_vars, fetch_vars, program=program)
    return load_from_file(prefix + ".pdmodel")


def deserialize_program(data: bytes):
    """bytes -> runnable artifact (jit.load'ed TranslatedLayer)."""
    import tempfile
    from .. import jit
    d = tempfile.mkdtemp()
    prefix = os.path.join(d, "prog")
    save_to_file(prefix + ".pdmodel", data)
    return jit.load(prefix)


def serialize_persistables(feed_vars, fetch_vars, program=None,
                           **kwargs) -> bytes:
    prog = program
    if prog is None:
        from . import default_main_program
        prog = default_main_program()
    return pickle.dumps({n: np.asarray(p._data)
                         for n, p in prog._params.items()}, protocol=4)


def deserialize_persistables(program, data: bytes, executor=None) -> None:
    set_program_state(program, pickle.loads(data))


def normalize_program(program, feed_vars, fetch_vars, **kwargs):
    """Reference normalize_program prunes to the inference graph — the
    for_test clone (optimizer stripped) is that here."""
    return program.clone(for_test=True)


# --------------------------------------------------------------------------
# metric expressions + debug + EMA + lr compat
# --------------------------------------------------------------------------

def accuracy(input, label, k=1, correct=None, total=None):
    """Top-k accuracy as a recordable expression (reference
    static.accuracy over the accuracy op)."""
    from ..framework.dispatch import call_op
    topk = call_op("topk", input, k=k)[1]              # indices [N, k]
    lab = call_op("reshape", label, shape=[-1, 1])
    eq = call_op("equal", topk, call_op("cast", lab, dtype="int64"))
    hits = call_op("cast", call_op("any", eq, axis=-1), dtype="float32")
    return call_op("mean", hits)


def auc(input, label, curve="ROC", num_thresholds=4095, **kwargs):
    """Batch AUC expression (reference static.auc). ``input`` holds
    per-class probabilities [N, 2]; rank-statistic formulation keeps it
    one jittable expression."""
    from ..framework.dispatch import call_op
    pos_score = call_op("slice", input, axes=[1], starts=[1], ends=[2])
    pos_score = call_op("reshape", pos_score, shape=[-1])
    lab = call_op("cast", call_op("reshape", label, shape=[-1]),
                  dtype="float32")
    order = call_op("argsort", pos_score)
    ranked = call_op("cast", call_op("argsort", order), dtype="float32")
    n_pos = call_op("sum", lab)
    n_neg = call_op("sum", 1.0 - lab)
    pos_rank_sum = call_op("sum", ranked * lab) + n_pos  # 1-based ranks
    a = (pos_rank_sum - n_pos * (n_pos + 1.0) / 2.0) / \
        call_op("maximum", n_pos * n_neg,
                call_op("full", shape=[], fill_value=1.0,
                        dtype="float32"))
    return a


def exponential_decay(learning_rate, decay_steps, decay_rate,
                      staircase=False):
    """Old fluid lr-decay API -> the modern scheduler (reference maps it
    the same way in 2.x)."""
    from ..optimizer import lr as lr_mod
    gamma = decay_rate ** (1.0 / decay_steps) if not staircase \
        else decay_rate
    if staircase:
        return lr_mod.StepDecay(learning_rate=learning_rate,
                                step_size=decay_steps, gamma=decay_rate)
    return lr_mod.ExponentialDecay(learning_rate=learning_rate,
                                   gamma=gamma)


def Print(input, first_n=-1, message=None, summarize=20,
          print_tensor_name=True, print_tensor_type=True,
          print_tensor_shape=True, print_tensor_layout=True,
          print_tensor_lod=True, print_phase="both"):
    """Identity with a device-side print (reference Print op ->
    jax.debug.print, which survives jit)."""
    import jax
    from ..autograd import differentiable_apply

    def fn(arr):
        jax.debug.print((message or "Print") + ": {x}", x=arr)
        return arr

    return differentiable_apply(fn, input)


class ExponentialMovingAverage:
    """EMA over the current program's parameters (reference
    static.ExponentialMovingAverage): ``update()`` after each step,
    ``apply()/restore()`` context swaps the shadow weights in/out."""

    def __init__(self, decay=0.999, thres_steps=None, name=None):
        self.decay = float(decay)
        self._shadow: Dict[str, np.ndarray] = {}
        self._backup: Dict[str, np.ndarray] = {}

    def _params(self):
        from . import default_main_program
        from ..framework import static_capture as _capture
        prog = _capture.current or default_main_program()
        return prog._params

    def update(self):
        import jax.numpy as jnp
        for n, p in self._params().items():
            cur = p._data
            prev = self._shadow.get(n)
            self._shadow[n] = cur if prev is None else \
                self.decay * prev + (1 - self.decay) * cur

    def apply(self, executor=None, need_restore=True):
        import contextlib

        @contextlib.contextmanager
        def ctx():
            params = self._params()
            self._backup = {n: p._data for n, p in params.items()}
            for n, p in params.items():
                if n in self._shadow:
                    p._data = self._shadow[n]
            try:
                yield self
            finally:
                if need_restore:
                    self.restore()
        return ctx()

    def restore(self, executor=None):
        params = self._params()
        for n, arr in self._backup.items():
            if n in params:
                params[n]._data = arr
        self._backup = {}


class WeightNormParamAttr:
    """Accepted for API parity; the weight-norm reparameterization is
    nn.utils.weight_norm's job in 2.x — constructing this warns and
    behaves as a plain ParamAttr."""

    def __new__(cls, dim=None, **kwargs):
        import warnings
        from ..nn.layer.layers import ParamAttr
        warnings.warn(
            "WeightNormParamAttr: use paddle.nn.utils.weight_norm for "
            "the reparameterization; treating as plain ParamAttr",
            UserWarning, stacklevel=2)
        kwargs.pop("dim", None)
        return ParamAttr(**kwargs)


# --------------------------------------------------------------------------
# device place lists + IPU family + PS metric bundle
# --------------------------------------------------------------------------

def cuda_places(device_ids=None):
    return []     # no CUDA devices on this backend — the truthful answer


def xpu_places(device_ids=None):
    return []


def npu_places(device_ids=None):
    return []


def mlu_places(device_ids=None):
    return []


def _no_ipu(*a, **k):
    # matches the reference's behavior when paddle is not compiled with
    # IPU support (python/paddle/device/__init__.py is_compiled_with_ipu)
    raise RuntimeError(
        "IPU support is not available: this backend targets TPU via "
        "XLA (the reference raises identically unless built with IPU)")


class IpuStrategy:
    def __init__(self, *a, **k):
        _no_ipu()


class IpuCompiledProgram:
    def __init__(self, *a, **k):
        _no_ipu()


def ipu_shard_guard(*a, **k):
    _no_ipu()


def set_ipu_shard(*a, **k):
    _no_ipu()


def ctr_metric_bundle(input, label, ins_tag_weight=None):
    raise NotImplementedError(
        "ctr_metric_bundle belongs to the descoped PS/CTR stack (see "
        "README.md scope decision); use paddle.metric.Auc or "
        "static.auc for AUC over program vars")
