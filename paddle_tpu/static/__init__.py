"""``paddle.static`` — static-graph user API facade.

Analog of the reference's ``python/paddle/static/`` (Program, Executor,
program_guard, append_backward over ProgramDesc). TPU-native stance
(SURVEY.md §7): the "program" is a traced, jit-compiled function — XLA is
the executor and the ProgramDesc/InterpreterCore layer disappears. This
module keeps the *ergonomics*: ``enable_static`` flips a mode flag,
``Program`` captures a python callable + example specs and compiles it
lazily, ``Executor.run`` executes the compiled artifact. ``InputSpec`` is
shared with ``paddle.jit``.
"""
from __future__ import annotations

import threading
from typing import Any, Dict, List, Optional, Sequence

import jax
import jax.numpy as jnp
import numpy as np

from ..framework.dtypes import convert_dtype
from ..framework.tensor import Tensor
from . import nn  # noqa: F401  (control-flow ops: cond/while_loop/...)

__all__ = ["enable_static", "disable_static", "in_dynamic_mode",
           "InputSpec", "Program", "program_guard", "default_main_program",
           "default_startup_program", "Executor", "data", "name_scope",
           "cpu_places", "device_guard", "save_inference_model",
           "load_inference_model"]

_mode = threading.local()


def enable_static():
    _mode.static = True


def disable_static():
    _mode.static = False


def in_dynamic_mode() -> bool:
    return not getattr(_mode, "static", False)


class InputSpec:
    """Shape/dtype declaration for compiled functions (reference
    python/paddle/static/input.py)."""

    def __init__(self, shape, dtype="float32", name=None):
        self.shape = tuple(shape)
        self.dtype = convert_dtype(dtype)
        self.name = name

    @classmethod
    def from_tensor(cls, t, name=None):
        return cls(t.shape, str(t.dtype), name or t.name)

    def to_aval(self, batch=1):
        shape = tuple(batch if s in (-1, None) else s for s in self.shape)
        return jax.ShapeDtypeStruct(shape, self.dtype)

    def __repr__(self):
        return f"InputSpec(shape={self.shape}, dtype={self.dtype}, " \
               f"name={self.name})"


class Program:
    """A lazily-jitted callable — the jaxpr/StableHLO artifact replaces
    ProgramDesc."""

    def __init__(self, fn=None, input_specs=None):
        self._fn = fn
        self._input_specs = input_specs
        self._compiled = None

    def __call__(self, *args):
        if self._fn is None:
            raise RuntimeError("empty Program")
        if self._compiled is None:
            self._compiled = jax.jit(self._fn)
        return self._compiled(*args)

    def clone(self, for_test=False):
        return Program(self._fn, self._input_specs)


_default_main = Program()
_default_startup = Program()


def default_main_program():
    return _default_main


def default_startup_program():
    return _default_startup


class program_guard:
    def __init__(self, main_program, startup_program=None):
        self.main = main_program

    def __enter__(self):
        return self.main

    def __exit__(self, *a):
        return False


def data(name, shape, dtype="float32", lod_level=0):
    return InputSpec(shape, dtype, name)


def name_scope(prefix=None):
    import contextlib
    return contextlib.nullcontext()


def cpu_places(device_count=None):
    from ..framework.place import CPUPlace
    return [CPUPlace()]


def device_guard(device=None):
    import contextlib
    return contextlib.nullcontext()


class Executor:
    """API-parity executor: runs jitted programs / callables (reference
    Executor.run fluid/executor.py:1109 → here XLA executes)."""

    def __init__(self, place=None):
        self.place = place

    def run(self, program=None, feed=None, fetch_list=None):
        if callable(program) and not isinstance(program, Program):
            out = program(**(feed or {}))
        elif isinstance(program, Program):
            out = program(**(feed or {})) if feed else program()
        else:
            raise TypeError("Executor.run needs a Program or callable")
        if fetch_list:
            return [np.asarray(o._data if isinstance(o, Tensor) else o)
                    for o in (out if isinstance(out, (list, tuple))
                              else [out])]
        return out


def save_inference_model(path_prefix, feed_vars, fetch_vars, executor=None,
                         program=None, **kwargs):
    """Reference: static.save_inference_model (fluid/io.py) — here wired
    onto jit.save's StableHLO artifact. ``fetch_vars`` may be a Layer or a
    callable producing the fetches from the feeds."""
    from .. import jit as _jit
    target = program if program is not None else fetch_vars
    specs = [v if isinstance(v, InputSpec) else InputSpec.from_tensor(v)
             for v in (feed_vars if isinstance(feed_vars, (list, tuple))
                       else [feed_vars])]
    return _jit.save(target, path_prefix, input_spec=specs)


def load_inference_model(path_prefix, executor=None, **kwargs):
    """Returns (predictor, feed_names, fetch_names) — reference signature
    (program, feed_target_names, fetch_targets)."""
    from .. import jit as _jit
    layer = _jit.load(path_prefix)
    return layer, layer.input_names, None
