"""``paddle.static`` — static-graph user API.

Analog of the reference's ``python/paddle/static/`` (Program, Executor,
program_guard, append_backward over ProgramDesc; fluid/executor.py:1109,
fluid/backward.py). TPU-native stance (SURVEY.md §7): a "program" is a
recorded op list replayed as a pure jax function — XLA is the executor,
``jax.grad`` is ``append_backward``, and the ProgramDesc/InterpreterCore
layer disappears.

How it works (r3 verdict item 7 — real feed/fetch semantics):

- ``enable_static()`` + ``program_guard`` activate op CAPTURE: every eager
  dispatch appends an OpNode to the current Program
  (framework/static_capture.py, hooked in framework/dispatch.py).
- ``static.data(name, shape)`` creates a feed Variable — a live Tensor
  holding a zero placeholder (None dims -> 1) whose id marks where feeds
  enter the recorded graph.
- Layers/ops run eagerly ONCE at build time (concrete placeholder values)
  while the recording happens — the build IS the trace.
- ``Executor.run(prog, feed={name: arr}, fetch_list=[vars])`` replays the
  node list as a jitted pure function of (feeds, params): feeds by NAME,
  fetches by Variable identity (or name). If an optimizer was attached via
  ``minimize()``, the replay is a full train step — jax.value_and_grad over
  the recorded loss + the optimizer's pure update rule — and parameter
  state persists across run() calls (written back to the live Parameters).
"""
from __future__ import annotations

import threading
from typing import Any, Dict, List, Optional, Sequence

import jax
import jax.numpy as jnp
import numpy as np

from ..framework import static_capture as _capture
from ..framework.dtypes import convert_dtype
from ..framework.tensor import Tensor
from . import nn  # noqa: F401  (control-flow ops: cond/while_loop/...)

__all__ = ["enable_static", "disable_static", "in_dynamic_mode",
           "InputSpec", "Program", "program_guard", "default_main_program",
           "default_startup_program", "Executor", "data", "name_scope",
           "cpu_places", "device_guard", "save_inference_model",
           "load_inference_model", "append_backward"]

_mode = threading.local()


def enable_static():
    _mode.static = True
    if _capture.current is None:
        _capture.set_current(_default_main)


def disable_static():
    _mode.static = False
    _capture.set_current(None)


def in_dynamic_mode() -> bool:
    return not getattr(_mode, "static", False)


class InputSpec:
    """Shape/dtype declaration for compiled functions (reference
    python/paddle/static/input.py)."""

    def __init__(self, shape, dtype="float32", name=None):
        self.shape = tuple(shape)
        self.dtype = convert_dtype(dtype)
        self.name = name

    @classmethod
    def from_tensor(cls, t, name=None):
        return cls(t.shape, str(t.dtype), name or t.name)

    def to_aval(self, batch=1):
        shape = tuple(batch if s in (-1, None) else s for s in self.shape)
        return jax.ShapeDtypeStruct(shape, self.dtype)

    def __repr__(self):
        return f"InputSpec(shape={self.shape}, dtype={self.dtype}, " \
               f"name={self.name})"


class Program:
    """A recorded op graph, replayable as a pure jitted function.

    Also still accepts a plain callable (legacy Program(fn) ergonomics).
    """

    def __init__(self, fn=None, input_specs=None):
        self._fn = fn
        self._input_specs = input_specs
        self._compiled = None
        # --- recorded-graph state ---
        self._nodes: List[_capture.OpNode] = []
        self._feeds: Dict[str, int] = {}          # feed name -> tensor id
        self._vars: Dict[int, Tensor] = {}        # keep-alive + fetch map
        self._var_names: Dict[str, int] = {}      # var name -> tensor id
        self._params: Dict[str, Tensor] = {}      # param name -> Parameter
        self._loss: Optional[Tensor] = None
        self._optimizer = None
        self._opt_state = None
        self._grad_vars: Dict[int, str] = {}      # grad var id -> param name
        self._replay_cache: Dict[Any, Any] = {}

    # -- capture hooks (called via framework/static_capture.py) ----------
    def _record_op(self, op_name, fn, in_tensors, out_tensors,
                   attrs=None):
        from ..framework.tensor import Parameter
        inputs = []
        for t in in_tensors:
            tid = id(t)
            self._vars.setdefault(tid, t)
            pname = None
            if isinstance(t, Parameter):
                pname = t.name
                self._params.setdefault(pname, t)
            inputs.append((tid, t._data, pname))
        out_ids = []
        for t in out_tensors:
            tid = id(t)
            self._vars[tid] = t
            out_ids.append(tid)
        self._nodes.append(
            _capture.OpNode(op_name, fn, inputs, out_ids, attrs))
        self._replay_cache.clear()

    def _add_feed(self, name, tensor):
        self._feeds[name] = id(tensor)
        self._vars[id(tensor)] = tensor
        self._var_names[name] = id(tensor)

    # -- program surface -------------------------------------------------
    def __call__(self, *args):
        if self._fn is None:
            raise RuntimeError("empty Program")
        if self._compiled is None:
            self._compiled = jax.jit(self._fn)
        return self._compiled(*args)

    def clone(self, for_test=False):
        p = Program(self._fn, self._input_specs)
        p._nodes = list(self._nodes)
        p._feeds = dict(self._feeds)
        p._vars = dict(self._vars)
        p._var_names = dict(self._var_names)
        p._params = dict(self._params)
        p._loss = self._loss
        p._grad_vars = dict(self._grad_vars)
        if not for_test:
            p._optimizer = self._optimizer
            p._opt_state = self._opt_state  # keep slot continuity
        return p

    def list_vars(self):
        return list(self._vars.values())

    @property
    def num_blocks(self):
        return 1

    # -- replay ----------------------------------------------------------
    def _resolve_fetch(self, item) -> int:
        if isinstance(item, Tensor):
            tid = id(item)
            if tid in self._vars or tid in self._grad_vars:
                return tid
            raise KeyError(
                f"fetch var {item.name!r} is not part of this program")
        if isinstance(item, str):
            if item in self._var_names:
                return self._var_names[item]
            raise KeyError(f"no variable named {item!r} in this program")
        raise TypeError(f"cannot fetch {type(item).__name__}")

    def _forward_env(self, feeds: Dict[str, Any], params: Dict[str, Any],
                     _observer=None):
        """Replay the node list; returns {tensor_id: array}.

        ``_observer(index, node, resolved_inputs)`` is called before each
        node executes — the calibration hook for program-level
        quantization (quantization/passes.py); jitted replays pass None
        so it costs nothing in the compiled path."""
        env: Dict[int, Any] = {}
        for name, tid in self._feeds.items():
            if name in feeds:
                env[tid] = feeds[name]
        for name, value in params.items():
            env[id(self._params[name])] = value
        for i, node in enumerate(self._nodes):
            ins = []
            for tid, const, pname in node.inputs:
                if pname is not None:
                    ins.append(params[pname])
                elif tid in env:
                    ins.append(env[tid])
                else:
                    ins.append(const)
            if _observer is not None:
                _observer(i, node, ins)
            out = node.fn(*ins)
            flat = jax.tree_util.tree_leaves(out)
            for tid, a in zip(node.out_ids, flat):
                env[tid] = a
        return env

    def _needed_ids(self, roots) -> set:
        """Tensor ids reachable backward from ``roots`` through the node
        list (the reference's graph pruning for fetch targets)."""
        needed = set(roots)
        for node in reversed(self._nodes):
            if any(tid in needed for tid in node.out_ids):
                needed.update(tid for tid, _, _ in node.inputs)
        return needed

    def _execute(self, feed: Dict[str, Any], fetch_ids: Sequence[int]):
        """One Executor.run: pure replay (+ train step when an optimizer
        is attached), jit-compiled and cached per feed-shape signature."""
        feed = {k: jnp.asarray(v) for k, v in feed.items()}
        params = {n: p._data for n, p in self._params.items()}
        train = self._optimizer is not None and self._loss is not None
        want_grads = [tid for tid in fetch_ids if tid in self._grad_vars]
        need_grad = train or bool(want_grads)

        # a feed the requested computation depends on must actually be
        # fed — falling back to the zero build-time placeholder would
        # silently return garbage (reference Executor raises too)
        roots = [t for t in fetch_ids if t not in self._grad_vars]
        if need_grad and self._loss is not None:
            roots.append(id(self._loss))
        needed = self._needed_ids(roots)
        missing = [name for name, tid in self._feeds.items()
                   if tid in needed and name not in feed]
        if missing:
            raise ValueError(
                f"feed is missing declared variable(s) {missing} required "
                f"by the requested fetch targets")

        key = (tuple(sorted((k, v.shape, str(v.dtype))
                            for k, v in feed.items())),
               tuple(fetch_ids), train)
        step = self._replay_cache.get(key)
        if step is None:
            loss_id = id(self._loss) if self._loss is not None else None

            def run_fn(feeds, params, opt_state, lr):
                if need_grad:
                    def loss_of(ps):
                        env = self._forward_env(feeds, ps)
                        return env[loss_id].astype(jnp.float32), env

                    (loss, env), grads = jax.value_and_grad(
                        loss_of, has_aux=True)(params)
                    for tid in self._grad_vars:
                        env[tid] = grads[self._grad_vars[tid]]
                    if train:
                        params, opt_state = \
                            self._optimizer.apply_gradients(
                                params, grads, opt_state, lr=lr)
                else:
                    env = self._forward_env(feeds, params)
                fetched = [env[tid] for tid in fetch_ids]
                return fetched, params, opt_state

            step = jax.jit(run_fn)
            self._replay_cache[key] = step

        if train and self._opt_state is None:
            self._opt_state = self._optimizer.init_state(params)
        lr = self._optimizer.get_lr() if train else 0.0
        fetched, new_params, new_opt_state = step(
            feed, params, self._opt_state, jnp.asarray(lr, jnp.float32))
        if train:
            self._opt_state = new_opt_state
            for n, p in self._params.items():
                p._data = new_params[n]  # persist across run() calls
        return [np.asarray(v) for v in fetched]


_default_main = Program()
_default_startup = Program()


def default_main_program():
    return _default_main


def default_startup_program():
    return _default_startup


class program_guard:
    """Route capture into ``main_program`` (reference
    fluid/framework.py program_guard)."""

    def __init__(self, main_program, startup_program=None):
        self.main = main_program
        self._prev = None

    def __enter__(self):
        self._prev = _capture.current
        _capture.set_current(self.main)
        return self.main

    def __exit__(self, *a):
        _capture.set_current(self._prev)
        return False


def data(name, shape, dtype="float32", lod_level=0):
    """Declare a feed Variable in the current program (reference
    static/input.py data). Returns a live placeholder Tensor."""
    prog = _capture.current or _default_main
    placeholder = jnp.zeros(
        tuple(1 if s in (-1, None) else int(s) for s in shape),
        convert_dtype(dtype))
    var = Tensor(placeholder, stop_gradient=True)
    var.name = name
    prog._add_feed(name, var)
    return var


def append_backward(loss, parameter_list=None, no_grad_set=None,
                    callbacks=None):
    """Register grad computation for ``loss`` (reference
    fluid/backward.py append_backward). Returns [(param, grad_var)] where
    the grad vars are fetchable through Executor.run."""
    prog = _capture.current or _default_main
    prog._loss = loss
    out = []
    names = set(parameter_list or ())
    for pname, param in prog._params.items():
        if names and pname not in names and param not in names:
            continue
        gvar = Tensor(jnp.zeros_like(param._data), stop_gradient=True)
        gvar.name = pname + "@GRAD"
        prog._grad_vars[id(gvar)] = pname
        prog._vars[id(gvar)] = gvar
        prog._var_names[gvar.name] = id(gvar)
        out.append((param, gvar))
    return out


def name_scope(prefix=None):
    import contextlib
    return contextlib.nullcontext()


def cpu_places(device_count=None):
    from ..framework.place import CPUPlace
    return [CPUPlace()]


def device_guard(device=None):
    import contextlib
    return contextlib.nullcontext()


class Executor:
    """Feed/fetch-by-name executor over recorded Programs (reference
    Executor.run fluid/executor.py:1109 → here the jitted replay runs
    through XLA)."""

    def __init__(self, place=None):
        self.place = place

    def _maybe_preflight(self, program) -> None:
        """Static-analysis pre-flight of a captured Program, once per
        program (cached on it), gated by ``FLAGS_static_analysis`` —
        the jaxpr linter replays the node list abstractly (no compile,
        no execution) and warns/raises on findings, the analog of the
        reference running its IR passes before the first executor step
        (framework/ir/pass.h). Analyzer crashes never block run()."""
        from .. import analysis
        mode = analysis.flag_mode()
        if mode == "off":
            return
        cached = getattr(program, "_analysis_report", None)
        if cached is not None:
            # analysis runs once per program, but error mode must KEEP
            # gating: a caller that caught the first AnalysisError and
            # retries run() may not execute the error-flagged program.
            # (warn mode stays quiet on repeats — the one warning stands)
            if cached and mode == "error" and not cached.ok():
                raise analysis.AnalysisError(cached)
            return
        try:
            report = analysis.analyze(program)
        except Exception as e:  # pragma: no cover - analyzer robustness
            import warnings
            warnings.warn(f"static-analysis pre-flight failed "
                          f"({type(e).__name__}: {e}); running anyway",
                          RuntimeWarning)
            program._analysis_report = False
            return
        program._analysis_report = report
        analysis.apply_mode(report, mode, "the captured Program")

    def run(self, program=None, feed=None, fetch_list=None,
            return_numpy=True):
        if program is None:
            program = _default_main
        if callable(program) and not isinstance(program, Program):
            out = program(**(feed or {}))
            if fetch_list:
                return [np.asarray(o._data if isinstance(o, Tensor) else o)
                        for o in (out if isinstance(out, (list, tuple))
                                  else [out])]
            return out
        if not isinstance(program, Program):
            raise TypeError("Executor.run needs a Program or callable")
        if program._nodes:
            # pause capture during replay: executing the program must not
            # append to it
            prev = _capture.current
            _capture.set_current(None)
            try:
                self._maybe_preflight(program)
                fetch_ids = [program._resolve_fetch(f)
                             for f in (fetch_list or [])]
                return program._execute(feed or {}, fetch_ids)
            finally:
                _capture.set_current(prev)
        if program._fn is not None:
            out = program(**(feed or {})) if feed else program()
            if fetch_list:
                return [np.asarray(o._data if isinstance(o, Tensor) else o)
                        for o in (out if isinstance(out, (list, tuple))
                                  else [out])]
            return out
        # startup program / empty main: parameters were initialised
        # eagerly at layer construction — nothing to do
        return []


def save_inference_model(path_prefix, feed_vars, fetch_vars, executor=None,
                         program=None, **kwargs):
    """Reference: static.save_inference_model (fluid/io.py) — here wired
    onto jit.save's StableHLO artifact. ``fetch_vars`` may be a Layer or a
    callable producing the fetches from the feeds; with a RECORDED
    ``program``, the pruned replay (feeds -> fetches, params baked) is
    what exports."""
    from .. import jit as _jit
    feed_list = (feed_vars if isinstance(feed_vars, (list, tuple))
                 else [feed_vars])
    specs = [v if isinstance(v, InputSpec) else InputSpec.from_tensor(v)
             for v in feed_list]
    if isinstance(program, Program) and program._nodes:
        fetch_list = (fetch_vars if isinstance(fetch_vars, (list, tuple))
                      else [fetch_vars])
        fetch_ids = [program._resolve_fetch(v) for v in fetch_list]
        id2name = {tid: n for n, tid in program._feeds.items()}
        feed_names = []
        for v in feed_list:
            tid = id(v) if isinstance(v, Tensor) else \
                program._var_names.get(getattr(v, "name", None) or v)
            if tid not in id2name:
                raise ValueError(
                    "feed_vars must be this program's declared "
                    "static.data variables")
            feed_names.append(id2name[tid])
        params = {n: p._data for n, p in program._params.items()}

        def replay(*arrays):
            env = program._forward_env(dict(zip(feed_names, arrays)),
                                       params)
            outs = [env[i] for i in fetch_ids]
            return outs[0] if len(outs) == 1 else tuple(outs)

        return _jit.save(replay, path_prefix, input_spec=specs)
    target = program if program is not None else fetch_vars
    return _jit.save(target, path_prefix, input_spec=specs)


def load_inference_model(path_prefix, executor=None, **kwargs):
    """Returns (predictor, feed_names, fetch_names) — reference signature
    (program, feed_target_names, fetch_targets)."""
    from .. import jit as _jit
    layer = _jit.load(path_prefix)
    return layer, layer.input_names, None


from .extras import *  # noqa: E402,F401,F403  (legacy/compat surface)
from . import extras as _extras  # noqa: E402
__all__ += _extras.__all__
