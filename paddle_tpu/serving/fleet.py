"""EngineFleet: N GenerationEngine replicas behind one stats surface.

The multi-engine router (ROADMAP: load-aware dispatch, prefix-cache
affinity) needs a substrate BEFORE any dispatch policy exists: a fleet
object that owns N replicas, submits to them, and — the hard part —
aggregates their telemetry correctly. Correct aggregation is not
averaging: counters SUM, per-replica occupancy stays PER-REPLICA
(gauges), and latency percentiles come from POOLING the replicas' raw
reservoirs into mergeable bucketed histograms
(:class:`~..framework.metrics.HistValue` — summed bucket counts give
the fleet percentile exactly to bin width; averaging per-replica p95s
gives a number that is simply wrong under skewed load).

Dispatch defaults to the null policy — round-robin with spill-over on
backpressure (a replica raising ``QueueFullError`` or a capacity error
passes the request to the next; only when every replica refuses does
the error propagate). Two opt-in policies land on top of the same
spill machinery (``route=``):

* ``"load"`` — rank replicas by MOST FREE BLOCKS from the per-replica
  health gauges (free slots as the dense fallback), unhealthy last,
  round-robin rotation breaking ties so equal replicas still share
  admissions;
* ``"affinity"`` — the prompt's block-aligned prefix (the exact unit
  the prefix-cache trie keys on) hashes to a PIN: the first admission
  chooses by load and pins, every later prompt sharing that prefix
  lands on the same replica — whose trie already holds the blocks — so
  a hot system prompt stays a prefix-cache HIT instead of being
  re-prefilled once per replica. Prompts shorter than one block, and
  any pinned replica that refuses, fall back to load order (spill is
  never sacrificed to affinity).

A POISONED replica (scheduler thread dead, stats() raising) must not
take the fleet's observability down with it: per-replica collection is
fault-isolated, the broken replica reports ``healthy: False`` with its
error, and aggregates cover the healthy rest — statusz exists for
exactly the moment one replica is on fire.

The fleet also registers itself with the metrics registry (gauges
labeled ``{fleet=, engine=}``) and a statusz section, so
``metrics.statusz()`` and the Prometheus scrape see every replica the
moment the fleet is built.
"""
from __future__ import annotations

import itertools
import threading
import weakref
from typing import Any, Dict, List, Optional, Sequence, Tuple

import numpy as np

from ..framework import metrics as _metrics
from ..framework.metrics import HistValue
from .paging import PoolCapacityError, PoolExhaustedError
from .scheduler import QueueFullError

__all__ = ["EngineFleet"]

# stats() keys that SUM across healthy replicas (lifetime counters and
# additive point-in-time totals)
_SUMMED_KEYS = (
    "queue_depth", "active_requests", "num_slots", "slots_in_use",
    "preempts", "requests_retired", "nonfinite_cycles", "num_blocks",
    "kv_blocks_in_use", "cached_blocks", "prefix_hits", "prefix_misses",
    "prefill_tokens_saved", "prefix_evictions", "kv_pool_capacity_bytes",
    "kv_bytes_in_use", "prefill_chunks", "chunked_prefill_tokens",
    "spec_cycles", "spec_proposed", "spec_accepted",
)
# throughput-style keys that also sum (per-replica rates are additive)
_SUMMED_RATES = ("decode_tokens_per_sec", "serving_flops_per_sec",
                 "chunked_prefill_tokens_per_sec")

_fleet_seq = itertools.count()
_LIVE_FLEETS: "weakref.WeakSet[EngineFleet]" = weakref.WeakSet()
_section_registered = False


class EngineFleet:
    """Wrap N engines; aggregate their stats; spill submissions."""

    #: dispatch policies (see module docstring); "rr" is the default
    ROUTES = ("rr", "load", "affinity")

    def __init__(self, engines: Sequence[Any], name: Optional[str] = None,
                 *, route: str = "rr",
                 affinity_block: Optional[int] = None,
                 slo: Optional[Any] = None):
        if not engines:
            raise ValueError("EngineFleet needs at least one engine")
        if route not in self.ROUTES:
            raise ValueError(
                f"route must be one of {self.ROUTES}, got {route!r}")
        if affinity_block is not None and int(affinity_block) < 1:
            raise ValueError(
                f"affinity_block must be >= 1, got {affinity_block}")
        self._engines = list(engines)
        self._name = name or f"fleet{next(_fleet_seq)}"
        self._route = route
        # affinity prefix granularity: explicit, else the replicas' own
        # paged block_size (read lazily from stats), else one min-bucket
        self._affinity_block = (int(affinity_block)
                                if affinity_block is not None else None)
        # prefix-hash -> replica index (host dict, lock-guarded); the
        # pin is advisory — spill always wins over affinity
        self._pins: Dict[int, int] = {}
        self._rr = itertools.cycle(range(len(self._engines)))
        self._lock = threading.Lock()
        self._closed = False
        # SLO plane (serving/slo.py): an attached tracker hooks every
        # replica's flight recorder and its report rides stats()
        self._slo = None
        if slo is not None:
            self.attach_slo(slo)
        _LIVE_FLEETS.add(self)
        _register_fleet_telemetry()
        # scrape-time collector: per-replica gauges under the fleet
        # label (weakref — a dropped fleet stops being scraped)
        ref = weakref.ref(self)

        def _collect():
            f = ref()
            return f._metric_samples() if f is not None else ()
        _metrics.register_collector(f"serving_fleet/{self._name}",
                                    _collect)

    def attach_slo(self, tracker) -> None:
        """Attach an :class:`~.slo.SLOTracker`: every replica's retired
        traces feed its objectives (replica keys = fleet indices) and
        ``stats()`` gains the ``slo`` report + per-replica goodput."""
        tracker.attach_fleet(self)
        self._slo = tracker

    @property
    def slo(self):
        return self._slo

    # -- dispatch ----------------------------------------------------------
    def _rotation(self) -> List[int]:
        """Round-robin visit order: the rotation start advances once
        per submit, so equal replicas share admissions."""
        with self._lock:
            start = next(self._rr)
        n = len(self._engines)
        return [(start + i) % n for i in range(n)]

    def _load_order(self) -> List[int]:
        """Rotation order re-ranked by load: healthy replicas first,
        MOST free blocks first (free slots as the dense tie-breaker /
        fallback), the round-robin rotation breaking exact ties — a
        stable sort over the rotated list, so equally-loaded replicas
        still take turns."""
        reps = {r["replica"]: r for r in self._replica_stats()}

        def rank(i):
            r = reps[i]
            if not r["healthy"]:
                return (1, 0, 0)
            blocks = r.get("num_blocks"), r.get("kv_blocks_in_use")
            free_b = (blocks[0] - blocks[1]
                      if None not in blocks else -1)
            slots = r.get("num_slots"), r.get("slots_in_use")
            free_s = (slots[0] - slots[1]
                      if None not in slots else -1)
            return (0, -free_b, -free_s)
        return sorted(self._rotation(), key=rank)

    def _prefix_pin_key(self, prompt_ids) -> Optional[int]:
        """Affinity key: hash of the prompt's BLOCK-ALIGNED prefix —
        the exact unit the paged prefix-cache trie keys on, so two
        prompts share a pin iff they could share cached blocks. None
        when the prompt doesn't cover one full block (nothing cacheable
        to be affine to)."""
        ids = np.asarray(prompt_ids, np.int32).reshape(-1)
        bs = self._affinity_block
        if bs is None:
            for r in self._replica_stats():
                if r["healthy"] and r.get("block_size"):
                    bs = int(r["block_size"])
                    break
            else:
                bs = 16
            self._affinity_block = bs
        m = (ids.size // bs) * bs
        if m < bs:
            return None
        return hash(tuple(int(t) for t in ids[:m]))

    def _submit_order(self, prompt_ids) -> Tuple[List[int], Optional[int]]:
        """(replica visit order, affinity key to pin on success)."""
        if self._route == "rr":
            return self._rotation(), None
        order = self._load_order()
        if self._route == "load":
            return order, None
        key = self._prefix_pin_key(prompt_ids)
        if key is None:
            return order, None
        with self._lock:
            pinned = self._pins.get(key)
        if pinned is not None and pinned in order:
            order.remove(pinned)
            order.insert(0, pinned)
        return order, key

    def submit(self, prompt_ids, max_new_tokens: int = 32, **kwargs):
        """Routed submit with spill-over: replicas are visited in the
        active policy's order (round-robin rotation, load rank, or
        pinned-replica-first — see the class docstring); a replica
        refusing with backpressure/capacity (QueueFullError,
        PoolCapacityError, a closed engine) passes the request on.
        When every replica refuses, the LAST error propagates. Returns
        the accepted replica's handle (``handle.trace`` etc.
        unchanged)."""
        if self._closed:
            raise RuntimeError("EngineFleet is closed")
        order, key = self._submit_order(prompt_ids)
        last_err: Optional[BaseException] = None
        for i in order:
            eng = self._engines[i]
            try:
                handle = eng.submit(prompt_ids, max_new_tokens, **kwargs)
            except (QueueFullError, PoolCapacityError,
                    PoolExhaustedError) as e:
                last_err = e        # backpressure/capacity: try the next
                # (PoolCapacityError IS a ValueError — it must be
                # caught before the malformed-request clause below)
            except (ValueError, TypeError):
                raise               # a malformed request fails everywhere
            except Exception as e:                       # noqa: BLE001
                last_err = e        # closed/poisoned: try the next
            else:
                if key is not None:
                    # pin follows the ACCEPTING replica: a spilled-over
                    # hot prefix warms its new home's cache, so later
                    # requests chase the blocks, not the original pin
                    with self._lock:
                        self._pins[key] = i
                return handle
        assert last_err is not None
        raise last_err

    def close(self, cancel_pending: bool = False) -> None:
        """Close every replica (each best-effort: one replica's broken
        close must not leak the rest)."""
        with self._lock:
            if self._closed:
                return
            self._closed = True
        _metrics.unregister_collector(f"serving_fleet/{self._name}")
        for eng in self._engines:
            try:
                eng.close(cancel_pending=cancel_pending)
            except Exception:                            # noqa: BLE001
                continue

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.close()
        return False

    def __len__(self) -> int:
        return len(self._engines)

    @property
    def name(self) -> str:
        return self._name

    @property
    def replicas(self) -> List[Any]:
        return list(self._engines)

    # -- aggregation -------------------------------------------------------
    def _replica_stats(self) -> List[Dict[str, Any]]:
        """Per-replica stats() snapshots, fault-isolated: a poisoned
        replica yields ``{healthy: False, error: repr}`` instead of
        killing the collection."""
        out = []
        for i, eng in enumerate(self._engines):
            try:
                s = dict(eng.stats())
                s["healthy"] = True
            except Exception as e:                       # noqa: BLE001
                s = {"healthy": False, "error": repr(e)}
            s["replica"] = i
            out.append(s)
        return out

    def _pooled_latency(self) -> Dict[str, Optional[dict]]:
        """Fleet TTFT/TPOT: each healthy replica's raw reservoir
        becomes a bucketed histogram; the bucket MERGE is the fleet
        distribution (percentiles exact to bin width vs pooling the
        raw samples — the acceptance tolerance)."""
        merged: Dict[str, Optional[HistValue]] = {"ttft_ms": None,
                                                  "tpot_ms": None}
        for eng in self._engines:
            try:
                samples = eng.flight_recorder.latency_samples()
            except Exception:                            # noqa: BLE001
                continue
            for key in merged:
                vals = samples.get(key) or []
                if not vals:
                    continue
                h = HistValue.from_samples(vals)
                merged[key] = h if merged[key] is None \
                    else merged[key].merge(h)
        return {k: (h.summary() if h is not None else None)
                for k, h in merged.items()}

    def stats(self) -> Dict[str, Any]:
        """The fleet operator snapshot: summed counters over healthy
        replicas, pooled latency percentiles, fleet-derived ratios, and
        the full per-replica gauge list (the router's future input:
        free slots/blocks, occupancy, health)."""
        reps = self._replica_stats()
        healthy = [r for r in reps if r["healthy"]]
        agg: Dict[str, Any] = {
            "fleet": self._name,
            "route": self._route,
            "replicas_total": len(reps),
            "replicas_healthy": len(healthy),
        }
        for key in _SUMMED_KEYS + _SUMMED_RATES:
            vals = [r[key] for r in healthy
                    if isinstance(r.get(key), (int, float))]
            if vals:
                agg[key] = type(vals[0])(sum(vals))
        if agg.get("num_slots"):
            agg["slot_utilization"] = \
                agg.get("slots_in_use", 0) / agg["num_slots"]
        if agg.get("num_blocks"):
            agg["block_utilization"] = \
                agg.get("kv_blocks_in_use", 0) / agg["num_blocks"]
        hits = agg.get("prefix_hits")
        if hits is not None:
            agg["prefix_hit_ratio"] = \
                hits / max(1, hits + agg.get("prefix_misses", 0))
        # tiered hit split summed across healthy replicas, re-derived
        # as fleet-level ratios (MIGRATION.md "prefix-hit split" — the
        # aggregate prefix_hit_ratio above stays for dashboards)
        th = {"hbm": 0, "host": 0, "miss": 0}
        tiered = False
        for r in healthy:
            for k, v in (r.get("tier_hits") or {}).items():
                th[k] = th.get(k, 0) + v
                tiered = True
        if tiered:
            denom = max(1, sum(th.values()))
            agg["tier_hits"] = th
            agg["prefix_hit_hbm"] = th["hbm"] / denom
            agg["prefix_hit_host"] = th["host"] / denom
            agg["prefix_miss"] = th["miss"] / denom
        if agg.get("spec_proposed"):
            agg["spec_accept_rate"] = \
                agg.get("spec_accepted", 0) / agg["spec_proposed"]
        # per-tenant goodput split summed across healthy replicas (the
        # front door's multi-tenancy plane — a tenant's traffic may be
        # routed anywhere, so only the fleet sum is the tenant's truth)
        tenants: Dict[str, Dict[str, Any]] = {}
        for r in healthy:
            for t, ts in (r.get("tenants") or {}).items():
                row = tenants.setdefault(
                    t, {"retired": 0, "goodput_rps": 0.0})
                row["retired"] += ts.get("retired", 0)
                row["goodput_rps"] += ts.get("goodput_rps", 0.0)
        if tenants:
            agg["tenants"] = tenants
        agg.update(self._pooled_latency())
        # SLO plane: exact attainment + burn rates + per-replica
        # goodput, fault-isolated like everything else on this surface
        goodput: Dict[str, float] = {}
        if self._slo is not None:
            try:
                rep = self._slo.report()
                agg["slo"] = rep
                goodput = rep.get("goodput_rps") or {}
                if goodput:
                    agg["goodput_rps"] = float(sum(goodput.values()))
            except Exception as e:                       # noqa: BLE001
                agg["slo"] = {"error": repr(e)}
        # per-replica view: identity + the load/health gauges a router
        # dispatches on, straight from each replica's own stats
        agg["replicas"] = [{
            "replica": r["replica"],
            "healthy": r["healthy"],
            **({"error": r["error"]} if not r["healthy"] else {}),
            "queue_depth": r.get("queue_depth"),
            "active_requests": r.get("active_requests"),
            "slots_in_use": r.get("slots_in_use"),
            "slot_utilization": r.get("slot_utilization"),
            "free_slots": (r["num_slots"] - r["slots_in_use"])
            if r.get("num_slots") is not None
            and r.get("slots_in_use") is not None else None,
            "free_blocks": (r["num_blocks"] - r["kv_blocks_in_use"])
            if r.get("num_blocks") is not None
            and r.get("kv_blocks_in_use") is not None else None,
            "kv_bytes_in_use": r.get("kv_bytes_in_use"),
            "prefix_hit_ratio": r.get("prefix_hit_ratio"),
            "goodput_rps": goodput.get(str(r["replica"])),
        } for r in reps]
        return agg

    # -- telemetry wiring --------------------------------------------------
    def _metric_samples(self):
        """Registry collector payload: per-replica gauges labeled
        ``{fleet, engine}`` plus fleet-level counters."""
        if self._closed:
            return ()
        out = []
        for r in self._replica_stats():
            labels = {"fleet": self._name, "engine": str(r["replica"])}
            out.append(("gauge", "serving_replica_healthy", labels,
                        1.0 if r["healthy"] else 0.0))
            if not r["healthy"]:
                continue
            for key, metric in (("queue_depth", "serving_queue_depth"),
                                ("slots_in_use", "serving_slots_in_use"),
                                ("kv_blocks_in_use",
                                 "serving_kv_blocks_in_use"),
                                ("kv_bytes_in_use",
                                 "serving_kv_bytes_in_use")):
                v = r.get(key)
                if isinstance(v, (int, float)):
                    out.append(("gauge", metric, labels, float(v)))
            v = r.get("requests_retired")
            if isinstance(v, (int, float)):
                out.append(("counter", "serving_requests_retired",
                            labels, float(v)))
        return out


def _fleet_section() -> str:
    fleets = [f for f in list(_LIVE_FLEETS) if not f._closed]
    if not fleets:
        return "(no fleets)"
    lines = []
    for f in fleets:
        s = f.stats()
        ttft = s.get("ttft_ms")
        head = (f"fleet {s['fleet']}: {s['replicas_healthy']}/"
                f"{s['replicas_total']} healthy, "
                f"retired {s.get('requests_retired', 0)}")
        if ttft:
            head += f", ttft p50 {ttft['p50']:.1f} ms"
        lines.append(head)
        slo = s.get("slo") or {}
        for oname, o in sorted((slo.get("objectives") or {}).items()):
            att = o.get("attainment")
            burns = o.get("burn_rate") or {}
            burn_txt = " ".join(f"burn[{w}]={b:.2f}"
                                for w, b in sorted(burns.items()))
            lines.append(
                f"  slo {oname}: {o['metric']} <= {o['target_ms']:g}ms "
                f"goal {o['goal']:.2%} attainment "
                + (f"{att:.2%}" if att is not None else "n/a")
                + (f" {burn_txt}" if burn_txt else ""))
        for r in s["replicas"]:
            mark = "ok " if r["healthy"] else "DOWN"
            lines.append(
                f"  [{r['replica']}] {mark} queue={r['queue_depth']} "
                f"active={r['active_requests']} "
                f"free_slots={r['free_slots']} "
                f"free_blocks={r['free_blocks']}"
                + (f" err={r.get('error')}" if not r["healthy"] else ""))
    return "\n".join(lines)


def _register_fleet_telemetry() -> None:
    global _section_registered
    if not _section_registered:
        _metrics.register_statusz_section("serving fleets",
                                          _fleet_section)
        _section_registered = True
