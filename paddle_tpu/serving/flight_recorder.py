"""Scheduler flight recorder: bounded postmortem telemetry, always on.

The span profiler answers "where did the time go" — but only while a
``profile()`` session is armed, which it never is when a production
scheduler stalls at 3am. The flight recorder is the always-on
complement: two bounded ring buffers the scheduler writes on every
cycle (host dicts, no device work, O(1) per cycle), dumpable as JSON
after the fact:

* **cycle records** — per scheduler cycle: the sweep / admission /
  prefill / decode-dispatch / host-fetch wall-time breakdown, batch
  occupancy, queue depth, tokens emitted, and (paged) blocks in use;
* **request events** — the tail of every request's lifecycle marks
  (submit, admitted, preempt, first_token, finish/cancel/deadline/
  error) interleaved in arrival order, so "which request was in flight
  when cycle N went sideways" is answerable.

It also aggregates per-engine latency samples: every retired
:class:`~.tracing.RequestTrace` deposits its TTFT/TPOT here, and
``engine.stats()`` reads the percentiles from THIS recorder — so two
engines in one process (or back-to-back tests) never contaminate each
other the way the process-global monitor histograms do.

**Tail sampling** (the SLO plane's postmortem half): averages hide the
outliers that blow an SLO, so past the normal rings the recorder keeps
FULL trace snapshots for three populations — the slowest-N requests by
TTFT, every request that violated the armed SLO
(:meth:`set_tail_slo`), and a short recent-trace ring for context.
``tail_traces()`` serves them to ``/tracez``. Retire hooks
(:meth:`add_retire_hook`) let the :class:`~.slo.SLOTracker` observe
every retired trace without the scheduler knowing it exists, and a
bounded retire-stamp ring powers the windowed :meth:`goodput` rate the
elastic-fleet signals consume.

``engine.dump_flight_recorder()`` snapshots everything on demand; the
scheduler's step-failure path calls :meth:`auto_dump` so a poisoned
cycle leaves a postmortem file behind even when nobody was watching
(``FLAGS_flight_dump_dir`` points those dumps at persistent storage).

Threading: written by the scheduler thread, read by any (stats / dump)
— every method takes the one small lock; writes are per-cycle, not
per-token, so contention is negligible. Retire hooks run on the
scheduler thread OUTSIDE the recorder lock (a hook may read this
recorder back).
"""
from __future__ import annotations

import heapq
import json
import os
import tempfile
import threading
import time
from collections import deque
from typing import Any, Callable, Dict, List, Optional

from ..framework.monitor import _percentile

__all__ = ["FlightRecorder"]


class FlightRecorder:
    """Bounded ring buffers of scheduler cycles + request events, plus
    per-engine TTFT/TPOT sample reservoirs."""

    def __init__(self, max_cycles: int = 256, max_events: int = 2048,
                 max_samples: int = 4096, tail_keep: int = 8,
                 recent_traces: int = 16):
        if max_cycles < 1 or max_events < 1:
            raise ValueError("flight recorder rings must hold >= 1 entry")
        self._lock = threading.Lock()
        self._cycles: deque = deque(maxlen=int(max_cycles))
        self._events: deque = deque(maxlen=int(max_events))
        self._ttft: deque = deque(maxlen=int(max_samples))
        self._tpot: deque = deque(maxlen=int(max_samples))
        self.cycles_recorded = 0       # monotonic (ring drops, this doesn't)
        self.events_recorded = 0
        self.retired = 0
        self.last_dump_path: Optional[str] = None
        self.dumps = 0
        # tail sampling: slowest-N (min-heap keyed by TTFT so the heap
        # root is the cheapest entry to evict), SLO violations, and a
        # recent ring for context; all hold JSON trace snapshots, not
        # live RequestTrace objects, so /tracez serialization is safe
        # off the scheduler thread
        self._tail_keep = max(1, int(tail_keep))
        self._tail_slow: List[tuple] = []           # (ttft, seq, snapshot)
        self._tail_seq = 0
        self._tail_violations: deque = deque(maxlen=self._tail_keep * 4)
        self._recent: deque = deque(maxlen=int(recent_traces))
        self.tail_slo_ms: Optional[float] = None
        self.slo_violations = 0                     # monotonic
        # (t_retired, ttft_ms) stamps for windowed goodput; bounded
        self._retire_stamps: deque = deque(maxlen=int(max_samples))
        # per-tenant accounting (the front door's multi-tenancy plane):
        # lifetime retire counters plus a bounded per-tenant stamp ring
        # so goodput splits by tenant label without a second pass over
        # the traces. Keyed by trace.tenant; untagged traffic lands
        # under "default".
        self._tenant_counts: Dict[str, int] = {}
        self._tenant_stamps: Dict[str, deque] = {}
        self._tenant_ring = max(256, int(max_samples) // 4)
        self._retire_hooks: List[Callable[[Any], None]] = []

    # -- SLO plane wiring ---------------------------------------------------
    def set_tail_slo(self, slo_ms: Optional[float]) -> None:
        """Arm (or disarm with None) the TTFT SLO that decides which
        retiring traces are tail-sampled as violations and which count
        as "good" in :meth:`goodput`."""
        with self._lock:
            self.tail_slo_ms = float(slo_ms) if slo_ms is not None else None

    def add_retire_hook(self, fn: Callable[[Any], None]) -> None:
        """``fn(trace)`` runs on the scheduler thread after every
        retire, outside the recorder lock; a raising hook is dropped
        from that call only (the scheduler must never die for an
        observer)."""
        with self._lock:
            self._retire_hooks.append(fn)

    # -- writers (scheduler thread) ----------------------------------------
    def record_cycle(self, rec: Dict[str, Any]) -> None:
        with self._lock:
            self._cycles.append(rec)
            self.cycles_recorded += 1

    def record_event(self, request_id: int, name: str,
                     t: Optional[float] = None,
                     meta: Optional[dict] = None) -> None:
        ev = {"request": int(request_id), "event": name,
              "t": t if t is not None else time.perf_counter()}
        if meta:
            ev["meta"] = meta
        with self._lock:
            self._events.append(ev)
            self.events_recorded += 1

    def retire(self, trace) -> None:
        """A request finished: bank its derived latencies so stats()
        percentiles come from this engine's own traffic, tail-sample
        the trace, and fan out to the registered retire hooks."""
        ttft, tpot = trace.ttft_ms, trace.tpot_ms
        now = time.perf_counter()
        snap = None
        try:
            snap = trace.snapshot()
        except Exception:                                # noqa: BLE001
            pass        # a malformed trace must not kill the scheduler
        tenant = getattr(trace, "tenant", None) or "default"
        with self._lock:
            self.retired += 1
            if ttft is not None:
                self._ttft.append(ttft)
            if tpot is not None:
                self._tpot.append(tpot)
            self._retire_stamps.append((now, ttft))
            self._tenant_counts[tenant] = \
                self._tenant_counts.get(tenant, 0) + 1
            ring = self._tenant_stamps.get(tenant)
            if ring is None:
                ring = self._tenant_stamps[tenant] = deque(
                    maxlen=self._tenant_ring)
            ring.append((now, ttft))
            if snap is not None:
                self._recent.append(snap)
                violated = (self.tail_slo_ms is not None
                            and ttft is not None
                            and ttft > self.tail_slo_ms)
                if violated:
                    self.slo_violations += 1
                    self._tail_violations.append(
                        dict(snap, tail="slo_violation"))
                if ttft is not None:
                    self._tail_seq += 1
                    heapq.heappush(self._tail_slow,
                                   (ttft, self._tail_seq, snap))
                    if len(self._tail_slow) > self._tail_keep:
                        heapq.heappop(self._tail_slow)   # evict fastest
            hooks = list(self._retire_hooks)
        for fn in hooks:
            try:
                fn(trace)
            except Exception:                            # noqa: BLE001
                pass

    # -- readers -----------------------------------------------------------
    def latency_samples(self) -> Dict[str, List[float]]:
        """Raw copies of the TTFT/TPOT reservoirs (bounded, newest
        window). The fleet aggregator pools THESE into mergeable
        histograms — fleet percentiles must come from pooled samples or
        summed bucket counts, never from averaging per-replica
        percentiles."""
        with self._lock:
            return {"ttft_ms": list(self._ttft),
                    "tpot_ms": list(self._tpot)}

    def latency_summary(self) -> Dict[str, Optional[dict]]:
        """Per-engine ``{"ttft_ms": {...}, "tpot_ms": {...}}`` with
        count/p50/p95/p99 over the retired-trace reservoirs (None while
        no request has produced the respective samples)."""
        with self._lock:
            ttft, tpot = list(self._ttft), list(self._tpot)

        def pct(vals: List[float]) -> Optional[dict]:
            if not vals:
                return None
            s = sorted(vals)
            return {"count": len(s), "p50": _percentile(s, 0.5),
                    "p95": _percentile(s, 0.95),
                    "p99": _percentile(s, 0.99)}

        return {"ttft_ms": pct(ttft), "tpot_ms": pct(tpot)}

    def tail_traces(self) -> Dict[str, Any]:
        """The tail-sampled populations for ``/tracez``: slowest-N by
        TTFT (slowest first), SLO-violating traces, and the recent ring
        — full JSON trace snapshots, outliers the percentiles hide."""
        with self._lock:
            slowest = [dict(s, tail="slowest")
                       for _, _, s in sorted(self._tail_slow,
                                             key=lambda e: -e[0])]
            violations = [dict(v) for v in self._tail_violations]
            recent = [dict(r) for r in self._recent]
            return {"tail_slo_ms": self.tail_slo_ms,
                    "slo_violations_total": self.slo_violations,
                    "slowest": slowest,
                    "slo_violations": violations,
                    "recent": recent}

    def goodput(self, window_s: float = 60.0,
                slo_ms: Optional[float] = None) -> Dict[str, Any]:
        """SLO-meeting completions per second over the trailing window:
        a retired request counts as good when its TTFT met the SLO
        (armed via :meth:`set_tail_slo` or passed here). The divisor is
        the window, clipped to the observed span when the engine is
        younger than the window — a 10s-old engine must not report a
        60x-diluted rate."""
        now = time.perf_counter()
        with self._lock:
            stamps = list(self._retire_stamps)
            slo = slo_ms if slo_ms is not None else self.tail_slo_ms
        in_window = [(t, v) for t, v in stamps if now - t <= window_s]
        if not in_window:
            return {"window_s": window_s, "total": 0, "good": 0,
                    "goodput_rps": 0.0}
        total = len(in_window)
        good = sum(1 for _, v in in_window
                   if v is not None and (slo is None or v <= slo))
        # fully covered window: oldest surviving stamp predates it
        if stamps[0][0] <= now - window_s:
            span = window_s
        else:
            span = max(1e-3, now - in_window[0][0])
        return {"window_s": window_s, "total": total, "good": good,
                "goodput_rps": good / span}

    def tenant_summary(self, window_s: float = 60.0) -> Dict[str, Any]:
        """Per-tenant retire/goodput split over the trailing window —
        the numbers behind the ``serving_tenant_*`` labeled metrics and
        the front door's per-tenant view. A retired request counts as
        good under the same armed tail SLO :meth:`goodput` uses; with
        no SLO armed every completed-with-a-TTFT request is good.
        Empty until the first retire (no phantom "default" row)."""
        now = time.perf_counter()
        with self._lock:
            slo = self.tail_slo_ms
            tenants = {t: (self._tenant_counts.get(t, 0), list(ring))
                       for t, ring in self._tenant_stamps.items()}
        out: Dict[str, Any] = {}
        for tenant, (retired, stamps) in sorted(tenants.items()):
            in_window = [(t, v) for t, v in stamps if now - t <= window_s]
            good = sum(1 for _, v in in_window
                       if v is not None and (slo is None or v <= slo))
            if stamps and stamps[0][0] <= now - window_s:
                span = window_s
            elif in_window:
                span = max(1e-3, now - in_window[0][0])
            else:
                span = window_s
            ttfts = sorted(v for _, v in in_window if v is not None)
            out[tenant] = {
                "retired": retired,
                "window_total": len(in_window),
                "window_good": good,
                "goodput_rps": good / span,
                "ttft_p50_ms": _percentile(ttfts, 0.5) if ttfts else None,
                "ttft_p95_ms": _percentile(ttfts, 0.95) if ttfts else None,
            }
        return out

    def cycle_throughput(self) -> Dict[str, float]:
        """Decode throughput over the cycle ring: cycles recorded in the
        ring, tokens emitted, and summed cycle wall seconds —
        ``engine.stats()`` derives per-engine tokens/sec and serving MFU
        from THIS ring (per-engine by construction, like the latency
        reservoirs)."""
        with self._lock:
            cycles = len(self._cycles)
            emitted = sum(c.get("emitted", 0) for c in self._cycles)
            secs = sum(c.get("cycle_ms", 0.0) for c in self._cycles) / 1e3
            decode_cycles = sum(
                1 for c in self._cycles
                if c.get("decode_dispatch_ms", 0.0) > 0.0)
            decode_flops = sum(c.get("decode_flops", 0.0)
                               for c in self._cycles)
            chunk_tokens = sum(c.get("chunk_tokens", 0)
                               for c in self._cycles)
            prefill_chunks = sum(c.get("prefill_chunks", 0)
                                 for c in self._cycles)
            spec_emitted = sum(c.get("spec_emitted", 0)
                               for c in self._cycles)
            spec_slots = sum(c.get("spec_slots", 0)
                             for c in self._cycles)
            spec_accepted = sum(c.get("spec_accepted", 0)
                                for c in self._cycles)
            spec_proposed = sum(c.get("spec_proposed", 0)
                                for c in self._cycles)
            # hierarchical-KV promotion accounting (ISSUE 20): cycles
            # spent with a waiter skipped for an in-flight H2D copy,
            # and blocks adopted back — the ring-window evidence that
            # promotions overlap decode instead of stalling it
            promo_waits = sum(c.get("promo_waits", 0)
                              for c in self._cycles)
            promoted_blocks = sum(c.get("promoted_blocks", 0)
                                  for c in self._cycles)
        return {"cycles": cycles, "emitted": emitted, "cycle_secs": secs,
                "decode_cycles": decode_cycles,
                "decode_flops": decode_flops,
                "chunk_tokens": chunk_tokens,
                "prefill_chunks": prefill_chunks,
                "spec_emitted": spec_emitted, "spec_slots": spec_slots,
                "spec_accepted": spec_accepted,
                "spec_proposed": spec_proposed,
                "promo_waits": promo_waits,
                "promoted_blocks": promoted_blocks}

    def snapshot(self) -> Dict[str, Any]:
        """JSON-serializable copy of both rings + the counters."""
        with self._lock:
            return {
                "cycles": [dict(c) for c in self._cycles],
                "events": [dict(e) for e in self._events],
                "cycles_recorded": self.cycles_recorded,
                "events_recorded": self.events_recorded,
                "requests_retired": self.retired,
                "ring_capacity": {"cycles": self._cycles.maxlen,
                                  "events": self._events.maxlen},
                "tail": {"slo_ms": self.tail_slo_ms,
                         "slowest": len(self._tail_slow),
                         "slo_violations": self.slo_violations,
                         "recent": len(self._recent)},
            }

    # -- dumps -------------------------------------------------------------
    def dump(self, path: Optional[str] = None,
             extra: Optional[dict] = None) -> Dict[str, Any]:
        """Snapshot (plus ``extra``, e.g. engine stats); written to
        ``path`` as JSON when given. Returns the document."""
        doc = self.snapshot()
        doc["latency"] = self.latency_summary()
        doc["tail_traces"] = self.tail_traces()
        if extra:
            doc.update(extra)
        if path:
            d = os.path.dirname(os.path.abspath(path))
            os.makedirs(d, exist_ok=True)
            with open(path, "w") as f:
                json.dump(doc, f, default=repr)
        return doc

    def auto_dump(self, reason: str) -> Optional[str]:
        """Failure-path dump: best effort, NEVER raises (it runs inside
        the scheduler's exception handler — a broken disk must not turn
        a poisoned step into a dead loop). Returns the file path.

        The filename carries a monotonic per-recorder sequence number:
        two poisoned cycles in quick succession are exactly the case a
        postmortem exists for, and without the suffix the second dump
        OVERWRITES the first — the origin cycle's evidence — at the
        pid+recorder path.

        The directory honors ``FLAGS_flight_dump_dir`` (env var wins
        over the flag registry so ops can redirect a running deployment
        without code) and is created on demand; empty falls back to the
        system tempdir."""
        try:
            d = os.environ.get("FLAGS_flight_dump_dir", "").strip()
            if not d:
                try:
                    from ..framework import flags as _flags
                    d = str(_flags.flag_value(
                        "FLAGS_flight_dump_dir") or "").strip()
                except Exception:                        # noqa: BLE001
                    d = ""
            d = d or tempfile.gettempdir()
            os.makedirs(d, exist_ok=True)
            with self._lock:
                seq = self.dumps
            path = os.path.join(
                d,
                f"paddle_serving_flight_{os.getpid()}_{id(self):x}"
                f"_{seq:04d}.json")
            self.dump(path, extra={"reason": reason,
                                   "dumped_at": time.time()})
            with self._lock:
                self.last_dump_path = path
                self.dumps += 1
            return path
        except Exception:                                # noqa: BLE001
            return None

    def __repr__(self):
        with self._lock:
            return (f"<FlightRecorder cycles={len(self._cycles)}/"
                    f"{self.cycles_recorded} events={len(self._events)}/"
                    f"{self.events_recorded} retired={self.retired}>")
