"""Continuous-batching scheduler: admit, decode, retire — every step.

The loop at the heart of ``GenerationEngine``. Unlike the gather-and-run
``inference.BatchingEngine`` (whole batch enters and leaves together),
membership of the in-flight batch changes EVERY step:

* **admit** — pop from the bounded admission queue into free pool
  slots under WEIGHTED-FAIR scheduling: queued requests are classed by
  (lane, tenant) and served by weighted deficit-round-robin (priority
  lanes — ``interactive`` outweighs ``batch`` 4:1 by default, so a
  batch prompt flood cannot starve interactive TTFT while idle
  capacity still flows to batch; one queued class degenerates to the
  old FCFS exactly); one prefill per admitted request, under a
  PREFILL BUDGET
  (tokens per cycle): a burst of long prompts may not starve the slots
  already decoding — when the budget is spent the remaining queue waits
  one decode step (counted as ``serving/preempt``);
* **decode** — ONE jitted, pool-donated step advances every active slot
  by one token (inactive slots compute garbage nobody reads); the
  single host fetch per cycle delivers each new token to its stream;
* **retire** — finished (EOS / token budget), cancelled and
  deadline-expired slots are freed IMMEDIATELY, so their capacity is
  reused by the very next admit — mid-flight, not at batch end.

CHUNKED mode (the fused ragged engine, ``do_chunked_step``): admission
becomes pure host bookkeeping (blocks reserved, ``req.pending_feed``
armed) and each cycle runs ONE fused ragged launch mixing
``prefill_budget`` tokens of prompt chunks with every decode row —
decode is never budget-charged, so a prompt burst cannot monopolize a
cycle, and the first generated token emits from the launch that feeds
the final chunk (``serving/prefill_chunks``/``serving/chunk_tokens``,
per-cycle ``chunk_tokens`` in the flight recorder).

Backpressure is explicit: a full queue raises :class:`QueueFullError`
in ``submit`` (the caller sheds load, nothing queues unboundedly), and
a per-request deadline turns into :class:`DeadlineExceeded` whether the
request is still queued or already decoding.

Observability (the serving SLO spine, ISSUE 6): every request carries a
:class:`~.tracing.RequestTrace` of timestamped lifecycle events
(submit → admitted → prefill → first token → per-token stamps →
finish/cancel/deadline, plus preemptions and prefix hits), from which
TTFT and TPOT derive per request; every CYCLE writes a record into the
always-on bounded :class:`~.flight_recorder.FlightRecorder` (sweep /
admit / prefill / decode-dispatch / host-fetch breakdown, occupancy,
queue depth) so a scheduler stall is debuggable postmortem without the
profiler armed. When a ``profiler.profile()`` session IS armed, the
same phases additionally emit nested ``serving/cycle`` spans and each
finished request exports a chrome-trace lane.

Threading contract: ``submit``/``cancel`` may be called from any
thread; the loop body, the pool, and all slot state belong to the
scheduler thread alone (trace marks and cycle records included — all
host stamps, taken outside every traced fn). The ONLY device→host sync
in the loop is :func:`_fetch` below — everything else stays async
(enforced by the ``serving-host-sync`` self-lint rule over this
package).
"""
from __future__ import annotations

import itertools
import queue
import threading
import time
from typing import Callable, Dict, List, Optional, Tuple

import numpy as np

from ..framework.monitor import stat_add, stat_observe
from ..profiler import memory as _memory
from ..profiler import span as _prof
from .flight_recorder import FlightRecorder
from .paging import PoolExhaustedError
from .tracing import RequestTrace

__all__ = ["QueueFullError", "DeadlineExceeded", "RequestCancelled",
           "GenerationRequest", "Scheduler"]


class QueueFullError(RuntimeError):
    """The admission queue is at capacity — shed load and retry later.

    Carries the scheduler's shed metadata, stamped AT RAISE TIME, so a
    wire layer can answer with an honest ``Retry-After`` instead of a
    guess: ``queue_depth`` (entries queued when the submit was refused)
    and ``est_wait_s`` (depth x the EWMA inter-admission interval;
    ``None`` until the scheduler has admitted at least two requests)."""

    def __init__(self, message: str = "", *,
                 queue_depth: Optional[int] = None,
                 est_wait_s: Optional[float] = None):
        super().__init__(message)
        self.queue_depth = queue_depth
        self.est_wait_s = est_wait_s


class DeadlineExceeded(TimeoutError):
    """The request's deadline passed before it finished (it may have
    produced some tokens first — they were streamed). Like
    :class:`QueueFullError` it carries ``queue_depth``/``est_wait_s``
    stamped at raise time — a client whose deadline died in the queue
    learns how deep the queue was and what a retry would likely wait."""

    def __init__(self, message: str = "", *,
                 queue_depth: Optional[int] = None,
                 est_wait_s: Optional[float] = None):
        super().__init__(message)
        self.queue_depth = queue_depth
        self.est_wait_s = est_wait_s


class RequestCancelled(RuntimeError):
    """The request was cancelled via ``GenerationRequest.cancel()``."""


_DONE = object()          # stream terminator sentinel


def _fetch(device_array):
    """THE one device→host sync of the serving loop: one fetch per decode
    cycle (a batch of tokens), one per prefill (the first token). Every
    other transfer in this package is host→device and async. The rule
    below is the package-wide lint (analysis/selflint.py
    ``serving-host-sync``); this call site is the argued exception."""
    import jax
    return np.asarray(jax.device_get(device_array))  # lint: ok


class GenerationRequest:
    """One submitted generation: the scheduler's work item AND the
    caller's handle (``stream()`` / ``result()`` / ``cancel()``).

    Caller-side API is thread-safe; the mutable decode state
    (``emitted``, ``last_token``) belongs to the scheduler thread.
    """

    _ids = itertools.count()

    def __init__(self, prompt: np.ndarray, max_new_tokens: int, *,
                 do_sample: bool = False, temperature: float = 1.0,
                 eos_token_id: Optional[int] = None, pad_token_id: int = 0,
                 timeout: Optional[float] = None,
                 tenant: str = "default", lane: str = "interactive"):
        self.id = next(self._ids)
        self.prompt = np.asarray(prompt, np.int32).reshape(-1)
        self.max_new_tokens = int(max_new_tokens)
        self.do_sample = bool(do_sample)
        self.temperature = float(temperature)
        self.eos_token_id = None if eos_token_id is None \
            else int(eos_token_id)
        self.pad_token_id = int(pad_token_id)
        # multi-tenancy identity: the (lane, tenant) pair is the
        # weighted-fair admission class — untagged traffic all lands in
        # one class, which degenerates to the old FCFS order exactly
        self.tenant = str(tenant)
        self.lane = str(lane)
        self._preempted = False     # replay victims outrank the queue
        # hierarchical-KV promotion state (paged engines with a host
        # tier): the in-flight PromotionTicket this request waits on,
        # and whether its admission was served through a promotion
        # (engine classifies the hit as tier=host)
        self._promo_ticket = None
        self._tier_promoted = False
        self.submitted_at = time.perf_counter()
        self.deadline = None if timeout is None \
            else self.submitted_at + float(timeout)
        # scheduler-side decode state
        self.tokens: List[int] = []     # generated so far (incl. EOS)
        self.emitted = 0
        self.last_token: Optional[int] = None
        # paged engines only: prompt/generated tokens still to be fed
        # through the decode step WITHOUT emitting (prefix-cache hits
        # skip prefill; preempted requests replay their own history on
        # re-admission). Rebuilt at every admission.
        self.replay: List[int] = []
        # fused (chunked-prefill) engines only: the not-yet-fed feed
        # tokens — drained in token-budget chunks through the fused
        # ragged step, mixed into decode launches. Rebuilt at every
        # admission; the first generated token emits from the launch
        # that feeds the final chunk.
        self.pending_feed: List[int] = []
        self.first_token_at: Optional[float] = None
        self._last_token_at: Optional[float] = None
        # lifecycle trace (host stamps; the scheduler marks events, the
        # caller reads derived TTFT/TPOT after result() returns)
        self.trace = RequestTrace(self.id, t_submit=self.submitted_at,
                                  tenant=self.tenant, lane=self.lane)
        self._recorder: Optional[FlightRecorder] = None   # set at submit
        # caller-side plumbing
        self._q: "queue.Queue" = queue.Queue()
        self._done = threading.Event()
        self.error: Optional[BaseException] = None
        self._cancelled = False

    # -- caller side -------------------------------------------------------
    def cancel(self) -> None:
        """Ask the scheduler to drop this request; queued requests are
        rejected at admission, active ones retire at the next decode
        cycle. Already-finished requests are unaffected."""
        self._cancelled = True

    @property
    def cancelled(self) -> bool:
        return self._cancelled and not self._done.is_set()

    def stream(self):
        """Iterator of generated token ids, yielded as each is produced
        (the first right after prefill). Raises the terminal error
        (:class:`RequestCancelled` / :class:`DeadlineExceeded`) after
        any tokens produced before it."""
        _prof.set_thread_name(
            f"stream consumer ({threading.current_thread().name})")
        while True:
            item = self._q.get()
            if item is _DONE:
                return
            if isinstance(item, BaseException):
                raise item
            yield item

    def result(self, timeout: Optional[float] = None) -> np.ndarray:
        """Block until the request finishes; returns the full sequence
        ``[prompt_len + max_new_tokens]`` int32 with post-EOS positions
        filled with ``pad_token_id`` — exactly ``models.generate``'s
        output row for this request."""
        if not self._done.wait(timeout):
            raise TimeoutError(
                f"request {self.id} not finished within {timeout}s")
        if self.error is not None:
            raise self.error
        pad = self.max_new_tokens - len(self.tokens)
        return np.concatenate([
            self.prompt, np.asarray(self.tokens, np.int32),
            np.full(pad, self.pad_token_id, np.int32)])

    def done(self) -> bool:
        return self._done.is_set()

    # -- scheduler side ----------------------------------------------------
    def expired(self, now: Optional[float] = None) -> bool:
        return self.deadline is not None \
            and (now or time.perf_counter()) > self.deadline

    def _emit(self, tok: int) -> None:
        now = time.perf_counter()
        if self.first_token_at is None:
            self.first_token_at = now
            stat_observe("serving/ttft_ms",
                         (now - self.submitted_at) * 1e3)
            self.trace.mark("first_token", t=now)
            if self._recorder is not None:
                self._recorder.record_event(self.id, "first_token", t=now)
        else:
            # the streaming cadence: one inter-token sample per decoded
            # token after the first (replayed tokens never land here)
            stat_observe("serving/tpot_ms",
                         (now - self._last_token_at) * 1e3)
        self._last_token_at = now
        self.trace.stamp_token(now)
        self.tokens.append(tok)
        self.emitted += 1
        self.last_token = tok
        self._q.put(tok)

    def _finish(self, error: Optional[BaseException] = None) -> None:
        self.error = error
        if error is None:
            name = "finish"
        elif isinstance(error, RequestCancelled):
            name = "cancelled"
        elif isinstance(error, DeadlineExceeded):
            name = "deadline"
        else:
            name = "error"
        self.trace.mark(name,
                        **({} if error is None else {"error": repr(error)}))
        if self._recorder is not None:
            self._recorder.record_event(
                self.id, name,
                meta=None if error is None else {"error": repr(error)})
            self._recorder.retire(self.trace)
        self.trace.export_spans()   # chrome-trace lane; no-op unarmed
        self._done.set()
        self._q.put(error if error is not None else _DONE)

    def __repr__(self):
        return (f"<GenerationRequest #{self.id} prompt={len(self.prompt)} "
                f"max_new={self.max_new_tokens} emitted={self.emitted}>")


class Scheduler:
    """The continuous-batching loop over a :class:`~.kv_pool.KVCachePool`.

    Device work is delegated to two engine-provided callables so the
    policy here stays host-pure and unit-testable:

    * ``do_prefill(request, slot, bucket) -> first_token`` — run the
      bucket's prefill step, write the slot, return the first token;
    * ``do_decode(slot_requests) -> [num_slots] token array`` — DISPATCH
      the shared decode step and return its result UN-fetched (a device
      array; plain numpy passes through): the scheduler performs the
      windowed ``_fetch`` itself so the cycle telemetry can time
      dispatch and host-fetch apart — a do_decode that syncs internally
      would hide the fetch inside ``decode_dispatch_ms``. Every slot
      gets a token (garbage for inactive slots).
    """

    def __init__(self, pool, do_prefill: Callable, do_decode: Callable, *,
                 max_queue: int = 128, prefill_budget: Optional[int] = None,
                 do_copy: Optional[Callable] = None,
                 do_chunked_step: Optional[Callable] = None,
                 do_spec_step: Optional[Callable] = None,
                 spec_k: int = 0,
                 recorder: Optional[FlightRecorder] = None,
                 lane_weights: Optional[Dict[str, float]] = None):
        if max_queue < 1:
            raise ValueError(f"max_queue must be >= 1, got {max_queue}")
        self._pool = pool
        self._do_prefill = do_prefill
        self._do_decode = do_decode
        # chunked-prefill mode (the fused ragged engine): prefill is no
        # longer a per-bucket program at admission — admission only
        # allocates blocks and arms ``req.pending_feed``, and
        # ``do_chunked_step(slot_requests, plan) -> token array`` runs
        # ONE ragged launch per cycle mixing budgeted prompt chunks
        # with the decode rows. The prefill budget becomes the per-
        # cycle CHUNK token budget: decode rows are never charged, so a
        # prompt burst can no longer monopolize a cycle.
        self._do_chunked = do_chunked_step
        self._chunked = do_chunked_step is not None
        self.prefill_chunks = 0          # chunk launches fed (slot-cycles)
        self.chunk_tokens = 0            # prompt tokens fed via chunks
        # speculative decoding (fused engines): ``do_spec_step(active,
        # plan, spec) -> [2S + S*spec_k + 1] device array`` — per slot
        # the accepted-prefix length, the corrected/sampled token, the
        # echoed draft tokens (the host never saw the device-side
        # proposals) and the logits-finite sentinel, all in ONE fetch.
        # Decode slots contribute min(spec_k, remaining) candidate rows
        # to the fused launch instead of 1; feed slots chunk as before.
        self._do_spec = do_spec_step
        self._spec = do_spec_step is not None
        self._spec_k = int(spec_k)
        if self._spec and not self._chunked:
            raise ValueError(
                "do_spec_step requires do_chunked_step: speculative "
                "verify rows ride the fused ragged launch")
        if self._spec and self._spec_k < 1:
            raise ValueError(f"spec_k must be >= 1, got {spec_k}")
        self.spec_cycles = 0             # cycles that verified >= 1 slot
        self.spec_proposed = 0           # draft tokens verified
        self.spec_accepted = 0           # draft tokens accepted
        # serving numerics sentinel: decode steps append a logits-finite
        # flag past the token row (models/generation.py), riding the one
        # windowed _fetch — cycles whose logits went NaN/Inf are counted
        # here and flagged in the flight-recorder cycle record
        self.nonfinite_cycles = 0
        # always-on postmortem telemetry: bounded cycle/event rings +
        # the per-engine TTFT/TPOT reservoirs stats() reads
        self.recorder = recorder if recorder is not None \
            else FlightRecorder()
        self._cycle = 0
        self._rec: Optional[dict] = None   # current cycle's record
        # paged pools bring block-granular admission, growth and
        # preemption into the loop; the dense path is untouched
        self._paged = bool(getattr(pool, "is_paged", False))
        self._do_copy = do_copy          # device block copy (COW append)
        self.preempts = 0                # requests evicted mid-flight
        self._max_queue = int(max_queue)
        # tokens of prefill allowed per cycle WHILE slots are decoding
        # (with an idle pool admission is unthrottled — there is nothing
        # to starve). A budget below the head's bucket cannot deadlock:
        # once the active slots drain, the idle-pool path admits it.
        self._prefill_budget = int(prefill_budget or pool.max_len)
        if self._prefill_budget < 1:
            raise ValueError(
                f"prefill_budget must be >= 1, got {self._prefill_budget}")
        self._queue: List[GenerationRequest] = []
        # weighted deficit-round-robin admission (the priority lanes):
        # each queued (lane, tenant) pair is a fairness class; every
        # rotation credits a class `quantum x lane weight` prefill
        # tokens of deficit and the class at the rotation head admits
        # while its deficit covers its head-of-line request's feed
        # cost. With ONE class queued the selector short-circuits to
        # plain FCFS — the legacy single-tenant order, byte for byte.
        # Deficits are capped so an idle class cannot bank unbounded
        # credit and then monopolize admission for whole seconds.
        self._lane_weights: Dict[str, float] = {
            "interactive": 4.0, "batch": 1.0}
        if lane_weights:
            for lane, w in lane_weights.items():
                if float(w) <= 0:
                    raise ValueError(
                        f"lane weight must be > 0, got {lane}={w}")
                self._lane_weights[str(lane)] = float(w)
        self._wdrr_quantum = 32.0            # deficit tokens per weight
        self._deficit: Dict[Tuple[str, str], float] = {}
        self._rr: List[Tuple[str, str]] = []  # class rotation order
        # inter-admission EWMA: the honest-Retry-After estimate carried
        # by QueueFullError/DeadlineExceeded (est_wait ~ depth x this)
        self._admit_stamp: Optional[float] = None
        self._admit_interval_s: Optional[float] = None
        self._slots: Dict[int, GenerationRequest] = {}
        self._cond = threading.Condition()
        self._closing = False
        self._thread = threading.Thread(target=self._loop, daemon=True,
                                        name="paddle-serving-scheduler")
        self._thread.start()

    # -- producer side -----------------------------------------------------
    def _est_wait_s(self, depth: int) -> Optional[float]:
        """Estimated queue wait for ``depth`` entries: depth x the EWMA
        inter-admission interval (None before two admissions — an
        estimate with no evidence behind it is a lie, not a hint).
        Callers hold ``self._cond`` or tolerate a stale read."""
        if self._admit_interval_s is None:
            return None
        return depth * self._admit_interval_s

    def submit(self, req: GenerationRequest) -> GenerationRequest:
        _prof.set_thread_name(
            f"submitter ({threading.current_thread().name})")
        with self._cond:
            if self._closing:
                raise RuntimeError("GenerationEngine is closed")
            if len(self._queue) >= self._max_queue:
                stat_add("serving/queue_full")
                depth = len(self._queue)
                raise QueueFullError(
                    f"admission queue is full ({self._max_queue} "
                    f"requests); retry after in-flight work drains",
                    queue_depth=depth,
                    est_wait_s=self._est_wait_s(depth))
            req._recorder = self.recorder
            # recorded before notify so the event ring can never show
            # this request admitted ahead of its own submit
            self.recorder.record_event(
                req.id, "submit", t=req.submitted_at,
                meta={"tenant": req.tenant, "lane": req.lane})
            self._queue.append(req)
            stat_observe("serving/queue_depth", len(self._queue))
            self._cond.notify_all()
        return req

    def close(self, cancel_pending: bool = False) -> None:
        """Stop accepting work and DRAIN: every queued and in-flight
        request runs to completion before the loop exits (with
        ``cancel_pending`` queued requests are cancelled instead —
        in-flight slots still finish)."""
        with self._cond:
            if self._closing and not self._thread.is_alive():
                return
            self._closing = True
            if cancel_pending:
                for r in self._queue:
                    r.cancel()
            self._cond.notify_all()
        self._thread.join()

    @property
    def queue_depth(self) -> int:
        with self._cond:
            return len(self._queue)

    @property
    def active(self) -> int:
        return len(self._slots)

    # -- scheduler thread --------------------------------------------------
    def _loop(self) -> None:
        _prof.set_thread_name("serving scheduler")
        while True:
            with self._cond:
                while not self._closing and not self._queue \
                        and not self._slots:
                    self._cond.wait()
                if self._closing and not self._queue and not self._slots:
                    return
            self._cycle += 1
            t0 = time.perf_counter()
            # the cycle record is ALWAYS captured (bounded ring, host
            # dicts only) — the spans below additionally land in the
            # profiler buffer when a profile() session is armed
            rec = self._rec = {
                "cycle": self._cycle, "t": t0, "sweep_ms": 0.0,
                "admit_ms": 0.0, "prefill_ms": 0.0,
                "decode_dispatch_ms": 0.0, "fetch_ms": 0.0,
                "admitted": [], "retired": [], "emitted": 0,
                "preempts": 0, "active": 0, "occupancy": 0.0,
                "promo_waits": 0, "promoted_blocks": 0,
            }
            failed = None
            try:
                with _prof.record("serving/cycle", "serving",
                                  args={"cycle": self._cycle}):
                    t = time.perf_counter()
                    with _prof.record("serving/sweep", "serving"):
                        self._sweep_queue()
                    rec["sweep_ms"] = (time.perf_counter() - t) * 1e3
                    if self._paged and \
                            getattr(self._pool, "host_tier", None) \
                            is not None:
                        # demotion pump: blocks freed by LAST cycle's
                        # retirements spill before THIS cycle's
                        # admissions can evict them (dispatch-only)
                        self._pool.tier_tick()
                        # promotion prefetch: start/land H2D copies
                        # for the queue FRONT while the decode slots
                        # are still busy (the pending-feed overlap)
                        self._prefetch_promotions()
                    t = time.perf_counter()
                    with _prof.record("serving/admit", "serving"):
                        self._admit()
                    rec["admit_ms"] = (time.perf_counter() - t) * 1e3
                    if self._slots:
                        self._decode_cycle()
                    elif rec["promo_waits"]:
                        # nothing decoding and the only queued work is
                        # waiting on in-flight promotions: nap on the
                        # tier's progress beacon (host Event, ~2ms)
                        # instead of hot-spinning the admit loop. With
                        # decode slots active this branch never runs —
                        # decode cycles never block on a promotion.
                        self._pool.host_tier.wait_progress(0.002)
            except Exception as e:                      # noqa: BLE001
                # a step failure (OOM, bad artifact) poisons the affected
                # requests, never the loop: fail everything in flight and
                # keep serving — the BatchingEngine worker-survival rule
                failed = e
                self._fail_inflight(e)
            finally:
                with self._cond:
                    rec["queue_depth"] = len(self._queue)
                if self._paged:
                    rec["blocks_in_use"] = self._pool.blocks_in_use
                if failed is not None:
                    rec["failed"] = repr(failed)
                rec["cycle_ms"] = (time.perf_counter() - t0) * 1e3
                stat_observe("serving/cycle_ms", rec["cycle_ms"])
                self.recorder.record_cycle(rec)
                # HBM watermark per cycle — a host-only stamp
                # (profiler/memory.py mark: ledger total, NO device
                # poll — polling belongs to the sampler thread; the
                # memory-stats-hot-path self-lint rule enforces it)
                _memory.mark("serving/cycle", cycle=self._cycle,
                             active=rec["active"])
                self._rec = None
                if failed is not None:
                    # leave the postmortem behind: the profiler is
                    # almost never armed when a production step dies,
                    # but the recorder's rings (this poisoned cycle
                    # included) hold what led here
                    self.recorder.auto_dump(reason=repr(failed))
                    if _memory.is_resource_exhausted(failed):
                        # out-of-HBM death: the memory picture (ledger,
                        # timeline, largest live arrays) lands as JSON
                        # next to the flight recorder's dump — best
                        # effort, the original error is already on its
                        # way to every poisoned request
                        _memory.oom_postmortem(failed, extra={
                            "phase": "serving.scheduler",
                            "cycle": self._cycle,
                            "flight_recorder":
                                self.recorder.last_dump_path})

    def _note_nonfinite(self, toks, rec, idx: Optional[int] = None) \
            -> None:
        """Read the decode step's logits-finite sentinel off the fetched
        token row (element ``[num_slots]`` — or ``idx`` for layouts
        like the speculative verify output whose sentinel sits past the
        draft echo; absent from mock/legacy decodes that return exactly
        ``num_slots`` tokens). A tripped flag marks the cycle record
        and counts ``serving/nonfinite_cycles`` — the tokens themselves
        still flow (an argmax over NaN logits is garbage, not a crash),
        so the loop survives and the operator sees WHY the output went
        bad."""
        idx = self._pool.num_slots if idx is None else int(idx)
        shape = getattr(toks, "shape", None)
        if shape and shape[0] > idx and bool(toks[idx]):
            self.nonfinite_cycles += 1
            stat_add("serving/nonfinite_cycles")
            if rec is not None:
                rec["nonfinite"] = True

    def note_decode_flops(self, flops: float) -> None:
        """Record the FLOPs of the decode program dispatched THIS cycle
        into the live cycle record (called by the engine's do_decode,
        scheduler thread). cycle_throughput sums it alongside emitted,
        keeping stats() achieved-FLOP/s on the same ring window as its
        wall-time denominator."""
        if self._rec is not None:
            self._rec["decode_flops"] = \
                self._rec.get("decode_flops", 0.0) + float(flops)

    def note_spec_dispatches(self, n: int) -> None:
        """Count the draft-proposal programs dispatched THIS cycle into
        the live cycle record (called by the engine's spec step,
        scheduler thread). The scanned proposal chain lands exactly 1
        here where the unrolled loop dispatched spec_k launches — the
        flight-recorder evidence for the one-dispatch-per-cycle win."""
        if self._rec is not None:
            self._rec["spec_draft_dispatches"] = \
                self._rec.get("spec_draft_dispatches", 0) + int(n)

    def _fail_inflight(self, error: BaseException) -> None:
        for slot in list(self._slots):
            req = self._slots.pop(slot)
            self._pool.free(slot)
            req._finish(RuntimeError(
                f"serving step failed for request {req.id}: {error!r}"))
        # the steps DONATE the pool buffer, so a step that failed at XLA
        # runtime may have left pool.data already deleted — reallocate
        # before serving on, or every later step dies on the stale handle
        self._pool.reset_data()

    def _sweep_queue(self) -> None:
        """Resolve terminal (cancelled / deadline-expired) entries
        ANYWHERE in the queue, not just at the head: a dead request
        behind a slot-starved head must fail its caller NOW, not when
        its turn finally comes, and must stop holding ``max_queue``
        capacity. Terminal entries are removed, so live-request FCFS
        order is untouched."""
        now = time.perf_counter()
        with self._cond:
            live = []
            for r in self._queue:
                if r.cancelled:
                    self._drop_ticket(r)
                    stat_add("serving/cancelled")
                    r._finish(RequestCancelled(
                        f"request {r.id} cancelled while queued"))
                elif r.expired(now):
                    self._drop_ticket(r)
                    stat_add("serving/deadline_exceeded")
                    depth = len(self._queue)
                    r._finish(DeadlineExceeded(
                        f"request {r.id} exceeded its deadline while "
                        f"queued",
                        queue_depth=depth,
                        est_wait_s=self._est_wait_s(depth)))
                else:
                    live.append(r)
            if len(live) != len(self._queue):
                self._queue[:] = live
                stat_observe("serving/queue_depth", len(live))

    def _select_next(self, skip=frozenset()) -> int:
        """Index into ``self._queue`` of the next admission candidate —
        weighted deficit-round-robin over the queued (lane, tenant)
        classes (caller holds ``self._cond``). Request ids in ``skip``
        (promotion-waiters this cycle) are invisible to the rotation;
        returns -1 when nothing else is queued.

        Preempted replay victims outrank everything (they predate every
        queued arrival and their history is hot). A single queued class
        short-circuits to its FCFS head — identical to the old bare
        FCFS, so untagged traffic and idle-capacity batch flow are
        untouched. With several classes, each rotation credits the
        rotation head ``quantum x lane weight`` tokens of deficit and a
        class admits while its deficit covers its head request's feed
        cost — an interactive lane at weight 4 admits ~4x the token
        rate of a batch flood, and the flood still drains whenever
        interactive has nothing queued (work-conserving)."""
        q = self._queue
        for i, r in enumerate(q):
            if r._preempted and r.id not in skip:
                return i
        heads: Dict[Tuple[str, str], int] = {}
        for i, r in enumerate(q):
            if r.id in skip:
                continue
            key = (r.lane, r.tenant)
            if key not in heads:
                heads[key] = i
        if not heads:
            return -1
        if len(heads) == 1:
            return next(iter(heads.values()))
        # keep the rotation stable across calls; retire dead classes
        self._rr = [k for k in self._rr if k in heads]
        for k in heads:
            if k not in self._rr:
                self._rr.append(k)
                self._deficit.setdefault(k, 0.0)
        # the deficit cap must exceed any admissible feed cost (feeds
        # are bounded by pool.max_len at submit time) or a fat head
        # could starve its own class forever
        cap = max(2.0 * self._pool.max_len, 8.0 * self._wdrr_quantum)
        for _ in range(10_000):
            k = self._rr[0]
            head = q[heads[k]]
            cost = float(max(1, len(head.prompt) + len(head.tokens)))
            if self._deficit.get(k, 0.0) >= cost:
                self._deficit[k] -= cost
                return heads[k]
            w = self._lane_weights.get(k[0], 1.0)
            self._deficit[k] = min(
                self._deficit.get(k, 0.0) + self._wdrr_quantum * w, cap)
            self._rr.append(self._rr.pop(0))
        return heads[self._rr[0]]     # unreachable: cap >= any cost

    def _drop_ticket(self, req: GenerationRequest) -> None:
        """Release a dead waiter's promotion ticket so the tier's
        registry (and the staged device buffers it pins) don't outlive
        the request. A ticket shared by a coalesced waiter survives —
        ``ticket_done`` only unregisters; adoption by the other waiter
        still works."""
        tk = req._promo_ticket
        if tk is None:
            return
        req._promo_ticket = None
        tier = getattr(self._pool, "host_tier", None)
        if tier is not None:
            tier.ticket_done(tk)

    def _prefetch_promotions(self) -> None:
        """Overlap promotion with decode (scheduler thread, right
        after the demotion pump): drive the promotion state machine
        for the FRONT of the queue while every decode slot is still
        busy, so a host-resident chain is requested BEFORE a slot
        frees up. Without this the ticket would only be requested
        when the waiter reaches admission with capacity in hand; a
        competing fresh request would steal that slot during the
        copy's one-or-two-cycle flight and the waiter would sit out
        a whole generation. Adoption is deliberately NOT driven here
        (``adopt=False``): republishing staged blocks before the
        waiter can take references would leave them refcount-0 in a
        pressured pool, where the very next fresh admission evicts
        them again — the ticket pins the staged copy instead, and
        the admission path adopts and refs in one step. Bounded to
        the promoter's double-buffer depth — everything here is host
        bookkeeping plus dispatch-only device calls."""
        with self._cond:
            head = [r for r in self._queue if not r.cancelled][:2]
            for req in head:
                self._promotion_state(req, adopt=False)

    def _promotion_state(self, req: GenerationRequest,
                         adopt: bool = True) -> str:
        """Drive ``req``'s host-tier promotion state machine (caller
        holds ``_cond``; scheduler thread). Returns ``"go"`` — admit
        now (no host-resident prefix, the engine would decline the hit
        anyway, the tier degraded to a plain miss, or the staged blocks
        were just adopted) — or ``"wait"`` — an H2D copy is in flight,
        skip this request until it lands."""
        pool = self._pool
        tk = req._promo_ticket
        if tk is not None:
            if not tk.ready.is_set():
                return "wait"
            if not adopt:
                return "go"     # staged; admission adopts + refs
            req._promo_ticket = None
            if pool.adopt_promotion(tk):
                req._tier_promoted = True
                if self._rec is not None:
                    self._rec["promoted_blocks"] += len(tk.staged_keys)
            return "go"                  # failed ticket = plain miss
        feed = req.prompt if not req.tokens else np.concatenate(
            [req.prompt, np.asarray(req.tokens, np.int32)])
        host_keys, covered = pool.tier_match(feed)
        if not host_keys:
            return "go"
        if not self._chunked and feed.size - covered > pool.min_bucket:
            # mirror the engine's hit heuristic: with an uncovered tail
            # past one min_bucket the engine prefills fresh regardless,
            # so waiting on a promotion would only add latency
            return "go"
        tk = pool.host_tier.request_promotion(host_keys)
        if tk is None:
            return "go"                  # tier degraded to a plain miss
        req._promo_ticket = tk
        return "wait"

    # admission: weighted-fair over (lane, tenant) classes — FCFS
    # within a class and when only one class is queued — under a
    # prefill budget (the loop sweeps the queue under its own
    # span/timer right before calling this)
    def _admit(self) -> None:
        decode_waiting = bool(self._slots)
        budget = self._prefill_budget
        skip: set = set()       # promotion-waiters sit out this cycle
        while True:
            with self._cond:
                if not self._queue:
                    return
                # a promotion whose H2D copy has LANDED admits ahead
                # of the fair rotation: landing it is a block adoption
                # plus a short replay — no prefill program runs — so
                # the jump costs the queue almost nothing, while
                # making the waiter sit through one more fresh
                # bucket-64 prefill would hand back most of the
                # latency the tier just saved
                idx = -1
                for i, r in enumerate(self._queue):
                    tk = r._promo_ticket
                    if r.id in skip:
                        continue
                    # _tier_promoted with no ticket = the chain was
                    # adopted on an earlier pass that then bounced off
                    # a capacity gate: its blocks sit refcount-0 and
                    # evictable, so admit it before any fresh prefill
                    # can steal them back
                    if (tk is not None and tk.ready.is_set()) \
                            or (tk is None and r._tier_promoted):
                        idx = i
                        break
                if idx < 0:
                    idx = self._select_next(skip)
                if idx < 0:
                    return      # only promotion-waiters left queued
                req = self._queue[idx]
                # re-check the head: cancel/expiry may race the sweep
                if req.cancelled:
                    self._queue.pop(idx)
                    self._drop_ticket(req)
                    stat_add("serving/cancelled")
                    req._finish(RequestCancelled(
                        f"request {req.id} cancelled while queued"))
                    continue
                if req.expired():
                    self._queue.pop(idx)
                    self._drop_ticket(req)
                    stat_add("serving/deadline_exceeded")
                    depth = len(self._queue)
                    req._finish(DeadlineExceeded(
                        f"request {req.id} exceeded its deadline while "
                        f"queued",
                        queue_depth=depth,
                        est_wait_s=self._est_wait_s(depth)))
                    continue
                # hierarchical KV: a request whose prefix continues in
                # the HOST tier is treated like a pending feed — start
                # (or poll) its async H2D promotion and admit the cycle
                # the blocks land. Meanwhile the rotation moves on to
                # other queued work, so a copy in flight never blocks a
                # decode cycle or a promotion-free admission.
                if self._paged and \
                        getattr(self._pool, "host_tier", None) is not None \
                        and self._promotion_state(req) == "wait":
                    if self._rec is not None:
                        self._rec["promo_waits"] += 1
                    tk = req._promo_ticket
                    if tk is not None and \
                            time.perf_counter() - tk.created_at < 0.05:
                        # hold the admission line while the copy is
                        # YOUNG: it lands within a cycle or two, and
                        # letting a later-arriving prefill overtake now
                        # would occupy the stream for exactly the time
                        # the hit was about to save (decode slots keep
                        # running — only fresh admissions wait). The
                        # age bound keeps a wedged promoter from
                        # starving the queue: past it, the rotation
                        # resumes overtaking as before.
                        return
                    skip.add(req.id)
                    continue
                # paged re-admission (preemption) replays the request's
                # own generated tokens, so the "prompt" being fed is the
                # whole sequence so far
                feed_len = len(req.prompt) + len(req.tokens) \
                    if self._paged else len(req.prompt)
                bucket = self._pool.bucket_for(feed_len)
                if self._paged and not self._pool.can_admit(feed_len):
                    # block pressure: wait for retirements (the head
                    # keeps its FCFS place; submit-time capacity checks
                    # guarantee it fits an idle pool, so no deadlock)
                    return
                if not self._chunked and decode_waiting and budget < bucket \
                        and not req._tier_promoted:
                    # (an adopted promotion is a guaranteed prefix hit:
                    # no prefill program will run, so the budget gate
                    # that throttles prefill latency does not apply)
                    # budget spent: decode the active slots first; the
                    # queue keeps its place (FCFS) and is retried next
                    # cycle. This is the anti-starvation preemption.
                    # (Chunked mode has no per-admission prefill program
                    # to budget — admission is host bookkeeping, and the
                    # budget throttles the per-cycle chunk feed instead.)
                    stat_add("serving/preempt")
                    return
                slot = self._pool.alloc()
                if slot is None:
                    return              # pool full: decode will retire
                self._queue.pop(idx)
                req._preempted = False
                # admission-rate EWMA: the evidence behind est_wait_s
                now = time.perf_counter()
                if self._admit_stamp is not None:
                    dt = now - self._admit_stamp
                    self._admit_interval_s = dt \
                        if self._admit_interval_s is None \
                        else 0.8 * self._admit_interval_s + 0.2 * dt
                self._admit_stamp = now
                stat_observe("serving/queue_depth", len(self._queue))
            try:
                prefilled = self._prefill(req, slot, bucket)
            except Exception as exc:                    # noqa: BLE001
                # at this point the request is in neither queue nor
                # slots: fail it HERE (or its caller hangs forever) and
                # reclaim the slot, then let the loop's handler fail the
                # other in-flight slots and reset the donated pool
                self._slots.pop(slot, None)
                if self._pool.is_allocated(slot):
                    self._pool.free(slot)
                if not req.done():
                    req._finish(RuntimeError(
                        f"serving step failed for request {req.id}: "
                        f"{exc!r}"))
                raise
            if prefilled:
                # a prefix-cache hit skipped prefill entirely, so it
                # costs the cycle's prefill budget nothing — charging
                # the bucket anyway would throttle exactly the
                # admissions the cache made cheap
                budget -= bucket

    def _prefill(self, req: GenerationRequest, slot: int,
                 bucket: int) -> bool:
        """Admit ``req`` into ``slot``. Returns whether a prefill
        program actually ran (False = paged prefix-cache hit)."""
        # admission wait: submit -> this admission (a re-admission after
        # preemption restarts nothing — the client has been waiting the
        # whole time, so the wall clock since submit IS the lane wait)
        wait_ms = (time.perf_counter() - req.submitted_at) * 1e3
        stat_observe("serving/lane_wait_ms", wait_ms)
        self._event(req, "admitted", slot=slot, bucket=bucket,
                    feed=len(req.prompt) + len(req.tokens),
                    tenant=req.tenant, lane=req.lane,
                    wait_ms=round(wait_ms, 3))
        if self._rec is not None:
            self._rec["admitted"].append(req.id)
        req.trace.mark("prefill_start", bucket=bucket)
        t0 = time.perf_counter()
        with _prof.record("serving/prefill", "serving",
                          args={"bucket": bucket, "slot": slot}):
            first = self._do_prefill(req, slot, bucket)
        dt_ms = (time.perf_counter() - t0) * 1e3
        if self._rec is not None:
            self._rec["prefill_ms"] += dt_ms
        # ran=False marks a paged prefix-cache hit: the engine skipped
        # the prefill program and stamped prefix_hit with tokens saved
        req.trace.mark("prefill_end", bucket=bucket,
                       ran=not (self._paged and first is None))
        if self._paged:
            # the engine set the slot's page table and positions; a
            # None first token means a prefix-cache hit — prefill was
            # skipped entirely and the remaining tokens arrive through
            # the replay path of the decode cycles
            self._slots[slot] = req
            if first is None:
                return False
            stat_add("serving/prefill_tokens", bucket)
            first = int(first)
            req._emit(first)
            stat_add("serving/tokens")
            if self._finished(req, first):
                self._retire(slot)
            return True
        first = int(first)
        stat_add("serving/prefill_tokens", bucket)
        # first generated token sits at cache index `bucket`; the slot's
        # valid keys start past the bucket's left pad
        self._pool.set_slot(slot, pos=bucket,
                            lo=bucket - len(req.prompt))
        self._slots[slot] = req
        req._emit(first)
        stat_add("serving/tokens")
        if self._finished(req, first):
            self._retire(slot)
        return True

    def _event(self, req: GenerationRequest, name: str, **meta) -> None:
        """One lifecycle event, stamped once into both the request's
        trace and the flight recorder's event ring."""
        t = time.perf_counter()
        req.trace.mark(name, t=t, **meta)
        self.recorder.record_event(req.id, name, t=t, meta=meta or None)

    def _finished(self, req: GenerationRequest, tok: int) -> bool:
        return (req.eos_token_id is not None and tok == req.eos_token_id) \
            or req.emitted >= req.max_new_tokens

    def _retire(self, slot: int,
                error: Optional[BaseException] = None) -> None:
        req = self._slots.pop(slot)
        self._pool.free(slot)
        if error is None:
            stat_add("serving/completed")
        if self._rec is not None:
            self._rec["retired"].append(req.id)
        req._finish(error)

    # -- paged memory pressure: growth, copy-on-write, preemption ----------
    def _preempt_youngest(self) -> bool:
        """Evict the youngest active request to free its blocks: the
        request is failed OUT of the pool but not failed to its caller
        — it re-enters the queue at the head (it predates everything
        queued) and replays its own history on re-admission. Returns
        False when nothing is active to evict."""
        if not self._slots:
            return False
        slot = max(self._slots, key=lambda s: self._slots[s].id)
        req = self._slots.pop(slot)
        self._pool.free(slot)
        req.replay = []                  # rebuilt at re-admission
        req.pending_feed = []            # ditto (fused chunked feed)
        req._preempted = True            # outranks WDRR selection
        req._tier_promoted = False       # re-classified at re-admission
        self.preempts += 1
        self._event(req, "preempt", emitted=req.emitted)
        if self._rec is not None:
            self._rec["preempts"] += 1
        stat_add("serving/preempt")
        with self._cond:
            self._queue.insert(0, req)
            stat_observe("serving/queue_depth", len(self._queue))
            self._cond.notify_all()
        return True

    def _prepare_paged(self) -> bool:
        """Before a paged decode step: every active slot must own a
        writable block at its position — grow tables, resolve
        copy-on-write appends, and answer exhaustion by preempting the
        youngest request (oldest-first order makes the youngest the
        victim, never the beneficiary). Returns False when no slots
        survive."""
        for slot in sorted(self._slots,
                           key=lambda s: self._slots[s].id):
            while slot in self._slots:
                try:
                    cow = self._pool.ensure_writable(slot)
                except PoolExhaustedError:
                    # slot itself is active, so there is always a
                    # youngest to evict — possibly slot itself, which
                    # the while re-check then skips
                    self._preempt_youngest()
                    continue
                if cow is not None and self._do_copy is not None:
                    self._do_copy(*cow)
                break
        return bool(self._slots)

    # -- chunked prefill (the fused ragged engine) -------------------------
    def _chunk_plan(self) -> Dict[int, int]:
        """Per-cycle row plan: how many query rows each active slot
        contributes to the fused ragged launch. Decode slots (feed
        drained) always get their 1 row — decode is NEVER budget-
        charged, which is the anti-starvation guarantee. Feeding slots
        split the prefill TOKEN budget FCFS by request age; a slot
        whose share hits 0 simply waits a cycle (its blocks are already
        reserved)."""
        budget = self._prefill_budget
        plan: Dict[int, int] = {}
        for slot in sorted(self._slots,
                           key=lambda s: self._slots[s].id):
            req = self._slots[slot]
            if req.pending_feed:
                n = min(len(req.pending_feed), budget)
                budget -= n
                if n > 0:
                    plan[slot] = n
            else:
                plan[slot] = 1
        return plan

    def _prepare_chunked(self, plan: Dict[int, int]) -> Dict[int, int]:
        """Chunked-mode twin of :meth:`_prepare_paged`: every planned
        slot must own writable blocks for its WHOLE row range this
        cycle (a chunk scatters ``[pos, pos + n)``). Exhaustion preempts
        the youngest request; evicted slots drop out of the plan."""
        for slot in sorted(plan, key=lambda s: self._slots[s].id
                           if s in self._slots else -1):
            while slot in self._slots and slot in plan:
                try:
                    cows = self._pool.ensure_writable_range(
                        slot, self._pool.slot_pos(slot) + plan[slot] - 1)
                except PoolExhaustedError as e:
                    # COW table swaps before the failure are already in
                    # place — their device copies must happen NOW (the
                    # retry sees a refcount-1 block and would never
                    # re-order them)
                    if self._do_copy is not None:
                        for cow in getattr(e, "partial_cows", ()):
                            self._do_copy(*cow)
                    self._preempt_youngest()
                    continue
                if self._do_copy is not None:
                    for cow in cows:
                        self._do_copy(*cow)
                break
        return {s: n for s, n in plan.items() if s in self._slots}

    def _decode_cycle(self) -> None:
        if self._chunked:
            self._chunked_cycle()
            return
        if self._paged and not self._prepare_paged():
            return
        active = dict(self._slots)
        occupancy = len(active) / self._pool.num_slots
        stat_observe("serving/active_slots", len(active))
        stat_observe("serving/batch_occupancy", occupancy)
        rec = self._rec
        if rec is not None:
            rec["active"] = len(active)
            rec["occupancy"] = occupancy
        # dispatch and the windowed host fetch are timed APART: a slow
        # cycle with fat fetch_ms is a host-sync problem, one with fat
        # dispatch_ms is tracing/compile churn — the flight recorder
        # must distinguish them postmortem
        t0 = time.perf_counter()
        with _prof.record("serving/decode_dispatch", "serving",
                          args={"active": len(active)}):
            toks_dev = self._do_decode(active)
        t1 = time.perf_counter()
        with _prof.record("serving/host_fetch", "serving"):
            toks = _fetch(toks_dev)
        t2 = time.perf_counter()
        if rec is not None:
            rec["decode_dispatch_ms"] += (t1 - t0) * 1e3
            rec["fetch_ms"] += (t2 - t1) * 1e3
        self._note_nonfinite(toks, rec)
        dt = t2 - t0
        emitted = 0
        now = time.perf_counter()
        for slot, req in active.items():
            self._pool.advance(slot)
            if req.cancelled:
                stat_add("serving/cancelled")
                self._retire(slot, RequestCancelled(
                    f"request {req.id} cancelled mid-generation"))
                continue
            if req.expired(now):
                stat_add("serving/deadline_exceeded")
                self._retire(slot, DeadlineExceeded(
                    f"request {req.id} exceeded its deadline after "
                    f"{req.emitted} token(s)",
                    queue_depth=len(self._queue),
                    est_wait_s=self._est_wait_s(len(self._queue))))
                continue
            if req.replay:
                # paged prefix-hit / re-admission: this cycle fed one
                # known token; the model's prediction is discarded and
                # the next known token queued — nothing reaches the
                # caller until the replay drains
                req.last_token = req.replay.pop(0)
                if not req.replay:
                    req.trace.mark("replay_done", emitted=req.emitted)
                continue
            tok = int(toks[slot])
            req._emit(tok)
            emitted += 1
            if self._finished(req, tok):
                self._retire(slot)
        stat_add("serving/tokens", emitted)
        if rec is not None:
            rec["emitted"] += emitted
        if dt > 0:
            stat_observe("serving/tokens_per_sec", emitted / dt)

    def _spec_plan(self, plan: Dict[int, int]) -> Dict[int, int]:
        """Speculative row plan: every DECODE slot (feed drained)
        contributes ``min(spec_k, remaining budget)`` candidate rows to
        the verify launch instead of 1 — the rows are the draft's
        proposals, and the slot emits up to that many tokens this
        cycle. Feed slots keep their chunk rows. Mutates ``plan`` (so
        ``_prepare_chunked`` reserves writable blocks for the whole
        candidate range) and returns ``{slot: n_candidates}``."""
        spec: Dict[int, int] = {}
        for slot, n in list(plan.items()):
            req = self._slots[slot]
            if req.pending_feed:
                continue
            k = min(self._spec_k, req.max_new_tokens - req.emitted)
            plan[slot] = spec[slot] = max(1, k)
        return spec

    def _chunked_cycle(self) -> None:
        """One fused ragged launch: budgeted prompt chunks mixed with
        every decode row. The launch's next-token array is real for
        decode slots AND for slots whose final feed chunk landed this
        cycle (their first generated token comes out of the same
        launch); mid-feed slots' rows are ignored. In SPECULATIVE mode
        decode slots contribute their draft-candidate rows instead and
        the launch returns ``[accepted | corrected | draft echo |
        sentinel]`` — accepted candidates emit host-side, the slot's
        pool position rolls back over the rejected rows (signed
        ``advance``), and any cache registration the dead rows touched
        is dropped."""
        plan = self._chunk_plan()
        spec = self._spec_plan(plan) if self._spec else {}
        plan = self._prepare_chunked(plan)
        spec = {s: n for s, n in spec.items() if s in plan}
        if not plan:
            return
        active = {s: self._slots[s] for s in plan}
        occupancy = len(self._slots) / self._pool.num_slots
        stat_observe("serving/active_slots", len(self._slots))
        stat_observe("serving/batch_occupancy", occupancy)
        rec = self._rec
        if rec is not None:
            rec["active"] = len(self._slots)
            rec["occupancy"] = occupancy
        t0 = time.perf_counter()
        with _prof.record("serving/decode_dispatch", "serving",
                          args={"active": len(active),
                                "spec_slots": len(spec),
                                "chunk_rows": sum(
                                    n for s, n in plan.items()
                                    if active[s].pending_feed)}):
            if spec:
                toks_dev = self._do_spec(active, plan, spec)
            else:
                toks_dev = self._do_chunked(active, plan)
        t1 = time.perf_counter()
        with _prof.record("serving/host_fetch", "serving"):
            toks = _fetch(toks_dev)
        t2 = time.perf_counter()
        if rec is not None:
            rec["decode_dispatch_ms"] += (t1 - t0) * 1e3
            rec["fetch_ms"] += (t2 - t1) * 1e3
        S = self._pool.num_slots
        K = self._spec_k
        if spec:
            # spec layout: [accepted (S) | corrected (S) | draft echo
            # (S*K) | sentinel] — the default S-indexed sentinel parse
            # would read a corrected token instead
            acc_row = toks[:S]
            corr_row = toks[S:2 * S]
            draft_rows = toks[2 * S:2 * S + S * K].reshape(S, K)
            self._note_nonfinite(toks, rec, idx=2 * S + S * K)
        else:
            self._note_nonfinite(toks, rec)
        dt = t2 - t0
        emitted = 0
        chunks = 0
        chunk_tokens = 0
        spec_accepted = 0
        spec_proposed = 0
        spec_emitted = 0
        now = time.perf_counter()
        for slot, req in active.items():
            n = plan[slot]
            feeding = bool(req.pending_feed)
            self._pool.advance(slot, n)
            if feeding:
                # the feed tokens' K/V are in the pool now: account the
                # chunk BEFORE the terminal checks so a cancel mid-feed
                # still leaves honest chunk telemetry behind
                del req.pending_feed[:n]
                chunks += 1
                chunk_tokens += n
                self.prefill_chunks += 1
                self.chunk_tokens += n
                stat_add("serving/prefill_chunks")
                stat_add("serving/chunk_tokens", n)
                req.trace.mark("prefill_chunk", tokens=n,
                               remaining=len(req.pending_feed))
            elif slot in spec:
                # verify outcome: the longest agreeing candidate prefix
                # is kept plus (on a rejection) one corrected token;
                # the pool position rolls back over the dead rows
                # (signed advance) and any cache registration they
                # touched is dropped — paged tables address by pos, so
                # the rollback is pure bookkeeping
                a = min(int(acc_row[slot]), n)
                cov = a + 1 if a < n else n
                if cov < n:
                    self._pool.advance(slot, cov - n)
                    self._pool.unpublish_from(
                        slot, self._pool.slot_pos(slot))
                spec_proposed += n
                spec_accepted += a
                self.spec_proposed += n
                self.spec_accepted += a
                stat_add("serving/spec_proposed", n)
                stat_add("serving/spec_accept", a)
                req.trace.mark("spec_verify", proposed=n, accepted=a)
            if req.cancelled:
                stat_add("serving/cancelled")
                self._retire(slot, RequestCancelled(
                    f"request {req.id} cancelled mid-generation"))
                continue
            if req.expired(now):
                stat_add("serving/deadline_exceeded")
                self._retire(slot, DeadlineExceeded(
                    f"request {req.id} exceeded its deadline after "
                    f"{req.emitted} token(s)",
                    queue_depth=len(self._queue),
                    est_wait_s=self._est_wait_s(len(self._queue))))
                continue
            if feeding:
                if req.pending_feed:
                    continue             # mid-feed: row output ignored
                # final chunk landed: publish the fully-written feed
                # blocks to the prefix cache, then emit the first
                # generated token — produced by this same launch
                self._pool.register_prefix(slot, np.concatenate(
                    [req.prompt, np.asarray(req.tokens, np.int32)]))
                req.trace.mark("chunked_prefill_done",
                               emitted=req.emitted)
            if slot in spec and not feeding:
                a = min(int(acc_row[slot]), n)
                emit = [int(t) for t in draft_rows[slot, :a]]
                if a < n:
                    emit.append(int(corr_row[slot]))
                slot_emitted = 0
                for tok in emit:
                    req._emit(tok)
                    emitted += 1
                    slot_emitted += 1
                    if self._finished(req, tok):
                        self._retire(slot)
                        break
                spec_emitted += slot_emitted
                stat_observe("serving/spec_tokens_per_cycle",
                             slot_emitted)
                continue
            tok = int(toks[S + slot] if spec else toks[slot])
            req._emit(tok)
            emitted += 1
            if self._finished(req, tok):
                self._retire(slot)
        if spec:
            self.spec_cycles += 1
            stat_add("serving/spec_cycles")
        stat_add("serving/tokens", emitted)
        if rec is not None:
            rec["emitted"] += emitted
            rec["prefill_chunks"] = rec.get("prefill_chunks", 0) + chunks
            rec["chunk_tokens"] = rec.get("chunk_tokens", 0) \
                + chunk_tokens
            if spec:
                rec["spec_proposed"] = spec_proposed
                rec["spec_accepted"] = spec_accepted
                rec["spec_emitted"] = spec_emitted
                rec["spec_slots"] = len(spec)
        if dt > 0 and emitted:
            stat_observe("serving/tokens_per_sec", emitted / dt)
