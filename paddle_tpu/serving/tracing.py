"""Per-request lifecycle tracing: the serving SLO measurement substrate.

Every :class:`~.scheduler.GenerationRequest` carries a
:class:`RequestTrace` — an append-only list of timestamped lifecycle
events (submit, admitted, prefill start/end with its bucket, prefix hit
with tokens saved, each preemption, replay, first token, finish/cancel/
deadline/error) plus a per-token decode stamp for every emitted token.
From those stamps the trace DERIVES the two serving latencies that
matter:

* **TTFT** (time to first token) — ``first_token - submit``, the
  queueing + prefill latency a client actually feels;
* **TPOT** (time per output token) — the mean inter-token decode
  interval after the first token, the streaming "smoothness" latency.

Both are per-request and per-engine by construction: the engine's
``stats()`` percentiles come from ITS OWN retired traces (via the
:class:`~.flight_recorder.FlightRecorder`), never from the
process-global monitor histograms two engines would contaminate.

Timestamps are ``time.perf_counter()`` host stamps taken in scheduler /
caller host code only — never inside a traced (jitted) function, where
a host read would either burn a trace-time constant or force a sync
(the ``serving-host-sync`` self-lint rule walks this module like the
rest of the package).

Chrome-trace export: when a :func:`profiler.span.profile` session is
armed, a finished trace exports itself as a REQUEST LANE — a synthetic
tid per request carrying queued/prefill/decode phase spans — next to
the scheduler thread's per-cycle spans, so one trace file shows both
views of the same stall (``export_spans``).
"""
from __future__ import annotations

import time
from typing import Any, Dict, List, Optional, Tuple

from ..profiler import span as _prof

__all__ = ["RequestTrace", "TERMINAL_EVENTS", "REQUEST_LANE_BASE"]

# lifecycle events that end a request (exactly one per trace)
TERMINAL_EVENTS = ("finish", "cancelled", "deadline", "error")

# chrome-trace lane offset: request lanes use tid = BASE + request id so
# they sort together below the real (python thread ident) lanes
REQUEST_LANE_BASE = 1_000_000_000


class RequestTrace:
    """Timestamped lifecycle of one generation request.

    Owned by the scheduler thread for writes (``mark`` /
    ``stamp_token``); callers read it freely AFTER ``handle.result()``
    returns — the terminal mark happens-before ``_done`` is set.
    """

    __slots__ = ("request_id", "events", "token_times", "tenant", "lane")

    def __init__(self, request_id: int, t_submit: Optional[float] = None,
                 tenant: Optional[str] = None, lane: Optional[str] = None):
        self.request_id = int(request_id)
        # multi-tenancy identity (the front door's admission class):
        # carried on the trace so tail samples, /tracez and the per-
        # tenant goodput accounting can attribute a retired request
        # without the live GenerationRequest object
        self.tenant = tenant
        self.lane = lane
        self.events: List[Tuple[str, float, Optional[dict]]] = [
            ("submit", t_submit if t_submit is not None
             else time.perf_counter(), None)]
        self.token_times: List[float] = []   # one host stamp per token

    # -- writers (scheduler thread) ----------------------------------------
    def mark(self, name: str, t: Optional[float] = None, **meta) -> None:
        self.events.append((name, t if t is not None
                            else time.perf_counter(), meta or None))

    def stamp_token(self, t: float) -> None:
        self.token_times.append(t)

    # -- readers -----------------------------------------------------------
    def t(self, name: str) -> Optional[float]:
        """Timestamp of the FIRST occurrence of ``name``, or None."""
        for n, ts, _ in self.events:
            if n == name:
                return ts
        return None

    def count(self, name: str) -> int:
        return sum(1 for n, _, _ in self.events if n == name)

    @property
    def submitted_at(self) -> float:
        return self.events[0][1]

    @property
    def finished_at(self) -> Optional[float]:
        for n, ts, _ in reversed(self.events):
            if n in TERMINAL_EVENTS:
                return ts
        return None

    @property
    def completed(self) -> bool:
        return self.finished_at is not None

    @property
    def ttft_ms(self) -> Optional[float]:
        """Submit → first token, the latency a client feels."""
        if not self.token_times:
            return None
        return (self.token_times[0] - self.submitted_at) * 1e3

    @property
    def tpot_ms(self) -> Optional[float]:
        """Mean inter-token interval after the first token (needs >= 2
        tokens — a single-token request has no decode cadence)."""
        if len(self.token_times) < 2:
            return None
        return (self.token_times[-1] - self.token_times[0]) * 1e3 \
            / (len(self.token_times) - 1)

    @property
    def admission_wait_ms(self) -> Optional[float]:
        """Submit → first admission: the queueing share of TTFT (the
        lane-wait the weighted-fair admission exists to bound)."""
        t_adm = self.t("admitted")
        if t_adm is None:
            return None
        return (t_adm - self.submitted_at) * 1e3

    @property
    def decode_intervals_ms(self) -> List[float]:
        tt = self.token_times
        return [(b - a) * 1e3 for a, b in zip(tt, tt[1:])]

    def timeline(self) -> List[Dict[str, Any]]:
        """JSON-friendly event list, times in ms relative to submit."""
        t0 = self.submitted_at
        out = [{"event": n, "t_ms": round((ts - t0) * 1e3, 3),
                **({"meta": m} if m else {})}
               for n, ts, m in self.events]
        for i, ts in enumerate(self.token_times):
            out.append({"event": "token", "i": i,
                        "t_ms": round((ts - t0) * 1e3, 3)})
        out.sort(key=lambda e: e["t_ms"])
        return out

    def snapshot(self) -> Dict[str, Any]:
        """Self-contained JSON dict of the whole trace — the unit the
        flight recorder tail-samples and ``/tracez`` serves. Derived
        latencies are materialized here so a retained snapshot stays
        meaningful after the live trace object is gone."""
        return {"request": self.request_id,
                **({"tenant": self.tenant} if self.tenant else {}),
                **({"lane": self.lane} if self.lane else {}),
                "completed": self.completed,
                "ttft_ms": self.ttft_ms,
                "tpot_ms": self.tpot_ms,
                "tokens": len(self.token_times),
                "preempts": self.count("preempt"),
                "prefix_hits": self.count("prefix_hit"),
                "timeline": self.timeline()}

    # -- chrome-trace export -----------------------------------------------
    def export_spans(self) -> None:
        """Emit this (finished) request as a chrome-trace lane into the
        armed profiler span buffer: one whole-lifetime span plus
        queued/prefill/decode phase children and zero-duration marks for
        preemptions and prefix hits. No-op (one bool check) when no
        profile() session is active — the scheduler calls this from the
        terminal path unconditionally."""
        if not _prof.is_active():
            return
        t0, t1 = self.submitted_at, self.finished_at
        if t1 is None:
            t1 = time.perf_counter()
        tid = REQUEST_LANE_BASE + self.request_id
        _prof.set_thread_name(f"request {self.request_id}", tid=tid)
        _prof.add_event(
            f"request {self.request_id}", "serving/request", t0, t1,
            tid=tid, depth=0,
            args={"ttft_ms": self.ttft_ms, "tpot_ms": self.tpot_ms,
                  "tokens": len(self.token_times),
                  "preempts": self.count("preempt")})
        name = f"request {self.request_id}"
        t_adm = self.t("admitted")
        if t_adm is not None:
            _prof.add_event("queued", "serving/request", t0, t_adm,
                            tid=tid, depth=1, parent=name)
        pending_ps = None   # pair prefill_start/_end sequentially: a
        for n, ts, meta in self.events:   # preempted request has several
            if n == "prefill_start":
                pending_ps = ts
            elif n == "prefill_end":
                if pending_ps is not None:
                    _prof.add_event("prefill", "serving/request",
                                    pending_ps, ts, tid=tid, depth=1,
                                    parent=name, args=meta)
                    pending_ps = None
            elif n in ("preempt", "prefix_hit", "replay_done"):
                _prof.add_event(n, "serving/request", ts, ts, tid=tid,
                                depth=1, parent=name, args=meta)
        if self.token_times:
            _prof.add_event("decode", "serving/request",
                            self.token_times[0], t1, tid=tid, depth=1,
                            parent=name,
                            args={"tokens": len(self.token_times)})

    def __repr__(self):
        return (f"<RequestTrace #{self.request_id} events="
                f"{len(self.events)} tokens={len(self.token_times)} "
                f"ttft_ms={self.ttft_ms} tpot_ms={self.tpot_ms}>")
