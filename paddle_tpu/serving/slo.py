"""Serving SLO plane: objectives, burn rates and goodput over the
metrics registry.

PR 13's registry carries the raw telemetry (labeled counters, gauges,
mergeable histograms, the sampler ring); this module gives it SERVICE
semantics — the signals an autoscaler or a pager actually acts on:

* an **objective** is a latency target over one derived request metric
  (``ttft_ms`` or ``tpot_ms``) plus an attainment goal — "TTFT ≤ 250ms
  for 99% of requests";
* **attainment** is the exact fraction of observed requests that met
  the target (good/total, counted per-event, not derived from
  percentiles);
* **burn rate** is the SRE multi-window signal: (observed error rate /
  error budget) over a fast (1m) and a slow (30m) trailing window,
  where the error budget is ``1 - goal``. Burn 1.0 spends the budget
  exactly at the sustainable rate; a fast-window burn of 14 pages
  someone. Windows are deltas against the registry's EXISTING sampler
  ring (:meth:`MetricsRegistry.timeseries`) — no second time-series
  store, one ring to bound;
* **goodput** is SLO-meeting completions per second per replica (from
  each engine's :class:`~.flight_recorder.FlightRecorder` retire
  stamps) — the elastic-fleet scaling signal.

The tracker attaches to engines through flight-recorder retire hooks
(the scheduler never learns it exists) and publishes through a
registry collector, so everything rides the one scrape:

* ``slo_events_total{objective=}`` / ``slo_good_total{objective=}``
  counters (the burn-rate substrate the sampler ring records);
* ``slo_attainment{objective=}`` and
  ``slo_burn_rate{objective=,window=}`` gauges;
* per-replica ``goodput_rps{engine=}`` gauges;
* a ``slo_latency_ms{objective=}`` histogram written at observe time
  (collectors cannot emit histograms), so a remote scraper can
  recompute attainment from cumulative bucket counts —
  :func:`attainment_from_buckets` bounds it to bucket resolution, and
  ``bench.py --serve-load`` asserts the HTTP-scraped value brackets
  the in-process one.

Host-purity: everything here is host arithmetic over host stamps —
no device fetches, no scheduler blocking (the ``ops-handler-sync``
self-lint rule walks this module).
"""
from __future__ import annotations

import threading
import time
import weakref
from typing import Any, Dict, List, Optional, Tuple

from ..framework import metrics as _metrics

__all__ = ["SLOObjective", "SLOTracker", "attainment_from_buckets"]

_METRICS = ("ttft_ms", "tpot_ms")


class SLOObjective:
    """One latency objective: ``metric <= target_ms`` for ``goal`` of
    requests."""

    __slots__ = ("name", "metric", "target_ms", "goal")

    def __init__(self, name: str, metric: str, target_ms: float,
                 goal: float):
        if metric not in _METRICS:
            raise ValueError(
                f"objective metric must be one of {_METRICS}, "
                f"got {metric!r}")
        if not (0.0 < goal < 1.0):
            raise ValueError("goal must be in (0, 1) — a goal of 1.0 "
                             "has a zero error budget and an undefined "
                             "burn rate")
        self.name = str(name)
        self.metric = metric
        self.target_ms = float(target_ms)
        self.goal = float(goal)

    @property
    def error_budget(self) -> float:
        return 1.0 - self.goal

    def __repr__(self):
        return (f"<SLOObjective {self.name}: {self.metric} <= "
                f"{self.target_ms:g}ms for {self.goal:.2%}>")


def attainment_from_buckets(bucket_pairs: List[Tuple[float, float]],
                            target_ms: float
                            ) -> Tuple[Optional[float], Optional[float]]:
    """Bracket the exact attainment from cumulative ``(le, count)``
    histogram pairs: returns ``(lo, hi)`` — the cumulative fraction at
    the last bound strictly below the target and at the first bound at
    or above it. The exact per-event attainment lies in ``[lo, hi]``;
    the interval width is one bucket of resolution, which is the
    tolerance the scrape-equivalence gate asserts. ``(None, None)``
    when the histogram is empty."""
    pairs = sorted(bucket_pairs, key=lambda p: p[0])
    if not pairs:
        return None, None
    total = float(pairs[-1][1])
    if total <= 0:
        return None, None
    below = 0.0
    for le, cum in pairs:
        if le >= target_ms:
            return below / total, float(cum) / total
        below = float(cum)
    return below / total, 1.0


class SLOTracker:
    """Objectives + burn rates + goodput, published through one
    registry collector.

    One tracker serves one engine or one fleet; it observes retiring
    traces via flight-recorder hooks (:meth:`attach_engine` /
    :meth:`attach_fleet`) or direct :meth:`observe_trace` calls, and is
    read via :meth:`report` (JSON) or the registry scrape.
    """

    def __init__(self, registry: Optional[_metrics.MetricsRegistry] = None,
                 name: str = "slo", fast_window_s: float = 60.0,
                 slow_window_s: float = 1800.0):
        self._registry = registry if registry is not None \
            else _metrics.registry()
        self._name = str(name)
        self._fast = float(fast_window_s)
        self._slow = float(slow_window_s)
        self._lock = threading.Lock()
        self._objectives: Dict[str, SLOObjective] = {}
        self._counts: Dict[str, List[int]] = {}        # name -> [good, total]
        # replica key -> weakref to its FlightRecorder (goodput source);
        # weak so a closed engine's recorder can be collected
        self._recorders: Dict[str, Any] = {}
        self._collector = f"serving_slo/{self._name}"
        self._registry.register_collector(self._collector, self._samples)

    # -- objectives ---------------------------------------------------------
    def add_objective(self, name: str, metric: str = "ttft_ms",
                      target_ms: float = 250.0,
                      goal: float = 0.99) -> SLOObjective:
        obj = SLOObjective(name, metric, target_ms, goal)
        with self._lock:
            self._objectives[obj.name] = obj
            self._counts.setdefault(obj.name, [0, 0])
        return obj

    @property
    def objectives(self) -> Dict[str, SLOObjective]:
        with self._lock:
            return dict(self._objectives)

    # -- attachment ---------------------------------------------------------
    def attach_engine(self, engine, replica: Optional[str] = None) -> str:
        """Hook one engine's flight recorder: every retired trace is
        observed against every objective, the recorder's tail-sampling
        SLO is armed at the tightest TTFT target, and the replica's
        goodput gauge starts publishing. Returns the replica key."""
        rec = engine.flight_recorder
        key = str(replica if replica is not None
                  else getattr(engine, "_eid", id(engine)))
        ttft_targets = [o.target_ms for o in self.objectives.values()
                        if o.metric == "ttft_ms"]
        if ttft_targets and getattr(rec, "set_tail_slo", None):
            rec.set_tail_slo(min(ttft_targets))
        with self._lock:
            self._recorders[key] = weakref.ref(rec)
        if getattr(rec, "add_retire_hook", None):
            rec.add_retire_hook(
                lambda trace, _k=key: self.observe_trace(trace,
                                                         replica=_k))
        return key

    def attach_fleet(self, fleet) -> List[str]:
        """Attach every replica, keyed by fleet replica index — the
        same ids ``EngineFleet.stats()`` reports."""
        return [self.attach_engine(eng, replica=str(i))
                for i, eng in enumerate(fleet.replicas)]

    # -- observation --------------------------------------------------------
    def observe_trace(self, trace, replica: Optional[str] = None) -> None:
        """Score one retired trace against every objective. Runs on the
        scheduler thread (retire hook): exact counters under the
        tracker lock plus one registry histogram write per objective —
        host work only, no device, bounded cost."""
        for obj in self.objectives.values():
            value = getattr(trace, obj.metric, None)
            if value is None:
                continue
            good = value <= obj.target_ms
            with self._lock:
                counts = self._counts.setdefault(obj.name, [0, 0])
                counts[1] += 1
                if good:
                    counts[0] += 1
            self._registry.observe("slo_latency_ms", float(value),
                                   objective=obj.name)

    # -- evaluation ---------------------------------------------------------
    def _window_label(self, w: float) -> str:
        if w >= 60 and abs(w / 60 - round(w / 60)) < 1e-9:
            return f"{int(round(w / 60))}m"
        return f"{int(w)}s"

    def burn_rates(self) -> Dict[str, Dict[str, float]]:
        """Per-objective ``{window: burn}``. Burn = (windowed error
        rate) / (error budget): the window delta comes from the sampler
        ring's recorded ``slo_events_total`` / ``slo_good_total``
        counters — the baseline is the newest ring entry at least one
        window old, falling back to zero (process lifetime) when the
        ring is younger than the window. 0.0 while the window saw no
        events (no traffic burns no budget)."""
        now = time.perf_counter()
        ring = self._registry.timeseries()
        with self._lock:
            counts = {n: tuple(c) for n, c in self._counts.items()}
            objectives = dict(self._objectives)
        out: Dict[str, Dict[str, float]] = {}
        for name, obj in objectives.items():
            good, total = counts.get(name, (0, 0))
            key_total = f'slo_events_total{{objective="{name}"}}'
            key_good = f'slo_good_total{{objective="{name}"}}'
            rates: Dict[str, float] = {}
            for w in (self._fast, self._slow):
                base_total = base_good = 0.0
                for entry in reversed(ring):
                    if entry["t"] <= now - w \
                            and key_total in entry["values"]:
                        base_total = entry["values"][key_total]
                        base_good = entry["values"].get(key_good, 0.0)
                        break
                d_total = total - base_total
                d_bad = (total - good) - (base_total - base_good)
                burn = 0.0
                if d_total > 0:
                    burn = (d_bad / d_total) / obj.error_budget
                rates[self._window_label(w)] = burn
            out[name] = rates
        return out

    def report(self) -> Dict[str, Any]:
        """The JSON SLO report ``EngineFleet.stats()`` embeds: per-
        objective exact attainment + burn rates, per-replica goodput."""
        rates = self.burn_rates()
        with self._lock:
            counts = {n: tuple(c) for n, c in self._counts.items()}
            objectives = dict(self._objectives)
            recorders = dict(self._recorders)
        objs: Dict[str, Any] = {}
        for name, obj in objectives.items():
            good, total = counts.get(name, (0, 0))
            objs[name] = {"metric": obj.metric,
                          "target_ms": obj.target_ms,
                          "goal": obj.goal,
                          "good": good, "total": total,
                          "attainment": (good / total) if total else None,
                          "burn_rate": rates.get(name, {})}
        goodput: Dict[str, float] = {}
        for key, ref in recorders.items():
            rec = ref()
            if rec is None:
                continue
            try:
                goodput[key] = rec.goodput(self._fast)["goodput_rps"]
            except Exception:                            # noqa: BLE001
                continue
        return {"objectives": objs, "goodput_rps": goodput,
                "windows_s": {"fast": self._fast, "slow": self._slow}}

    # -- registry collector -------------------------------------------------
    def _samples(self):
        """Scrape-time collector: counters first (the sampler ring
        records them, closing the burn-rate loop), then the derived
        gauges."""
        with self._lock:
            counts = {n: tuple(c) for n, c in self._counts.items()}
            objectives = dict(self._objectives)
            recorders = dict(self._recorders)
        out = []
        for name in objectives:
            good, total = counts.get(name, (0, 0))
            out.append(("counter", "slo_events_total",
                        {"objective": name}, total))
            out.append(("counter", "slo_good_total",
                        {"objective": name}, good))
            if total:
                out.append(("gauge", "slo_attainment",
                            {"objective": name}, good / total))
        for name, rates in self.burn_rates().items():
            for wlab, burn in rates.items():
                out.append(("gauge", "slo_burn_rate",
                            {"objective": name, "window": wlab}, burn))
        for key, ref in recorders.items():
            rec = ref()
            if rec is None:
                continue
            try:
                g = rec.goodput(self._fast)
            except Exception:                            # noqa: BLE001
                continue
            out.append(("gauge", "goodput_rps", {"engine": key},
                        g["goodput_rps"]))
        return out

    def close(self) -> None:
        self._registry.unregister_collector(self._collector)

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.close()
        return False

    def __repr__(self):
        with self._lock:
            return (f"<SLOTracker {self._name!r} "
                    f"objectives={list(self._objectives)} "
                    f"replicas={list(self._recorders)}>")
