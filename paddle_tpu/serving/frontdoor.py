"""HTTP inference front door: OpenAI-style /v1/completions on the ops port.

The serving stack so far ends at a Python API — ``engine.submit(...)``
returns a handle, ``handle.stream()`` yields tokens. This module puts
that API on a socket with the three things a shared endpoint needs and
a library call does not:

* **wire protocol** — ``POST /v1/completions`` takes OpenAI-style JSON
  (``prompt`` as token ids — the repo has no tokenizer, so text is the
  space-joined id string), answers a completion document, or streams
  Server-Sent Events (``stream: true``): one ``data:`` chunk per token,
  a final chunk carrying ``finish_reason``, then ``data: [DONE]``;
* **admission control** — a per-tenant token bucket (cost = prompt
  tokens + ``max_tokens``) sheds over-budget tenants with 429 and an
  honest ``Retry-After`` BEFORE the request touches the engine, and a
  full scheduler queue answers 503 with a ``Retry-After`` derived from
  the scheduler's own admission-rate EWMA (``QueueFullError.est_wait_s``);
* **identity** — the tenant comes off the wire (``Authorization:
  Bearer <key>`` through the ``api_keys`` map, or the ``X-Tenant``
  header) and rides the request into the scheduler's weighted-fair
  (lane, tenant) admission classes, the flight recorder's per-tenant
  goodput accounting and the shed counters, so one noisy tenant is
  visible and boundable instead of anonymous.

Transport: the stdlib threaded HTTP server shared with
:class:`~.opsserver.OpsServer` — ``FrontDoor.mount(ops)`` registers its
routes in the ops route table so ``/metrics`` and ``/v1/completions``
share one process and one port (``FrontDoor.start()`` builds and owns
an ``OpsServer`` when there is none to mount on). Threaded, not async:
the container bakes in no web framework and generation is minutes-long
streaming against a thread-safe engine API — one OS thread per live
connection is the honest concurrency model here, and the SSE loop is
just a blocking iterator over ``handle.stream()``. The scheduler's
one-fetch-per-cycle device contract is untouched: the front door never
holds a device handle (the ``ops-handler-sync`` self-lint rule walks
this module), it only enqueues work and drains host-side token queues.

Error surface (all JSON, the server thread survives every one):

=====  ====================================================================
400    malformed JSON, oversized body, missing/invalid ``prompt`` or
       ``lane``, per-request ``top_k``/``top_p`` differing from the
       engine's static sampling structure, over-capacity prompt
401    ``api_keys`` configured and the bearer key is unknown
404    unknown path (the ops server's canonical body)
429    tenant over token-bucket budget; ``Retry-After`` from the refill
       rate, shed counted per tenant (``serving/tenant_shed``)
503    scheduler queue full (``Retry-After`` from the admission EWMA)
       or the engine is closed
=====  ====================================================================
"""
from __future__ import annotations

import json
import math
import threading
import time
from typing import Any, Dict, Iterable, Optional, Tuple

from ..framework import metrics as _metrics
from ..framework.monitor import stat_add
from .scheduler import DeadlineExceeded, QueueFullError, RequestCancelled

__all__ = ["FrontDoor", "TokenBucket", "LANES"]

# the scheduler's admission lanes (weights live on the engine); the wire
# rejects anything else with 400 instead of minting ad-hoc classes
LANES = ("interactive", "batch")

_MODEL_ID = "paddle-tpu"


class TokenBucket:
    """Classic token bucket: ``burst`` capacity, ``rate`` tokens/s refill.

    ``try_take(cost)`` is the whole API: 0.0 means admitted (cost
    debited), a positive return is the seconds until the bucket could
    cover ``cost`` — the honest ``Retry-After``. A cost above ``burst``
    can never be admitted (the level is capped); the returned wait is
    computed as if the bucket were uncapped — always positive, so the
    caller always sheds — and a client that retries on schedule and
    still sees 429 should split the request. Thread-safe; monotonic
    clock."""

    __slots__ = ("rate", "burst", "_level", "_t", "_lock")

    def __init__(self, rate: float, burst: float):
        if rate <= 0 or burst <= 0:
            raise ValueError(
                f"rate and burst must be > 0, got rate={rate} burst={burst}")
        self.rate = float(rate)
        self.burst = float(burst)
        self._level = float(burst)
        self._t = time.monotonic()
        self._lock = threading.Lock()

    def try_take(self, cost: float) -> float:
        cost = float(cost)
        with self._lock:
            now = time.monotonic()
            self._level = min(self.burst,
                              self._level + (now - self._t) * self.rate)
            self._t = now
            if cost <= self._level:
                self._level -= cost
                return 0.0
            return (cost - self._level) / self.rate

    def __repr__(self):
        return f"<TokenBucket rate={self.rate}/s burst={self.burst}>"


class FrontDoor:
    """The OpenAI-style completions surface over one engine (or fleet).

    ``engine`` is anything with the ``submit(prompt_ids, max_new_tokens,
    **kwargs) -> handle`` contract (a ``GenerationEngine`` or an
    ``EngineFleet``). Admission knobs:

    * ``rate_tokens_per_s`` / ``burst_tokens`` — the default per-tenant
      token bucket (None = no rate limit);
    * ``tenant_limits`` — ``{tenant: (rate, burst)}`` overrides;
    * ``api_keys`` — ``{bearer_key: tenant}``; when set, a request with
      an ``Authorization: Bearer`` header MUST present a known key
      (401 otherwise). Requests without one fall back to ``X-Tenant``
      or ``default_tenant`` — key-only deployments should front this
      with their key requirement (this is a paper repro, not a vault).
    * ``max_body_bytes`` — requests with a larger Content-Length are
      refused with 400 before the body is read.

    Mount on an existing ops server (``door.mount(srv)``) or let
    ``door.start()`` build one::

        door = FrontDoor(engine, rate_tokens_per_s=500, burst_tokens=2000)
        srv = door.start()               # owns an OpsServer
        requests.post(srv.url + "/v1/completions", json={...})
        door.close()
    """

    def __init__(self, engine: Any, *,
                 rate_tokens_per_s: Optional[float] = None,
                 burst_tokens: Optional[float] = None,
                 tenant_limits: Optional[Dict[str, Tuple[float, float]]] = None,
                 api_keys: Optional[Dict[str, str]] = None,
                 default_tenant: str = "default",
                 default_max_tokens: int = 16,
                 max_body_bytes: int = 1 << 20,
                 registry: Optional[_metrics.MetricsRegistry] = None):
        self._engine = engine
        self._rate = None if rate_tokens_per_s is None \
            else float(rate_tokens_per_s)
        self._burst = float(burst_tokens) if burst_tokens is not None \
            else (None if self._rate is None else 4.0 * self._rate)
        self._tenant_limits = dict(tenant_limits or {})
        self._api_keys = dict(api_keys or {})
        self._default_tenant = str(default_tenant)
        self._default_max_tokens = int(default_max_tokens)
        self._max_body_bytes = int(max_body_bytes)
        self._registry = registry if registry is not None \
            else _metrics.registry()
        self._buckets: Dict[str, TokenBucket] = {}
        self._lock = threading.Lock()
        self._served = 0
        self._streamed = 0
        self._shed: Dict[str, int] = {}
        self._ops: Optional[Any] = None      # owned server, if start()ed

    # -- mounting ------------------------------------------------------------
    def mount(self, ops: Any) -> "FrontDoor":
        """Register this front door's routes in an
        :class:`~.opsserver.OpsServer` route table — completions and
        /metrics then share that server's process and port."""
        ops.add_route("POST", "/v1/completions", self._handle_completions)
        ops.add_route("GET", "/v1/models", self._handle_models)
        return self

    def start(self, host: str = "127.0.0.1", port: int = 0):
        """Build, mount on and start an owned ops server bound to the
        engine (health/tracez reflect it); returns the server — read
        ``srv.url`` for the base address. ``close()`` shuts it down."""
        from .opsserver import OpsServer
        if self._ops is None:
            self._ops = OpsServer(target=self._engine, host=host, port=port,
                                  registry=self._registry)
            self.mount(self._ops)
        return self._ops.start()

    def close(self) -> None:
        ops, self._ops = self._ops, None
        if ops is not None:
            ops.close()

    def __enter__(self):
        self.start()
        return self

    def __exit__(self, *exc):
        self.close()
        return False

    # -- admission -----------------------------------------------------------
    def _bucket(self, tenant: str) -> Optional[TokenBucket]:
        with self._lock:
            b = self._buckets.get(tenant)
            if b is None:
                if tenant in self._tenant_limits:
                    rate, burst = self._tenant_limits[tenant]
                elif self._rate is not None:
                    rate, burst = self._rate, self._burst
                else:
                    return None
                b = self._buckets[tenant] = TokenBucket(rate, burst)
            return b

    def _resolve_tenant(self, h) -> Tuple[Optional[str], Optional[str]]:
        """(tenant, None) or (None, error message) for a 401."""
        auth = h.headers.get("Authorization", "")
        if auth.startswith("Bearer ") and self._api_keys:
            key = auth[len("Bearer "):].strip()
            tenant = self._api_keys.get(key)
            if tenant is None:
                return None, "unknown API key"
            return tenant, None
        tenant = h.headers.get("X-Tenant")
        if tenant:
            return str(tenant).strip(), None
        return self._default_tenant, None

    def _count_shed(self, tenant: str, reason: str) -> None:
        stat_add("serving/tenant_shed")
        with self._lock:
            self._shed[tenant] = self._shed.get(tenant, 0) + 1
        try:
            self._registry.inc("serving_tenant_shed", 1,
                               tenant=tenant, reason=reason)
        except Exception:                                # noqa: BLE001
            pass

    # -- wire helpers --------------------------------------------------------
    @staticmethod
    def _reply(h, code: int, doc: Any,
               headers: Optional[Dict[str, str]] = None) -> None:
        data = json.dumps(doc, default=repr).encode()
        h.send_response(code)
        h.send_header("Content-Type", "application/json")
        h.send_header("Content-Length", str(len(data)))
        for k, v in (headers or {}).items():
            h.send_header(k, str(v))
        h.end_headers()
        h.wfile.write(data)

    @classmethod
    def _reply_error(cls, h, code: int, message: str, etype: str,
                     headers: Optional[Dict[str, str]] = None,
                     **extra) -> None:
        cls._reply(h, code,
                   {"error": {"message": message, "type": etype, **extra}},
                   headers)

    def _read_body(self, h) -> Tuple[Optional[dict], Optional[str]]:
        """(parsed body, None) or (None, error) — the error is the 400
        message; an oversized Content-Length is refused UNREAD so a
        hostile body never buffers."""
        try:
            length = int(h.headers.get("Content-Length") or 0)
        except (TypeError, ValueError):
            return None, "invalid Content-Length"
        if length <= 0:
            return None, "a JSON body is required"
        if length > self._max_body_bytes:
            return None, (f"body of {length} bytes exceeds the "
                          f"{self._max_body_bytes} byte limit")
        raw = h.rfile.read(length)
        try:
            body = json.loads(raw)
        except (ValueError, UnicodeDecodeError) as e:
            return None, f"malformed JSON body: {e}"
        if not isinstance(body, dict):
            return None, "the JSON body must be an object"
        return body, None

    @staticmethod
    def _parse_prompt(body: dict) -> Tuple[Optional[list], Optional[str]]:
        prompt = body.get("prompt", body.get("prompt_ids"))
        if isinstance(prompt, int):
            prompt = [prompt]
        if not isinstance(prompt, list) or not prompt \
                or not all(isinstance(t, int) and not isinstance(t, bool)
                           for t in prompt):
            return None, ("'prompt' must be a non-empty list of token ids "
                          "(ints) — this serving stack is tokenizer-free")
        return prompt, None

    # -- finish-reason / documents -------------------------------------------
    @staticmethod
    def _finish_reason(handle, error: Optional[BaseException]) -> str:
        if isinstance(error, DeadlineExceeded):
            return "deadline"
        if isinstance(error, RequestCancelled):
            return "cancelled"
        if error is not None:
            return "error"
        eos = getattr(handle, "eos_token_id", None)
        toks = getattr(handle, "tokens", ())
        if eos is not None and toks and toks[-1] == eos:
            return "stop"
        return "length"

    @staticmethod
    def _completion_doc(rid: int, tokens: Iterable[int], n_prompt: int,
                        finish_reason: str) -> dict:
        toks = [int(t) for t in tokens]
        return {"id": f"cmpl-{rid}",
                "object": "text_completion",
                "model": _MODEL_ID,
                "choices": [{"index": 0,
                             "text": " ".join(str(t) for t in toks),
                             "token_ids": toks,
                             "finish_reason": finish_reason}],
                "usage": {"prompt_tokens": n_prompt,
                          "completion_tokens": len(toks),
                          "total_tokens": n_prompt + len(toks)}}

    # -- route handlers ------------------------------------------------------
    def _handle_models(self, h) -> None:
        self._reply(h, 200, {"object": "list",
                             "data": [{"id": _MODEL_ID, "object": "model",
                                       "owned_by": "paddle_tpu"}]})

    def _handle_completions(self, h) -> None:
        tenant, auth_err = self._resolve_tenant(h)
        if auth_err is not None:
            self._reply_error(h, 401, auth_err, "invalid_api_key")
            return
        body, body_err = self._read_body(h)
        if body_err is not None:
            self._reply_error(h, 400, body_err, "invalid_request_error")
            return
        prompt, prompt_err = self._parse_prompt(body)
        if prompt_err is not None:
            self._reply_error(h, 400, prompt_err, "invalid_request_error")
            return
        lane = str(body.get("lane") or h.headers.get("X-Lane")
                   or "interactive")
        if lane not in LANES:
            self._reply_error(
                h, 400, f"lane must be one of {list(LANES)}, got {lane!r}",
                "invalid_request_error")
            return
        try:
            max_tokens = int(body.get("max_tokens",
                                      self._default_max_tokens))
        except (TypeError, ValueError):
            self._reply_error(h, 400, "'max_tokens' must be an int",
                              "invalid_request_error")
            return
        stream = bool(body.get("stream", False))

        # per-tenant token-bucket admission BEFORE the engine sees the
        # request: cost is the request's whole token footprint
        bucket = self._bucket(tenant)
        if bucket is not None:
            retry_s = bucket.try_take(len(prompt) + max(1, max_tokens))
            if retry_s > 0:
                self._count_shed(tenant, "rate_limit")
                self._reply_error(
                    h, 429,
                    f"tenant {tenant!r} is over its token budget; retry "
                    f"in {retry_s:.2f}s", "rate_limit_exceeded",
                    headers={"Retry-After": max(1, math.ceil(retry_s))},
                    retry_after_s=round(retry_s, 3), tenant=tenant)
                return

        kwargs: Dict[str, Any] = {"tenant": tenant, "lane": lane}
        for wire, kw in (("temperature", "temperature"),
                         ("do_sample", "do_sample"),
                         ("top_k", "top_k"), ("top_p", "top_p"),
                         ("eos_token_id", "eos_token_id"),
                         ("timeout_s", "timeout")):
            if body.get(wire) is not None:
                kwargs[kw] = body[wire]
        try:
            handle = self._engine.submit(prompt, max_tokens, **kwargs)
        except QueueFullError as e:
            self._count_shed(tenant, "queue_full")
            retry = getattr(e, "est_wait_s", None)
            self._reply_error(
                h, 503, str(e), "overloaded",
                headers={"Retry-After": max(1, math.ceil(retry))
                         if retry else 1},
                queue_depth=getattr(e, "queue_depth", None),
                est_wait_s=retry, tenant=tenant)
            return
        except (ValueError, TypeError) as e:
            self._reply_error(h, 400, str(e), "invalid_request_error")
            return
        except RuntimeError as e:
            # PoolCapacityError is a RuntimeError too — but capacity is
            # the CLIENT's prompt being too big: that one is a 400
            if type(e).__name__ == "PoolCapacityError":
                self._reply_error(h, 400, str(e), "invalid_request_error")
            else:
                self._reply_error(h, 503, str(e), "overloaded")
            return

        with self._lock:
            self._served += 1
            if stream:
                self._streamed += 1
        if stream:
            self._stream_response(h, handle, len(prompt))
        else:
            self._unary_response(h, handle, len(prompt))

    # -- response bodies -----------------------------------------------------
    def _unary_response(self, h, handle, n_prompt: int) -> None:
        # collect by draining the host-side stream queue — NEVER
        # handle.result(): that returns the padded device row and is
        # exactly the sync shape the ops-handler-sync lint rule bans
        tokens, err = [], None
        try:
            for tok in handle.stream():
                tokens.append(int(tok))
        except (DeadlineExceeded, RequestCancelled) as e:
            err = e
        self._reply(h, 200, self._completion_doc(
            handle.id, tokens, n_prompt, self._finish_reason(handle, err)))

    def _stream_response(self, h, handle, n_prompt: int) -> None:
        """SSE over HTTP/1.0 connection-close framing: one ``data:``
        JSON chunk per token as the scheduler produces it, a final
        chunk with ``finish_reason`` + usage, then ``data: [DONE]``."""
        h.send_response(200)
        h.send_header("Content-Type", "text/event-stream")
        h.send_header("Cache-Control", "no-cache")
        h.send_header("X-Accel-Buffering", "no")
        h.end_headers()
        rid = f"cmpl-{handle.id}"

        def emit(doc: Any) -> None:
            h.wfile.write(b"data: " + json.dumps(doc).encode() + b"\n\n")
            h.wfile.flush()

        n, err = 0, None
        try:
            try:
                for tok in handle.stream():
                    emit({"id": rid, "object": "text_completion.chunk",
                          "model": _MODEL_ID,
                          "choices": [{"index": 0, "token_id": int(tok),
                                       "text": f"{int(tok)} ",
                                       "finish_reason": None}]})
                    n += 1
            except (DeadlineExceeded, RequestCancelled) as e:
                err = e
            emit({"id": rid, "object": "text_completion.chunk",
                  "model": _MODEL_ID,
                  "choices": [{"index": 0, "token_id": None, "text": "",
                               "finish_reason":
                               self._finish_reason(handle, err)}],
                  "usage": {"prompt_tokens": n_prompt,
                            "completion_tokens": n,
                            "total_tokens": n_prompt + n}})
            h.wfile.write(b"data: [DONE]\n\n")
            h.wfile.flush()
        except (BrokenPipeError, ConnectionResetError, OSError):
            # client went away mid-stream: stop generating for it
            handle.cancel()

    # -- introspection -------------------------------------------------------
    def stats(self) -> Dict[str, Any]:
        with self._lock:
            return {"served": self._served,
                    "streamed": self._streamed,
                    "shed": dict(self._shed),
                    "shed_total": sum(self._shed.values()),
                    "tenants_seen": sorted(
                        set(self._buckets) | set(self._shed))}

    def __repr__(self):
        s = self.stats()
        return (f"<FrontDoor served={s['served']} "
                f"shed={s['shed_total']} engine={self._engine!r}>")
