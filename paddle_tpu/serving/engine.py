"""``GenerationEngine`` — the user surface of the continuous-batching
LLM server.

Many concurrent ``submit(prompt_ids, ...)`` calls are served by ONE
jitted, pool-donated decode step over a slot-based KV-cache pool
(:mod:`.kv_pool`), driven by the prefill/decode scheduler
(:mod:`.scheduler`). The serving-side twin of the PR-2 donated training
loop: buffers are donated and rebound, the hot loop never syncs except
the one windowed token fetch, and every step program must pass the PR-3
analyzer clean (``engine.analyze()``).

Two KV layouts share this surface (``kv_layout=``):

* ``"dense"`` — one ``[heads, max_len, head_dim]`` stripe per slot
  (:mod:`.kv_pool`): simplest, but concurrency is capped by worst-case
  sequence length;
* ``"paged"`` — a block pool addressed through per-request page tables
  (:mod:`.paging`): a request owns only the blocks covering its tokens
  so far, admission gates on FREE BLOCKS instead of free slots, memory
  pressure preempts the youngest request (requeued, replayed) instead
  of deadlocking, and full prompt blocks are shared across requests
  through the prefix cache — a repeated system prompt skips prefill
  entirely. Greedy paged output is token-identical to the dense slot
  engine (tests/test_serving_paging.py).

Compile discipline: the dense decode step traces ONCE per engine (the
paged one once per pow2 TABLE bucket), and prefill traces once per
CAPACITY BUCKET (pow2 prompt lengths) — all watched by
``framework.trace_probe`` sites (``serving/decode#N``,
``serving/decode[tT]#N``, ``serving/prefill[B]#N``), so a retrace shows
up in the ``dispatch/retrace_cause`` counters exactly like
training-loop churn.

Observability (PR-1 wiring + the ISSUE-6 SLO spine): counters
``serving/requests``, ``serving/completed``, ``serving/tokens``,
``serving/preempt``, ``serving/queue_full``, ``serving/cancelled``,
``serving/deadline_exceeded``, ``serving/prefix_hit``/``prefix_miss``/
``prefill_tokens_saved``/``prefix_evict`` (paged); histograms
``serving/queue_depth``, ``serving/active_slots``,
``serving/batch_occupancy``, ``serving/cycle_ms``, ``serving/ttft_ms``,
``serving/tpot_ms``, ``serving/tokens_per_sec``,
``serving/kv_blocks_in_use`` (paged); spans ``serving/cycle`` with
nested sweep/admit/prefill/decode_dispatch/host_fetch children, plus a
chrome-trace LANE per finished request (``serving/tracing.py``). Every
request handle carries ``handle.trace`` (a
:class:`~.tracing.RequestTrace` with derived TTFT/TPOT), the scheduler
keeps an always-on bounded flight recorder
(:meth:`GenerationEngine.dump_flight_recorder`, auto-dumped when a
step failure poisons requests), and the :meth:`GenerationEngine.stats`
snapshot packages the operator view — per-ENGINE TTFT/TPOT percentiles
included — so nobody has to scrape process-global monitor counters by
prefix.
"""
from __future__ import annotations

import threading
import weakref
from typing import Iterator, Optional

import numpy as np

from ..framework import metrics as _metrics
from ..framework import program_registry as _registry
from ..framework import trace_probe as _probe
from ..framework.monitor import stat_add
from ..profiler import memory as _memory
from .kv_pool import KVCachePool
from .paging import PagedKVPool, PoolCapacityError
from .scheduler import (GenerationRequest, Scheduler, _fetch)

__all__ = ["GenerationEngine", "PlanError"]

_engine_seq = 0
_engine_seq_lock = threading.Lock()


class PlanError(RuntimeError):
    """The static HBM plan says this replica will not fit (ISSUE 18).

    Raised at ``GenerationEngine(hbm_budget_bytes=...)`` construction —
    BEFORE any compile — when the donation-aware liveness estimate of
    the LARGEST decode-path bucket plus the pool+scales ledger bytes
    exceeds the budget. Carries the full plan dict as ``.plan``
    (``static_peak_bytes``, ``pool_bytes``, ``budget_bytes``,
    ``peak_point``)."""

    def __init__(self, message: str, plan: dict):
        super().__init__(message)
        self.plan = plan


def _device_memory_limit() -> Optional[int]:
    """Per-device HBM limit when the backend reports one, else None
    (CPU reports nothing — no fake numbers, no default gate there).
    Construction-time admission query, not scheduler-cycle polling —
    the memory-stats-hot-path rule's argued exception."""
    import jax
    try:
        stats = jax.devices()[0].memory_stats()  # lint: ok
    except Exception:                            # noqa: BLE001
        return None
    if not stats:
        return None
    limit = stats.get("bytes_limit") or stats.get("bytes_reservable_limit")
    return int(limit) if limit else None


def _next_engine_id() -> int:
    global _engine_seq
    with _engine_seq_lock:
        _engine_seq += 1
        return _engine_seq


# live engines for the statusz console (weak: a GC'd or closed engine
# drops out of the section on its own); the section is registered with
# the metrics registry once, at the first engine construction, so a
# process that never serves never shows an empty serving section twice
_LIVE_ENGINES: "weakref.WeakSet" = weakref.WeakSet()
_statusz_registered = False
_statusz_lock = threading.Lock()


def _engine_section() -> str:
    """statusz section: one line of load + cache + health per live
    engine, plus recent flight-recorder trouble (failed cycles, the
    last auto-dump path) — the serving half of the ops console."""
    engines = [e for e in list(_LIVE_ENGINES) if not e._closed]
    if not engines:
        return "(no live engines)"
    lines = []
    for e in sorted(engines, key=lambda e: e._eid):
        try:
            s = e.stats()
            head = (f"engine #{e._eid} [{s['kv_layout']}/"
                    f"{s['attention']}] queue={s['queue_depth']} "
                    f"active={s['active_requests']} "
                    f"slots={s['slots_in_use']}/{s['num_slots']}")
            if "kv_blocks_in_use" in s:
                head += (f" blocks={s['kv_blocks_in_use']}/"
                         f"{s['num_blocks']}")
            if "prefix_hit_ratio" in s:
                head += f" prefix_hit={s['prefix_hit_ratio']:.2f}"
            if "host_tier" in s:
                ht = s["host_tier"]
                head += (f" host_tier={ht['blocks']}/"
                         f"{ht['capacity_blocks']}")
            if "spec_accept_rate" in s:
                head += f" spec_accept={s['spec_accept_rate']:.2f}"
            if s.get("serving_mfu") is not None:
                head += f" mfu={s['serving_mfu']:.3f}"
            if s.get("decode_tokens_per_sec") is not None:
                head += f" tok/s={s['decode_tokens_per_sec']:.1f}"
            lines.append(head)
            ttft = s.get("ttft_ms")
            if ttft:
                lines.append(f"  ttft p50 {ttft['p50']:.1f} ms  "
                             f"p95 {ttft['p95']:.1f} ms  "
                             f"(n={ttft['count']})")
            if s.get("nonfinite_cycles"):
                lines.append(f"  !! nonfinite decode cycles: "
                             f"{s['nonfinite_cycles']}")
            rec = e.flight_recorder
            failed = [c for c in rec.snapshot()["cycles"]
                      if c.get("failed")]
            if failed:
                lines.append(f"  !! {len(failed)} failed cycles in the "
                             f"ring; last: {failed[-1].get('failed')}")
            if rec.last_dump_path:
                lines.append(f"  last auto-dump: {rec.last_dump_path}")
        except Exception as err:                         # noqa: BLE001
            lines.append(f"engine #{e._eid}: (stats error: {err!r})")
    return "\n".join(lines)


def _register_engine_telemetry(engine: "GenerationEngine") -> None:
    global _statusz_registered
    with _statusz_lock:
        if not _statusz_registered:
            _metrics.register_statusz_section("serving engines",
                                              _engine_section)
            _statusz_registered = True
    _LIVE_ENGINES.add(engine)
    # per-engine scrape-time collector: the stats() island re-published
    # as labeled registry metrics ({engine=<id>}), pulled only when a
    # snapshot/export/sampler asks — zero cost on the serving hot path
    ref = weakref.ref(engine)

    def _collect():
        e = ref()
        if e is None or e._closed:
            return ()
        s = e.stats()
        labels = {"engine": str(e._eid)}
        out = [("gauge", "serving_queue_depth", labels,
                s["queue_depth"]),
               ("gauge", "serving_slots_in_use", labels,
                s["slots_in_use"]),
               ("gauge", "serving_kv_bytes_in_use", labels,
                s["kv_bytes_in_use"]),
               ("counter", "serving_requests_retired", labels,
                s["requests_retired"]),
               ("counter", "serving_preempts", labels, s["preempts"]),
               ("counter", "serving_nonfinite_cycles", labels,
                s["nonfinite_cycles"])]
        if "kv_blocks_in_use" in s:
            out.append(("gauge", "serving_kv_blocks_in_use", labels,
                        s["kv_blocks_in_use"]))
            out.append(("gauge", "serving_prefix_hit_ratio", labels,
                        s["prefix_hit_ratio"]))
            # tiered hit split: one {engine, tier} counter series per
            # tier so dashboards can stack hbm/host/miss admissions
            for tier, n in (s.get("tier_hits") or {}).items():
                out.append(("counter", "serving_tier_hit",
                            dict(labels, tier=str(tier)), n))
        ht = s.get("host_tier")
        if ht is not None:
            out.append(("gauge", "serving_host_tier_bytes_in_use",
                        labels, ht["bytes_in_use"]))
            out.append(("counter", "serving_host_tier_demoted", labels,
                        ht["demoted_blocks"]))
            out.append(("counter", "serving_host_tier_promoted", labels,
                        ht["promoted_blocks"]))
        if s.get("decode_tokens_per_sec") is not None:
            out.append(("gauge", "serving_decode_tokens_per_sec",
                        labels, s["decode_tokens_per_sec"]))
        # per-tenant goodput labels (front-door multi-tenancy): one
        # {engine, tenant} series per tenant seen by this engine
        for tenant, ts in (s.get("tenants") or {}).items():
            tl = dict(labels, tenant=str(tenant))
            out.append(("counter", "serving_tenant_retired", tl,
                        ts["retired"]))
            out.append(("gauge", "serving_tenant_goodput_rps", tl,
                        ts["goodput_rps"]))
        return out
    _metrics.register_collector(f"serving_engine/{engine._eid}", _collect)


class GenerationEngine:
    """Continuous-batching autoregressive serving over a GPT-style model.

    ``model`` is a ``models.GPTForPretraining`` / ``GPTModel`` (anything
    exposing the ``gpt`` prefill/decode surface used by
    ``models.generate``); its parameters are snapshotted at construction
    (sharded parameters serve sharded — jit follows the placement).

    * ``num_slots`` — concurrent in-flight requests (the pool's batch);
    * ``max_len`` — per-slot cache capacity; a dense request needs
      ``bucket(prompt) + max_new_tokens <= max_len``, a paged one only
      ``prompt + max_new_tokens <= max_len`` (no left-pad tax);
    * ``top_k``/``top_p`` — the sampled path's truncation, STATIC per
      engine (part of the single decode trace); per-request
      ``do_sample``/``temperature`` are traced values;
    * ``max_queue``/``prefill_budget`` — backpressure and the
      anti-starvation admission policy (see :mod:`.scheduler`);
    * ``kv_layout``/``block_size``/``num_blocks`` — ``"paged"`` swaps
      the dense pool for the block-granular :class:`~.paging.PagedKVPool`
      (``num_blocks`` defaults to the dense-equivalent device budget;
      shrink it to realise the capacity win — admission then gates on
      blocks, pressure preempts, and full prompt blocks are shared
      through the prefix cache);
    * ``attention`` — ``"gather"`` (default) keeps the gather-based
      paged decode step (the correctness oracle); ``"fused"`` (paged
      only, ``block_size >= 8``) serves every cycle with ONE fused
      ragged-paged-attention Pallas launch
      (``ops/ragged_paged_attention.py``): no materialized KV gather,
      and CHUNKED PREFILL — prompts feed in ``prefill_budget``-token
      chunks mixed into decode launches, so a prompt burst can no
      longer monopolize a cycle, and the first generated token comes
      out of the same launch that fed the final chunk. One trace per
      (pow2 q-row bucket, pow2 table bucket).

    Greedy engine output is token-identical to ``models.generate`` run
    per request (the parity contract, tests/test_serving_engine.py and
    tests/test_serving_paging.py).
    """

    def __init__(self, model, num_slots: int = 8,
                 max_len: Optional[int] = None, *, top_k: int = 0,
                 top_p: float = 1.0, pad_token_id: int = 0,
                 max_queue: int = 128, prefill_budget: Optional[int] = None,
                 min_bucket: int = 8, seed: int = 0, dtype=None,
                 kv_layout: str = "dense", block_size: int = 16,
                 num_blocks: Optional[int] = None,
                 attention: str = "gather", kv_dtype=None,
                 spec_draft=None, spec_k: int = 4,
                 mesh=None, mp_axis: str = "mp",
                 hbm_budget_bytes: Optional[int] = None,
                 lane_weights: Optional[dict] = None,
                 host_tier_bytes: Optional[int] = None):
        import jax

        from ..models.generation import build_slot_decode_fn
        from ..nn.layer.layers import get_buffers_tree, get_params_tree

        if kv_layout not in ("dense", "paged"):
            raise ValueError(
                f"kv_layout must be 'dense' or 'paged', got {kv_layout!r}")
        if attention not in ("gather", "fused"):
            raise ValueError(
                f"attention must be 'gather' or 'fused', got {attention!r}")
        if kv_dtype is not None and kv_layout != "paged":
            raise ValueError(
                "kv_dtype (quantized KV blocks) requires "
                "kv_layout='paged': the per-block max-abs scales live "
                "beside the block pool (PagedKVPool.scales); the dense "
                "slot pool has no block granularity to scale")
        if attention == "fused":
            from ..ops.ragged_paged_attention import (MIN_KV_BLOCK,
                                                      min_kv_block_for)
            if kv_layout != "paged":
                raise ValueError(
                    "attention='fused' is the fused RAGGED PAGED "
                    "attention path — it requires kv_layout='paged' "
                    "(the dense slot pool has no page tables to walk)")
            need = min_kv_block_for(kv_dtype) if kv_dtype is not None \
                else MIN_KV_BLOCK
            if int(block_size) < need:
                raise ValueError(
                    f"attention='fused' requires block_size >= {need} "
                    f"for kv_dtype={kv_dtype or 'float'}: the kernel's "
                    f"(block_size, head_dim) KV scratch has no legal "
                    f"TPU tiling below the dtype's sublane count")
        if spec_draft is not None and attention != "fused":
            raise ValueError(
                "spec_draft (speculative decoding) requires "
                "attention='fused': the k-token verify IS one fused "
                "ragged launch — each slot's candidate tokens are extra "
                "ragged rows, exactly like a prefill chunk")
        if host_tier_bytes is not None:
            if kv_layout != "paged":
                raise ValueError(
                    "host_tier_bytes (hierarchical KV cache) requires "
                    "kv_layout='paged': the host tier stores demoted "
                    "prefix-cache BLOCKS; the dense slot pool has no "
                    "block granularity to demote")
            if mesh is not None:
                raise ValueError(
                    "host_tier_bytes does not compose with mesh= yet: "
                    "demotion/promotion copies would need per-shard "
                    "gathers against the head-partitioned pool — run "
                    "tiered engines single-device (or per EngineFleet "
                    "replica)")
        if mesh is not None:
            # tensor-parallel serving (ISSUE 15): the paged pool is a
            # head-partitioned GSPMD array and every step is a
            # shard_map over mp_axis — scale-UP, vs EngineFleet's
            # scale-OUT replicas
            if kv_layout != "paged":
                raise ValueError(
                    "mesh= (tensor-parallel serving) requires "
                    "kv_layout='paged': the mp shards partition the "
                    "block pool's head axis; the dense slot pool has "
                    "no sharded step builders")
            if kv_dtype is not None:
                raise ValueError(
                    "mesh= does not compose with kv_dtype= yet: the "
                    "quantized block scales would need their own "
                    "head-sharded layout — serve quantized pools "
                    "single-device (or per EngineFleet replica)")
            if spec_draft is not None:
                raise ValueError(
                    "mesh= does not compose with spec_draft= yet: the "
                    "draft tower and verify program have no sharded "
                    "builders — run speculative engines single-device")
        self._fused = attention == "fused"
        gpt = model.gpt if hasattr(model, "gpt") else model
        cfg = gpt.cfg
        max_len = int(max_len or cfg.max_position_embeddings)
        model.eval()                      # serving is inference-only
        self._model = model
        self._gpt = gpt
        self._pad = int(pad_token_id)
        self._top_k, self._top_p = int(top_k), float(top_p)
        self._mesh = mesh
        self._mp_axis = str(mp_axis)
        self._mp = 1
        if mesh is not None:
            from ..models.generation import (_mp_mesh_check,
                                             shard_params_megatron)
            self._mp = _mp_mesh_check(gpt, mesh, self._mp_axis)
            # lay the weights out Megatron-style BEFORE the snapshot:
            # the params tree then holds the sharded arrays and the
            # shard_map'd steps consume their local shards directly
            shard_params_megatron(model, mesh, mp_axis=self._mp_axis)
        self._params = get_params_tree(model)
        self._buffers = get_buffers_tree(model)
        if dtype is None:
            dtype = self._params[next(iter(self._params))].dtype
        self._paged = kv_layout == "paged"
        head_dim = cfg.hidden_size // cfg.num_attention_heads
        self._key = jax.random.PRNGKey(int(seed))
        self._eid = _next_engine_id()
        self._prefill_jits = {}           # bucket -> jitted prefill step
        if self._paged:
            # the dense layout fails this at construction inside
            # build_slot_decode_fn; every paged jit is deferred, so
            # without this check an oversized max_len would only
            # surface as SILENTLY WRONG tokens (XLA clamps the
            # out-of-range wpe gather at decode positions past mpe)
            if max_len > cfg.max_position_embeddings:
                raise ValueError(
                    f"max_len {max_len} exceeds max_position_embeddings="
                    f"{cfg.max_position_embeddings}")
            # prefill scatters WHOLE blocks, so capacity buckets must be
            # block multiples: round the floor up rather than reject it
            mb = -(-max(int(min_bucket), int(block_size))
                   // int(block_size)) * int(block_size)
            self._pool = PagedKVPool(
                cfg.num_hidden_layers, num_slots, cfg.num_attention_heads,
                max_len, head_dim, block_size=block_size,
                num_blocks=num_blocks, dtype=kv_dtype or dtype,
                min_bucket=mb, mesh=mesh, mp_axis=mp_axis)
            self._decode_jit = None       # per-table-bucket instead
            self._decode_jits = {}        # table bucket -> jitted step
            self._fused_jits = {}         # (q bucket, table bucket) -> step
            self._spec_jits = {}          # (q, table) -> spec verify step
            self._copy_jit = None         # lazy COW device block copy
        else:
            self._pool = KVCachePool(
                cfg.num_hidden_layers, num_slots, cfg.num_attention_heads,
                max_len, head_dim, dtype=dtype, min_bucket=min_bucket)
            self._decode_probe = _probe.site(f"serving/decode#{self._eid}")
            # program-registry AOT site (same jit semantics, donated
            # pool): THE decode step's compile ms + XLA cost analysis
            # land under this name — stats() derives flops-per-token
            # and serving MFU from its record
            self._decode_jit = _registry.aot_site(
                f"serving/decode#{self._eid}",
                build_slot_decode_fn(model, self._pool.num_slots, max_len,
                                     top_k=self._top_k, top_p=self._top_p,
                                     probe=self._decode_probe),
                donate_argnums=(2,))
        # hierarchical KV cache (ISSUE 20): a bounded host-DRAM block
        # store behind the device prefix cache — LRU-evicted
        # refcount-0 blocks demote instead of dying, and a hit on a
        # demoted prefix promotes it back via async H2D copies the
        # scheduler overlaps with decode. Host DRAM, so hbm_budget
        # planning never bills it.
        self._host_tier = None
        if host_tier_bytes is not None:
            from .host_tier import HostBlockPool
            self._host_tier = HostBlockPool(
                int(host_tier_bytes), self._pool.host_block_nbytes,
                scale_nbytes=self._pool.host_scale_nbytes,
                name=f"serving/host_tier#{self._eid}")
            self._pool.attach_host_tier(self._host_tier)
        self._closed = False
        self._close_lock = threading.Lock()
        # speculative decoding (fused engines only): a small draft
        # model proposes spec_k tokens per decode slot per cycle; the
        # target verifies all of them in ONE fused ragged launch
        self._spec = spec_draft is not None
        self._spec_k = int(spec_k)
        if self._spec:
            if self._spec_k < 1:
                raise ValueError(f"spec_k must be >= 1, got {spec_k}")
            self._init_draft(spec_draft, max_len)
        # fit-BEFORE-compile admission (ISSUE 18): statically plan the
        # LARGEST decode-path bucket + pool/scales ledger bytes against
        # the HBM budget (explicit, else the device limit when the
        # backend reports one — CPU reports none) and raise PlanError
        # naming the fattest program point before any compile. The plan
        # is a make_jaxpr trace of the RAW step builder — no AotSite,
        # no probe, no registry record, zero compiles.
        self._hbm_budget_bytes = int(hbm_budget_bytes) \
            if hbm_budget_bytes is not None else _device_memory_limit()
        self._plan = None
        if self._hbm_budget_bytes is not None:
            self._plan = self.plan_replica(self._hbm_budget_bytes)
        # per-engine compute accounting (scheduler-thread writes, host
        # ints): FLOPs of the decode programs actually DISPATCHED — a
        # paged engine runs different table-bucket programs with very
        # different costs, so stats() must average what ran, not bill
        # the largest bucket to every cycle
        self._decode_flops_dispatched = 0.0
        self._decode_dispatches = 0
        self._sched = Scheduler(
            self._pool, self._run_prefill, self._run_decode,
            max_queue=max_queue, prefill_budget=prefill_budget,
            do_copy=self._run_copy if self._paged else None,
            do_chunked_step=self._run_fused_step if self._fused else None,
            do_spec_step=self._run_spec_step if self._spec else None,
            spec_k=self._spec_k, lane_weights=lane_weights)
        # telemetry spine wiring (ISSUE 13): the engine joins the
        # statusz console and publishes its stats() island through the
        # labeled metrics registry ({engine=<id>} gauges/counters)
        _register_engine_telemetry(self)

    # -- client side -------------------------------------------------------
    def submit(self, prompt_ids, max_new_tokens: int = 32, *,
               do_sample: bool = False, temperature: float = 1.0,
               top_k: Optional[int] = None, top_p: Optional[float] = None,
               eos_token_id: Optional[int] = None,
               timeout: Optional[float] = None,
               tenant: str = "default",
               lane: str = "interactive") -> GenerationRequest:
        """Enqueue one generation; returns its handle immediately.

        The handle streams tokens as they are produced
        (``handle.stream()``), blocks for the padded full sequence
        (``handle.result()``), and cancels mid-flight
        (``handle.cancel()``). ``timeout`` (seconds) is a hard deadline:
        a request that has not FINISHED by then fails with
        ``DeadlineExceeded``. A full admission queue raises
        ``QueueFullError`` here, synchronously.

        ``do_sample``/``temperature`` are per-request (traced values of
        the shared decode program). ``top_k``/``top_p`` are NOT: they
        are static truncation structure baked into the engine's compile
        key at construction, so a differing per-request value here is
        rejected with :class:`ValueError` instead of silently retracing
        the decode step per sampling mix (the retrace-storm bug class
        the ``dispatch/retrace_cause`` counters exist to expose).

        ``tenant``/``lane`` tag the request's weighted-fair admission
        class (the HTTP front door sets them from the wire identity):
        the scheduler deficit-round-robins admission over the queued
        (lane, tenant) classes with per-lane weights
        (``GenerationEngine(lane_weights=...)``, default interactive 4
        : batch 1), so a batch flood cannot starve interactive TTFT.
        Untagged traffic all shares one class — plain FCFS."""
        if self._closed:
            raise RuntimeError("GenerationEngine is closed")
        if top_k is not None and int(top_k) != self._top_k:
            raise ValueError(
                f"per-request top_k={top_k} differs from the engine's "
                f"static top_k={self._top_k}: top_k is part of the decode "
                f"step's compile key — build a GenerationEngine("
                f"top_k={top_k}) instead of risking one retrace per "
                f"sampling mix")
        if top_p is not None and float(top_p) != self._top_p:
            raise ValueError(
                f"per-request top_p={top_p} differs from the engine's "
                f"static top_p={self._top_p}: top_p is part of the decode "
                f"step's compile key — build a GenerationEngine("
                f"top_p={top_p}) instead of risking one retrace per "
                f"sampling mix")
        ids = np.asarray(prompt_ids, np.int32).reshape(-1)
        if ids.size < 1:
            raise ValueError("prompt_ids must contain at least one token")
        if max_new_tokens < 1:
            raise ValueError(
                f"max_new_tokens must be >= 1, got {max_new_tokens}")
        if self._paged:
            # paged sequences are aligned at virtual 0 — no left-pad tax,
            # only the true footprint counts (this is the capacity win)
            if ids.size + int(max_new_tokens) > self._pool.max_len:
                raise PoolCapacityError(
                    f"prompt {ids.size} + max_new_tokens {max_new_tokens} "
                    f"exceeds the pool's virtual capacity "
                    f"{self._pool.max_len}; shorten the request or build "
                    f"the engine with a larger max_len")
            # bucket feasibility, incl. the WORST re-admission: a
            # preempted request re-prefills prompt + generated-so-far
            # (up to max_new - 1 tokens), and that feed's pow2 bucket
            # must exist — without this gate a bucket ladder that
            # overshoots max_len (non-pow2 max_len / large min_bucket)
            # admits a request whose prefill can never trace, and the
            # scheduler-thread crash poisons every in-flight request.
            # FUSED engines have no prefill buckets at all — any feed
            # up to max_len chunks through the ragged step, so the
            # ladder constraint simply does not exist there.
            worst = ids.size + int(max_new_tokens) - 1
            if not self._fused \
                    and self._pool.bucket_for(worst) > self._pool.max_len:
                raise PoolCapacityError(
                    f"no prefill bucket fits this request: prompt "
                    f"{ids.size} (+ up to {int(max_new_tokens) - 1} "
                    f"replayed tokens after a preemption) needs bucket "
                    f"{self._pool.bucket_for(worst)} > max_len "
                    f"{self._pool.max_len}; shorten the request or build "
                    f"the engine with a larger max_len / smaller "
                    f"min_bucket")
        else:
            bucket = self._pool.bucket_for(ids.size)
            if bucket + int(max_new_tokens) > self._pool.max_len:
                raise ValueError(
                    f"prompt bucket {bucket} + max_new_tokens "
                    f"{max_new_tokens} exceeds the pool capacity "
                    f"{self._pool.max_len}; shorten the request or build "
                    f"the engine with a larger max_len")
        req = GenerationRequest(
            ids, max_new_tokens, do_sample=do_sample,
            temperature=temperature, eos_token_id=eos_token_id,
            pad_token_id=self._pad, timeout=timeout,
            tenant=tenant, lane=lane)
        handle = self._sched.submit(req)   # QueueFullError propagates
        stat_add("serving/requests")       # counts ACCEPTED requests
        return handle

    def stream(self, prompt_ids, **kwargs) -> Iterator[int]:
        """``submit(...).stream()`` in one call: an iterator of token
        ids, yielded as each is produced."""
        return self.submit(prompt_ids, **kwargs).stream()

    def close(self, cancel_pending: bool = False) -> None:
        """Graceful shutdown: stop accepting work, DRAIN everything
        queued and in flight, then stop the scheduler thread. With
        ``cancel_pending`` the queue is cancelled instead of served
        (in-flight slots still finish)."""
        with self._close_lock:
            if self._closed:
                return
            self._closed = True
        self._sched.close(cancel_pending=cancel_pending)
        # host tier after the scheduler: no more tier_tick/promotions
        # can be dispatched, so close() only has queued work to drain
        # (the spiller finishes in-flight demotions, then both worker
        # threads join)
        if self._host_tier is not None:
            self._host_tier.close()
        # a closed engine's pool is no longer an accounted HBM owner
        self._pool.drop_ledger()
        # ...nor a scraped metrics source or statusz row
        _metrics.unregister_collector(f"serving_engine/{self._eid}")
        _LIVE_ENGINES.discard(self)

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.close()
        return False

    # -- introspection -----------------------------------------------------
    @property
    def flight_recorder(self):
        """This engine's always-on :class:`~.flight_recorder.
        FlightRecorder` — the per-engine latency reservoirs and cycle
        ring the fleet aggregator pools."""
        return self._sched.recorder

    @property
    def num_slots(self) -> int:
        return self._pool.num_slots

    @property
    def queue_depth(self) -> int:
        return self._sched.queue_depth

    @property
    def active_requests(self) -> int:
        return self._sched.active

    def stats(self) -> dict:
        """One coherent operator snapshot — queue depth, in-flight
        requests, slot/block utilization and the prefix-cache hit ratio
        — so nobody has to scrape process-global monitor counters by
        ``serving/`` prefix (those aggregate across every engine ever
        constructed; this reads THIS engine's pool and scheduler).
        Host bookkeeping only: never blocks on the device."""
        pool = self._pool
        s = {
            "kv_layout": "paged" if self._paged else "dense",
            "attention": "fused" if self._fused else "gather",
            "queue_depth": self._sched.queue_depth,
            "active_requests": self._sched.active,
            "num_slots": pool.num_slots,
            "slots_in_use": pool.n_active,
            "slot_utilization": pool.n_active / pool.num_slots,
            "preempts": self._sched.preempts,
            "requests_retired": self._sched.recorder.retired,
            # serving numerics sentinel (scheduler._note_nonfinite):
            # decode cycles whose logits carried a NaN/Inf — the flag
            # rides the existing per-cycle token fetch, zero extra syncs
            "nonfinite_cycles": self._sched.nonfinite_cycles,
        }
        # per-ENGINE latency percentiles, derived from this engine's own
        # retired request traces — the process-global serving/ttft_ms
        # histogram aggregates every engine ever constructed in the
        # process, so two engines (or back-to-back tests) would
        # contaminate each other's figures there
        s.update(self._sched.recorder.latency_summary())
        # SLO plane: once a tracker (or caller) armed a tail SLO on the
        # recorder, the per-replica goodput rate is part of the
        # operator snapshot — the fleet sums it, the autoscaler reads it
        rec = self._sched.recorder
        if rec.tail_slo_ms is not None:
            g = rec.goodput()
            s["goodput_rps"] = g["goodput_rps"]
            s["slo_violations"] = rec.slo_violations
        # per-tenant goodput split (front-door multi-tenancy): which
        # tenant's traffic is meeting the SLO, labeled per tenant in
        # the scraped serving_tenant_* series via the collector below
        tenants = rec.tenant_summary()
        if tenants:
            s["tenants"] = tenants
        s.update(self._compute_stats())
        # KV memory, from the HBM ledger (profiler/memory.py — the pool
        # publishes capacity + in-use bytes there on every alloc/free)
        led = _memory.ledger()
        s["kv_pool_capacity_bytes"] = led.get(
            f"{pool.ledger_key}/capacity", pool.capacity_bytes)
        s["kv_bytes_in_use"] = led.get(
            f"{pool.ledger_key}/in_use", pool.bytes_in_use)
        if self._paged:
            hits, misses = pool.prefix_hits, pool.prefix_misses
            # tiered hit split (MIGRATION.md "prefix-hit split"): the
            # aggregate prefix_hit_ratio stays for dashboards; the
            # split keys say WHICH tier served each admission — hbm
            # (device trie), host (served through a promotion), miss.
            # Present tier or no tier (host is just 0 untiered).
            th = pool.tier_hits
            denom = max(1, th["hbm"] + th["host"] + th["miss"])
            s.update({
                "block_size": pool.block_size,
                "num_blocks": pool.num_blocks,
                "kv_blocks_in_use": pool.blocks_in_use,
                "block_utilization": pool.blocks_in_use / pool.num_blocks,
                "cached_blocks": pool.cached_blocks,
                "prefix_hits": hits,
                "prefix_misses": misses,
                "prefix_hit_ratio": hits / max(1, hits + misses),
                "tier_hits": dict(th),
                "prefix_hit_hbm": th["hbm"] / denom,
                "prefix_hit_host": th["host"] / denom,
                "prefix_miss": th["miss"] / denom,
                "prefill_tokens_saved": pool.tokens_saved,
                "prefix_evictions": pool.evictions,
                # tiered KV bytes: block storage vs the scale side-array
                # (zero for float pools) — int8 blocks are the whole
                # point of the ~2x-requests-per-budget win, so the
                # operator view must show where the bytes went
                "kv_dtype": pool.dtype.name,
                # block_storage_bytes is PER DEVICE (a sharded pool
                # divides its head axis over mp shards); on a
                # single-device pool shards == 1 and this is the total
                "kv_bytes": {
                    "blocks": pool.block_storage_bytes,
                    "scales": pool.scales_bytes,
                },
            })
            if self._host_tier is not None:
                # hierarchical tier snapshot: host capacity/occupancy,
                # demotion/promotion volumes, and the end-to-end
                # promotion latency (ticket creation -> adoption) —
                # the "did the second tier pay for itself" numbers
                s["host_tier"] = self._host_tier.stats()
            if self._mp > 1:
                s["mp"] = self._mp
                s["mp_axis"] = self._mp_axis
                s["kv_bytes_per_device"] = pool.block_storage_bytes
        if self._fused:
            # chunked-prefill observability: lifetime chunk counters
            # plus ring-window chunk token throughput, so the "long
            # prompts no longer monopolize a cycle" win is measurable
            # (ONE ring pass serves the spec figures below too)
            s["prefill_chunks"] = self._sched.prefill_chunks
            s["chunked_prefill_tokens"] = self._sched.chunk_tokens
            thr = self._sched.recorder.cycle_throughput()
            if thr["cycle_secs"] > 0 and thr["chunk_tokens"] > 0:
                s["chunked_prefill_tokens_per_sec"] = \
                    thr["chunk_tokens"] / thr["cycle_secs"]
        if self._spec:
            # the two numbers that prove (or disprove) the multiplier:
            # how often the draft agrees, and how many tokens a decode
            # slot actually nets per cycle (1.0 = plain decode)
            s["spec_k"] = self._spec_k
            s["spec_cycles"] = self._sched.spec_cycles
            s["spec_proposed"] = self._sched.spec_proposed
            s["spec_accepted"] = self._sched.spec_accepted
            s["spec_accept_rate"] = self._sched.spec_accepted \
                / max(1, self._sched.spec_proposed)
            if thr["spec_slots"] > 0:
                s["spec_tokens_per_cycle"] = \
                    thr["spec_emitted"] / thr["spec_slots"]
            s["draft_layers"] = \
                self._draft_gpt.cfg.num_hidden_layers
            if self._paged:
                s["kv_bytes"]["draft"] = \
                    int(np.prod(self._draft_shape)) \
                    * np.dtype(self._draft_dtype).itemsize
        return s

    def _compute_stats(self) -> dict:
        """Model-FLOPs-per-token and serving MFU, from the decode
        step's program-registry cost analysis (``serving/decode*`` AOT
        sites). One decode step advances EVERY slot one token, so
        flops-per-token = step FLOPs / num_slots (the full-batch cost —
        a partially occupied batch still pays it, which is exactly what
        an operator sizing capacity wants to see). Throughput comes
        from THIS engine's flight-recorder cycle ring; MFU needs a
        known device peak (``program_registry.peak_flops``, env-
        overridable) — absent one (CPU), raw FLOP/s are reported."""
        if not self._decode_dispatches:
            return {}
        mean_step_flops = \
            self._decode_flops_dispatched / self._decode_dispatches
        if not mean_step_flops:
            return {}
        out = {}
        S = self._pool.num_slots
        out["model_flops_per_token"] = mean_step_flops / S
        rec = None
        if self._fused:
            if self._fused_jits:
                rec = self._fused_jits[max(self._fused_jits)].record
        elif self._paged:
            if self._decode_jits:
                rec = self._decode_jits[max(self._decode_jits)].record
        elif self._decode_jit is not None:
            rec = getattr(self._decode_jit, "record", None)
        if rec is not None and rec.bytes_accessed and rec.flops:
            # scale the largest bucket's bytes by the mean-cost ratio so
            # bytes-per-token tracks what actually ran, like the FLOPs
            out["decode_bytes_per_token"] = \
                rec.bytes_accessed * (mean_step_flops / rec.flops) / S
        thr = self._sched.recorder.cycle_throughput()
        if thr["cycle_secs"] > 0 and thr["decode_cycles"] > 0:
            out["decode_tokens_per_sec"] = \
                thr["emitted"] / thr["cycle_secs"]
            # FLOPs summed per cycle IN the ring (same window as the
            # wall-time denominator; sweep-only/drain cycles contribute
            # wall but zero FLOPs); the lifetime mean is only the
            # fallback for rings recorded before the engine attached
            flops_in_ring = thr["decode_flops"] or \
                mean_step_flops * thr["decode_cycles"]
            achieved = flops_in_ring / thr["cycle_secs"]
            out["serving_flops_per_sec"] = achieved
            peak = _registry.peak_flops()
            if peak:
                out["serving_mfu"] = achieved / peak
        return out

    def dump_flight_recorder(self, path: Optional[str] = None) -> dict:
        """Postmortem snapshot of the scheduler's always-on flight
        recorder — the last N cycle records (sweep/admit/prefill/
        decode-dispatch/host-fetch breakdown, occupancy, queue depth)
        and the tail of every request's lifecycle events — plus this
        engine's :meth:`stats` snapshot. Written to ``path`` as JSON
        when given; also dumped AUTOMATICALLY (to a temp file, path in
        ``engine._sched.recorder.last_dump_path``) when a step failure
        poisons the in-flight requests, so a production stall is
        debuggable without the profiler ever having been armed."""
        return self._sched.recorder.dump(path, extra={"engine":
                                                      self.stats()})

    def analyze(self, passes=None):
        """PR-3 pre-flight of THE decode step: trace the jitted program
        (donation contract auto-read from the pjit eqn) and run the
        analysis pipeline. The clean-bill contract is zero
        error-severity findings — donation-safe, no host sync in the
        hot loop; asserted by ``bench.py --dry-run`` and the tier-1
        tests. Tracing hits jit's signature cache, so this never
        retraces (the probe counters stay honest). A paged engine
        analyzes its LARGEST built table bucket (the step that actually
        served), falling back to the one-block bucket on a fresh
        engine."""
        from .. import analysis

        S = self._pool.num_slots
        if self._spec and self._spec_jits:
            # the speculative verify program (largest built bucket):
            # zeroed metadata is a legal no-op launch, and n_spec = 0
            # everywhere keeps the rejection sampler on its base path
            from ..ops.ragged_paged_attention import BLOCK_Q
            Q, T = max(self._spec_jits)
            K = self._spec_k
            V = self._gpt.cfg.vocab_size
            scales = (self._pool.scales,) if self._pool.quantized else ()
            return analysis.analyze(
                self._spec_step_fn(Q, T), self._params, self._buffers,
                self._pool.data, *scales, np.zeros(Q, np.int32),
                np.zeros(Q, np.int32), np.zeros(Q, np.int32),
                np.zeros(Q, np.int32), np.zeros(Q // BLOCK_Q, np.int32),
                np.zeros(S, np.int32), np.zeros(S, np.int32),
                np.zeros((S, T), np.int32), np.zeros(S, np.int32),
                np.zeros(S, np.int32), np.zeros(S, np.int32),
                np.zeros(S, np.int32), np.zeros((S, K), np.int32),
                np.zeros((S, K, V), np.float32), np.zeros(S, bool),
                np.ones(S, np.float32), self._key, passes=passes,
                name=f"serving.spec_verify[{S} slots, k{K}, q{Q}, t{T}]")
        if self._fused:
            # largest built fused bucket (the step that actually
            # served), falling back to the smallest on a fresh engine.
            # Zeroed metadata is a legal no-op launch: blk_seq 0 maps
            # every q block to slot 0 with kv_len 0, so the KV walk
            # runs zero iterations.
            from ..ops.ragged_paged_attention import BLOCK_Q
            Q, T = max(self._fused_jits) if self._fused_jits \
                else (BLOCK_Q, 1)
            scales = (self._pool.scales,) if self._pool.quantized else ()
            return analysis.analyze(
                self._fused_step_fn(Q, T), self._params, self._buffers,
                self._pool.data, *scales, np.zeros(Q, np.int32),
                np.zeros(Q, np.int32), np.zeros(Q, np.int32),
                np.zeros(Q, np.int32), np.zeros(Q // BLOCK_Q, np.int32),
                np.zeros(S, np.int32), np.zeros(S, np.int32),
                np.zeros((S, T), np.int32), np.zeros(S, np.int32),
                np.zeros(S, np.int32), np.zeros(S, np.int32),
                np.zeros(S, bool), np.ones(S, np.float32), self._key,
                passes=passes,
                name=f"serving.fused_step[{S} slots, q{Q}, t{T}]")
        if self._paged:
            T = max(self._decode_jits) if self._decode_jits else 1
            scales = (self._pool.scales,) if self._pool.quantized else ()
            return analysis.analyze(
                self._paged_decode_fn(T), self._params, self._buffers,
                self._pool.data, *scales, np.zeros(S, np.int32),
                np.zeros(S, np.int32), np.zeros(S, np.int32),
                np.zeros((S, T), np.int32), np.zeros(S, bool),
                np.ones(S, np.float32), self._key, passes=passes,
                name=f"serving.paged_decode[{S} slots, {T}-block tables]")
        return analysis.analyze(
            self._decode_jit, self._params, self._buffers, self._pool.data,
            np.zeros(S, np.int32), np.zeros(S, np.int32),
            np.zeros(S, np.int32), np.zeros(S, bool),
            np.ones(S, np.float32), self._key,
            passes=passes, name=f"serving.decode[{S} slots]")

    def plan_replica(self, hbm_budget_bytes: Optional[int] = None,
                     top_k: int = 4) -> dict:
        """Static fit-before-compile HBM plan of this replica's worst
        case (ISSUE 18): donation-aware liveness
        (``analysis/liveness.py``) over the LARGEST decode-path bucket
        this engine can dispatch — the spec-verify / fused step at the
        full-slot q bucket and max table bucket, the gather decode at
        the max table bucket, or THE dense decode step — with the
        pool+scales ledger bytes attributed PER DEVICE (a head-sharded
        pool's global-shape operand is swapped for its per-device
        ``capacity_bytes``). Trace-only: the RAW step builder goes
        through ``jax.make_jaxpr`` with no AotSite, no probe and no
        registry record, so ``compile/count`` does not move — proven by
        the bench.py dry-run canary. Raises :class:`PlanError` naming
        the fattest program point when ``hbm_budget_bytes`` (or the
        construction-time budget) is exceeded; the same call is the
        elastic scale-out path's dry admission check."""
        from ..analysis import liveness

        budget = int(hbm_budget_bytes) if hbm_budget_bytes is not None \
            else self._hbm_budget_bytes
        S = self._pool.num_slots
        params, buffers = self._params, self._buffers
        pool = self._pool
        scales = ()
        if self._paged and pool.quantized:
            scales = (pool.scales,)

        if self._fused:
            from ..ops.ragged_paged_attention import BLOCK_Q
            T = pool.max_table_len
            if self._spec:
                from ..models.generation import build_spec_verify_fn
                K = self._spec_k
                # each speculating slot contributes k+1 ragged rows,
                # padded to whole q blocks
                blocks_per_slot = -(-(K + 1) // BLOCK_Q)
                Q = self._q_bucket(S * blocks_per_slot * BLOCK_Q)
                V = self._gpt.cfg.vocab_size
                fn = build_spec_verify_fn(
                    self._model, S, Q, K, T, pool.block_size,
                    top_k=self._top_k, top_p=self._top_p,
                    quantized=pool.quantized, qmax=pool.qmax or 127.0)
                args = (params, buffers, pool.data, *scales,
                        np.zeros(Q, np.int32), np.zeros(Q, np.int32),
                        np.zeros(Q, np.int32), np.zeros(Q, np.int32),
                        np.zeros(Q // BLOCK_Q, np.int32),
                        np.zeros(S, np.int32), np.zeros(S, np.int32),
                        np.zeros((S, T), np.int32), np.zeros(S, np.int32),
                        np.zeros(S, np.int32), np.zeros(S, np.int32),
                        np.zeros(S, np.int32), np.zeros((S, K), np.int32),
                        np.zeros((S, K, V), np.float32),
                        np.zeros(S, bool), np.ones(S, np.float32),
                        self._key)
                flavor, site = "spec", f"spec_verify[q{Q},t{T}]"
            else:
                Q = self._q_bucket(S * BLOCK_Q)
                if self._mesh is not None:
                    from ..models.generation import \
                        build_sharded_fused_step_fn
                    fn = build_sharded_fused_step_fn(
                        self._model, S, Q, T, pool.block_size,
                        self._mesh, mp_axis=self._mp_axis,
                        top_k=self._top_k, top_p=self._top_p)
                else:
                    from ..models.generation import build_fused_step_fn
                    fn = build_fused_step_fn(
                        self._model, S, Q, T, pool.block_size,
                        top_k=self._top_k, top_p=self._top_p,
                        quantized=pool.quantized, qmax=pool.qmax or 127.0)
                args = (params, buffers, pool.data, *scales,
                        np.zeros(Q, np.int32), np.zeros(Q, np.int32),
                        np.zeros(Q, np.int32), np.zeros(Q, np.int32),
                        np.zeros(Q // BLOCK_Q, np.int32),
                        np.zeros(S, np.int32), np.zeros(S, np.int32),
                        np.zeros((S, T), np.int32), np.zeros(S, np.int32),
                        np.zeros(S, np.int32), np.zeros(S, np.int32),
                        np.zeros(S, bool), np.ones(S, np.float32),
                        self._key)
                flavor, site = "fused", f"fused_step[q{Q},t{T}]"
            donate = (2, 3) if pool.quantized else (2,)
        elif self._paged:
            T = pool.max_table_len
            Q = None
            if self._mesh is not None:
                from ..models.generation import \
                    build_sharded_paged_decode_fn
                fn = build_sharded_paged_decode_fn(
                    self._model, S, T, pool.block_size, self._mesh,
                    mp_axis=self._mp_axis, top_k=self._top_k,
                    top_p=self._top_p)
            else:
                from ..models.generation import build_paged_decode_fn
                fn = build_paged_decode_fn(
                    self._model, S, T, pool.block_size,
                    top_k=self._top_k, top_p=self._top_p,
                    quantized=pool.quantized, qmax=pool.qmax or 127.0)
            args = (params, buffers, pool.data, *scales,
                    np.zeros(S, np.int32), np.zeros(S, np.int32),
                    np.zeros(S, np.int32), np.zeros((S, T), np.int32),
                    np.zeros(S, bool), np.ones(S, np.float32), self._key)
            donate = (2, 3) if pool.quantized else (2,)
            flavor, site = "paged", f"paged_decode[t{T}]"
        else:
            T = Q = None
            fn = self._decode_jit       # tracer-transparent AotSite
            args = (params, buffers, pool.data,
                    np.zeros(S, np.int32), np.zeros(S, np.int32),
                    np.zeros(S, np.int32), np.zeros(S, bool),
                    np.ones(S, np.float32), self._key)
            donate = (2,)
            flavor, site = "dense", "decode"

        rep = liveness.callable_liveness(fn, *args, donate_argnums=donate,
                                         top_k=top_k)

        # per-device pool attribution: the step's operand carries the
        # pool at its GLOBAL shape; a head-sharded engine holds only
        # capacity_bytes of it per device (paging.py's ledger figure)
        def _nbytes(a):
            return int(np.prod(a.shape)) * np.dtype(a.dtype).itemsize

        operand_pool = _nbytes(pool.data) + sum(_nbytes(s) for s in scales)
        per_device_pool = pool.capacity_bytes if self._paged \
            else operand_pool
        total = rep.static_peak_bytes - operand_pool + per_device_pool

        pk = rep.peak
        plan = {
            "site": f"serving.{site}[{S} slots]#{self._eid}",
            "flavor": flavor, "q_bucket": Q, "table_bucket": T,
            "step_peak_bytes": int(rep.static_peak_bytes),
            "pool_bytes": int(per_device_pool),
            "static_peak_bytes": int(total),
            "budget_bytes": budget,
            "fits": None if budget is None else bool(total <= budget),
            "headroom_bytes": None if budget is None
            else int(budget - total),
            "peak_point": pk.as_dict() if pk else None,
            "timeline": [p.as_dict() for p in rep.timeline],
        }
        if self._host_tier is not None:
            # informational only: host DRAM, deliberately NOT added to
            # static_peak_bytes — the HBM fit check must never bill
            # the spill tier against the device budget
            plan["host_tier_bytes"] = self._host_tier.capacity_bytes
        if plan["fits"] is False:
            raise PlanError(
                f"replica does not fit: static peak {total:,} B "
                f"(largest {flavor} bucket"
                f"{f' q{Q}' if Q else ''}{f' t{T}' if T else ''} + "
                f"pool ledger {per_device_pool:,} B) exceeds "
                f"hbm_budget_bytes={budget:,} — fattest program point: "
                f"{pk.primitive if pk else 'n/a'} with "
                f"{pk.live_bytes:,} B live at "
                f"{(pk.source if pk else None) or 'unknown source'}",
                plan)
        return plan

    # -- device side (called from the scheduler thread only) ---------------
    def _prefill_fn(self, bucket: int):
        fn = self._prefill_jits.get(bucket)
        if fn is None:
            from ..models.generation import (
                build_paged_prefill_fn, build_sharded_paged_prefill_fn,
                build_slot_prefill_fn)
            probe = _probe.site(f"serving/prefill[{bucket}]#{self._eid}")
            donate = (2,)
            if self._mesh is not None:
                built = build_sharded_paged_prefill_fn(
                    self._model, bucket, self._pool.block_size,
                    self._mesh, mp_axis=self._mp_axis,
                    top_k=self._top_k, top_p=self._top_p, probe=probe)
            elif self._paged:
                built = build_paged_prefill_fn(
                    self._model, bucket, self._pool.block_size,
                    top_k=self._top_k, top_p=self._top_p, probe=probe,
                    quantized=self._pool.quantized,
                    qmax=self._pool.qmax or 127.0)
                if self._pool.quantized:
                    donate = (2, 3)       # pool AND its scale array
            else:
                built = build_slot_prefill_fn(
                    self._model, bucket, self._pool.max_len,
                    top_k=self._top_k, top_p=self._top_p, probe=probe)
            fn = _registry.aot_site(
                f"serving/prefill[{bucket}]#{self._eid}", built,
                donate_argnums=donate)
            self._prefill_jits[bucket] = fn
        return fn

    def _paged_decode_fn(self, table_len: int):
        fn = self._decode_jits.get(table_len)
        if fn is None:
            from ..models.generation import (build_paged_decode_fn,
                                             build_sharded_paged_decode_fn)
            probe = _probe.site(f"serving/decode[t{table_len}]#{self._eid}")
            if self._mesh is not None:
                built = build_sharded_paged_decode_fn(
                    self._model, self._pool.num_slots, table_len,
                    self._pool.block_size, self._mesh,
                    mp_axis=self._mp_axis, top_k=self._top_k,
                    top_p=self._top_p, probe=probe)
            else:
                built = build_paged_decode_fn(
                    self._model, self._pool.num_slots, table_len,
                    self._pool.block_size, top_k=self._top_k,
                    top_p=self._top_p, probe=probe,
                    quantized=self._pool.quantized,
                    qmax=self._pool.qmax or 127.0)
            fn = _registry.aot_site(
                f"serving/decode[t{table_len}]#{self._eid}", built,
                donate_argnums=(2, 3) if self._pool.quantized else (2,))
            self._decode_jits[table_len] = fn
        return fn

    def _run_prefill(self, req: GenerationRequest, slot: int,
                     bucket: int) -> Optional[int]:
        if self._fused:
            return self._run_fused_admit(req, slot)
        if self._paged:
            return self._run_paged_prefill(req, slot, bucket)
        ids = np.full((1, bucket), self._pad, np.int32)
        ids[0, bucket - req.prompt.size:] = req.prompt
        key_valid = np.zeros((1, bucket), bool)
        key_valid[0, bucket - req.prompt.size:] = True
        self._pool.data, first, self._key = self._prefill_fn(bucket)(
            self._params, self._buffers, self._pool.data, ids, key_valid,
            np.int32(slot), np.bool_(req.do_sample),
            np.float32(req.temperature), self._key)
        return int(_fetch(first)[0])

    def _run_paged_prefill(self, req: GenerationRequest, slot: int,
                           bucket: int) -> Optional[int]:
        """Admit one request into the paged pool. On a prefix-cache hit
        the matched blocks are adopted and prefill is SKIPPED entirely —
        the uncovered tail (plus, after a preemption, the request's own
        generated history) replays through the shared decode step, one
        token per cycle, predictions discarded until the replay drains.
        Replay costs one decode cycle PER TOKEN, so the hit is only
        taken when the tail fits one ``min_bucket`` (a smallest
        prefill's worth); a longer tail prefills the whole feed fresh
        instead — one prefill call beats a tail-long replay, and the
        shared blocks are still deduplicated in the cache. On a miss
        the whole feed prefills into freshly allocated blocks and its
        full token blocks are published to the prefix cache."""
        pool = self._pool
        # a re-admitted (preempted) request replays prompt + everything
        # it already generated; a fresh request's feed IS its prompt
        feed = np.concatenate(
            [req.prompt, np.asarray(req.tokens, np.int32)])
        cached = pool.match_prefix(feed)
        if cached and feed.size - len(cached) * pool.block_size \
                > pool.min_bucket:
            cached = []                   # tail too long: prefill wins
        if cached:
            pool.admit_cached(slot, cached)
            # tier split: a hit served through a just-landed promotion
            # is a HOST-tier hit; a plain trie hit never left HBM
            pool.note_tier_hit(
                "host" if req._tier_promoted else "hbm")
            m = len(cached) * pool.block_size
            pool.set_slot(slot, pos=m, lo=0)
            req.last_token = int(feed[m])
            req.replay = [int(t) for t in feed[m + 1:]]
            req.trace.mark("prefix_hit", tokens_saved=m,
                           replay=len(req.replay))
            return None
        pool.note_tier_hit("miss")
        blocks = pool.admit_fresh(slot, feed.size)
        table = np.zeros(bucket // pool.block_size, np.int32)
        table[:len(blocks)] = blocks      # padding -> the scratch block
        ids = np.zeros((1, bucket), np.int32)
        ids[0, :feed.size] = feed         # RIGHT-padded: virtual index 0
        key_valid = np.zeros((1, bucket), bool)
        key_valid[0, :feed.size] = True
        args = (ids, key_valid, table, np.int32(feed.size),
                np.bool_(req.do_sample), np.float32(req.temperature),
                self._key)
        if pool.quantized:
            pool.data, pool.scales, first, self._key = \
                self._prefill_fn(bucket)(self._params, self._buffers,
                                         pool.data, pool.scales, *args)
        else:
            pool.data, first, self._key = self._prefill_fn(bucket)(
                self._params, self._buffers, pool.data, *args)
        pool.set_slot(slot, pos=feed.size, lo=0)
        pool.register_prefix(slot, feed)
        req.replay = []
        return int(_fetch(first)[0])

    def _run_fused_admit(self, req: GenerationRequest,
                         slot: int) -> None:
        """Admit one request into the FUSED engine: pure host
        bookkeeping, no prefill program. Blocks covering the whole feed
        are reserved, a prefix-cache match adopts its blocks (ANY tail
        length — chunks drain a long tail in budgeted launches, so the
        gather path's one-``min_bucket`` decline heuristic is obsolete
        here), and the remaining tokens arm ``req.pending_feed`` for
        the per-cycle chunk plan."""
        pool = self._pool
        if self._spec:
            # this slot's previous occupant's draft cache is stale: the
            # next speculative cycle re-syncs via a draft prefill
            self._draft_synced[slot] = False
        feed = np.concatenate(
            [req.prompt, np.asarray(req.tokens, np.int32)])
        cached = pool.match_prefix(feed)
        if cached:
            pool.admit_cached(slot, cached)
            pool.note_tier_hit(
                "host" if req._tier_promoted else "hbm")
            m = len(cached) * pool.block_size
            pool.set_slot(slot, pos=m, lo=0)
            req.pending_feed = [int(t) for t in feed[m:]]
            req.trace.mark("prefix_hit", tokens_saved=m,
                           pending=len(req.pending_feed))
        else:
            pool.note_tier_hit("miss")
            pool.admit_fresh(slot, feed.size)
            # position 0 is where the first pending token's K/V land
            pool.set_slot(slot, pos=0, lo=0)
            req.pending_feed = [int(t) for t in feed]
        req.replay = []
        return None

    def _ragged_operands(self, slot_requests, plan, spec=None):
        """Host-side flattened ragged-row operands shared by the fused
        step and the speculative verify launch: per-slot contiguous
        padded rows, page-table-resolved write targets, and the
        scalar-prefetch metadata. Speculating slots (``spec``)
        contribute their candidate rows with only ``last_token``
        host-known — the draft tokens overlay on the device inside the
        verify program."""
        from ..ops.ragged_paged_attention import BLOCK_Q, ragged_layout

        pool = self._pool
        S = pool.num_slots
        bs = pool.block_size
        q_lens = [0] * S
        pos0s = [0] * S
        row_tokens = {}
        kv_len = np.zeros(S, np.int32)
        sample_mask = np.zeros(S, bool)
        temps = np.ones(S, np.float32)
        n_spec = np.zeros(S, np.int32)
        for slot, req in slot_requests.items():
            n = int(plan.get(slot, 0))
            if n < 1:
                continue
            p = pool.slot_pos(slot)
            q_lens[slot] = n
            pos0s[slot] = p
            kv_len[slot] = p + n
            sample_mask[slot] = req.do_sample
            temps[slot] = req.temperature
            if spec and slot in spec:
                n_spec[slot] = n
                row_tokens[slot] = [req.last_token]
            else:
                row_tokens[slot] = (req.pending_feed[:n]
                                    if req.pending_feed
                                    else [req.last_token])
        padded = sum(-(-n // BLOCK_Q) * BLOCK_Q for n in q_lens if n)
        Q = self._q_bucket(padded)
        blk_seq, qstart, pos0, last_row, _ = ragged_layout(
            q_lens, pos0s, q_bucket=Q)
        token_ids = np.zeros(Q, np.int32)
        qpos = np.zeros(Q, np.int32)
        write_block = np.zeros(Q, np.int32)   # pad rows -> scratch block
        write_off = np.zeros(Q, np.int32)
        for slot, toks in row_tokens.items():
            r0, p0 = int(qstart[slot]), int(pos0[slot])
            table = pool.slot_table(slot)
            for i in range(q_lens[slot]):
                if i < len(toks):
                    token_ids[r0 + i] = toks[i]
                qpos[r0 + i] = p0 + i
                write_block[r0 + i] = table[(p0 + i) // bs]
                write_off[r0 + i] = (p0 + i) % bs
        T = max(pool.table_bucket(s) for s in row_tokens)
        tables = pool.table_array(T, row_tokens)
        lo = np.zeros(S, np.int32)            # paged virtual floor
        return (Q, T, (token_ids, qpos, write_block, write_off, blk_seq,
                       qstart, pos0, tables, lo, kv_len, last_row),
                n_spec, sample_mask, temps)

    def _run_fused_step(self, slot_requests, plan):
        """Dispatch ONE fused ragged launch (the chunked-mode
        do_chunked_step): budgeted prompt chunks + decode rows,
        flattened into the padded row layout of
        ``ops.ragged_paged_attention`` and served by the
        ``build_fused_step_fn`` program for this (q bucket, table
        bucket). Returns the next-token DEVICE array un-fetched."""
        pool = self._pool
        Q, T, ops, _, sample_mask, temps = self._ragged_operands(
            slot_requests, plan)
        step = self._fused_step_fn(Q, T)
        args = ops + (sample_mask, temps, self._key)
        if pool.quantized:
            pool.data, pool.scales, nxt, self._key = step(
                self._params, self._buffers, pool.data, pool.scales,
                *args)
        else:
            pool.data, nxt, self._key = step(
                self._params, self._buffers, pool.data, *args)
        self._note_decode_dispatch(step)
        return nxt

    def _q_bucket(self, rows: int) -> int:
        """pow2 bucket over the launch's padded q rows — one fused
        trace per (q bucket, table bucket), the ragged twin of the
        prefill-bucket discipline."""
        from ..ops.ragged_paged_attention import BLOCK_Q
        b = BLOCK_Q
        while b < rows:
            b *= 2
        return b

    def _fused_step_fn(self, q_rows: int, table_len: int):
        key = (q_rows, table_len)
        fn = self._fused_jits.get(key)
        if fn is None:
            from ..models.generation import (build_fused_step_fn,
                                             build_sharded_fused_step_fn)
            probe = _probe.site(
                f"serving/fused[q{q_rows},t{table_len}]#{self._eid}")
            if self._mesh is not None:
                built = build_sharded_fused_step_fn(
                    self._model, self._pool.num_slots, q_rows,
                    table_len, self._pool.block_size, self._mesh,
                    mp_axis=self._mp_axis, top_k=self._top_k,
                    top_p=self._top_p, probe=probe)
            else:
                built = build_fused_step_fn(
                    self._model, self._pool.num_slots, q_rows,
                    table_len, self._pool.block_size,
                    top_k=self._top_k, top_p=self._top_p, probe=probe,
                    quantized=self._pool.quantized,
                    qmax=self._pool.qmax or 127.0)
            fn = _registry.aot_site(
                f"serving/fused[q{q_rows},t{table_len}]#{self._eid}",
                built,
                donate_argnums=(2, 3) if self._pool.quantized else (2,))
            self._fused_jits[key] = fn
        return fn

    # -- speculative decoding (draft propose + fused verify) ---------------
    def _init_draft(self, spec_draft, max_len) -> None:
        """Set up the draft side of speculative decoding: resolve the
        draft model (``"auto"`` builds a 2-layer GPT sharing the
        target's embeddings via ``models.generation.make_draft_model``),
        snapshot its params, and allocate its DENSE per-slot KV pool —
        the draft is small, so worst-case stripes cost little, and the
        dense layout needs no page-table bookkeeping. Draft positions
        mirror the target pool's ``slot_pos`` exactly (both write a
        row's K/V when the row is fed), so the only per-slot draft
        state is a 'synced' flag."""
        import jax.numpy as jnp

        from ..models.generation import make_draft_model
        from ..nn.layer.layers import get_buffers_tree, get_params_tree

        if spec_draft == "auto":
            spec_draft = make_draft_model(self._model)
        dgpt = spec_draft.gpt if hasattr(spec_draft, "gpt") \
            else spec_draft
        if dgpt.cfg.vocab_size != self._gpt.cfg.vocab_size:
            raise ValueError(
                f"draft vocab {dgpt.cfg.vocab_size} != target vocab "
                f"{self._gpt.cfg.vocab_size}: rejection sampling "
                f"compares distributions over the SAME vocabulary")
        if max_len > dgpt.cfg.max_position_embeddings:
            raise ValueError(
                f"max_len {max_len} exceeds the draft's "
                f"max_position_embeddings="
                f"{dgpt.cfg.max_position_embeddings}")
        spec_draft.eval()
        self._draft_model = spec_draft
        self._draft_gpt = dgpt
        self._draft_params = get_params_tree(spec_draft)
        self._draft_buffers = get_buffers_tree(spec_draft)
        dh = dgpt.cfg.hidden_size // dgpt.cfg.num_attention_heads
        pdt = self._draft_params[next(iter(self._draft_params))].dtype
        self._draft_max_len = int(max_len)
        self._draft_shape = (dgpt.cfg.num_hidden_layers, 2,
                             self._pool.num_slots,
                             dgpt.cfg.num_attention_heads,
                             self._draft_max_len, dh)
        self._draft_dtype = pdt
        self._draft_pool = jnp.zeros(self._draft_shape, pdt)
        self._draft_synced = np.zeros(self._pool.num_slots, bool)
        self._draft_prefill_jits = {}
        self._draft_scan_jits = {}        # kmax -> scanned propose chain

    def _reset_draft(self) -> None:
        """Failure-path twin of ``pool.reset_data()``: the draft pool
        is donated through its steps, so a failed cycle may have left
        it deleted — reallocate and drop every sync flag."""
        import jax.numpy as jnp
        self._draft_pool = jnp.zeros(self._draft_shape, self._draft_dtype)
        self._draft_synced[:] = False

    def _draft_bucket(self, n: int) -> int:
        """pow2 context bucket for the draft prefill, capped at the
        draft pool's max_len (the cap is reachable because a slot's
        context is always < max_len)."""
        b = 8
        while b < n:
            b *= 2
        return min(b, self._draft_max_len)

    def _draft_prefill_fn(self, bucket: int):
        fn = self._draft_prefill_jits.get(bucket)
        if fn is None:
            from ..models.generation import build_draft_prefill_fn
            probe = _probe.site(
                f"serving/spec_prefill[{bucket}]#{self._eid}")
            fn = _registry.aot_site(
                f"serving/spec_prefill[{bucket}]#{self._eid}",
                build_draft_prefill_fn(self._draft_model, bucket,
                                       self._draft_max_len, probe=probe),
                donate_argnums=(2,))
            self._draft_prefill_jits[bucket] = fn
        return fn

    def _draft_scan_fn(self, kmax: int):
        """ONE program for the whole draft proposal chain: ``lax.scan``
        over the per-token draft step
        (``build_draft_propose_scan_fn``), so a speculative cycle costs
        a single draft dispatch instead of ``kmax`` sequential small
        launches. One trace per distinct ``kmax`` (at most spec_k of
        them; in practice two — the full chain and the budget tail)."""
        fn = self._draft_scan_jits.get(kmax)
        if fn is None:
            from ..models.generation import build_draft_propose_scan_fn
            probe = _probe.site(
                f"serving/spec_draft[k{kmax}]#{self._eid}")
            fn = _registry.aot_site(
                f"serving/spec_draft[k{kmax}]#{self._eid}",
                build_draft_propose_scan_fn(
                    self._draft_model, self._pool.num_slots,
                    self._draft_max_len, kmax, top_k=self._top_k,
                    top_p=self._top_p, probe=probe),
                donate_argnums=(2,))
            self._draft_scan_jits[kmax] = fn
        return fn

    def _spec_step_fn(self, q_rows: int, table_len: int):
        key = (q_rows, table_len)
        fn = self._spec_jits.get(key)
        if fn is None:
            from ..models.generation import build_spec_verify_fn
            probe = _probe.site(
                f"serving/spec[q{q_rows},t{table_len}]#{self._eid}")
            fn = _registry.aot_site(
                f"serving/spec[q{q_rows},t{table_len}]#{self._eid}",
                build_spec_verify_fn(self._model, self._pool.num_slots,
                                     q_rows, self._spec_k, table_len,
                                     self._pool.block_size,
                                     top_k=self._top_k,
                                     top_p=self._top_p, probe=probe,
                                     quantized=self._pool.quantized,
                                     qmax=self._pool.qmax or 127.0),
                donate_argnums=(2, 3) if self._pool.quantized else (2,))
            self._spec_jits[key] = fn
        return fn

    def _sync_draft(self, slot: int, req: GenerationRequest) -> None:
        """Bring the draft's KV cache for ``slot`` up to the target's
        context ``[0, pos)``: one right-padded draft prefill of
        ``prompt + generated`` minus the last (not-yet-written) token.
        Runs when a slot starts (or resumes, after preemption/reuse)
        speculative decoding."""
        feed = np.concatenate(
            [req.prompt, np.asarray(req.tokens, np.int32)])
        ctx = feed[:-1]
        if ctx.size:
            b = self._draft_bucket(ctx.size)
            ids = np.zeros((1, b), np.int32)
            ids[0, :ctx.size] = ctx
            key_valid = np.zeros((1, b), bool)
            key_valid[0, :ctx.size] = True
            self._draft_pool = self._draft_prefill_fn(b)(
                self._draft_params, self._draft_buffers,
                self._draft_pool, ids, key_valid, np.int32(slot))
        self._draft_synced[slot] = True

    def _run_spec_step(self, slot_requests, plan, spec):
        """Dispatch ONE speculative serving cycle without any host
        sync: (1) newly-decoding slots' draft caches sync via a
        right-padded draft prefill; (2) ``spec_k`` draft launches
        propose candidates autoregressively (each step feeds the
        previous step's device-side proposal — the host never fetches
        a draft token); (3) ONE fused ragged verify launch scores
        every candidate row next to the cycle's prefill-chunk rows,
        runs device-side rejection sampling, and returns ``[accepted |
        corrected | draft echo | sentinel]`` for the scheduler's
        single fetch. Returns that DEVICE array un-fetched."""
        try:
            return self._run_spec_inner(slot_requests, plan, spec)
        except Exception:
            # the draft pool is donated through its steps: a failure
            # may leave it deleted — rebuild so the engine serves on
            # after the scheduler resets the target pool
            self._reset_draft()
            raise

    def _run_spec_inner(self, slot_requests, plan, spec):
        import jax.numpy as jnp

        pool = self._pool
        S = pool.num_slots
        K = self._spec_k
        for slot in spec:
            if not self._draft_synced[slot]:
                self._sync_draft(slot, slot_requests[slot])
        # --- draft proposal loop: K launches, device-chained ---------
        sample_mask = np.zeros(S, bool)
        temps = np.ones(S, np.float32)
        feed0 = np.zeros(S, np.int32)
        pos_d = np.zeros(S, np.int32)
        for slot, req in slot_requests.items():
            sample_mask[slot] = req.do_sample
            temps[slot] = req.temperature
        for slot in spec:
            feed0[slot] = slot_requests[slot].last_token
            pos_d[slot] = pool.slot_pos(slot)
        lo_d = np.zeros(S, np.int32)
        # only as many scanned draft steps as the cycle's LARGEST
        # candidate count needs (every slot's n_spec = min(spec_k,
        # remaining) — a batch tail one token from its budget would
        # otherwise pay spec_k full draft passes for one verified
        # candidate); the verify signature stays [S, K], zero-padded
        # past kmax. The whole chain is ONE lax.scan program: what
        # used to be kmax sequential small launches is a single
        # dispatch per cycle (the flight recorder's
        # spec_draft_dispatches proves it)
        kmax = max(spec.values())
        self._draft_pool, d_dev, q_dev, self._key = \
            self._draft_scan_fn(kmax)(
                self._draft_params, self._draft_buffers,
                self._draft_pool, feed0, pos_d, lo_d, sample_mask,
                temps, self._key)
        self._sched.note_spec_dispatches(1)
        if kmax < K:
            d_dev = jnp.pad(d_dev, ((0, 0), (0, K - kmax)))
            q_dev = jnp.pad(q_dev, ((0, 0), (0, K - kmax), (0, 0)))
        # --- the fused verify launch ---------------------------------
        Q, T, ops, n_spec, sample_mask, temps = self._ragged_operands(
            slot_requests, plan, spec=spec)
        step = self._spec_step_fn(Q, T)
        args = ops + (n_spec, d_dev, q_dev, sample_mask, temps,
                      self._key)
        if pool.quantized:
            pool.data, pool.scales, out, self._key = step(
                self._params, self._buffers, pool.data, pool.scales,
                *args)
        else:
            pool.data, out, self._key = step(
                self._params, self._buffers, pool.data, *args)
        self._note_decode_dispatch(step)
        return out

    def _run_decode(self, slot_requests):
        """Dispatch ONE decode step; returns the next-token DEVICE
        array — the scheduler performs the windowed ``_fetch`` itself so
        its cycle telemetry can time dispatch and host-fetch apart."""
        S = self._pool.num_slots
        tokens = np.zeros(S, np.int32)
        sample_mask = np.zeros(S, bool)
        temps = np.ones(S, np.float32)
        for slot, req in slot_requests.items():
            tokens[slot] = req.last_token
            sample_mask[slot] = req.do_sample
            temps[slot] = req.temperature
        pos, lo = self._pool.position_arrays()
        if self._paged:
            # the cohort decodes at the largest member's pow2 table
            # bucket (shorter tables pad with the scratch block) — one
            # trace per bucket, exactly the prefill-bucket discipline
            T = max(self._pool.table_bucket(s) for s in slot_requests)
            tables = self._pool.table_array(T, slot_requests)
            step = self._paged_decode_fn(T)
            if self._pool.quantized:
                (self._pool.data, self._pool.scales, nxt,
                 self._key) = step(
                    self._params, self._buffers, self._pool.data,
                    self._pool.scales, tokens, pos, lo, tables,
                    sample_mask, temps, self._key)
            else:
                self._pool.data, nxt, self._key = step(
                    self._params, self._buffers, self._pool.data, tokens,
                    pos, lo, tables, sample_mask, temps, self._key)
            self._note_decode_dispatch(step)
            return nxt
        self._pool.data, nxt, self._key = self._decode_jit(
            self._params, self._buffers, self._pool.data, tokens, pos, lo,
            sample_mask, temps, self._key)
        self._note_decode_dispatch(self._decode_jit)
        return nxt

    def _note_decode_dispatch(self, step) -> None:
        """Account the FLOPs of the decode program that actually ran
        this cycle (host arithmetic only): lifetime counters for the
        mean, plus the live cycle record so cycle_throughput() keeps
        achieved-FLOP/s on the same ring window as its wall-time
        denominator (a lifetime mean alone would lag a shifting
        bucket mix)."""
        self._decode_dispatches += 1
        flops = getattr(step, "last_dispatch_flops", None)
        if flops is None:
            rec = getattr(step, "record", None)
            flops = rec.flops if rec is not None else None
        if flops:
            self._decode_flops_dispatched += flops
            self._sched.note_decode_flops(flops)

    def _run_copy(self, dst: int, src: int) -> None:
        """Copy-on-write append support: device-copy block ``src`` over
        block ``dst`` across every layer/kv plane before the decode step
        scatters into ``dst`` — a quantized pool copies the block's
        per-(layer, kv, head) scales in the same program, so the clone
        dequantizes identically. Block ids are traced scalars — ONE
        trace serves every copy — and the pool (and scale array) is
        donated like every other step. Device-to-device only: no host
        sync."""
        if self._copy_jit is None:
            if self._pool.quantized:
                def _copy(pool, scales, dst, src):
                    return (pool.at[:, :, dst].set(pool[:, :, src]),
                            scales.at[:, :, dst].set(scales[:, :, src]))

                self._copy_jit = _registry.aot_site(
                    f"serving/copy#{self._eid}", _copy,
                    donate_argnums=(0, 1))
            else:
                def _copy(pool, dst, src):
                    return pool.at[:, :, dst].set(pool[:, :, src])

                self._copy_jit = _registry.aot_site(
                    f"serving/copy#{self._eid}", _copy,
                    donate_argnums=(0,))
        if self._pool.quantized:
            self._pool.data, self._pool.scales = self._copy_jit(
                self._pool.data, self._pool.scales, np.int32(dst),
                np.int32(src))
        else:
            self._pool.data = self._copy_jit(
                self._pool.data, np.int32(dst), np.int32(src))
