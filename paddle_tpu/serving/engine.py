"""``GenerationEngine`` — the user surface of the continuous-batching
LLM server.

Many concurrent ``submit(prompt_ids, ...)`` calls are served by ONE
jitted, pool-donated decode step over a slot-based KV-cache pool
(:mod:`.kv_pool`), driven by the prefill/decode scheduler
(:mod:`.scheduler`). The serving-side twin of the PR-2 donated training
loop: buffers are donated and rebound, the hot loop never syncs except
the one windowed token fetch, and every step program must pass the PR-3
analyzer clean (``engine.analyze()``).

Compile discipline: the decode step traces ONCE per engine (slot count,
pool shape and sampling support are static; per-request temperature and
greedy/sampled choice are traced values), and prefill traces once per
CAPACITY BUCKET (pow2 prompt lengths) — both watched by
``framework.trace_probe`` sites (``serving/decode#N``,
``serving/prefill[B]#N``), so a retrace shows up in the
``dispatch/retrace_cause`` counters exactly like training-loop churn.

Observability (PR-1 wiring): counters ``serving/requests``,
``serving/completed``, ``serving/tokens``, ``serving/preempt``,
``serving/queue_full``, ``serving/cancelled``,
``serving/deadline_exceeded``; histograms ``serving/queue_depth``,
``serving/active_slots``, ``serving/ttft_ms``,
``serving/tokens_per_sec``; spans ``serving/prefill`` and
``serving/decode_step``.
"""
from __future__ import annotations

import threading
from typing import Iterator, Optional

import numpy as np

from ..framework import trace_probe as _probe
from ..framework.monitor import stat_add
from .kv_pool import KVCachePool
from .scheduler import (GenerationRequest, Scheduler, _fetch)

__all__ = ["GenerationEngine"]

_engine_seq = 0
_engine_seq_lock = threading.Lock()


def _next_engine_id() -> int:
    global _engine_seq
    with _engine_seq_lock:
        _engine_seq += 1
        return _engine_seq


class GenerationEngine:
    """Continuous-batching autoregressive serving over a GPT-style model.

    ``model`` is a ``models.GPTForPretraining`` / ``GPTModel`` (anything
    exposing the ``gpt`` prefill/decode surface used by
    ``models.generate``); its parameters are snapshotted at construction
    (sharded parameters serve sharded — jit follows the placement).

    * ``num_slots`` — concurrent in-flight requests (the pool's batch);
    * ``max_len`` — per-slot cache capacity; a request needs
      ``bucket(prompt) + max_new_tokens <= max_len``;
    * ``top_k``/``top_p`` — the sampled path's truncation, STATIC per
      engine (part of the single decode trace); per-request
      ``do_sample``/``temperature`` are traced values;
    * ``max_queue``/``prefill_budget`` — backpressure and the
      anti-starvation admission policy (see :mod:`.scheduler`).

    Greedy engine output is token-identical to ``models.generate`` run
    per request (the parity contract, tests/test_serving_engine.py).
    """

    def __init__(self, model, num_slots: int = 8,
                 max_len: Optional[int] = None, *, top_k: int = 0,
                 top_p: float = 1.0, pad_token_id: int = 0,
                 max_queue: int = 128, prefill_budget: Optional[int] = None,
                 min_bucket: int = 8, seed: int = 0, dtype=None):
        import jax

        from ..models.generation import build_slot_decode_fn
        from ..nn.layer.layers import get_buffers_tree, get_params_tree

        gpt = model.gpt if hasattr(model, "gpt") else model
        cfg = gpt.cfg
        max_len = int(max_len or cfg.max_position_embeddings)
        model.eval()                      # serving is inference-only
        self._model = model
        self._gpt = gpt
        self._pad = int(pad_token_id)
        self._top_k, self._top_p = int(top_k), float(top_p)
        self._params = get_params_tree(model)
        self._buffers = get_buffers_tree(model)
        if dtype is None:
            dtype = self._params[next(iter(self._params))].dtype
        self._pool = KVCachePool(
            cfg.num_hidden_layers, num_slots, cfg.num_attention_heads,
            max_len, cfg.hidden_size // cfg.num_attention_heads,
            dtype=dtype, min_bucket=min_bucket)
        self._key = jax.random.PRNGKey(int(seed))
        self._eid = _next_engine_id()
        self._decode_probe = _probe.site(f"serving/decode#{self._eid}")
        self._decode_jit = jax.jit(
            build_slot_decode_fn(model, self._pool.num_slots, max_len,
                                 top_k=self._top_k, top_p=self._top_p,
                                 probe=self._decode_probe),
            donate_argnums=(2,))
        self._prefill_jits = {}           # bucket -> jitted prefill step
        self._closed = False
        self._close_lock = threading.Lock()
        self._sched = Scheduler(self._pool, self._run_prefill,
                                self._run_decode, max_queue=max_queue,
                                prefill_budget=prefill_budget)

    # -- client side -------------------------------------------------------
    def submit(self, prompt_ids, max_new_tokens: int = 32, *,
               do_sample: bool = False, temperature: float = 1.0,
               eos_token_id: Optional[int] = None,
               timeout: Optional[float] = None) -> GenerationRequest:
        """Enqueue one generation; returns its handle immediately.

        The handle streams tokens as they are produced
        (``handle.stream()``), blocks for the padded full sequence
        (``handle.result()``), and cancels mid-flight
        (``handle.cancel()``). ``timeout`` (seconds) is a hard deadline:
        a request that has not FINISHED by then fails with
        ``DeadlineExceeded``. A full admission queue raises
        ``QueueFullError`` here, synchronously."""
        if self._closed:
            raise RuntimeError("GenerationEngine is closed")
        ids = np.asarray(prompt_ids, np.int32).reshape(-1)
        if ids.size < 1:
            raise ValueError("prompt_ids must contain at least one token")
        if max_new_tokens < 1:
            raise ValueError(
                f"max_new_tokens must be >= 1, got {max_new_tokens}")
        bucket = self._pool.bucket_for(ids.size)
        if bucket + int(max_new_tokens) > self._pool.max_len:
            raise ValueError(
                f"prompt bucket {bucket} + max_new_tokens "
                f"{max_new_tokens} exceeds the pool capacity "
                f"{self._pool.max_len}; shorten the request or build the "
                f"engine with a larger max_len")
        req = GenerationRequest(
            ids, max_new_tokens, do_sample=do_sample,
            temperature=temperature, eos_token_id=eos_token_id,
            pad_token_id=self._pad, timeout=timeout)
        handle = self._sched.submit(req)   # QueueFullError propagates
        stat_add("serving/requests")       # counts ACCEPTED requests
        return handle

    def stream(self, prompt_ids, **kwargs) -> Iterator[int]:
        """``submit(...).stream()`` in one call: an iterator of token
        ids, yielded as each is produced."""
        return self.submit(prompt_ids, **kwargs).stream()

    def close(self, cancel_pending: bool = False) -> None:
        """Graceful shutdown: stop accepting work, DRAIN everything
        queued and in flight, then stop the scheduler thread. With
        ``cancel_pending`` the queue is cancelled instead of served
        (in-flight slots still finish)."""
        with self._close_lock:
            if self._closed:
                return
            self._closed = True
        self._sched.close(cancel_pending=cancel_pending)

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.close()
        return False

    # -- introspection -----------------------------------------------------
    @property
    def num_slots(self) -> int:
        return self._pool.num_slots

    @property
    def queue_depth(self) -> int:
        return self._sched.queue_depth

    @property
    def active_requests(self) -> int:
        return self._sched.active

    def analyze(self, passes=None):
        """PR-3 pre-flight of THE decode step: trace the jitted program
        (donation contract auto-read from the pjit eqn) and run the
        analysis pipeline. The clean-bill contract is zero
        error-severity findings — donation-safe, no host sync in the
        hot loop; asserted by ``bench.py --dry-run`` and the tier-1
        tests. Tracing hits jit's signature cache, so this never
        retraces (the probe counters stay honest)."""
        from .. import analysis

        S = self._pool.num_slots
        return analysis.analyze(
            self._decode_jit, self._params, self._buffers, self._pool.data,
            np.zeros(S, np.int32), np.zeros(S, np.int32),
            np.zeros(S, np.int32), np.zeros(S, bool),
            np.ones(S, np.float32), self._key,
            passes=passes, name=f"serving.decode[{S} slots]")

    # -- device side (called from the scheduler thread only) ---------------
    def _prefill_fn(self, bucket: int):
        fn = self._prefill_jits.get(bucket)
        if fn is None:
            import jax

            from ..models.generation import build_slot_prefill_fn
            probe = _probe.site(f"serving/prefill[{bucket}]#{self._eid}")
            fn = jax.jit(
                build_slot_prefill_fn(self._model, bucket,
                                      self._pool.max_len,
                                      top_k=self._top_k,
                                      top_p=self._top_p, probe=probe),
                donate_argnums=(2,))
            self._prefill_jits[bucket] = fn
        return fn

    def _run_prefill(self, req: GenerationRequest, slot: int,
                     bucket: int) -> int:
        ids = np.full((1, bucket), self._pad, np.int32)
        ids[0, bucket - req.prompt.size:] = req.prompt
        key_valid = np.zeros((1, bucket), bool)
        key_valid[0, bucket - req.prompt.size:] = True
        self._pool.data, first, self._key = self._prefill_fn(bucket)(
            self._params, self._buffers, self._pool.data, ids, key_valid,
            np.int32(slot), np.bool_(req.do_sample),
            np.float32(req.temperature), self._key)
        return int(_fetch(first)[0])

    def _run_decode(self, slot_requests) -> np.ndarray:
        S = self._pool.num_slots
        tokens = np.zeros(S, np.int32)
        sample_mask = np.zeros(S, bool)
        temps = np.ones(S, np.float32)
        for slot, req in slot_requests.items():
            tokens[slot] = req.last_token
            sample_mask[slot] = req.do_sample
            temps[slot] = req.temperature
        pos, lo = self._pool.position_arrays()
        self._pool.data, nxt, self._key = self._decode_jit(
            self._params, self._buffers, self._pool.data, tokens, pos, lo,
            sample_mask, temps, self._key)
        return _fetch(nxt)
