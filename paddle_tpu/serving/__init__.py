"""``paddle_tpu.serving`` — continuous-batching LLM serving (L9+).

The autoregressive counterpart of ``inference.BatchingEngine``: where
that engine gathers fixed-shape ``predictor.run`` calls, this one serves
many concurrent ``generate``-style requests through ONE jitted, donated
decode step over a slot-based KV-cache pool. Requests join and leave
the in-flight batch EVERY step (continuous batching) instead of waiting
for a whole generation to drain — a long request never stalls a short
one, and a retired slot's capacity is reused mid-flight.

Reference analog: the reference serves decoder LMs through
fused_multi_transformer's fixed-capacity CacheKV
(paddle/fluid/operators/fused/fused_multi_transformer_op.cu:1) behind
AnalysisPredictor + paddle-serving request batching; the TPU-native
collapse is slot-addressed decode over a shared pool (the Ragged Paged
Attention shape, PAPERS.md) with XLA-donated in-place updates.

::

    from paddle_tpu.serving import GenerationEngine

    engine = GenerationEngine(model, num_slots=8, max_len=256)
    handle = engine.submit(prompt_ids, max_new_tokens=64,
                           eos_token_id=eos)
    for token in handle.stream():   # tokens as they are produced
        ...
    engine.close()                  # drains in-flight work

Two KV layouts share the surface: the dense slot pool above, and
``GenerationEngine(kv_layout="paged", block_size=...)`` — block-granular
KV management (:mod:`.paging`) with per-request page tables, ref-counted
block sharing and a prefix cache, so admission gates on FREE BLOCKS
instead of worst-case slot stripes and a repeated system prompt skips
prefill entirely. ``kv_dtype="int8"`` stores the blocks QUANTIZED with
per-block max-abs scales (~4x blocks per byte budget, ~2x+ concurrent
requests), and ``spec_draft=`` + ``spec_k=`` (fused engines) adds
draft-model SPECULATIVE DECODING — k candidate tokens verified per
slot per cycle in one fused ragged launch, exact greedy parity,
``stats()['spec_tokens_per_cycle']`` > 1 on agreeing workloads.

SLO observability (ISSUE 6): every handle carries ``handle.trace`` — a
:class:`~.tracing.RequestTrace` of timestamped lifecycle events with
derived per-request TTFT/TPOT — the scheduler keeps an always-on
bounded :class:`~.flight_recorder.FlightRecorder`
(``engine.dump_flight_recorder()``, auto-dumped on step failure), and
``engine.stats()`` reports per-ENGINE TTFT/TPOT percentiles from its
own retired traces. ``bench.py --serve-load`` drives seeded
open-arrival traffic against both KV layouts and writes the
TTFT/TPOT/goodput curve into a BENCH json.

Modules: :mod:`.kv_pool` (the pooled cache + slot allocator +
capacity buckets), :mod:`.paging` (the paged block pool: free-list
allocator, page tables, refcounts/copy-on-write, prefix-cache trie +
LRU eviction), :mod:`.scheduler` (admission queue, backpressure,
prefill-budget policy, block-pressure preemption, the decode loop),
:mod:`.tracing` (per-request lifecycle traces + chrome-trace lanes),
:mod:`.flight_recorder` (bounded postmortem rings + per-engine latency
reservoirs + tail-sampled traces), :mod:`.engine` (the thread-safe
user surface + monitor/profiler/analysis wiring), :mod:`.slo` (SLO
objectives, multi-window burn rates, per-replica goodput),
:mod:`.opsserver` (the zero-dependency HTTP ops surface: /metrics,
/statusz, /varz, /healthz, /readyz, /tracez, /timeline — a pluggable
route table), :mod:`.frontdoor` (the OpenAI-style ``/v1/completions``
inference front door: SSE streaming, per-tenant token-bucket admission,
weighted-fair interactive/batch lanes riding the scheduler's
(lane, tenant) deficit-round-robin), :mod:`.host_tier` (the
hierarchical KV cache: ``GenerationEngine(host_tier_bytes=...)`` spills
LRU-evicted prefix blocks to a bounded host-DRAM
:class:`~.host_tier.HostBlockPool` on a background spiller thread and
promotes them back through double-buffered async H2D copies the
scheduler overlaps with decode — the prefix cache outgrows HBM).
"""
from __future__ import annotations

from .engine import GenerationEngine, PlanError  # noqa: F401
from .fleet import EngineFleet  # noqa: F401
from .flight_recorder import FlightRecorder  # noqa: F401
from .frontdoor import FrontDoor, TokenBucket  # noqa: F401
from .host_tier import (HostBlockPool, HostTierError,  # noqa: F401
                        HostTierFullError, PromotionTicket)
from .kv_pool import KVCachePool  # noqa: F401
from .opsserver import OpsServer  # noqa: F401
from .paging import (BlockError, PagedKVPool,  # noqa: F401
                     PoolCapacityError, PoolExhaustedError)
from .scheduler import (DeadlineExceeded, GenerationRequest,  # noqa: F401
                        QueueFullError, RequestCancelled, Scheduler)
from .slo import SLOObjective, SLOTracker  # noqa: F401
from .slo import attainment_from_buckets  # noqa: F401
from .tracing import RequestTrace  # noqa: F401

__all__ = ["GenerationEngine", "PlanError", "EngineFleet", "KVCachePool",
           "PagedKVPool", "GenerationRequest", "Scheduler",
           "QueueFullError", "DeadlineExceeded", "RequestCancelled",
           "PoolCapacityError", "PoolExhaustedError", "BlockError",
           "RequestTrace", "FlightRecorder", "OpsServer",
           "FrontDoor", "TokenBucket",
           "HostBlockPool", "HostTierError", "HostTierFullError",
           "PromotionTicket",
           "SLOTracker", "SLOObjective", "attainment_from_buckets"]
