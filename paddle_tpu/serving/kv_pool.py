"""Slot-based KV-cache pool: the device-resident memory the continuous
batcher schedules over.

One array ``[layers, 2, slots, heads, max_len, head_dim]`` holds every
in-flight request's KV cache — the pooled, slot-addressed form of the
``FusedMultiHeadAttention._cached_attention`` CacheKV layout
(``[2, B, H, max_len, Dh]`` per layer,
incubate/nn/layer/fused_transformer.py), stacked over layers with the
batch axis reinterpreted as SLOTS. A request owns a slot for exactly the
steps it is decoding; the moment it finishes (EOS / budget / cancel /
deadline) the slot returns to the free list and the NEXT admission's
prefill overwrites it — capacity is reused mid-flight, which is the
whole reason one long request cannot hold a batch hostage (the
Ragged-Paged-Attention argument, PAPERS.md).

Host-side bookkeeping lives here too: the free list, per-slot position
tracking (``pos`` = cache index of the slot's last token, ``lo`` = first
valid index, i.e. the left-pad offset of its admission bucket), and the
CAPACITY BUCKETS — prompts are left-padded to power-of-two lengths so
the prefill step traces once per bucket, never once per prompt length
(the BatchingEngine pow2 argument, applied to sequence length).

Threading contract: the pool is owned by the scheduler thread; ``alloc``
/ ``free`` / ``set_slot`` are only called from it. ``data`` is rebound
by the engine after every donated step (the old array is deleted by XLA
— donation — so nothing else may hold it).
"""
from __future__ import annotations

import itertools
from typing import Dict, List, Optional

import numpy as np

from ..profiler import memory as _memory

__all__ = ["KVCachePool", "SlotPoolBase"]

# process-wide pool numbering for the HBM ledger keys (two engines in
# one process must not alias each other's ledger entries)
_pool_ids = itertools.count(1)


def _drop_pool_ledger(ledger_key: str) -> None:
    """weakref.finalize target for a pool's ledger entries — a module
    function so the finalizer holds no reference to the pool."""
    _memory.ledger_drop(f"{ledger_key}/capacity")
    _memory.ledger_drop(f"{ledger_key}/in_use")


class _Slot:
    """Position state of one allocated slot (host ints, scheduler-owned)."""

    __slots__ = ("pos", "lo")

    def __init__(self, pos: int = 0, lo: int = 0):
        self.pos = pos
        self.lo = lo


class SlotPoolBase:
    """Slot/position/bucket bookkeeping shared by every KV pool layout.

    The scheduler talks to pools through this interface only: request
    slots (the decode batch axis) with deterministic lowest-index
    allocation, per-slot ``pos``/``lo`` tracking, and the pow2 capacity
    buckets that keep prefill at ONE trace per bucket. Subclasses bind
    the device memory (``data``/``shape``/``dtype``) in their
    constructors, pick the per-slot state record via ``_slot_cls``, and
    hook ``_slot_freed`` for layout-specific teardown (the paged pool
    unrefs the slot's blocks there).
    """

    _slot_cls = _Slot
    # advance()'s overrun diagnostic, per layout (dense bills the pow2
    # bucket, paged only the true footprint)
    _capacity_noun = "cache capacity"
    _admission_law = "bucket + max_new <= max_len"
    # quantized block storage is a paged-pool feature (per-block
    # scales); the dense pool is always a plain float layout
    quantized = False
    qmax = None
    scales = None

    # subclass constructors set: num_slots, max_len, min_bucket,
    # shape, dtype, data — then call _init_slots()
    def _init_slots(self) -> None:
        # lowest-index-first keeps slot assignment deterministic (tests
        # and trace/debug output stay stable across runs)
        import weakref
        self._free_slots: List[int] = list(range(self.num_slots))
        self._slots: Dict[int, _Slot] = {}
        self.ledger_key = f"serving/kv_pool#{next(_pool_ids)}"
        # a pool dropped WITHOUT engine.close() (exception paths, tests
        # building pools directly) must not haunt crosscheck()/OOM
        # postmortems with phantom KV bytes — same finalizer discipline
        # as the hapi train-state ledger keys
        weakref.finalize(self, _drop_pool_ledger, self.ledger_key)
        self._update_ledger()

    # -- HBM ledger (profiler/memory.py) -----------------------------------
    @property
    def capacity_bytes(self) -> int:
        """Device bytes of the whole pool array (host arithmetic over
        shape/dtype — never touches the array)."""
        return int(np.prod(self.shape)) * self.dtype.itemsize

    @property
    def bytes_in_use(self) -> int:
        """Bytes of the capacity actually claimed by live requests —
        whole slot stripes here; the paged pool overrides with
        block-granular accounting."""
        return self.n_active * (self.capacity_bytes // self.num_slots)

    def _update_ledger(self) -> None:
        """Publish capacity + in-use bytes into the process HBM ledger
        (the 'what we think is live' side of the ledger-vs-device
        crosscheck). Host dict stores only — called from alloc/free and
        the paged block hooks, all scheduler-thread, all sync-free."""
        _memory.ledger_set(f"{self.ledger_key}/capacity",
                           self.capacity_bytes)
        _memory.ledger_set(f"{self.ledger_key}/in_use", self.bytes_in_use)

    def drop_ledger(self) -> None:
        """Remove this pool's ledger entries (engine close): the pool
        array may outlive the engine object briefly, but a closed
        engine's pool is no longer an accounted owner."""
        _memory.ledger_drop(f"{self.ledger_key}/capacity")
        _memory.ledger_drop(f"{self.ledger_key}/in_use")

    # -- slot allocation ---------------------------------------------------
    def alloc(self) -> Optional[int]:
        """Claim the lowest free slot, or None when the pool is full."""
        if not self._free_slots:
            return None
        slot = min(self._free_slots)
        self._free_slots.remove(slot)
        self._slots[slot] = self._slot_cls()
        self._update_ledger()
        _memory.mark("kv/alloc", pool=self.ledger_key, slot=slot,
                     in_use=self.bytes_in_use)
        return slot

    def free(self, slot: int) -> None:
        """Return ``slot`` to the free list (``_slot_freed`` runs the
        layout's teardown first). Its device rows are NOT cleared — the
        next occupant's prefill overwrites them and the decode mask
        never looks past ``pos``, so stale K/V are unreachable by
        construction."""
        if slot not in self._slots:
            raise ValueError(f"slot {slot} is not allocated")
        st = self._slots.pop(slot)
        self._slot_freed(st)
        self._free_slots.append(slot)
        self._update_ledger()
        _memory.mark("kv/free", pool=self.ledger_key, slot=slot,
                     in_use=self.bytes_in_use)

    def _slot_freed(self, st) -> None:
        """Layout hook: called by :meth:`free` with the popped slot
        state, before the slot rejoins the free list."""

    def is_allocated(self, slot: int) -> bool:
        return slot in self._slots

    def reset_data(self) -> None:
        """Reallocate the device pool. The steps DONATE ``data``, so a
        step that fails at XLA runtime may leave it already deleted —
        serving on with the stale handle would fail every later step
        with "Array has been deleted". Called by the scheduler's
        failure path after the in-flight slots are failed and freed;
        fresh zeros are safe because only live slots carry meaningful
        cache rows and none survive the failure."""
        import jax.numpy as jnp
        self.data = jnp.zeros(self.shape, self.dtype)

    @property
    def n_active(self) -> int:
        return len(self._slots)

    @property
    def n_free(self) -> int:
        return len(self._free_slots)

    def active_slots(self) -> List[int]:
        return sorted(self._slots)

    # -- per-slot position tracking ---------------------------------------
    def set_slot(self, slot: int, *, pos: int, lo: int) -> None:
        st = self._slots[slot]
        if not 0 <= lo <= pos < self.max_len:
            raise ValueError(
                f"slot {slot}: bad position state lo={lo} pos={pos} "
                f"(max_len={self.max_len})")
        st.pos = int(pos)
        st.lo = int(lo)

    def advance(self, slot: int, n: int = 1) -> int:
        """``n`` tokens landed (one decode step, or one prefill chunk
        of the fused ragged step): the slot's write position moves
        ``n`` cache indices later. ``n`` is a SIGNED delta — the
        speculative-decoding scheduler rolls back the rows a rejected
        draft wrote with a negative ``n`` (paged tables address by
        ``pos``, so rollback is pure bookkeeping: the stale K/V beyond
        the new ``pos`` are masked out of attention and overwritten by
        the next append). Returns the new ``pos``."""
        if n == 0:
            raise ValueError("advance needs n != 0")
        st = self._slots[slot]
        new_pos = st.pos + int(n)        # validate BEFORE mutating: a
        if new_pos >= self.max_len:      # rejected advance must leave
            raise RuntimeError(          # the slot state untouched
                f"slot {slot} overran the {self._capacity_noun} "
                f"{self.max_len} — the admission check "
                f"({self._admission_law}) is broken")
        if new_pos < st.lo:
            raise RuntimeError(
                f"slot {slot}: rollback below the slot's floor "
                f"(pos={new_pos} < lo={st.lo}) — a speculative rollback "
                f"may only unwind rows written this cycle")
        st.pos = new_pos
        return st.pos

    def slot_pos(self, slot: int) -> int:
        return self._slots[slot].pos

    def slot_lo(self, slot: int) -> int:
        return self._slots[slot].lo

    def position_arrays(self):
        """(tokens-independent) dense ``pos``/``lo`` int32 arrays over ALL
        slots for the decode step; free slots read 0 — they compute
        garbage the scheduler ignores and the next prefill overwrites."""
        pos = np.zeros(self.num_slots, np.int32)
        lo = np.zeros(self.num_slots, np.int32)
        for slot, st in self._slots.items():
            pos[slot] = st.pos
            lo[slot] = st.lo
        return pos, lo

    # -- capacity buckets --------------------------------------------------
    def bucket_for(self, prompt_len: int) -> int:
        """The capacity bucket of a prompt: next power of two >=
        ``prompt_len`` (floored at ``min_bucket``) — ONE prefill trace
        per bucket, O(log max_len) buckets total."""
        if prompt_len < 1:
            raise ValueError(f"prompt_len must be >= 1, got {prompt_len}")
        b = self.min_bucket
        while b < prompt_len:
            b *= 2
        return b

    def buckets(self) -> List[int]:
        """Every admissible bucket size (pow2 from min_bucket to max_len)."""
        out, b = [], self.min_bucket
        while b <= self.max_len:
            out.append(b)
            b *= 2
        return out


class KVCachePool(SlotPoolBase):
    """Fixed-capacity pooled KV cache + slot allocator.

    ``data`` is the jnp array ``[layers, 2, slots, heads, max_len,
    head_dim]``; the engine threads it through the donated prefill and
    decode steps and rebinds it here. Everything else is host
    bookkeeping: which slots are live, where each slot's sequence starts
    (``lo``) and currently ends (``pos``).
    """

    def __init__(self, num_layers: int, num_slots: int, num_heads: int,
                 max_len: int, head_dim: int, dtype="float32",
                 min_bucket: int = 8):
        import jax.numpy as jnp

        if num_slots < 1:
            raise ValueError(f"num_slots must be >= 1, got {num_slots}")
        if min_bucket < 1:
            raise ValueError(f"min_bucket must be >= 1, got {min_bucket}")
        if max_len < min_bucket:
            raise ValueError(
                f"max_len={max_len} is below min_bucket={min_bucket}: no "
                f"prompt could ever be admitted")
        self.num_layers = int(num_layers)
        self.num_slots = int(num_slots)
        self.num_heads = int(num_heads)
        self.max_len = int(max_len)
        self.head_dim = int(head_dim)
        self.min_bucket = int(min_bucket)
        self.shape = (self.num_layers, 2, self.num_slots, self.num_heads,
                      self.max_len, self.head_dim)
        self.dtype = jnp.dtype(dtype)
        self.data = jnp.zeros(self.shape, self.dtype)
        self._init_slots()

    def __repr__(self):
        return (f"<KVCachePool {self.shape} {self.data.dtype} "
                f"active={self.n_active}/{self.num_slots}>")
