"""Paged KV-cache memory manager: block-granular pooling + prefix cache.

The dense :class:`~.kv_pool.KVCachePool` reserves a full
``[heads, max_len, head_dim]`` stripe per slot, so concurrency is capped
by WORST-CASE sequence length even when most requests are short — the
fragmentation problem paged, block-granular KV management solves on TPU
(the Ragged-Paged-Attention argument, PAPERS.md). Here the device pool
is ``[layers, 2, num_blocks + 1, heads, block_size, head_dim]``: a
request owns only the blocks covering its tokens SO FAR, addressed
through a per-request page table that maps virtual cache index
``i`` to ``(table[i // block_size], i % block_size)``. Physical block 0
is a reserved SCRATCH block — page-table padding points at it, prefill
pad-position garbage lands in it, and nothing ever reads it through an
unmasked position.

Host-side manager (this module, scheduler-thread-owned):

* **free-list block allocator** — blocks move between the free list,
  request page tables (refcounted), and the prefix cache's LRU of
  released-but-reusable blocks;
* **page tables in pow2 buckets** — the decode step's table width is
  the next power of two over the blocks a request holds (capped at
  ``max_table_len``), so there is ONE decode trace per table bucket,
  never one per table length — the serving twin of the dense engine's
  pow2 prompt buckets;
* **refcounts + copy-on-write** — a block reachable from several page
  tables (prefix sharing) is never written through; the manager's
  ``ensure_writable`` hands the engine a ``(dst, src)`` copy order and
  swaps the table entry, so appends always hit a refcount-1 block. By
  construction shared blocks sit strictly below every sharer's write
  position (reuse is capped at ``(len - 1) // block_size`` full
  blocks), so COW is a guard rail, not a hot path;
* **prefix-cache trie** — full token blocks are registered under their
  token-prefix key (the dict key IS the exact prefix tuple, so "hash"
  collisions cannot alias two different prefixes); a later request
  whose prompt starts with the same full blocks reuses their K/V and
  skips prefill entirely (the remaining tokens are replayed through the
  shared decode step, one per cycle — which is why the ENGINE only
  takes the hit when the uncovered tail fits one ``min_bucket``; a
  longer tail prefills fresh instead). Released cached blocks wait in
  an LRU; allocation pressure evicts the oldest refcount-0 entry (and
  unregisters its now-unreachable descendants) before giving up.

Virtual layout note: unlike the dense pool's left-padded capacity
buckets, paged sequences are aligned at virtual index 0 (``lo == 0``) —
block contents then depend only on the token prefix, which is what
makes them shareable across requests and prompt lengths.

Monitor wiring (PR-1): ``serving/kv_blocks_in_use`` histogram,
``serving/prefix_hit`` / ``serving/prefix_miss`` /
``serving/prefill_tokens_saved`` / ``serving/prefix_evict`` counters
(``serving/preempt`` is counted by the scheduler's preemption path).

Threading contract: exactly the dense pool's — the manager is owned by
the scheduler thread; ``data`` is rebound by the engine after every
donated step.
"""
from __future__ import annotations

import heapq
from collections import OrderedDict
from typing import Dict, List, Optional, Tuple

import numpy as np

from ..framework.monitor import stat_add, stat_observe
from .kv_pool import SlotPoolBase

__all__ = ["PagedKVPool", "PoolCapacityError", "PoolExhaustedError",
           "BlockError"]


class PoolCapacityError(ValueError):
    """The request can NEVER fit this pool (virtual capacity or total
    block budget) — raised at ``submit()`` time, fail fast."""


class PoolExhaustedError(RuntimeError):
    """No free and no evictable block right now — a TRANSIENT pressure
    signal; the scheduler answers it by preempting the youngest active
    request, never by corrupting the free list."""


class BlockError(ValueError):
    """Block bookkeeping misuse (double free / unref of an unreferenced
    block) — named so tests can assert the free list was protected."""


class _PagedSlot:
    """Per-request decode state: virtual positions + the page table."""

    __slots__ = ("pos", "lo", "table")

    def __init__(self):
        self.pos = 0
        self.lo = 0
        self.table: List[int] = []      # physical block ids, virtual order


class _TrieNode:
    """One cached full block. Keyed in ``_trie`` by the exact token
    prefix tuple it encodes (root..this block, inclusive)."""

    __slots__ = ("key", "block", "children")

    def __init__(self, key: Tuple[int, ...], block: int):
        self.key = key
        self.block = block
        self.children: set = set()      # child keys (one block longer)


class PagedKVPool(SlotPoolBase):
    """Block-pooled KV cache + page-table/prefix-cache manager.

    ``data`` is the jnp array ``[layers, 2, num_blocks + 1, heads,
    block_size, head_dim]`` (index 0 = scratch); the engine threads it
    through the donated paged prefill/decode steps and rebinds it here.
    ``num_slots`` bounds concurrent REQUESTS (the decode batch axis),
    ``num_blocks`` bounds their total KV footprint — with mixed lengths
    the block budget, not the slot count, is what fills first, and a
    same-device-budget paged pool admits strictly more concurrent
    requests than the dense pool (tests/test_serving_paging.py).
    """

    is_paged = True
    _slot_cls = _PagedSlot
    _capacity_noun = "virtual capacity"
    _admission_law = "prompt + max_new <= max_len"

    #: storage dtypes quantized with per-block max-abs scales (the
    #: EQuARX per-chunk scheme of the PR-10 gradient wire, applied to
    #: KV blocks): int8 now, fp8 slots in when the backend has it
    _QUANT_QMAX = {"int8": 127.0, "float8_e4m3fn": 448.0}

    def __init__(self, num_layers: int, num_slots: int, num_heads: int,
                 max_len: int, head_dim: int, *, block_size: int = 16,
                 num_blocks: Optional[int] = None, dtype="float32",
                 min_bucket: int = 8, mesh=None, mp_axis: str = "mp"):
        import jax.numpy as jnp

        if num_slots < 1:
            raise ValueError(f"num_slots must be >= 1, got {num_slots}")
        if block_size < 1 or (block_size & (block_size - 1)):
            raise ValueError(
                f"block_size must be a power of two, got {block_size}")
        if min_bucket < block_size or min_bucket % block_size:
            raise ValueError(
                f"min_bucket={min_bucket} must be a multiple of "
                f"block_size={block_size} (prefill buckets scatter whole "
                f"blocks)")
        if max_len < min_bucket:
            raise ValueError(
                f"max_len={max_len} is below min_bucket={min_bucket}: no "
                f"prompt could ever be admitted")
        self.num_layers = int(num_layers)
        self.num_slots = int(num_slots)
        self.num_heads = int(num_heads)
        self.max_len = int(max_len)
        self.head_dim = int(head_dim)
        self.block_size = int(block_size)
        self.min_bucket = int(min_bucket)
        # blocks a single request can ever hold (covers [0, max_len))
        self.max_table_len = -(-self.max_len // self.block_size)
        if num_blocks is None:
            # dense-equivalent device budget: every slot could still go
            # the full max_len (callers shrink this to realise the
            # capacity win; see README "paged vs dense")
            num_blocks = self.num_slots * self.max_table_len
        self.num_blocks = int(num_blocks)
        if self.num_blocks < self.max_table_len:
            raise ValueError(
                f"num_blocks={self.num_blocks} cannot hold even one "
                f"max-length request ({self.max_table_len} blocks)")
        # +1: physical block 0 is the reserved scratch block
        self.shape = (self.num_layers, 2, self.num_blocks + 1,
                      self.num_heads, self.block_size, self.head_dim)
        self.dtype = jnp.dtype(dtype)
        # tensor-parallel pool: the block array is head-partitioned over
        # a 1-D mp mesh ([.., H/mp, ..] per device) while every host
        # structure below — page tables, free list, refcounts, prefix
        # trie — stays replicated host-side, untouched by the mesh
        self.mesh = mesh
        self.mp_axis = str(mp_axis)
        self.shards = 1 if mesh is None else int(mesh.shape[self.mp_axis])
        if mesh is not None:
            if self.num_heads % self.shards:
                raise ValueError(
                    f"num_heads={self.num_heads} not divisible by mesh "
                    f"{self.mp_axis}={self.shards}")
            if jnp.dtype(dtype).name in self._QUANT_QMAX:
                raise ValueError(
                    f"quantized KV blocks (dtype={dtype}) are not "
                    f"supported on a tensor-parallel pool yet")
        # quantized block storage: per-block max-abs scales live in a
        # parallel [L, 2, num_blocks + 1, H] f32 array riding every
        # donated step beside the pool (gather steps multiply after the
        # pool read; the fused kernel dequantizes in-register off the
        # scalar-prefetch metadata). Scale 0 = untouched block, whose
        # dequantized content is the same zeros a fresh float pool holds.
        self.quantized = self.dtype.name in self._QUANT_QMAX
        self.qmax = self._QUANT_QMAX.get(self.dtype.name)
        self.scales_shape = (self.num_layers, 2, self.num_blocks + 1,
                             self.num_heads)
        self.scales = (jnp.zeros(self.scales_shape, jnp.float32)
                       if self.quantized else None)
        self.data = self._alloc_data()
        # min-heap: deterministic lowest-id allocation at O(log n) —
        # unlike the base slot list (num_slots entries), num_blocks is
        # production-large and a min()+remove() scan per block would
        # sit on the per-decode-cycle hot path
        self._free: List[int] = list(range(1, self.num_blocks + 1))
        self._ref: Dict[int, int] = {}            # block -> request refs
        # prefix cache: exact-prefix-keyed trie + LRU of released blocks
        # (before _init_slots: the base ctor publishes the HBM ledger
        # entry, whose in-use figure reads blocks_in_use -> _lru)
        self._trie: Dict[Tuple[int, ...], _TrieNode] = {}
        self._block_key: Dict[int, Tuple[int, ...]] = {}
        self._lru: "OrderedDict[Tuple[int, ...], _TrieNode]" = OrderedDict()
        self._init_slots()                        # request slots (base)
        # pool-local prefix stats (engine.stats() reads these without
        # scraping process-global monitor counters)
        self.prefix_hits = 0
        self.prefix_misses = 0
        self.tokens_saved = 0
        self.evictions = 0
        # hierarchical host-DRAM tier (host_tier.py), attached by the
        # engine when host_tier_bytes= is set: keys that just went
        # refcount-0 wait in _tier_pending until the scheduler's
        # once-per-cycle tier_tick() dispatches ONE batched demotion
        # gather for all of them (write-back, off the hot path)
        self.host_tier = None
        self._tier_pending: set = set()
        self.tier_hits = {"hbm": 0, "host": 0, "miss": 0}
        self.tier_degraded = 0

    def _alloc_data(self):
        """Fresh zeroed block array — head-partitioned over the mesh's
        ``mp`` axis when this is a tensor-parallel pool (each device
        holds ``[L, 2, NB+1, H/mp, bs, Dh]``), a plain single-device
        array otherwise."""
        import jax
        import jax.numpy as jnp
        if self.mesh is None:
            return jnp.zeros(self.shape, self.dtype)
        from jax.sharding import NamedSharding, PartitionSpec as P
        sh = NamedSharding(
            self.mesh, P(None, None, None, self.mp_axis, None, None))
        return jax.device_put(jnp.zeros(self.shape, self.dtype), sh)

    # -- request slots (decode batch axis: SlotPoolBase) -------------------
    def _slot_freed(self, st: _PagedSlot) -> None:
        """free() teardown: unref every block in the slot's page table.
        Refcount-0 cached blocks stay in the prefix cache (LRU,
        evictable); uncached ones return to the free list."""
        for b in st.table:
            self._unref(b)
        self._observe()

    def reset_data(self) -> None:
        """Reallocate the (donated, possibly already-deleted) device
        pool AND drop every cached block: zeroed device rows no longer
        match any trie key, so serving a prefix hit off them would
        replay garbage. Called by the scheduler's failure path after
        every in-flight slot has been failed and freed."""
        import jax.numpy as jnp
        if self._slots:
            raise RuntimeError(
                "reset_data with live slots: fail and free them first")
        self.data = self._alloc_data()
        if self.quantized:
            self.scales = jnp.zeros(self.scales_shape, jnp.float32)
        self._trie.clear()
        self._block_key.clear()
        self._lru.clear()
        self._ref.clear()
        self._free = list(range(1, self.num_blocks + 1))
        # pending demotions point at the old (possibly deleted) device
        # array — drop them; already-DEMOTED host copies stay valid
        # (content is a pure function of the prefix key)
        self._tier_pending.clear()
        self._observe()

    # (per-slot position tracking and the pow2 capacity buckets are the
    # SlotPoolBase implementations, shared verbatim with the dense pool)

    # -- block bookkeeping -------------------------------------------------
    def blocks_for(self, n_tokens: int) -> int:
        """Blocks covering virtual indices [0, n_tokens)."""
        return -(-int(n_tokens) // self.block_size)

    @property
    def blocks_in_use(self) -> int:
        """Blocks referenced by at least one page table (scratch and
        cached-but-released blocks excluded)."""
        return self.num_blocks - len(self._free) - len(self._lru)

    @property
    def blocks_available(self) -> int:
        """Free plus evictable (released cached) blocks."""
        return len(self._free) + len(self._lru)

    @property
    def cached_blocks(self) -> int:
        """Blocks currently registered in the prefix cache (referenced
        or waiting in the LRU)."""
        return len(self._trie)

    @property
    def block_storage_bytes(self) -> int:
        """PER-DEVICE bytes of the quantized-or-not block array alone —
        a tensor-parallel pool holds ``1/mp`` of the heads on each
        device, so the ledger (and every byte figure derived here)
        bills what ONE chip actually stores."""
        return int(np.prod(self.shape)) * self.dtype.itemsize \
            // self.shards

    @property
    def scales_bytes(self) -> int:
        """Device bytes of the per-block scale array (0 for float
        pools)."""
        if not self.quantized:
            return 0
        return int(np.prod(self.scales_shape)) * 4

    @property
    def capacity_bytes(self) -> int:
        """Device bytes of the whole pool — block storage PLUS the
        per-block scale array of a quantized pool, so the same-byte-
        budget capacity comparison against a float pool stays honest."""
        return self.block_storage_bytes + self.scales_bytes

    @property
    def block_bytes(self) -> int:
        """Device bytes of ONE block across every layer/kv plane —
        scale bytes included for quantized pools (the quantum the HBM
        ledger accounts paged usage in)."""
        return self.capacity_bytes // (self.num_blocks + 1)

    @classmethod
    def blocks_within_budget(cls, budget_bytes: int, *, num_layers: int,
                             num_heads: int, block_size: int,
                             head_dim: int, dtype="float32") -> int:
        """Largest ``num_blocks`` whose pool (scratch block and, for
        quantized dtypes, the per-block scale array included) fits
        ``budget_bytes`` — the same-byte-budget sizing rule the
        capacity tests and ``--kv-dtype`` comparisons use. An int8 pool
        packs ~4x the blocks of an fp32 pool into the same budget
        (minus the f32 scale overhead of ``1 / (block_size *
        head_dim)``)."""
        import jax.numpy as jnp
        itemsize = jnp.dtype(dtype).itemsize
        per_block = num_layers * 2 * num_heads * block_size * head_dim \
            * itemsize
        if jnp.dtype(dtype).name in cls._QUANT_QMAX:
            per_block += num_layers * 2 * num_heads * 4
        # num_blocks + 1 physical blocks (scratch) must fit
        return max(0, int(budget_bytes) // per_block - 1)

    @property
    def bytes_in_use(self) -> int:
        """Block-granular override of the base's whole-slot accounting:
        only blocks referenced by live page tables count."""
        return self.blocks_in_use * self.block_bytes

    def can_admit(self, n_tokens: int) -> bool:
        """Admission gate: enough free + evictable blocks to hold the
        request's first ``n_tokens`` tokens. Growth past that is the
        preemption policy's problem, so a head request never waits for
        its WORST case — the whole point of paging."""
        return self.blocks_available >= self.blocks_for(n_tokens)

    def _observe(self) -> None:
        stat_observe("serving/kv_blocks_in_use", self.blocks_in_use)
        # block-granular HBM ledger refresh: _observe already fires at
        # every block-count change (alloc/unref/evict/free/reset)
        self._update_ledger()

    def _alloc_block(self) -> int:
        if not self._free:
            self._evict_one()            # raises PoolExhaustedError
        b = heapq.heappop(self._free)    # deterministic, like slot alloc
        self._ref[b] = 1
        if self.quantized:
            # a recycled block carries its previous tenant's per-block
            # max-abs scale, and _quant_append only GROWS scales
            # (scatter-max) — growth appends into this block would
            # quantize fresh K/V at an arbitrarily coarse stale scale.
            # Zero it at allocation (prefill rewrites it anyway;
            # LRU-adopted cached blocks never pass through here, so
            # their valid scales survive). Lazy device op, no sync.
            self.scales = self.scales.at[:, :, b].set(0.0)
        return b

    def _unref(self, b: int) -> None:
        rc = self._ref.get(b, 0)
        if rc <= 0:
            raise BlockError(
                f"block {b} is not referenced (double free would corrupt "
                f"the free list)")
        self._ref[b] = rc - 1
        if rc == 1:
            key = self._block_key.get(b)
            if key is not None and key in self._trie:
                # released but cached: joins the LRU (most-recent end),
                # reusable by a later prefix hit until evicted
                self._lru[key] = self._trie[key]
                if self.host_tier is not None:
                    # write-back candidate: demoted at the next
                    # tier_tick() if still evictable then
                    self._tier_pending.add(key)
            else:
                heapq.heappush(self._free, b)

    def _evict_one(self) -> None:
        """Reclaim the least-recently-released cached block (and drop
        its now-unreachable cached descendants)."""
        if not self._lru:
            raise PoolExhaustedError(
                f"all {self.num_blocks} blocks are referenced and the "
                f"prefix cache has nothing to evict")
        key = next(iter(self._lru))
        self._drop_node(key)
        self.evictions += 1
        stat_add("serving/prefix_evict")

    def _drop_node(self, key: Tuple[int, ...]) -> None:
        """Unregister the cached block at ``key`` and its subtree. A
        refcount-0 block returns to the free list; a block still held
        by a request merely loses its cache membership (its owner frees
        it normally later)."""
        node = self._trie.pop(key, None)
        if node is None:
            return
        self._lru.pop(key, None)
        self._block_key.pop(node.block, None)
        if self._ref.get(node.block, 0) == 0:
            heapq.heappush(self._free, node.block)
        parent = self._trie.get(key[:-self.block_size])
        if parent is not None:
            parent.children.discard(key)
        for child in list(node.children):
            self._drop_node(child)

    # -- hierarchical host tier (host_tier.py) -----------------------------
    @property
    def host_block_nbytes(self) -> int:
        """HOST bytes of one demoted block — FULL heads (a
        tensor-parallel pool's demotion gathers the global value, so
        the host entry is shard-agnostic), no scratch, no sharding
        divisor."""
        return (self.num_layers * 2 * self.num_heads * self.block_size
                * self.head_dim * self.dtype.itemsize)

    @property
    def host_scale_nbytes(self) -> int:
        """Host bytes of one block's per-block scale row (0 for float
        pools)."""
        return self.num_layers * 2 * self.num_heads * 4 \
            if self.quantized else 0

    def attach_host_tier(self, tier) -> None:
        """Bind a :class:`~.host_tier.HostBlockPool` as the spill
        target for LRU-evicted refcount-0 blocks (engine ctor,
        ``host_tier_bytes=``). Eagerly compiles the tier's batched
        gather/scatter for every pow2 width it can ever use — a
        first-use compile would otherwise stall the scheduler thread
        (and every decode slot with it) for ~100ms mid-serving."""
        self.host_tier = tier
        m = 1
        while True:
            ids = np.zeros(m, np.int32)
            blk = self.data[:, :, ids]                 # demote gather
            self.data = self.data.at[:, :, ids].set(blk)   # adopt
            if self.quantized:
                sca = self.scales[:, :, ids]
                self.scales = self.scales.at[:, :, ids].set(sca)
            if m >= self.num_blocks:
                break
            m *= 2

    def tier_tick(self) -> None:
        """Once-per-cycle demotion pump (scheduler thread, start of
        cycle): batch every key that went refcount-0 since the last
        tick and is STILL evictable into ONE lazy device gather, and
        hand it to the tier's spiller thread. The gather
        ``data[:, :, ids]`` is an independent non-donated array whose
        value is captured before any later donated step can delete the
        pool storage, so the spiller's blocking copy never races XLA
        donation. Dispatch-only — no device sync on this thread."""
        tier = self.host_tier
        if tier is None or not self._tier_pending:
            return
        pending, self._tier_pending = self._tier_pending, set()
        keys = [k for k in pending if k in self._lru and not tier.has(k)]
        if not keys:
            return
        # pow2-pad the gather width (repeat the last id — the spiller
        # only reads the first len(keys) lanes): an eager gather
        # compiles once per distinct index length, and a per-batch
        # shape would put a fresh ~100ms XLA compile on the scheduler
        # thread every few cycles. Same bucket discipline as prefill.
        raw = [self._trie[k].block for k in keys]
        m = 1 << (len(raw) - 1).bit_length()
        ids = np.asarray(raw + [raw[-1]] * (m - len(raw)), np.int32)
        blk = self.data[:, :, ids]        # lazy batched gather
        sca = self.scales[:, :, ids] if self.quantized else None
        tier.spill(keys, blk, sca)

    def tier_match(self, tokens) -> Tuple[List[Tuple[int, ...]], int]:
        """Continue :meth:`match_prefix`'s walk into the HOST tier:
        the chain of demoted full blocks that extends the device-cached
        prefix of ``tokens`` (same proper-prefix cap). Returns
        ``(host_keys, covered_tokens)`` where ``covered_tokens`` counts
        the device+host contiguous coverage — the scheduler's
        promotion gate mirrors the engine's uncovered-tail heuristic
        with it. Read-only."""
        tier = self.host_tier
        if tier is None:
            return [], 0
        toks = tuple(int(t) for t in tokens)
        bs = self.block_size
        host_keys: List[Tuple[int, ...]] = []
        covered = 0
        for i in range(1, (len(toks) - 1) // bs + 1):
            key = toks[:i * bs]
            if key in self._trie:
                covered = i * bs
                continue
            if tier.has(key):
                host_keys.append(key)
                covered = i * bs
            else:
                break
        return host_keys, covered

    def adopt_promotion(self, ticket) -> bool:
        """Land a staged promotion (scheduler thread, the cycle the
        ticket's H2D copy completed): allocate device blocks, scatter
        the staged batch into them (lazy ``.at[].set`` — no new trace
        site, no sync), and republish each key as a refcount-0 cached
        trie node, exactly as if the blocks had never been evicted.
        The content-canonical invariant makes every overlap safe: keys
        republished on the device while the copy staged are simply
        skipped (identical bytes), and exhaustion degrades to adopting
        the chain PREFIX that fits — or to a plain miss — never to an
        error on the serving path."""
        tier = self.host_tier
        if tier is None or ticket is None:
            return False
        if ticket.adopted:
            return True
        if ticket.failed or not ticket.staged_keys:
            tier.ticket_done(ticket)
            return False
        keep = [i for i, k in enumerate(ticket.staged_keys)
                if k not in self._trie]
        if not keep:
            # the whole chain was republished on the device while the
            # copy staged — identical bytes by the content-canonical
            # invariant, nothing to land
            ticket.adopted = True
            tier.ticket_done(ticket)
            return True
        ids: List[int] = []
        try:
            for _ in keep:
                ids.append(self._alloc_block())
        except PoolExhaustedError:
            pass                          # adopt the prefix that fits
        keep = keep[:len(ids)]
        if not keep:
            self.tier_degraded += 1
            stat_add("serving/tier_degraded")
            tier.ticket_done(ticket)
            return False
        # uniform pow2-wide gather + scatter, whatever subset of the
        # chain is being landed: the staged batch is already pow2-padded
        # (promoter side), and padding BOTH index vectors by repeating
        # their last entry keeps every adoption on one compiled shape
        # per bucket — duplicate scatter lanes write identical bytes,
        # so the result is unchanged. Without this, each distinct chain
        # length would eagerly compile a fresh gather/scatter pair on
        # the scheduler thread, stalling decode for ~100ms a pop.
        m = int(ticket.staged.shape[2])
        sel = np.asarray(keep + [keep[-1]] * (m - len(keep)), np.int32)
        idx = np.asarray(ids + [ids[-1]] * (m - len(ids)), np.int32)
        blk = ticket.staged[:, :, sel]
        sca = ticket.staged_scales
        self.data = self.data.at[:, :, idx].set(blk)
        if self.quantized and sca is not None:
            # adopted blocks carry their ORIGINAL per-block scales —
            # overwrite the zeros _alloc_block just staged
            self.scales = self.scales.at[:, :, idx].set(sca[:, :, sel])
        for k_i, b in zip(keep, ids):
            key = ticket.staged_keys[k_i]
            self._ref[b] = 0              # cache-resident, unreferenced
            node = _TrieNode(key, b)
            self._trie[key] = node
            self._block_key[b] = key
            parent = self._trie.get(key[:-self.block_size])
            if parent is not None:
                parent.children.add(key)
            self._lru[key] = node         # evictable until admitted
        ticket.adopted = True
        tier.note_promoted(ticket, len(keep))
        tier.ticket_done(ticket)
        self._observe()
        return True

    def note_tier_hit(self, kind: str) -> None:
        """Classify one admission for the tiered hit split: ``hbm``
        (device trie hit), ``host`` (hit served through a promotion),
        or ``miss``. Counted by the engine on every paged admission so
        the split keys exist tier or no tier."""
        self.tier_hits[kind] = self.tier_hits.get(kind, 0) + 1
        stat_add(f"serving/tier_hit_{kind}")

    # -- admission: prefix matching + table setup --------------------------
    def match_prefix(self, tokens) -> List[int]:
        """Longest chain of cached full blocks covering a PROPER prefix
        of ``tokens`` — capped at ``(len - 1) // block_size`` blocks so
        at least one token is always recomputed (its forward pass is
        what produces the next-token logits, and the cap is also what
        keeps every write strictly past the shared region, making COW a
        guard rail instead of a hot path). Returns the physical block
        ids, longest match first-to-last. Read-only."""
        toks = tuple(int(t) for t in tokens)
        bs = self.block_size
        blocks: List[int] = []
        for i in range(1, (len(toks) - 1) // bs + 1):
            node = self._trie.get(toks[:i * bs])
            if node is None:
                break
            blocks.append(node.block)
        return blocks

    def admit_cached(self, slot: int, blocks: List[int]) -> None:
        """Seed the slot's page table with matched prefix blocks
        (refcount++ each; a block leaves the LRU while referenced)."""
        st = self._require(slot)
        if st.table:
            raise BlockError(f"slot {slot} already has a page table")
        for b in blocks:
            rc = self._ref.get(b, 0)
            self._ref[b] = rc + 1
            if rc == 0:
                self._lru.pop(self._block_key.get(b), None)
        st.table = list(blocks)
        self.prefix_hits += 1
        self.tokens_saved += len(blocks) * self.block_size
        stat_add("serving/prefix_hit")
        stat_add("serving/prefill_tokens_saved",
                 len(blocks) * self.block_size)
        self._observe()

    def admit_fresh(self, slot: int, n_tokens: int) -> List[int]:
        """Allocate the page table covering ``[0, n_tokens)`` for a
        prefix-miss prefill. All-or-nothing: on exhaustion the partial
        allocation is rolled back and :class:`PoolExhaustedError`
        propagates (admission re-tries next cycle)."""
        st = self._require(slot)
        if st.table:
            raise BlockError(f"slot {slot} already has a page table")
        got: List[int] = []
        try:
            for _ in range(self.blocks_for(n_tokens)):
                got.append(self._alloc_block())
        except PoolExhaustedError:
            for b in got:
                self._unref(b)
            raise
        st.table = got
        self.prefix_misses += 1
        stat_add("serving/prefix_miss")
        self._observe()
        return list(got)

    def register_prefix(self, slot: int, tokens) -> None:
        """Publish the slot's full token blocks into the prefix cache.
        Called after a prefill WROTE them; an existing entry for the
        same prefix stays canonical (this slot's duplicate block simply
        remains privately owned)."""
        st = self._require(slot)
        toks = tuple(int(t) for t in tokens)
        bs = self.block_size
        for i in range(len(toks) // bs):
            key = toks[:(i + 1) * bs]
            if key in self._trie:
                continue
            block = st.table[i]
            if block in self._block_key:
                continue                  # already published elsewhere
            self._trie[key] = _TrieNode(key, block)
            self._block_key[block] = key
            parent = self._trie.get(key[:-bs])
            if parent is not None:
                parent.children.add(key)

    def unpublish_from(self, slot: int, pos: int) -> None:
        """Drop any prefix-cache registration of the slot's blocks
        covering virtual index ``pos`` onward — the speculative-decode
        rollback guard: rows a rejected draft wrote must not leave a
        published block whose device content no longer matches its
        token-prefix key. Structurally the write path already unshares
        (COW) and unregisters (``_ensure_block``) before any write, so
        this is the same airtight-cheap insurance, called by the
        scheduler after a rollback."""
        st = self._require(slot)
        for vb in range(int(pos) // self.block_size, len(st.table)):
            key = self._block_key.get(st.table[vb])
            if key is not None:
                self._drop_node(key)

    # -- decode-time growth + copy-on-write --------------------------------
    def ensure_writable(self, slot: int) -> Optional[Tuple[int, int]]:
        """Guarantee the block holding virtual index ``pos`` exists and
        is exclusively owned before the decode step scatters into it.
        Returns ``(dst, src)`` when the engine must device-copy a
        shared block first (copy-on-write append), else None. May raise
        :class:`PoolExhaustedError` — the scheduler's preemption
        trigger."""
        st = self._require(slot)
        return self._ensure_block(slot, st, st.pos // self.block_size)

    def ensure_writable_range(self, slot: int,
                              last_pos: int) -> List[Tuple[int, int]]:
        """Chunked-prefill variant: guarantee EVERY block covering
        virtual indices ``[pos, last_pos]`` exists and is exclusively
        owned (a chunk scatters a run of positions in one fused
        launch). Returns the copy-on-write ``(dst, src)`` orders, in
        virtual-block order. May raise :class:`PoolExhaustedError`
        mid-growth — already-granted blocks stay on the table (they are
        freed with the slot if the scheduler preempts it), and any COW
        orders collected BEFORE the failure ride on the exception as
        ``partial_cows``: the table swap already happened, so the
        caller must still perform those device copies — a retry after
        preemption sees the swapped (refcount-1) block and would never
        re-order the copy."""
        st = self._require(slot)
        if last_pos < st.pos:
            raise ValueError(
                f"slot {slot}: range end {last_pos} precedes pos {st.pos}")
        cows: List[Tuple[int, int]] = []
        for vb in range(st.pos // self.block_size,
                        last_pos // self.block_size + 1):
            try:
                cow = self._ensure_block(slot, st, vb)
            except PoolExhaustedError as e:
                e.partial_cows = list(cows)
                raise
            if cow is not None:
                cows.append(cow)
        return cows

    def _ensure_block(self, slot: int, st: _PagedSlot,
                      vb: int) -> Optional[Tuple[int, int]]:
        if vb > len(st.table):
            raise RuntimeError(
                f"slot {slot}: page table has {len(st.table)} blocks but "
                f"virtual block {vb} is needed — positions outran "
                f"allocation")
        if vb == len(st.table):
            st.table.append(self._alloc_block())
            self._observe()
            return None
        b = st.table[vb]
        if self._ref.get(b, 0) > 1:
            nb = self._alloc_block()      # may raise: caller preempts
            st.table[vb] = nb
            self._unref(b)
            self._observe()
            return (nb, b)
        key = self._block_key.get(b)
        if key is not None:
            # about to append into a cached block in place: its content
            # will no longer match its prefix key, so unregister it
            # (structurally unreachable — reuse is capped below every
            # write position — but cheap to keep airtight)
            self._drop_node(key)
        return None

    def table_bucket(self, slot: int) -> int:
        """The slot's decode-trace bucket: next pow2 over its page-table
        length, capped at ``max_table_len`` — ONE decode trace per
        bucket, O(log max_table_len) buckets total."""
        n = max(1, len(self._require(slot).table))
        t = 1
        while t < n:
            t *= 2
        return min(t, self.max_table_len)

    def table_array(self, bucket: int, slots) -> np.ndarray:
        """Dense int32 ``[num_slots, bucket]`` page-table operand for
        the decode step. Rows of slots outside ``slots`` (and padding
        past a member's table) read 0 — the scratch block, whose
        gathered garbage the ``[lo, pos]`` mask hides and whose writes
        nobody reads."""
        out = np.zeros((self.num_slots, int(bucket)), np.int32)
        for slot in slots:
            table = self._require(slot).table
            if len(table) > bucket:
                raise RuntimeError(
                    f"slot {slot}: table length {len(table)} exceeds its "
                    f"bucket {bucket}")
            out[slot, :len(table)] = table
        return out

    def slot_table(self, slot: int) -> List[int]:
        return list(self._require(slot).table)

    def _require(self, slot: int) -> _PagedSlot:
        st = self._slots.get(slot)
        if st is None:
            raise ValueError(f"slot {slot} is not allocated")
        return st

    def __repr__(self):
        return (f"<PagedKVPool blocks={self.blocks_in_use}/"
                f"{self.num_blocks} x{self.block_size} "
                f"active={self.n_active}/{self.num_slots} "
                f"cached={len(self._trie)}>")
