"""Zero-dependency ops HTTP server: the wire end of the telemetry spine.

PR 13 made every telemetry island scrapeable in-process; this module
puts that surface on a socket — stdlib ``http.server`` only (the
container bakes in no web framework, and an ops endpoint that needs one
is an ops endpoint that is down when pip is), threaded, bound to an
ephemeral localhost port by default:

======================  ==================================================
``GET /metrics``        Prometheus text exposition v0.0.4
                        (``MetricsRegistry.to_prometheus``)
``GET /varz``           the JSON registry snapshot
                        (``MetricsRegistry.snapshot``)
``GET /statusz``        the human ops console (``metrics.statusz()``)
``GET /healthz``        200 when the target is fully healthy, 503 with a
                        JSON body naming the poisoned replicas otherwise
``GET /readyz``         200 while the target can accept work (>= 1
                        healthy replica, not closed) — a degraded fleet
                        is unhealthy but still ready
``GET /tracez``         recent + tail-sampled request traces per replica
                        (``FlightRecorder.tail_traces``) + the SLO report
``GET /timeline``       the merged chrome-trace document
                        (``profiler.timeline.unified_trace_doc``)
======================  ==================================================

Attach it to a :class:`~.engine.GenerationEngine`, an
:class:`~.fleet.EngineFleet`, or nothing (process-level metrics only)::

    srv = OpsServer(target=fleet, slo=tracker).start()
    print(srv.url)          # http://127.0.0.1:<ephemeral>
    ...
    srv.close()

Routing is a pluggable table: built-ins register through the same
``add_route(method, path, handler)`` seam extensions use, so the
inference front door (:mod:`.frontdoor`) mounts ``POST
/v1/completions`` beside ``/metrics`` in one process on one port.

Handler contract (the ``ops-handler-sync`` self-lint rule enforces the
letter of it): handlers NEVER touch the device and never block on the
scheduler — everything they serve comes from scrape-time collectors,
host rings and host counters. A handler exception returns a 500 body;
it must not kill the serving thread (an ops surface that dies with the
thing it observes is useless at exactly 3am). Request logging is
silenced — a 5s Prometheus scrape interval must not spam stderr.
"""
from __future__ import annotations

import json
import threading
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from typing import Any, Dict, Optional, Tuple

from ..framework import metrics as _metrics

__all__ = ["OpsServer"]


class _OpsHandler(BaseHTTPRequestHandler):
    server_version = "paddle-ops/1"

    def log_message(self, *args):                        # noqa: D102
        pass

    def _send(self, code: int, ctype: str, body) -> None:
        data = body if isinstance(body, bytes) else str(body).encode()
        self.send_response(code)
        self.send_header("Content-Type", ctype)
        self.send_header("Content-Length", str(len(data)))
        self.end_headers()
        self.wfile.write(data)

    def _send_json(self, code: int, doc: Any) -> None:
        self._send(code, "application/json",
                   json.dumps(doc, default=repr))

    def _dispatch(self, method: str) -> None:
        """Route one request through the server's handler table. An
        unknown (method, path) answers the canonical 404; a raising
        handler answers 500 — the serving thread lives on either way."""
        ops = self.server.ops                            # type: ignore
        path = self.path.split("?", 1)[0].rstrip("/") or "/"
        try:
            handler = ops.route(method, path)
            if handler is None:
                self._send_json(404, {"error": f"no such endpoint "
                                      f"{path!r}", "see": "/"})
                return
            handler(self)
        except Exception as e:                           # noqa: BLE001
            # a broken section answers 500; the serving thread lives on
            try:
                self._send_json(500, {"error": repr(e), "path": path})
            except Exception:                            # noqa: BLE001
                pass

    def do_GET(self) -> None:                            # noqa: N802
        self._dispatch("GET")

    def do_POST(self) -> None:                           # noqa: N802
        self._dispatch("POST")


class OpsServer:
    """One process, one ops surface: a threaded stdlib HTTP server over
    the metrics registry, optionally bound to an engine or fleet for
    health/traces.

    ``target`` may be a ``GenerationEngine``, an ``EngineFleet`` or
    ``None``; ``slo`` an :class:`~.slo.SLOTracker` whose report rides
    ``/tracez``. ``port=0`` binds an ephemeral port (read it back from
    ``srv.port`` / ``srv.url``)."""

    def __init__(self, target: Optional[Any] = None,
                 host: str = "127.0.0.1", port: int = 0,
                 registry: Optional[_metrics.MetricsRegistry] = None,
                 slo: Optional[Any] = None):
        self._target = target
        self._slo = slo
        self._registry = registry if registry is not None \
            else _metrics.registry()
        self._host = host
        self._port = int(port)
        self._httpd: Optional[ThreadingHTTPServer] = None
        self._thread: Optional[threading.Thread] = None
        # the route table: (METHOD, path) -> handler(request_handler).
        # Built-ins register through the same seam extensions use
        # (add_route) — the inference front door mounts POST
        # /v1/completions here so /metrics and the completions API
        # share one process and one port.
        self._routes: Dict[Tuple[str, str], Any] = {}
        self._register_builtin_routes()

    # -- route table --------------------------------------------------------
    def add_route(self, method: str, path: str, handler) -> None:
        """Mount ``handler(request_handler)`` at (``method``, ``path``).

        The handler receives the live ``BaseHTTPRequestHandler`` and
        answers via ``_send``/``_send_json`` (POST bodies via
        ``request_handler.rfile`` + the Content-Length header). Route
        handlers inherit the ops-surface contract (the
        ``ops-handler-sync`` self-lint rule): never touch the device,
        never block on the scheduler loop — engine HANDLES (submit /
        stream) are the only legal way in. Registering an existing
        (method, path) replaces it; unknown paths keep answering the
        canonical 404."""
        path = path.split("?", 1)[0].rstrip("/") or "/"
        self._routes[(method.upper(), path)] = handler

    def route(self, method: str, path: str) -> Optional[Any]:
        """The handler mounted at (``method``, ``path``), or None."""
        return self._routes.get((method.upper(), path))

    def endpoints(self) -> list:
        """Sorted unique route paths (the ``/`` index body)."""
        return sorted({p for _, p in self._routes if p != "/"})

    def _register_builtin_routes(self) -> None:
        def _metrics_h(h):
            h._send(200, "text/plain; version=0.0.4; charset=utf-8",
                    self.registry.to_prometheus())

        def _varz(h):
            h._send_json(200, self.registry.snapshot())

        def _statusz(h):
            h._send(200, "text/plain; charset=utf-8",
                    self.registry.statusz())

        def _healthz(h):
            ok, doc = self.health()
            h._send_json(200 if ok else 503, doc)

        def _readyz(h):
            ok, doc = self.ready()
            h._send_json(200 if ok else 503, doc)

        def _tracez(h):
            h._send_json(200, self.tracez())

        def _timeline(h):
            from ..profiler.timeline import unified_trace_doc
            h._send_json(200, unified_trace_doc())

        def _index(h):
            h._send_json(200, {"endpoints": self.endpoints()})

        self.add_route("GET", "/metrics", _metrics_h)
        self.add_route("GET", "/varz", _varz)
        self.add_route("GET", "/statusz", _statusz)
        self.add_route("GET", "/healthz", _healthz)
        self.add_route("GET", "/readyz", _readyz)
        self.add_route("GET", "/tracez", _tracez)
        self.add_route("GET", "/timeline", _timeline)
        self.add_route("GET", "/", _index)

    # -- lifecycle ----------------------------------------------------------
    def start(self) -> "OpsServer":
        if self._httpd is not None:
            return self
        httpd = ThreadingHTTPServer((self._host, self._port),
                                    _OpsHandler)
        httpd.daemon_threads = True
        httpd.ops = self                                 # type: ignore
        self._httpd = httpd
        self._thread = threading.Thread(
            target=httpd.serve_forever, daemon=True,
            name=f"paddle-ops-server:{httpd.server_address[1]}")
        self._thread.start()
        return self

    def close(self) -> None:
        httpd, self._httpd = self._httpd, None
        thread, self._thread = self._thread, None
        if httpd is not None:
            httpd.shutdown()
            httpd.server_close()
        if thread is not None:
            thread.join(timeout=5)

    def __enter__(self):
        return self.start()

    def __exit__(self, *exc):
        self.close()
        return False

    # -- addresses ----------------------------------------------------------
    @property
    def port(self) -> Optional[int]:
        if self._httpd is None:
            return None
        return self._httpd.server_address[1]

    @property
    def url(self) -> Optional[str]:
        if self._httpd is None:
            return None
        return f"http://{self._host}:{self.port}"

    @property
    def registry(self) -> _metrics.MetricsRegistry:
        return self._registry

    # -- target introspection (host-only, fault-isolated) -------------------
    def _target_stats(self) -> Tuple[Optional[dict], Optional[str]]:
        t = self._target
        if t is None:
            return None, None
        try:
            return dict(t.stats()), None
        except Exception as e:                           # noqa: BLE001
            return None, repr(e)

    def health(self) -> Tuple[bool, Dict[str, Any]]:
        """Full health: every replica up, target not closed. A fleet
        with ANY poisoned replica answers 503 here (and 200 on
        ``/readyz`` while at least one replica still serves)."""
        t = self._target
        if t is None:
            return True, {"ok": True, "target": None}
        if getattr(t, "_closed", False):
            return False, {"ok": False, "reason": "target closed"}
        s, err = self._target_stats()
        if s is None:
            return False, {"ok": False, "reason": err}
        if "replicas_total" in s:
            unhealthy = [r["replica"] for r in s.get("replicas", ())
                         if not r.get("healthy")]
            ok = s["replicas_healthy"] == s["replicas_total"] \
                and not unhealthy
            return ok, {"ok": ok,
                        "replicas_healthy": s["replicas_healthy"],
                        "replicas_total": s["replicas_total"],
                        "unhealthy": unhealthy}
        return True, {"ok": True,
                      "queue_depth": s.get("queue_depth"),
                      "active_requests": s.get("active_requests")}

    def ready(self) -> Tuple[bool, Dict[str, Any]]:
        """Readiness: can the target still accept a submit? A degraded
        fleet (1 of 2 replicas poisoned) is NOT healthy but IS ready."""
        t = self._target
        if t is None:
            return True, {"ready": True, "target": None}
        if getattr(t, "_closed", False):
            return False, {"ready": False, "reason": "target closed"}
        s, err = self._target_stats()
        if s is None:
            return False, {"ready": False, "reason": err}
        if "replicas_total" in s:
            ok = s["replicas_healthy"] >= 1
            return ok, {"ready": ok,
                        "replicas_healthy": s["replicas_healthy"],
                        "replicas_total": s["replicas_total"]}
        return True, {"ready": True}

    def _recorders(self) -> Dict[str, Any]:
        """Replica-keyed flight recorders (fault-isolated)."""
        t = self._target
        if t is None:
            return {}
        if hasattr(t, "replicas"):
            out = {}
            for i, eng in enumerate(t.replicas):
                try:
                    out[str(i)] = eng.flight_recorder
                except Exception:                        # noqa: BLE001
                    continue
            return out
        rec = getattr(t, "flight_recorder", None)
        return {"0": rec} if rec is not None else {}

    def tracez(self) -> Dict[str, Any]:
        """The /tracez document: per-replica tail-sampled + recent
        traces, plus the SLO report when a tracker is attached."""
        engines: Dict[str, Any] = {}
        for key, rec in self._recorders().items():
            try:
                engines[key] = rec.tail_traces()
            except Exception as e:                       # noqa: BLE001
                engines[key] = {"error": repr(e)}
        doc: Dict[str, Any] = {"engines": engines}
        if self._slo is not None:
            try:
                doc["slo"] = self._slo.report()
            except Exception as e:                       # noqa: BLE001
                doc["slo"] = {"error": repr(e)}
        return doc

    def __repr__(self):
        return f"<OpsServer url={self.url} target={self._target!r}>"
