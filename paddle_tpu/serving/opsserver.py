"""Zero-dependency ops HTTP server: the wire end of the telemetry spine.

PR 13 made every telemetry island scrapeable in-process; this module
puts that surface on a socket — stdlib ``http.server`` only (the
container bakes in no web framework, and an ops endpoint that needs one
is an ops endpoint that is down when pip is), threaded, bound to an
ephemeral localhost port by default:

======================  ==================================================
``GET /metrics``        Prometheus text exposition v0.0.4
                        (``MetricsRegistry.to_prometheus``)
``GET /varz``           the JSON registry snapshot
                        (``MetricsRegistry.snapshot``)
``GET /statusz``        the human ops console (``metrics.statusz()``)
``GET /healthz``        200 when the target is fully healthy, 503 with a
                        JSON body naming the poisoned replicas otherwise
``GET /readyz``         200 while the target can accept work (>= 1
                        healthy replica, not closed) — a degraded fleet
                        is unhealthy but still ready
``GET /tracez``         recent + tail-sampled request traces per replica
                        (``FlightRecorder.tail_traces``) + the SLO report
``GET /timeline``       the merged chrome-trace document
                        (``profiler.timeline.unified_trace_doc``)
======================  ==================================================

Attach it to a :class:`~.engine.GenerationEngine`, an
:class:`~.fleet.EngineFleet`, or nothing (process-level metrics only)::

    srv = OpsServer(target=fleet, slo=tracker).start()
    print(srv.url)          # http://127.0.0.1:<ephemeral>
    ...
    srv.close()

Handler contract (the ``ops-handler-sync`` self-lint rule enforces the
letter of it): handlers NEVER touch the device and never block on the
scheduler — everything they serve comes from scrape-time collectors,
host rings and host counters. A handler exception returns a 500 body;
it must not kill the serving thread (an ops surface that dies with the
thing it observes is useless at exactly 3am). Request logging is
silenced — a 5s Prometheus scrape interval must not spam stderr.
"""
from __future__ import annotations

import json
import threading
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from typing import Any, Dict, Optional, Tuple

from ..framework import metrics as _metrics

__all__ = ["OpsServer"]


class _OpsHandler(BaseHTTPRequestHandler):
    server_version = "paddle-ops/1"

    def log_message(self, *args):                        # noqa: D102
        pass

    def _send(self, code: int, ctype: str, body) -> None:
        data = body if isinstance(body, bytes) else str(body).encode()
        self.send_response(code)
        self.send_header("Content-Type", ctype)
        self.send_header("Content-Length", str(len(data)))
        self.end_headers()
        self.wfile.write(data)

    def _send_json(self, code: int, doc: Any) -> None:
        self._send(code, "application/json",
                   json.dumps(doc, default=repr))

    def do_GET(self) -> None:                            # noqa: N802
        ops = self.server.ops                            # type: ignore
        path = self.path.split("?", 1)[0].rstrip("/") or "/"
        try:
            if path == "/metrics":
                self._send(200,
                           "text/plain; version=0.0.4; charset=utf-8",
                           ops.registry.to_prometheus())
            elif path == "/varz":
                self._send_json(200, ops.registry.snapshot())
            elif path == "/statusz":
                self._send(200, "text/plain; charset=utf-8",
                           ops.registry.statusz())
            elif path == "/healthz":
                ok, doc = ops.health()
                self._send_json(200 if ok else 503, doc)
            elif path == "/readyz":
                ok, doc = ops.ready()
                self._send_json(200 if ok else 503, doc)
            elif path == "/tracez":
                self._send_json(200, ops.tracez())
            elif path == "/timeline":
                from ..profiler.timeline import unified_trace_doc
                self._send_json(200, unified_trace_doc())
            elif path == "/":
                self._send_json(200, {"endpoints": sorted(
                    ("/metrics", "/varz", "/statusz", "/healthz",
                     "/readyz", "/tracez", "/timeline"))})
            else:
                self._send_json(404, {"error": f"no such endpoint "
                                      f"{path!r}", "see": "/"})
        except Exception as e:                           # noqa: BLE001
            # a broken section answers 500; the serving thread lives on
            try:
                self._send_json(500, {"error": repr(e), "path": path})
            except Exception:                            # noqa: BLE001
                pass


class OpsServer:
    """One process, one ops surface: a threaded stdlib HTTP server over
    the metrics registry, optionally bound to an engine or fleet for
    health/traces.

    ``target`` may be a ``GenerationEngine``, an ``EngineFleet`` or
    ``None``; ``slo`` an :class:`~.slo.SLOTracker` whose report rides
    ``/tracez``. ``port=0`` binds an ephemeral port (read it back from
    ``srv.port`` / ``srv.url``)."""

    def __init__(self, target: Optional[Any] = None,
                 host: str = "127.0.0.1", port: int = 0,
                 registry: Optional[_metrics.MetricsRegistry] = None,
                 slo: Optional[Any] = None):
        self._target = target
        self._slo = slo
        self._registry = registry if registry is not None \
            else _metrics.registry()
        self._host = host
        self._port = int(port)
        self._httpd: Optional[ThreadingHTTPServer] = None
        self._thread: Optional[threading.Thread] = None

    # -- lifecycle ----------------------------------------------------------
    def start(self) -> "OpsServer":
        if self._httpd is not None:
            return self
        httpd = ThreadingHTTPServer((self._host, self._port),
                                    _OpsHandler)
        httpd.daemon_threads = True
        httpd.ops = self                                 # type: ignore
        self._httpd = httpd
        self._thread = threading.Thread(
            target=httpd.serve_forever, daemon=True,
            name=f"paddle-ops-server:{httpd.server_address[1]}")
        self._thread.start()
        return self

    def close(self) -> None:
        httpd, self._httpd = self._httpd, None
        thread, self._thread = self._thread, None
        if httpd is not None:
            httpd.shutdown()
            httpd.server_close()
        if thread is not None:
            thread.join(timeout=5)

    def __enter__(self):
        return self.start()

    def __exit__(self, *exc):
        self.close()
        return False

    # -- addresses ----------------------------------------------------------
    @property
    def port(self) -> Optional[int]:
        if self._httpd is None:
            return None
        return self._httpd.server_address[1]

    @property
    def url(self) -> Optional[str]:
        if self._httpd is None:
            return None
        return f"http://{self._host}:{self.port}"

    @property
    def registry(self) -> _metrics.MetricsRegistry:
        return self._registry

    # -- target introspection (host-only, fault-isolated) -------------------
    def _target_stats(self) -> Tuple[Optional[dict], Optional[str]]:
        t = self._target
        if t is None:
            return None, None
        try:
            return dict(t.stats()), None
        except Exception as e:                           # noqa: BLE001
            return None, repr(e)

    def health(self) -> Tuple[bool, Dict[str, Any]]:
        """Full health: every replica up, target not closed. A fleet
        with ANY poisoned replica answers 503 here (and 200 on
        ``/readyz`` while at least one replica still serves)."""
        t = self._target
        if t is None:
            return True, {"ok": True, "target": None}
        if getattr(t, "_closed", False):
            return False, {"ok": False, "reason": "target closed"}
        s, err = self._target_stats()
        if s is None:
            return False, {"ok": False, "reason": err}
        if "replicas_total" in s:
            unhealthy = [r["replica"] for r in s.get("replicas", ())
                         if not r.get("healthy")]
            ok = s["replicas_healthy"] == s["replicas_total"] \
                and not unhealthy
            return ok, {"ok": ok,
                        "replicas_healthy": s["replicas_healthy"],
                        "replicas_total": s["replicas_total"],
                        "unhealthy": unhealthy}
        return True, {"ok": True,
                      "queue_depth": s.get("queue_depth"),
                      "active_requests": s.get("active_requests")}

    def ready(self) -> Tuple[bool, Dict[str, Any]]:
        """Readiness: can the target still accept a submit? A degraded
        fleet (1 of 2 replicas poisoned) is NOT healthy but IS ready."""
        t = self._target
        if t is None:
            return True, {"ready": True, "target": None}
        if getattr(t, "_closed", False):
            return False, {"ready": False, "reason": "target closed"}
        s, err = self._target_stats()
        if s is None:
            return False, {"ready": False, "reason": err}
        if "replicas_total" in s:
            ok = s["replicas_healthy"] >= 1
            return ok, {"ready": ok,
                        "replicas_healthy": s["replicas_healthy"],
                        "replicas_total": s["replicas_total"]}
        return True, {"ready": True}

    def _recorders(self) -> Dict[str, Any]:
        """Replica-keyed flight recorders (fault-isolated)."""
        t = self._target
        if t is None:
            return {}
        if hasattr(t, "replicas"):
            out = {}
            for i, eng in enumerate(t.replicas):
                try:
                    out[str(i)] = eng.flight_recorder
                except Exception:                        # noqa: BLE001
                    continue
            return out
        rec = getattr(t, "flight_recorder", None)
        return {"0": rec} if rec is not None else {}

    def tracez(self) -> Dict[str, Any]:
        """The /tracez document: per-replica tail-sampled + recent
        traces, plus the SLO report when a tracker is attached."""
        engines: Dict[str, Any] = {}
        for key, rec in self._recorders().items():
            try:
                engines[key] = rec.tail_traces()
            except Exception as e:                       # noqa: BLE001
                engines[key] = {"error": repr(e)}
        doc: Dict[str, Any] = {"engines": engines}
        if self._slo is not None:
            try:
                doc["slo"] = self._slo.report()
            except Exception as e:                       # noqa: BLE001
                doc["slo"] = {"error": repr(e)}
        return doc

    def __repr__(self):
        return f"<OpsServer url={self.url} target={self._target!r}>"
