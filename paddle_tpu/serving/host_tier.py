"""Host-DRAM spill tier for the paged prefix cache (hierarchical KV).

The prefix trie (``paging.py``) is HBM-bounded: at millions-of-users
scale the hot set of shared system prompts and few-shot preambles far
exceeds the device pool, so LRU-evicted refcount-0 blocks die and their
prefill work is repaid on the next hit. :class:`HostBlockPool` gives
those blocks a second life in a bounded host-DRAM store:

* **demotion (D2H, write-back)** — the scheduler thread dispatches ONE
  lazy batched gather per cycle over the blocks that just went
  refcount-0 (``PagedKVPool.tier_tick``) and hands the resulting
  independent device array to the SPILLER thread, which performs the
  blocking device→host copy off the decode hot path and files each
  block (plus its int8 per-block scale) under its exact token-prefix
  key. The gathered array is NOT the donated pool — its value is
  captured before any later donated step can delete the storage — so
  the spiller never races XLA donation.
* **promotion (H2D, double-buffered)** — a prefix hit on a demoted
  chain creates a :class:`PromotionTicket`; the PROMOTER thread stacks
  the chain into one contiguous batch ("Memory-efficient array
  redistribution", PAPERS.md: batch the copies, don't trickle blocks)
  and stages it with an async ``jax.device_put`` through a depth-2
  queue — the ``io.device_prefetch`` double-buffering idiom — so the
  H2D copy overlaps the decode cycles that keep running meanwhile. The
  scheduler treats the waiting request like a pending feed: decode
  slots are never blocked, and the request admits the cycle its blocks
  land (``PagedKVPool.adopt_promotion`` scatters the staged batch into
  freshly allocated device blocks and republishes the trie nodes).

Content-canonical invariant: every device write path either
copies-on-write or unregisters the trie key first, so a published key's
block content is a pure function of the key. Host copies inherit that —
a demoted block filed under key K can be adopted at ANY later time and
is bit-identical to a never-evicted block for K (fp32 and int8+scales),
which is what makes the demotion-vs-republish race and keeping the host
copy after promotion both safe.

Capacity is a ledger of its own: entries are billed block+scale bytes
against ``capacity_bytes`` with LRU eviction inside the tier, published
under ``host/``-prefixed keys so the HBM ledger-vs-device crosscheck
(``profiler/memory.py``) reports host bytes separately and
``plan_replica()`` never bills host DRAM against the HBM budget.

Nothing on the serving path raises: a full tier, a full spill queue, or
a busy promoter degrades to plain eviction / a plain prefix miss and is
counted (``serving/tier_degraded``). The named errors
(:class:`HostTierError` / :class:`HostTierFullError`) fire only on API
misuse (oversized single entry, operating a closed tier).

Threading contract: ``spill`` / ``request_promotion`` / ``has`` /
``get`` are called from the scheduler thread; the spiller and promoter
threads touch only the host store under ``_lock`` plus their queues.
The ONE sanctioned device→host copy in the serving package is
:meth:`HostBlockPool._fetch` (``# lint: ok``) — it runs on the spiller
thread, off the decode hot path; ``serving-host-sync`` keeps it that
way by construction.
"""
from __future__ import annotations

import itertools
import queue
import threading
import time
import weakref
from collections import OrderedDict, deque
from typing import Dict, List, Optional, Tuple

import numpy as np

from ..framework import metrics as _metrics
from ..framework.monitor import _percentile, stat_add, stat_observe
from ..profiler import memory as _memory

__all__ = ["HostBlockPool", "PromotionTicket", "HostTierError",
           "HostTierFullError"]

# process-wide tier numbering for the host ledger keys (mirrors the
# pool-ledger discipline in kv_pool.py)
_tier_ids = itertools.count(1)

_END = object()                      # queue sentinel (io.device_prefetch)


class HostTierError(RuntimeError):
    """Host-tier API misuse (operating a closed tier, malformed entry)
    — named so tests can assert the serving path never sees it."""


class HostTierFullError(HostTierError):
    """A single entry exceeds the tier's whole capacity — a
    configuration error, not a pressure signal (pressure is answered by
    the tier's own LRU eviction, silently)."""


def _drop_tier_ledger(ledger_key: str) -> None:
    """weakref.finalize target — module function so the finalizer holds
    no reference to the tier (kv_pool.py idiom)."""
    _memory.ledger_drop(f"{ledger_key}/capacity")
    _memory.ledger_drop(f"{ledger_key}/in_use")


class PromotionTicket:
    """One in-flight H2D promotion of a contiguous chain of demoted
    blocks. Created by ``request_promotion`` (scheduler thread), staged
    by the promoter thread (``staged``/``staged_scales`` become device
    arrays, ``ready`` is set), adopted exactly once by
    ``PagedKVPool.adopt_promotion`` (scheduler thread again)."""

    __slots__ = ("keys", "staged_keys", "staged", "staged_scales",
                 "ready", "failed", "adopted", "created_at", "staged_at")

    def __init__(self, keys: List[Tuple[int, ...]]):
        self.keys = list(keys)           # requested chain, root-first
        self.staged_keys: List[Tuple[int, ...]] = []
        self.staged = None               # device [L, 2, n, H, bs, hd]
        self.staged_scales = None        # device [L, 2, n, H] or None
        self.ready = threading.Event()
        self.failed = False
        self.adopted = False
        self.created_at = time.perf_counter()
        self.staged_at: Optional[float] = None


class HostBlockPool:
    """Bounded host-DRAM store of demoted KV blocks, keyed by exact
    token-prefix tuples (the same keys as the device trie — no hashing,
    no aliasing). ``block_nbytes``/``scale_nbytes`` are the HOST bytes
    of one full-heads block (a tensor-parallel pool demotes the
    gathered full-heads value, so host entries are shard-agnostic)."""

    def __init__(self, capacity_bytes: int, block_nbytes: int, *,
                 scale_nbytes: int = 0, name: Optional[str] = None,
                 spill_depth: int = 4, promote_depth: int = 2):
        if capacity_bytes < 1:
            raise ValueError(
                f"capacity_bytes must be >= 1, got {capacity_bytes}")
        if block_nbytes < 1:
            raise ValueError(
                f"block_nbytes must be >= 1, got {block_nbytes}")
        self.capacity_bytes = int(capacity_bytes)
        self.block_nbytes = int(block_nbytes)
        self.scale_nbytes = int(scale_nbytes)
        self.entry_nbytes = self.block_nbytes + self.scale_nbytes
        if self.entry_nbytes > self.capacity_bytes:
            raise HostTierFullError(
                f"one block+scale entry is {self.entry_nbytes} bytes but "
                f"host_tier capacity is only {self.capacity_bytes} — the "
                f"tier could never hold a single block")
        self.name = name or f"serving/host_tier#{next(_tier_ids)}"
        # entries: key -> (np block [L,2,H,bs,hd], np scale [L,2,H]|None)
        self._store: "OrderedDict[Tuple[int, ...], tuple]" = OrderedDict()
        self._lock = threading.Lock()
        self._tickets: Dict[Tuple[int, ...], PromotionTicket] = {}
        # progress beacon: set on every ticket completion so an
        # otherwise-idle scheduler (no decode slots, only
        # promotion-waiters queued) can nap instead of hot-spinning
        self._progress = threading.Event()
        self._closed = False
        # counters (tier-owned; engine.stats() surfaces them)
        self.demoted_blocks = 0
        self.promoted_blocks = 0
        self.dropped_blocks = 0          # spill-queue-full degradations
        self.tier_evictions = 0          # host-LRU capacity evictions
        self.promo_shed = 0              # promoter-busy degradations
        self._promo_ms: "deque[float]" = deque(maxlen=512)
        self._demo_ms: "deque[float]" = deque(maxlen=512)
        # host ledger (host/ prefix: crosscheck() splits these out of
        # the device ledger-vs-HBM comparison)
        self.ledger_key = f"host/{self.name}"
        weakref.finalize(self, _drop_tier_ledger, self.ledger_key)
        _memory.ledger_set(f"{self.ledger_key}/capacity",
                           self.capacity_bytes)
        _memory.ledger_set(f"{self.ledger_key}/in_use", 0)
        # spiller: bounded so a slow host copy back-pressures into
        # plain eviction (degrade), never into the scheduler blocking
        self._spill_q: "queue.Queue" = queue.Queue(maxsize=spill_depth)
        # promoter: depth-2 = double buffering (io.device_prefetch) —
        # one chain staging on the copy engine while one waits adopted
        self._promo_q: "queue.Queue" = queue.Queue(maxsize=promote_depth)
        self._spiller = threading.Thread(
            target=self._spill_loop, name=f"{self.name}-spiller",
            daemon=True)
        self._promoter = threading.Thread(
            target=self._promote_loop, name=f"{self.name}-promoter",
            daemon=True)
        self._spiller.start()
        self._promoter.start()

    # -- capacity / introspection ------------------------------------------
    @property
    def blocks(self) -> int:
        with self._lock:
            return len(self._store)

    @property
    def bytes_in_use(self) -> int:
        with self._lock:
            return len(self._store) * self.entry_nbytes

    @property
    def capacity_blocks(self) -> int:
        return self.capacity_bytes // self.entry_nbytes

    def has(self, key: Tuple[int, ...]) -> bool:
        with self._lock:
            return key in self._store

    def get(self, key: Tuple[int, ...]):
        """The host copy under ``key`` as ``(block, scale)`` numpy
        arrays (scale None for float pools). Refreshes the tier LRU.
        Raises :class:`HostTierError` on a missing key — tests only;
        the serving path goes through tickets."""
        with self._lock:
            entry = self._store.get(key)
            if entry is None:
                raise HostTierError(f"key {key!r} is not host-resident")
            self._store.move_to_end(key)
            return entry

    # -- demotion (D2H) ----------------------------------------------------
    def spill(self, keys: List[Tuple[int, ...]], blocks_dev,
              scales_dev=None) -> bool:
        """Enqueue a batched demotion: ``blocks_dev`` is the lazy
        device gather ``[L, 2, len(keys), H, bs, hd]`` the scheduler
        dispatched (an independent array — NOT the donated pool), and
        ``scales_dev`` its ``[L, 2, len(keys), H]`` companion for
        quantized pools. Never blocks: a full spill queue degrades to
        plain eviction (the blocks simply die, as they did before the
        tier existed) and returns False."""
        if self._closed or not keys:
            return False
        item = (list(keys), blocks_dev, scales_dev, time.perf_counter())
        try:
            self._spill_q.put_nowait(item)
        except queue.Full:
            self.dropped_blocks += len(keys)
            stat_add("serving/tier_degraded", len(keys))
            return False
        return True

    def put(self, key: Tuple[int, ...], block: np.ndarray,
            scale: Optional[np.ndarray] = None) -> None:
        """Directly file one HOST block (tests / future disaggregation
        transport). Raises :class:`HostTierFullError` only when the
        single entry could never fit; capacity pressure evicts the
        tier's own LRU silently."""
        if self._closed:
            raise HostTierError(f"{self.name} is closed")
        with self._lock:
            self._put_locked(key, block, scale)
        self._update_ledger()

    def _put_locked(self, key, block, scale) -> None:
        if key in self._store:
            self._store.move_to_end(key)  # refreshed, content identical
            return
        while len(self._store) + 1 > self.capacity_blocks:
            self._store.popitem(last=False)
            self.tier_evictions += 1
        self._store[key] = (block, scale)
        self.demoted_blocks += 1

    def _fetch(self, dev) -> np.ndarray:
        """THE sanctioned device→host copy of the serving package: the
        batched demotion gather, materialized on the SPILLER thread off
        the decode hot path. An instance method so race tests can
        monkeypatch it to gate/instrument the copy."""
        import jax
        return np.asarray(jax.device_get(dev))  # lint: ok

    def _spill_loop(self) -> None:
        while True:
            item = self._spill_q.get()
            try:
                if item is _END:
                    return
                keys, blocks_dev, scales_dev, t0 = item
                try:
                    host = self._fetch(blocks_dev)
                    sca = (self._fetch(scales_dev)
                           if scales_dev is not None else None)
                except Exception:
                    # a failed copy (engine torn down mid-flight) is a
                    # degradation, never a crash on a daemon thread
                    self.dropped_blocks += len(keys)
                    stat_add("serving/tier_degraded", len(keys))
                    continue
                with self._lock:
                    for i, key in enumerate(keys):
                        self._put_locked(
                            key, host[:, :, i],
                            None if sca is None else sca[:, :, i])
                self._update_ledger()
                dt_ms = (time.perf_counter() - t0) * 1e3
                nbytes = len(keys) * self.entry_nbytes
                self._demo_ms.append(dt_ms)
                stat_add("serving/tier_demote", len(keys))
                stat_observe("serving/demotion_ms", dt_ms)
                stat_observe("serving/demotion_bytes", nbytes)
                _metrics.observe("serving_demotion_ms", dt_ms)
                _metrics.observe("serving_demotion_bytes", nbytes)
            finally:
                self._spill_q.task_done()

    # -- promotion (H2D) ---------------------------------------------------
    def request_promotion(
            self, keys: List[Tuple[int, ...]]) -> Optional[PromotionTicket]:
        """Coalesce the host-resident chain ``keys`` (root-first) into
        one promotion ticket. Idempotent per chain — a second request
        for the same chain returns the in-flight ticket. Returns None
        (degrade to a plain miss) when the tier is closed, the chain's
        root already left the store, or the promoter is busy past its
        double buffer."""
        if self._closed or not keys:
            return None
        keys = [tuple(k) for k in keys]
        with self._lock:
            tk = self._tickets.get(keys[-1])
            if tk is not None:
                return tk
            if keys[0] not in self._store:
                return None
            tk = PromotionTicket(keys)
            try:
                self._promo_q.put_nowait(tk)
            except queue.Full:
                self.promo_shed += 1
                stat_add("serving/tier_degraded")
                return None
            self._tickets[keys[-1]] = tk
            return tk

    def _promote_loop(self) -> None:
        while True:
            tk = self._promo_q.get()
            try:
                if tk is _END:
                    return
                try:
                    with self._lock:
                        entries, staged_keys = [], []
                        for key in tk.keys:
                            e = self._store.get(key)
                            if e is None:
                                break     # chain truncates at first gap
                            self._store.move_to_end(key)
                            entries.append(e)
                            staged_keys.append(key)
                    if not entries:
                        tk.failed = True
                        continue
                    # one contiguous batch per chain (redistribution
                    # paper: few big copies beat many small ones), and
                    # device_put is ASYNC — the H2D DMA overlaps the
                    # decode cycles running while the ticket waits
                    import jax
                    # pow2-pad the staged width (repeat the last block;
                    # adoption gathers only real lanes): every chain
                    # length then lands through one compiled
                    # gather/scatter shape per bucket instead of eagerly
                    # compiling a fresh pair on the scheduler thread
                    m = 1 << (len(entries) - 1).bit_length()
                    entries = entries + [entries[-1]] * (m - len(entries))
                    blocks = np.stack([e[0] for e in entries], axis=2)
                    tk.staged = jax.device_put(blocks)
                    if entries[0][1] is not None:
                        scales = np.stack([e[1] for e in entries], axis=2)
                        tk.staged_scales = jax.device_put(scales)
                    tk.staged_keys = staged_keys
                    tk.staged_at = time.perf_counter()
                except Exception:
                    tk.failed = True
            finally:
                if tk is not _END:
                    tk.ready.set()
                    self._progress.set()
                self._promo_q.task_done()

    def note_promoted(self, ticket: PromotionTicket, n_blocks: int) -> None:
        """Adoption callback (scheduler thread): the chain's blocks are
        device-resident and republished — close the latency ledger."""
        dt_ms = (time.perf_counter() - ticket.created_at) * 1e3
        nbytes = n_blocks * self.entry_nbytes
        self.promoted_blocks += n_blocks
        self._promo_ms.append(dt_ms)
        stat_add("serving/tier_promote", n_blocks)
        stat_observe("serving/promotion_ms", dt_ms)
        stat_observe("serving/promotion_bytes", nbytes)
        _metrics.observe("serving_promotion_ms", dt_ms)
        _metrics.observe("serving_promotion_bytes", nbytes)

    def ticket_done(self, ticket: PromotionTicket) -> None:
        """Retire a ticket from the registry (adopted or failed) so a
        later hit on the same chain can promote again."""
        with self._lock:
            for key, tk in list(self._tickets.items()):
                if tk is ticket:
                    del self._tickets[key]

    def wait_progress(self, timeout: float) -> bool:
        """Nap until SOME ticket completes (or ``timeout``): the
        scheduler's anti-hot-spin wait when the only queued requests
        are promotion-waiters and no decode slot is active. A host
        Event wait — never a device sync."""
        hit = self._progress.wait(timeout)
        self._progress.clear()
        return hit

    # -- lifecycle ---------------------------------------------------------
    def _update_ledger(self) -> None:
        _memory.ledger_set(f"{self.ledger_key}/in_use", self.bytes_in_use)

    def drain(self) -> None:
        """Block until every queued demotion and promotion has been
        processed — tests and the dry-run canary use this to make the
        async tier deterministic; the serving path never calls it."""
        self._spill_q.join()
        self._promo_q.join()

    def close(self) -> None:
        """Stop both worker threads (queued work drains first) and drop
        the ledger entries. Idempotent; the store itself survives so
        late ``get``s in teardown paths stay safe."""
        if self._closed:
            return
        self._closed = True
        self._spill_q.put(_END)
        self._promo_q.put(_END)
        self._spiller.join(timeout=10.0)
        self._promoter.join(timeout=10.0)
        with self._lock:
            for tk in self._tickets.values():
                tk.failed = True
                tk.ready.set()
            self._tickets.clear()
        self._progress.set()
        _drop_tier_ledger(self.ledger_key)

    def stats(self) -> dict:
        """Host-tier snapshot for ``engine.stats()['host_tier']``."""
        with self._lock:
            blocks = len(self._store)
        out = {
            "capacity_bytes": self.capacity_bytes,
            "bytes_in_use": blocks * self.entry_nbytes,
            "blocks": blocks,
            "capacity_blocks": self.capacity_blocks,
            "demoted_blocks": self.demoted_blocks,
            "promoted_blocks": self.promoted_blocks,
            "dropped_blocks": self.dropped_blocks,
            "tier_evictions": self.tier_evictions,
            "promo_shed": self.promo_shed,
        }
        for label, ring in (("promotion_ms", self._promo_ms),
                            ("demotion_ms", self._demo_ms)):
            vals = sorted(ring)
            out[label] = ({"count": len(vals),
                           "p50": _percentile(vals, 0.5),
                           "p95": _percentile(vals, 0.95)}
                          if vals else {"count": 0})
        return out

    def __repr__(self):
        return (f"<HostBlockPool {self.name} blocks={self.blocks}/"
                f"{self.capacity_blocks} demoted={self.demoted_blocks} "
                f"promoted={self.promoted_blocks}>")
