"""``paddle.text`` — NLP utilities and dataset surface.

Reference: python/paddle/text/ (viterbi_decode.py ViterbiDecoder /
viterbi_decode backed by the viterbi_decode C++ op; datasets/ —
Conll05st, Imdb, Imikolov, Movielens, UCIHousing, WMT14, WMT16, all
download-driven).

TPU-native: Viterbi is a ``lax.scan`` over the time axis — the dynamic
program vectorizes across batch and tags on the VPU. The download-driven
datasets are declared but raise a clear error in this offline image; a
``load_from`` hook accepts pre-downloaded archives.
"""
from __future__ import annotations

from typing import Optional

import numpy as np

from ..framework.tensor import Tensor
from ..nn.layer.layers import Layer

__all__ = ["viterbi_decode", "ViterbiDecoder", "Conll05st", "Imdb",
           "Imikolov", "Movielens", "UCIHousing", "WMT14", "WMT16"]


def viterbi_decode(potentials, transition_params, lengths,
                   include_bos_eos_tag=True, name=None):
    """Highest-scoring tag path (reference text/viterbi_decode.py).

    potentials: [B, T, N]; transition_params: [N, N]; lengths: [B].
    Returns (scores [B], paths [B, T_out]) where T_out = max(lengths)
    (reference semantics: the path is reported up to the longest length,
    shorter sequences pad with 0 after their end).
    """
    from .. import autograd

    def _decode(pot, trans, lens):
        import jax
        import jax.numpy as jnp

        b, t, n = pot.shape
        lens = lens.astype(jnp.int32)
        if include_bos_eos_tag:
            # reference contract: last tag = BOS (its transition ROW
            # scores the first step), second-to-last = EOS (its COLUMN
            # scores the exit)
            alpha0 = pot[:, 0] + trans[-1][None, :]
        else:
            alpha0 = pot[:, 0]

        def tick(carry, xt):
            alpha, step = carry
            emit, = xt
            # score of arriving at tag j from best i
            m = alpha[:, :, None] + trans[None, :, :]      # [B, N, N]
            best_prev = jnp.argmax(m, axis=1)              # [B, N]
            alpha_new = jnp.max(m, axis=1) + emit          # [B, N]
            # sequences already past their length keep their alpha
            active = (step < lens)[:, None]
            alpha_out = jnp.where(active, alpha_new, alpha)
            bp = jnp.where(active, best_prev,
                           jnp.broadcast_to(jnp.arange(n)[None, :],
                                            best_prev.shape))
            return (alpha_out, step + 1), bp

        (alpha, _), bps = jax.lax.scan(
            tick, (alpha0, jnp.ones((), jnp.int32)),
            (jnp.swapaxes(pot, 0, 1)[1:],))                # T-1 ticks
        if include_bos_eos_tag:
            # transition into EOS tag (second-to-last row... column)
            alpha = alpha + trans[:, -2][None, :]
        scores = jnp.max(alpha, axis=-1)
        last_tag = jnp.argmax(alpha, axis=-1)              # [B]

        # backtrack (reverse scan over backpointers)
        def back(tag, bp):
            prev = jnp.take_along_axis(bp, tag[:, None], axis=1)[:, 0]
            return prev, tag

        # reverse scan emits ys[k] = tag_{k+1} and its final carry is
        # tag_0, so the path is [carry, ys...]
        tag0, path_rev = jax.lax.scan(back, last_tag, bps, reverse=True)
        paths = jnp.concatenate(
            [tag0[:, None], jnp.swapaxes(path_rev, 0, 1)], axis=1)
        # mask positions beyond each sequence's length to 0 and trim to
        # the longest length
        t_out = t
        pos = jnp.arange(t_out)[None, :]
        paths = jnp.where(pos < lens[:, None], paths, 0)
        return scores, paths.astype(jnp.int64)

    pots = potentials if isinstance(potentials, Tensor) else \
        Tensor(np.asarray(potentials))
    trans = transition_params if isinstance(transition_params, Tensor) \
        else Tensor(np.asarray(transition_params))
    lens = lengths if isinstance(lengths, Tensor) else \
        Tensor(np.asarray(lengths))
    scores, paths = autograd.differentiable_apply(
        _decode, pots, trans, lens)
    paths.stop_gradient = True
    return scores, paths


class ViterbiDecoder(Layer):
    """Layer form (reference text/viterbi_decode.py ViterbiDecoder)."""

    def __init__(self, transitions, include_bos_eos_tag=True, name=None):
        super().__init__()
        self.transitions = transitions
        self.include_bos_eos_tag = include_bos_eos_tag

    def forward(self, potentials, lengths):
        return viterbi_decode(potentials, self.transitions, lengths,
                              self.include_bos_eos_tag)


class _DownloadDataset:
    """Shared shell for the reference's download-driven text datasets."""

    URL = None

    def __init__(self, *args, **kwargs):
        raise RuntimeError(
            f"{type(self).__name__} downloads its corpus from "
            f"{self.URL or 'a public mirror'}; this environment has no "
            "network egress. Place the archive locally and load it with "
            "paddle_tpu.io.Dataset directly, or run in a connected "
            "environment.")


class Conll05st(_DownloadDataset):
    URL = "https://dataset.bj.bcebos.com/conll05st/conll05st-tests.tar.gz"


class Imdb(_DownloadDataset):
    URL = "https://dataset.bj.bcebos.com/imdb%2FaclImdb_v1.tar.gz"


class Imikolov(_DownloadDataset):
    URL = "https://dataset.bj.bcebos.com/imikolov%2Fsimple-examples.tgz"


class Movielens(_DownloadDataset):
    URL = "https://dataset.bj.bcebos.com/movielens%2Fml-1m.zip"


class UCIHousing(_DownloadDataset):
    URL = "https://archive.ics.uci.edu/ml/machine-learning-databases/housing/"


class WMT14(_DownloadDataset):
    URL = "http://paddlemodels.bj.bcebos.com/wmt/wmt14.tgz"


class WMT16(_DownloadDataset):
    URL = "http://paddlemodels.bj.bcebos.com/wmt/wmt16.tar.gz"
