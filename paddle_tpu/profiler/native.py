"""ctypes bindings for the native tpu_prof event recorder
(native/tpu_prof.cc — reference analog: platform/profiler/
host_event_recorder.h). Falls back gracefully when no toolchain exists;
the python recorder in profiler.py remains the source of truth for tests.
"""
from __future__ import annotations

import ctypes
import json
import os
import threading
from typing import Optional

__all__ = ["available", "enable", "disable", "begin", "end", "instant",
           "count", "dropped", "dump", "merge_into"]

_HERE = os.path.dirname(os.path.abspath(__file__))
_SRC = os.path.join(os.path.dirname(os.path.dirname(_HERE)), "native",
                    "tpu_prof.cc")

_lib = None
_lib_err: Optional[str] = None
_build_lock = threading.Lock()


def _load():
    global _lib, _lib_err
    if _lib is not None or _lib_err is not None:
        return _lib
    with _build_lock:
        if _lib is not None or _lib_err is not None:
            return _lib
        try:
            from ..utils import cpp_extension
            ext = cpp_extension.load("tpu_prof", [_SRC])
            lib = ext.__lib__
        except Exception as e:
            _lib_err = f"{type(e).__name__}: {e}"
            return None
        lib.tp_enable.argtypes = [ctypes.c_uint64]
        lib.tp_begin.argtypes = [ctypes.c_char_p]
        lib.tp_instant.argtypes = [ctypes.c_char_p]
        lib.tp_count.restype = ctypes.c_uint64
        lib.tp_dropped.restype = ctypes.c_uint64
        lib.tp_dump.argtypes = [ctypes.c_char_p, ctypes.c_longlong]
        lib.tp_dump.restype = ctypes.c_longlong
        lib.tp_enabled.restype = ctypes.c_int
        _lib = lib
    return _lib


def available() -> bool:
    return _load() is not None


def enable(capacity: int = 1 << 20):
    lib = _load()
    if lib is not None:
        lib.tp_enable(capacity)


def disable():
    lib = _load()
    if lib is not None:
        lib.tp_disable()


def resume():
    """Re-arm recording without clearing accumulated events."""
    lib = _load()
    if lib is not None:
        lib.tp_resume()


def begin(name: str):
    lib = _load()
    if lib is not None:
        lib.tp_begin(name.encode())


def end():
    lib = _load()
    if lib is not None:
        lib.tp_end()


def instant(name: str):
    lib = _load()
    if lib is not None:
        lib.tp_instant(name.encode())


def count() -> int:
    lib = _load()
    return int(lib.tp_count()) if lib is not None else 0


def dropped() -> int:
    lib = _load()
    return int(lib.tp_dropped()) if lib is not None else 0


def dump(path: str, pid: Optional[int] = None) -> int:
    lib = _load()
    if lib is None:
        return 0
    return int(lib.tp_dump(path.encode(),
                           os.getpid() if pid is None else pid))


def merge_into(trace: dict) -> dict:
    """Append the native events into the chrome-trace dict as a separate
    pid lane (pid+1, labeled via a process_name metadata event)."""
    import tempfile
    if not available() or count() == 0:
        return trace
    lane = os.getpid() + 1
    with tempfile.NamedTemporaryFile(suffix=".json", delete=False) as f:
        tmp = f.name
    try:
        n = dump(tmp, pid=lane)
        if n <= 0:
            return trace  # IO error in the C recorder: keep the py trace
        try:
            with open(tmp) as f:
                native_trace = json.load(f)
        except ValueError:
            return trace
        events = trace.setdefault("traceEvents", [])
        events.append({"ph": "M", "name": "process_name", "pid": lane,
                       "args": {"name": "tpu_prof (native recorder)"}})
        events.extend(native_trace.get("traceEvents", []))
    finally:
        os.unlink(tmp)
    return trace
