"""Unified chrome-trace merger: every timeline, one clock, one file.

Three observability layers record time-stamped events against DIFFERENT
buffers today: the span profiler (PR 1 — host spans + the PR-6 serving
request lanes, ``perf_counter`` seconds), the HBM memory tracker (PR 7
— timeline ring, also ``perf_counter``), and the XPlane device trace
(jax's own, producer-clock nanoseconds). Debugging a serving stall or a
step-time regression means eyeballing all three — which is exactly the
correlation job a trace viewer does, IF the events share a clock and a
file. This module merges them:

* **host spans** — re-emitted as-is (they already share the
  ``perf_counter`` axis), thread/lane labels included, under the main
  process;
* **memory timeline** — ``ph:"C"`` counter events (``bytes_in_use``,
  ``ledger_bytes``) that the viewer draws as a stacked area under the
  trace, plus ``ph:"i"`` instant marks for the labeled watermarks
  (``kv/alloc``, ``serving/cycle``, fit flushes);
* **device ops** — decoded from the newest ``*.xplane.pb``
  (:func:`..xplane.device_events`, the version-tolerant parser) on a
  separate "device" pid. Their clock is the producer's: alignment pins
  the FIRST device event to the host ``perf_counter`` stamp taken when
  ``start_trace`` returned (``Profiler._trace_anchor_us``), falling
  back to the earliest host span. That is an alignment HEURISTIC — good
  to roughly the trace-start latency (sub-ms in practice), and the
  honest best available without a cross-clock sync protocol; the
  ``clock`` arg of every device event records the applied shift so a
  skeptical reader can un-shift.

Open the result in Perfetto / chrome://tracing: request lanes above,
scheduler + op spans below, device ops beneath them, HBM level along
the bottom — the whole story of a cycle in one scroll.
"""
from __future__ import annotations

import json
import os
from typing import Any, Dict, List, Optional

__all__ = ["export_unified_trace", "unified_trace_doc"]


def unified_trace_doc(trace_dir: Optional[str] = None,
                      include_memory: bool = True,
                      anchor_us: Optional[float] = None,
                      window_us: Optional[tuple] = None) -> Dict[str, Any]:
    """Build the merged chrome-trace document (see module docstring).
    ``trace_dir`` adds the XPlane device lane when it holds a trace;
    ``anchor_us`` is the host ``perf_counter``-microseconds stamp of
    ``start_trace`` (device-lane alignment). ``window_us`` (t0, t1)
    clips the MEMORY lane to a profiling session's window — the memory
    timeline ring is process-global and outlives any one session, so
    without the clip a long-lived process drags hours-old HBM samples
    into every trace. Host spans are already session-scoped (the span
    recorder is cleared per session) and are never clipped."""
    from . import span as _span

    pid = os.getpid()
    trace: List[dict] = [{"name": "process_name", "ph": "M", "pid": pid,
                          "args": {"name": "paddle_tpu host"}}]
    for tid, tname in sorted(_span.thread_names().items()):
        trace.append({"name": "thread_name", "ph": "M", "pid": pid,
                      "tid": tid, "args": {"name": tname}})
    span_events = _span.events()
    first_host_us = min((ev["ts"] for ev in span_events), default=None)
    for ev in span_events:
        trace.append({
            "name": ev["name"], "cat": ev["cat"], "ph": "X", "pid": pid,
            "tid": ev["tid"], "ts": ev["ts"], "dur": ev["dur"],
            "args": {"depth": ev["depth"], "parent": ev["parent"],
                     **(ev["args"] or {})},
        })

    if include_memory:
        from . import memory as _memory
        mem_pid = pid + 1
        trace.append({"name": "process_name", "ph": "M", "pid": mem_pid,
                      "args": {"name": "paddle_tpu memory"}})
        # 0.25 s slack: sampler ticks straddling the window edges stay
        w0, w1 = (window_us if window_us else (None, None))
        slack = 0.25e6
        for entry in _memory.timeline():
            ts = entry["t"] * 1e6            # perf_counter s -> us
            if w0 is not None and not (w0 - slack <= ts <= w1 + slack):
                continue
            counters = {k: entry[k] for k in
                        ("bytes_in_use", "ledger_bytes") if k in entry}
            if counters:
                trace.append({"name": "hbm", "ph": "C", "pid": mem_pid,
                              "ts": ts, "args": counters})
            label = entry.get("label")
            if label and label != "sampler":
                trace.append({"name": label, "cat": "memory", "ph": "i",
                              "pid": mem_pid, "tid": 0, "ts": ts,
                              "s": "p"})

    if trace_dir:
        from .xplane import device_events
        devs = device_events(trace_dir)
        if devs:
            dev_pid = pid + 2
            trace.append({"name": "process_name", "ph": "M",
                          "pid": dev_pid,
                          "args": {"name": "paddle_tpu device (XPlane)"}})
            first_dev_us = min(d["t_us"] for d in devs)
            anchor = anchor_us if anchor_us is not None else first_host_us
            shift_us = (anchor - first_dev_us) if anchor is not None \
                else 0.0
            lanes: Dict[str, int] = {}
            for d in devs:
                key = f"{d['plane']}:{d['line']}"
                if key not in lanes:
                    lanes[key] = len(lanes)
                    trace.append({"name": "thread_name", "ph": "M",
                                  "pid": dev_pid, "tid": lanes[key],
                                  "args": {"name": d["line"] or
                                           d["plane"]}})
                lane = lanes[key]
                trace.append({
                    "name": d["name"], "cat": "device", "ph": "X",
                    "pid": dev_pid, "tid": lane,
                    "ts": d["t_us"] + shift_us, "dur": d["dur_us"],
                    "args": {"clock": "xplane",
                             "shift_us": round(shift_us, 3)},
                })
    return {"traceEvents": trace, "displayTimeUnit": "ms"}


def export_unified_trace(path: str, trace_dir: Optional[str] = None,
                         include_memory: bool = True,
                         anchor_us: Optional[float] = None,
                         window_us: Optional[tuple] = None) -> str:
    """Write :func:`unified_trace_doc` to ``path``; returns the path."""
    doc = unified_trace_doc(trace_dir=trace_dir,
                            include_memory=include_memory,
                            anchor_us=anchor_us,
                            window_us=window_us)
    d = os.path.dirname(os.path.abspath(path))
    os.makedirs(d, exist_ok=True)
    with open(path, "w") as f:
        json.dump(doc, f)
    return path
