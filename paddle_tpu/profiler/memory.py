"""HBM memory tracker: timeline, ledger, and the OOM postmortem.

Reference analog: the profiler's memory tab +
``paddle.device.cuda.memory_allocated`` over the STAT gpu-mem counters
(fluid/memory/stats.cc). On TPU, allocation belongs to PjRt — the
user-visible surface is observability, three layers of it:

* **timeline** — a bounded ring of samples over
  ``device.memory_stats()`` (``bytes_in_use`` / ``peak`` / ``limit``),
  fed by a background sampler thread (:func:`start_sampler`) plus
  labeled watermarks at the moments that matter: fit's flush windows,
  serving cycles, KV-pool alloc/free. Watermarks from the scheduler hot
  path use :func:`mark` — a host-only stamp that NEVER polls the device
  (the ``memory-stats-hot-path`` self-lint rule keeps polling on the
  sampler thread); :func:`sample` additionally reads the device stats.
* **ledger** — the bytes WE think are live, by owner: the train state
  (params / opt_state / buffers, registered by ``Model.fit``) and the
  serving KV pools (capacity + in-use, registered by the pools).
  :func:`crosscheck` compares the ledger total against the device's
  ``bytes_in_use`` — the gap is what nobody is accounting for.
* **OOM postmortem** — ``RESOURCE_EXHAUSTED`` caught in ``Model.fit``
  and the serving scheduler dumps the timeline, the ledger, and the
  largest live arrays (``jax.live_arrays()``) to a JSON file next to
  the flight recorder's auto-dump, never masking the original error.

Threading: writers (``mark``/``ledger_set``) take the one small lock
per call — they run per flush window / pool event / scheduler cycle,
not per op, so contention is negligible (same argument as the flight
recorder). The module-level default tracker is what the framework
integrations use; tests build their own :class:`MemoryTracker` with a
mocked stats function.
"""
from __future__ import annotations

import json
import logging
import os
import tempfile
import threading
import time
from collections import deque
from typing import Any, Callable, Dict, List, Optional

from ..framework.monitor import stat_add, stat_observe

__all__ = ["MemoryTracker", "tracker", "sample", "mark", "ledger_set",
           "ledger_drop", "ledger", "ledger_total", "crosscheck",
           "start_sampler", "stop_sampler", "timeline",
           "largest_live_arrays", "oom_postmortem",
           "is_resource_exhausted"]

logger = logging.getLogger(__name__)

# substrings that mark an out-of-HBM failure across the surfaces it
# arrives on (XlaRuntimeError repr, RuntimeError text, wrapped reprs).
# Deliberately NO bare "OOM": three characters match inside unrelated
# identifiers ("BOOM", a path segment) and a spurious postmortem
# actively misdirects the triage it exists to aid.
_OOM_MARKERS = ("RESOURCE_EXHAUSTED", "Out of memory", "out of memory")


def is_resource_exhausted(error: BaseException) -> bool:
    """Does this exception look like the device ran out of memory?"""
    text = f"{type(error).__name__}: {error!r}"
    return any(m in text for m in _OOM_MARKERS)


def _device_stats() -> dict:
    """One ``device.memory_stats()`` poll; {} when the backend doesn't
    report (CPU) or the query fails."""
    try:
        from .. import device as _device
        return _device.memory_stats() or {}
    except Exception:                                    # noqa: BLE001
        return {}


class MemoryTracker:
    """Bounded HBM timeline + byte ledger + postmortem dump."""

    def __init__(self, max_samples: int = 2048,
                 stats_fn: Optional[Callable[[], dict]] = None):
        if max_samples < 1:
            raise ValueError("max_samples must be >= 1")
        # RLock, not Lock: ledger owners drop their keys from weakref
        # FINALIZERS (hapi Model._drop_ledger_keys), and a finalizer can
        # fire on whatever thread happens to allocate — including THIS
        # thread while it holds the lock inside timeline()/ledger()'s
        # copy (the copy allocates, allocation can trigger GC). With a
        # plain Lock that is a same-thread deadlock.
        self._lock = threading.RLock()
        self._ring: deque = deque(maxlen=int(max_samples))
        self._ledger: Dict[str, int] = {}
        self._stats_fn = stats_fn or _device_stats
        self.samples_recorded = 0       # monotonic (ring drops, this doesn't)
        self.last_dump_path: Optional[str] = None
        self.dumps = 0
        self._sampler: Optional[threading.Thread] = None
        self._sampler_stop = threading.Event()

    # -- timeline ----------------------------------------------------------
    def _append(self, entry: dict) -> None:
        with self._lock:
            entry["ledger_bytes"] = sum(self._ledger.values())
            self._ring.append(entry)
            self.samples_recorded += 1

    def sample(self, label: Optional[str] = None, **meta) -> dict:
        """Poll the device stats and append one timeline entry. NOT for
        the scheduler hot path — that is :meth:`mark`'s job (the
        ``memory-stats-hot-path`` self-lint rule enforces it)."""
        stats = self._stats_fn() or {}
        entry: Dict[str, Any] = {"t": time.perf_counter()}
        if label is not None:
            entry["label"] = label
        for k in ("bytes_in_use", "peak_bytes_in_use", "bytes_limit"):
            if k in stats:
                entry[k] = int(stats[k])
        entry.update(meta)
        self._append(entry)
        if "bytes_in_use" in entry:
            stat_observe("memory/bytes_in_use", entry["bytes_in_use"])
        return entry

    def mark(self, label: str, **meta) -> dict:
        """Host-only watermark: a labeled timeline stamp carrying the
        ledger total but NO device poll — safe from the scheduler
        thread, pool alloc/free, and anywhere else a stats query would
        stall the hot path. Device numbers around it come from the
        sampler thread's periodic :meth:`sample` entries."""
        entry: Dict[str, Any] = {"t": time.perf_counter(), "label": label}
        entry.update(meta)
        self._append(entry)
        return entry

    def timeline(self) -> List[dict]:
        with self._lock:
            return [dict(e) for e in self._ring]

    # -- background sampler ------------------------------------------------
    def start(self, interval: float = 0.2) -> None:
        """Start the background sampler thread (idempotent): one
        :meth:`sample` every ``interval`` seconds until :meth:`stop`."""
        with self._lock:
            if self._sampler is not None and self._sampler.is_alive():
                return
            self._sampler_stop = threading.Event()
            stop = self._sampler_stop

            def _loop():
                while not stop.wait(interval):
                    try:
                        self.sample(label="sampler")
                    except Exception:                    # noqa: BLE001
                        pass        # a flaky stats query must not kill it
            self._sampler = threading.Thread(
                target=_loop, daemon=True, name="paddle-memory-sampler")
            self._sampler.start()

    def stop(self) -> None:
        with self._lock:
            t, self._sampler = self._sampler, None
            self._sampler_stop.set()
        if t is not None:
            t.join(timeout=5)

    # -- ledger ------------------------------------------------------------
    def ledger_set(self, key: str, nbytes: int) -> None:
        with self._lock:
            self._ledger[key] = int(nbytes)

    def ledger_drop(self, key: str) -> None:
        with self._lock:
            self._ledger.pop(key, None)

    def ledger(self) -> Dict[str, int]:
        with self._lock:
            return dict(self._ledger)

    def ledger_total(self) -> int:
        with self._lock:
            return sum(self._ledger.values())

    def crosscheck(self) -> dict:
        """Ledger vs device: how much of ``bytes_in_use`` do the
        registered owners explain? ``device_bytes_in_use`` is ``None``
        where the backend doesn't report (CPU) — then only the ledger
        side is meaningful. Ledger keys under the ``host/`` prefix
        (the serving host-DRAM spill tier) are accounted SEPARATELY as
        ``host_ledger_bytes`` — host DRAM must never inflate the
        device-side explained ratio."""
        stats = self._stats_fn() or {}
        in_use = stats.get("bytes_in_use")
        with self._lock:
            host = sum(v for k, v in self._ledger.items()
                       if k.startswith("host/"))
            led = sum(self._ledger.values()) - host
        out: Dict[str, Any] = {
            "ledger_bytes": led,
            "host_ledger_bytes": host,
            "device_bytes_in_use": None if in_use is None else int(in_use),
            "unexplained_bytes": None,
            "explained_ratio": None,
        }
        if in_use:
            out["unexplained_bytes"] = int(in_use) - led
            out["explained_ratio"] = led / int(in_use)
        return out

    # -- postmortem --------------------------------------------------------
    def largest_live_arrays(self, n: int = 20) -> List[dict]:
        """The ``n`` biggest live device arrays (shape/dtype/bytes),
        biggest first — the "what is actually holding HBM" list of the
        OOM postmortem. Host bookkeeping only (sizes come from avals)."""
        try:
            import jax
            arrays = jax.live_arrays()
        except Exception:                                # noqa: BLE001
            return []
        rows = []
        for a in arrays:
            try:
                rows.append({"shape": list(a.shape), "dtype": str(a.dtype),
                             "nbytes": int(a.nbytes)})
            except Exception:                            # noqa: BLE001
                continue        # deleted/donated handles have no size
        rows.sort(key=lambda r: r["nbytes"], reverse=True)
        return rows[:n]

    def oom_postmortem(self, error: Optional[BaseException] = None,
                       path: Optional[str] = None,
                       extra: Optional[dict] = None) -> Optional[str]:
        """Dump the memory picture at the moment of death: timeline,
        ledger, ledger-vs-device crosscheck, and the largest live
        arrays, as JSON. Best effort and NEVER raises — it runs inside
        failure handlers, and a broken disk must not mask the original
        error. Returns the file path (``None`` on failure)."""
        try:
            doc: Dict[str, Any] = {
                "reason": repr(error) if error is not None else "requested",
                "dumped_at": time.time(),
                "timeline": self.timeline(),
                "ledger": self.ledger(),
                "crosscheck": self.crosscheck(),
                "largest_live_arrays": self.largest_live_arrays(),
            }
            if extra:
                doc.update(extra)
            if path is None:
                path = os.path.join(
                    tempfile.gettempdir(),
                    f"paddle_oom_postmortem_{os.getpid()}_{id(self):x}"
                    f".json")
            d = os.path.dirname(os.path.abspath(path))
            os.makedirs(d, exist_ok=True)
            with open(path, "w") as f:
                json.dump(doc, f, default=repr)
            with self._lock:
                self.last_dump_path = path
                self.dumps += 1
            if error is not None:
                stat_add("memory/oom_postmortem")
                logger.error("OOM postmortem written to %s", path)
            else:
                # requested dump (e.g. riding a numerics postmortem):
                # same artifact, but nobody ran out of memory — an
                # alert gating on memory/oom_postmortem must not fire
                stat_add("memory/postmortem_requested")
                logger.info("memory postmortem written to %s", path)
            return path
        except Exception:                                # noqa: BLE001
            return None

    def __repr__(self):
        with self._lock:
            return (f"<MemoryTracker samples={len(self._ring)}/"
                    f"{self.samples_recorded} ledger_keys="
                    f"{len(self._ledger)}>")


# ---------------------------------------------------------------------------
# module-level default tracker (what the framework integrations use)
# ---------------------------------------------------------------------------

_tracker = MemoryTracker()


def tracker() -> MemoryTracker:
    return _tracker


def sample(label: Optional[str] = None, **meta) -> dict:
    return _tracker.sample(label, **meta)


def mark(label: str, **meta) -> dict:
    return _tracker.mark(label, **meta)


def ledger_set(key: str, nbytes: int) -> None:
    _tracker.ledger_set(key, nbytes)


def ledger_drop(key: str) -> None:
    _tracker.ledger_drop(key)


def ledger() -> Dict[str, int]:
    return _tracker.ledger()


def ledger_total() -> int:
    return _tracker.ledger_total()


def crosscheck() -> dict:
    return _tracker.crosscheck()


def start_sampler(interval: float = 0.2) -> None:
    _tracker.start(interval)


def stop_sampler() -> None:
    _tracker.stop()


def timeline() -> List[dict]:
    return _tracker.timeline()


def largest_live_arrays(n: int = 20) -> List[dict]:
    return _tracker.largest_live_arrays(n)


def oom_postmortem(error: Optional[BaseException] = None,
                   path: Optional[str] = None,
                   extra: Optional[dict] = None) -> Optional[str]:
    return _tracker.oom_postmortem(error, path=path, extra=extra)


def _metrics_collector():
    """Registry collector (ISSUE 13): the HBM ledger as per-owner
    gauges plus one device poll for in-use/limit. Scrape-time only —
    the collector is PULLED by snapshot/export, so the device query
    rides the operator's scrape cadence, never a hot path."""
    led = _tracker.ledger()
    out = [("gauge", "hbm_ledger_bytes", {"owner": k}, float(v))
           for k, v in led.items()]
    out.append(("gauge", "hbm_ledger_total_bytes", {},
                float(sum(led.values()))))
    stats = _tracker._stats_fn() or {}
    if "bytes_in_use" in stats:
        out.append(("gauge", "hbm_bytes_in_use", {},
                    float(stats["bytes_in_use"])))
    if "bytes_limit" in stats:
        out.append(("gauge", "hbm_bytes_limit", {},
                    float(stats["bytes_limit"])))
    return out


def _register_memory_collector() -> None:
    try:
        from ..framework import metrics as _metrics
        _metrics.register_collector("memory", _metrics_collector)
    except Exception:                                    # noqa: BLE001
        pass


_register_memory_collector()
