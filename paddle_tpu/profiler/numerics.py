"""Training numerics health: device-side sentinels, gradient telemetry,
and a train-loop flight recorder with anomaly postmortem.

The reference framework's numerics debugging story is
``FLAGS_check_nan_inf`` — a synchronous host sweep of EVERY op output
after EVERY kernel launch (framework/details/nan_inf_utils_detail.cc) —
which is exactly the per-step host sync the donated async train step
(PR 2) exists to eliminate. This module is the TPU-native replacement,
built on one rule: **the audit is computed ON DEVICE inside the already-
compiled train step and fetched only at the existing flush windows**, so
a ``fit()`` with numerics armed costs zero extra host syncs and zero
extra compiled programs (the ``hapi/host_sync`` counter and the PR-7
program-registry ``compile/count`` are both asserted unchanged by
tests and ``bench.py --dry-run``).

Three layers:

* **device audit** (:func:`build_audit`, traced into the train step by
  ``hapi/model.py _build_train_step`` when ``Model.fit(numerics=...)``
  is not ``'off'``) — one small f32 vector per step: a packed finite
  bitmask (loss / grads / post-update params), the global grad norm
  (REUSED from the ``ClipGradByGlobalNorm`` clip path when present —
  never computed twice), the clipped norm, the global param norm, the
  update norm ``‖Δw‖``, and per-layer-group nonfinite gradient element
  counts for blame. The vector rides the fit window next to the loss
  and is converted to numpy at ``_flush_window`` — already-computed
  arrays behind the window's one blocking fetch.
* **telemetry + flight recorder** (:class:`NumericsRecorder`) — on
  every flush the decoded records feed the monitor histograms
  (``hapi/grad_norm``, ``hapi/update_ratio``, ``hapi/grad_clip_ratio``)
  and counters (``hapi/nonfinite_steps``, ``hapi/loss_spikes``), and
  land in a bounded per-Model ring of per-step records mirroring the
  serving flight recorder (loss, grad norm, update ratio, lr, finite
  bitmask, GradScaler state, retrace-cause delta, HBM-ledger bytes) —
  always on while numerics is armed, dumpable after the fact.
* **policy + postmortem** — ``Model.fit(numerics='record'|'warn'|
  'halt')`` reacts at the window: nonfinite steps in ``halt`` mode
  raise a named :class:`NumericsError` AFTER the anomaly postmortem
  JSON lands (ring tail + blamed layer groups + scaler state + monitor
  snapshot + the PR-7 memory-postmortem path) and fit's existing
  ``on_train_abort`` teardown runs; ``warn`` dumps the same postmortem
  and warns without killing the run. A loss-spike detector (robust
  z-score over the ring: ``|loss - median| / (1.4826 * MAD)``) fires
  the postmortem in ``warn``/``halt`` mode but NEVER raises — a spike
  is a lead, not a verdict.

Threading / sync contract: everything in this module is host-pure over
NUMPY inputs (``hapi/model.py`` converts the device vectors inside its
flush window) except :func:`build_audit`, which is jnp code traced into
the step. The ``numerics-host-sync`` self-lint rule
(analysis/selflint.py) enforces that no ``.item()``/``jax.device_get``/
``.numpy()`` sync ever creeps in here — audit fetches belong to the
flush window, nowhere else.
"""
from __future__ import annotations

import json
import math
import os
import statistics
import tempfile
import threading
import time
import warnings
from collections import deque
from dataclasses import dataclass, field
from typing import Any, Dict, List, Optional, Sequence, Tuple

import numpy as np

from ..framework.monitor import (all_stats, stat_add, stat_histogram,
                                 stat_observe)
from . import memory as _memory

__all__ = ["NumericsError", "AuditLayout", "NumericsRecorder",
           "build_audit", "build_audit_flat", "group_params",
           "decode_audit", "flag_mode", "live_recorders", "MODES",
           "N_FIXED", "FINITE_ALL"]

MODES = ("off", "record", "warn", "halt")

# live recorders, for the statusz training section and the metrics
# registry collector (weakly held: recorders die with their Models)
import weakref  # noqa: E402

_LIVE_RECORDERS: "weakref.WeakSet" = weakref.WeakSet()
_recorder_seq = 0


def live_recorders() -> List["NumericsRecorder"]:
    """The process's live training recorders, recorder_id order."""
    return sorted(_LIVE_RECORDERS,
                  key=lambda r: getattr(r, "recorder_id", 0))


def _metrics_collector():
    """Registry collector: per-recorder anomaly counters, labeled
    ``{recorder=<id>}`` — the numerics island on the fleet scrape."""
    out = []
    for rec in list(_LIVE_RECORDERS):
        labels = {"recorder": str(getattr(rec, "recorder_id", 0))}
        out.append(("counter", "training_steps_recorded", labels,
                    rec.steps_recorded))
        out.append(("counter", "training_anomalies_recorded", labels,
                    rec.anomalies_recorded))
        out.append(("counter", "training_postmortem_dumps", labels,
                    rec.dumps))
    return out


def _register_numerics_collector() -> None:
    try:
        from ..framework import metrics as _metrics
        _metrics.register_collector("training_numerics",
                                    _metrics_collector)
    except Exception:                                    # noqa: BLE001
        pass


_register_numerics_collector()

# audit vector layout: fixed scalar slots, then one per-group count
IDX_BITS = 0          # packed finite bitmask (see bit constants below)
IDX_LOSS = 1          # the step's loss value (f32)
IDX_GRAD_NORM = 2     # global UNCLIPPED grad norm
IDX_CLIPPED_NORM = 3  # global grad norm after clipping (== raw w/o clip)
IDX_PARAM_NORM = 4    # global trainable-param norm (pre-update)
IDX_UPDATE_NORM = 5   # global update norm ‖Δw‖ (post - pre)
N_FIXED = 6

BIT_LOSS = 1          # loss is finite
BIT_GRADS = 2         # every gradient element is finite
BIT_UPDATE = 4        # every post-update param element is finite
FINITE_ALL = BIT_LOSS | BIT_GRADS | BIT_UPDATE


class NumericsError(RuntimeError):
    """Training numerics went nonfinite under ``fit(numerics='halt')``.

    Raised at the flush window that detected the anomaly, AFTER the
    anomaly postmortem JSON was dumped (its path is in the message) —
    fit's ``on_train_abort`` teardown runs on the way out exactly as for
    any other training failure."""


def group_params(names: Sequence[str],
                 max_groups: int = 32) -> Dict[str, Tuple[str, ...]]:
    """Deterministic layer-group partition of parameter tree names, for
    nonfinite blame. Prefers the parent-module path (``"0.weight"`` →
    ``"0"``, ``"gpt.blocks.3.attn.q.weight"`` → the attn layer), then
    coarsens (first two components, then the first) until the group
    count fits ``max_groups`` — the audit vector carries one count per
    group, so blame granularity trades off against vector size."""
    names = sorted(names)

    def parent(n: str) -> str:
        head, _, _ = n.rpartition(".")
        return head or n

    keyfns = [parent,
              lambda n: ".".join(n.split(".")[:2]),
              lambda n: n.split(".", 1)[0]]
    groups: Dict[str, List[str]] = {}
    for keyfn in keyfns:
        groups = {}
        for n in names:
            groups.setdefault(keyfn(n), []).append(n)
        if len(groups) <= max_groups:
            break
    if len(groups) > max_groups:
        # a flat net (40+ sibling layers) defeats every prefix keyfn —
        # the cap is a hard bound on the device vector's size, so merge
        # lexicographic RANGES of groups until it holds, labeled by
        # their span ("0..17.weight") so blame still localizes
        keys = list(groups)
        per = -(-len(keys) // max_groups)
        merged: Dict[str, List[str]] = {}
        for i in range(0, len(keys), per):
            chunk = keys[i:i + per]
            label = chunk[0] if len(chunk) == 1 \
                else f"{chunk[0]}..{chunk[-1]}"
            merged[label] = [n for k in chunk for n in groups[k]]
        groups = merged
    return {g: tuple(ms) for g, ms in groups.items()}


@dataclass(frozen=True)
class AuditLayout:
    """Host-side schema of the device audit vector: the ordered layer
    groups and their member parameter names. Static per train-step
    trace (the frozen set is baked in, so the trainable name set is
    too); held on the Model next to the step it describes."""

    groups: Tuple[str, ...]
    members: Dict[str, Tuple[str, ...]] = field(hash=False)

    @staticmethod
    def build(trainable_names: Sequence[str],
              max_groups: int = 32) -> "AuditLayout":
        members = group_params(trainable_names, max_groups)
        return AuditLayout(groups=tuple(members), members=members)

    @property
    def size(self) -> int:
        return N_FIXED + len(self.groups)


def global_grad_norm(grads):
    """True global L2 norm over a gradient tree, f32-accumulated — THE
    reduction the audit reports when the clip path has none to reuse.
    One owner (here) so the audit's fallback in ``build_audit`` and the
    per-tensor-clip fallback in ``hapi/model.py`` can never diverge.
    (``ClipGradByGlobalNorm.clip_with_norm`` keeps its own reduction:
    the eager path filters ``Parameter.need_clip`` there, a semantic
    this tree-of-arrays helper deliberately does not have — in the
    functional train step the leaves are plain jnp arrays, so the
    filter never fires and the two reductions agree.)"""
    import jax.numpy as jnp
    sq = sum((jnp.sum(jnp.square(g.astype(jnp.float32)))
              for g in grads.values()), jnp.zeros((), jnp.float32))
    return jnp.sqrt(sq)


def build_audit(loss, grads, params, new_params, layout: AuditLayout,
                grad_norm=None, clipped_norm=None):
    """The device-side audit: jnp code TRACED INTO the donated train
    step (no program of its own — the zero-extra-programs contract).

    ``grads``/``params``/``new_params`` are the RAW trainable-param
    trees (grads pre-clip, so blame points at the true origin: a
    global-norm clip smears one NaN over every gradient). ``grad_norm``
    / ``clipped_norm`` reuse the clip path's reduction when the
    optimizer clips by global norm — the norm is never computed twice.
    Returns one f32 vector of ``layout.size`` elements (see the
    ``IDX_*`` layout constants)."""
    import jax.numpy as jnp

    loss_s = jnp.reshape(jnp.asarray(loss, jnp.float32), (-1,))[0]
    counts = []
    for g in layout.groups:
        c = jnp.zeros((), jnp.int32)
        for name in layout.members[g]:
            c = c + jnp.sum(~jnp.isfinite(grads[name])).astype(jnp.int32)
        counts.append(c)
    total_nonfinite = sum(counts, jnp.zeros((), jnp.int32))
    if grad_norm is None:
        grad_norm = global_grad_norm(grads)
    grad_norm = jnp.asarray(grad_norm, jnp.float32)
    clipped_norm = grad_norm if clipped_norm is None \
        else jnp.asarray(clipped_norm, jnp.float32)
    p_sq = sum((jnp.sum(jnp.square(p.astype(jnp.float32)))
                for p in params.values()), jnp.zeros((), jnp.float32))
    u_sq = sum((jnp.sum(jnp.square(new_params[k].astype(jnp.float32)
                                   - params[k].astype(jnp.float32)))
                for k in params), jnp.zeros((), jnp.float32))
    update_ok = jnp.ones((), bool)
    for v in new_params.values():
        update_ok = update_ok & jnp.all(jnp.isfinite(v))
    bits = (jnp.isfinite(loss_s).astype(jnp.float32) * BIT_LOSS
            + (total_nonfinite == 0).astype(jnp.float32) * BIT_GRADS
            + update_ok.astype(jnp.float32) * BIT_UPDATE)
    vec = jnp.stack([bits, loss_s, grad_norm, clipped_norm,
                     jnp.sqrt(p_sq), jnp.sqrt(u_sq)])
    if counts:
        vec = jnp.concatenate(
            [vec, jnp.stack(counts).astype(jnp.float32)])
    return vec


def build_audit_flat(loss, flat_grads, flat_params, flat_new_params,
                     group_ids, layout: AuditLayout, axis_name: str,
                     grad_norm=None, clipped_norm=None):
    """Sharded-stripe variant of :func:`build_audit` for the ZeRO train
    step (hapi/zero.py): each replica holds a 1/dp STRIPE of the flat
    gradient/param vectors, so every reduction carries a cross-shard
    ``psum`` term — the reported norms and finite bits cover the FULL
    (post-exchange, dequantized) gradient and update, never the local
    shard. ``flat_grads`` must be the post-reduce-scatter pre-clip
    stripe: under quantized comms that is the dequantized gradient, so
    quantization corruption is blamed at the exact step like any other
    nonfinite. ``group_ids`` maps each stripe element to its layer
    group (the extra ``len(groups)`` bucket is padding and is
    dropped). Same output layout as build_audit; decode_audit reads
    both. The vector is REPLICATED across the axis (every term is a
    psum/pmean), so the step returns it with a replicated out_spec."""
    import jax
    import jax.numpy as jnp

    n_groups = len(layout.groups)
    loss_s = jnp.reshape(jnp.asarray(loss, jnp.float32), (-1,))[0]
    nf = (~jnp.isfinite(flat_grads)).astype(jnp.int32)
    counts = jax.ops.segment_sum(nf, group_ids,
                                 num_segments=n_groups + 1)[:n_groups]
    counts = jax.lax.psum(counts, axis_name)
    total_nonfinite = jnp.sum(counts) if n_groups \
        else jax.lax.psum(jnp.sum(nf), axis_name)
    if grad_norm is None:
        grad_norm = jnp.sqrt(jax.lax.psum(
            jnp.sum(jnp.square(flat_grads.astype(jnp.float32))),
            axis_name))
    grad_norm = jnp.asarray(grad_norm, jnp.float32)
    clipped_norm = grad_norm if clipped_norm is None \
        else jnp.asarray(clipped_norm, jnp.float32)
    pf = flat_params.astype(jnp.float32)
    nf32 = flat_new_params.astype(jnp.float32)
    p_sq = jax.lax.psum(jnp.sum(jnp.square(pf)), axis_name)
    u_sq = jax.lax.psum(jnp.sum(jnp.square(nf32 - pf)), axis_name)
    bad_new = jax.lax.psum(
        jnp.sum((~jnp.isfinite(flat_new_params)).astype(jnp.int32)),
        axis_name)
    bits = (jnp.isfinite(loss_s).astype(jnp.float32) * BIT_LOSS
            + (total_nonfinite == 0).astype(jnp.float32) * BIT_GRADS
            + (bad_new == 0).astype(jnp.float32) * BIT_UPDATE)
    vec = jnp.stack([bits, loss_s, grad_norm, clipped_norm,
                     jnp.sqrt(p_sq), jnp.sqrt(u_sq)])
    if n_groups:
        vec = jnp.concatenate([vec, counts.astype(jnp.float32)])
    return vec


def decode_audit(vec: np.ndarray, layout: AuditLayout) -> Dict[str, Any]:
    """Host-side decode of one fetched audit vector (numpy in, plain
    Python out) into the per-step record the recorder rings."""
    v = np.asarray(vec, np.float64).ravel()
    bits = int(v[IDX_BITS])
    grad_norm = float(v[IDX_GRAD_NORM])
    clipped = float(v[IDX_CLIPPED_NORM])
    p_norm = float(v[IDX_PARAM_NORM])
    u_norm = float(v[IDX_UPDATE_NORM])
    rec: Dict[str, Any] = {
        "finite_bits": bits,
        "finite": bits == FINITE_ALL,
        "loss_finite": bool(bits & BIT_LOSS),
        "grads_finite": bool(bits & BIT_GRADS),
        "update_finite": bool(bits & BIT_UPDATE),
        "loss": float(v[IDX_LOSS]),
        "grad_norm": grad_norm,
        "clipped_grad_norm": clipped,
        "param_norm": p_norm,
        "update_norm": u_norm,
    }
    rec["update_ratio"] = (u_norm / p_norm) if p_norm > 0 else 0.0
    rec["clip_ratio"] = (clipped / grad_norm) \
        if (grad_norm > 0 and math.isfinite(grad_norm)) else 1.0
    rec["nonfinite_groups"] = {
        g: int(c) for g, c in zip(layout.groups, v[N_FIXED:]) if c > 0}
    return rec


def flag_mode() -> str:
    """Env-seeded default mode for ``Model.fit(numerics=None)``:
    ``FLAGS_numerics`` when set to a known mode (lenient normalization
    — a bad env value means un-audited, never a crash blaming an
    argument that was never passed); otherwise ``FLAGS_check_nan_inf``
    seeds ``'halt'`` — the reference flag ABORTS on the first NaN/Inf,
    and this is its windowed, zero-sync analog — else ``'off'``."""
    from ..framework.flags import flag_value
    v = str(flag_value("FLAGS_numerics") or "").strip().lower()
    if v in MODES:
        return v
    if v in ("1", "on", "true", "yes"):
        return "warn"
    if flag_value("FLAGS_check_nan_inf"):
        return "halt"
    return "off"


class NumericsRecorder:
    """The TRAINING flight recorder: a bounded ring of per-step numerics
    records plus the anomaly policy, mirroring the serving
    :class:`~..serving.flight_recorder.FlightRecorder` (host dicts,
    bounded, always on while numerics is armed, dumpable postmortem).

    Written by ``Model._flush_window`` (one ``record_window`` call per
    flush, decoded numpy in); read by anyone (``snapshot()`` / the
    postmortem dump) — the one small lock covers both, and writes are
    per-window, not per-step-dispatch, so contention is negligible."""

    def __init__(self, max_steps: int = 1024, max_anomalies: int = 64,
                 spike_zscore: float = 8.0, spike_min_history: int = 8,
                 spike_window: int = 64):
        if max_steps < 1:
            raise ValueError("max_steps must be >= 1")
        self._lock = threading.Lock()
        self._ring: deque = deque(maxlen=int(max_steps))
        self._anomalies: deque = deque(maxlen=int(max_anomalies))
        self.steps_recorded = 0       # monotonic (ring drops, this doesn't)
        self.anomalies_recorded = 0
        self.last_dump_path: Optional[str] = None
        self.dumps = 0
        self._spike_z = float(spike_zscore)
        self._spike_min = int(spike_min_history)
        self._spike_window = int(spike_window)
        self._run = 0        # fit generation (see new_run)
        # telemetry spine (ISSUE 13): live recorders are a statusz
        # section and a registry-collector source; weak, so a dropped
        # Model's recorder leaves the console with it
        global _recorder_seq
        _recorder_seq += 1
        self.recorder_id = _recorder_seq
        _LIVE_RECORDERS.add(self)

    def new_run(self) -> None:
        """Mark a fit boundary. The ring deliberately persists across
        fits (the flight-recorder continuity that makes postmortems
        useful), but the loss-SPIKE baseline must not: a new task/
        dataset whose healthy initial loss sits far from the previous
        run's converged median would otherwise z-score as a spike on
        its very first windows. Records are stamped with the run
        generation and the spike history reads only the current one."""
        with self._lock:
            self._run += 1

    # -- spike detection ---------------------------------------------------
    def _run_losses(self) -> List[float]:
        """The current run's finite losses in ring order — the spike
        baseline. Built ONCE per flush (record_window extends it
        incrementally as the window's records ring), so a big epoch-tail
        window costs O(window + ring), not O(window × ring)."""
        with self._lock:
            return [r["loss"] for r in self._ring
                    if r.get("run") == self._run
                    and math.isfinite(r.get("loss", math.nan))]

    def _spike_z_of(self, loss: float,
                    hist: List[float]) -> Optional[float]:
        """Robust z-score of ``loss`` against the recent FINITE losses
        in ``hist``: ``|x - median| / max(1.4826 * MAD, floor)``. The
        floor (1e-3 of the median's magnitude) keeps a perfectly flat
        loss history from turning any wiggle into an infinite score
        while still letting a genuine jump off a plateau register."""
        if not math.isfinite(loss):
            return None
        hist = hist[-self._spike_window:]
        if len(hist) < self._spike_min:
            return None
        med = statistics.median(hist)
        mad = statistics.median([abs(x - med) for x in hist])
        scale = max(1.4826 * mad, 1e-3 * max(1.0, abs(med)))
        return abs(loss - med) / scale

    # -- the per-flush entry point -----------------------------------------
    def record_window(self, entries: Sequence[Tuple[int, np.ndarray]],
                      layout: AuditLayout, *, mode: str = "record",
                      lr: Optional[float] = None,
                      scaler: Optional[dict] = None,
                      retrace_delta: int = 0,
                      ledger_bytes: Optional[int] = None,
                      context: Optional[dict] = None) -> Dict[str, Any]:
        """Ingest one flush window's decoded audits: feed the monitor,
        ring the per-step records, detect anomalies, and apply the
        policy. ``entries`` is ``[(global_step, numpy audit vector)]``
        in step order — ALREADY fetched by the caller (this module
        never syncs; the ``numerics-host-sync`` lint rule holds it to
        that).

        Returns the flush-log update (``grad_norm``, plus
        ``loss_scale`` when a scaler is active) for the ProgBar.
        Raises :class:`NumericsError` only in ``halt`` mode on a
        nonfinite step, AFTER the postmortem dump; a loss spike warns
        and dumps but never raises, and every other internal failure is
        the caller's to absorb."""
        anomalies: List[dict] = []
        last: Optional[dict] = None
        hist = self._run_losses()
        for step, vec in entries:
            rec = decode_audit(vec, layout)
            rec["step"] = int(step)
            rec["run"] = self._run
            if lr is not None:
                rec["lr"] = float(lr)
            if scaler is not None:
                rec["scaler"] = dict(scaler)
            # window-level context rides on every record of the window:
            # retraces since the last flush and the HBM-ledger watermark
            rec["retrace_delta"] = int(retrace_delta)
            if ledger_bytes is not None:
                rec["ledger_bytes"] = int(ledger_bytes)
            if math.isfinite(rec["grad_norm"]):
                stat_observe("hapi/grad_norm", rec["grad_norm"])
            if math.isfinite(rec["update_ratio"]):
                stat_observe("hapi/update_ratio", rec["update_ratio"])
            if math.isfinite(rec["clip_ratio"]):
                stat_observe("hapi/grad_clip_ratio", rec["clip_ratio"])
            if not rec["finite"]:
                stat_add("hapi/nonfinite_steps")
                anomalies.append({
                    "kind": "nonfinite", "step": rec["step"],
                    "loss_finite": rec["loss_finite"],
                    "grads_finite": rec["grads_finite"],
                    "update_finite": rec["update_finite"],
                    "blamed_groups": sorted(rec["nonfinite_groups"]),
                    "nonfinite_counts": rec["nonfinite_groups"],
                })
            else:
                z = self._spike_z_of(rec["loss"], hist)
                if z is not None and z >= self._spike_z:
                    stat_add("hapi/loss_spikes")
                    anomalies.append({
                        "kind": "loss_spike", "step": rec["step"],
                        "loss": rec["loss"], "zscore": round(z, 2),
                    })
            with self._lock:
                self._ring.append(rec)
                self.steps_recorded += 1
            if math.isfinite(rec["loss"]):
                hist.append(rec["loss"])
            last = rec
        logs: Dict[str, Any] = {}
        if last is not None:
            logs["grad_norm"] = last["grad_norm"]
            if scaler is not None:
                logs["loss_scale"] = float(scaler.get("scale", 0.0))
        if not anomalies:
            return logs
        with self._lock:
            for a in anomalies:
                self._anomalies.append(a)
                self.anomalies_recorded += 1
        if mode in ("warn", "halt"):
            hard = [a for a in anomalies if a["kind"] == "nonfinite"]
            lead = hard[0] if hard else anomalies[0]
            path = self.postmortem(lead, context=context)
            if mode == "halt" and hard:
                blamed = hard[0]["blamed_groups"] or "loss/update only"
                raise NumericsError(
                    f"nonfinite training numerics at step "
                    f"{hard[0]['step']} (blamed layer groups: {blamed}); "
                    f"anomaly postmortem: {path}")
            warnings.warn(
                f"training numerics anomaly: {lead} "
                f"(postmortem: {path})", RuntimeWarning, stacklevel=3)
        return logs

    # -- readers -----------------------------------------------------------
    def snapshot(self) -> Dict[str, Any]:
        with self._lock:
            return {
                "records": [dict(r) for r in self._ring],
                "anomalies": [dict(a) for a in self._anomalies],
                "steps_recorded": self.steps_recorded,
                "anomalies_recorded": self.anomalies_recorded,
                "ring_capacity": self._ring.maxlen,
            }

    def anomaly_list(self) -> List[dict]:
        with self._lock:
            return [dict(a) for a in self._anomalies]

    # -- postmortem --------------------------------------------------------
    def postmortem(self, anomaly: Optional[dict] = None,
                   path: Optional[str] = None,
                   context: Optional[dict] = None) -> Optional[str]:
        """Dump the numerics picture: the ring tail, the anomaly and its
        blamed layer groups, the active GradScaler state, a monitor
        snapshot (``hapi/``/``amp/``/``dispatch/`` counters plus the
        numerics histograms), and the path of a PR-7 MEMORY postmortem
        dumped alongside (profiler/memory.py — ledger, timeline,
        largest live arrays). Best effort and NEVER raises — it runs
        inside the flush's failure handling, and a broken disk must not
        replace the numerics error with an IO one. Returns the file
        path (``None`` on failure)."""
        try:
            mem_path = _memory.oom_postmortem(
                None, extra={"phase": "numerics",
                             "anomaly_step":
                                 (anomaly or {}).get("step")})
            hist_names = ("hapi/grad_norm", "hapi/update_ratio",
                          "hapi/grad_clip_ratio", "hapi/step_time_ms",
                          "hapi/host_sync_ms", "amp/loss_scale")
            with self._lock:
                ring = [dict(r) for r in self._ring]
                anoms = [dict(a) for a in self._anomalies]
            scaler = ring[-1].get("scaler") if ring else None
            doc: Dict[str, Any] = {
                "reason": "numerics anomaly" if anomaly is not None
                          else "requested",
                "anomaly": anomaly,
                # the full anomaly ring: once NaN propagates, every
                # later window re-dumps with ITS anomaly — the ORIGIN
                # (the first nonfinite step) must stay in the artifact
                "anomalies": anoms,
                "blamed_groups": (anomaly or {}).get("blamed_groups"),
                "dumped_at": time.time(),
                "ring": ring,
                "scaler": scaler,
                "monitor": {
                    "counters": {k: v for k, v in all_stats().items()
                                 if k.startswith(("hapi/", "amp/",
                                                  "dispatch/"))},
                    "histograms": {n: stat_histogram(n)
                                   for n in hist_names
                                   if stat_histogram(n) is not None},
                },
                "memory_postmortem": mem_path,
            }
            if context:
                doc["context"] = context
            if path is None:
                path = os.path.join(
                    tempfile.gettempdir(),
                    f"paddle_numerics_postmortem_{os.getpid()}_"
                    f"{id(self):x}.json")
            d = os.path.dirname(os.path.abspath(path))
            os.makedirs(d, exist_ok=True)
            with open(path, "w") as f:
                json.dump(doc, f, default=repr)
            with self._lock:
                self.last_dump_path = path
                self.dumps += 1
            stat_add("hapi/numerics_postmortem")
            return path
        except Exception:                                # noqa: BLE001
            return None

    def __repr__(self):
        with self._lock:
            return (f"<NumericsRecorder steps={len(self._ring)}/"
                    f"{self.steps_recorded} anomalies="
                    f"{self.anomalies_recorded} dumps={self.dumps}>")
