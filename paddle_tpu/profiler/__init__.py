"""``paddle.profiler`` — tracing/profiling surface.

Reference: python/paddle/profiler/profiler.py:271 (``Profiler`` with
scheduler states + ``RecordEvent`` annotations + chrome-trace export at
:158, stats in profiler_statistic.py); C++ host/device tracers under
paddle/fluid/platform/profiler/ (host_event_recorder.h ring buffers,
chrometracing_logger.cc).

TPU-native: device-side tracing is XLA's own — ``jax.profiler`` captures
an XPlane/TensorBoard trace of every compiled program, DMA and ICI
transfer, far richer than CUPTI hooks.  This module layers the reference's
API shape on top: a host-side event recorder (RecordEvent ranges on a ring
buffer, ≙ HostTracer) that ALSO forwards each range into the XPlane trace
via ``jax.profiler.TraceAnnotation``, a step-aware scheduler state
machine, chrome-trace JSON export of the host timeline, and a summary
table.  ``Profiler.start/stop`` bracket ``jax.profiler.start_trace/
stop_trace`` so one object drives both timelines.
"""
from .profiler import (  # noqa: F401
    Profiler, ProfilerState, ProfilerTarget, RecordEvent, load_profiler_result,
    make_scheduler, export_chrome_tracing, export_protobuf, SortedKeys,
)
from .xplane import device_op_table, summary_table  # noqa: F401

# structured span profiler (span.py): the substrate the framework's hot
# paths are instrumented with — record() spans, a profile() session, and
# chrome-trace / Prometheus / table exporters over spans + monitor stats
from .span import (  # noqa: F401
    record, profile, enable, disable, reset, is_active, events, dropped,
    span_summary, export_chrome_trace, export_prometheus,
)

# HBM memory tracker (memory.py): bounded device-stats timeline +
# byte ledger (train state, KV pools) + the OOM postmortem dump
from . import memory  # noqa: F401

# unified chrome-trace merger (timeline.py): host spans + request
# lanes + memory timeline + XPlane device ops on one clock in one file
from .timeline import export_unified_trace  # noqa: F401

# training numerics health (numerics.py): device-side NaN/Inf sentinels
# fused into the donated train step, gradient telemetry histograms, the
# train-loop flight recorder and the anomaly postmortem
from . import numerics  # noqa: F401
from .numerics import NumericsError  # noqa: F401

__all__ = ["Profiler", "ProfilerState", "ProfilerTarget", "RecordEvent",
           "make_scheduler", "export_chrome_tracing", "export_protobuf",
           "SortedKeys", "load_profiler_result", "device_op_table",
           "summary_table",
           "record", "profile", "enable", "disable", "reset", "is_active",
           "events", "dropped", "span_summary", "export_chrome_trace",
           "export_prometheus", "export_unified_trace", "memory",
           "numerics", "NumericsError"]
