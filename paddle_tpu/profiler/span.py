"""Structured span profiler: trace spans + exporters over the monitor stats.

Reference analog: paddle/fluid/platform/profiler/ — ``RecordEvent`` ranges
feeding a host event recorder, chrome-tracing export
(chrometracing_logger.cc) and the ``StatRegistry`` counter tables. The
class-based ``Profiler`` in profiler.py keeps the reference's *API shape*
(scheduler states, step()); this module is the low-level substrate the
framework itself is instrumented with:

* ``record(name, category)`` — span context manager AND decorator with
  thread-local nesting (each span knows its depth and parent) writing to
  one lock-guarded global event buffer;
* ``profile()`` — session context manager arming the buffer; when no
  session is active every instrumentation point reduces to ONE module-bool
  check (near-zero cost — the dispatch hot loop stays within the perf-gate
  budget);
* exporters — ``export_chrome_trace`` (chrome://tracing / Perfetto JSON),
  ``export_prometheus`` (text exposition of monitor counters, histograms
  and span aggregates), ``span_summary`` (human-readable table with
  p50/p95/p99 from the event buffer).

Threading contract: ``_Span.__exit__`` appends under ``_lock`` (spans are
orders of magnitude rarer than counter bumps, so a lock here is fine —
unlike monitor.stat_add, see framework/monitor.py for that contract); the
per-thread nesting stack is ``threading.local`` and needs no lock.

Flags: ``FLAGS_enable_profiler`` arms the buffer at import (env
``FLAGS_enable_profiler=1`` profiles a whole process without code
changes); ``FLAGS_profiler_max_events`` bounds the buffer — past the cap
events are counted in ``dropped()`` instead of appended, so a runaway
loop cannot eat the host's RAM.
"""
from __future__ import annotations

import functools
import json
import os
import threading
import time
from typing import Any, Callable, Dict, List, Optional

__all__ = ["record", "profile", "enable", "disable", "reset", "is_active",
           "events", "dropped", "add_event", "set_thread_name",
           "thread_names", "export_chrome_trace", "export_prometheus",
           "span_summary"]

# hot-path gate: instrumentation sites check this module attribute before
# allocating anything. Sessions nest (reentrant profile() is a no-op
# restart, not an error) via _active_count; _active mirrors count > 0.
_active = False
_active_count = 0
_lock = threading.Lock()
_events: List[tuple] = []   # (name, cat, t0, t1, tid, depth, parent, args)
_dropped = 0
_max_events = 1_000_000
_jax_bridge = False
_tls = threading.local()
# tid -> human label for the trace viewer (real python threads AND the
# synthetic per-request lanes the serving tracer emits). Survives
# reset() — lane identity is stable across sessions — and is bounded so
# a thread-churning server cannot grow it without limit.
_thread_names: Dict[int, str] = {}
# tid -> the Thread object that registered it (weakref; absent for
# synthetic lanes registered with an explicit tid). The OS REUSES thread
# idents: without owner tracking, a label registered by a long-dead
# thread would stick to its recycled ident forever and first-writer-wins
# would silently mislabel every later thread that inherits the ident
# (the order-dependent serving-trace flake).
_thread_owners: Dict[int, Any] = {}
_MAX_THREAD_NAMES = 4096


def _flag(name: str, default):
    try:
        from ..framework.flags import flag_value
        return flag_value(name)
    except Exception:
        return default


def is_active() -> bool:
    return _active


def dropped() -> int:
    """Events discarded because the buffer hit FLAGS_profiler_max_events."""
    return _dropped


_enable_stack: List[tuple] = []   # (max_events, jax_bridge) to restore


def enable(max_events: Optional[int] = None, jax_bridge: bool = False):
    """Arm the global span buffer (idempotent / reentrant). A nested
    enable may override the cap or turn the jax bridge on for its window;
    without explicit arguments it INHERITS the enclosing session's
    settings, and the matching disable always restores them."""
    global _active, _active_count, _max_events, _jax_bridge
    with _lock:
        nested = _active_count > 0
        _active_count += 1
        _enable_stack.append((_max_events, _jax_bridge))
        if max_events is not None:
            _max_events = int(max_events)
        elif not nested:
            _max_events = int(_flag("FLAGS_profiler_max_events",
                                    _max_events))
        _jax_bridge = _jax_bridge or jax_bridge
        _active = True


def disable():
    global _active, _active_count, _max_events, _jax_bridge
    with _lock:
        _active_count = max(0, _active_count - 1)
        if _enable_stack:
            _max_events, _jax_bridge = _enable_stack.pop()
        if _active_count == 0:
            _active = False
            _jax_bridge = False


_generation = 0   # bumped by reset(): spans begun before a reset are stale


def reset():
    """Drop all buffered events (does not change the active state)."""
    global _dropped, _generation
    with _lock:
        _events.clear()
        _dropped = 0
        _generation += 1


def events() -> List[Dict[str, Any]]:
    """Snapshot of the buffer as dicts (ts/dur in microseconds)."""
    with _lock:
        snap = list(_events)
    out = []
    for name, cat, t0, t1, tid, depth, parent, args in snap:
        out.append({"name": name, "cat": cat, "ts": t0 * 1e6,
                    "dur": (t1 - t0) * 1e6, "tid": tid, "depth": depth,
                    "parent": parent, "args": args})
    return out


def set_thread_name(name: str, tid: Optional[int] = None) -> None:
    """Label a trace lane for the chrome-trace viewer: the calling
    thread's by default, or an explicit ``tid`` (used for the serving
    tracer's synthetic per-request lanes). The export emits these as
    ``thread_name`` metadata events so the viewer shows "serving
    scheduler" instead of a bare thread ident. Cheap enough to call
    unconditionally; first-writer-wins per tid keeps a thread that
    plays several roles from flapping — but a label whose registering
    thread has DIED is stale (the OS recycles idents), so the current
    thread reclaims its own ident instead of inheriting a dead
    thread's role."""
    import weakref
    cur = None
    if tid is None:
        tid = threading.get_ident()
        cur = threading.current_thread()
    with _lock:
        if tid in _thread_names:
            owner = _thread_owners.get(tid)
            # single deref: GC may collect the Thread between checks
            owner_thread = owner() if owner is not None else None
            alive = owner_thread is not None and owner_thread.is_alive()
            if cur is None or alive:
                return          # same-thread role flap / synthetic lane
        elif len(_thread_names) >= _MAX_THREAD_NAMES:
            return
        _thread_names[tid] = str(name)
        if cur is not None:
            _thread_owners[tid] = weakref.ref(cur)


def thread_names() -> Dict[int, str]:
    with _lock:
        return dict(_thread_names)


def add_event(name: str, category: str, t0: float, t1: float, *,
              tid: Optional[int] = None, depth: int = 0,
              parent: Optional[str] = None,
              args: Optional[dict] = None) -> None:
    """Append one already-timed span to the buffer (same gate/cap as a
    live ``record()`` span). The escape hatch for events whose begin/end
    do not bracket a code region on the current thread — e.g. a serving
    request's lifecycle, reconstructed onto a synthetic lane when it
    finishes. ``t0``/``t1`` are ``time.perf_counter()`` seconds."""
    if not _active:
        return
    global _dropped
    if tid is None:
        tid = threading.get_ident()
    with _lock:
        if len(_events) < _max_events:
            _events.append((name, category, float(t0), float(t1),
                            int(tid), int(depth), parent, args))
        else:
            _dropped += 1


def _stack() -> list:
    st = getattr(_tls, "stack", None)
    if st is None:
        st = _tls.stack = []
    return st


class _Span:
    """One annotation range. Context manager, begin()/end() pair, and
    decorator (``@record("name", "cat")``). All state is per-instance, so
    a scheduler flip between begin and end cannot desync the thread-local
    nesting stack."""

    __slots__ = ("name", "category", "args", "_t0", "_depth", "_parent",
                 "_ann", "_open", "_gen")

    def __init__(self, name: str, category: str = "user",
                 args: Optional[dict] = None):
        self.name = name
        self.category = category
        self.args = args
        self._t0 = None
        self._ann = None
        self._open = False

    def begin(self):
        if not _active:
            return self
        st = _stack()
        self._parent = st[-1] if st else None
        self._depth = len(st)
        st.append(self.name)
        self._open = True
        self._gen = _generation
        if _jax_bridge:
            # guarded bridge: the span also lands in the XLA/XPlane trace
            # when a jax device trace is running (TensorBoard alignment)
            try:
                import jax
                self._ann = jax.profiler.TraceAnnotation(self.name)
                self._ann.__enter__()
            except Exception:
                self._ann = None
        self._t0 = time.perf_counter()
        return self

    def end(self):
        if not self._open:
            return
        t1 = time.perf_counter()
        if self._ann is not None:
            try:
                self._ann.__exit__(None, None, None)
            finally:
                self._ann = None
        st = _stack()
        if st and st[-1] is self.name:
            st.pop()
        elif self.name in st:          # unbalanced exit: repair, don't leak
            st.remove(self.name)
        self._open = False
        global _dropped
        with _lock:
            if self._gen != _generation:
                pass   # the buffer was reset mid-span (a new session
                       # started): a stale event from the old timeline
                       # must not pollute the new session's trace
            elif len(_events) < _max_events:
                _events.append((self.name, self.category, self._t0, t1,
                                threading.get_ident(), self._depth,
                                self._parent, self.args))
            else:
                _dropped += 1
        self._t0 = None

    def __enter__(self):
        return self.begin()

    def __exit__(self, *exc):
        self.end()
        return False

    def __call__(self, fn: Callable) -> Callable:
        name = self.name or getattr(fn, "__qualname__", fn.__name__)
        category, args = self.category, self.args

        @functools.wraps(fn)
        def wrapper(*a, **kw):
            if not _active:           # decoration-time state is irrelevant;
                return fn(*a, **kw)   # activity is sampled per call
            with _Span(name, category, args):
                return fn(*a, **kw)

        return wrapper


def record(name: str, category: str = "user",
           args: Optional[dict] = None) -> _Span:
    """Span over a code region: ``with record("op/add", "dispatch"): ...``
    or ``@record("step", "hapi")``. A no-op (one bool check) when no
    ``profile()`` session is active."""
    return _Span(name, category, args)


class _Session:
    """Handle returned by ``profile()`` — scopes the armed buffer and
    carries the exporters so the common flow reads::

        with profiler.profile() as sess:
            model.train_batch(...)
        sess.export_chrome_trace("trace.json")
    """

    def __init__(self, max_events=None, jax_bridge=False, clear=True):
        self._max_events = max_events
        self._jax_bridge = jax_bridge
        self._clear = clear

    def __enter__(self):
        # never clear when nesting inside an active session — an inner
        # window (e.g. ProfilerCallback inside a user's own profile())
        # must not wipe the outer session's buffer
        if self._clear and _active_count == 0:
            reset()
        enable(self._max_events, self._jax_bridge)
        return self

    def __exit__(self, *exc):
        disable()
        return False

    # exporters operate on the retained buffer, usable after __exit__
    events = staticmethod(events)
    dropped = staticmethod(dropped)

    def export_chrome_trace(self, path: str) -> str:
        return export_chrome_trace(path)

    def export_prometheus(self, path: Optional[str] = None) -> str:
        return export_prometheus(path)

    def summary(self) -> str:
        return span_summary()


def profile(max_events: Optional[int] = None, jax_bridge: bool = False,
            clear: bool = True) -> _Session:
    """Profiling session context manager. Entering arms the global span
    buffer (cleared first unless ``clear=False``); leaving disarms it but
    KEEPS the events so the session's exporters still work."""
    return _Session(max_events, jax_bridge, clear)


# ---------------------------------------------------------------------------
# exporters
# ---------------------------------------------------------------------------

def export_chrome_trace(path: str) -> str:
    """Write the buffered spans as a chrome://tracing (catapult) JSON file
    — ``ph:"X"`` complete events with ``cat``/``ts``/``dur`` in
    microseconds, one ``tid`` lane per python thread. Open in
    chrome://tracing, Perfetto, or speedscope."""
    pid = os.getpid()
    trace = [{"name": "process_name", "ph": "M", "pid": pid,
              "args": {"name": "paddle_tpu"}}]
    # thread/lane labels: scheduler, submitter and stream-consumer
    # threads (and the serving tracer's per-request lanes) show their
    # registered names in the viewer instead of bare tids
    for tid, tname in sorted(thread_names().items()):
        trace.append({"name": "thread_name", "ph": "M", "pid": pid,
                      "tid": tid, "args": {"name": tname}})
    for ev in events():
        trace.append({
            "name": ev["name"], "cat": ev["cat"], "ph": "X", "pid": pid,
            "tid": ev["tid"], "ts": ev["ts"], "dur": ev["dur"],
            "args": {"depth": ev["depth"], "parent": ev["parent"],
                     **(ev["args"] or {})},
        })
    doc = {"traceEvents": trace, "displayTimeUnit": "ms"}
    d = os.path.dirname(os.path.abspath(path))
    os.makedirs(d, exist_ok=True)
    with open(path, "w") as f:
        json.dump(doc, f)
    return path


def _span_aggregates() -> Dict[tuple, list]:
    agg: Dict[tuple, list] = {}
    for ev in events():
        agg.setdefault((ev["cat"], ev["name"]), []).append(ev["dur"] / 1e3)
    return agg


def span_summary() -> str:
    """Human-readable per-span table (calls, total/avg/p50/p95/p99 ms),
    sorted by total time — the profiler_statistic table analog."""
    from ..framework.monitor import _percentile
    agg = _span_aggregates()
    if not agg:
        return "(no spans recorded)"
    rows = sorted(agg.items(), key=lambda kv: -sum(kv[1]))
    head = (f"{'Category':<12} {'Name':<36} {'Calls':>7} {'Total(ms)':>11} "
            f"{'Avg(ms)':>9} {'p50':>8} {'p95':>8} {'p99':>8}")
    lines = [head, "-" * len(head)]
    for (cat, name), durs in rows:
        s = sorted(durs)
        tot = sum(durs)
        lines.append(
            f"{cat:<12} {name:<36} {len(durs):>7} {tot:>11.3f} "
            f"{tot / len(durs):>9.3f} {_percentile(s, 0.5):>8.3f} "
            f"{_percentile(s, 0.95):>8.3f} {_percentile(s, 0.99):>8.3f}")
    if _dropped:
        lines.append(f"(+ {_dropped} events dropped at the "
                     f"FLAGS_profiler_max_events={_max_events} cap)")
    return "\n".join(lines)


def export_prometheus(path: Optional[str] = None) -> str:
    """Prometheus text exposition (v0.0.4) of the full observability
    surface: monitor counters as a counter family, monitor histograms and
    span durations as summary families with quantile labels. Returns the
    text; also writes it to ``path`` when given (point a node_exporter
    textfile collector at it)."""
    from ..framework import monitor

    def _num(v: float) -> str:
        # exact exposition: %g truncates past 6 significant digits, which
        # makes large monotone counters (collective_bytes, op_count past
        # 1e6) appear frozen between scrapes; .17g round-trips any float
        f = float(v)
        return str(int(f)) if f.is_integer() else f"{f:.17g}"

    def esc(v: str) -> str:
        return v.replace("\\", "\\\\").replace('"', '\\"').replace(
            "\n", "\\n")

    lines = ["# HELP paddle_tpu_counter process-wide monitor counters",
             "# TYPE paddle_tpu_counter counter"]
    for name, val in sorted(monitor.all_stats().items()):
        lines.append(f'paddle_tpu_counter{{name="{esc(name)}"}} {_num(val)}')
    lines.append("# HELP paddle_tpu_stat monitor value distributions")
    lines.append("# TYPE paddle_tpu_stat summary")
    for name, h in sorted(monitor.all_histograms().items()):
        n = esc(name)
        for q, key in ((0.5, "p50"), (0.95, "p95"), (0.99, "p99")):
            lines.append(f'paddle_tpu_stat{{name="{n}",quantile="{q}"}} '
                         f'{_num(h[key])}')
        lines.append(f'paddle_tpu_stat_sum{{name="{n}"}} {_num(h["sum"])}')
        lines.append(f'paddle_tpu_stat_count{{name="{n}"}} {h["count"]}')
    lines.append("# HELP paddle_tpu_span_ms profiler span durations (ms)")
    lines.append("# TYPE paddle_tpu_span_ms summary")
    for (cat, name), durs in sorted(_span_aggregates().items()):
        from ..framework.monitor import _percentile
        s = sorted(durs)
        lab = f'name="{esc(name)}",category="{esc(cat)}"'
        for q in (0.5, 0.95, 0.99):
            lines.append(f'paddle_tpu_span_ms{{{lab},quantile="{q}"}} '
                         f'{_num(_percentile(s, q))}')
        lines.append(f'paddle_tpu_span_ms_sum{{{lab}}} {_num(sum(durs))}')
        lines.append(f'paddle_tpu_span_ms_count{{{lab}}} {len(durs)}')
    text = "\n".join(lines) + "\n"
    if path:
        os.makedirs(os.path.dirname(os.path.abspath(path)), exist_ok=True)
        with open(path, "w") as f:
            f.write(text)
    return text


# env-seeded whole-process profiling: FLAGS_enable_profiler=1 arms the
# buffer from import, no code changes needed (flags.py seeds from env)
if _flag("FLAGS_enable_profiler", False):
    enable()
