"""Profiler implementation (reference: python/paddle/profiler/profiler.py)."""
from __future__ import annotations

import enum
import json
import os
import threading
import time
from typing import Callable, Iterable, Optional

__all__ = ["Profiler", "ProfilerState", "ProfilerTarget", "RecordEvent",
           "make_scheduler", "export_chrome_tracing",
           "load_profiler_result"]


class ProfilerState(enum.Enum):
    """Reference: profiler.py ProfilerState (scheduler output per step)."""
    CLOSED = 0
    READY = 1
    RECORD = 2
    RECORD_AND_RETURN = 3   # last record step of a cycle: trace is returned


class ProfilerTarget(enum.Enum):
    CPU = 0
    GPU = 1   # accepted for reference API parity; maps to device tracing
    TPU = 2


def make_scheduler(*, closed: int, ready: int, record: int,
                   repeat: int = 0, skip_first: int = 0
                   ) -> Callable[[int], ProfilerState]:
    """Reference: profiler.py make_scheduler — cyclic CLOSED^closed →
    READY^ready → RECORD^(record-1) → RECORD_AND_RETURN, repeated
    ``repeat`` times (0 = forever), after ``skip_first`` CLOSED steps."""
    if closed < 0 or ready < 0 or record < 1:
        raise ValueError("make_scheduler: need closed>=0, ready>=0, "
                         "record>=1")
    span = closed + ready + record

    def schedule(step: int) -> ProfilerState:
        if step < skip_first:
            return ProfilerState.CLOSED
        step -= skip_first
        if repeat and step >= repeat * span:
            return ProfilerState.CLOSED
        pos = step % span
        if pos < closed:
            return ProfilerState.CLOSED
        if pos < closed + ready:
            return ProfilerState.READY
        if pos == span - 1:
            return ProfilerState.RECORD_AND_RETURN
        return ProfilerState.RECORD

    return schedule


def _default_state_scheduler(step: int) -> ProfilerState:
    return ProfilerState.RECORD


# ---------------------------------------------------------------------------
# host event recorder (≙ HostTracer ring buffers, host_event_recorder.h)
# ---------------------------------------------------------------------------

class _HostEvent:
    __slots__ = ("name", "t0", "t1", "tid", "step")

    def __init__(self, name, t0, t1, tid, step):
        self.name, self.t0, self.t1 = name, t0, t1
        self.tid, self.step = tid, step


class _HostRecorder:
    def __init__(self, capacity: int = 1_000_000):
        self.events: list[_HostEvent] = []
        self.capacity = capacity
        self.enabled = False
        self._lock = threading.Lock()

    def add(self, ev: _HostEvent):
        with self._lock:
            if len(self.events) < self.capacity:
                self.events.append(ev)

    def clear(self):
        with self._lock:
            self.events = []


_recorder = _HostRecorder()
_recorder.native_active = False
_current_step = [0]


def _native():
    from . import native as _native_mod
    return _native_mod


class RecordEvent:
    """User annotation range (reference: profiler.py RecordEvent).

    Context manager AND begin/end object; when a device trace is active the
    range also lands in the XPlane timeline via TraceAnnotation so host
    annotations line up with XLA executions in TensorBoard.
    """

    def __init__(self, name: str, event_type=None):
        self.name = name
        self._t0 = None
        self._ann = None
        self._native_open = False

    def begin(self):
        self._t0 = time.perf_counter()
        if _recorder.enabled:
            if _recorder.native_active:
                _native().begin(self.name)
                self._native_open = True
            try:
                import jax
                self._ann = jax.profiler.TraceAnnotation(self.name)
                self._ann.__enter__()
            except Exception:
                self._ann = None

    def end(self):
        if self._t0 is None:
            return
        t1 = time.perf_counter()
        if self._ann is not None:
            self._ann.__exit__(None, None, None)
            self._ann = None
        if self._native_open:
            # paired per-instance: an end never pops a range it didn't open
            # (scheduler transitions between begin and end can't desync the
            # native stack)
            _native().end()
            self._native_open = False
        if _recorder.enabled:
            _recorder.add(_HostEvent(self.name, self._t0, t1,
                                     threading.get_ident(),
                                     _current_step[0]))
        self._t0 = None

    def __enter__(self):
        self.begin()
        return self

    def __exit__(self, *exc):
        self.end()
        return False


def export_chrome_tracing(dir_name: str, worker_name: Optional[str] = None):
    """Reference: profiler.py export_chrome_tracing — returns an
    ``on_trace_ready`` callback writing a chrome trace per cycle."""

    def handler(prof: "Profiler"):
        os.makedirs(dir_name, exist_ok=True)
        name = worker_name or f"host_{os.getpid()}"
        path = os.path.join(
            dir_name, f"{name}_step{_current_step[0]}.json")
        prof.export(path)

    return handler


def load_profiler_result(path: str):
    """Load a chrome trace JSON written by Profiler.export."""
    with open(path) as f:
        return json.load(f)


class Profiler:
    """Reference: profiler.py:271.

    ``targets`` including TPU/GPU turns on the XPlane device trace
    (written to ``trace_dir``, viewable in TensorBoard/XProf/Perfetto);
    the host RecordEvent timeline is always captured and exportable as
    chrome trace JSON via ``export``.
    """

    def __init__(self, *, targets: Optional[Iterable] = None,
                 scheduler=None, on_trace_ready=None,
                 trace_dir: Optional[str] = None, timer_only: bool = False,
                 use_native: Optional[bool] = None):
        # use_native: mirror host ranges into the C++ tpu_prof recorder
        # (native/tpu_prof.cc, ~100ns/event). Resolved HERE — a first-use
        # build (g++ subprocess) must happen at construction, never inside
        # the profiled region.
        if use_native or use_native is None:
            requested = bool(use_native)
            use_native = _native().available()
            if requested and not use_native:
                import warnings
                warnings.warn("use_native=True but the tpu_prof extension "
                              "is unavailable; falling back to the python "
                              "recorder")
        self._use_native = bool(use_native)
        self._native_session = False
        if scheduler is None:
            self._scheduler = _default_state_scheduler
        elif callable(scheduler):
            self._scheduler = scheduler
        elif isinstance(scheduler, (tuple, list)) and len(scheduler) == 2:
            start, end = scheduler
            self._scheduler = make_scheduler(
                closed=max(start - 1, 0), ready=1 if start > 0 else 0,
                record=end - start, repeat=1)
        else:
            raise TypeError(f"bad scheduler: {scheduler!r}")
        targets = list(targets) if targets is not None else \
            [ProfilerTarget.CPU, ProfilerTarget.TPU]
        self._device_trace = any(
            t in (ProfilerTarget.TPU, ProfilerTarget.GPU) for t in targets)
        self._timer_only = timer_only
        self._on_trace_ready = on_trace_ready
        self.trace_dir = trace_dir or os.path.join(
            os.getcwd(), "paddle_profiler_trace")
        self._device_active = False
        self.current_state = ProfilerState.CLOSED
        self._step_t0 = None
        self._step_times: list[float] = []

    # -- lifecycle ---------------------------------------------------------
    def start(self):
        self.current_state = self._scheduler(_current_step[0])
        self._transition(ProfilerState.CLOSED, self.current_state)
        self._step_t0 = time.perf_counter()
        self._session_t0_us = self._step_t0 * 1e6
        return self

    def stop(self):
        self._transition(self.current_state, ProfilerState.CLOSED)
        self.current_state = ProfilerState.CLOSED
        self._session_t1_us = time.perf_counter() * 1e6
        if self._native_session:
            _native().disable()
        if self._on_trace_ready is not None and _recorder.events:
            self._on_trace_ready(self)

    def step(self):
        """Advance the scheduler one training step."""
        now = time.perf_counter()
        if self._step_t0 is not None:
            self._step_times.append(now - self._step_t0)
        self._step_t0 = now
        old = self.current_state
        _current_step[0] += 1
        new = self._scheduler(_current_step[0])
        self._transition(old, new)
        self.current_state = new
        if old == ProfilerState.RECORD_AND_RETURN and \
                self._on_trace_ready is not None:
            self._on_trace_ready(self)

    def _transition(self, old: ProfilerState, new: ProfilerState):
        was = old in (ProfilerState.RECORD, ProfilerState.RECORD_AND_RETURN)
        now = new in (ProfilerState.RECORD, ProfilerState.RECORD_AND_RETURN)
        if now and not was:
            _recorder.enabled = True
            if self._use_native:
                if not self._native_session:
                    # first RECORD of this profiler: clear + arm; later
                    # cycles AND restarts resume without clearing, so the
                    # native lane accumulates like the python lane
                    _native().enable()
                    self._native_session = True
                else:
                    _native().resume()
                _recorder.native_active = True
            if self._device_trace and not self._timer_only and \
                    not self._device_active:
                try:
                    import jax
                    jax.profiler.start_trace(self.trace_dir)
                    self._device_active = True
                    # host anchor for the unified-timeline merger: the
                    # xplane's device clock is aligned by pinning its
                    # first event to this perf_counter stamp
                    import time as _t
                    self._trace_anchor_us = _t.perf_counter() * 1e6
                except Exception:
                    self._device_active = False
        elif was and not now:
            _recorder.enabled = False
            _recorder.native_active = False
            if self._device_active:
                try:
                    import jax
                    jax.profiler.stop_trace()
                except Exception:
                    pass
                self._device_active = False

    def __enter__(self):
        return self.start()

    def __exit__(self, *exc):
        self.stop()
        return False

    # -- results -----------------------------------------------------------
    def export(self, path: str, format: str = "json"):
        """Write the host timeline as a chrome trace (catapult) file.

        Reference: chrome-trace export profiler.py:158 /
        chrometracing_logger.cc. The XPlane device trace is exported
        separately by jax into ``trace_dir``.
        """
        events = []
        for ev in _recorder.events:
            events.append({
                "name": ev.name, "ph": "X", "pid": os.getpid(),
                "tid": ev.tid, "ts": ev.t0 * 1e6,
                "dur": (ev.t1 - ev.t0) * 1e6,
                "args": {"step": ev.step},
            })
        doc = {"traceEvents": events, "displayTimeUnit": "ms"}
        if self._native_session and _native().count():
            # merge the native recorder's (monotonic-clock) timeline as a
            # separate pid lane
            doc = _native().merge_into(doc)
        os.makedirs(os.path.dirname(os.path.abspath(path)), exist_ok=True)
        with open(path, "w") as f:
            json.dump(doc, f)
        return path

    def summary(self, sorted_by=None, op_detail=True, thread_sep=False,
                time_unit="ms"):
        """Aggregate host ranges by name (≙ profiler_statistic tables)."""
        agg = {}
        for ev in _recorder.events:
            tot, cnt = agg.get(ev.name, (0.0, 0))
            agg[ev.name] = (tot + (ev.t1 - ev.t0), cnt + 1)
        scale = {"s": 1.0, "ms": 1e3, "us": 1e6}[time_unit]
        rows = sorted(agg.items(), key=lambda kv: -kv[1][0])
        lines = [f"{'Name':<40} {'Calls':>8} {'Total(' + time_unit + ')':>14}"
                 f" {'Avg(' + time_unit + ')':>12}"]
        for name, (tot, cnt) in rows:
            lines.append(f"{name:<40} {cnt:>8} {tot * scale:>14.3f} "
                         f"{tot * scale / cnt:>12.3f}")
        if self._step_times:
            import numpy as np
            st = np.asarray(self._step_times[1:] or self._step_times)
            lines.append(f"{'[step]':<40} {len(st):>8} "
                         f"{st.sum() * scale:>14.3f} "
                         f"{st.mean() * scale:>12.3f}")
        if self._device_trace and not self._timer_only:
            # per-op device-time table decoded from the XPlane trace
            # (reference: profiler_statistic.py's device view; r3 weak #9)
            from .xplane import summary_table
            lines.append("")
            lines.append("-- Device ops (from XPlane) " + "-" * 48)
            lines.append(summary_table(self.trace_dir))
        return "\n".join(lines)

    def device_op_table(self, device_only: bool = True):
        """Raw per-op device-time rows from the XPlane trace:
        [{name, plane, calls, total_us, avg_us}] sorted by total."""
        from .xplane import device_op_table
        return device_op_table(self.trace_dir, device_only=device_only)

    def export_unified(self, path: str) -> str:
        """ONE chrome-trace file with everything on one clock: the span
        profiler's host timeline (serving request lanes included), the
        HBM memory timeline as counter/instant events, and this
        profiler's XPlane device ops aligned via the start_trace host
        anchor (:mod:`.timeline`)."""
        from .timeline import export_unified_trace
        t0 = getattr(self, "_session_t0_us", None)
        t1 = getattr(self, "_session_t1_us", None)
        window = (t0, t1) if t0 is not None and t1 is not None else None
        return export_unified_trace(
            path, trace_dir=self.trace_dir,
            anchor_us=getattr(self, "_trace_anchor_us", None),
            window_us=window)

    @property
    def events(self):
        return list(_recorder.events)

    def reset(self):
        _recorder.clear()
        self._step_times = []


class SortedKeys:
    """Sort keys for Profiler.summary (reference profiler/profiler.py
    SortedKeys enum)."""
    CPUTotal = 0
    CPUAvg = 1
    CPUMax = 2
    CPUMin = 3
    GPUTotal = 4
    GPUAvg = 5
    GPUMax = 6
    GPUMin = 7


def export_protobuf(dir_name: str, worker_name: Optional[str] = None):
    """Reference: profiler.export_protobuf — an ``on_trace_ready``
    handler keeping the protobuf-format device trace. Here the XPlane
    .pb files ARE the protobuf result (written by the XLA profiler into
    the Profiler's trace_dir); the handler copies the newest into
    ``dir_name``."""

    def handler(prof: "Profiler"):
        import glob
        import shutil
        os.makedirs(dir_name, exist_ok=True)
        trace_dir = getattr(prof, "_trace_dir", None) or \
            getattr(prof, "trace_dir", None)
        if not trace_dir:
            return
        files = glob.glob(os.path.join(trace_dir, "**", "*.xplane.pb"),
                          recursive=True)
        name = worker_name or f"host_{os.getpid()}"
        for i, f in enumerate(sorted(files, key=os.path.getmtime)[-1:]):
            shutil.copy(f, os.path.join(dir_name, f"{name}.xplane.pb"))

    return handler
