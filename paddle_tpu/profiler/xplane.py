"""XPlane (.xplane.pb) parser + device-op statistics.

Reference analog: the profiler_statistic.py device-time tables built from
the C++ HostTraceAnalyzer/ChromeTracingLogger stack
(python/paddle/profiler/profiler_statistic.py). TPU-native: the device
timeline comes out of PjRt/XLA as an XPlane protobuf written by
``jax.profiler.start_trace``; this module decodes it with a ~100-line
wire-format reader (no tensorflow/tensorboard dependency in the image)
and aggregates per-op device time.

XPlane schema (tensorflow/core/profiler/protobuf/xplane.proto):
XSpace.planes[].lines[].events[] with event durations in picoseconds and
names interned in plane-level event_metadata.
"""
from __future__ import annotations

import glob
import os
from typing import Dict, Iterator, List, Optional, Tuple

__all__ = ["parse_xspace", "device_op_table", "device_events",
           "latest_xplane_file", "summary_table"]


# ---------------------------------------------------------------------------
# minimal protobuf wire-format reader
# ---------------------------------------------------------------------------

def _varint(buf: bytes, i: int) -> Tuple[int, int]:
    out = 0
    shift = 0
    while True:
        b = buf[i]
        i += 1
        out |= (b & 0x7F) << shift
        if not b & 0x80:
            return out, i
        shift += 7


def _fields(buf: bytes) -> Iterator[Tuple[int, int, object]]:
    """Yield (field_number, wire_type, value). Length-delimited values are
    bytes; varints are ints; fixed32/64 are raw ints."""
    i, n = 0, len(buf)
    while i < n:
        tag, i = _varint(buf, i)
        field, wire = tag >> 3, tag & 7
        if wire == 0:  # varint
            val, i = _varint(buf, i)
        elif wire == 1:  # fixed64
            val = int.from_bytes(buf[i:i + 8], "little")
            i += 8
        elif wire == 2:  # length-delimited
            ln, i = _varint(buf, i)
            val = buf[i:i + ln]
            i += ln
        elif wire == 5:  # fixed32
            val = int.from_bytes(buf[i:i + 4], "little")
            i += 4
        else:
            raise ValueError(f"unsupported wire type {wire}")
        yield field, wire, val


def _parse_event(buf: bytes) -> Tuple[int, int, int]:
    """XEvent -> (metadata_id, offset_ps, duration_ps)."""
    meta, off, dur = 0, 0, 0
    for field, _, val in _fields(buf):
        if field == 1:
            meta = val
        elif field == 2:
            off = val
        elif field == 3:
            dur = val
    return meta, off, dur


def _parse_line(buf: bytes) -> Tuple[str, int, List[Tuple[int, int, int]]]:
    """XLine -> (name, timestamp_ns, [(metadata_id, offset_ps,
    duration_ps)]). ``timestamp_ns`` is the line's epoch on the
    producer's clock; event offsets are relative to it — the unified
    timeline merger needs both to place device ops on the host axis."""
    name = ""
    ts_ns = 0
    events = []
    for field, _, val in _fields(buf):
        if field == 2:
            name = val.decode("utf-8", "replace")
        elif field == 3:
            ts_ns = val
        elif field == 4:
            events.append(_parse_event(val))
    return name, ts_ns, events


def _parse_event_metadata(buf: bytes) -> Tuple[int, str]:
    """map entry -> XEventMetadata -> (id, name)."""
    mid, name = 0, ""
    for field, _, val in _fields(buf):
        if field == 1:  # map key
            mid = val
        elif field == 2:  # map value: XEventMetadata
            for f2, _, v2 in _fields(val):
                if f2 == 2:
                    name = v2.decode("utf-8", "replace")
                elif f2 == 4 and not name:
                    name = v2.decode("utf-8", "replace")
    return mid, name


def _parse_plane(buf: bytes) -> dict:
    name = ""
    lines = []
    meta: Dict[int, str] = {}
    for field, _, val in _fields(buf):
        if field == 2:
            name = val.decode("utf-8", "replace")
        elif field == 3:
            lines.append(_parse_line(val))
        elif field == 4:
            mid, mname = _parse_event_metadata(val)
            meta[mid] = mname
    return {"name": name, "lines": lines, "event_metadata": meta}


def parse_xspace(data: bytes) -> List[dict]:
    """XSpace bytes -> [{name, lines: [(line_name, timestamp_ns,
    [(meta_id, offset_ps, dur_ps)])], event_metadata: {id: name}}]."""
    return [_parse_plane(val) for field, _, val in _fields(data)
            if field == 1]


def _is_device_line(plane_name: str, line_name: str) -> bool:
    """Version-tolerant "is this a device/executable timeline" test.

    On TPU the device ops live in ``/device:TPU:*`` planes. On the CPU
    backend they live in the host plane, in the XLA client's line —
    whose NAME drifts with the jax/xla version: ``XLAPjRt...`` on
    older stacks, ``tf_XLATfrtCpuClient/<id>`` on the 0.4.37 image
    (the drift that emptied ``device_op_table`` here). Match the
    stable substring — an XLA-client marker — rather than any one
    release's spelling."""
    if ("/device:" in plane_name or "TPU" in plane_name
            or "GPU" in plane_name):
        return True
    return "XLA" in line_name


# ---------------------------------------------------------------------------
# statistics
# ---------------------------------------------------------------------------

def latest_xplane_file(trace_dir: str) -> Optional[str]:
    files = glob.glob(os.path.join(trace_dir, "**", "*.xplane.pb"),
                      recursive=True)
    return max(files, key=os.path.getmtime) if files else None


def device_op_table(trace_dir: str, device_only: bool = True
                    ) -> List[dict]:
    """Aggregate per-op device time from the newest xplane.pb under
    ``trace_dir``. Returns rows sorted by total time:
    {name, plane, calls, total_us, avg_us}."""
    path = latest_xplane_file(trace_dir)
    if path is None:
        return []
    with open(path, "rb") as f:
        planes = parse_xspace(f.read())
    agg: Dict[Tuple[str, str], List[float]] = {}
    for plane in planes:
        pname = plane["name"]
        meta = plane["event_metadata"]
        for line_name, _ts_ns, events in plane["lines"]:
            if device_only and not _is_device_line(pname, line_name):
                continue
            for mid, _off_ps, dur_ps in events:
                key = (meta.get(mid, f"#{mid}"), pname)
                cell = agg.setdefault(key, [0.0, 0])
                cell[0] += dur_ps / 1e6  # ps -> us
                cell[1] += 1
    rows = [{"name": name, "plane": plane, "calls": cnt,
             "total_us": tot, "avg_us": tot / cnt}
            for (name, plane), (tot, cnt) in agg.items()]
    rows.sort(key=lambda r: -r["total_us"])
    return rows


def device_events(trace_dir: str, device_only: bool = True) -> List[dict]:
    """Individual timed device events from the newest xplane.pb:
    ``{name, plane, line, t_us, dur_us}`` with ``t_us`` on the
    PRODUCER's clock (``line.timestamp_ns + event.offset_ps``) — the
    unified-timeline merger (:mod:`.timeline`) shifts them onto the
    host ``perf_counter`` axis. Zero-duration bookkeeping events are
    dropped."""
    path = latest_xplane_file(trace_dir)
    if path is None:
        return []
    with open(path, "rb") as f:
        planes = parse_xspace(f.read())
    rows = []
    for plane in planes:
        pname = plane["name"]
        meta = plane["event_metadata"]
        for line_name, ts_ns, events in plane["lines"]:
            if device_only and not _is_device_line(pname, line_name):
                continue
            for mid, off_ps, dur_ps in events:
                if dur_ps <= 0:
                    continue
                rows.append({
                    "name": meta.get(mid, f"#{mid}"),
                    "plane": pname, "line": line_name,
                    "t_us": ts_ns / 1e3 + off_ps / 1e6,
                    "dur_us": dur_ps / 1e6,
                })
    rows.sort(key=lambda r: r["t_us"])
    return rows


def summary_table(trace_dir: str, limit: int = 30,
                  device_only: bool = True) -> str:
    """Formatted device-op table (≙ profiler_statistic.py's device view)."""
    rows = device_op_table(trace_dir, device_only=device_only)
    if not rows:
        return "(no xplane trace found under %s)" % trace_dir
    lines = [f"{'Device op':<48} {'Calls':>7} {'Total(us)':>12} "
             f"{'Avg(us)':>10}"]
    for r in rows[:limit]:
        lines.append(f"{r['name'][:48]:<48} {r['calls']:>7} "
                     f"{r['total_us']:>12.1f} {r['avg_us']:>10.1f}")
    if len(rows) > limit:
        lines.append(f"... ({len(rows) - limit} more rows)")
    return "\n".join(lines)
