"""``paddle.incubate.asp`` — Automatic SParsity (2:4 structured pruning).

Analog of the reference's python/paddle/incubate/asp/ (+
fluid/contrib/sparsity): compute n:m sparse masks for supported weights,
prune, and wrap the optimizer so masks are re-applied after every step
(OptimizerWithSparsityGuarantee). Masks live device-resident and the
re-masking multiply fuses into the jitted update.
"""
from __future__ import annotations

from typing import Dict, List

import numpy as np

import jax.numpy as jnp

__all__ = ["decorate", "prune_model", "set_excluded_layers",
           "reset_excluded_layers", "calculate_density", "check_sparsity"]

_EXCLUDED: set = set()
_MASKS: Dict[str, jnp.ndarray] = {}


def set_excluded_layers(model=None, param_names: List[str] = None):
    for n in (param_names or []):
        _EXCLUDED.add(n)


def reset_excluded_layers(model=None):
    _EXCLUDED.clear()


def calculate_density(x) -> float:
    arr = np.asarray(x._data if hasattr(x, "_data") else x)
    return float((arr != 0).sum() / arr.size)


def _mask_1d_nm(w: np.ndarray, n: int, m: int) -> np.ndarray:
    """Keep the n largest-|w| entries in every group of m consecutive
    elements along the last axis (reference sparsity/utils.py
    get_mask_1d)."""
    shape = w.shape
    flat = w.reshape(-1, shape[-1])
    cols = shape[-1]
    pad = (-cols) % m
    if pad:
        flat = np.concatenate(
            [flat, np.zeros((flat.shape[0], pad), w.dtype)], axis=1)
    groups = np.abs(flat).reshape(flat.shape[0], -1, m)
    order = np.argsort(-groups, axis=-1)
    mask = np.zeros_like(groups)
    np.put_along_axis(mask, order[..., :n], 1.0, axis=-1)
    mask = mask.reshape(flat.shape)[:, :cols]
    return mask.reshape(shape).astype(w.dtype)


def check_sparsity(x, n=2, m=4) -> bool:
    arr = np.asarray(x._data if hasattr(x, "_data") else x)
    flat = np.abs(arr.reshape(-1, arr.shape[-1]))
    cols = flat.shape[1]
    pad = (-cols) % m
    if pad:
        flat = np.concatenate(
            [flat, np.zeros((flat.shape[0], pad))], axis=1)
    groups = (flat.reshape(flat.shape[0], -1, m) != 0).sum(axis=-1)
    return bool((groups <= n).all())


def _supported(param) -> bool:
    return param.ndim >= 2 and min(param.shape) >= 4


def _prunable_params(model):
    """Weights of Linear/Conv layers only — the reference never prunes
    embeddings, norms, or biases, so shape alone is not enough (an
    embedding table is >=2-D with large dims)."""
    from paddle_tpu import nn
    prunable_types = (nn.Linear, nn.Conv1D, nn.Conv2D, nn.Conv3D,
                      nn.Conv1DTranspose, nn.Conv2DTranspose,
                      nn.Conv3DTranspose)
    seen = set()
    for layer in model.sublayers(include_self=True):
        if not isinstance(layer, prunable_types):
            continue
        w = getattr(layer, "weight", None)
        if w is not None and id(w) not in seen:
            seen.add(id(w))
            yield w


def prune_model(model, n=2, m=4, mask_algo="mask_1d", with_mask=True):
    """Compute masks for every supported parameter of ``model`` and zero
    the pruned entries in place. Returns {param_name: mask}."""
    if mask_algo not in ("mask_1d", "mask_2d_greedy", "mask_2d_best"):
        raise ValueError(f"unknown mask_algo {mask_algo!r}")
    out = {}
    for param in _prunable_params(model):
        if param.name in _EXCLUDED or not _supported(param):
            continue
        w = np.asarray(param._data)
        mask = _mask_1d_nm(w, n, m)
        param._data = jnp.asarray(w * mask)
        if with_mask:
            _MASKS[param.name] = jnp.asarray(mask)
            out[param.name] = _MASKS[param.name]
    return out


class OptimizerWithSparsityGuarantee:
    """Re-applies the pruning masks after every optimizer step so pruned
    weights stay zero through training (reference: asp/asp.py)."""

    def __init__(self, optimizer):
        self._optimizer = optimizer

    def __getattr__(self, item):
        return getattr(self._optimizer, item)

    def step(self):
        self._optimizer.step()
        self._apply_masks()

    def minimize(self, loss, startup_program=None, parameters=None,
                 no_grad_set=None):
        loss.backward()
        self.step()  # masked step, not the raw optimizer's
        self.clear_grad()
        return None, None

    def _apply_masks(self):
        for p in self._optimizer._parameter_list or []:
            mask = _MASKS.get(p.name)
            if mask is not None:
                p._data = p._data * mask

    def clear_grad(self, set_to_zero=False):
        self._optimizer.clear_grad(set_to_zero)


def decorate(optimizer):
    return OptimizerWithSparsityGuarantee(optimizer)
