"""Fused transformer layers (reference incubate/nn/layer/
fused_transformer.py:176/437/641)."""
from __future__ import annotations

import math

import numpy as np
from typing import Optional

from .... import nn
from ....framework.dispatch import call_op
from ....nn import functional as F
from ....nn.layer.layers import Layer

__all__ = ["FusedMultiHeadAttention", "FusedFeedForward",
           "FusedTransformerEncoderLayer"]


class FusedMultiHeadAttention(Layer):
    """Attention + residual + (pre/post) LayerNorm in one module
    (reference fused_transformer.py:176 — fused_attention_op.cu).

    On TPU the attention core runs through
    ``F.scaled_dot_product_attention`` (Pallas flash attention when the
    shapes qualify) and the LN through the fused Pallas LN; XLA fuses
    the qkv bias add, dropout and residual epilogues.
    """

    def __init__(self, embed_dim, num_heads, dropout_rate=0.5,
                 attn_dropout_rate=0.5, normalize_before=False,
                 need_weights=False, qkv_weight_attr=None,
                 qkv_bias_attr=None, linear_weight_attr=None,
                 linear_bias_attr=None, pre_ln_scale_attr=None,
                 pre_ln_bias_attr=None, ln_scale_attr=None,
                 ln_bias_attr=None, epsilon=1e-5, name=None):
        super().__init__()
        if embed_dim % num_heads:
            raise ValueError("num_heads must divide embed_dim")
        if need_weights:
            raise NotImplementedError(
                "need_weights=True is unsupported (the reference fused op "
                "asserts the same); use nn.MultiHeadAttention to inspect "
                "attention weights")
        attrs = [qkv_weight_attr, qkv_bias_attr, linear_weight_attr,
                 linear_bias_attr, pre_ln_scale_attr, pre_ln_bias_attr,
                 ln_scale_attr, ln_bias_attr]
        if any(a is not None for a in attrs):
            raise NotImplementedError(
                "ParamAttr-based initializers are not wired for the fused "
                "layers; initialize via state_dict/set_state_dict instead "
                "of silently ignoring the attrs")
        self.embed_dim = embed_dim
        self.num_heads = num_heads
        self.head_dim = embed_dim // num_heads
        self.normalize_before = normalize_before
        self.dropout_rate = dropout_rate
        self.attn_dropout_rate = attn_dropout_rate
        self.qkv_proj = nn.Linear(embed_dim, 3 * embed_dim)
        self.out_proj = nn.Linear(embed_dim, embed_dim)
        self.norm = nn.LayerNorm(embed_dim, epsilon=epsilon)

    def forward(self, x, attn_mask=None, cache=None, time_step=None):
        b, s, d = x.shape
        residual = x
        if self.normalize_before:
            x = self.norm(x)
        qkv = self.qkv_proj(x)                       # [B, S, 3D]
        qkv = call_op("reshape", qkv,
                      shape=(b, s, 3, self.num_heads, self.head_dim))
        q = qkv[:, :, 0]
        k = qkv[:, :, 1]
        v = qkv[:, :, 2]                             # [B, S, H, Dh]
        if cache is None:
            out = F.scaled_dot_product_attention(
                q, k, v, attn_mask=attn_mask,
                dropout_p=self.attn_dropout_rate if self.training else 0.0)
            new_cache = None
        else:
            out, new_cache = self._cached_attention(q, k, v, cache,
                                                    time_step, attn_mask)
        out = call_op("reshape", out, shape=(b, s, d))
        out = self.out_proj(out)
        if self.dropout_rate and self.training:
            out = F.dropout(out, p=self.dropout_rate, training=True)
        out = residual + out
        if not self.normalize_before:
            out = self.norm(out)
        return out if cache is None else (out, new_cache)

    def _cached_attention(self, q, k, v, cache, time_step, attn_mask):
        """Fixed-capacity CacheKV attention, the reference kernel's
        layout: cache [2, B, H, max_len, Dh]
        (fused_multi_transformer_op.cu:1). time_step=None is the context
        (prefill) stage — the prompt's K/V land at slots [0, S); an
        int/Tensor SCALAR time_step writes the chunk at [t, t+S) (S=1 is
        the usual decode step); a VECTOR time_step [B] is the
        slot-indexed update for pooled decode: example b's chunk lands
        at [t_b, t_b+S) with a per-row causal horizon, so sequences at
        DIFFERENT positions decode in one batch. This is the
        CacheKV-layout counterpart of the continuous-batching serving
        engine's decode step (models/generation.py
        ``build_slot_decode_fn``, which applies the same contract over
        the pooled 6-D ``serving.KVCachePool`` layout) — the engine does
        NOT call through here; both are pinned to ``generate()``'s
        semantics by their own parity tests. Queries attend
        causally to slots <= their own, intersected with any caller
        attn_mask. Functional update: the new cache is RETURNED, not
        aliased."""
        import jax.numpy as jnp
        from jax import lax

        from ....framework.tensor import Tensor
        ckv = cache._data if isinstance(cache, Tensor) else \
            jnp.asarray(cache)
        max_len = ckv.shape[3]
        # [B, S, H, Dh] -> the cache's [B, H, S, Dh]
        kv = jnp.stack([jnp.swapaxes(k._data, 1, 2),
                        jnp.swapaxes(v._data, 1, 2)]).astype(ckv.dtype)
        z = jnp.int32(0)
        s = q.shape[1]
        b = q.shape[0]
        if time_step is None:                         # prefill
            start = 0
        else:
            ts = time_step._data if isinstance(time_step, Tensor) else \
                time_step
            start = ts
        if getattr(start, "ndim", 0) == 1:            # slot-indexed [B]
            return self._slot_indexed_attention(q, kv, ckv, start,
                                                attn_mask, max_len, s, b)
        if isinstance(start, (int, np.integer)):
            if int(start) + s > max_len:
                raise ValueError(
                    f"time_step {int(start)} + chunk {s} exceeds the "
                    f"cache capacity {max_len} — dynamic_update_slice "
                    f"would silently clamp and corrupt slot "
                    f"{max_len - 1}")
        pos = jnp.asarray(start, jnp.int32).reshape(())
        # query at slot pos+i attends to cache slots <= pos+i
        valid = (jnp.arange(max_len)[None, :] <=
                 (pos + jnp.arange(s))[:, None])[None, None]  # [1,1,S,L]
        if attn_mask is not None:
            m = attn_mask._data if isinstance(attn_mask, Tensor) else \
                jnp.asarray(attn_mask)
            if m.shape[-1] not in (1, max_len):
                raise ValueError(
                    f"attn_mask last dim {m.shape[-1]} must equal the "
                    f"cache capacity max_len={max_len} (or be 1 for a "
                    f"per-query broadcast): cached attention scores span "
                    f"every cache slot, so a prompt-length mask cannot "
                    f"broadcast against them — pad the mask to max_len "
                    f"(False / -inf for empty slots)")
            if m.dtype == jnp.bool_:
                mask = valid & m
            else:  # additive float mask: keep it, kill invalid slots
                mask = jnp.where(valid, m.astype(jnp.float32), -1e30)
        else:
            mask = valid
        ckv = lax.dynamic_update_slice(ckv, kv, (z, z, z, pos, z))
        k_full = Tensor(jnp.swapaxes(ckv[0], 1, 2))   # [B, L, H, Dh]
        v_full = Tensor(jnp.swapaxes(ckv[1], 1, 2))
        out = F.scaled_dot_product_attention(
            q, k_full, v_full, attn_mask=Tensor(mask))
        return out, Tensor(ckv, stop_gradient=True)

    def _slot_indexed_attention(self, q, kv, ckv, starts, attn_mask,
                                max_len, s, b):
        """Per-example time_step [B]: example b's S-chunk scatters to
        time indices [starts[b], starts[b]+S) and its queries see slots
        <= starts[b]+i. One trace serves every position mix (starts is
        traced), which is what lets a continuous batcher decode
        sequences of different lengths in one program. (The serving
        engine itself implements this contract over its pooled layout in
        ``build_slot_decode_fn``; this is the incubate-API twin.)"""
        import jax.numpy as jnp

        from ....framework.tensor import Tensor
        starts = jnp.asarray(starts, jnp.int32).reshape(-1)
        if starts.shape[0] != b:
            raise ValueError(
                f"vector time_step has {starts.shape[0]} entries for "
                f"batch {b}")
        tidx = starts[:, None] + jnp.arange(s)[None, :]        # [B, S]
        # concrete starts get the same loud capacity check as the scalar
        # path (an out-of-range scatter index silently DROPS the write);
        # traced starts can't be inspected — their bound is the serving
        # engine's admission contract
        try:
            hi = int(np.max(np.asarray(starts)))
        except Exception:                               # noqa: BLE001
            hi = None                                   # traced under jit
        if hi is not None and hi + s > max_len:
            raise ValueError(
                f"time_step max {hi} + chunk {s} exceeds the cache "
                f"capacity {max_len}")
        # kv [2, B, H, S, Dh] -> scatter rows at [b, tidx[b, i]]
        val = jnp.transpose(kv, (1, 3, 0, 2, 4))       # [B, S, 2, H, Dh]
        ckv = ckv.at[:, jnp.arange(b)[:, None], :, tidx].set(val)
        # query i of example b attends to slots <= starts[b] + i
        valid = (jnp.arange(max_len)[None, None, :] <=
                 tidx[:, :, None])[:, None]            # [B, 1, S, L]
        if attn_mask is not None:
            m = attn_mask._data if isinstance(attn_mask, Tensor) else \
                jnp.asarray(attn_mask)
            if m.shape[-1] not in (1, max_len):
                raise ValueError(
                    f"attn_mask last dim {m.shape[-1]} must equal the "
                    f"cache capacity max_len={max_len} (or be 1 for a "
                    f"per-query broadcast)")
            if m.dtype == jnp.bool_:
                mask = valid & m
            else:
                mask = jnp.where(valid, m.astype(jnp.float32), -1e30)
        else:
            mask = valid
        k_full = Tensor(jnp.swapaxes(ckv[0], 1, 2))    # [B, L, H, Dh]
        v_full = Tensor(jnp.swapaxes(ckv[1], 1, 2))
        out = F.scaled_dot_product_attention(
            q, k_full, v_full, attn_mask=Tensor(mask))
        return out, Tensor(ckv, stop_gradient=True)


class FusedFeedForward(Layer):
    """FFN + residual + (pre/post) LN (reference fused_transformer.py:437
    — fused_feedforward_op)."""

    def __init__(self, d_model, dim_feedforward, dropout_rate=0.1,
                 epsilon=1e-5, activation="relu", act_dropout_rate=None,
                 normalize_before=False, linear1_weight_attr=None,
                 linear1_bias_attr=None, linear2_weight_attr=None,
                 linear2_bias_attr=None, ln1_scale_attr=None,
                 ln1_bias_attr=None, ln2_scale_attr=None,
                 ln2_bias_attr=None, name=None):
        super().__init__()
        self.normalize_before = normalize_before
        self.dropout_rate = dropout_rate
        self.act_dropout_rate = dropout_rate if act_dropout_rate is None \
            else act_dropout_rate
        # dispatch by NAME through the functional registry — silently
        # substituting gelu for an unknown activation trains a different
        # model with no diagnostic
        if not hasattr(F, activation):
            raise ValueError(
                f"unknown activation {activation!r} (no "
                f"paddle_tpu.nn.functional.{activation})")
        self.activation = activation
        self.linear1 = nn.Linear(d_model, dim_feedforward)
        self.linear2 = nn.Linear(dim_feedforward, d_model)
        self.norm = nn.LayerNorm(d_model, epsilon=epsilon)

    def forward(self, src, cache=None):
        residual = src
        x = self.norm(src) if self.normalize_before else src
        x = self.linear1(x)
        x = getattr(F, self.activation)(x)
        if self.act_dropout_rate and self.training:
            x = F.dropout(x, p=self.act_dropout_rate, training=True)
        x = self.linear2(x)
        if self.dropout_rate and self.training:
            x = F.dropout(x, p=self.dropout_rate, training=True)
        out = residual + x
        if not self.normalize_before:
            out = self.norm(out)
        return out


class FusedTransformerEncoderLayer(Layer):
    """Reference fused_transformer.py:641: FusedMultiHeadAttention +
    FusedFeedForward."""

    def __init__(self, d_model, nhead, dim_feedforward,
                 dropout_rate=0.1, activation="relu", attn_dropout_rate=None,
                 act_dropout_rate=None, normalize_before=False):
        super().__init__()
        self.fused_attn = FusedMultiHeadAttention(
            d_model, nhead,
            dropout_rate=dropout_rate,
            attn_dropout_rate=(dropout_rate if attn_dropout_rate is None
                               else attn_dropout_rate),
            normalize_before=normalize_before)
        self.ffn = FusedFeedForward(
            d_model, dim_feedforward, dropout_rate=dropout_rate,
            activation=activation,
            act_dropout_rate=act_dropout_rate,
            normalize_before=normalize_before)

    def forward(self, src, src_mask=None, cache=None, time_step=None):
        if cache is None:
            out = self.fused_attn(src, attn_mask=src_mask)
            return self.ffn(out)
        out, new_cache = self.fused_attn(src, attn_mask=src_mask,
                                         cache=cache, time_step=time_step)
        return self.ffn(out), new_cache


class FusedLinear(Layer):
    """Reference incubate/nn/layer/fc.py FusedLinear — cublasLt-epilogue
    fused matmul+bias there; XLA fuses the same epilogue on TPU, so this
    is the plain expression with the reference's transpose_weight knob."""

    def __init__(self, in_features, out_features, weight_attr=None,
                 bias_attr=None, transpose_weight=False, name=None):
        super().__init__()
        self._transpose_weight = transpose_weight
        shape = [out_features, in_features] if transpose_weight else \
            [in_features, out_features]
        self.weight = self.create_parameter(shape, attr=weight_attr)
        self.bias = None if bias_attr is False else \
            self.create_parameter([out_features], attr=bias_attr,
                                  is_bias=True)

    def forward(self, x):
        from ....nn import functional as F
        w = self.weight
        if self._transpose_weight:
            from ....framework.dispatch import call_op
            w = call_op("transpose", w, perm=[1, 0])
        return F.linear(x, w, self.bias)


class FusedBiasDropoutResidualLayerNorm(Layer):
    """Reference fused_transformer.py FusedBiasDropoutResidualLayerNorm:
    y = layer_norm(residual + dropout(x + bias)) in one kernel there;
    one fused XLA region here (LN itself takes the Pallas fused path)."""

    def __init__(self, embed_dim, dropout_rate=0.5, weight_attr=None,
                 bias_attr=None, epsilon=1e-5, name=None):
        super().__init__()
        from ....nn.initializer import Constant
        self._dropout_rate = dropout_rate
        self._epsilon = epsilon
        self.linear_bias = self.create_parameter(
            [embed_dim], attr=bias_attr, is_bias=True)
        self.ln_scale = self.create_parameter(
            [embed_dim], attr=weight_attr,
            default_initializer=Constant(1.0))
        self.ln_bias = self.create_parameter([embed_dim], is_bias=True)

    def forward(self, x, residual):
        from ....nn import functional as F
        y = x + self.linear_bias
        if self._dropout_rate:
            y = F.dropout(y, p=self._dropout_rate,
                          training=self.training)
        return F.layer_norm(residual + y, y.shape[-1:],
                            weight=self.ln_scale, bias=self.ln_bias,
                            epsilon=self._epsilon)


class FusedMultiTransformer(Layer):
    """Reference fused_transformer.py FusedMultiTransformer — the fused
    GPT decoder stack (fused_multi_transformer_op.cu): pre-LN attention
    (causal) + FFN per layer. Here each layer rides the flash-attention
    dispatch and XLA's epilogue fusion; weights live in per-layer
    sublayers rather than the reference's flat weight lists."""

    def __init__(self, embed_dim, num_heads, dim_feedforward,
                 dropout_rate=0.0, activation="gelu",
                 normalize_before=True, ln_scale_attrs=None,
                 num_layers=-1, nranks=1, ring_id=-1, name=None):
        super().__init__()
        if num_layers < 1:
            raise ValueError("num_layers must be >= 1")
        if not normalize_before:
            raise NotImplementedError(
                "FusedMultiTransformer is pre-LN by definition in the "
                "reference kernel; normalize_before=False is not a "
                "supported configuration there either")
        from ....nn import LayerList
        self.layers = LayerList([
            FusedTransformerEncoderLayer(
                embed_dim, num_heads, dim_feedforward,
                dropout_rate=dropout_rate, activation=activation,
                normalize_before=True)
            for _ in range(num_layers)])

    def gen_cache(self, batch, max_len, dtype="float32"):
        """Preallocate the per-layer CacheKV tensors the reference makes
        callers build by hand: list of [2, B, num_heads, max_len,
        head_dim] zeros (fused_multi_transformer_op.cu CacheKV layout)."""
        import jax.numpy as jnp

        from ....framework.tensor import Tensor
        a = self.layers[0].fused_attn
        shape = (2, batch, a.num_heads, max_len, a.head_dim)
        return [Tensor(jnp.zeros(shape, jnp.dtype(dtype)),
                       stop_gradient=True) for _ in self.layers]

    def forward(self, src, attn_mask=None, caches=None, time_step=None):
        if time_step is not None and caches is None:
            raise ValueError(
                "time_step requires caches (decode steps read/write the "
                "CacheKV tensors); pass caches=gen_cache(...)")
        if caches is not None:
            # inference stages (reference contract: returns (out, caches)):
            # time_step None = context/prefill, else chunk decode at t
            if len(caches) != len(self.layers):
                raise ValueError(
                    f"got {len(caches)} cache tensors for "
                    f"{len(self.layers)} layers")
            out = src
            new_caches = []
            for layer, c in zip(self.layers, caches):
                out, nc = layer(out, src_mask=attn_mask, cache=c,
                                time_step=time_step)
                new_caches.append(nc)
            return out, new_caches
        if attn_mask is None:
            # the reference kernel is a CAUSAL decoder by construction —
            # ported callers pass no mask and still expect causality
            import jax.numpy as jnp
            from ....framework.tensor import Tensor
            s = src.shape[1]
            causal = jnp.where(
                jnp.tril(jnp.ones((s, s), jnp.bool_)), 0.0, -1e9)
            attn_mask = Tensor(causal.reshape(1, 1, s, s),
                               stop_gradient=True)
        out = src
        for layer in self.layers:
            out = layer(out, src_mask=attn_mask)
        return out
