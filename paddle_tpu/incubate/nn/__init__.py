"""``paddle.incubate.nn`` — fused transformer layers.

Reference: python/paddle/incubate/nn/layer/fused_transformer.py —
``FusedMultiHeadAttention`` (:176), ``FusedFeedForward`` (:437),
``FusedTransformerEncoderLayer`` (:641), backed by the hand-fused CUDA
kernels in operators/fused/ (fused_attention_op.cu, fused_feedforward).

TPU-native: "fused" is a property of the compiled program, not a special
layer class — these layers express attention through
``scaled_dot_product_attention`` (served by the Pallas flash-attention
kernel on TPU) and layer_norm through the fused Pallas LN, and XLA fuses
the bias/residual/dropout epilogues the CUDA kernels fuse by hand. The
classes exist for API parity and for the pre/post-LN + residual wiring
the reference bakes into its fused ops.
"""
from . import functional  # noqa: F401
from .layer.fused_transformer import (  # noqa: F401
    FusedBiasDropoutResidualLayerNorm, FusedFeedForward, FusedLinear,
    FusedMultiHeadAttention, FusedMultiTransformer,
    FusedTransformerEncoderLayer,
)

__all__ = ["FusedMultiHeadAttention", "FusedFeedForward",
           "FusedTransformerEncoderLayer", "FusedLinear",
           "FusedBiasDropoutResidualLayerNorm", "FusedMultiTransformer",
           "functional"]
