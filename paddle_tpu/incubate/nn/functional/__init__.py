"""``paddle.incubate.nn.functional`` — functional forms of the fused ops.

Analog of the reference's python/paddle/incubate/nn/functional/
(fused_transformer.py, fused_matmul_bias.py). On TPU "fused" means one XLA
fusion region (+ Pallas flash attention / fused LN where registered): the
functional forms below compose the same primitives the fused layers use,
weights passed explicitly.
"""
from __future__ import annotations

from ....framework.dispatch import call_op as _op
from ....framework import random as _random
from ....nn import functional as F

__all__ = ["fused_matmul_bias", "fused_linear",
           "fused_bias_dropout_residual_layer_norm",
           "fused_multi_head_attention", "fused_feedforward"]


def fused_matmul_bias(x, y, bias=None, transpose_x=False, transpose_y=False,
                      name=None):
    """Reference: fused_matmul_bias.py — matmul + bias epilogue (cublasLt
    there, one XLA fusion here)."""
    out = _op("matmul", x, y, transpose_x=transpose_x,
              transpose_y=transpose_y)
    if bias is not None:
        out = _op("add", out, bias)
    return out


def fused_linear(x, weight, bias=None, transpose_weight=False, name=None):
    return fused_matmul_bias(x, weight, bias,
                             transpose_y=transpose_weight)


def fused_bias_dropout_residual_layer_norm(
        x, residual, bias=None, ln_scale=None, ln_bias=None,
        dropout_rate=0.5, ln_epsilon=1e-5, training=True,
        mode="upscale_in_train", name=None):
    """Reference: fused_transformer.py:225 — out = LN(residual +
    dropout(x + bias))."""
    if bias is not None:
        x = _op("add", x, bias)
    if dropout_rate and training:
        x = F.dropout(x, p=dropout_rate, training=True, mode=mode)
    y = _op("add", residual, x)
    return _op("layer_norm", y, ln_scale, ln_bias, epsilon=ln_epsilon,
               begin_norm_axis=len(y.shape) - 1)


def fused_multi_head_attention(
        x, qkv_weight, linear_weight, pre_layer_norm=False,
        pre_ln_scale=None, pre_ln_bias=None, ln_scale=None, ln_bias=None,
        pre_ln_epsilon=1e-5, qkv_bias=None, linear_bias=None,
        cache_kv=None, attn_mask=None, dropout_rate=0.5,
        attn_dropout_rate=0.5, ln_epsilon=1e-5, training=True,
        mode="upscale_in_train", ring_id=-1, add_residual=True, name=None):
    """Reference: fused_transformer.py:371 (fused_attention_op.cu).
    qkv_weight: [3, H, Dh, D]; linear_weight: [D, D]."""
    if cache_kv is not None:
        raise NotImplementedError(
            "cache_kv (incremental decode) is not supported by the fused "
            "attention path; use nn.MultiHeadAttention with its cache")
    b, s, d = x.shape
    n_heads = qkv_weight.shape[1]
    head_dim = qkv_weight.shape[2]
    residual = x
    if pre_layer_norm:
        x = _op("layer_norm", x, pre_ln_scale, pre_ln_bias,
                epsilon=pre_ln_epsilon, begin_norm_axis=len(x.shape) - 1)
    w = _op("reshape", qkv_weight, shape=(3 * n_heads * head_dim, d))
    qkv = _op("matmul", x, w, transpose_y=True)        # [B, S, 3HDh]
    if qkv_bias is not None:
        qkv = _op("add", qkv,
                  _op("reshape", qkv_bias, shape=(3 * n_heads * head_dim,)))
    qkv = _op("reshape", qkv, shape=(b, s, 3, n_heads, head_dim))
    q = qkv[:, :, 0]
    k = qkv[:, :, 1]
    v = qkv[:, :, 2]
    out = F.scaled_dot_product_attention(
        q, k, v, attn_mask=attn_mask,
        dropout_p=attn_dropout_rate if training else 0.0,
        training=training)
    out = _op("reshape", out, shape=(b, s, n_heads * head_dim))
    out = _op("matmul", out, linear_weight)
    if linear_bias is not None:
        out = _op("add", out, linear_bias)
    if dropout_rate and training:
        out = F.dropout(out, p=dropout_rate, training=True, mode=mode)
    if add_residual:
        out = _op("add", residual, out)
    if not pre_layer_norm:
        out = _op("layer_norm", out, ln_scale, ln_bias, epsilon=ln_epsilon,
                  begin_norm_axis=len(out.shape) - 1)
    return out


def fused_feedforward(x, linear1_weight, linear2_weight, linear1_bias=None,
                      linear2_bias=None, ln1_scale=None, ln1_bias=None,
                      ln2_scale=None, ln2_bias=None, dropout1_rate=0.5,
                      dropout2_rate=0.5, activation="relu",
                      ln1_epsilon=1e-5, ln2_epsilon=1e-5,
                      pre_layer_norm=False, training=True,
                      mode="upscale_in_train", ring_id=-1, name=None):
    """Reference: fused_transformer.py:31 (fused_feedforward_op.cu):
    residual + dropout2(linear2(dropout1(act(linear1(LN(x))))))."""
    residual = x
    if pre_layer_norm:
        x = _op("layer_norm", x, ln1_scale, ln1_bias, epsilon=ln1_epsilon,
                begin_norm_axis=len(x.shape) - 1)
    h = fused_matmul_bias(x, linear1_weight, linear1_bias)
    h = getattr(F, activation)(h)
    if dropout1_rate and training:
        h = F.dropout(h, p=dropout1_rate, training=True, mode=mode)
    h = fused_matmul_bias(h, linear2_weight, linear2_bias)
    if dropout2_rate and training:
        h = F.dropout(h, p=dropout2_rate, training=True, mode=mode)
    out = _op("add", residual, h)
    if not pre_layer_norm:
        out = _op("layer_norm", out, ln2_scale, ln2_bias,
                  epsilon=ln2_epsilon, begin_norm_axis=len(out.shape) - 1)
    return out
