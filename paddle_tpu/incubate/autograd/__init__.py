"""``paddle.incubate.autograd`` — functional/prim autodiff API.

Analog of the reference's python/paddle/incubate/autograd/primapi.py
(forward/reverse primitive rules). On TPU the "primitive" layer IS jax's
jvp/vjp machinery, so enable_prim is a mode flag kept for parity and the
functional entry points delegate to the autograd facade.
"""
from __future__ import annotations

__all__ = ["enable_prim", "disable_prim", "prim_enabled", "forward_grad",
           "grad", "jvp", "vjp", "Jacobian", "Hessian", "prim2orig"]

_prim = {"enabled": False}


def enable_prim():
    if not _prim["enabled"]:
        import logging
        logging.getLogger("paddle_tpu").info(
            "enable_prim(): jax's jvp/vjp machinery IS the primitive "
            "layer on this backend — the flag is recorded for parity "
            "but changes no behavior")
    _prim["enabled"] = True


def disable_prim():
    _prim["enabled"] = False


def prim_enabled() -> bool:
    return _prim["enabled"]


def jvp(func, xs, v=None):
    from ...autograd import jvp as _jvp
    return _jvp(func, xs, v)


def vjp(func, xs, v=None):
    from ...autograd import vjp as _vjp
    return _vjp(func, xs, v)


def forward_grad(outputs_fn, xs, v=None):
    """Forward-mode derivative of ``outputs_fn`` at ``xs`` along ``v``
    (reference primapi.forward_grad)."""
    _, tangents = jvp(outputs_fn, xs, v)
    return tangents


def grad(outputs_fn, xs, v=None):
    """Reverse-mode gradients (reference primapi.grad)."""
    _, grads = vjp(outputs_fn, xs, v)
    return grads


def prim2orig(block=None):
    """Reference primapi.prim2orig: lower primitive ops back to original
    ops. jax's jaxprs ARE the primitive layer and XLA lowers them — a
    recorded program never holds prim ops, so this is a checked no-op."""
    return None


class Jacobian:
    """Lazy Jacobian view (reference incubate/autograd/functional.py
    Jacobian): J = Jacobian(func, xs); J[:] materializes, rows/cols
    index. Built on autograd.jacobian."""

    def __init__(self, func, xs, is_batched=False):
        from ...autograd import jacobian as _jac
        self._mat = _jac(func, xs)

    def __getitem__(self, idx):
        return self._mat[idx]

    @property
    def shape(self):
        return self._mat.shape

    def numpy(self):
        return self._mat.numpy()


class Hessian(Jacobian):
    """Lazy Hessian view (reference functional.py Hessian)."""

    def __init__(self, func, xs, is_batched=False):
        from ...autograd import hessian as _hes
        self._mat = _hes(func, xs)
