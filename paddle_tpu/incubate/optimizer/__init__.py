"""``paddle.incubate.optimizer`` — LookAhead and ModelAverage.

Analog of the reference's python/paddle/incubate/optimizer/{lookahead.py,
modelaverage.py}: wrappers around an inner optimizer that keep auxiliary
parameter copies (slow weights / running averages) as device-resident
arrays.
"""
from __future__ import annotations

import contextlib

import jax.numpy as jnp

from ...framework.tensor import Tensor
from ...optimizer.optimizer import Optimizer

__all__ = ["LookAhead", "ModelAverage"]


class LookAhead(Optimizer):
    """k-step lookahead (reference: incubate/optimizer/lookahead.py):
    every k inner steps, slow <- slow + alpha*(fast - slow); fast <- slow."""

    def __init__(self, inner_optimizer, alpha=0.5, k=5, name=None):
        if inner_optimizer is None:
            raise ValueError("inner optimizer cannot be None")
        params = inner_optimizer._parameter_list
        super().__init__(learning_rate=inner_optimizer._lr,
                         parameters=params)
        self.inner_optimizer = inner_optimizer
        self.alpha = float(alpha)
        self.k = int(k)
        # snapshot slow weights at construction: the first k-boundary sync
        # interpolates init -> fast_k (lazy init would make it a no-op)
        self._slow = {p.name: p._data for p in params or []}

    def step(self):
        self.inner_optimizer.step()
        self._step_count += 1
        if self._step_count % self.k != 0:
            return
        for p in self.inner_optimizer._parameter_list or []:
            name = p.name
            if name not in self._slow:
                self._slow[name] = p._data
            slow = self._slow[name] + self.alpha * (p._data
                                                    - self._slow[name])
            self._slow[name] = slow
            p._data = slow

    def clear_grad(self, set_to_zero=False):
        self.inner_optimizer.clear_grad(set_to_zero)

    def state_dict(self):
        out = self.inner_optimizer.state_dict()
        out["@lookahead_step"] = self._step_count
        for name, arr in self._slow.items():
            out[f"{name}_slow"] = Tensor(arr)
        return out

    def set_state_dict(self, state):
        self._step_count = int(state.pop("@lookahead_step", 0))
        slow_keys = [k for k in state if k.endswith("_slow")]
        for k in slow_keys:
            v = state.pop(k)
            self._slow[k[:-5]] = v._data if isinstance(v, Tensor) \
                else jnp.asarray(v)
        self.inner_optimizer.set_state_dict(state)


class ModelAverage(Optimizer):
    """Running parameter average (reference:
    incubate/optimizer/modelaverage.py): accumulates sum(param) per step;
    ``apply()`` swaps in the average, ``restore()`` swaps back."""

    def __init__(self, average_window_rate, parameters=None,
                 min_average_window=10000, max_average_window=10000,
                 name=None):
        super().__init__(learning_rate=0.0, parameters=parameters)
        self.avg_rate = float(average_window_rate)
        self.min_avg = int(min_average_window)
        self.max_avg = int(max_average_window)
        self._sum = {}
        self._num = {}
        self._backup = None

    def step(self):
        for p in self._parameter_list or []:
            name = p.name
            if name not in self._sum:
                self._sum[name] = jnp.zeros_like(p._data)
                self._num[name] = 0
            self._sum[name] = self._sum[name] + p._data
            self._num[name] += 1
            window = max(self.min_avg,
                         min(self.max_avg,
                             int(self._num[name] * self.avg_rate)))
            if self._num[name] > window:
                # decay old contribution: keep a moving window by rescale
                self._sum[name] = self._sum[name] * (
                    window / self._num[name])
                self._num[name] = window
        self._step_count += 1

    @contextlib.contextmanager
    def apply(self, executor=None, need_restore=True):
        """Context manager (reference contract: ``with ma.apply(): ...``,
        modelaverage.py:377 @signature_safe_contextmanager): swaps the
        running averages into the parameters for the block's duration and
        restores the live weights on exit unless need_restore=False."""
        self._backup = {p.name: p._data
                        for p in self._parameter_list or []}
        for p in self._parameter_list or []:
            n = self._num.get(p.name, 0)
            if n > 0:
                p._data = self._sum[p.name] / n
        try:
            yield
        finally:
            if need_restore:
                self.restore()
            else:
                self._backup = None

    def restore(self, executor=None):
        if self._backup is None:
            return
        for p in self._parameter_list or []:
            if p.name in self._backup:
                p._data = self._backup[p.name]
        self._backup = None
