"""``paddle.incubate.autotune`` (reference:
python/paddle/incubate/autotune.py — kernel/layout/dataloader autotuning
switches). TPU mapping: the kernel knob gates the measured Pallas dispatch
tier, the dataloader knob tunes io prefetch depth; layout autotune is XLA's
job and the knob is accepted for parity.
"""
from __future__ import annotations

import json

__all__ = ["set_config", "stats", "tune_attention"]

_CONFIG = {
    "kernel": {"enable": True, "tuning_range": [1, 10]},
    "layout": {"enable": False},
    "dataloader": {"enable": False, "tuning_steps": 500},
}


def set_config(config=None):
    """Accepts a dict or a path to a JSON file (reference contract)."""
    from ..framework import flags as _flags

    if config is None:
        config = {}
    if isinstance(config, str):
        with open(config) as f:
            config = json.load(f)
    bad = [k for k in config if k not in _CONFIG]
    if bad:
        raise ValueError(f"unknown autotune domain(s) {bad} "
                         f"(kernel/layout/dataloader)")
    for key, val in config.items():
        _CONFIG[key].update(val)
    if "kernel" in config and "enable" in config["kernel"]:
        _flags.set_flags(
            {"FLAGS_use_pallas": bool(config["kernel"]["enable"])})
    return dict(_CONFIG)


def stats():
    """Hit/miss/measure counters + entry count of the shape-class kernel
    cache (reference: autotune cache stats in switch_autotune.h)."""
    from ..ops import autotune_cache
    return autotune_cache.stats()


def tune_attention(q, k, v, is_causal=False, **kwargs):
    """Measure lax vs pallas block configs for this shape class and
    persist the winner per device kind (ops/pallas_kernels.py
    tune_attention; kwargs: include_bwd, skip_if_cached, persist)."""
    from ..ops.pallas_kernels import tune_attention as _tune
    return _tune(q, k, v, is_causal=is_causal, **kwargs)
