"""``paddle.incubate`` — experimental features.

Analog of the reference's ``python/paddle/incubate/`` (fused transformer
layers, MoE, functional autograd, sparse, autotune).
"""
from . import asp, autograd, autotune, moe, nn, optimizer  # noqa: F401
from .graph_ops import (  # noqa: F401
    graph_khop_sampler, graph_reindex, graph_sample_neighbors,
    graph_send_recv, segment_max, segment_mean, segment_min, segment_sum,
    softmax_mask_fuse, softmax_mask_fuse_upper_triangle,
)
from .moe import MoELayer  # noqa: F401
from .optimizer import LookAhead, ModelAverage  # noqa: F401
