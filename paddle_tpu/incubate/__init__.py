"""``paddle.incubate`` — experimental features.

Analog of the reference's ``python/paddle/incubate/`` (fused transformer
layers, MoE, functional autograd, sparse, autotune).
"""
from . import asp, autograd, autotune, moe, nn, optimizer  # noqa: F401
from .moe import MoELayer  # noqa: F401
from .optimizer import LookAhead, ModelAverage  # noqa: F401
