"""Mixture-of-Experts with expert parallelism.

Analog of the reference's ``MoELayer``
(incubate/distributed/models/moe/moe_layer.py) + gates (gshard/switch/naive)
+ the ``global_scatter``/``global_gather`` alltoall C++ ops
(operators/collective/global_scatter_op.cc).

TPU-native (GShard-style): token→expert routing is expressed as dense
einsum dispatch/combine against a capacity-bounded one-hot mask — static
shapes, MXU-friendly. When the global mesh has an "expert" axis that
divides both the token count and the expert count, dispatch runs through
an EXPLICIT shard_map + lax.all_to_all exchange with per-shard capacity
(_forward_expert_parallel — the analog of global_scatter/global_gather);
otherwise the dense single-shard einsum path is the fallback, with GLOBAL
capacity semantics. The two paths agree whenever capacity is generous
enough that no tokens drop.
"""
from __future__ import annotations

import math
from typing import List, Optional

import numpy as np

from .. import autograd, nn
from ..framework import random as _random
from ..framework.dispatch import call_op
from ..framework.tensor import Tensor
from ..nn import functional as F
from ..distributed.fleet.meta_parallel.parallel_layers.mp_layers import (
    constrain, mark_sharding,
)

__all__ = ["NaiveGate", "SwitchGate", "GShardGate", "MoELayer",
           "ExpertMLP"]


class NaiveGate(nn.Layer):
    """Top-k linear gate (reference moe/gate/naive_gate.py)."""

    def __init__(self, d_model, num_experts, topk=2):
        super().__init__()
        self.fc = nn.Linear(d_model, num_experts)
        self.topk = topk
        self.num_experts = num_experts

    def forward(self, x):
        return self.fc(x)


class SwitchGate(NaiveGate):
    def __init__(self, d_model, num_experts):
        super().__init__(d_model, num_experts, topk=1)


class GShardGate(NaiveGate):
    pass


class ExpertMLP(nn.Layer):
    """One expert: FFN. Weights carry a leading expert dim stacked by
    MoELayer, so this class defines the per-expert math only."""

    def __init__(self, d_model, d_hidden):
        super().__init__()
        self.fc1 = nn.Linear(d_model, d_hidden)
        self.fc2 = nn.Linear(d_hidden, d_model)

    def forward(self, x):
        return self.fc2(F.gelu(self.fc1(x)))


class MoELayer(nn.Layer):
    """Reference: moe_layer.py MoELayer(gate, experts, ...).

    forward: [B, L, D] -> [B, L, D] with auxiliary load-balance loss
    stashed on ``self.l_aux`` (reference parity).
    """

    def __init__(self, d_model, experts: Optional[List[nn.Layer]] = None,
                 gate=None, num_experts=None, d_hidden=None, topk=2,
                 capacity_factor=1.25, group=None, recompute_interval=0):
        super().__init__()
        if experts is not None:
            num_experts = len(experts)
            # stack expert weights into [E, ...] batched params
            names = [n for n, _ in experts[0].named_parameters()]
            import jax.numpy as jnp
            for n in names:
                stacked = jnp.stack(
                    [dict(e.named_parameters())[n]._data for e in experts])
                p = self.create_parameter(
                    list(stacked.shape),
                    default_initializer=nn.initializer.Assign(
                        np.asarray(stacked)))
                mark_sharding(p, "expert",
                              *(None,) * (stacked.ndim - 1))
                self.add_parameter("expert_" + n.replace(".", "_"), p)
            # the template is only the per-expert FUNCTION body (vmapped
            # over the stacked expert_* params above) — keep it out of the
            # sublayer registry or its unused per-instance params would
            # surface in parameters()/optimizer slots with no grads
            self.__dict__["_template_holder"] = [experts[0]]
            self._expert_param_names = names
        else:
            if num_experts is None or d_hidden is None:
                raise ValueError(
                    "pass experts=[...] or num_experts+d_hidden")
            tmpl = ExpertMLP(d_model, d_hidden)
            self.__init__(d_model,
                          experts=[ExpertMLP(d_model, d_hidden)
                                   for _ in range(num_experts)],
                          gate=gate, topk=topk,
                          capacity_factor=capacity_factor)
            return
        self.num_experts = num_experts
        self.topk = topk
        self.capacity_factor = capacity_factor
        self.gate = gate if isinstance(gate, nn.Layer) else \
            NaiveGate(d_model, num_experts, topk=topk)
        self.l_aux = None

    def _route(self, probs_a, cap):
        """GShard top-k routing with capacity: probs [S, E] ->
        (dispatch [S,E,C], combine [S,E,C], me [E], ce [E])."""
        import jax
        import jax.numpy as jnp

        s, e = probs_a.shape
        topv, topi = jax.lax.top_k(probs_a, self.topk)       # [S, K]
        onehot = jax.nn.one_hot(topi, e, dtype=probs_a.dtype)  # [S, K, E]
        # position of each token within its expert queue, token-major
        # order: an early token's 2nd choice queues ahead of a later
        # token's 1st choice (differs from GShard's strict k-priority;
        # only observable when tokens drop)
        flat = onehot.reshape(s * self.topk, e)
        pos = jnp.cumsum(flat, axis=0) - flat                # [S*K, E]
        pos = (pos * flat).sum(-1).reshape(s, self.topk)     # [S, K]
        keep = pos < cap
        gates = topv * keep                                   # [S, K]
        denom = jnp.maximum(gates.sum(-1, keepdims=True), 1e-9)
        gates = gates / denom
        cap_oh = jax.nn.one_hot(
            jnp.where(keep, pos, cap).astype(jnp.int32), cap + 1,
            dtype=probs_a.dtype)[..., :cap]                  # [S, K, C]
        dispatch = jnp.einsum("ske,skc->sec", onehot, cap_oh)
        combine = jnp.einsum("sk,ske,skc->sec", gates, onehot, cap_oh)
        # load-balance aux terms (reference moe grad path / GShard eq.4)
        me = probs_a.mean(0)                                  # [E]
        ce = onehot[:, 0].mean(0)                             # top-1 share
        return dispatch, combine, me, ce

    @property
    def _expert_template(self):
        return self.__dict__["_template_holder"][0]

    def _one_expert_fn(self):
        from ..nn.layer.layers import functional_state
        tmpl = self._expert_template
        names = self._expert_param_names

        def one_expert(pvals, xe):
            pj = dict(zip(names, pvals))
            with functional_state(tmpl, pj, {}):
                return tmpl(Tensor(xe, stop_gradient=True))._data

        return one_expert

    def _gate_param_items(self):
        return list(self.gate.named_parameters())

    def _expert_param_tensors(self):
        return [getattr(self, "expert_" + n.replace(".", "_"))
                for n in self._expert_param_names]

    def _forward_arrays(self, x2, gate_vals, pvals):
        """Pure array->array MoE forward: [S, D] tokens -> ([S, D] out,
        scalar l_aux).  Differentiable by jax; shared by the functional
        (traced) path and the eager tape node."""
        import jax
        import jax.numpy as jnp
        from ..distributed import env as _env
        from ..nn.layer.layers import functional_state
        from ..framework.tensor import no_grad_guard

        s, d = x2.shape
        e = self.num_experts

        gate_names = [n for n, _ in self._gate_param_items()]
        with functional_state(self.gate, dict(zip(gate_names, gate_vals)),
                              {}):
            with no_grad_guard():
                logits = self.gate(Tensor(x2, stop_gradient=True))._data
        probs_a = jax.nn.softmax(logits, axis=-1)
        one_expert = self._one_expert_fn()

        mesh = _env.get_mesh()
        ep = int(mesh.shape.get("expert", 1)) if mesh is not None else 1
        if ep > 1:
            if s % ep == 0 and e % ep == 0:
                return self._forward_expert_parallel(
                    x2, probs_a, pvals, one_expert, mesh, ep)
            if not getattr(self, "_warned_dense_fallback", False):
                import warnings
                warnings.warn(
                    f"MoELayer: expert mesh axis degree {ep} does not "
                    f"divide tokens={s} / experts={e}; falling back to "
                    f"dense dispatch with GLOBAL capacity — routing "
                    f"semantics differ from the expert-parallel path")
                self._warned_dense_fallback = True

        # single-shard (dense-dispatch) path
        cap = max(1, int(math.ceil(s / e * self.capacity_factor)))
        dispatch, combine, me, ce = self._route(probs_a, cap)
        l_aux = jnp.sum(me * ce) * e
        expert_in = jnp.einsum("sd,sec->ecd", x2, dispatch)
        expert_in = constrain(expert_in, "expert", None, None)
        expert_out = jax.vmap(one_expert, in_axes=(0, 0))(pvals, expert_in)
        expert_out = constrain(expert_out, "expert", None, None)
        out = jnp.einsum("ecd,sec->sd", expert_out, combine)
        return out, l_aux

    def forward(self, x):
        b, l, d = x.shape
        gate_tensors = [p for _, p in self._gate_param_items()]
        expert_tensors = self._expert_param_tensors()
        n_gate = len(gate_tensors)

        def pure(xa, *flat):
            out2, l_aux = self._forward_arrays(
                xa.reshape(b * l, d), list(flat[:n_gate]),
                list(flat[n_gate:]))
            return out2.reshape(b, l, d), l_aux

        # one regime-correct application (autograd.differentiable_apply):
        # traced steps differentiate through jax tracing; eager training
        # records ONE tape node with a jax.vjp backward, so
        # loss.backward() delivers real grads to the gate and expert
        # params (r2 verdict weak #6: the raw-array path silently
        # produced no grads here)
        out, l_aux = autograd.differentiable_apply(
            pure, x, *gate_tensors, *expert_tensors)
        self.l_aux = l_aux
        return out

    def _forward_expert_parallel(self, tokens, probs, pvals, one_expert,
                                 mesh, ep):
        """Expert-parallel dispatch via explicit all_to_all over the
        "expert" mesh axis (reference: global_scatter/global_gather,
        operators/collective/global_scatter_op.cc — here the exchange is
        a lax.all_to_all inside shard_map riding ICI).

        Tokens are sharded over the expert axis; each shard routes its
        local tokens with LOCAL capacity, all-to-alls the per-expert
        slices to the experts' owners, applies its resident experts, and
        reverses the exchange.
        """
        import jax
        import jax.numpy as jnp
        from jax.sharding import PartitionSpec as P
        try:
            from jax import shard_map
        except ImportError:  # older jax
            from jax.experimental.shard_map import shard_map

        s, d = tokens.shape
        e = self.num_experts
        # derive local capacity from the GLOBAL capacity cap_g. Shards
        # need a uniform static capacity for the all_to_all, so the
        # aggregate ep*ceil(cap_g/ep) can still exceed cap_g by up to
        # ep-1 slots (vs up to ep*(e-1)/e before this fix); exact parity
        # with the dense path holds whenever ep divides cap_g, and in all
        # no-drop regimes.
        cap_g = max(1, int(math.ceil(s / e * self.capacity_factor)))
        cap_l = max(1, int(math.ceil(cap_g / ep)))

        def local_fn(tokens_l, probs_l, *pvals_l):
            dispatch, combine, me, ce = self._route(probs_l, cap_l)
            # aux loss over ALL tokens: shards are equal-sized, so the
            # global mean is the mean of shard means
            me_g = jax.lax.pmean(me, "expert")
            ce_g = jax.lax.pmean(ce, "expert")
            l_aux = jnp.sum(me_g * ce_g) * e
            expert_in = jnp.einsum("sd,sec->ecd", tokens_l, dispatch)
            # [E, C, D] -> [E/ep, ep*C, D]: expert slices travel to their
            # owner; capacity slots from every source shard concatenate
            expert_in = jax.lax.all_to_all(
                expert_in, "expert", split_axis=0, concat_axis=1,
                tiled=True)
            expert_out = jax.vmap(one_expert, in_axes=(0, 0))(
                list(pvals_l), expert_in)
            expert_out = jax.lax.all_to_all(
                expert_out, "expert", split_axis=1, concat_axis=0,
                tiled=True)                                   # [E, C, D]
            out_l = jnp.einsum("ecd,sec->sd", expert_out, combine)
            return out_l, l_aux

        # NOTE: tokens/probs shard over the "expert" axis only. On a mesh
        # whose other axes (data/sharding) are also >1, GSPMD reshards the
        # full batch onto expert shards and replicates routing across the
        # data axis — correct but wasteful; the EP path assumes "expert"
        # is the only nontrivial axis over tokens (advisor r2).
        in_specs = (P("expert"), P("expert"),
                    *([P("expert")] * len(pvals)))
        out, l_aux = shard_map(
            local_fn, mesh=mesh, in_specs=in_specs,
            out_specs=(P("expert"), P()))(tokens, probs, *pvals)
        return out, l_aux
