"""Mixture-of-Experts with expert parallelism.

Analog of the reference's ``MoELayer``
(incubate/distributed/models/moe/moe_layer.py) + gates (gshard/switch/naive)
+ the ``global_scatter``/``global_gather`` alltoall C++ ops
(operators/collective/global_scatter_op.cc).

TPU-native (GShard-style): token→expert routing is expressed as dense
einsum dispatch/combine against a capacity-bounded one-hot mask — static
shapes, MXU-friendly. With the expert dimension sharded over the "expert"
mesh axis, GSPMD lowers the dispatch einsum to exactly the all-to-all the
reference implements by hand; on one device it is a plain batched matmul.
"""
from __future__ import annotations

import math
from typing import List, Optional

import numpy as np

from .. import nn
from ..framework import random as _random
from ..framework.dispatch import call_op
from ..framework.tensor import Tensor
from ..nn import functional as F
from ..distributed.fleet.meta_parallel.parallel_layers.mp_layers import (
    constrain, mark_sharding,
)

__all__ = ["NaiveGate", "SwitchGate", "GShardGate", "MoELayer",
           "ExpertMLP"]


class NaiveGate(nn.Layer):
    """Top-k linear gate (reference moe/gate/naive_gate.py)."""

    def __init__(self, d_model, num_experts, topk=2):
        super().__init__()
        self.fc = nn.Linear(d_model, num_experts)
        self.topk = topk
        self.num_experts = num_experts

    def forward(self, x):
        return self.fc(x)


class SwitchGate(NaiveGate):
    def __init__(self, d_model, num_experts):
        super().__init__(d_model, num_experts, topk=1)


class GShardGate(NaiveGate):
    pass


class ExpertMLP(nn.Layer):
    """One expert: FFN. Weights carry a leading expert dim stacked by
    MoELayer, so this class defines the per-expert math only."""

    def __init__(self, d_model, d_hidden):
        super().__init__()
        self.fc1 = nn.Linear(d_model, d_hidden)
        self.fc2 = nn.Linear(d_hidden, d_model)

    def forward(self, x):
        return self.fc2(F.gelu(self.fc1(x)))


class MoELayer(nn.Layer):
    """Reference: moe_layer.py MoELayer(gate, experts, ...).

    forward: [B, L, D] -> [B, L, D] with auxiliary load-balance loss
    stashed on ``self.l_aux`` (reference parity).
    """

    def __init__(self, d_model, experts: Optional[List[nn.Layer]] = None,
                 gate=None, num_experts=None, d_hidden=None, topk=2,
                 capacity_factor=1.25, group=None, recompute_interval=0):
        super().__init__()
        if experts is not None:
            num_experts = len(experts)
            # stack expert weights into [E, ...] batched params
            names = [n for n, _ in experts[0].named_parameters()]
            import jax.numpy as jnp
            for n in names:
                stacked = jnp.stack(
                    [dict(e.named_parameters())[n]._data for e in experts])
                p = self.create_parameter(
                    list(stacked.shape),
                    default_initializer=nn.initializer.Assign(
                        np.asarray(stacked)))
                mark_sharding(p, "expert",
                              *(None,) * (stacked.ndim - 1))
                self.add_parameter("expert_" + n.replace(".", "_"), p)
            self._expert_template = experts[0]
            self._expert_param_names = names
        else:
            if num_experts is None or d_hidden is None:
                raise ValueError(
                    "pass experts=[...] or num_experts+d_hidden")
            tmpl = ExpertMLP(d_model, d_hidden)
            self.__init__(d_model,
                          experts=[ExpertMLP(d_model, d_hidden)
                                   for _ in range(num_experts)],
                          gate=gate, topk=topk,
                          capacity_factor=capacity_factor)
            return
        self.num_experts = num_experts
        self.topk = topk
        self.capacity_factor = capacity_factor
        self.gate = gate if isinstance(gate, nn.Layer) else \
            NaiveGate(d_model, num_experts, topk=topk)
        self.l_aux = None

    def forward(self, x):
        import jax
        import jax.numpy as jnp

        b, l, d = x.shape
        s = b * l
        e = self.num_experts
        cap = max(1, int(math.ceil(s / e * self.capacity_factor)))

        tokens = call_op("reshape", x, shape=(s, d))
        logits = self.gate(tokens)  # [S, E]
        probs = F.softmax(logits, axis=-1)

        probs_a = probs._data
        # top-k assignment with capacity via cumsum position (GShard):
        topv, topi = jax.lax.top_k(probs_a, self.topk)       # [S, K]
        onehot = jax.nn.one_hot(topi, e, dtype=probs_a.dtype)  # [S, K, E]
        # position of each token within its expert queue, k-major order
        flat = onehot.reshape(s * self.topk, e)
        pos = jnp.cumsum(flat, axis=0) - flat                # [S*K, E]
        pos = (pos * flat).sum(-1).reshape(s, self.topk)     # [S, K]
        keep = pos < cap
        gates = topv * keep                                   # [S, K]
        denom = jnp.maximum(gates.sum(-1, keepdims=True), 1e-9)
        gates = gates / denom
        cap_oh = jax.nn.one_hot(
            jnp.where(keep, pos, cap), cap + 1,
            dtype=probs_a.dtype)[..., :cap]                  # [S, K, C]
        # dispatch/combine tensors
        dispatch = jnp.einsum("ske,skc->sec", onehot,
                              cap_oh)                        # [S, E, C]
        combine = jnp.einsum("sk,ske,skc->sec", gates, onehot, cap_oh)

        # load-balance aux loss (reference moe grad path / GShard eq.4)
        me = probs_a.mean(0)                                  # [E]
        ce = onehot[:, 0].mean(0)                             # top-1 share
        self.l_aux = Tensor(jnp.sum(me * ce) * e)

        expert_in = jnp.einsum("sd,sec->ecd", tokens._data, dispatch)
        expert_in = constrain(expert_in, "expert", None, None)

        # batched expert apply via vmap over stacked weights
        pdict = {n: getattr(self,
                            "expert_" + n.replace(".", "_"))._data
                 for n in self._expert_param_names}
        tmpl = self._expert_template
        from ..nn.layer.layers import functional_state

        def one_expert(pvals, xe):
            pj = dict(zip(self._expert_param_names, pvals))
            with functional_state(tmpl, pj, {}):
                return tmpl(Tensor(xe, stop_gradient=True))._data

        expert_out = jax.vmap(one_expert, in_axes=(0, 0))(
            [pdict[n] for n in self._expert_param_names], expert_in)
        expert_out = constrain(expert_out, "expert", None, None)

        out = jnp.einsum("ecd,sec->sd", expert_out, combine)
        # NOTE: routing math runs on raw arrays — differentiable under the
        # functional/jit train path (the only path MoE training uses); the
        # eager tape does not record it.
        return Tensor(out.reshape(b, l, d), stop_gradient=False)
