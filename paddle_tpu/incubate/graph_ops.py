"""Graph-learning ops (reference: python/paddle/incubate/operators/
graph_send_recv.py, graph_khop_sampler.py, graph_reindex.py,
graph_sample_neighbors.py; python/paddle/incubate/tensor/math.py
segment_*; softmax_mask_fuse*.py).

TPU mapping: the dense message-passing compute (segment reductions,
send/recv aggregation, masked softmax) is jax segment ops / XLA-fused
expressions — static-shaped and differentiable. The SAMPLING ops
(khop/neighbors/reindex) are data-dependent-shape graph preprocessing:
they run host-side on numpy (exactly where the reference's CPU kernels
run them in a sampler worker) and feed static batches to the device.
"""
from __future__ import annotations

from typing import Optional, Tuple

import numpy as np

from ..framework.tensor import Tensor

__all__ = ["segment_sum", "segment_mean", "segment_max", "segment_min",
           "graph_send_recv", "graph_khop_sampler", "graph_reindex",
           "graph_sample_neighbors", "softmax_mask_fuse",
           "softmax_mask_fuse_upper_triangle"]


def _arr(x):
    import jax.numpy as jnp
    return x._data if isinstance(x, Tensor) else jnp.asarray(x)


def _segment(data, ids, mode):
    """Shared segment reduction; num_segments = max(ids)+1 (host-read,
    like the reference's dynamic output) — inside jit pass concrete
    arrays only through the functional forms below."""
    import jax
    import jax.numpy as jnp
    d, i = _arr(data), _arr(ids).astype(jnp.int32)
    n = int(jax.device_get(i.max())) + 1 if i.size else 0
    from ..autograd import differentiable_apply

    def fn(dd):
        if mode == "sum":
            return jax.ops.segment_sum(dd, i, num_segments=n)
        if mode == "mean":
            s = jax.ops.segment_sum(dd, i, num_segments=n)
            cnt = jax.ops.segment_sum(jnp.ones_like(i, dd.dtype), i,
                                      num_segments=n)
            shape = (n,) + (1,) * (dd.ndim - 1)
            return s / jnp.maximum(cnt, 1).reshape(shape)
        if mode == "max":
            return jax.ops.segment_max(dd, i, num_segments=n)
        return jax.ops.segment_min(dd, i, num_segments=n)

    return differentiable_apply(
        fn, data if isinstance(data, Tensor) else Tensor(d))


def segment_sum(data, segment_ids, name=None):
    return _segment(data, segment_ids, "sum")


def segment_mean(data, segment_ids, name=None):
    return _segment(data, segment_ids, "mean")


def segment_max(data, segment_ids, name=None):
    return _segment(data, segment_ids, "max")


def segment_min(data, segment_ids, name=None):
    return _segment(data, segment_ids, "min")


def graph_send_recv(x, src_index, dst_index, pool_type="sum",
                    out_size=None, name=None):
    """Message passing: gather x at src, segment-reduce onto dst
    (reference graph_send_recv op)."""
    import jax
    import jax.numpy as jnp
    xv = _arr(x)
    src = _arr(src_index).astype(jnp.int32)
    dst = _arr(dst_index).astype(jnp.int32)
    n = int(out_size) if out_size else xv.shape[0]
    mode = pool_type.lower()
    from ..autograd import differentiable_apply

    def fn(xx):
        msgs = xx[src]
        if mode == "sum":
            return jax.ops.segment_sum(msgs, dst, num_segments=n)
        if mode == "mean":
            s = jax.ops.segment_sum(msgs, dst, num_segments=n)
            cnt = jax.ops.segment_sum(jnp.ones_like(dst, xx.dtype), dst,
                                      num_segments=n)
            return s / jnp.maximum(cnt, 1).reshape(
                (n,) + (1,) * (xx.ndim - 1))
        if mode == "max":
            out = jax.ops.segment_max(msgs, dst, num_segments=n)
            return jnp.where(jnp.isfinite(out), out, 0)  # empty dst -> 0
        if mode == "min":
            out = jax.ops.segment_min(msgs, dst, num_segments=n)
            return jnp.where(jnp.isfinite(out), out, 0)
        raise ValueError(f"unknown pool_type {pool_type!r}")

    return differentiable_apply(
        fn, x if isinstance(x, Tensor) else Tensor(xv))


# --------------------------------------------------------------------------
# host-side samplers (data-dependent shapes; run where the reference's
# CPU sampler kernels run — in the input pipeline)
# --------------------------------------------------------------------------

def _np(x):
    return np.asarray(x.numpy() if isinstance(x, Tensor) else x)


def graph_sample_neighbors(row, colptr, input_nodes, sample_size=-1,
                           eids=None, return_eids=False, perm_buffer=None,
                           name=None):
    """Uniform neighbor sampling on a CSC graph (reference
    graph_sample_neighbors): returns (out_neighbors, out_count[, eids])."""
    rng = np.random
    row_np, colptr_np = _np(row), _np(colptr)
    nodes = _np(input_nodes)
    eids_np = _np(eids) if eids is not None else None
    out, out_eids, counts = [], [], []
    for v in nodes.reshape(-1):
        lo, hi = int(colptr_np[v]), int(colptr_np[v + 1])
        neigh = row_np[lo:hi]
        idx = np.arange(lo, hi)
        if sample_size >= 0 and len(neigh) > sample_size:
            pick = rng.choice(len(neigh), sample_size, replace=False)
            neigh, idx = neigh[pick], idx[pick]
        out.append(neigh)
        counts.append(len(neigh))
        if eids_np is not None:
            out_eids.append(eids_np[idx])
    out_neigh = Tensor(np.concatenate(out) if out else
                       np.zeros((0,), row_np.dtype))
    out_count = Tensor(np.asarray(counts, np.int32))
    if return_eids:
        if eids_np is None:
            raise ValueError("return_eids=True requires eids")
        return out_neigh, out_count, Tensor(
            np.concatenate(out_eids) if out_eids else
            np.zeros((0,), eids_np.dtype))
    return out_neigh, out_count


def graph_reindex(x, neighbors, count, value_buffer=None,
                  index_buffer=None, flag_buffer_hashtable=False,
                  name=None):
    """Compact global node ids to local ids (reference graph_reindex):
    returns (reindexed_src, reindexed_dst, out_nodes)."""
    xs, neigh, cnt = _np(x).reshape(-1), _np(neighbors).reshape(-1), \
        _np(count).reshape(-1)
    order: dict = {}
    for v in xs:
        order.setdefault(int(v), len(order))
    for v in neigh:
        order.setdefault(int(v), len(order))
    out_nodes = np.fromiter(order.keys(), dtype=xs.dtype,
                            count=len(order))
    re_src = np.asarray([order[int(v)] for v in neigh], np.int64)
    re_dst = np.repeat(np.arange(len(xs), dtype=np.int64), cnt)
    return Tensor(re_src), Tensor(re_dst), Tensor(out_nodes)


def graph_khop_sampler(row, colptr, input_nodes, sample_sizes,
                       sorted_eids=None, return_eids=False, name=None):
    """K-hop sampling = repeated neighbor sampling + one final reindex
    (reference graph_khop_sampler). Returns (edge_src, edge_dst,
    sample_index, reindex_nodes): local-id edges, the global ids of all
    touched nodes, and the center nodes' local ids."""
    if return_eids and sorted_eids is None:
        raise ValueError("return_eids=True requires sorted_eids")
    centers = _np(input_nodes).reshape(-1)
    all_src, all_dst, all_eids = [], [], []
    frontier = centers
    for size in sample_sizes:
        res = graph_sample_neighbors(row, colptr, frontier,
                                     sample_size=size, eids=sorted_eids,
                                     return_eids=return_eids)
        neigh, cnt = res[0], res[1]
        neigh_np, cnt_np = _np(neigh), _np(cnt)
        all_src.append(neigh_np)
        all_dst.append(np.repeat(frontier, cnt_np))
        if return_eids:
            all_eids.append(_np(res[2]))
        frontier = np.unique(neigh_np)
    src = np.concatenate(all_src) if all_src else np.zeros((0,), np.int64)
    dst = np.concatenate(all_dst) if all_dst else np.zeros((0,), np.int64)
    # compact global -> local: centers first, then new neighbors
    order: dict = {}
    for v in centers:
        order.setdefault(int(v), len(order))
    for v in src:
        order.setdefault(int(v), len(order))
    nodes = np.fromiter(order.keys(), dtype=np.int64, count=len(order))
    edge_src = np.asarray([order[int(v)] for v in src], np.int64)
    edge_dst = np.asarray([order[int(v)] for v in dst], np.int64)
    center_local = np.asarray([order[int(v)] for v in centers], np.int64)
    out = (Tensor(edge_src), Tensor(edge_dst), Tensor(nodes),
           Tensor(center_local))
    if return_eids:
        eids_cat = np.concatenate(all_eids) if all_eids else \
            np.zeros((0,), np.int64)
        return out + (Tensor(eids_cat),)
    return out


# --------------------------------------------------------------------------
# fused masked softmax (reference softmax_mask_fuse*.py — CUDA fused
# kernels; XLA fuses the same expression on TPU)
# --------------------------------------------------------------------------

def softmax_mask_fuse(x, mask, name=None):
    """softmax(x + mask) along the last axis, fp32 accumulation."""
    import jax
    import jax.numpy as jnp
    from ..autograd import differentiable_apply
    m = _arr(mask)

    def fn(xx):
        z = xx.astype(jnp.float32) + m.astype(jnp.float32)
        return jax.nn.softmax(z, axis=-1).astype(xx.dtype)

    return differentiable_apply(
        fn, x if isinstance(x, Tensor) else Tensor(_arr(x)))


def softmax_mask_fuse_upper_triangle(x):
    """Causal masked softmax: positions j > i get -inf (reference's
    fused upper-triangle variant for GPT attention scores)."""
    import jax
    import jax.numpy as jnp
    from ..autograd import differentiable_apply

    def fn(xx):
        s = xx.shape[-1]
        causal = jnp.tril(jnp.ones((s, s), jnp.bool_))
        z = jnp.where(causal, xx.astype(jnp.float32), -1e9)
        return jax.nn.softmax(z, axis=-1).astype(xx.dtype)

    return differentiable_apply(
        fn, x if isinstance(x, Tensor) else Tensor(_arr(x)))
