"""``paddle.hub`` (reference: python/paddle/hub.py) — load models/entry
points from a ``hubconf.py``. Local and file sources are fully supported;
the github source needs network egress and raises a clear error in
air-gapped environments.
"""
from __future__ import annotations

import importlib.util
import os
import sys

__all__ = ["list", "help", "load"]

_HUBCONF = "hubconf.py"


def _check_dependencies(mod):
    """A hubconf may declare ``dependencies = ["pkg", ...]``; fail fast
    with the full missing list before any entrypoint runs (reference
    hapi/hub.py:158)."""
    deps = getattr(mod, "dependencies", None)
    if not deps:
        return
    missing = []
    for pkg in deps:
        try:
            found = importlib.util.find_spec(pkg) is not None
        except (ModuleNotFoundError, ValueError):
            # dotted names raise when the parent is absent; a stale
            # sys.modules entry with __spec__=None raises ValueError —
            # both mean "not usable", which is what we are reporting
            found = False
        if not found:
            missing.append(pkg)
    if missing:
        raise RuntimeError("Missing dependencies: " + ", ".join(missing))


def _load_hubconf(repo_dir):
    path = os.path.join(repo_dir, _HUBCONF)
    if not os.path.isfile(path):
        raise FileNotFoundError(f"no {_HUBCONF} found under {repo_dir}")
    spec = importlib.util.spec_from_file_location("paddle_tpu_hubconf", path)
    mod = importlib.util.module_from_spec(spec)
    sys.path.insert(0, repo_dir)
    try:
        spec.loader.exec_module(mod)
    finally:
        sys.path.remove(repo_dir)
    _check_dependencies(mod)
    return mod


def _resolve(repo_dir, source):
    if source in ("local", "file"):
        return repo_dir
    if source == "github":
        raise RuntimeError(
            "paddle.hub github source requires network access; clone the "
            "repo and use source='local'")
    raise ValueError(f"unknown source {source!r} (local/file/github)")


def list(repo_dir, source="github", force_reload=False):
    mod = _load_hubconf(_resolve(repo_dir, source))
    return [n for n in dir(mod)
            if callable(getattr(mod, n)) and not n.startswith("_")]


def help(repo_dir, model, source="github", force_reload=False):
    mod = _load_hubconf(_resolve(repo_dir, source))
    fn = getattr(mod, model, None)
    if fn is None:
        raise ValueError(f"model {model!r} not found in {_HUBCONF}")
    return fn.__doc__


def load(repo_dir, model, source="github", force_reload=False, **kwargs):
    mod = _load_hubconf(_resolve(repo_dir, source))
    fn = getattr(mod, model, None)
    if fn is None:
        raise ValueError(f"model {model!r} not found in {_HUBCONF}")
    return fn(**kwargs)
