"""``paddle.cost_model`` (reference: python/paddle/cost_model/cost_model.py
— measures per-op cost of a program to feed the auto-parallel tuner).

TPU-native version: measures per-op wall time through the dispatch layer's
benchmark counters (framework/monitor.py) while executing a callable, and
supports static cost estimation from a jaxpr (FLOP counting via XLA's cost
analysis when available).
"""
from __future__ import annotations

import time

__all__ = ["CostModel"]


class CostModel:
    def __init__(self):
        self._costs = {}

    def profile_measure(self, fn_or_program, *args, device="tpu",
                        fetch_cost_list=("time",), repeat=3, **kwargs):
        """Run ``fn_or_program`` and collect per-op time from the dispatch
        benchmark sweep. Returns {op_name: {"time": seconds_mean, ...}}."""
        from ..framework import flags as _flags
        from ..framework import monitor as _monitor
        old = _flags.get_flags("FLAGS_benchmark").get("FLAGS_benchmark")
        _flags.set_flags({"FLAGS_benchmark": True})
        _monitor.stat_reset()
        try:
            for _ in range(int(repeat)):
                fn_or_program(*args, **kwargs)
        finally:
            _flags.set_flags({"FLAGS_benchmark": bool(old)})
        # op_time_ms/<op> is a DISTRIBUTION (monitor histograms): mean
        # comes straight from its sum/count, and the tails ride along for
        # tuners that want tail latency, not just the average
        self._costs = {}
        for key, h in _monitor.all_histograms().items():
            if not key.startswith("op_time_ms/"):
                continue
            op = key[len("op_time_ms/"):]
            self._costs[op] = {"time": h["sum"] / 1e3 / max(h["count"], 1),
                               "calls": int(h["count"]),
                               "p95_ms": h["p95"], "p99_ms": h["p99"]}
        return self._costs

    def static_cost_data(self):
        """Last measured table (reference keeps a static json of measured
        op benchmarks — here the table is always measured in-situ)."""
        return self._costs

    def get_static_op_time(self, op_name, forward=True, dtype="float32"):
        key = op_name if forward else f"{op_name}_grad"
        if key in self._costs:
            return self._costs[key]
        raise ValueError(
            f"op {key!r} has no measured cost; run profile_measure first")


def estimate_flops(fn, *example_args):
    """FLOP estimate for a jittable callable via XLA cost analysis
    (``framework/program_registry.analyze_callable`` — the one owner of
    the trace→compile→cost_analysis dance). Returns ``None`` when the
    backend provides no analysis — a dashboard must see "unknown", not
    the ``-1.0`` this used to silently return and callers charted."""
    import logging

    from ..framework.program_registry import analyze_callable
    res = analyze_callable(fn, *example_args)
    if res is None or res.get("flops") is None:
        logging.getLogger(__name__).debug(
            "estimate_flops: backend provides no cost analysis for %r",
            fn)
        return None
    return float(res["flops"])
