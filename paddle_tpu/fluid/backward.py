"""``fluid.backward`` shim submodule."""
from ..static import append_backward, gradients  # noqa: F401
