"""``fluid.io`` shim: 1.x save/load entry points WITH the 1.x calling
conventions (executor-first, dirname + feeded_var_names as strings) —
aliasing the 2.x functions directly would bind arguments wrongly.
"""
from __future__ import annotations

import os

from ..io import DataLoader, Dataset  # noqa: F401

__all__ = ["save_persistables", "load_persistables",
           "save_inference_model", "load_inference_model", "DataLoader",
           "Dataset"]


def _prog(main_program):
    from .. import static
    return main_program or static.default_main_program()


def save_persistables(executor, dirname, main_program=None,
                      filename=None):
    """1.x order: (executor, dirname, main_program)."""
    from .. import static
    os.makedirs(dirname, exist_ok=True)
    static.save(_prog(main_program),
                os.path.join(dirname, filename or "params"))


def load_persistables(executor, dirname, main_program=None,
                      filename=None):
    from .. import static
    static.load(_prog(main_program),
                os.path.join(dirname, filename or "params"))


def save_inference_model(dirname, feeded_var_names, target_vars,
                         executor, main_program=None, **kwargs):
    """1.x convention: feed vars by NAME into a directory."""
    from .. import static
    prog = _prog(main_program)
    feed_vars = [prog._vars[prog._var_names[n]] if isinstance(n, str)
                 else n for n in feeded_var_names]
    os.makedirs(dirname, exist_ok=True)
    return static.save_inference_model(
        os.path.join(dirname, "model"), feed_vars, target_vars, executor,
        program=prog if prog._nodes else None)


def load_inference_model(dirname, executor, **kwargs):
    from .. import static
    return static.load_inference_model(os.path.join(dirname, "model"),
                                       executor)
