"""``fluid.layers`` shim: the 1.x op namespace. Resolution order:
static.nn builders (fc/conv2d/batch_norm/sequence_*...), then the
top-level functional API (mean/concat/reshape/...), then
nn.functional — covering the names 1.x model code actually calls.
"""
from __future__ import annotations

from ..static.nn import *  # noqa: F401,F403
from ..static.nn import fc  # noqa: F401
from ..static.nn import cond, while_loop, case, switch_case  # noqa: F401


def __getattr__(name):
    import paddle_tpu as _p
    from paddle_tpu.nn import functional as _F
    for src in (_p, _F):
        if hasattr(src, name):
            return getattr(src, name)
    raise AttributeError(
        f"fluid.layers.{name} is not mapped; use the paddle 2.x API "
        f"(paddle.{name} / paddle.nn.functional.{name} / "
        f"paddle.static.nn.{name})")


def data(name, shape, dtype="float32", append_batch_size=True,
         lod_level=0):
    """1.x fluid.layers.data: ``shape`` is PER-SAMPLE and a batch dim is
    prepended (append_batch_size=True default) — unlike 2.x static.data
    whose shape is the full tensor shape."""
    from ..static import data as _data
    shape = list(shape)
    if append_batch_size:
        shape = [-1] + shape
    return _data(name, shape, dtype)


def cross_entropy(input, label, soft_label=False, ignore_index=-100):
    """1.x cross_entropy took PROBABILITIES (post-softmax). Supports
    arbitrary leading dims ([N, ..., C] with label [N, ..., 1]) and
    ignore_index masking, returning a loss shaped like ``label``."""
    import paddle_tpu as _p
    logp = _p.log(_p.clip(input, 1e-8, 1.0))
    if soft_label:
        return -(_p.sum(label * logp, axis=-1, keepdim=True))
    c = input.shape[-1]
    flat_logp = _p.reshape(logp, [-1, c])
    flat_label = _p.reshape(label, [-1])
    safe = _p.clip(flat_label, 0, c - 1)
    picked = -_p.squeeze(
        _p.take_along_axis(flat_logp,
                           _p.reshape(safe, [-1, 1]), axis=1), axis=1)
    mask = _p.cast(flat_label != ignore_index, picked.dtype)
    return _p.reshape(picked * mask, label.shape)


def softmax_with_cross_entropy(logits, label, soft_label=False, axis=-1):
    from paddle_tpu.nn import functional as _F
    return _F.softmax_with_cross_entropy(logits, label,
                                         soft_label=soft_label, axis=axis)
