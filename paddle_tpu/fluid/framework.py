"""``fluid.framework`` shim as a REAL submodule so the dominant 1.x
import style (`from paddle.fluid.framework import ...`) works."""
from ..framework.tensor import Parameter, Tensor as Variable  # noqa: F401
from ..static import (  # noqa: F401
    Program, default_main_program, default_startup_program,
    in_dynamic_mode, program_guard,
)


def in_dygraph_mode() -> bool:
    return in_dynamic_mode()
