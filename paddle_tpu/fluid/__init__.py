"""``paddle.fluid`` legacy-namespace shim (reference python/paddle/fluid
— the 1.x API surface still shipped in 2.3). Real code migrating from
the reference frequently does ``import paddle.fluid as fluid``; this
module maps the commonly-used legacy names onto their 2.x homes so such
code runs, while new code should use the top-level API.

Coverage: the Program/Executor workflow, places, ParamAttr/initializer,
optimizer, io, dygraph basics, layers (fluid.layers -> static.nn + the
functional namespace). Exotic fluid internals (core C++ bindings, IR
passes) are intentionally absent — XLA replaced them.
"""
from ..framework.place import CPUPlace, CUDAPlace  # noqa: F401
from ..framework.tensor import Tensor as Variable  # noqa: F401
from ..nn.layer.layers import ParamAttr  # noqa: F401
from ..static import (  # noqa: F401
    Executor, Program, Scope, append_backward, data, default_main_program,
    default_startup_program, global_scope, in_dynamic_mode, program_guard,
    scope_guard,
)
from .. import nn  # noqa: F401
from .. import optimizer  # noqa: F401
from ..nn import initializer  # noqa: F401
from . import backward  # noqa: F401
from . import dygraph  # noqa: F401
from . import framework  # noqa: F401
from . import io  # noqa: F401
from . import layers  # noqa: F401


def CUDAPinnedPlace():
    # no pinned-host concept under PjRt; plain CPU place is truthful
    return CPUPlace()


def cuda_places(device_ids=None):
    from ..static import cuda_places as _cp
    return _cp(device_ids)


def cpu_places(device_count=None):
    from ..static import cpu_places as _cp
    return _cp(device_count)


def is_compiled_with_cuda() -> bool:
    from ..device import is_compiled_with_cuda as _c
    return _c()


def in_dygraph_mode() -> bool:
    return in_dynamic_mode()


