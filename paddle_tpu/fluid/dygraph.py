"""``fluid.dygraph`` shim: 1.x imperative API."""
from __future__ import annotations

import contextlib

from ..nn.layer.layers import Layer  # noqa: F401


def to_variable(value, name=None, zero_copy=None, dtype=None):
    import paddle_tpu as _p
    return _p.to_tensor(value, dtype=dtype)


@contextlib.contextmanager
def guard(place=None):
    """1.x dygraph.guard: dynamic mode is the default here; the guard
    just ensures it (and restores static mode after, if it was on)."""
    from .. import static as _s
    was_static = not _s.in_dynamic_mode()
    if was_static:
        _s.disable_static()
    try:
        yield
    finally:
        if was_static:
            _s.enable_static()


def no_grad(fn=None):
    """1.x no_grad: context manager AND decorator."""
    import functools
    import paddle_tpu as _p
    if fn is None:
        return _p.no_grad()

    @functools.wraps(fn)
    def wrapped(*args, **kwargs):
        with _p.no_grad():
            return fn(*args, **kwargs)
    return wrapped
