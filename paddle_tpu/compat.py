"""``paddle.compat`` (reference: python/paddle/compat.py) — py2/py3 string
compatibility helpers still used by downstream code."""
from __future__ import annotations

__all__ = ["long_type", "to_text", "to_bytes", "round", "floor_division",
           "get_exception_message"]

import math

long_type = int


def _convert(obj, conv, container_conv):
    if obj is None:
        return obj
    if isinstance(obj, (list, set)):
        return type(obj)(container_conv(o) for o in obj)
    return conv(obj)


def to_text(obj, encoding="utf-8", inplace=False):
    if isinstance(obj, dict):
        return {to_text(k, encoding): to_text(v, encoding)
                for k, v in obj.items()}
    return _convert(
        obj,
        lambda o: o.decode(encoding) if isinstance(o, bytes) else str(o),
        lambda o: to_text(o, encoding))


def to_bytes(obj, encoding="utf-8", inplace=False):
    if isinstance(obj, dict):
        return {to_bytes(k, encoding): to_bytes(v, encoding)
                for k, v in obj.items()}
    return _convert(
        obj,
        lambda o: o.encode(encoding) if isinstance(o, str) else bytes(o),
        lambda o: to_bytes(o, encoding))


def round(x, d=0):
    """Python2-style round (half away from zero)."""
    if x is None:
        return None
    p = 10 ** d
    if x >= 0:
        return float(math.floor((x * p) + 0.5)) / p
    return float(math.ceil((x * p) - 0.5)) / p


def floor_division(x, y):
    return x // y


def get_exception_message(exc):
    return str(exc)
