"""``paddle.device`` — device query/selection + HBM memory stats.

Reference: python/paddle/device/ (set_device/get_device, cuda memory
query APIs) over the C++ memory facade (fluid/memory/malloc.h,
AllocatorFacade, stats.cc STAT_ADD gpu mem counters — SURVEY §1 L2).

TPU-native: allocation itself belongs to PjRt/XLA (no user-visible
allocator to re-implement — arrays are managed buffers), so the facade's
real surface is OBSERVABILITY: per-device HBM statistics straight from
the PjRt client (``jax`` ``Device.memory_stats``). ``paddle.device.cuda``
is aliased to the same implementation so ported scripts keep working on
TPU.
"""
from __future__ import annotations

from typing import Optional

from ..framework import get_device, set_device  # noqa: F401

__all__ = ["get_device", "set_device", "device_count", "synchronize",
           "get_device_properties", "memory_allocated",
           "max_memory_allocated", "memory_reserved", "memory_stats",
           "memory_summary", "cuda", "is_compiled_with_cuda"]


def _jax():
    import jax
    return jax


def _device(device=None):
    jax = _jax()
    if device is None:
        return jax.devices()[0]
    if isinstance(device, int):
        return jax.devices()[device]
    if isinstance(device, str):
        if ":" in device:
            return jax.devices()[int(device.rsplit(":", 1)[1])]
        # index-less name ("tpu", "gpu", "cpu"): first device of that
        # platform. Unknown/unavailable platforms RAISE — silently
        # falling back to another device hides a 100x misconfiguration.
        devs = jax.devices(device)  # raises for unknown platforms
        if not devs:
            raise RuntimeError(f"no devices for platform {device!r}")
        return devs[0]
    return device


def device_count() -> int:
    return len(_jax().devices())


def is_compiled_with_cuda() -> bool:
    return False  # honest: this build targets TPU via XLA


def synchronize(device=None):
    """Wait until all queued work on the device finished (reference:
    paddle.device.cuda.synchronize). XLA exposes a global effects
    barrier rather than per-stream sync."""
    jax = _jax()
    jax.effects_barrier()


def memory_stats(device=None) -> dict:
    """Raw PjRt memory statistics (bytes_in_use, peak_bytes_in_use,
    bytes_limit, ...); {} where the backend doesn't report (CPU)."""
    d = _device(device)
    try:
        stats = d.memory_stats()
    except Exception:
        stats = None
    return dict(stats or {})


def memory_allocated(device=None) -> int:
    """Bytes currently held by live buffers on the device (reference:
    paddle.device.cuda.memory_allocated over STAT gpu mem)."""
    return int(memory_stats(device).get("bytes_in_use", 0))


def max_memory_allocated(device=None) -> int:
    return int(memory_stats(device).get("peak_bytes_in_use", 0))


def memory_reserved(device=None) -> int:
    """The backend pool's reservation; PjRt reports the usable limit."""
    s = memory_stats(device)
    return int(s.get("bytes_reserved", s.get("bytes_in_use", 0)))


def _fmt_bytes(n: Optional[float]) -> str:
    if n is None:
        return "n/a"
    n = float(n)
    for unit in ("B", "KiB", "MiB", "GiB", "TiB"):
        if abs(n) < 1024 or unit == "TiB":
            return f"{n:,.1f} {unit}" if unit != "B" else f"{int(n):,} B"
        n /= 1024
    return f"{n:,.1f} TiB"


def memory_summary(device=None) -> str:
    """Human-readable HBM table (reference:
    ``paddle.device.cuda.memory_summary``): bytes in use, peak, limit
    and utilization, straight from the PjRt stats. On backends that
    report nothing (CPU) the table says so instead of printing zeros
    that look like measurements."""
    d = _device(device)
    s = memory_stats(d)
    name = getattr(d, "device_kind", str(d))
    lines = [f"device {d.platform}:{d.id} ({name})"]
    if not s:
        lines.append("  (backend reports no memory statistics)")
        return "\n".join(lines)
    in_use = s.get("bytes_in_use")
    peak = s.get("peak_bytes_in_use")
    limit = s.get("bytes_limit")
    rows = [("bytes_in_use", _fmt_bytes(in_use)),
            ("peak_bytes_in_use", _fmt_bytes(peak)),
            ("bytes_limit", _fmt_bytes(limit))]
    if in_use is not None and limit:
        rows.append(("utilization", f"{100.0 * in_use / limit:.1f}%"))
    if peak is not None and limit:
        rows.append(("peak_utilization", f"{100.0 * peak / limit:.1f}%"))
    w = max(len(k) for k, _ in rows)
    lines += [f"  {k:<{w}}  {v}" for k, v in rows]
    return "\n".join(lines)


class _Properties:
    def __init__(self, d):
        self.name = getattr(d, "device_kind", str(d))
        self.total_memory = int(
            memory_stats(d).get("bytes_limit", 0))
        self.platform = d.platform
        self.id = d.id

    def __repr__(self):
        return (f"DeviceProperties(name={self.name!r}, id={self.id}, "
                f"platform={self.platform!r}, "
                f"total_memory={self.total_memory})")


def get_device_properties(device=None) -> _Properties:
    return _Properties(_device(device))


class _CudaAlias:
    """``paddle.device.cuda`` compatibility surface: ported GPU scripts
    query memory/sync through the TPU PjRt stats."""
    device_count = staticmethod(device_count)
    synchronize = staticmethod(synchronize)
    memory_allocated = staticmethod(memory_allocated)
    max_memory_allocated = staticmethod(max_memory_allocated)
    memory_reserved = staticmethod(memory_reserved)
    memory_summary = staticmethod(memory_summary)
    get_device_properties = staticmethod(get_device_properties)

    @staticmethod
    def empty_cache():
        # PjRt owns its pools; there is no user-level cache to drop.
        return None


cuda = _CudaAlias()


# --------------------------------------------------------------------------
# device-family compat surface (reference python/paddle/device/__init__.py)
# the truthful answers on a TPU/XLA backend: no CUDA/XPU/NPU/MLU/IPU
# compilation, no cudnn; device discovery reports what PjRt sees
# --------------------------------------------------------------------------

class _UnavailablePlace:
    _kind = "device"

    def __init__(self, dev_id=0):
        raise RuntimeError(
            f"{type(self).__name__}: this backend is TPU-over-XLA; "
            f"{self._kind} devices do not exist here (the reference "
            f"raises identically unless compiled with that device)")


class XPUPlace(_UnavailablePlace):
    _kind = "XPU"


class MLUPlace(_UnavailablePlace):
    _kind = "MLU"


class IPUPlace(_UnavailablePlace):
    _kind = "IPU"


def is_compiled_with_ipu() -> bool:
    return False


def is_compiled_with_mlu() -> bool:
    return False


def is_compiled_with_npu() -> bool:
    return False


def is_compiled_with_xpu() -> bool:
    return False


def is_compiled_with_cinn() -> bool:
    return False


def is_compiled_with_rocm() -> bool:
    return False


def get_cudnn_version():
    return None     # no cuDNN in an XLA/TPU build


def get_all_device_type():
    import jax
    return sorted({d.platform for d in jax.devices()})


def get_all_custom_device_type():
    return []


def get_available_device():
    import jax
    return [f"{d.platform}:{d.id}" for d in jax.devices()]


def get_available_custom_device():
    return []


__all__ += ["XPUPlace", "MLUPlace", "IPUPlace", "is_compiled_with_ipu",
            "is_compiled_with_mlu", "is_compiled_with_npu",
            "is_compiled_with_xpu", "is_compiled_with_cinn",
            "is_compiled_with_rocm", "get_cudnn_version",
            "get_all_device_type", "get_all_custom_device_type",
            "get_available_device", "get_available_custom_device"]
