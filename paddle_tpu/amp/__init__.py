"""``paddle.amp`` — automatic mixed precision.

Analog of the reference's ``python/paddle/amp/`` (auto_cast.py:21-78 O1/O2
black/white lists; grad_scaler.py:26 GradScaler with dynamic loss scaling
backed by check_finite_and_unscale / update_loss_scaling CUDA ops).

TPU-native design: bf16 is the native mixed-precision dtype — it needs NO
loss scaling (same exponent range as fp32), so ``auto_cast`` with bf16 is a
pure dtype policy and ``GradScaler`` degenerates to a pass-through unless
fp16 is explicitly requested. The O1 white/black list maps to a per-op cast
decision applied in the dispatch layer; O2 casts parameters once.
"""
from __future__ import annotations

import contextlib
import threading
import weakref
from typing import Optional

import jax
import jax.numpy as jnp

from ..framework.monitor import stat_add, stat_observe
from ..framework.tensor import Tensor

__all__ = ["auto_cast", "amp_guard", "GradScaler", "decorate",
           "white_list", "black_list", "is_auto_cast_enabled",
           "get_amp_dtype", "active_scaler"]

# O1 lists (reference amp/auto_cast.py WHITE_LIST/BLACK_LIST): matmul-class
# ops run in low precision; numerically-sensitive ops stay fp32.
white_list = {
    "matmul", "bmm", "mv", "linear", "conv1d", "conv2d", "conv3d",
    "conv2d_transpose", "einsum", "addmm",
    "scaled_dot_product_attention",
}
black_list = {
    "softmax", "log_softmax", "layer_norm", "batch_norm", "group_norm",
    "instance_norm", "rms_norm", "cross_entropy",
    "softmax_with_cross_entropy", "nll_loss", "bce_loss", "bce_with_logits",
    "mean", "sum", "p_norm", "frobenius_norm", "logsumexp", "exp", "log",
    "cumsum", "prod",
}

_amp_state = threading.local()


def is_auto_cast_enabled() -> bool:
    return getattr(_amp_state, "enabled", False)


def get_amp_dtype():
    return getattr(_amp_state, "dtype", None)


def get_amp_level():
    return getattr(_amp_state, "level", "O0")


@contextlib.contextmanager
def auto_cast(enable=True, custom_white_list=None, custom_black_list=None,
              level="O1", dtype="bfloat16"):
    """Context manager enabling per-op autocast in the dispatch layer."""
    old = (getattr(_amp_state, "enabled", False),
           getattr(_amp_state, "dtype", None),
           getattr(_amp_state, "level", "O0"),
           getattr(_amp_state, "white", None),
           getattr(_amp_state, "black", None))
    _amp_state.enabled = enable
    _amp_state.dtype = jnp.bfloat16 if dtype in ("bfloat16", "bf16") \
        else jnp.float16
    _amp_state.level = level
    _amp_state.white = white_list | set(custom_white_list or ())
    _amp_state.black = (black_list - set(custom_white_list or ())) | \
        set(custom_black_list or ())
    try:
        yield
    finally:
        (_amp_state.enabled, _amp_state.dtype, _amp_state.level,
         _amp_state.white, _amp_state.black) = old


amp_guard = auto_cast


def amp_cast_inputs(op_name: str, arrays):
    """Called from dispatch.call_op: cast op inputs per the active policy."""
    if not is_auto_cast_enabled():
        return arrays
    dtype = get_amp_dtype()
    level = get_amp_level()
    white = getattr(_amp_state, "white", white_list)
    black = getattr(_amp_state, "black", black_list)
    if op_name in black:
        target = jnp.float32
    elif op_name in white or level == "O2":
        target = dtype
    else:
        return arrays
    out = []
    for a in arrays:
        if hasattr(a, "dtype") and jnp.issubdtype(a.dtype, jnp.floating) \
                and a.dtype != target:
            out.append(a.astype(target))
        else:
            out.append(a)
    return out


def decorate(models, optimizers=None, level="O2", dtype="bfloat16",
             master_weight=None, save_dtype=None):
    """O2 decoration: cast model parameters to the AMP dtype (reference
    amp/auto_cast.py:decorate / fluid contrib decorator.py). With bf16 on
    TPU, master weights stay fp32 inside optimizer slots."""
    dt = "bfloat16" if dtype in ("bfloat16", "bf16") else "float16"
    single = not isinstance(models, (list, tuple))
    model_list = [models] if single else list(models)
    if level == "O2":
        for m in model_list:
            m.to(dtype=dt)
    # record the policy on the model so compiled-step engines
    # (ParallelEngine, hapi adapter) trace the forward under auto_cast —
    # otherwise fp32 *inputs* meet low-precision weights and dtype-strict
    # ops (conv) reject the mix
    for m in model_list:
        m._amp_level = level
        m._amp_dtype = dt
    if optimizers is None:
        return models if single else model_list
    return (models if single else model_list), optimizers


# the most recently constructed ENABLED scaler (weakref): the numerics
# flight recorder (profiler/numerics.py) stamps its state — scale,
# good/bad-step streaks, found_inf — into every per-step record so a
# postmortem shows what the loss-scaling state machine was doing when
# training went nonfinite
_active_scaler: Optional["weakref.ref"] = None


def active_scaler() -> Optional["GradScaler"]:
    """The live, enabled :class:`GradScaler` most recently constructed
    in this process, or ``None`` (bf16 runs have no scaler)."""
    s = _active_scaler() if _active_scaler is not None else None
    return s if s is not None and s._enable else None


class GradScaler:
    """Dynamic loss scaling (reference amp/grad_scaler.py:26).

    State machine: scale *= incr_ratio after incr_every_n_steps finite
    steps; scale *= decr_ratio after decr_every_n_nan_or_inf non-finite
    steps, which are skipped. For bf16 (enable=False or use_loss_scaling
    False) this is a transparent pass-through — the TPU-native default.

    Observable: every ``update()`` lands the post-update scale in the
    ``amp/loss_scale`` histogram and counts nonfinite updates in
    ``amp/found_inf``; :meth:`state` is the snapshot the training
    numerics flight recorder rides along per step.
    """

    def __init__(self, enable=True, init_loss_scaling=2. ** 15,
                 incr_ratio=2.0, decr_ratio=0.5, incr_every_n_steps=1000,
                 decr_every_n_nan_or_inf=2, use_dynamic_loss_scaling=True):
        self._enable = enable
        self._scale = float(init_loss_scaling) if enable else 1.0
        self._incr_ratio = incr_ratio
        self._decr_ratio = decr_ratio
        self._incr_every = incr_every_n_steps
        self._decr_every = decr_every_n_nan_or_inf
        self._dynamic = use_dynamic_loss_scaling
        self._good_steps = 0
        self._bad_steps = 0
        self._found_inf = False
        # INIT -> UNSCALED -> STEPPED cycle, reset by update() (reference
        # grad_scaler.py OptimizerState tracking).
        self._stage = "INIT"
        if enable:
            global _active_scaler
            _active_scaler = weakref.ref(self)

    def is_enable(self):
        return self._enable

    def is_use_dynamic_loss_scaling(self):
        return self._enable and self._dynamic

    def get_loss_scaling(self):
        return self._scale

    def scale(self, loss):
        if not self._enable:
            return loss
        from ..framework.dispatch import call_op
        return call_op("scale", loss, scale=self._scale, bias=0.0)

    def unscale_(self, optimizer):
        """Unscale grads in-place and record found_inf (reference
        grad_scaler.py:243 _unscale → check_finite_and_unscale op)."""
        if not self._enable:
            return
        if self._stage != "INIT":
            raise RuntimeError(
                "unscale_() may only be called once between update()s, "
                "and not after step().")
        params = optimizer._parameter_list or []
        inv = 1.0 / self._scale
        found = False
        for p in params:
            if p.grad is None:
                continue
            g = p.grad._data * inv
            p.grad._data = g
            if not bool(jnp.all(jnp.isfinite(g))):
                found = True
        self._found_inf = found
        self._stage = "UNSCALED"

    def step(self, optimizer):
        if not self._enable:
            optimizer.step()
            return
        if self._stage == "STEPPED":
            raise RuntimeError(
                "step() has already been called since the last update().")
        if self._stage != "UNSCALED":
            self.unscale_(optimizer)
        if not self._found_inf:
            optimizer.step()
        # NOTE: no update() here — the canonical pattern is
        # `scaler.step(opt); scaler.update()` (reference grad_scaler.py:159).
        self._stage = "STEPPED"

    def minimize(self, optimizer, scaled_loss):
        scaled_loss.backward()
        self.step(optimizer)
        self.update()

    def update(self):
        self._stage = "INIT"
        if not (self._enable and self._dynamic):
            return
        if self._found_inf:
            stat_add("amp/found_inf")
            self._bad_steps += 1
            self._good_steps = 0
            if self._bad_steps >= self._decr_every:
                self._scale = max(self._scale * self._decr_ratio, 1.0)
                self._bad_steps = 0
        else:
            self._good_steps += 1
            self._bad_steps = 0
            if self._good_steps >= self._incr_every:
                self._scale *= self._incr_ratio
                self._good_steps = 0
        self._found_inf = False
        # post-update scale: the histogram's trajectory IS the loss-
        # scaling state machine's history (halvings on inf bursts,
        # doublings on good streaks)
        stat_observe("amp/loss_scale", self._scale)

    def state(self) -> dict:
        """Host snapshot for the numerics flight recorder: the scale,
        the good/bad-step streaks, and the pending found_inf verdict."""
        return {"scale": self._scale, "good_steps": self._good_steps,
                "bad_steps": self._bad_steps,
                "found_inf": self._found_inf, "enabled": self._enable}

    def state_dict(self):
        return {"scale": self._scale, "incr_ratio": self._incr_ratio,
                "decr_ratio": self._decr_ratio,
                "incr_count": self._good_steps,
                "decr_count": self._bad_steps,
                "use_dynamic_loss_scaling": self._dynamic}

    def load_state_dict(self, state):
        self._scale = state.get("scale", self._scale)
        self._good_steps = state.get("incr_count", 0)
        self._bad_steps = state.get("decr_count", 0)
