"""Optimizer base and the built-in optimizers.

Analog of the reference's ``python/paddle/optimizer/optimizer.py`` (state
accumulators, ``_append_optimize_op``, grad-clip integration) and the
per-optimizer device kernels (paddle/fluid/operators/optimizers/). TPU-native
design: each optimizer's update rule is one pure function
``_rule(param, grad, slots, lr) -> (new_param, new_slots)``; the eager
``step()`` applies it per parameter, while ``apply_gradients`` runs the same
rule inside a jitted train step where XLA fuses the whole parameter sweep
(the role of the reference's multi-tensor ``merged_adam``).
"""
from __future__ import annotations

from typing import Dict, List, Optional

import jax
import jax.numpy as jnp
import numpy as np

from ..framework.tensor import Parameter, Tensor, no_grad_guard
from ..nn.clip import ClipGradBase
from .lr import LRScheduler

__all__ = ["Optimizer", "SGD", "Momentum", "Adam", "AdamW", "Adamax",
           "Adagrad", "Adadelta", "RMSProp", "Lamb"]


class L2Decay:
    def __init__(self, coeff=0.0):
        self.coeff = float(coeff)


class L1Decay:
    def __init__(self, coeff=0.0):
        self.coeff = float(coeff)


class Optimizer:
    _slot_names: List[str] = []
    # elementwise rules shard onto flat parameter stripes (the ZeRO
    # weight update, hapi/zero.py); optimizers whose rule has per-PARAM
    # semantics a flat view cannot express (Lamb's per-layer trust
    # ratio) opt out and fit(zero=1) rejects them with a clear error
    _flat_rule_supported = True

    def __init__(self, learning_rate=0.001, parameters=None,
                 weight_decay=None, grad_clip=None, name=None,
                 multi_precision=False):
        self._lr = learning_rate
        if parameters is not None:
            parameters = list(parameters)
        self._parameter_list = parameters
        if isinstance(weight_decay, float):
            weight_decay = L2Decay(weight_decay)
        self._weight_decay = weight_decay
        self._grad_clip = grad_clip
        self._multi_precision = multi_precision
        # slots[param_name][slot_name] -> jnp array; counters separate
        self._slots: Dict[str, Dict[str, jnp.ndarray]] = {}
        self._step_count = 0
        # param currently being updated (AdamW/Lamb per-param weight-decay
        # exclusion hooks read these; _current_param is the Parameter in
        # eager mode, a name-only shim in the functional path)
        self._current_param_name = None
        self._current_param = None

    # -- lr -----------------------------------------------------------------
    def get_lr(self) -> float:
        if isinstance(self._lr, LRScheduler):
            return float(self._lr())
        return float(self._lr)

    def set_lr(self, value):
        if isinstance(self._lr, LRScheduler):
            raise RuntimeError(
                "set_lr is not allowed when the lr is an LRScheduler; call "
                "scheduler.step() instead")
        self._lr = float(value)

    @property
    def _learning_rate(self):
        return self._lr

    # -- state --------------------------------------------------------------
    def _adopt_alias(self, name: str) -> bool:
        """Adopt slots mirrored under a hapi tree name: Model.fit keys
        its functional state structurally ('0.weight') while the eager
        step keys by Parameter.name — migrating the entry (pop + rekey)
        carries the trained moments into an eager continuation
        consistently with _step_count, instead of bias-correcting fresh
        zeros at an inflated step, and keeps state_dict() to a single
        key family."""
        alias = getattr(self, "_slot_aliases", {}).get(name)
        if alias is not None and alias in self._slots:
            self._slots[name] = self._slots.pop(alias)
            return True
        return False

    def _ensure_slots(self, name: str, param_value: jnp.ndarray):
        if name not in self._slots and not self._adopt_alias(name):
            self._slots[name] = {
                s: jnp.zeros_like(param_value) for s in self._slot_names}
        return self._slots[name]

    def state_dict(self) -> dict:
        out = {}
        for pname, slots in self._slots.items():
            for sname, arr in slots.items():
                out[f"{pname}_{sname}"] = Tensor(arr)
        out["@step"] = self._step_count
        if isinstance(self._lr, LRScheduler):
            out["LR_Scheduler"] = self._lr.state_dict()
        return out

    def set_state_dict(self, state: dict):
        self._step_count = int(state.get("@step", 0))
        if isinstance(self._lr, LRScheduler) and "LR_Scheduler" in state:
            self._lr.set_state_dict(state["LR_Scheduler"])
        # slots are restored lazily by name on first step; eager restore:
        for key, value in state.items():
            if key in ("@step", "LR_Scheduler"):
                continue
            # "_t0" is the per-param birth-step marker written by
            # progressive unfreezing (see apply_gradients) — restored
            # like any slot so the offset survives a checkpoint
            for sname in list(self._slot_names) + ["master_weight",
                                                   "_t0"]:
                suffix = "_" + sname
                if key.endswith(suffix):
                    pname = key[: -len(suffix)]
                    arr = value._data if isinstance(value, Tensor) \
                        else jnp.asarray(value)
                    self._slots.setdefault(pname, {})[sname] = arr
                    break

    # -- update rule (pure; subclasses override) ----------------------------
    def _rule(self, p, g, slots, lr, step):
        raise NotImplementedError

    def _decay_grad(self, p, g):
        if isinstance(self._weight_decay, L2Decay) and \
                self._weight_decay.coeff:
            return g + self._weight_decay.coeff * p
        if isinstance(self._weight_decay, L1Decay) and \
                self._weight_decay.coeff:
            return g + self._weight_decay.coeff * jnp.sign(p)
        return g

    def _needs_master(self, value) -> bool:
        return self._multi_precision and value.dtype in (
            jnp.bfloat16, jnp.float16)

    def _apply_rule(self, p_value, g, slots, lr, step):
        """Run _rule with fp32 master weights when multi_precision asks for
        them (reference: optimizers' master_param accumulators) — the master
        is updated and the low-precision param is a cast-down view, so small
        updates don't round away every step."""
        if self._needs_master(p_value):
            master = slots.get("master_weight")
            if master is None:
                master = p_value.astype(jnp.float32)
            rule_slots = {k: v for k, v in slots.items()
                          if k != "master_weight"}
            new_master, new_slots = self._rule(master, g, rule_slots, lr,
                                               step)
            new_slots = dict(new_slots)
            new_slots["master_weight"] = new_master
            return new_master.astype(p_value.dtype), new_slots
        return self._rule(p_value, g, slots, lr, step)

    # -- eager step ---------------------------------------------------------
    def step(self):
        if self._parameter_list is None:
            raise ValueError(
                "optimizer was created without a parameter list; pass "
                "parameters=model.parameters()")
        params_grads = [(p, p.grad._data) for p in self._parameter_list
                        if p.grad is not None and not p.stop_gradient]
        if self._grad_clip is not None:
            params_grads = self._grad_clip(params_grads)
        self._step_count += 1
        self._use_fused = True  # eager path may take the Pallas kernel
        try:
            with no_grad_guard():
                for p, g in params_grads:
                    self._current_param_name = p.name
                    self._current_param = p
                    lr = self.get_lr() * getattr(
                        p, "optimize_attr", {}).get("learning_rate", 1.0)
                    g = self._decay_grad(p._data, g.astype(p._data.dtype)
                                         if hasattr(g, "astype") else g)
                    slots = self._ensure_slots(p.name, p._data)
                    # honor the per-param birth step (progressive
                    # unfreezing / hapi adoption) in eager mode too
                    t0 = slots.get("_t0")
                    eff = self._step_count if t0 is None else \
                        self._step_count - int(t0)
                    new_p, new_slots = self._apply_rule(
                        p._data, g, slots, lr, eff)
                    if t0 is not None:
                        new_slots = dict(new_slots)
                        new_slots["_t0"] = t0
                    p._data = new_p
                    self._slots[p.name] = new_slots
        finally:
            self._use_fused = False
            self._current_param_name = None
            self._current_param = None

    def minimize(self, loss, startup_program=None, parameters=None,
                 no_grad_set=None):
        from ..framework import static_capture
        if static_capture.current is not None:
            # static-graph mode: attach loss + this optimizer to the
            # program being built; Executor.run replays the graph as a
            # jitted train step (reference: minimize under program_guard
            # appending backward + optimize ops to the ProgramDesc)
            prog = static_capture.current
            prog._loss = loss
            prog._optimizer = self
            return [], []
        loss.backward()
        self.step()
        self.clear_grad()
        return None, None

    def clear_grad(self, set_to_zero=False):
        if self._parameter_list is not None:
            for p in self._parameter_list:
                p.clear_grad()

    clear_gradients = clear_grad

    # -- functional API for jitted train steps ------------------------------
    def _init_slot_dict(self, value):
        slots = {s: jnp.zeros_like(value) for s in self._slot_names}
        if self._needs_master(value):
            slots["master_weight"] = value.astype(jnp.float32)
        return slots

    def init_state(self, params: Dict[str, jnp.ndarray]):
        """Pure optimizer state for `apply_gradients` (step=0)."""
        return {
            "step": jnp.zeros((), jnp.int32),
            "slots": {name: self._init_slot_dict(v)
                      for name, v in params.items()},
        }

    def flat_rule(self, p, g, slots, lr, step, decay_mask=None):
        """Shard-local weight update over one flat f32 STRIPE of the
        parameter vector — the ZeRO-sharded train step's per-replica
        rule (hapi/zero.py). ``p``/``g`` are 1-D f32 stripes; ``slots``
        holds this stripe's slice of each flat slot; ``step`` may be a
        per-ELEMENT vector (params (re)born mid-run carry their own age
        — the flat analog of the ``_t0`` marker, broadcast through the
        elementwise bias-correction math). ``decay_mask`` is a 0/1
        per-element mask when only some params take weight decay.

        Default implementation folds L2/L1 decay into the gradient
        (masked) and runs the elementwise ``_rule`` — exact for every
        built-in optimizer whose update touches elements independently;
        per-param-semantics optimizers set ``_flat_rule_supported =
        False`` instead of shipping a silently-wrong flat rule."""
        if isinstance(self._weight_decay, L2Decay) and \
                self._weight_decay.coeff:
            d = self._weight_decay.coeff * p
            g = g + (d if decay_mask is None else d * decay_mask)
        elif isinstance(self._weight_decay, L1Decay) and \
                self._weight_decay.coeff:
            d = self._weight_decay.coeff * jnp.sign(p)
            g = g + (d if decay_mask is None else d * decay_mask)
        return self._rule(p, g, slots, lr, step)

    def apply_gradients(self, params, grads, state, lr=None):
        """Pure update: (params, grads, state) -> (new_params, new_state).

        Runs under jit; `lr` arrives as a traced scalar so schedulers never
        retrigger compilation.
        """
        lr = lr if lr is not None else self.get_lr()
        step = state["step"] + 1
        new_params, new_slots = {}, {}
        for name, p in params.items():
            g = grads[name]
            if g is None:
                new_params[name] = p
                new_slots[name] = state["slots"][name]
                continue
            self._current_param_name = name
            from types import SimpleNamespace
            self._current_param = SimpleNamespace(name=name)
            g = self._decay_grad(p, g.astype(p.dtype))
            slots_in = state["slots"][name]
            # "_t0" marks a param whose slots were (re)born mid-run —
            # progressive unfreezing — so step-dependent rules (Adam
            # bias correction) see its OWN age, not the global step:
            # zeroed moments at a large step would otherwise update at
            # ~3x the intended lr for the first few steps
            t0 = slots_in.get("_t0")
            new_p, ns = self._apply_rule(
                p, g, slots_in, lr, step if t0 is None else step - t0)
            if t0 is not None:
                ns = dict(ns)
                ns["_t0"] = t0
            new_params[name] = new_p
            new_slots[name] = ns
        self._current_param_name = None
        self._current_param = None
        return new_params, {"step": step, "slots": new_slots}


class SGD(Optimizer):
    _slot_names: List[str] = []

    def __init__(self, learning_rate=0.001, parameters=None,
                 weight_decay=None, grad_clip=None, name=None,
                 multi_precision=False):
        super().__init__(learning_rate, parameters, weight_decay, grad_clip,
                         name, multi_precision)

    def _rule(self, p, g, slots, lr, step):
        return (p - lr * g).astype(p.dtype), slots


class Momentum(Optimizer):
    _slot_names = ["velocity"]

    def __init__(self, learning_rate=0.001, momentum=0.9, parameters=None,
                 use_nesterov=False, weight_decay=None, grad_clip=None,
                 name=None, multi_precision=False, rescale_grad=1.0):
        super().__init__(learning_rate, parameters, weight_decay, grad_clip,
                         name, multi_precision)
        self._momentum = momentum
        self._nesterov = use_nesterov

    def _rule(self, p, g, slots, lr, step):
        v = self._momentum * slots["velocity"] + g
        if self._nesterov:
            new_p = p - lr * (g + self._momentum * v)
        else:
            new_p = p - lr * v
        return new_p.astype(p.dtype), {"velocity": v}


class Adam(Optimizer):
    _slot_names = ["moment1", "moment2"]

    def __init__(self, learning_rate=0.001, beta1=0.9, beta2=0.999,
                 epsilon=1e-8, parameters=None, weight_decay=None,
                 grad_clip=None, lazy_mode=False, multi_precision=False,
                 name=None):
        super().__init__(learning_rate, parameters, weight_decay, grad_clip,
                         name, multi_precision)
        self._beta1, self._beta2, self._eps = beta1, beta2, epsilon

    def _rule(self, p, g, slots, lr, step):
        fused = self._maybe_fused(p, g, slots, lr, step, wd=0.0)
        if fused is not None:
            return fused
        gf = g.astype(jnp.float32)
        m = self._beta1 * slots["moment1"] + (1 - self._beta1) * gf
        v = self._beta2 * slots["moment2"] + (1 - self._beta2) * gf * gf
        stepf = jnp.asarray(step, jnp.float32)
        mhat = m / (1 - self._beta1 ** stepf)
        vhat = v / (1 - self._beta2 ** stepf)
        new_p = p.astype(jnp.float32) - lr * mhat / (
            jnp.sqrt(vhat) + self._eps)
        return new_p.astype(p.dtype), {"moment1": m, "moment2": v}

    def _maybe_fused(self, p, g, slots, lr, step, wd):
        """Eager step on TPU: one fused Pallas kernel per param (reference:
        operators/optimizers/adam_op.cu / merged_adam multi-tensor path)."""
        if not getattr(self, "_use_fused", False):
            return None
        from ..ops import pallas_kernels as pk
        if not pk.fused_adamw_available():
            return None
        new_p, m, v = pk.fused_adamw(
            p, g, slots["moment1"], slots["moment2"], lr,
            self._beta1, self._beta2, self._eps, wd, step)
        return new_p, {"moment1": m, "moment2": v}

    def _ensure_slots(self, name, value):
        if name not in self._slots and not self._adopt_alias(name):
            self._slots[name] = self._init_slot_dict(value)
        return self._slots[name]

    def _init_slot_dict(self, value):
        slots = {s: jnp.zeros(value.shape, jnp.float32)
                 for s in self._slot_names}
        if self._needs_master(value):
            slots["master_weight"] = value.astype(jnp.float32)
        return slots


class AdamW(Adam):
    """Decoupled weight decay (reference optimizer/adamw.py)."""

    def __init__(self, learning_rate=0.001, beta1=0.9, beta2=0.999,
                 epsilon=1e-8, parameters=None, weight_decay=0.01,
                 lr_ratio=None, apply_decay_param_fun=None, grad_clip=None,
                 lazy_mode=False, multi_precision=False, name=None):
        super().__init__(learning_rate, beta1, beta2, epsilon, parameters,
                         None, grad_clip, lazy_mode, multi_precision, name)
        self._wd_coeff = float(weight_decay) \
            if not isinstance(weight_decay, (L2Decay, L1Decay)) \
            else weight_decay.coeff
        self._apply_decay_param_fun = apply_decay_param_fun
        self._current_param_name = None

    def _decay_grad(self, p, g):
        return g  # decoupled — handled in _rule

    def flat_rule(self, p, g, slots, lr, step, decay_mask=None):
        """Flat-stripe AdamW: the Adam moments elementwise plus the
        DECOUPLED decay term, masked per element — the flat carrier of
        ``apply_decay_param_fun`` (the ZeRO step bakes the per-param
        predicate into a 0/1 vector; see FlatLayout.mask_from)."""
        gf = g.astype(jnp.float32)
        m = self._beta1 * slots["moment1"] + (1 - self._beta1) * gf
        v = self._beta2 * slots["moment2"] + (1 - self._beta2) * gf * gf
        stepf = jnp.asarray(step, jnp.float32)
        mhat = m / (1 - self._beta1 ** stepf)
        vhat = v / (1 - self._beta2 ** stepf)
        pf = p.astype(jnp.float32)
        decay = self._wd_coeff if decay_mask is None \
            else self._wd_coeff * decay_mask
        new_p = pf - lr * (mhat / (jnp.sqrt(vhat) + self._eps)
                           + decay * pf)
        return new_p.astype(p.dtype), {"moment1": m, "moment2": v}

    def _wd_enabled(self, name):
        return self._apply_decay_param_fun is None or \
            self._apply_decay_param_fun(name)

    def _rule(self, p, g, slots, lr, step):
        decay = self._wd_coeff if (
            self._current_param_name is None or
            self._wd_enabled(self._current_param_name)) else 0.0
        fused = self._maybe_fused(p, g, slots, lr, step, wd=decay)
        if fused is not None:
            return fused
        gf = g.astype(jnp.float32)
        m = self._beta1 * slots["moment1"] + (1 - self._beta1) * gf
        v = self._beta2 * slots["moment2"] + (1 - self._beta2) * gf * gf
        stepf = jnp.asarray(step, jnp.float32)
        mhat = m / (1 - self._beta1 ** stepf)
        vhat = v / (1 - self._beta2 ** stepf)
        pf = p.astype(jnp.float32)
        new_p = pf - lr * (mhat / (jnp.sqrt(vhat) + self._eps) + decay * pf)
        return new_p.astype(p.dtype), {"moment1": m, "moment2": v}


class Adamax(Optimizer):
    _slot_names = ["moment", "inf_norm"]

    def __init__(self, learning_rate=0.001, beta1=0.9, beta2=0.999,
                 epsilon=1e-8, parameters=None, weight_decay=None,
                 grad_clip=None, name=None):
        super().__init__(learning_rate, parameters, weight_decay, grad_clip,
                         name)
        self._beta1, self._beta2, self._eps = beta1, beta2, epsilon

    def _rule(self, p, g, slots, lr, step):
        gf = g.astype(jnp.float32)
        m = self._beta1 * slots["moment"] + (1 - self._beta1) * gf
        u = jnp.maximum(self._beta2 * slots["inf_norm"], jnp.abs(gf))
        stepf = jnp.asarray(step, jnp.float32)
        new_p = p.astype(jnp.float32) - \
            (lr / (1 - self._beta1 ** stepf)) * m / (u + self._eps)
        return new_p.astype(p.dtype), {"moment": m, "inf_norm": u}


class Adagrad(Optimizer):
    _slot_names = ["moment"]

    def __init__(self, learning_rate, epsilon=1e-6, parameters=None,
                 weight_decay=None, grad_clip=None, name=None,
                 initial_accumulator_value=0.0):
        super().__init__(learning_rate, parameters, weight_decay, grad_clip,
                         name)
        self._eps = epsilon
        self._init_acc = initial_accumulator_value

    def _ensure_slots(self, name, value):
        if name not in self._slots and not self._adopt_alias(name):
            self._slots[name] = {"moment": jnp.full(
                value.shape, self._init_acc, jnp.float32)}
        return self._slots[name]

    def _rule(self, p, g, slots, lr, step):
        gf = g.astype(jnp.float32)
        acc = slots["moment"] + gf * gf
        new_p = p.astype(jnp.float32) - lr * gf / (jnp.sqrt(acc) + self._eps)
        return new_p.astype(p.dtype), {"moment": acc}


class Adadelta(Optimizer):
    _slot_names = ["avg_squared_grad", "avg_squared_update"]

    def __init__(self, learning_rate=0.001, epsilon=1e-6, rho=0.95,
                 parameters=None, weight_decay=None, grad_clip=None,
                 name=None):
        super().__init__(learning_rate, parameters, weight_decay, grad_clip,
                         name)
        self._eps, self._rho = epsilon, rho

    def _rule(self, p, g, slots, lr, step):
        gf = g.astype(jnp.float32)
        eg = self._rho * slots["avg_squared_grad"] + (1 - self._rho) * gf * gf
        update = -jnp.sqrt(
            (slots["avg_squared_update"] + self._eps) /
            (eg + self._eps)) * gf
        eu = self._rho * slots["avg_squared_update"] + \
            (1 - self._rho) * update * update
        new_p = p.astype(jnp.float32) + lr * update
        return new_p.astype(p.dtype), {"avg_squared_grad": eg,
                                       "avg_squared_update": eu}


class RMSProp(Optimizer):
    _slot_names = ["mean_square", "mean_grad", "momentum"]

    def __init__(self, learning_rate, rho=0.95, epsilon=1e-6, momentum=0.0,
                 centered=False, parameters=None, weight_decay=None,
                 grad_clip=None, name=None):
        super().__init__(learning_rate, parameters, weight_decay, grad_clip,
                         name)
        self._rho, self._eps = rho, epsilon
        self._momentum = momentum
        self._centered = centered

    def _rule(self, p, g, slots, lr, step):
        gf = g.astype(jnp.float32)
        ms = self._rho * slots["mean_square"] + (1 - self._rho) * gf * gf
        if self._centered:
            mg = self._rho * slots["mean_grad"] + (1 - self._rho) * gf
            denom = jnp.sqrt(ms - mg * mg + self._eps)
        else:
            mg = slots["mean_grad"]
            denom = jnp.sqrt(ms + self._eps)
        mom = self._momentum * slots["momentum"] + lr * gf / denom
        new_p = p.astype(jnp.float32) - mom
        return new_p.astype(p.dtype), {"mean_square": ms, "mean_grad": mg,
                                       "momentum": mom}


class Lamb(Optimizer):
    """Layer-wise adaptive moments for large-batch training (reference
    optimizer/lamb.py)."""

    _slot_names = ["moment1", "moment2"]
    # the trust ratio is a per-PARAM norm ratio — a flat stripe spans
    # many params, so no elementwise rule can express it
    _flat_rule_supported = False

    def __init__(self, learning_rate=0.001, lamb_weight_decay=0.01,
                 beta1=0.9, beta2=0.999, epsilon=1e-6, parameters=None,
                 grad_clip=None, exclude_from_weight_decay_fn=None,
                 name=None):
        super().__init__(learning_rate, parameters, None, grad_clip, name)
        self._wd = lamb_weight_decay
        self._beta1, self._beta2, self._eps = beta1, beta2, epsilon
        self._exclude_fn = exclude_from_weight_decay_fn

    def _rule(self, p, g, slots, lr, step):
        gf = g.astype(jnp.float32)
        pf = p.astype(jnp.float32)
        m = self._beta1 * slots["moment1"] + (1 - self._beta1) * gf
        v = self._beta2 * slots["moment2"] + (1 - self._beta2) * gf * gf
        stepf = jnp.asarray(step, jnp.float32)
        mhat = m / (1 - self._beta1 ** stepf)
        vhat = v / (1 - self._beta2 ** stepf)
        wd = self._wd
        # reference API: the callback receives the Parameter (lamb.py) —
        # a name-only shim stands in under the functional/jit path
        if self._exclude_fn is not None and \
                self._current_param is not None and \
                self._exclude_fn(self._current_param):
            wd = 0.0
        r = mhat / (jnp.sqrt(vhat) + self._eps) + wd * pf
        w_norm = jnp.linalg.norm(pf)
        r_norm = jnp.linalg.norm(r)
        trust = jnp.where((w_norm > 0) & (r_norm > 0), w_norm / r_norm, 1.0)
        new_p = pf - lr * trust * r
        return new_p.astype(p.dtype), {"moment1": m, "moment2": v}
