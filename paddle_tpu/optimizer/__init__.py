"""``paddle.optimizer`` — optimizers and LR schedulers.

Analog of the reference's ``python/paddle/optimizer/``.
"""
from . import lr  # noqa: F401
from .optimizer import (  # noqa: F401
    Adadelta, Adagrad, Adam, Adamax, AdamW, L1Decay, L2Decay, Lamb,
    Momentum, Optimizer, RMSProp, SGD,
)
