"""``paddle.fft`` — discrete Fourier transforms.

Reference: python/paddle/fft.py (fft/ifft/rfft/... over pocketfft/cuFFT
kernels). TPU-native: XLA lowers FFTs natively on every backend.
"""
from __future__ import annotations

import jax.numpy as jnp

from .framework.tensor import Tensor

__all__ = ["fft", "ifft", "rfft", "irfft", "hfft", "ihfft",
           "hfft2", "ihfft2", "hfftn", "ihfftn",
           "fft2", "ifft2", "rfft2", "irfft2",
           "fftn", "ifftn", "rfftn", "irfftn",
           "fftfreq", "rfftfreq", "fftshift", "ifftshift"]


def _arr(x):
    return x._data if isinstance(x, Tensor) else jnp.asarray(x)


def _wrap1(jnp_fn):
    def fn(x, n=None, axis=-1, norm="backward", name=None):
        return Tensor(jnp_fn(_arr(x), n=n, axis=axis, norm=norm))
    fn.__name__ = jnp_fn.__name__
    return fn


def _wrap2(jnp_fn):
    def fn(x, s=None, axes=(-2, -1), norm="backward", name=None):
        return Tensor(jnp_fn(_arr(x), s=s, axes=axes, norm=norm))
    fn.__name__ = jnp_fn.__name__
    return fn


def _wrapn(jnp_fn):
    def fn(x, s=None, axes=None, norm="backward", name=None):
        return Tensor(jnp_fn(_arr(x), s=s, axes=axes, norm=norm))
    fn.__name__ = jnp_fn.__name__
    return fn


fft = _wrap1(jnp.fft.fft)
ifft = _wrap1(jnp.fft.ifft)
rfft = _wrap1(jnp.fft.rfft)
irfft = _wrap1(jnp.fft.irfft)
hfft = _wrap1(jnp.fft.hfft)
ihfft = _wrap1(jnp.fft.ihfft)
fft2 = _wrap2(jnp.fft.fft2)
ifft2 = _wrap2(jnp.fft.ifft2)
rfft2 = _wrap2(jnp.fft.rfft2)
irfft2 = _wrap2(jnp.fft.irfft2)
def _hfftn_impl(a, s=None, axes=None, norm="backward"):
    """hfftn per scipy semantics (the reference follows scipy.fft):
    FFT over all axes but the last, then a Hermitian FFT (real output)
    over the last axis. With ``s`` given and axes omitted, the LAST
    len(s) axes transform (scipy's alignment rule)."""
    if axes is None:
        axes = tuple(range(a.ndim)) if s is None else \
            tuple(range(a.ndim - len(s), a.ndim))
    axes = tuple(axes)
    head, last = axes[:-1], axes[-1]
    if head:
        a = jnp.fft.fftn(a, s=None if s is None else s[:-1], axes=head,
                         norm=norm)
    n_last = None if s is None else s[-1]
    return jnp.fft.hfft(a, n=n_last, axis=last, norm=norm)


def _ihfftn_impl(a, s=None, axes=None, norm="backward"):
    if axes is None:
        axes = tuple(range(a.ndim)) if s is None else \
            tuple(range(a.ndim - len(s), a.ndim))
    axes = tuple(axes)
    head, last = axes[:-1], axes[-1]
    n_last = None if s is None else s[-1]
    a = jnp.fft.ihfft(a, n=n_last, axis=last, norm=norm)
    if head:
        a = jnp.fft.ifftn(a, s=None if s is None else s[:-1], axes=head,
                          norm=norm)
    return a


hfftn = _wrapn(_hfftn_impl)
ihfftn = _wrapn(_ihfftn_impl)


def _fix2(fn):
    def two_d(a, s=None, axes=(-2, -1), norm="backward"):
        return fn(a, s=s, axes=axes, norm=norm)
    return two_d


hfft2 = _wrapn(_fix2(_hfftn_impl))
ihfft2 = _wrapn(_fix2(_ihfftn_impl))
fftn = _wrapn(jnp.fft.fftn)
ifftn = _wrapn(jnp.fft.ifftn)
rfftn = _wrapn(jnp.fft.rfftn)
irfftn = _wrapn(jnp.fft.irfftn)


def fftfreq(n, d=1.0, dtype=None, name=None):
    return Tensor(jnp.fft.fftfreq(n, d=d))


def rfftfreq(n, d=1.0, dtype=None, name=None):
    return Tensor(jnp.fft.rfftfreq(n, d=d))


def fftshift(x, axes=None, name=None):
    return Tensor(jnp.fft.fftshift(_arr(x), axes=axes))


def ifftshift(x, axes=None, name=None):
    return Tensor(jnp.fft.ifftshift(_arr(x), axes=axes))
