"""``paddle.fft`` — discrete Fourier transforms.

Reference: python/paddle/fft.py (fft/ifft/rfft/... over pocketfft/cuFFT
kernels). TPU-native: XLA lowers FFTs natively on every backend.
"""
from __future__ import annotations

import jax.numpy as jnp

from .framework.tensor import Tensor

__all__ = ["fft", "ifft", "rfft", "irfft", "hfft", "ihfft",
           "fft2", "ifft2", "rfft2", "irfft2",
           "fftn", "ifftn", "rfftn", "irfftn",
           "fftfreq", "rfftfreq", "fftshift", "ifftshift"]


def _arr(x):
    return x._data if isinstance(x, Tensor) else jnp.asarray(x)


def _wrap1(jnp_fn):
    def fn(x, n=None, axis=-1, norm="backward", name=None):
        return Tensor(jnp_fn(_arr(x), n=n, axis=axis, norm=norm))
    fn.__name__ = jnp_fn.__name__
    return fn


def _wrap2(jnp_fn):
    def fn(x, s=None, axes=(-2, -1), norm="backward", name=None):
        return Tensor(jnp_fn(_arr(x), s=s, axes=axes, norm=norm))
    fn.__name__ = jnp_fn.__name__
    return fn


def _wrapn(jnp_fn):
    def fn(x, s=None, axes=None, norm="backward", name=None):
        return Tensor(jnp_fn(_arr(x), s=s, axes=axes, norm=norm))
    fn.__name__ = jnp_fn.__name__
    return fn


fft = _wrap1(jnp.fft.fft)
ifft = _wrap1(jnp.fft.ifft)
rfft = _wrap1(jnp.fft.rfft)
irfft = _wrap1(jnp.fft.irfft)
hfft = _wrap1(jnp.fft.hfft)
ihfft = _wrap1(jnp.fft.ihfft)
fft2 = _wrap2(jnp.fft.fft2)
ifft2 = _wrap2(jnp.fft.ifft2)
rfft2 = _wrap2(jnp.fft.rfft2)
irfft2 = _wrap2(jnp.fft.irfft2)
fftn = _wrapn(jnp.fft.fftn)
ifftn = _wrapn(jnp.fft.ifftn)
rfftn = _wrapn(jnp.fft.rfftn)
irfftn = _wrapn(jnp.fft.irfftn)


def fftfreq(n, d=1.0, dtype=None, name=None):
    return Tensor(jnp.fft.fftfreq(n, d=d))


def rfftfreq(n, d=1.0, dtype=None, name=None):
    return Tensor(jnp.fft.rfftfreq(n, d=d))


def fftshift(x, axes=None, name=None):
    return Tensor(jnp.fft.fftshift(_arr(x), axes=axes))


def ifftshift(x, axes=None, name=None):
    return Tensor(jnp.fft.ifftshift(_arr(x), axes=axes))
