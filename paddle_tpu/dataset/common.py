"""Shared dataset plumbing (reference: python/paddle/dataset/common.py)."""
from __future__ import annotations

import hashlib
import os

__all__ = ["DATA_HOME", "md5file", "download", "cluster_files_reader"]

DATA_HOME = os.path.expanduser(
    os.environ.get("PADDLE_DATA_HOME", "~/.cache/paddle/dataset"))


def md5file(fname):
    h = hashlib.md5()
    with open(fname, "rb") as f:
        for chunk in iter(lambda: f.read(4096), b""):
            h.update(chunk)
    return h.hexdigest()


def download(url, module_name, md5sum, save_name=None):
    """No network egress in this environment: resolve against DATA_HOME and
    fail loudly with placement instructions instead of fetching."""
    dirname = os.path.join(DATA_HOME, module_name)
    filename = os.path.join(
        dirname, save_name if save_name else url.split("/")[-1])
    if os.path.exists(filename):
        if not md5sum or md5file(filename) == md5sum:
            return filename
        raise RuntimeError(f"{filename} exists but fails md5 check")
    raise RuntimeError(
        f"cannot download {url} (no network egress); place the file at "
        f"{filename}")


def cluster_files_reader(files_pattern, trainer_count, trainer_id,
                         loader=None):
    import glob

    def reader():
        flist = sorted(glob.glob(files_pattern))
        my = flist[trainer_id::trainer_count]
        for fn in my:
            if loader is None:
                with open(fn, "rb") as f:
                    yield f.read()
            else:
                yield from loader(fn)

    return reader
