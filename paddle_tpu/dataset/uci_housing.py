"""paddle.dataset.uci_housing (reference:
python/paddle/dataset/uci_housing.py) — 13-feature Boston housing
regression; the canonical fit_a_line smoke dataset."""
from __future__ import annotations

import os

import numpy as np

from . import common

__all__ = ["train", "test", "feature_names"]

feature_names = ["CRIM", "ZN", "INDUS", "CHAS", "NOX", "RM", "AGE", "DIS",
                 "RAD", "TAX", "PTRATIO", "B", "LSTAT"]

_TRAIN_RATIO = 0.8


def _load():
    path = os.path.join(common.DATA_HOME, "uci_housing", "housing.data")
    if not os.path.exists(path):
        raise RuntimeError(
            f"place the UCI housing data at {path} (no network egress)")
    data = np.loadtxt(path)
    feats = data[:, :-1]
    # per-feature max/min normalization against train stats (reference)
    n_train = int(len(data) * _TRAIN_RATIO)
    mx = feats[:n_train].max(axis=0)
    mn = feats[:n_train].min(axis=0)
    avg = feats[:n_train].mean(axis=0)
    feats = (feats - avg) / np.maximum(mx - mn, 1e-6)
    return np.concatenate([feats, data[:, -1:]], axis=1), n_train


def train():
    def reader():
        data, n_train = _load()
        for row in data[:n_train]:
            yield row[:-1].astype(np.float32), row[-1:].astype(np.float32)

    return reader


def test():
    def reader():
        data, n_train = _load()
        for row in data[n_train:]:
            yield row[:-1].astype(np.float32), row[-1:].astype(np.float32)

    return reader
