"""paddle.dataset.flowers (reference: python/paddle/dataset/flowers.py) —
Oxford-102 readers over local tarballs."""
from __future__ import annotations

import os

from . import common

__all__ = ["train", "test", "valid"]


def _reader(mode):
    def reader():
        base = os.path.join(common.DATA_HOME, "flowers")
        img = os.path.join(base, "102flowers.tgz")
        lab = os.path.join(base, "imagelabels.mat")
        setid = os.path.join(base, "setid.mat")
        for p in (img, lab, setid):
            if not os.path.exists(p):
                raise RuntimeError(
                    f"place {os.path.basename(p)} at {p} (no egress)")
        import scipy.io as sio
        import tarfile
        import numpy as np
        labels = sio.loadmat(lab)["labels"][0]
        ids = sio.loadmat(setid)
        key = {"train": "trnid", "test": "tstid", "valid": "valid"}[mode]
        wanted = set(int(i) for i in ids[key][0])
        from PIL import Image
        import io
        with tarfile.open(img) as tarf:
            for tf in tarf:
                if not tf.name.endswith(".jpg"):
                    continue
                idx = int(tf.name[-9:-4])
                if idx not in wanted:
                    continue
                data = tarf.extractfile(tf).read()
                arr = np.asarray(Image.open(io.BytesIO(data)), np.float32)
                yield arr.transpose(2, 0, 1) / 255.0, int(labels[idx - 1]) - 1

    return reader


def _wrap(base, mapper, cycle):
    def reader():
        while True:
            for sample in base():
                yield mapper(sample) if mapper is not None else sample
            if not cycle:
                return

    return reader


def train(mapper=None, buffered_size=1024, use_xmap=True, cycle=False):
    return _wrap(_reader("train"), mapper, cycle)


def test(mapper=None, buffered_size=1024, use_xmap=True, cycle=False):
    return _wrap(_reader("test"), mapper, cycle)


def valid(mapper=None, buffered_size=1024, use_xmap=True, cycle=False):
    return _wrap(_reader("valid"), mapper, cycle)
