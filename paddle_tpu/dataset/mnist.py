"""paddle.dataset.mnist (reference: python/paddle/dataset/mnist.py)."""
from __future__ import annotations

import os

import numpy as np

from . import common

__all__ = ["train", "test"]


def _reader_creator(image_path, label_path, buffer_size=100):
    from ..vision.datasets import MNIST

    def reader():
        ds = MNIST(image_path=image_path, label_path=label_path)
        for i in range(len(ds)):
            img, lab = ds[i]
            yield (np.asarray(img, np.float32).reshape(-1) / 127.5 - 1.0,
                   int(np.asarray(lab)))

    return reader


def _paths(split):
    base = os.path.join(common.DATA_HOME, "mnist")
    return (os.path.join(base, f"{split}-images-idx3-ubyte.gz"),
            os.path.join(base, f"{split}-labels-idx1-ubyte.gz"))


def train():
    """Reader over normalized [-1,1] flattened images, label int."""
    img, lab = _paths("train")
    return _reader_creator(img, lab)


def test():
    img, lab = _paths("t10k")
    return _reader_creator(img, lab)
