"""paddle.dataset.conll05 (reference: python/paddle/dataset/conll05.py) —
CoNLL-2005 semantic-role-labeling test-split readers.

Sample format (reference parity): 9 parallel sequences
(word, ctx_n2, ctx_n1, ctx_0, ctx_p1, ctx_p2, predicate, mark, label) —
the five ctx features are the predicate's +/-2 context window broadcast
over the sentence, ``mark`` flags the window positions.
"""
from __future__ import annotations

import gzip
import os
import tarfile

from . import common

__all__ = ["get_dict", "get_embedding", "test", "UNK_IDX"]

UNK_IDX = 0

_WORDDICT = "conll05st-release/test.wsj/words/test.wsj.words.gz"
_PROPS = "conll05st-release/test.wsj/props/test.wsj.props.gz"


def _tar_path():
    return os.path.join(common.DATA_HOME, "conll05st",
                        "conll05st-tests.tar.gz")


def _aux_path(name):
    return os.path.join(common.DATA_HOME, "conll05st", name)


def _open_tar():
    path = _tar_path()
    if not os.path.exists(path):
        raise RuntimeError(
            f"place conll05st-tests.tar.gz at {path} (no network egress)")
    return tarfile.open(path)


def _load_dict_file(path):
    if not os.path.exists(path):
        raise RuntimeError(
            f"place the conll05 dict file at {path} (no network egress)")
    out = {}
    opener = gzip.open if path.endswith(".gz") else open
    with opener(path, "rt") as f:
        for i, line in enumerate(f):
            out[line.strip()] = i
    return out


def load_label_dict(filename):
    """Expand the B-/I- tag inventory from the label list file."""
    out = {}
    idx = 0
    with open(filename) as f:
        for line in f:
            tag = line.strip()
            if tag.startswith("B-"):
                out[tag] = idx
                out["I-" + tag[2:]] = idx + 1
                idx += 2
            elif tag == "O":
                out[tag] = idx
                idx += 1
    return out


def load_dict(filename):
    return _load_dict_file(filename)


def _sentences():
    """Yield (words, per-predicate prop columns) per sentence."""
    with _open_tar() as tar:
        wf = gzip.GzipFile(fileobj=tar.extractfile(_WORDDICT))
        pf = gzip.GzipFile(fileobj=tar.extractfile(_PROPS))
        words, rows = [], []
        for wline, pline in zip(wf, pf):
            word = wline.decode().strip()
            cols = pline.decode().strip().split()
            if not cols:  # blank line = sentence boundary
                if words:
                    yield words, rows
                words, rows = [], []
            else:
                words.append(word)
                rows.append(cols)
        if words:
            yield words, rows


def _spans_to_bio(col):
    """One props column ('(A0*', '*', '*)', '(V*)', …) -> BIO tags."""
    tags = []
    cur, inside = "O", False
    for cell in col:
        if cell == "*":
            tags.append("I-" + cur if inside else "O")
        elif cell == "*)":
            tags.append("I-" + cur)
            inside = False
        elif "(" in cell and ")" in cell:
            cur = cell[1:cell.index("*")]
            tags.append("B-" + cur)
            inside = False
        elif "(" in cell:
            cur = cell[1:cell.index("*")]
            tags.append("B-" + cur)
            inside = True
        else:
            raise RuntimeError(f"unexpected props cell {cell!r}")
    return tags


def corpus_reader(data_path=None, words_name=None, props_name=None):
    """Yield (sentence_words, predicate, bio_labels) per predicate."""

    def reader():
        for words, rows in _sentences():
            n_preds = len(rows[0]) - 1
            verbs = [r[0] for r in rows if r[0] != "-"]
            for k in range(n_preds):
                col = [r[k + 1] for r in rows]
                yield words, verbs[k], _spans_to_bio(col)

    return reader


def reader_creator(corpus_rdr, word_dict=None, predicate_dict=None,
                   label_dict=None):
    def reader():
        for sentence, predicate, labels in corpus_rdr():
            n = len(sentence)
            v = labels.index("B-V")
            mark = [0] * n

            def ctx(offset, fallback):
                i = v + offset
                if 0 <= i < n:
                    mark[i] = 1
                    return sentence[i]
                return fallback

            ctx_n2 = ctx(-2, "bos")
            ctx_n1 = ctx(-1, "bos")
            ctx_0 = ctx(0, "bos")
            ctx_p1 = ctx(1, "eos")
            ctx_p2 = ctx(2, "eos")

            word_idx = [word_dict.get(w, UNK_IDX) for w in sentence]
            broadcast = [
                [word_dict.get(c, UNK_IDX)] * n
                for c in (ctx_n2, ctx_n1, ctx_0, ctx_p1, ctx_p2)]
            pred_idx = [predicate_dict[predicate]] * n
            label_idx = [label_dict[t] for t in labels]
            yield (word_idx, *broadcast, pred_idx, mark, label_idx)

    return reader


def get_dict():
    """(word_dict, verb_dict, label_dict) from the companion dict files
    placed next to the test tarball."""
    word_dict = _load_dict_file(_aux_path("wordDict.txt"))
    verb_dict = _load_dict_file(_aux_path("verbDict.txt"))
    label_dict = load_label_dict(_aux_path("targetDict.txt"))
    return word_dict, verb_dict, label_dict


def get_embedding():
    """Path of the pre-trained word-embedding file (reference returns the
    downloaded emb file)."""
    path = _aux_path("emb")
    if not os.path.exists(path):
        raise RuntimeError(
            f"place the conll05 embedding file at {path} "
            "(no network egress)")
    return path


def test():
    word_dict, verb_dict, label_dict = get_dict()
    return reader_creator(corpus_reader(), word_dict, verb_dict,
                          label_dict)
