"""paddle.dataset.cifar (reference: python/paddle/dataset/cifar.py)."""
from __future__ import annotations

import os

import numpy as np

from . import common

__all__ = ["train10", "test10", "train100", "test100"]


def _reader(cls_name, data_file, mode, cycle=False):
    from ..vision import datasets as V

    def reader():
        ds = getattr(V, cls_name)(data_file=data_file, mode=mode)
        while True:
            for i in range(len(ds)):
                img, lab = ds[i]
                yield (np.asarray(img, np.float32).reshape(-1) / 255.0,
                       int(np.asarray(lab)))
            if not cycle:
                return

    return reader


def train10(cycle=False):
    path = os.path.join(common.DATA_HOME, "cifar",
                        "cifar-10-python.tar.gz")
    return _reader("Cifar10", path, "train", cycle)


def test10(cycle=False):
    path = os.path.join(common.DATA_HOME, "cifar",
                        "cifar-10-python.tar.gz")
    return _reader("Cifar10", path, "test", cycle)


def train100():
    path = os.path.join(common.DATA_HOME, "cifar",
                        "cifar-100-python.tar.gz")
    return _reader("Cifar100", path, "train")


def test100():
    path = os.path.join(common.DATA_HOME, "cifar",
                        "cifar-100-python.tar.gz")
    return _reader("Cifar100", path, "test")
