"""paddle.dataset.voc2012 (reference: python/paddle/dataset/voc2012.py) —
Pascal VOC2012 segmentation readers yielding (image, label) HWC arrays.
"""
from __future__ import annotations

import io
import os
import tarfile

import numpy as np

from . import common

__all__ = ["train", "test", "val"]

_SET_FILE = "VOCdevkit/VOC2012/ImageSets/Segmentation/{}.txt"
_DATA_FILE = "VOCdevkit/VOC2012/JPEGImages/{}.jpg"
_LABEL_FILE = "VOCdevkit/VOC2012/SegmentationClass/{}.png"


def _tar_path():
    return os.path.join(common.DATA_HOME, "voc2012",
                        "VOCtrainval_11-May-2012.tar")


def _reader_creator(sub_name):
    path = _tar_path()
    if not os.path.exists(path):
        raise RuntimeError(
            f"place the VOC2012 tarball at {path} (no network egress)")

    def reader():
        try:
            from PIL import Image
        except ImportError as e:  # pillow is optional in this image
            raise RuntimeError(
                "voc2012 readers need pillow to decode jpg/png") from e
        with tarfile.open(path) as tar:
            members = {m.name: m for m in tar.getmembers()}
            sets = tar.extractfile(members[_SET_FILE.format(sub_name)])
            for line in sets:
                stem = line.decode().strip()
                img = Image.open(io.BytesIO(tar.extractfile(
                    members[_DATA_FILE.format(stem)]).read()))
                lbl = Image.open(io.BytesIO(tar.extractfile(
                    members[_LABEL_FILE.format(stem)]).read()))
                yield np.array(img), np.array(lbl)

    return reader


def train():
    """2913 trainval images, HWC uint8."""
    return _reader_creator("trainval")


def test():
    """1464 train images (reference quirk: test() reads 'train')."""
    return _reader_creator("train")


def val():
    """1449 val images."""
    return _reader_creator("val")
