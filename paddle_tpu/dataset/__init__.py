"""``paddle.dataset`` — legacy reader-style dataset namespace.

Analog of the reference's python/paddle/dataset/ (mnist, cifar, imdb,
uci_housing, …): each module exposes ``train()``/``test()`` reader creators
yielding samples. This environment has no network egress, so loaders read
from ``common.DATA_HOME`` (or explicit paths) and raise a clear error when
the files are absent — same behavior as the reference on a download failure.
"""
from . import common  # noqa: F401
from . import mnist  # noqa: F401
from . import cifar  # noqa: F401
from . import uci_housing  # noqa: F401
from . import imdb  # noqa: F401
from . import imikolov  # noqa: F401
from . import movielens  # noqa: F401
from . import flowers  # noqa: F401
from . import wmt14  # noqa: F401
from . import wmt16  # noqa: F401
from . import conll05  # noqa: F401
from . import voc2012  # noqa: F401

__all__ = ["common", "mnist", "cifar", "uci_housing", "imdb", "imikolov",
           "movielens", "flowers", "wmt14", "wmt16", "conll05", "voc2012"]
