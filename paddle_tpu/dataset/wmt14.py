"""paddle.dataset.wmt14 (reference: python/paddle/dataset/wmt14.py) —
EN→FR translation readers over the preprocessed wmt14 tarball.

Sample format (reference parity): (src_ids, trg_ids, trg_ids_next) with
<s>/<e> wrapping on the source, <s>-prefixed target input and <e>-suffixed
target output; training pairs longer than 80 tokens are dropped.
"""
from __future__ import annotations

import os
import tarfile

from . import common

__all__ = ["train", "test", "get_dict", "START", "END", "UNK", "UNK_IDX"]

START = "<s>"
END = "<e>"
UNK = "<unk>"
UNK_IDX = 2

_MAX_LEN = 80


def _tar_path():
    return os.path.join(common.DATA_HOME, "wmt14", "wmt14.tgz")


def _open_tar():
    path = _tar_path()
    if not os.path.exists(path):
        raise RuntimeError(
            f"place the preprocessed wmt14 tarball at {path} "
            "(no network egress)")
    return tarfile.open(path)


def _dict_from_member(tar, suffix, dict_size):
    names = [m.name for m in tar if m.name.endswith(suffix)]
    assert len(names) == 1, f"expected one {suffix} in the archive"
    out = {}
    for i, line in enumerate(tar.extractfile(names[0])):
        if i >= dict_size:
            break
        out[line.decode().strip()] = i
    return out


def _load_dicts(dict_size):
    with _open_tar() as tar:
        return (_dict_from_member(tar, "src.dict", dict_size),
                _dict_from_member(tar, "trg.dict", dict_size))


def _reader_creator(file_suffix, dict_size):
    def reader():
        src_dict, trg_dict = _load_dicts(dict_size)
        with _open_tar() as tar:
            names = [m.name for m in tar if m.name.endswith(file_suffix)]
            for name in names:
                for raw in tar.extractfile(name):
                    cols = raw.decode().strip().split("\t")
                    if len(cols) != 2:
                        continue
                    src_ids = [src_dict.get(w, UNK_IDX)
                               for w in [START] + cols[0].split() + [END]]
                    trg = [trg_dict.get(w, UNK_IDX)
                           for w in cols[1].split()]
                    if len(src_ids) > _MAX_LEN or len(trg) > _MAX_LEN:
                        continue
                    yield (src_ids, [trg_dict[START]] + trg,
                           trg + [trg_dict[END]])

    return reader


def train(dict_size):
    return _reader_creator("train/train", dict_size)


def test(dict_size):
    return _reader_creator("test/test", dict_size)


def gen(dict_size):
    return _reader_creator("gen/gen", dict_size)


def get_dict(dict_size, reverse=True):
    """Returns (src, trg) dicts; ``reverse`` gives idx->word maps."""
    src_dict, trg_dict = _load_dicts(dict_size)
    if reverse:
        src_dict = {v: k for k, v in src_dict.items()}
        trg_dict = {v: k for k, v in trg_dict.items()}
    return src_dict, trg_dict
