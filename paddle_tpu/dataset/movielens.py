"""paddle.dataset.movielens (reference:
python/paddle/dataset/movielens.py) — ML-1M ratings readers over a local
zip."""
from __future__ import annotations

import os
import re
import zipfile

from . import common

__all__ = ["train", "test", "get_movie_title_dict", "max_movie_id",
           "max_user_id", "age_table", "max_job_id", "movie_categories",
           "user_info", "movie_info", "MovieInfo", "UserInfo"]

age_table = [1, 18, 25, 35, 45, 50, 56]


class MovieInfo:
    def __init__(self, index, categories, title):
        self.index = int(index)
        self.categories = categories
        self.title = title

    def value(self):
        return [self.index, [CATEGORIES_DICT[c] for c in self.categories],
                [TITLE_DICT[w.lower()] for w in self.title.split()]]


class UserInfo:
    def __init__(self, index, gender, age, job_id):
        self.index = int(index)
        self.is_male = gender == "M"
        self.age = age_table.index(int(age))
        self.job_id = int(job_id)

    def value(self):
        return [self.index, 0 if self.is_male else 1, self.age, self.job_id]


MOVIE_INFO = None
MOVIE_TITLE_DICT = None
CATEGORIES_DICT = None
TITLE_DICT = None
USER_INFO = None
RATINGS = None


def _zip_path():
    return os.path.join(common.DATA_HOME, "movielens", "ml-1m.zip")


def _load():
    global MOVIE_INFO, CATEGORIES_DICT, TITLE_DICT, USER_INFO, RATINGS
    if MOVIE_INFO is not None:
        return
    path = _zip_path()
    if not os.path.exists(path):
        raise RuntimeError(
            f"place ml-1m.zip at {path} (no network egress)")
    pattern = re.compile(r"^(.*)\((\d+)\)$")
    MOVIE_INFO = {}
    categories = set()
    titles = set()
    with zipfile.ZipFile(path) as z:
        with z.open("ml-1m/movies.dat") as f:
            for line in f:
                mid, title, cats = line.decode("latin-1").strip().split("::")
                cat_list = cats.split("|")
                categories.update(cat_list)
                m = pattern.match(title)
                title_clean = m.group(1).strip() if m else title
                titles.update(w.lower() for w in title_clean.split())
                MOVIE_INFO[int(mid)] = MovieInfo(mid, cat_list, title_clean)
        CATEGORIES_DICT = {c: i for i, c in enumerate(sorted(categories))}
        TITLE_DICT = {w: i for i, w in enumerate(sorted(titles))}
        USER_INFO = {}
        with z.open("ml-1m/users.dat") as f:
            for line in f:
                uid, gender, age, job, _ = \
                    line.decode("latin-1").strip().split("::")
                USER_INFO[int(uid)] = UserInfo(uid, gender, age, job)
        RATINGS = []
        with z.open("ml-1m/ratings.dat") as f:
            for line in f:
                uid, mid, rating, _ = \
                    line.decode("latin-1").strip().split("::")
                RATINGS.append((int(uid), int(mid), float(rating)))


def _reader(is_test, test_ratio=0.1):
    def reader():
        _load()
        for i, (uid, mid, rating) in enumerate(RATINGS):
            in_test = (i % int(1 / test_ratio)) == 0
            if in_test != is_test:
                continue
            usr = USER_INFO[uid]
            mov = MOVIE_INFO[mid]
            yield usr.value() + mov.value() + [[rating]]

    return reader


def train():
    return _reader(False)


def test():
    return _reader(True)


def get_movie_title_dict():
    _load()
    return TITLE_DICT


def max_movie_id():
    _load()
    return max(MOVIE_INFO)


def max_user_id():
    _load()
    return max(USER_INFO)


def max_job_id():
    _load()
    return max(u.job_id for u in USER_INFO.values())


def movie_categories():
    _load()
    return CATEGORIES_DICT


def user_info():
    _load()
    return USER_INFO


def movie_info():
    _load()
    return MOVIE_INFO
