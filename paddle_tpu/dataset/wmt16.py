"""paddle.dataset.wmt16 (reference: python/paddle/dataset/wmt16.py) —
EN↔DE ACL2016 multimodal translation readers with on-demand vocab builds.

Dictionaries are built from the training split on first use and cached at
``DATA_HOME/wmt16/{lang}_{size}.dict``; samples are
(src_ids, trg_ids, trg_ids_next) with shared <s>/<e>/<unk> index layout.
"""
from __future__ import annotations

import os
import tarfile
from collections import defaultdict

from . import common

__all__ = ["train", "test", "validation", "get_dict", "fetch",
           "TOTAL_EN_WORDS", "TOTAL_DE_WORDS"]

START_MARK = "<s>"
END_MARK = "<e>"
UNK_MARK = "<unk>"

TOTAL_EN_WORDS = 11250
TOTAL_DE_WORDS = 19220


def _tar_path():
    return os.path.join(common.DATA_HOME, "wmt16", "wmt16.tar.gz")


def _open_tar():
    path = _tar_path()
    if not os.path.exists(path):
        raise RuntimeError(
            f"place the wmt16 tarball at {path} (no network egress)")
    return tarfile.open(path)


def _build_dict(dict_size, save_path, lang):
    freq = defaultdict(int)
    col = 0 if lang == "en" else 1
    with _open_tar() as tar:
        for raw in tar.extractfile("wmt16/train"):
            cols = raw.decode().strip().split("\t")
            if len(cols) != 2:
                continue
            for w in cols[col].split():
                freq[w] += 1
    with open(save_path, "w") as f:
        f.write(f"{START_MARK}\n{END_MARK}\n{UNK_MARK}\n")
        for i, (word, _) in enumerate(
                sorted(freq.items(), key=lambda kv: kv[1], reverse=True)):
            if i + 3 == dict_size:
                break
            f.write(word + "\n")


def _load_dict(dict_size, lang, reverse=False):
    path = os.path.join(common.DATA_HOME, "wmt16",
                        f"{lang}_{dict_size}.dict")
    if not os.path.exists(path) or \
            sum(1 for _ in open(path, "rb")) != dict_size:
        os.makedirs(os.path.dirname(path), exist_ok=True)
        _build_dict(dict_size, path, lang)
    out = {}
    with open(path) as f:
        for i, line in enumerate(f):
            if reverse:
                out[i] = line.strip()
            else:
                out[line.strip()] = i
    return out


def _clip_sizes(src_dict_size, trg_dict_size, src_lang):
    src_total = TOTAL_EN_WORDS if src_lang == "en" else TOTAL_DE_WORDS
    trg_total = TOTAL_DE_WORDS if src_lang == "en" else TOTAL_EN_WORDS
    return min(src_dict_size, src_total), min(trg_dict_size, trg_total)


def _reader_creator(file_name, src_dict_size, trg_dict_size, src_lang):
    if src_lang not in ("en", "de"):
        raise ValueError("src_lang must be 'en' or 'de'")
    src_dict_size, trg_dict_size = _clip_sizes(
        src_dict_size, trg_dict_size, src_lang)

    def reader():
        src_dict = _load_dict(src_dict_size, src_lang)
        trg_dict = _load_dict(trg_dict_size,
                              "de" if src_lang == "en" else "en")
        start_id, end_id, unk_id = (src_dict[START_MARK],
                                    src_dict[END_MARK],
                                    src_dict[UNK_MARK])
        src_col = 0 if src_lang == "en" else 1
        with _open_tar() as tar:
            for raw in tar.extractfile(file_name):
                cols = raw.decode().strip().split("\t")
                if len(cols) != 2:
                    continue
                src_ids = ([start_id]
                           + [src_dict.get(w, unk_id)
                              for w in cols[src_col].split()]
                           + [end_id])
                trg = [trg_dict.get(w, unk_id)
                       for w in cols[1 - src_col].split()]
                yield src_ids, [start_id] + trg, trg + [end_id]

    return reader


def train(src_dict_size, trg_dict_size, src_lang="en"):
    return _reader_creator("wmt16/train", src_dict_size, trg_dict_size,
                           src_lang)


def test(src_dict_size, trg_dict_size, src_lang="en"):
    return _reader_creator("wmt16/test", src_dict_size, trg_dict_size,
                           src_lang)


def validation(src_dict_size, trg_dict_size, src_lang="en"):
    return _reader_creator("wmt16/val", src_dict_size, trg_dict_size,
                           src_lang)


def get_dict(lang, dict_size, reverse=False):
    total = TOTAL_EN_WORDS if lang == "en" else TOTAL_DE_WORDS
    return _load_dict(min(dict_size, total), lang, reverse)


def fetch():
    """Parity shim: verify the tarball is in place (the reference
    pre-downloads here)."""
    _open_tar().close()
