"""paddle.dataset.imikolov (reference: python/paddle/dataset/imikolov.py) —
PTB language-model n-gram readers over a local simple-examples tarball."""
from __future__ import annotations

import os
import tarfile

from . import common

__all__ = ["build_dict", "train", "test", "NGRAM", "SEQ"]

NGRAM = "ngram"
SEQ = "seq"


def _tar_path():
    return os.path.join(common.DATA_HOME, "imikolov",
                        "simple-examples.tgz")


def _lines(split):
    path = _tar_path()
    if not os.path.exists(path):
        raise RuntimeError(
            f"place simple-examples.tgz at {path} (no network egress)")
    name = f"./simple-examples/data/ptb.{split}.txt"
    with tarfile.open(path) as tarf:
        f = tarf.extractfile(name)
        for line in f:
            yield line.decode().strip().split()


def build_dict(min_word_freq=50):
    freq = {}
    for words in _lines("train"):
        for w in words:
            freq[w] = freq.get(w, 0) + 1
    freq.pop("<unk>", None)
    freq = {w: f for w, f in freq.items() if f > min_word_freq}
    items = sorted(freq.items(), key=lambda x: (-x[1], x[0]))
    word_idx = {w: i for i, (w, _) in enumerate(items)}
    word_idx["<unk>"] = len(word_idx)
    return word_idx


def _reader_creator(split, word_idx, n, data_type):
    def reader():
        unk = word_idx["<unk>"]
        for words in _lines(split):
            if data_type == NGRAM:
                assert n > -1, "Invalid gram length"
                toks = ["<s>"] + words + ["<e>"]
                ids = [word_idx.get(w, unk) for w in toks]
                for i in range(n, len(ids) + 1):
                    yield tuple(ids[i - n:i])
            else:
                ids = [word_idx.get(w, unk)
                       for w in ["<s>"] + words + ["<e>"]]
                yield ids[:-1], ids[1:]

    return reader


def train(word_idx, n, data_type=NGRAM):
    return _reader_creator("train", word_idx, n, data_type)


def test(word_idx, n, data_type=NGRAM):
    return _reader_creator("test", word_idx, n, data_type)
