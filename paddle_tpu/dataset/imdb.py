"""paddle.dataset.imdb (reference: python/paddle/dataset/imdb.py) —
tokenized IMDB sentiment readers over a local aclImdb tarball."""
from __future__ import annotations

import os
import re
import string
import tarfile

from . import common

__all__ = ["build_dict", "train", "test", "word_dict"]


def _tar_path():
    return os.path.join(common.DATA_HOME, "imdb", "aclImdb_v1.tar.gz")


def tokenize(pattern):
    path = _tar_path()
    if not os.path.exists(path):
        raise RuntimeError(
            f"place aclImdb_v1.tar.gz at {path} (no network egress)")
    with tarfile.open(path) as tarf:
        tf = tarf.next()
        while tf is not None:
            if bool(pattern.match(tf.name)):
                data = tarf.extractfile(tf).read().decode("latin-1")
                yield data.lower().translate(
                    str.maketrans("", "", string.punctuation)).split()
            tf = tarf.next()


def build_dict(pattern, cutoff):
    word_freq = {}
    for doc in tokenize(pattern):
        for w in doc:
            word_freq[w] = word_freq.get(w, 0) + 1
    word_freq = {w: f for w, f in word_freq.items() if f > cutoff}
    dictionary = sorted(word_freq.items(), key=lambda x: (-x[1], x[0]))
    words, _ = list(zip(*dictionary)) if dictionary else ((), ())
    word_idx = dict(zip(words, range(len(words))))
    word_idx["<unk>"] = len(words)
    return word_idx


def _reader_creator(re_pos, re_neg, word_idx):
    unk = word_idx["<unk>"]

    def reader():
        for doc in tokenize(re_pos):
            yield [word_idx.get(w, unk) for w in doc], 0
        for doc in tokenize(re_neg):
            yield [word_idx.get(w, unk) for w in doc], 1

    return reader


def train(word_idx):
    return _reader_creator(
        re.compile(r"aclImdb/train/pos/.*\.txt$"),
        re.compile(r"aclImdb/train/neg/.*\.txt$"), word_idx)


def test(word_idx):
    return _reader_creator(
        re.compile(r"aclImdb/test/pos/.*\.txt$"),
        re.compile(r"aclImdb/test/neg/.*\.txt$"), word_idx)


def word_dict(cutoff=150):
    return build_dict(re.compile(r"aclImdb/((train)|(test))/((pos)|(neg))/.*\.txt$"),
                      cutoff)
