"""``paddle.jit`` — to_static, save, load.

Reference analogs: ``@to_static`` + ProgramTranslator
(python/paddle/fluid/dygraph/jit.py:163, dygraph_to_static/), the C++ jit
Layer/serializer (paddle/fluid/jit/layer.h, serializer.cc) and
``save_inference_model`` round-trips (python/paddle/fluid/io.py).

TPU-native stance (SURVEY §7): the AST-rewriting translator collapses —
jax tracing IS the dy2static transform. ``to_static`` wraps a callable in a
jit-compiled bridge; ``jit.save`` exports the traced function as a
serialized StableHLO artifact (via jax.export) with parameters baked in,
plus a separate ``.pdiparams`` state-dict for weight interchange;
``jit.load`` deserializes into a TranslatedLayer-shaped predictor that runs
through PjRt with no Python model code.

Artifact layout for ``jit.save(layer, "/p/model")``:
  /p/model.pdmodel    — serialized jax.export artifact (StableHLO)
  /p/model.pdiparams  — pickled state_dict (framework.io.save)
  /p/model.meta.json  — input specs + framework version
"""
from __future__ import annotations

import json
import os
from typing import Any, List, Optional, Sequence

import jax
import numpy as np

from ..framework import io as _io
from ..framework.tensor import Tensor, no_grad_guard
from ..static import InputSpec

__all__ = ["to_static", "save", "load", "TranslatedLayer", "not_to_static",
           "TracedLayer", "set_code_level", "set_verbosity",
           "ProgramTranslator", "enable_to_static", "ignore_module"]

_FORMAT_VERSION = 1


def _leaf_is_tensor(x):
    return isinstance(x, Tensor)


def _unwrap_tree(out):
    return jax.tree_util.tree_map(
        lambda t: t._data if isinstance(t, Tensor) else t, out,
        is_leaf=_leaf_is_tensor)


def _wrap_tree(out):
    return jax.tree_util.tree_map(
        lambda a: Tensor(a, stop_gradient=True), out)


def _make_raw(fn, training=False):
    """arrays -> arrays bridge around a Tensor-level callable; parameters
    referenced by the callable become trace constants (inference export)."""

    def raw(*arrays):
        with no_grad_guard():
            ins = [Tensor(a, stop_gradient=True) for a in arrays]
            out = fn(*ins)
        return _unwrap_tree(out)

    return raw


class StaticFunction:
    """The ``@to_static`` wrapper: eager-looking call, jit-compiled body
    (reference: dygraph_to_static/program_translator.py StaticFunction).

    Layer-bound instances pass the parameter tree as TRACED INPUTS every
    call (no stale-weight baking after optimizer.step), and fall back to
    the eager tape whenever gradients are enabled on the params — so
    training through a to_static model stays correct, matching the
    reference's train-capable to_static."""

    def __init__(self, function, input_spec=None, layer=None):
        self._fn = function
        self._layer = layer
        self.input_spec = list(input_spec) if input_spec else None
        self._compiled = None
        self._conv = None
        self.__name__ = getattr(function, "__name__", "forward")

    def _converted_fn(self):
        """The dy2static-rewritten body: tensor-dependent if/while become
        static.nn.cond/while_loop (reference: the dygraph_to_static AST
        pipeline; here in dy2static.py)."""
        if self._conv is None:
            from .dy2static import convert_to_static
            self._conv = convert_to_static(self._fn)
        return self._conv

    def _get_compiled(self):
        if self._compiled is None:
            fn = self._converted_fn()
            if self._layer is not None:
                from ..nn.layer.layers import functional_state

                def raw(params, *arrays):
                    with no_grad_guard():
                        ins = [Tensor(a, stop_gradient=True)
                               for a in arrays]
                        # call the (converted) ORIGINAL forward — the
                        # layer's .forward is this StaticFunction now
                        with functional_state(self._layer, params, {}):
                            out = fn(*ins)
                    return _unwrap_tree(out)

                self._compiled = jax.jit(raw)
            else:
                self._compiled = jax.jit(_make_raw(fn))
        return self._compiled

    def _needs_eager(self):
        from ..framework.tensor import is_grad_enabled
        if not _translator_enabled():
            return True
        if self._layer is None:
            return False
        return is_grad_enabled() and any(
            not p.stop_gradient for p in self._layer.parameters())

    def __call__(self, *args):
        if self._needs_eager():
            return self._fn(*args)  # training: run on the tape
        arrays = [a._data if isinstance(a, Tensor) else np.asarray(a)
                  for a in args]
        try:
            if self._layer is not None:
                from ..nn.layer.layers import get_params_tree
                out = self._get_compiled()(get_params_tree(self._layer),
                                           *arrays)
            else:
                out = self._get_compiled()(*arrays)
        except jax.errors.TracerBoolConversionError as e:
            from .dy2static import explain_trace_error
            raise explain_trace_error(e, self._fn) from e
        return _wrap_tree(out)

    # reference-parity introspection hooks
    @property
    def concrete_program(self):
        return self._get_compiled()


def to_static(function=None, input_spec=None, build_strategy=None,
              backend=None, **kwargs):
    """Decorator/wrapper converting a dygraph callable into a compiled
    StaticFunction (reference fluid/dygraph/jit.py:163)."""

    def deco(fn):
        # Layer: compile its forward, keep the layer callable
        from ..nn.layer.layers import Layer
        if isinstance(fn, Layer):
            sf = StaticFunction(fn.forward, input_spec, layer=fn)
            fn.forward = sf
            return fn
        return StaticFunction(fn, input_spec)

    if function is not None:
        return deco(function)
    return deco


def not_to_static(fn):
    """Marker no-op (reference jit.not_to_static) — everything traces."""
    return fn


class ProgramTranslator:
    """Global to_static switch (reference:
    dygraph_to_static/program_translator.py ProgramTranslator). Singleton;
    ``enable(False)`` makes every StaticFunction run its original dygraph
    body."""

    _instance = None
    _enabled = True

    def __new__(cls):
        if cls._instance is None:
            cls._instance = super().__new__(cls)
        return cls._instance

    @classmethod
    def get_instance(cls):
        return cls()

    def enable(self, enable_to_static: bool):
        type(self)._enabled = bool(enable_to_static)

    @property
    def enable_to_static(self):
        return type(self)._enabled


def enable_to_static(enable: bool = True):
    """Reference: paddle.jit.enable_to_static."""
    ProgramTranslator().enable(enable)


def ignore_module(modules):
    """Reference parity no-op: modules are never AST-converted here —
    only the decorated function itself is rewritten."""
    return modules


def _translator_enabled():
    return ProgramTranslator._enabled


def _resolve_specs(input_spec, example_inputs=None):
    specs = []
    for s in (input_spec or []):
        if isinstance(s, InputSpec):
            specs.append(s)
        elif isinstance(s, Tensor):
            specs.append(InputSpec.from_tensor(s))
        else:
            a = np.asarray(s)
            specs.append(InputSpec(a.shape, str(a.dtype)))
    return specs


def save(layer, path, input_spec=None, **configs):
    """Export ``layer`` (or a StaticFunction / plain callable) for
    inference. Reference: jit.save -> TorchScript-like program+params
    (fluid/dygraph/jit.py, fluid/jit/serializer.cc)."""
    from ..nn.layer.layers import Layer

    from .dy2static import convert_to_static

    if isinstance(layer, Layer):
        was_training = layer.training
        layer.eval()
        fn = layer.forward
        fn = fn._converted_fn() if isinstance(fn, StaticFunction) \
            else convert_to_static(fn)
        if input_spec is None and isinstance(layer.forward, StaticFunction):
            input_spec = layer.forward.input_spec
        state = layer.state_dict()
    elif isinstance(layer, StaticFunction):
        was_training = None
        fn = layer._converted_fn()
        input_spec = input_spec or layer.input_spec
        state = {}
    else:
        was_training = None
        fn = convert_to_static(layer)
        state = {}
    try:
        if not input_spec:
            raise ValueError(
                "jit.save needs input_spec=[InputSpec(shape, dtype), ...] "
                "(or example Tensors) to trace the export")
        specs = _resolve_specs(input_spec)
        avals = _export_avals(specs)

        raw = _make_raw(fn)
        exported = None
        errors = []
        for platforms in (("cpu", "tpu"), None):
            try:
                e = jax.export.export(jax.jit(raw)) if platforms is None \
                    else jax.export.export(jax.jit(raw),
                                           platforms=platforms)
                exported = e(*avals)
                break
            except Exception as exc:  # multi-platform/symbolic unsupported
                errors.append(exc)
        if exported is None:
            # final fallback: static shapes (-1 -> 1), current platform
            exported = jax.export.export(jax.jit(raw))(
                *[s.to_aval() for s in specs])

        os.makedirs(os.path.dirname(os.path.abspath(path)) or ".",
                    exist_ok=True)
        with open(path + ".pdmodel", "wb") as f:
            f.write(exported.serialize())
        _io.save(state, path + ".pdiparams")
        meta = {
            "format_version": _FORMAT_VERSION,
            "platforms": list(exported.platforms),
            "input_specs": [{"shape": list(s.shape),
                             "dtype": np.dtype(s.dtype).name,
                             "name": s.name} for s in specs],
        }
        with open(path + ".meta.json", "w") as f:
            json.dump(meta, f)
    finally:
        if was_training:
            layer.train()
    return path


def _export_avals(specs):
    """ShapeDtypeStructs for export; -1/None dims become jax.export
    symbolic dims so the artifact accepts any size there (dynamic batch)."""
    avals = []
    for i, s in enumerate(specs):
        if any(d in (-1, None) for d in s.shape):
            names = ", ".join(
                f"d{i}_{j}" if d in (-1, None) else str(d)
                for j, d in enumerate(s.shape))
            shape = jax.export.symbolic_shape(names)
        else:
            shape = s.shape
        avals.append(jax.ShapeDtypeStruct(shape, s.dtype))
    return avals


class TranslatedLayer:
    """A loaded inference program (reference: TranslatedLayer of jit.load /
    the C++ jit::Layer). Callable on Tensors/arrays; no Python model code
    involved — execution is the deserialized StableHLO via PjRt."""

    def __init__(self, exported, state, meta):
        self._exported = exported
        self._state = state
        self._meta = meta
        self.training = False

    def __call__(self, *args):
        arrays = [a._data if isinstance(a, Tensor) else np.asarray(a)
                  for a in args]
        out = self._exported.call(*arrays)
        return _wrap_tree(out)

    forward = __call__

    def state_dict(self):
        return self._state

    def eval(self):
        self.training = False
        return self

    @property
    def input_specs(self) -> List[dict]:
        return self._meta.get("input_specs", [])

    @property
    def input_names(self) -> List[str]:
        return [s.get("name") or f"input_{i}"
                for i, s in enumerate(self.input_specs)]

    @property
    def platforms(self):
        return tuple(self._meta.get("platforms", ()))


def load(path, **configs) -> TranslatedLayer:
    """Load a ``jit.save`` artifact into a runnable predictor."""
    with open(path + ".pdmodel", "rb") as f:
        exported = jax.export.deserialize(f.read())
    state = {}
    if os.path.exists(path + ".pdiparams"):
        state = _io.load(path + ".pdiparams")
    meta = {}
    if os.path.exists(path + ".meta.json"):
        with open(path + ".meta.json") as f:
            meta = json.load(f)
    return TranslatedLayer(exported, state, meta)


# --------------------------------------------------------------------------
# dy2static logging knobs + legacy TracedLayer (reference jit/api.py)
# --------------------------------------------------------------------------

def set_verbosity(level=0, also_to_stdout=False):
    """Reference jit.set_verbosity: dy2static transform log level."""
    import logging
    logger = logging.getLogger("paddle_tpu.dy2static")
    logger.setLevel(max(logging.DEBUG,
                        logging.WARNING - 10 * int(level)))
    if also_to_stdout and not logger.handlers:
        import sys
        logger.addHandler(logging.StreamHandler(sys.stdout))


def set_code_level(level=100, also_to_stdout=False):
    """Reference jit.set_code_level: log the transformed code. Here the
    AST converter (jit/dy2static.py) logs its rewritten source at DEBUG;
    this lowers the logger to show it."""
    set_verbosity(3 if level else 0, also_to_stdout)


class TracedLayer:
    """Legacy trace API (reference fluid/dygraph/jit.py TracedLayer):
    ``TracedLayer.trace(layer, inputs)`` -> (outputs, traced); the traced
    object replays the jitted forward and exports via
    ``save_inference_model``. On this backend tracing IS jax tracing of
    one concrete call."""

    def __init__(self, layer, example_inputs):
        self._layer = layer
        self._examples = list(example_inputs)

    @classmethod
    def trace(cls, layer, inputs):
        traced = cls(layer, inputs)
        outputs = traced(*inputs)
        return outputs, traced

    def __call__(self, *inputs):
        return self._layer(*inputs)

    def save_inference_model(self, path, feed=None, fetch=None, **kwargs):
        from ..static import InputSpec
        if feed is not None or fetch is not None:
            import warnings
            warnings.warn(
                "TracedLayer.save_inference_model feed=/fetch= subsetting "
                "is not supported on this backend; exporting the FULL "
                "traced signature", UserWarning, stacklevel=2)
        specs = [InputSpec.from_tensor(t) for t in self._examples]
        was_training = self._layer.training
        self._layer.eval()
        try:
            return save(self._layer, path, input_spec=specs)
        finally:
            self._layer.train() if was_training else self._layer.eval()
