"""dy2static — AST conversion of data-dependent Python control flow.

Reference analogs: the dygraph_to_static transformer pipeline
(python/paddle/fluid/dygraph/dygraph_to_static/ifelse_transformer.py,
loop_transformer.py, program_translator.py — ~20 AST transformers feeding
a static Program).

TPU-native stance: jax tracing already converts everything EXCEPT Python
``if``/``while`` statements whose predicate is a traced tensor — those hit
``TracerBoolConversionError``. So this module rewrites exactly those two
statement forms into ``static.nn.cond`` / ``static.nn.while_loop`` calls
(which lower to ``lax.cond`` / ``lax.while_loop`` under a trace and run as
plain Python eagerly), bottom-up, and leaves every other construct to the
tracer. Predicates that are ordinary Python bools keep their exact eager
semantics through the same helpers.

Rewrite shape (names are illustrative)::

    if x.mean() > 0:            def __pd_d2s_true_0(y):
        y = x + 1                   y = x + 1        # x read via closure
    else:                           return (y,)
        y = x - 1       ==>     def __pd_d2s_false_0(y):
                                    y = x - 1
                                    return (y,)
                                (y,) = _jst.convert_ifelse(
                                    x.mean() > 0, __pd_d2s_true_0,
                                    __pd_d2s_false_0, (y,))

Variables assigned in either branch travel as explicit args/results (so
augmented assignment works and ``lax.cond`` sees a matched pytree);
everything merely *read* rides the closure. A ``try/except NameError``
guard seeds names that may be unbound before the statement with
``UNDEFINED`` so the canonical "defined in both branches, not before"
pattern works.

Unsupported-by-XLA shapes (early return in one branch only, break/continue
in a converted while) are left untransformed: with a Python-bool predicate
they run exactly as written; with a traced predicate the tracer raises and
``explain_trace_error`` turns it into a Dy2StaticError naming the line.
"""
from __future__ import annotations

import ast
import functools
import inspect
import textwrap
import types
from typing import Optional

__all__ = ["convert_to_static", "Dy2StaticError", "UNDEFINED",
           "convert_ifelse", "convert_while", "explain_trace_error"]

_PREFIX = "__pd_d2s_"
_JST = _PREFIX + "jst__"


class Dy2StaticError(Exception):
    """A control-flow construct could not be converted to static form."""


class _Undefined:
    _instance: Optional["_Undefined"] = None

    def __new__(cls):
        if cls._instance is None:
            cls._instance = super().__new__(cls)
        return cls._instance

    def __repr__(self):
        return "<dy2static UNDEFINED>"

    def __bool__(self):
        raise Dy2StaticError(
            "read of a variable that is not assigned on the taken branch "
            "of a converted if/while")


UNDEFINED = _Undefined()


def _is_tracer(x):
    import jax
    from ..framework.tensor import Tensor
    if isinstance(x, Tensor):
        x = x._data
    return isinstance(x, jax.core.Tracer)


def _tree_has_tracer(tree):
    import jax
    from ..framework.tensor import Tensor
    return any(
        _is_tracer(leaf) for leaf in jax.tree_util.tree_leaves(
            tree, is_leaf=lambda t: isinstance(t, Tensor)))


# ---------------------------------------------------------------------------
# runtime helpers (targets of the generated code)
# ---------------------------------------------------------------------------

def convert_ifelse(pred, true_fn, false_fn, args):
    """Branch on ``pred``: Python branch for concrete values,
    ``static.nn.cond`` (→ lax.cond) for traced ones."""
    from ..framework.tensor import Tensor
    p = pred._data if isinstance(pred, Tensor) else pred
    if _is_tracer(p):
        from ..static import nn as snn
        try:
            return tuple(snn.cond(pred, lambda: tuple(true_fn(*args)),
                                  lambda: tuple(false_fn(*args))))
        except (TypeError, ValueError) as e:
            raise Dy2StaticError(
                "both branches of a converted `if` must produce matching "
                "shapes/dtypes for every variable assigned in either "
                f"branch (a variable assigned in only one branch cannot "
                f"be traced): {e}") from e
    try:
        out = true_fn(*args) if bool(p) else false_fn(*args)
    except (NameError, UnboundLocalError) as e:
        raise Dy2StaticError(
            f"variable read in an if-branch before assignment: {e}") from e
    return tuple(out)


def convert_while(cond_fn, body_fn, init):
    """Loop: Python while for concrete predicates, lax.while_loop for
    traced ones (carried variables must keep shape/dtype)."""
    from ..framework.tensor import Tensor
    pred = cond_fn(*init)
    p = pred._data if isinstance(pred, Tensor) else pred
    if _is_tracer(p) or _tree_has_tracer(list(init)):
        bad = [i for i, v in enumerate(init) if v is UNDEFINED]
        if bad:
            raise Dy2StaticError(
                "a variable carried through a converted `while` must be "
                "initialised before the loop (loop var(s) at position(s) "
                f"{bad} are undefined)")
        from ..static import nn as snn
        out = snn.while_loop(cond_fn,
                             lambda *vs: tuple(body_fn(*vs)), list(init))
        return tuple(out)
    def truth(v):
        return bool(v._data if isinstance(v, Tensor) else v)

    vars_ = tuple(init)
    while truth(cond_fn(*vars_)):
        vars_ = tuple(body_fn(*vars_))
    return vars_


def explain_trace_error(exc, fn):
    """Wrap a jax TracerBoolConversionError raised while tracing ``fn``
    into a Dy2StaticError that names the offending construct."""
    name = getattr(fn, "__qualname__", getattr(fn, "__name__", "?"))
    return Dy2StaticError(
        f"to_static could not convert {name}: a Python `if`/`while`/loop "
        "depends on a traced tensor value in a form dy2static does not "
        "rewrite (early return from one branch only, or break/continue "
        "inside the loop). Restructure so both branches return, or use "
        "static.nn.cond / static.nn.while_loop directly. "
        f"Original error: {exc}")


# ---------------------------------------------------------------------------
# AST analysis
# ---------------------------------------------------------------------------

class _AssignedNames(ast.NodeVisitor):
    """Names bound by a statement list, NOT descending into nested
    function/class scopes or comprehension targets."""

    def __init__(self):
        self.names = set()

    def visit_FunctionDef(self, node):
        self.names.add(node.name)

    visit_AsyncFunctionDef = visit_FunctionDef
    visit_ClassDef = visit_FunctionDef

    def visit_Lambda(self, node):
        pass

    def visit_comprehension(self, node):
        self.visit(node.iter)
        for i in node.ifs:
            self.visit(i)

    def visit_Name(self, node):
        if isinstance(node.ctx, (ast.Store, ast.Del)):
            self.names.add(node.id)

    def visit_Import(self, node):
        for a in node.names:
            self.names.add((a.asname or a.name).split(".")[0])

    visit_ImportFrom = visit_Import


def _assigned(stmts):
    v = _AssignedNames()
    for s in stmts:
        v.visit(s)
    return {n for n in v.names if not n.startswith(_PREFIX)}


def _reads(expr):
    return {n.id for n in ast.walk(expr)
            if isinstance(n, ast.Name) and isinstance(n.ctx, ast.Load)}


class _EscapeFinder(ast.NodeVisitor):
    """Return/Break/Continue at this statement level (skipping nested
    scopes and nested loops' own break/continue)."""

    def __init__(self, skip_loop_ctl=False):
        self.returns = []
        self.breaks = []
        self._loop_depth = 1 if skip_loop_ctl else 0

    def visit_FunctionDef(self, node):
        pass

    visit_AsyncFunctionDef = visit_FunctionDef
    visit_ClassDef = visit_FunctionDef
    visit_Lambda = visit_FunctionDef

    def visit_Return(self, node):
        self.returns.append(node)

    def _loop(self, node):
        self._loop_depth += 1
        self.generic_visit(node)
        self._loop_depth -= 1

    def visit_While(self, node):
        self._loop(node)

    visit_For = visit_While

    def visit_Break(self, node):
        if self._loop_depth == 0:
            self.breaks.append(node)

    visit_Continue = visit_Break


def _escapes(stmts, skip_loop_ctl=False):
    f = _EscapeFinder(skip_loop_ctl)
    # for while-bodies the body IS the loop: break/continue bind to it
    for s in stmts:
        f.visit(s)
    return f


# ---------------------------------------------------------------------------
# the transformer
# ---------------------------------------------------------------------------

def _name(id_, ctx):
    return ast.Name(id=id_, ctx=ctx)


def _tuple(names, ctx):
    return ast.Tuple(elts=[_name(n, ctx) for n in names], ctx=ctx)


def _jst_attr(fn_name):
    return ast.Attribute(value=_name(_JST, ast.Load()), attr=fn_name,
                         ctx=ast.Load())


def _guard_stmt(varname):
    """try: v\nexcept (NameError, UnboundLocalError): v = UNDEFINED"""
    return ast.Try(
        body=[ast.Expr(value=_name(varname, ast.Load()))],
        handlers=[ast.ExceptHandler(
            type=ast.Tuple(
                elts=[_name("NameError", ast.Load()),
                      _name("UnboundLocalError", ast.Load())],
                ctx=ast.Load()),
            name=None,
            body=[ast.Assign(
                targets=[_name(varname, ast.Store())],
                value=_jst_attr("UNDEFINED"))])],
        orelse=[], finalbody=[])


def _def(fn_name, params, body):
    return ast.FunctionDef(
        name=fn_name,
        args=ast.arguments(
            posonlyargs=[], args=[ast.arg(arg=p) for p in params],
            vararg=None, kwonlyargs=[], kw_defaults=[], kwarg=None,
            defaults=[]),
        body=body, decorator_list=[], returns=None)


class _ControlFlowTransformer(ast.NodeTransformer):
    def __init__(self):
        self._counter = 0

    def _next(self):
        i = self._counter
        self._counter += 1
        return i

    # --- if ---------------------------------------------------------------
    def visit_If(self, node):
        self.generic_visit(node)
        body_esc = _escapes(node.body)
        else_esc = _escapes(node.orelse)
        if body_esc.breaks or else_esc.breaks:
            # break/continue bound to an enclosing loop can't move into a
            # nested function; leave as written (loop stays Python-eager)
            return node
        if body_esc.returns or else_esc.returns:
            return self._rewrite_if_returns(node, body_esc, else_esc)
        return self._rewrite_if_assigns(node)

    def _rewrite_if_assigns(self, node):
        names = sorted(_assigned(node.body) | _assigned(node.orelse))
        i = self._next()
        tname, fname = f"{_PREFIX}true_{i}", f"{_PREFIX}false_{i}"
        ret = ast.Return(value=_tuple(names, ast.Load()))
        tdef = _def(tname, names, list(node.body) + [ret])
        fdef = _def(fname, names,
                    list(node.orelse) + [ast.Return(
                        value=_tuple(names, ast.Load()))])
        call = ast.Call(
            func=_jst_attr("convert_ifelse"),
            args=[node.test, _name(tname, ast.Load()),
                  _name(fname, ast.Load()), _tuple(names, ast.Load())],
            keywords=[])
        if names:
            final = ast.Assign(targets=[_tuple(names, ast.Store())],
                               value=call)
        else:
            final = ast.Expr(value=call)
        # original branch statements keep their true locations; generated
        # nodes are filled in by fix_missing_locations at module level
        return [_guard_stmt(n) for n in names] + [tdef, fdef, final]

    def _rewrite_if_returns(self, node, body_esc, else_esc):
        """Only the tail-return-in-both-branches shape converts; anything
        else is left as written (fine for Python predicates; a traced
        predicate then raises via explain_trace_error)."""
        both_tail = (
            node.body and node.orelse
            and isinstance(node.body[-1], ast.Return)
            and isinstance(node.orelse[-1], ast.Return)
            and body_esc.returns == [node.body[-1]]
            and else_esc.returns == [node.orelse[-1]])
        if not both_tail:
            return node
        i = self._next()
        tname, fname = f"{_PREFIX}true_{i}", f"{_PREFIX}false_{i}"

        def mk(stmts, fn_name):
            last = stmts[-1]
            value = last.value if last.value is not None \
                else ast.Constant(value=None)
            body = list(stmts[:-1]) + [
                ast.Return(value=ast.Tuple(elts=[value], ctx=ast.Load()))]
            return _def(fn_name, [], body)

        tdef = mk(node.body, tname)
        fdef = mk(node.orelse, fname)
        call = ast.Call(
            func=_jst_attr("convert_ifelse"),
            args=[node.test, _name(tname, ast.Load()),
                  _name(fname, ast.Load()),
                  ast.Tuple(elts=[], ctx=ast.Load())],
            keywords=[])
        final = ast.Return(value=ast.Subscript(
            value=call, slice=ast.Constant(value=0), ctx=ast.Load()))
        return [tdef, fdef, final]

    # --- while ------------------------------------------------------------
    def visit_While(self, node):
        self.generic_visit(node)
        esc = _escapes(node.body, skip_loop_ctl=False)
        if esc.returns or esc.breaks or node.orelse:
            # break/continue/return/else: leave as written (Python-pred
            # loops still work; traced preds raise a clear error)
            return node
        # loop carries = names the body rebinds (anything only READ — in
        # the test or the body — stays constant and rides the closure;
        # globals/builtins in the test therefore never become carries)
        names = sorted(_assigned(node.body))
        i = self._next()
        cname, bname = f"{_PREFIX}while_cond_{i}", f"{_PREFIX}while_body_{i}"
        cdef = _def(cname, names, [ast.Return(value=node.test)])
        bdef = _def(bname, names,
                    list(node.body) + [ast.Return(
                        value=_tuple(names, ast.Load()))])
        call = ast.Call(
            func=_jst_attr("convert_while"),
            args=[_name(cname, ast.Load()), _name(bname, ast.Load()),
                  _tuple(names, ast.Load())],
            keywords=[])
        if names:
            final = ast.Assign(targets=[_tuple(names, ast.Store())],
                               value=call)
        else:
            final = ast.Expr(value=call)
        return [_guard_stmt(n) for n in names] + [cdef, bdef, final]


# ---------------------------------------------------------------------------
# function-level conversion
# ---------------------------------------------------------------------------

class _HasControlFlow(ast.NodeVisitor):
    def __init__(self):
        self.found = False
        self.has_global = False

    def visit_If(self, node):
        self.found = True
        self.generic_visit(node)

    visit_While = visit_If

    def visit_Global(self, node):
        self.has_global = True

    visit_Nonlocal = visit_Global


_CACHE: dict = {}


def convert_to_static(fn):
    """Return ``fn`` with tensor-dependent ``if``/``while`` rewritten to
    static.nn control flow. Bound methods stay bound; functions whose
    source is unavailable (C code, lambdas, REPL) or that contain no
    control flow are returned unchanged."""
    bound_self = getattr(fn, "__self__", None)
    func = fn.__func__ if bound_self is not None else fn
    if not isinstance(func, types.FunctionType):
        return fn
    cached = _CACHE.get(func)
    if cached is None:
        cached = _convert_function(func)
        _CACHE[func] = cached
    if cached is func:
        return fn
    if bound_self is not None:
        return types.MethodType(cached, bound_self)
    return cached


def _convert_function(func):
    try:
        src = textwrap.dedent(inspect.getsource(func))
        tree = ast.parse(src)
    except (OSError, TypeError, SyntaxError, IndentationError):
        return func
    if not tree.body or not isinstance(tree.body[0], ast.FunctionDef):
        return func
    fdef = tree.body[0]
    probe = _HasControlFlow()
    probe.visit(fdef)
    if not probe.found or probe.has_global:
        return func  # nothing to rewrite (or global/nonlocal: unsafe)

    fdef.decorator_list = []  # don't re-run @to_static/@wraps on exec
    _ControlFlowTransformer().visit(fdef)

    freevars = func.__code__.co_freevars
    module = ast.Module(body=[fdef], type_ignores=[])
    if freevars:
        factory_name = _PREFIX + "factory__"
        factory = _def(factory_name, list(freevars),
                       [fdef, ast.Return(value=_name(fdef.name,
                                                     ast.Load()))])
        ast.copy_location(factory, fdef)
        module = ast.Module(body=[factory], type_ignores=[])
    ast.fix_missing_locations(module)
    import logging
    logger = logging.getLogger("paddle_tpu.dy2static")
    if logger.isEnabledFor(logging.DEBUG):
        # jit.set_code_level/set_verbosity surface the rewritten source
        logger.debug("dy2static transformed %s:\n%s", func.__qualname__,
                     ast.unparse(fdef))
    try:
        lineno = func.__code__.co_firstlineno
        ast.increment_lineno(module, lineno - 1)
        code = compile(module, func.__code__.co_filename, "exec")
    except SyntaxError:
        return func

    from . import dy2static as _self
    namespace = dict(func.__globals__)
    namespace[_JST] = _self
    exec(code, namespace)
    if freevars:
        cells = [c.cell_contents for c in func.__closure__]
        new = namespace[_PREFIX + "factory__"](*cells)
    else:
        new = namespace[fdef.name]
    new.__defaults__ = func.__defaults__
    new.__kwdefaults__ = func.__kwdefaults__
    functools.update_wrapper(new, func)
    new.__wrapped__ = func
    return new
