"""ctypes bindings + on-demand build for the native tpu_dataio shared
memory ring (native/tpu_dataio.cc).

Reference analog: mmap_allocator.cc shared-memory tensors +
dataloader_iter.py's shared-memory batch queue. The .so is compiled with
the system g++ on first use and cached next to the source; everything
degrades gracefully (``available()`` is False) when no toolchain exists.
"""
from __future__ import annotations

import ctypes
import os
import pickle
import threading
from typing import Optional

__all__ = ["available", "ShmRing"]

_HERE = os.path.dirname(os.path.abspath(__file__))
_NATIVE_DIR = os.path.join(os.path.dirname(os.path.dirname(_HERE)),
                           "native")
_SRC = os.path.join(_NATIVE_DIR, "tpu_dataio.cc")

_lib = None
_lib_err: Optional[str] = None
_build_lock = threading.Lock()


def _load():
    global _lib, _lib_err
    if _lib is not None or _lib_err is not None:
        return _lib
    with _build_lock:
        if _lib is not None or _lib_err is not None:
            return _lib
        try:
            # one build pipeline for all native code: content-hash cache
            # dir works from read-only installs, unlike building next to
            # the source
            from ..utils import cpp_extension
            ext = cpp_extension.load(
                "tpu_dataio", [_SRC],
                extra_ldflags=["-lpthread", "-lrt"])
            lib = ext.__lib__
        except Exception as e:  # no toolchain / load failure: fall back
            _lib_err = f"{type(e).__name__}: {e}"
            return None
        lib.td_create.argtypes = [ctypes.c_char_p, ctypes.c_uint64,
                                  ctypes.c_uint64]
        lib.td_create.restype = ctypes.c_int
        lib.td_attach.argtypes = [ctypes.c_char_p]
        lib.td_attach.restype = ctypes.c_int
        lib.td_push.argtypes = [ctypes.c_int, ctypes.c_char_p,
                                ctypes.c_uint64, ctypes.c_long]
        lib.td_push.restype = ctypes.c_int
        lib.td_pop.argtypes = [ctypes.c_int, ctypes.c_char_p,
                               ctypes.c_uint64, ctypes.c_long]
        lib.td_pop.restype = ctypes.c_longlong
        lib.td_slot_bytes.argtypes = [ctypes.c_int]
        lib.td_slot_bytes.restype = ctypes.c_uint64
        lib.td_pending.argtypes = [ctypes.c_int]
        lib.td_pending.restype = ctypes.c_uint64
        lib.td_close.argtypes = [ctypes.c_int]
        lib.td_destroy.argtypes = [ctypes.c_char_p]
        _lib = lib
        return _lib


def available() -> bool:
    return _load() is not None


def build_error() -> Optional[str]:
    _load()
    return _lib_err


class ShmRing:
    """Fixed-slot shared-memory queue usable across fork/spawn processes.

    ``push_obj``/``pop_obj`` move pickled python objects (numpy batches)
    through the segment — one copy in, one copy out, no pipe.

    Threading contract: a ShmRing OBJECT belongs to one thread — pop
    reuses a single buffer, and ``close`` must not race in-flight
    push/pop on the same handle (the native layer guards the handle
    table, not readers mid-wait). Cross-PROCESS concurrency is the
    supported axis: any number of processes each holding their own
    attach()ed ring."""

    def __init__(self, name: str, slot_bytes: int = 8 << 20,
                 n_slots: int = 8, create: bool = True):
        lib = _load()
        if lib is None:
            raise RuntimeError(
                f"native tpu_dataio unavailable: {_lib_err}")
        self._lib = lib
        self.name = name.encode()
        if create:
            self._h = lib.td_create(self.name, slot_bytes, n_slots)
        else:
            self._h = lib.td_attach(self.name)
        if self._h < 0:
            raise OSError(-self._h, os.strerror(-self._h),
                          name)
        self._owner = create
        self.slot_bytes = int(lib.td_slot_bytes(self._h))
        # one reusable pop buffer per ring: a fresh slot-sized
        # (64 MB in the DataLoader) allocation per pop would churn the
        # allocator on the hot path. NOTE: pop is therefore not safe
        # from multiple threads of ONE process on the same ShmRing
        # object (processes each have their own).
        self._pop_buf = None

    def push(self, data: bytes, timeout_ms: int = 10000) -> None:
        rc = self._lib.td_push(self._h, data, len(data), timeout_ms)
        if rc == -91 or rc == -90:  # EMSGSIZE differs per libc
            raise ValueError(
                f"message of {len(data)} bytes exceeds slot capacity "
                f"{self.slot_bytes}")
        if rc != 0:
            raise TimeoutError(f"ring push failed: errno {-rc}")

    def pop(self, timeout_ms: int = 10000) -> bytes:
        if self._pop_buf is None:
            self._pop_buf = ctypes.create_string_buffer(self.slot_bytes)
        buf = self._pop_buf
        n = self._lib.td_pop(self._h, buf, self.slot_bytes, timeout_ms)
        if n < 0:
            raise TimeoutError(f"ring pop failed: errno {-n}")
        return buf.raw[:n]

    def push_obj(self, obj, timeout_ms: int = 10000) -> None:
        self.push(pickle.dumps(obj, protocol=pickle.HIGHEST_PROTOCOL),
                  timeout_ms)

    def pop_obj(self, timeout_ms: int = 10000):
        return pickle.loads(self.pop(timeout_ms))

    def pending(self) -> int:
        return int(self._lib.td_pending(self._h))

    def close(self):
        if self._h >= 0:
            self._lib.td_close(self._h)
            if self._owner:
                self._lib.td_destroy(self.name)
            self._h = -1

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.close()
        return False

    def __del__(self):
        try:
            self.close()
        except Exception:
            pass
