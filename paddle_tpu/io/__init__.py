"""``paddle.io`` — datasets, samplers, DataLoader.

Analog of the reference's ``python/paddle/io/`` + ``fluid/dataloader/``
(dataset.py, batch_sampler.py, dataloader_iter.py). The reference feeds GPUs
with forked worker processes writing mmap shared-memory tensors
(fluid/reader.py:275, dataloader_iter.py). TPU-native host loading favors a
thread pool: workers produce numpy batches (no CUDA context to protect, and
the GIL is released inside numpy/IO), and the iterator keeps a prefetch
queue ahead of the accelerator — double-buffering H2D against the jitted
step the way the reference overlaps its shared-memory queue.
"""
from __future__ import annotations

import itertools
import math
import os
import queue as _queue
import threading
import time as _time
from typing import Any, Iterable, List, Optional, Sequence

import numpy as np

from ..framework import random as _random
from ..framework.monitor import stat_add, stat_observe
from ..framework.tensor import Tensor
from ..profiler import span as _prof

__all__ = ["Dataset", "IterableDataset", "TensorDataset", "ComposeDataset",
           "ChainDataset", "Subset", "random_split", "Sampler",
           "SequenceSampler", "RandomSampler", "WeightedRandomSampler",
           "BatchSampler", "DistributedBatchSampler", "DataLoader",
           "get_worker_info", "default_collate_fn", "device_prefetch",
           "DeviceDataLoader", "BucketedBatchSampler",
           "pad_sequence_collate_fn"]


class Dataset:
    def __getitem__(self, idx):
        raise NotImplementedError

    def __len__(self):
        raise NotImplementedError


class IterableDataset(Dataset):
    def __iter__(self):
        raise NotImplementedError

    def __getitem__(self, idx):
        raise RuntimeError("IterableDataset is not indexable")

    def __len__(self):
        raise RuntimeError("IterableDataset has no len()")


class TensorDataset(Dataset):
    def __init__(self, tensors: Sequence):
        arrays = [t.numpy() if isinstance(t, Tensor) else np.asarray(t)
                  for t in tensors]
        n = arrays[0].shape[0]
        if any(a.shape[0] != n for a in arrays):
            raise ValueError("all tensors must share dim 0")
        self.tensors = arrays

    def __getitem__(self, idx):
        return tuple(a[idx] for a in self.tensors)

    def __len__(self):
        return self.tensors[0].shape[0]


class ComposeDataset(Dataset):
    def __init__(self, datasets):
        self.datasets = list(datasets)
        if not self.datasets:
            raise ValueError("datasets must not be empty")
        n = len(self.datasets[0])
        if any(len(d) != n for d in self.datasets):
            raise ValueError("datasets must share length")

    def __len__(self):
        return len(self.datasets[0])

    def __getitem__(self, idx):
        out = []
        for d in self.datasets:
            item = d[idx]
            out.extend(item if isinstance(item, tuple) else (item,))
        return tuple(out)


class ChainDataset(IterableDataset):
    def __init__(self, datasets):
        self.datasets = list(datasets)

    def __iter__(self):
        for d in self.datasets:
            yield from d


class Subset(Dataset):
    def __init__(self, dataset, indices):
        self.dataset = dataset
        self.indices = list(indices)

    def __getitem__(self, idx):
        return self.dataset[self.indices[idx]]

    def __len__(self):
        return len(self.indices)


def random_split(dataset, lengths, generator=None):
    if sum(lengths) != len(dataset):
        raise ValueError("sum of lengths must equal dataset length")
    g = np.random.RandomState(
        generator.initial_seed() if generator else None)
    perm = g.permutation(len(dataset))
    out, off = [], 0
    for n in lengths:
        out.append(Subset(dataset, perm[off:off + n].tolist()))
        off += n
    return out


class Sampler:
    def __init__(self, data_source=None):
        self.data_source = data_source

    def __iter__(self):
        raise NotImplementedError

    def __len__(self):
        return len(self.data_source)


class SequenceSampler(Sampler):
    def __iter__(self):
        return iter(range(len(self.data_source)))


class RandomSampler(Sampler):
    def __init__(self, data_source, replacement=False, num_samples=None,
                 generator=None):
        super().__init__(data_source)
        self.replacement = replacement
        self._num_samples = num_samples
        self.generator = generator

    @property
    def num_samples(self):
        return self._num_samples or len(self.data_source)

    def __iter__(self):
        n = len(self.data_source)
        seed = self.generator.initial_seed() if self.generator else \
            int(np.random.randint(0, 2 ** 31 - 1))
        g = np.random.RandomState(seed)
        if self.replacement:
            return iter(g.randint(0, n, self.num_samples).tolist())
        return iter(g.permutation(n)[:self.num_samples].tolist())

    def __len__(self):
        return self.num_samples


class WeightedRandomSampler(Sampler):
    def __init__(self, weights, num_samples, replacement=True):
        self.weights = np.asarray(weights, np.float64)
        self.num_samples = num_samples
        self.replacement = replacement

    def __iter__(self):
        p = self.weights / self.weights.sum()
        idx = np.random.choice(len(self.weights), self.num_samples,
                               replace=self.replacement, p=p)
        return iter(idx.tolist())

    def __len__(self):
        return self.num_samples


class BatchSampler(Sampler):
    def __init__(self, dataset=None, sampler=None, shuffle=False,
                 batch_size=1, drop_last=False):
        if sampler is None:
            sampler = RandomSampler(dataset) if shuffle else \
                SequenceSampler(dataset)
        self.sampler = sampler
        self.batch_size = batch_size
        self.drop_last = drop_last

    def __iter__(self):
        batch = []
        for idx in self.sampler:
            batch.append(idx)
            if len(batch) == self.batch_size:
                yield batch
                batch = []
        if batch and not self.drop_last:
            yield batch

    def __len__(self):
        n = len(self.sampler)
        if self.drop_last:
            return n // self.batch_size
        return (n + self.batch_size - 1) // self.batch_size


class DistributedBatchSampler(BatchSampler):
    """Splits the sample space across data-parallel ranks (reference
    fluid/dataloader/batch_sampler.py DistributedBatchSampler)."""

    def __init__(self, dataset, batch_size, num_replicas=None, rank=None,
                 shuffle=False, drop_last=False):
        if num_replicas is None or rank is None:
            try:
                from ..distributed import env as _env
                num_replicas = num_replicas if num_replicas is not None \
                    else _env.get_world_size()
                rank = rank if rank is not None else _env.get_rank()
            except ImportError:  # distributed not initialised: single rank
                num_replicas, rank = num_replicas or 1, rank or 0
        self.dataset = dataset
        self.batch_size = batch_size
        self.nranks = num_replicas
        self.local_rank = rank
        self.shuffle = shuffle
        self.drop_last = drop_last
        self.epoch = 0
        self.num_samples = int(
            math.ceil(len(dataset) / self.nranks)) if not drop_last else \
            len(dataset) // self.nranks
        self.total_size = self.num_samples * self.nranks

    def set_epoch(self, epoch):
        self.epoch = epoch

    def __iter__(self):
        n = len(self.dataset)
        if self.shuffle:
            g = np.random.RandomState(self.epoch)
            indices = g.permutation(n).tolist()
        else:
            indices = list(range(n))
        # pad to make evenly divisible, then subsample this rank's strip
        if not self.drop_last:
            indices += indices[: self.total_size - len(indices)]
        else:
            indices = indices[: self.total_size]
        indices = indices[self.local_rank:self.total_size:self.nranks]
        batch = []
        for idx in indices:
            batch.append(idx)
            if len(batch) == self.batch_size:
                yield batch
                batch = []
        if batch and not self.drop_last:
            yield batch

    def __len__(self):
        if self.drop_last:
            return self.num_samples // self.batch_size
        return (self.num_samples + self.batch_size - 1) // self.batch_size


class BucketedBatchSampler(BatchSampler):
    """Length-bucketed batching for variable-length data — the DataLoader
    half of the TPU-native LoD replacement (ops/sequence_ops.py is the
    compute half).

    The reference carries ragged batches as LoDTensors
    (/root/reference/paddle/fluid/framework/lod_tensor.h:1) so it never
    pads; on TPU every batch must have a static shape, and naive padding
    to the corpus max wastes compute while per-batch maxlens force one
    XLA recompile per distinct length. This sampler does the standard
    TPU resolution: sort-ish grouping by length into a FIXED, small set
    of bucket boundaries, so (a) padding waste is bounded by the bucket
    granularity and (b) the train step compiles once per bucket, not
    once per batch.

    Sample lengths come from (in priority order) ``lengths`` — a
    precomputed sequence, so datasets whose ``__getitem__`` does real
    work (file decode, tokenization) are never materialized just to be
    measured — or ``length_fn(dataset[i]) -> int`` (default:
    ``len(sample[0])``). ``bucket_boundaries`` are the padded lengths;
    samples longer than the last boundary are dropped (counted in
    ``n_dropped``).

    DataLoader integration: pass this as ``batch_sampler`` together with
    ``collate_fn=pad_sequence_collate_fn(boundaries=...)`` — because all
    samples of a batch share one bucket, the collate fn recovers the
    bucket's static padded shape by rounding the batch max length up to
    the nearest boundary; no side channel is needed. For hand-rolled
    loops ``yield_boundary=True`` yields (indices, boundary) pairs
    instead (NOT valid as a DataLoader batch_sampler).
    """

    def __init__(self, dataset, batch_size, bucket_boundaries,
                 length_fn=None, lengths=None, shuffle=True,
                 drop_last=False, seed=0, yield_boundary=False):
        self.dataset = dataset
        self.batch_size = batch_size
        self.bucket_boundaries = sorted(int(b) for b in bucket_boundaries)
        self.length_fn = length_fn or (lambda s: len(s[0]))
        self.shuffle = shuffle
        self.drop_last = drop_last
        self.seed = seed
        self.epoch = 0
        self.yield_boundary = yield_boundary
        # bucket assignment is data-dependent but cheap; do it once
        self._buckets = {b: [] for b in self.bucket_boundaries}
        self.n_dropped = 0
        for i in range(len(dataset)):
            ln = int(lengths[i]) if lengths is not None \
                else self.length_fn(dataset[i])
            for b in self.bucket_boundaries:
                if ln <= b:
                    self._buckets[b].append(i)
                    break
            else:
                self.n_dropped += 1

    def set_epoch(self, epoch):
        self.epoch = epoch

    def __iter__(self):
        g = np.random.RandomState(self.seed + self.epoch)
        batches = []
        for b, idxs in self._buckets.items():
            idxs = list(idxs)
            if self.shuffle:
                g.shuffle(idxs)
            for k in range(0, len(idxs), self.batch_size):
                chunk = idxs[k:k + self.batch_size]
                if self.drop_last and len(chunk) < self.batch_size:
                    continue
                batches.append((chunk, b))
        if self.shuffle:
            g.shuffle(batches)
        for chunk, b in batches:
            yield (chunk, b) if self.yield_boundary else chunk

    def __len__(self):
        n = 0
        for idxs in self._buckets.values():
            if self.drop_last:
                n += len(idxs) // self.batch_size
            else:
                n += (len(idxs) + self.batch_size - 1) // self.batch_size
        return n


def pad_sequence_collate_fn(boundary=None, pad_value=0,
                            length_dtype="int64", boundaries=None):
    """Collate variable-length samples to a dense (batch, maxlen, ...)
    array + lengths vector — the producer side of sequence_pad. Each
    sample is (sequence, *rest); rest fields are stacked unchanged.

    The padded length is either ``boundary`` (fixed) or, with
    ``boundaries``, the smallest boundary >= the batch's max length —
    the DataLoader-compatible form: BucketedBatchSampler guarantees each
    batch stays within one bucket, so rounding up reproduces the
    bucket's static shape without a side channel (one XLA compile per
    bucket, not per batch)."""
    if (boundary is None) == (boundaries is None):
        raise ValueError("pass exactly one of boundary= or boundaries=")
    if boundaries is not None and not list(boundaries):
        raise ValueError("boundaries= must be a non-empty list")
    bset = sorted(int(b) for b in boundaries) if boundaries else None

    def collate(batch):
        bsz = len(batch)
        first = np.asarray(batch[0][0])
        mx = max(len(np.asarray(s[0])) for s in batch)
        if bset is not None:
            pad_to = next((b for b in bset if mx <= b), None)
            if pad_to is None:
                raise ValueError(
                    f"batch max length {mx} exceeds the largest boundary "
                    f"{bset[-1]}; add a boundary or filter long samples "
                    f"(truncating silently would corrupt training data)")
        else:
            pad_to = boundary
            if mx > pad_to:
                raise ValueError(
                    f"batch max length {mx} exceeds boundary={pad_to}; "
                    f"raise boundary= or pre-truncate in the dataset")
        out = np.full((bsz, pad_to) + first.shape[1:], pad_value,
                      dtype=first.dtype)
        lengths = np.zeros((bsz,), dtype=length_dtype)
        for i, sample in enumerate(batch):
            seq = np.asarray(sample[0])
            ln = len(seq)
            out[i, :ln] = seq
            lengths[i] = ln
        rest = [np.stack([np.asarray(s[j]) for s in batch])
                for j in range(1, len(batch[0]))]
        return (out, lengths, *rest)

    return collate


# ---------------------------------------------------------------------------
# collate + worker info
# ---------------------------------------------------------------------------

def default_collate_fn(batch):
    """Stack samples into batched numpy arrays (reference
    fluid/dataloader/collate.py)."""
    sample = batch[0]
    if isinstance(sample, np.ndarray):
        return np.stack(batch)
    if isinstance(sample, Tensor):
        return np.stack([s.numpy() for s in batch])
    if isinstance(sample, (int, np.integer)):
        return np.asarray(batch, np.int64)
    if isinstance(sample, (float, np.floating)):
        return np.asarray(batch, np.float32)
    if isinstance(sample, (list, tuple)):
        transposed = zip(*batch)
        return type(sample)(default_collate_fn(list(x)) for x in transposed)
    if isinstance(sample, dict):
        return {k: default_collate_fn([d[k] for d in batch]) for k in sample}
    return batch


class WorkerInfo:
    def __init__(self, id, num_workers, dataset, seed):
        self.id = id
        self.num_workers = num_workers
        self.dataset = dataset
        self.seed = seed


_worker_tls = threading.local()


class _SPILLED:
    """Marker for a result too large for its shm slot, shipped via a
    spill file instead (multiprocess DataLoader path)."""

    def __init__(self, path):
        self.path = path


def get_worker_info():
    return getattr(_worker_tls, "info", None)


# ---------------------------------------------------------------------------
# DataLoader
# ---------------------------------------------------------------------------

class DataLoader:
    """Reference: fluid/reader.py:275 DataLoader. num_workers>0 enables a
    thread pool with an ordered prefetch queue (the shared-memory
    subprocess machinery of the reference is replaced by threads +
    zero-copy numpy; the C++ tpu_dataio ring buffer can slot in underneath
    without changing this API)."""

    def __init__(self, dataset, feed_list=None, places=None,
                 return_list=True, batch_sampler=None, batch_size=1,
                 shuffle=False, drop_last=False, collate_fn=None,
                 num_workers=0, use_buffer_reader=True, prefetch_factor=2,
                 use_shared_memory=True, timeout=0, worker_init_fn=None,
                 persistent_workers=False):
        self.dataset = dataset
        self.return_list = return_list
        self.collate_fn = collate_fn or default_collate_fn
        self.num_workers = max(0, int(num_workers))
        self.prefetch_factor = max(1, int(prefetch_factor))
        self.use_shared_memory = bool(use_shared_memory)
        self.timeout = timeout
        self.worker_init_fn = worker_init_fn
        self._iterable_mode = isinstance(dataset, IterableDataset)
        if self._iterable_mode:
            self.batch_sampler = None
            self.batch_size = batch_size
            self.drop_last = drop_last
        elif batch_sampler is not None:
            self.batch_sampler = batch_sampler
        else:
            self.batch_sampler = BatchSampler(
                dataset, shuffle=shuffle, batch_size=batch_size,
                drop_last=drop_last)

    def __len__(self):
        if self._iterable_mode:
            raise TypeError("length of IterableDataset DataLoader unknown")
        return len(self.batch_sampler)

    def _fetch(self, indices):
        return self.collate_fn([self.dataset[i] for i in indices])

    def _iter_iterable(self):
        batch = []
        for sample in self.dataset:
            batch.append(sample)
            if len(batch) == self.batch_size:
                yield self.collate_fn(batch)
                batch = []
        if batch and not self.drop_last:
            yield self.collate_fn(batch)

    def __iter__(self):
        if self._iterable_mode:
            yield from self._iter_iterable()
            return
        if self.num_workers == 0:
            for indices in self.batch_sampler:
                yield self._fetch(indices)
            return
        if self.use_shared_memory:
            from . import shm_ring
            if shm_ring.available():
                yield from self._iter_multiprocess()
                return
        yield from self._iter_threaded()

    def _iter_multiprocess(self):
        """Subprocess workers + the native shared-memory rings
        (reference: dataloader_iter.py _DataLoaderIterMultiProcess over
        mmap shared memory). A TASK ring carries batch indices to the
        workers; a RESULT ring carries the fetched batches back. The
        parent only keeps ``inflight`` tasks outstanding, so the
        reorder buffer, the result ring, and every worker's progress are
        all bounded by prefetch_factor — a slow batch applies
        backpressure instead of letting the rest of the epoch pile up in
        parent RAM."""
        import multiprocessing as mp

        from . import shm_ring

        import pickle as _pickle
        import tempfile

        batches = list(self.batch_sampler)
        if not batches:
            return
        n_workers = min(self.num_workers, len(batches))
        inflight = max(n_workers, n_workers * self.prefetch_factor)
        uid = f"{os.getpid()}_{id(self)}"
        # reference timeout semantics: 0 means "no timeout" — producers
        # always block until space frees (the parent going slow must
        # stall workers, not kill them); an explicit timeout bounds only
        # the parent's wait for data
        _FOREVER_MS = 7 * 24 * 3600 * 1000
        pop_timeout_ms = int(self.timeout * 1000) if self.timeout else \
            _FOREVER_MS
        # size the task slots for the LARGEST index batch (batch_size is
        # unbounded; a fixed slot would cap it)
        biggest = max(batches, key=len)
        task_slot = max(1 << 16,
                        2 * len(_pickle.dumps((len(batches), biggest))))
        task_ring = shm_ring.ShmRing(f"/pdtpu_t_{uid}",
                                     slot_bytes=task_slot,
                                     n_slots=inflight + n_workers,
                                     create=True)
        res_ring = shm_ring.ShmRing(f"/pdtpu_r_{uid}",
                                    slot_bytes=64 << 20,
                                    n_slots=inflight, create=True)
        spill_dir = tempfile.mkdtemp(prefix="pdtpu_dl_spill_")

        def worker(wid):
            _worker_tls.info = WorkerInfo(wid, n_workers, self.dataset,
                                          wid)
            if self.worker_init_fn is not None:
                self.worker_init_fn(wid)
            w_tasks = shm_ring.ShmRing(task_ring.name.decode(),
                                       create=False)
            w_res = shm_ring.ShmRing(res_ring.name.decode(),
                                     create=False)
            try:
                while True:
                    task = w_tasks.pop_obj(_FOREVER_MS)
                    if task is None:  # sentinel: drain done
                        return
                    i, indices = task
                    try:
                        result = self._fetch(indices)
                        payload = _pickle.dumps(
                            (i, None, result),
                            protocol=_pickle.HIGHEST_PROTOCOL)
                        if len(payload) > w_res.slot_bytes:
                            # batch exceeds the shm slot: spill to disk
                            # and ship the path (keeps arbitrary batch
                            # sizes working; shm stays the fast path)
                            path = os.path.join(spill_dir,
                                                f"batch_{i}.pkl")
                            with open(path, "wb") as f:
                                f.write(payload)
                            w_res.push_obj((i, None, _SPILLED(path)),
                                           _FOREVER_MS)
                        else:
                            w_res.push(payload, _FOREVER_MS)
                    except Exception as e:  # parent re-raises the
                        #                     ORIGINAL exception type
                        try:
                            w_res.push_obj((i, e, None), _FOREVER_MS)
                        except Exception:
                            w_res.push_obj(
                                (i, RuntimeError(
                                    f"{type(e).__name__}: {e}"), None),
                                _FOREVER_MS)
            finally:
                w_tasks.close()
                w_res.close()

        ctx = mp.get_context("fork")
        procs = [ctx.Process(target=worker, args=(w,), daemon=True)
                 for w in range(n_workers)]
        for p in procs:
            p.start()
        issued = 0
        done_sent = False
        try:
            pending = {}
            next_out = 0
            waited_ms = 0
            while next_out < len(batches):
                # keep at most `inflight` tasks outstanding
                while issued < len(batches) and \
                        issued - next_out < inflight:
                    task_ring.push_obj((issued, batches[issued]),
                                       _FOREVER_MS)
                    issued += 1
                if issued == len(batches) and not done_sent:
                    for _ in range(n_workers):
                        task_ring.push_obj(None, _FOREVER_MS)
                    done_sent = True
                if next_out in pending:
                    yield pending.pop(next_out)
                    next_out += 1
                    continue
                # poll in short slices so a dead worker surfaces as an
                # error instead of a multi-day hang (reference: the
                # launcher/iterator watch worker exit); a sub-5s user
                # timeout keeps its precision
                try:
                    slice_ms = min(5000,
                                   max(1, pop_timeout_ms - waited_ms))
                    i, err, result = res_ring.pop_obj(slice_ms)
                    waited_ms = 0
                except TimeoutError:
                    waited_ms += slice_ms
                    dead = [p for p in procs
                            if p.exitcode not in (None, 0)]
                    if dead:
                        raise RuntimeError(
                            f"DataLoader worker died with exit code "
                            f"{dead[0].exitcode} (killed/OOM?); "
                            f"{len(batches) - next_out} batches "
                            f"unfetched")
                    if all(p.exitcode is not None for p in procs) and \
                            res_ring.pending() == 0:
                        raise RuntimeError(
                            "all DataLoader workers exited but "
                            f"{len(batches) - next_out} batches were "
                            "never produced")
                    if waited_ms >= pop_timeout_ms:
                        raise
                    continue
                if err is not None:
                    raise err
                if isinstance(result, _SPILLED):
                    spath = result.path
                    with open(spath, "rb") as f:
                        _, _, result = _pickle.loads(f.read())
                    os.unlink(spath)
                pending[i] = result
        finally:
            for p in procs:
                if p.is_alive():
                    p.terminate()
            for p in procs:
                p.join(timeout=2)
            task_ring.close()
            res_ring.close()
            import shutil
            shutil.rmtree(spill_dir, ignore_errors=True)

    def _iter_threaded(self):
        """Ordered prefetch: worker threads pull index-batches from a task
        queue; results are released strictly in order."""
        task_q: _queue.Queue = _queue.Queue()
        done: dict = {}
        done_lock = threading.Lock()
        done_cv = threading.Condition(done_lock)
        stop = threading.Event()
        batches = list(self.batch_sampler)
        for i, b in enumerate(batches):
            task_q.put((i, b))
        inflight_limit = self.num_workers * self.prefetch_factor
        next_out = 0

        def worker(wid):
            _worker_tls.info = WorkerInfo(wid, self.num_workers,
                                          self.dataset, wid)
            if self.worker_init_fn is not None:
                self.worker_init_fn(wid)
            while not stop.is_set():
                try:
                    i, idxs = task_q.get_nowait()
                except _queue.Empty:
                    return
                # backpressure: stay at most `inflight_limit` ahead
                with done_cv:
                    while i - next_out >= inflight_limit and \
                            not stop.is_set():
                        done_cv.wait(0.05)
                try:
                    result = self._fetch(idxs)
                    err = None
                except Exception as e:  # propagate to consumer
                    result, err = None, e
                with done_cv:
                    done[i] = (result, err)
                    done_cv.notify_all()

        threads = [threading.Thread(target=worker, args=(w,), daemon=True)
                   for w in range(self.num_workers)]
        for t in threads:
            t.start()
        try:
            for i in range(len(batches)):
                with done_cv:
                    while i not in done:
                        done_cv.wait(self.timeout or None)
                    result, err = done.pop(i)
                    next_out = i + 1
                    done_cv.notify_all()
                if err is not None:
                    raise err
                yield result
        finally:
            stop.set()
            with done_cv:
                done_cv.notify_all()
            for t in threads:
                t.join(timeout=1.0)


# ---------------------------------------------------------------------------
# Device prefetch: overlap host->device transfer with compute
# ---------------------------------------------------------------------------

def device_prefetch(iterable, sharding=None, buffer_size=2):
    """Iterate ``iterable`` (typically a DataLoader) with batches moved to
    device AHEAD of consumption: a background thread calls
    ``jax.device_put`` on upcoming batches into a bounded buffer, so the
    H2D transfer of batch N+1 rides under the compute of batch N instead
    of serializing in front of it (r2 verdict: 449 ms synchronous H2D per
    ResNet step at the measured 86 MB/s was the dominant step cost).

    Reference analog: the subprocess + shared-memory + pinned-buffer
    pipeline of fluid/dataloader/dataloader_iter.py — on TPU the transfer
    engine is asynchronous, so a thread + double buffer delivers the same
    overlap without shared-memory machinery.

    ``sharding``: optional ``jax.sharding.Sharding`` (e.g. the batch
    sharding of a ParallelEngine) applied to every array in the batch.
    """
    import jax

    def put(batch):
        def one(a):
            if isinstance(a, Tensor):
                a = a._data
            if sharding is not None:
                # honor an already-matching layout: a batch that landed
                # with the requested sharding (e.g. dp-split for the
                # ZeRO train step) must not be forced through a
                # gather-and-redistribute round trip
                if isinstance(a, jax.Array) and \
                        getattr(a, "sharding", None) is not None and \
                        a.sharding.is_equivalent_to(sharding, a.ndim):
                    return a
                return jax.device_put(a, sharding)
            if isinstance(a, jax.Array):
                return a  # already on device: a re-put is a wasted dispatch
            return jax.device_put(a)
        if isinstance(batch, (list, tuple)):
            return type(batch)(one(a) for a in batch)
        return one(batch)

    q: _queue.Queue = _queue.Queue(maxsize=max(1, int(buffer_size)))
    _END = object()
    stop = threading.Event()

    def _put(item):
        # bounded put that aborts when the consumer went away — otherwise
        # an early `break` out of the consuming loop leaves this thread
        # blocked forever, pinning device batches and the inner loader
        while not stop.is_set():
            try:
                q.put(item, timeout=0.1)
                return True
            except _queue.Full:
                continue
        return False

    def producer():
        # observability: each H2D enqueue is a span + histogram sample
        # (prefetch_put_ms), so the trace shows whether transfers really
        # ride under compute or the producer is the bottleneck
        try:
            for batch in iterable:
                t0 = _time.perf_counter()
                with _prof.record("io/device_put", "io"):
                    d = put(batch)
                stat_observe("prefetch_put_ms",
                             (_time.perf_counter() - t0) * 1e3)
                if not _put(d):
                    return
            _put(_END)
        except Exception as e:  # propagate into the consumer
            _put(e)

    t = threading.Thread(target=producer, daemon=True)
    t.start()
    try:
        while True:
            # prefetch_wait_ms ~ 0 means the pipeline keeps the device
            # fed; a distribution skewed high means the loader starves it
            t0 = _time.perf_counter()
            with _prof.record("io/queue_wait", "io"):
                item = q.get()
            if item is _END:
                break
            if isinstance(item, Exception):
                raise item
            # only REAL batches count — the end sentinel and propagated
            # producer errors must not skew the starvation signal
            stat_observe("prefetch_wait_ms",
                         (_time.perf_counter() - t0) * 1e3)
            stat_add("prefetch_batches")
            yield item
    finally:
        stop.set()
        t.join(timeout=1.0)


class DeviceDataLoader:
    """DataLoader wrapper yielding device-resident batches via
    ``device_prefetch`` (len()/attributes delegate to the inner loader)."""

    def __init__(self, loader, sharding=None, buffer_size=2):
        self._loader = loader
        self._sharding = sharding
        self._buffer_size = buffer_size

    def __iter__(self):
        return device_prefetch(self._loader, self._sharding,
                               self._buffer_size)

    def __len__(self):
        return len(self._loader)

    def __getattr__(self, item):
        return getattr(self._loader, item)
