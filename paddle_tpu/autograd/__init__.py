"""``paddle.autograd`` — backward, grad, PyLayer, functional jacobian/hessian.

Analog of the reference's ``python/paddle/autograd/`` (backward_mode.py,
py_layer.py, functional.py). The eager tape lives in framework/tensor.py;
here are the user-facing entry points. The functional jacobian/hessian are
direct jax transforms — the reference's 1.5k-LoC double-grad machinery
collapses into ``jax.jacfwd/jacrev``.
"""
from __future__ import annotations

from typing import List, Optional, Sequence

import jax
import jax.numpy as jnp
import numpy as np

from ..framework.dispatch import call_op
from ..framework.tensor import (
    GradNode, Tensor, is_grad_enabled, no_grad_guard, run_backward,
)

__all__ = ["backward", "grad", "PyLayer", "PyLayerContext", "no_grad",
           "jacobian", "hessian", "vjp", "jvp", "differentiable_apply"]

from ..framework.tensor import no_grad  # noqa: F401  (re-export)


def backward(tensors, grad_tensors=None, retain_graph=False):
    """``paddle.autograd.backward`` (reference backward_mode.py:backward)."""
    if isinstance(tensors, Tensor):
        tensors = [tensors]
    if grad_tensors is None:
        grad_tensors = [None] * len(tensors)
    elif isinstance(grad_tensors, Tensor):
        grad_tensors = [grad_tensors]
    for t, g in zip(tensors, grad_tensors):
        run_backward(t, g, retain_graph=retain_graph)


def grad(outputs, inputs, grad_outputs=None, retain_graph=None,
         create_graph=False, only_inputs=True, allow_unused=False,
         no_grad_vars=None):
    """``paddle.grad`` — grads of outputs w.r.t. inputs without touching
    ``.grad`` (reference dygraph grad)."""
    outputs = [outputs] if isinstance(outputs, Tensor) else list(outputs)
    inputs = [inputs] if isinstance(inputs, Tensor) else list(inputs)
    # preserve existing .grad, run backward with retain, then harvest
    saved = [(t, t.grad) for t in inputs]
    retain = True if retain_graph is None else retain_graph
    for t in inputs:
        t.grad = None
        t._retain_grads = True
    if grad_outputs is None:
        grad_outputs = [None] * len(outputs)
    for o, g in zip(outputs, grad_outputs):
        run_backward(o, g, retain_graph=retain)
    results = []
    for t in inputs:
        if t.grad is None:
            if not allow_unused:
                raise RuntimeError(
                    f"input tensor {t.name} is unreachable from outputs; "
                    "pass allow_unused=True to get None instead")
            results.append(None)
        else:
            results.append(t.grad)
    for t, old in saved:
        t.grad = old
        t._retain_grads = False
    return results


class PyLayerContext:
    """Saved-tensor container handed to PyLayer.forward/backward
    (reference autograd/py_layer.py PyLayerContext)."""

    def __init__(self):
        self._saved = ()
        self._extra = {}

    def save_for_backward(self, *tensors):
        self._saved = tensors

    def saved_tensor(self):
        return self._saved

    def __setattr__(self, k, v):
        object.__setattr__(self, k, v)


class PyLayer:
    """Custom autograd op: subclass with static ``forward(ctx, ...)`` and
    ``backward(ctx, *grads)``.

    TPU-native note: the backward runs the user's Python, so a PyLayer is an
    eager-only construct (inside jitted train steps use ``jax.custom_vjp``
    via ops.registry instead). This mirrors the reference where PyLayer
    calls back into Python from C++ grad nodes.
    """

    @classmethod
    def apply(cls, *args, **kwargs):
        ctx = PyLayerContext()
        with no_grad_guard():
            outputs = cls.forward(ctx, *args, **kwargs)
        single = not isinstance(outputs, (list, tuple))
        out_list = [outputs] if single else list(outputs)

        in_tensors = [a for a in args if isinstance(a, Tensor)]
        needs_grad = is_grad_enabled() and any(
            t._requires_grad() for t in in_tensors)
        if needs_grad:
            out_meta = [(tuple(o.shape), o.dtype) for o in out_list]

            def vjp_fn(cotangents):
                cts = [Tensor(c) for c in cotangents]
                with no_grad_guard():
                    gin = cls.backward(ctx, *cts)
                gin = [gin] if isinstance(gin, Tensor) else list(gin or [])
                flat = []
                gi = iter(gin)
                for a in args:
                    if isinstance(a, Tensor):
                        g = next(gi, None)
                        flat.append(None if g is None else g._data)
                return flat

            node = GradNode(
                op_name=f"py_layer_{cls.__name__}",
                vjp_fn=lambda cot: vjp_fn(cot),
                inputs=in_tensors,
                n_outputs=len(out_list),
                out_treedef=jax.tree_util.tree_structure(
                    tuple(range(len(out_list)))),
                out_meta=out_meta,
            )
            for i, o in enumerate(out_list):
                o._node = node
                o._out_idx = i
                o.stop_gradient = False
        return out_list[0] if single else tuple(out_list)

    @staticmethod
    def forward(ctx, *args, **kwargs):
        raise NotImplementedError

    @staticmethod
    def backward(ctx, *args):
        raise NotImplementedError


def _as_fn_over_arrays(func, example_inputs):
    def fn(*arrays):
        ins = [Tensor(a, stop_gradient=True) for a in arrays]
        out = func(*ins)
        outs = out if isinstance(out, (list, tuple)) else [out]
        return tuple(o._data for o in outs)
    return fn


def _wrap_arrays(obj):
    """Recursively wrap raw arrays in Tensors using plain python lists
    (Tensor is itself a pytree node, so tree_map would immediately unwrap
    what it wraps)."""
    if isinstance(obj, (tuple, list)):
        out = [_wrap_arrays(o) for o in obj]
        return out[0] if len(out) == 1 else out
    return Tensor(obj)


def jacobian(func, xs, create_graph=False, allow_unused=False):
    """Functional jacobian (reference autograd/functional.py:jacobian).
    Returns a Tensor for single input/output, else nested lists
    [output][input]."""
    single = isinstance(xs, Tensor)
    xs_list = [xs] if single else list(xs)
    arrays = [x._data for x in xs_list]
    fn = _as_fn_over_arrays(func, arrays)
    jac = jax.jacrev(lambda *a: fn(*a), argnums=tuple(range(len(arrays))))(
        *arrays)
    return _wrap_arrays(jac)


def hessian(func, xs, create_graph=False, allow_unused=False):
    """Hessian of a scalar-valued func — jax.hessian under the hood,
    replacing the reference's double-grad engine."""
    single = isinstance(xs, Tensor)
    xs_list = [xs] if single else list(xs)
    arrays = [x._data for x in xs_list]

    def scalar_fn(*a):
        ins = [Tensor(x, stop_gradient=True) for x in a]
        out = func(*ins)
        return jnp.reshape(out._data, ())

    h = jax.hessian(scalar_fn, argnums=tuple(range(len(arrays))))(*arrays)
    return _wrap_arrays(h)


def vjp(func, xs, v=None):
    single = isinstance(xs, Tensor)
    xs_list = [xs] if single else list(xs)
    arrays = [x._data for x in xs_list]
    fn = _as_fn_over_arrays(func, arrays)
    out, vjp_fn = jax.vjp(fn, *arrays)
    if v is None:
        cot = tuple(jnp.ones_like(o) for o in out)
    else:
        v_list = [v] if isinstance(v, Tensor) else list(v)
        cot = tuple(x._data for x in v_list)
    grads = vjp_fn(cot)
    outs = [Tensor(o) for o in out]
    gs = [Tensor(g) for g in grads]
    return (outs[0] if len(outs) == 1 else outs,
            gs[0] if single else gs)


def jvp(func, xs, v=None):
    single = isinstance(xs, Tensor)
    xs_list = [xs] if single else list(xs)
    arrays = [x._data for x in xs_list]
    fn = _as_fn_over_arrays(func, arrays)
    if v is None:
        tangents = tuple(jnp.ones_like(a) for a in arrays)
    else:
        v_list = [v] if isinstance(v, Tensor) else list(v)
        tangents = tuple(x._data for x in v_list)
    out, jout = jax.jvp(fn, tuple(arrays), tangents)
    outs = [Tensor(o) for o in out]
    js = [Tensor(j) for j in jout]
    return (outs[0] if len(outs) == 1 else outs,
            js[0] if len(js) == 1 else js)


class _ArrayFnLayer(PyLayer):
    """Tape node for an arbitrary pure array function (used by
    differentiable_apply)."""

    @staticmethod
    def forward(ctx, fn, *tensors):
        arrays = [t._data for t in tensors]
        outs, vjp_fn = jax.vjp(fn, *arrays)
        single = not isinstance(outs, (tuple, list))
        out_list = [outs] if single else list(outs)
        ctx.vjp_fn = vjp_fn
        ctx.single = single
        ctx.out_meta = [(o.shape, o.dtype) for o in out_list]
        ts = [Tensor(o) for o in out_list]
        return ts[0] if single else tuple(ts)

    @staticmethod
    def backward(ctx, *grads):
        import jax.numpy as jnp
        cots = []
        for g, (shape, dtype) in zip(grads, ctx.out_meta):
            cots.append(jnp.zeros(shape, dtype) if g is None else
                        g._data.astype(dtype))
        cot = cots[0] if ctx.single else tuple(cots)
        gins = ctx.vjp_fn(cot)
        return tuple(Tensor(g) for g in gins)


def differentiable_apply(fn, *tensors):
    """Run a pure array function over Tensor inputs with correct autograd
    in EVERY regime (the pattern scan/while-based layers need — a python
    fallback loop would unroll under jit, and raw arrays would silently
    skip the eager tape, the r2 MoE bug):

    * traced (inside a jitted step) or grads-off: plain call — jax's own
      AD/tracing handles it;
    * eager with grads on: ONE tape node whose backward applies jax.vjp.

    ``fn(*arrays) -> array | tuple`` must be jax-traceable.
    Returns Tensor or tuple of Tensors.
    """
    arrays = [t._data for t in tensors]
    tracing = any(isinstance(a, jax.core.Tracer) for a in arrays)
    from ..framework.tensor import is_grad_enabled
    wants = is_grad_enabled() and any(t._requires_grad() for t in tensors)
    if tracing or not wants:
        outs = fn(*arrays)
        single = not isinstance(outs, (tuple, list))
        out_list = [outs] if single else list(outs)
        # eager grads-off (no_grad / frozen params): outputs must NOT
        # re-enter autograd; traced outputs keep stop_gradient=False so
        # functional consumers treat them as differentiable
        sg = not tracing
        ts = [Tensor(o, stop_gradient=sg) for o in out_list]
        return ts[0] if single else tuple(ts)
    return _ArrayFnLayer.apply(fn, *tensors)
