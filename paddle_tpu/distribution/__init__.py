"""``paddle.distribution`` — probability distributions.

Reference: python/paddle/distribution/ (Distribution base, Normal,
Uniform, Categorical, Beta, Dirichlet, kl_divergence registry in kl.py).

TPU-native: sampling draws from the framework RNG (functional PRNG keys),
log_prob/entropy are closed-form jnp expressions — all jit-traceable.
"""
from __future__ import annotations

import math
from typing import Dict, Optional, Tuple, Type

import numpy as np

from ..framework import random as _random
from ..framework.tensor import Tensor

__all__ = ["Distribution", "Normal", "Uniform", "Categorical", "Beta",
           "Dirichlet", "kl_divergence", "register_kl"]


def _arr(x):
    import jax.numpy as jnp
    if isinstance(x, Tensor):
        return x._data.astype(jnp.float32)
    return jnp.asarray(x, jnp.float32)


class Distribution:
    """Reference distribution/distribution.py Distribution base."""

    def __init__(self, batch_shape=(), event_shape=()):
        self._batch_shape = tuple(batch_shape)
        self._event_shape = tuple(event_shape)

    @property
    def batch_shape(self):
        return self._batch_shape

    @property
    def event_shape(self):
        return self._event_shape

    def sample(self, shape=()):
        raise NotImplementedError

    def rsample(self, shape=()):
        raise NotImplementedError

    def log_prob(self, value):
        raise NotImplementedError

    def prob(self, value):
        from ..framework.dispatch import call_op
        return call_op("exp", self.log_prob(value))

    def entropy(self):
        raise NotImplementedError

    def kl_divergence(self, other):
        return kl_divergence(self, other)


def _draw_key(seed):
    """seed=0 (the reference default) draws from the global stream; an
    explicit nonzero seed gives a reproducible dedicated stream."""
    import jax
    if seed:
        return jax.random.key(int(seed))
    return _random.next_key()


class Normal(Distribution):
    """Reference distribution/normal.py."""

    def __init__(self, loc, scale, name=None):
        # keep original Tensor params so rsample stays differentiable
        self._loc_t = loc if isinstance(loc, Tensor) else None
        self._scale_t = scale if isinstance(scale, Tensor) else None
        self.loc = _arr(loc)
        self.scale = _arr(scale)
        import jax.numpy as jnp
        shape = jnp.broadcast_shapes(self.loc.shape, self.scale.shape)
        super().__init__(batch_shape=shape)

    @property
    def mean(self):
        return Tensor(self.loc)

    @property
    def variance(self):
        return Tensor(self.scale ** 2)

    def sample(self, shape=(), seed=0):
        import jax
        key = _draw_key(seed)
        out = self.loc + self.scale * jax.random.normal(
            key, tuple(shape) + self.batch_shape)
        return Tensor(out)

    def rsample(self, shape=(), seed=0):
        """Reparameterized draw: differentiable w.r.t. Tensor loc/scale
        (loc + scale * eps) — feeds VAE/policy-gradient training."""
        import jax
        from .. import autograd
        key = _draw_key(seed)
        eps = jax.random.normal(key, tuple(shape) + self.batch_shape)
        loc_t = self._loc_t if self._loc_t is not None else \
            Tensor(self.loc)
        scale_t = self._scale_t if self._scale_t is not None else \
            Tensor(self.scale)
        return autograd.differentiable_apply(
            lambda l, s: l + s * eps, loc_t, scale_t)

    def log_prob(self, value):
        import jax.numpy as jnp
        v = _arr(value)
        var = self.scale ** 2
        return Tensor(-((v - self.loc) ** 2) / (2 * var)
                      - jnp.log(self.scale)
                      - 0.5 * math.log(2 * math.pi))

    def entropy(self):
        import jax.numpy as jnp
        ent = 0.5 + 0.5 * math.log(2 * math.pi) + jnp.log(self.scale)
        return Tensor(jnp.broadcast_to(ent, self.batch_shape))

    def probs(self, value):
        return self.prob(value)


class Uniform(Distribution):
    """Reference distribution/uniform.py: U[low, high)."""

    def __init__(self, low, high, name=None):
        self._low_t = low if isinstance(low, Tensor) else None
        self._high_t = high if isinstance(high, Tensor) else None
        self.low = _arr(low)
        self.high = _arr(high)
        import jax.numpy as jnp
        shape = jnp.broadcast_shapes(self.low.shape, self.high.shape)
        super().__init__(batch_shape=shape)

    def sample(self, shape=(), seed=0):
        import jax
        key = _draw_key(seed)
        u = jax.random.uniform(key, tuple(shape) + self.batch_shape)
        return Tensor(self.low + u * (self.high - self.low))

    def rsample(self, shape=(), seed=0):
        import jax
        from .. import autograd
        key = _draw_key(seed)
        u = jax.random.uniform(key, tuple(shape) + self.batch_shape)
        low_t = self._low_t if self._low_t is not None else \
            Tensor(self.low)
        high_t = self._high_t if self._high_t is not None else \
            Tensor(self.high)
        return autograd.differentiable_apply(
            lambda lo, hi: lo + u * (hi - lo), low_t, high_t)

    def log_prob(self, value):
        import jax.numpy as jnp
        v = _arr(value)
        inside = (v >= self.low) & (v < self.high)
        lp = -jnp.log(self.high - self.low)
        return Tensor(jnp.where(inside, lp, -jnp.inf))

    def entropy(self):
        import jax.numpy as jnp
        return Tensor(jnp.log(self.high - self.low))


class Categorical(Distribution):
    """Reference distribution/categorical.py (constructed from logits)."""

    def __init__(self, logits, name=None):
        import jax
        import jax.numpy as jnp
        self.logits = _arr(logits)
        self._log_p = jax.nn.log_softmax(self.logits, axis=-1)
        super().__init__(batch_shape=self.logits.shape[:-1])

    @property
    def probs_tensor(self):
        import jax.numpy as jnp
        return Tensor(jnp.exp(self._log_p))

    def sample(self, shape=(), seed=0):
        import jax
        key = _draw_key(seed)
        out = jax.random.categorical(
            key, self.logits, shape=tuple(shape) + self.batch_shape)
        return Tensor(out)

    def log_prob(self, value):
        import jax.numpy as jnp
        v = _arr(value).astype(jnp.int32)
        return Tensor(jnp.take_along_axis(
            self._log_p, v[..., None], axis=-1)[..., 0])

    def probs(self, value):
        return self.prob(value)

    def entropy(self):
        import jax.numpy as jnp
        p = jnp.exp(self._log_p)
        return Tensor(-(p * self._log_p).sum(-1))


class Beta(Distribution):
    """Reference distribution/beta.py."""

    def __init__(self, alpha, beta):
        self.alpha = _arr(alpha)
        self.beta = _arr(beta)
        import jax.numpy as jnp
        shape = jnp.broadcast_shapes(self.alpha.shape, self.beta.shape)
        super().__init__(batch_shape=shape)

    @property
    def mean(self):
        return Tensor(self.alpha / (self.alpha + self.beta))

    def sample(self, shape=(), seed=0):
        import jax
        key = _draw_key(seed)
        return Tensor(jax.random.beta(
            key, self.alpha, self.beta, tuple(shape) + self.batch_shape))

    def log_prob(self, value):
        import jax.scipy.special as jsp
        import jax.numpy as jnp
        v = _arr(value)
        lbeta = (jsp.gammaln(self.alpha) + jsp.gammaln(self.beta)
                 - jsp.gammaln(self.alpha + self.beta))
        return Tensor((self.alpha - 1) * jnp.log(v)
                      + (self.beta - 1) * jnp.log1p(-v) - lbeta)

    def entropy(self):
        import jax.scipy.special as jsp
        a, b = self.alpha, self.beta
        lbeta = (jsp.gammaln(a) + jsp.gammaln(b) - jsp.gammaln(a + b))
        return Tensor(lbeta - (a - 1) * jsp.digamma(a)
                      - (b - 1) * jsp.digamma(b)
                      + (a + b - 2) * jsp.digamma(a + b))


class Dirichlet(Distribution):
    """Reference distribution/dirichlet.py."""

    def __init__(self, concentration):
        self.concentration = _arr(concentration)
        super().__init__(batch_shape=self.concentration.shape[:-1],
                         event_shape=self.concentration.shape[-1:])

    def sample(self, shape=(), seed=0):
        import jax
        key = _draw_key(seed)
        return Tensor(jax.random.dirichlet(
            key, self.concentration, tuple(shape) + self.batch_shape))

    def log_prob(self, value):
        import jax.scipy.special as jsp
        import jax.numpy as jnp
        v = _arr(value)
        a = self.concentration
        norm = jsp.gammaln(a.sum(-1)) - jsp.gammaln(a).sum(-1)
        return Tensor(((a - 1) * jnp.log(v)).sum(-1) + norm)

    def entropy(self):
        import jax.scipy.special as jsp
        a = self.concentration
        a0 = a.sum(-1)
        k = a.shape[-1]
        lnB = jsp.gammaln(a).sum(-1) - jsp.gammaln(a0)
        return Tensor(lnB + (a0 - k) * jsp.digamma(a0)
                      - ((a - 1) * jsp.digamma(a)).sum(-1))


# ---------------------------------------------------------------------------
# KL divergence registry (reference distribution/kl.py)
# ---------------------------------------------------------------------------

_KL_REGISTRY: Dict[Tuple[Type, Type], callable] = {}


def register_kl(p_cls, q_cls):
    def deco(fn):
        _KL_REGISTRY[(p_cls, q_cls)] = fn
        return fn
    return deco


def kl_divergence(p: Distribution, q: Distribution) -> Tensor:
    for (pc, qc), fn in _KL_REGISTRY.items():
        if isinstance(p, pc) and isinstance(q, qc):
            return fn(p, q)
    raise NotImplementedError(
        f"no KL rule for ({type(p).__name__}, {type(q).__name__})")


@register_kl(Normal, Normal)
def _kl_normal_normal(p, q):
    import jax.numpy as jnp
    var_ratio = (p.scale / q.scale) ** 2
    t1 = ((p.loc - q.loc) / q.scale) ** 2
    return Tensor(0.5 * (var_ratio + t1 - 1 - jnp.log(var_ratio)))


@register_kl(Uniform, Uniform)
def _kl_uniform_uniform(p, q):
    import jax.numpy as jnp
    r = jnp.log((q.high - q.low) / (p.high - p.low))
    outside = (p.low < q.low) | (p.high > q.high)
    return Tensor(jnp.where(outside, jnp.inf, r))


@register_kl(Categorical, Categorical)
def _kl_categorical_categorical(p, q):
    import jax.numpy as jnp
    pp = jnp.exp(p._log_p)
    return Tensor((pp * (p._log_p - q._log_p)).sum(-1))


@register_kl(Beta, Beta)
def _kl_beta_beta(p, q):
    import jax.scipy.special as jsp
    a1, b1, a2, b2 = p.alpha, p.beta, q.alpha, q.beta
    lbeta1 = jsp.gammaln(a1) + jsp.gammaln(b1) - jsp.gammaln(a1 + b1)
    lbeta2 = jsp.gammaln(a2) + jsp.gammaln(b2) - jsp.gammaln(a2 + b2)
    return Tensor(lbeta2 - lbeta1
                  + (a1 - a2) * jsp.digamma(a1)
                  + (b1 - b2) * jsp.digamma(b1)
                  + (a2 - a1 + b2 - b1) * jsp.digamma(a1 + b1))


@register_kl(Dirichlet, Dirichlet)
def _kl_dirichlet_dirichlet(p, q):
    import jax.scipy.special as jsp
    a, b = p.concentration, q.concentration
    a0 = a.sum(-1)
    lnB_a = jsp.gammaln(a).sum(-1) - jsp.gammaln(a0)
    lnB_b = jsp.gammaln(b).sum(-1) - jsp.gammaln(b.sum(-1))
    return Tensor(lnB_b - lnB_a
                  + ((a - b) * (jsp.digamma(a)
                                - jsp.digamma(a0)[..., None])).sum(-1))
